"""Benchmark: gossip throughput + convergence, un-losable by design.

Prints ONE JSON line:
  {"metric": "gossip-rounds/sec/chip", "value": N, "unit": "rounds/s",
   "vs_baseline": R, ...extras}

The scenario is the framework's north-star workload (BASELINE.md): a
formed LAN cluster on the sparse circulant view plane, a mass failure
injected, SWIM + Lifeguard + gossip + push-pull converging every
surviving view, Vivaldi coordinates learning the ground-truth latency
map throughout.

Hardening (this file must emit a result no matter what the backend
does — the TPU tunnel in this environment can hang *inside backend
initialization* indefinitely):

  - The parent process never imports jax. Each backend (TPU, CPU) runs
    in its own child subprocess (``BENCH_CHILD``) with a hard deadline;
    a hung backend init is killed, not waited on.
  - Children stream one JSON line per completed phase (setup /
    throughput / convergence / rmse / sweep entries), so the parent
    harvests whatever finished even when a child dies mid-run (OOM,
    device fault, timeout).
  - Every phase inside the child is try/except-wrapped; errors become
    diagnostics in the output, never silence.
  - The CPU fallback number is ALWAYS recorded alongside the TPU one,
    so no round publishes nothing.
  - Default shape is the sparse profile (view_degree=32) — dense
    n=4096 (K=4095 views) is a deliberately heavy stress shape, not a
    benchmark default.
  - The TPU child runs under the single-flight device lock
    (consul_tpu/utils/tpu_lock.py): two JAX clients on this tunnel
    deadlock, and killing the second can wedge the relay for everyone.
    If another process holds the lock, the attempt is recorded as
    ``tpu-busy`` rather than risking the wedge.
  - A successful TPU run is saved to ``BENCH_TPU_SESSION_LATEST.json``.
    When the end-of-round TPU window is dead (init-hang / timeout /
    busy), the freshest saved TPU session artifact is re-emitted as the
    primary result with explicit ``replayed_from`` provenance — an
    honest replay beats silently reporting a CPU number as the round's
    headline.

``vs_baseline``: the reference publishes no gossip-throughput numbers
(BASELINE.json ``published: {}``), so the baseline is the protocol's
real-time cadence — a real memberlist cluster advances one gossip round
per 200 ms (5 rounds/s, reference memberlist/config.go:252). The value
is therefore the per-chip simulation speed-up over real time.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)

from consul_tpu.utils import tpu_lock  # noqa: E402  (no jax inside)
from consul_tpu.runtime import watchdog as runtime_watchdog  # noqa: E402  (stdlib only)
from consul_tpu.obs import blackbox as obs_blackbox  # noqa: E402  (stdlib only)


# ----------------------------------------------------------------------
# Child: actually run the benchmark phases on one backend.
# ----------------------------------------------------------------------

def _emit(obj):
    # Uniform timing contract: every phase line carries BOTH wall_s and
    # compile_s (0.0 when the phase had no separately measured compile
    # region), so downstream consumers never branch on key presence.
    # Error lines are diagnostics, not measurements, and stay bare.
    if obj.get("phase") and obj["phase"] != "error":
        obj.setdefault("wall_s", 0.0)
        obj.setdefault("compile_s", 0.0)
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def child(platform: str, deadline: float):
    def left():
        return deadline - time.monotonic()

    t0 = time.monotonic()
    # BENCH_DEVICES: force a host (CPU) device count for the multi-chip
    # path without real chips. Must land in XLA_FLAGS before the first
    # jax import in this process — the flag only affects the CPU
    # backend, so it is harmless on a real TPU child. The same value
    # also caps default_mesh() below, so BENCH_DEVICES=4 on an 8-chip
    # host means "run the 4-device mesh".
    bench_devices = int(os.environ.get("BENCH_DEVICES", "0") or 0)
    if bench_devices > 1:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={bench_devices}")
    try:
        import jax

        if platform != "default":
            # Must land before the first backend touch; this environment
            # pins jax_platforms via sitecustomize, so the env var alone
            # is not enough.
            jax.config.update("jax_platforms", platform)
        devs = jax.devices()
        # Per-device memory provenance: on TPU, memory_stats() reports
        # HBM in use / limit; the CPU backend may return None or raise,
        # so every read is guarded — this phase must never kill a child.
        mem = []
        for d in jax.local_devices():
            try:
                ms = d.memory_stats() or {}
                mem.append({
                    "device": str(d),
                    "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                    "bytes_limit": int(ms.get("bytes_limit", 0)),
                })
            except Exception:
                mem.append({"device": str(d), "memory_stats": None})
        _emit({
            "phase": "setup",
            "platform": devs[0].platform,
            "device": str(devs[0]),
            "devices": len(devs),
            "jax": jax.__version__,
            "init_s": round(time.monotonic() - t0, 1),
            "wall_s": round(time.monotonic() - t0, 1),
            "memory": mem,
        })
    except Exception as e:  # backend init failed: nothing else can run
        _emit({"phase": "error", "where": "setup", "error": repr(e)[:500]})
        return 1

    import jax.numpy as jnp

    from consul_tpu.config import SimConfig, clamp_view_degree
    from consul_tpu.models.cluster import Simulation
    from consul_tpu.parallel import mesh as pmesh
    from consul_tpu.utils import compile_cache
    from consul_tpu.utils import metrics as obs

    # Persistent XLA compilation cache (CONSUL_TPU_COMPILE_CACHE, or the
    # parent's --compile-cache flag exported into the child env): every
    # compile_s below carries hit/miss provenance so a near-zero number
    # is legible as warm-from-disk rather than a measurement artifact.
    cc_dir = compile_cache.maybe_enable_from_env()
    if cc_dir:
        _emit({"phase": "compile_cache_enabled", "dir": cc_dir})

    n = int(os.environ.get("BENCH_N", "65536"))
    view_degree = int(os.environ.get("BENCH_VIEW_DEGREE", "32"))
    kill_frac = float(os.environ.get("BENCH_KILL_FRAC", "0.05"))
    chunk = int(os.environ.get("BENCH_CHUNK", "128"))
    profile = os.environ.get("BENCH_PROFILE", "")
    n_dc = int(os.environ.get("BENCH_N_DC", "1"))

    def build(n_nodes, cls=Simulation, device_count=None):
        # Multi-chip is the default headline path: whenever more than
        # one device is visible, every phase sim runs its fused core
        # under shard_map over the full elastic mesh (parallel/mesh:
        # default_mesh trims the device count to a divisor of n).
        # BENCH_DEVICES caps the mesh, BENCH_N_DC folds in a dc axis;
        # a single visible device keeps the exact single-device path.
        cfg = SimConfig(n=n_nodes,
                        view_degree=clamp_view_degree(n_nodes, view_degree))
        dc = device_count if device_count is not None else \
            (bench_devices or None)
        return cls(cfg, seed=0,
                   mesh=pmesh.default_mesh(n_nodes, device_count=dc,
                                           n_dc=n_dc))

    # AOT prewarm (utils/prewarm.py): compile every (n, kind, chunk,
    # mesh-shape) signature this child is about to run into the
    # persistent compile cache BEFORE any timed region, so the
    # compile_s fields below record trace + cache-read, not XLA builds.
    # Most useful with the cache enabled (a later cold process warm-
    # starts from disk); gated behind --prewarm / BENCH_PREWARM because
    # the AOT compiles themselves cost the same wall as the first run.
    if os.environ.get("BENCH_PREWARM", ""):
        from consul_tpu.utils import prewarm as prewarm_mod

        sweep_ns = [int(x) for x in
                    os.environ.get("BENCH_SWEEP", "").split(",") if x.strip()]
        for pn in [n] + [x for x in sweep_ns if x != n]:
            if left() < 180:
                _emit({"phase": "prewarm_skipped", "n": pn,
                       "reason": "deadline"})
                continue
            try:
                summary = prewarm_mod.prewarm(
                    ns=[pn], kinds=("swim", "serf"), chunks=(chunk,),
                    metrics_modes=(False, True),
                    device_count=bench_devices or None, n_dc=n_dc,
                    view_degree=view_degree)
                _emit({"phase": "prewarm", "n": pn,
                       "cache_enabled": bool(cc_dir),
                       "compiled": summary["compiled"],
                       "cache": summary["cache"],
                       "wall_s": summary["wall_s"],
                       # Prewarm's wall IS compile: the phase exists
                       # only to pay AOT builds outside timed regions.
                       "compile_s": summary["wall_s"]})
            except Exception as e:
                _emit({"phase": "error", "where": f"prewarm:{pn}",
                       "error": repr(e)[:500]})

    sim = None
    try:
        t = time.monotonic()
        cc0 = compile_cache.stats()
        sim = build(n)
        # Throughput: chunked scans (never one monolithic program), the
        # same compiled program warmed once so XLA compilation stays out
        # of the timed region.
        runner_ticks = chunk
        sim.run(runner_ticks, chunk=chunk, with_metrics=False)  # warm+compile
        jax.block_until_ready(sim.state.view_key)
        reps = 4
        t1 = time.monotonic()
        sim.run(runner_ticks * reps, chunk=chunk, with_metrics=False)
        jax.block_until_ready(sim.state.view_key)
        timed_wall = time.monotonic() - t1
        rounds_per_s = runner_ticks * reps / timed_wall
        _emit({
            "phase": "throughput",
            "n": n,
            "view_degree": view_degree,
            "mesh": (None if sim.mesh is None else
                     [int(sim.mesh.shape[a]) for a in sim.mesh.axis_names]),
            "rounds_per_s": round(rounds_per_s, 2),
            "wall_s": round(timed_wall, 2),
            "compile_s": round(t1 - t, 1),
            "compile_cache": compile_cache.stats_delta(cc0),
            "counters": sim.counters_snapshot(),
        })
    except Exception as e:
        _emit({"phase": "error", "where": "throughput", "error": repr(e)[:500]})

    try:
        if sim is not None and left() > 30:
            # Warm the metrics-on runner (run_until_converged's
            # program) BEFORE the kill, so its one-off compile is
            # measured as compile_s instead of polluting the
            # convergence wall — the extra formed ticks are harmless.
            t_warm = time.monotonic()
            sim.run(chunk, chunk=chunk, with_metrics=True)
            jax.block_until_ready(sim.state.view_key)
            conv_compile_s = time.monotonic() - t_warm
            if profile:
                jax.profiler.start_trace(profile)
            n_kill = int(n * kill_frac)
            sim.kill(jnp.arange(sim.cfg.n) < n_kill)
            t1 = time.monotonic()
            converged, ticks_used, _ = sim.run_until_converged(
                max_ticks=4096, chunk=chunk
            )
            wall = time.monotonic() - t1
            if profile:
                jax.profiler.stop_trace()
            sim_s = ticks_used * sim.cfg.gossip.tick_ms / 1000.0
            _emit({
                "phase": "convergence",
                "n": n,
                "converged": bool(converged),
                "kill_frac": kill_frac,
                "wall_s": round(wall, 2),
                "compile_s": round(conv_compile_s, 1),
                "sim_s": round(sim_s, 1),
                "ticks": int(ticks_used),
                "counters": sim.counters_snapshot(),
            })
    except Exception as e:
        _emit({"phase": "error", "where": "convergence", "error": repr(e)[:500]})

    try:
        if sim is not None:
            t_rmse = time.monotonic()
            h = sim.health()
            _emit({
                "phase": "rmse",
                "vivaldi_rmse_ms": round(sim.rmse() * 1000.0, 3),
                "agreement": round(float(h.agreement), 4),
                "false_positive": round(float(h.false_positive), 6),
                "health_score_mean": round(
                    float(jnp.mean(jnp.asarray(sim.state.awareness, jnp.float32))), 3
                ),
                "wall_s": round(time.monotonic() - t_rmse, 2),
            })
    except Exception as e:
        _emit({"phase": "error", "where": "rmse", "error": repr(e)[:500]})
    finally:
        sim = None  # free the headline sim before the serf build below

    # Memory-budget provenance (runtime/membudget.py): at-rest bytes
    # per node for each state layout x kind, the packed compaction
    # factor vs the dense f32/i32 baseline, and the largest population
    # one chip could hold resident per layout under its reported
    # budget. Sizing is pure eval_shape arithmetic (zero allocation);
    # the per-device peak HBM readings are guarded like the setup
    # phase's — the CPU backend may report nothing.
    try:
        from consul_tpu.runtime import membudget

        t_mem = time.monotonic()
        cfg_mem = SimConfig(n=n, view_degree=clamp_view_degree(n, view_degree))
        layouts = {}
        for lay in ("dense", "packed"):
            per_kind = {}
            for mkind in membudget.KINDS:
                mp = membudget.plan(cfg_mem, mkind, layout=lay)
                per_kind[mkind] = {
                    "bytes_per_node": round(mp.state_bytes_per_node, 2),
                    "dense_f32i32_bytes_per_node": round(
                        mp.dense_f32i32_bytes_per_node, 2),
                    "packed_cut": round(mp.packed_cut, 3),
                    "max_n_per_chip": int(mp.max_n_resident),
                    "streamed_at_bench_n": bool(mp.streamed),
                    "cohort_n": int(mp.cohort_n),
                }
            layouts[lay] = per_kind
        peaks = []
        for d in jax.local_devices():
            try:
                ms = d.memory_stats() or {}
                peaks.append({
                    "device": str(d),
                    "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                    "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", 0)),
                    "bytes_limit": int(ms.get("bytes_limit", 0)),
                })
            except Exception:
                peaks.append({"device": str(d), "memory_stats": None})
        # Kernel traffic contract (ops/pallas_gossip.py): the fused
        # pallas tick's HBM bytes/tick/node — one packed read + one
        # packed write + world — must stay within a small constant of
        # the packed at-rest footprint. Regression-asserted here so a
        # state field landing outside the codec (silently re-dense-ing
        # the tick's HBM traffic) fails the bench, not just a TPU A/B.
        from consul_tpu.models import layout as layout_mod
        from consul_tpu.models import state as sim_state
        from consul_tpu.ops import pallas_gossip
        from consul_tpu.ops import topology as topo_mod

        k0 = jax.random.PRNGKey(0)
        st_aval, w_aval = jax.eval_shape(
            lambda kk: (layout_mod.pack_state(sim_state.init(cfg_mem, kk)),
                        topo_mod.make_world(cfg_mem, kk)), k0)
        traffic = pallas_gossip.tick_hbm_bytes_per_node(
            st_aval, w_aval, None, n=n)
        at_rest = layouts["packed"]["swim"]["bytes_per_node"]
        traffic_bound = 3.0
        assert traffic <= traffic_bound * at_rest, (
            f"pallas tick HBM traffic {traffic:.1f} B/tick/node exceeds "
            f"{traffic_bound}x the packed at-rest footprint {at_rest:.1f} "
            "B/node — some per-tick state is bypassing the packed codec")
        _emit({"phase": "memory", "n": n, "view_degree": view_degree,
               "layouts": layouts, "device_peaks": peaks,
               "kernel_traffic": {
                   "packed_hbm_bytes_per_tick_per_node": round(traffic, 2),
                   "at_rest_bytes_per_node": at_rest,
                   "bound": traffic_bound,
               },
               "wall_s": round(time.monotonic() - t_mem, 2)})
    except Exception as e:
        _emit({"phase": "error", "where": "memory", "error": repr(e)[:500]})

    # Pallas kernel A/B (ops/pallas_gossip.py): rounds/s/chip for the
    # fused packed-native tick versus the XLA scan body at the same
    # (n, packed) signature, plus the measured HBM bytes/tick/node each
    # engine moves (pallas: pure packed bytes; xla: the dense working
    # set it unpacks to). TPU-only by default — interpret-mode pallas
    # on CPU is an evaluator, not a perf measurement — BENCH_KERNEL=1
    # forces it (tiny-n smoke), BENCH_KERNEL=0 skips even on TPU.
    try:
        want_kernel = os.environ.get("BENCH_KERNEL", "auto")
        on_tpu = jax.default_backend() == "tpu"
        if want_kernel != "0" and (on_tpu or want_kernel == "1") \
                and left() > 120:
            from consul_tpu.models import layout as layout_mod
            from consul_tpu.models import state as sim_state
            from consul_tpu.ops import pallas_gossip
            from consul_tpu.ops import topology as topo_mod

            kern_ns = [int(x) for x in os.environ.get(
                "BENCH_KERNEL_NS", "65536,1048576").split(",") if x]
            if not on_tpu:  # forced CPU smoke: keep the shapes tiny
                kern_ns = [int(x) for x in os.environ.get(
                    "BENCH_KERNEL_NS", "1024").split(",") if x]
            kchunk = int(os.environ.get("BENCH_KERNEL_CHUNK", str(chunk)))
            kreps = int(os.environ.get("BENCH_KERNEL_REPS", "2"))
            entries = []
            for kn in kern_ns:
                if left() < 90:
                    break
                row = {"n": kn}
                kcfg = SimConfig(
                    n=kn, view_degree=clamp_view_degree(kn, view_degree))
                k0 = jax.random.PRNGKey(0)
                pst, wav = jax.eval_shape(
                    lambda kk: (layout_mod.pack_state(
                        sim_state.init(kcfg, kk)),
                        topo_mod.make_world(kcfg, kk)), k0)
                dst = jax.eval_shape(
                    lambda kk: sim_state.init(kcfg, kk), k0)
                row["hbm_bytes_per_tick_per_node"] = {
                    "pallas": round(pallas_gossip.tick_hbm_bytes_per_node(
                        pst, wav, None, n=kn), 2),
                    # The XLA scan body unpacks to the dense working set
                    # in HBM every tick: dense read+write + world.
                    "xla": round(pallas_gossip.tick_hbm_bytes_per_node(
                        dst, wav, None, n=kn), 2),
                }
                for eng in ("xla", "pallas"):
                    t_build = time.monotonic()
                    ksim = Simulation(
                        kcfg, seed=0, layout="packed", kernel=eng,
                        mesh=pmesh.default_mesh(
                            kn, device_count=bench_devices or None,
                            n_dc=n_dc))
                    ksim.run(kchunk, chunk=kchunk,
                             with_metrics=False)  # warm+compile
                    jax.block_until_ready(ksim.state)
                    t1 = time.monotonic()
                    ksim.run(kchunk * kreps, chunk=kchunk,
                             with_metrics=False)
                    jax.block_until_ready(ksim.state)
                    wall = time.monotonic() - t1
                    row[eng] = {
                        "rounds_per_s": round(kchunk * kreps / wall, 2),
                        "wall_s": round(wall, 2),
                        "compile_s": round(t1 - t_build, 1),
                    }
                    del ksim
                row["speedup"] = round(
                    row["pallas"]["rounds_per_s"] /
                    max(row["xla"]["rounds_per_s"], 1e-9), 3)
                entries.append(row)
            _emit({"phase": "kernel", "chunk": kchunk,
                   "interpret": not on_tpu, "entries": entries,
                   "wall_s": round(sum(r[e]["wall_s"] for r in entries
                                       for e in ("xla", "pallas")), 2),
                   "compile_s": round(sum(r[e]["compile_s"]
                                          for r in entries
                                          for e in ("xla", "pallas")), 1)})
    except Exception as e:
        _emit({"phase": "error", "where": "kernel", "error": repr(e)[:500]})

    # Chaos SLO probe: a short partition-heal scenario through the
    # compiled fault-schedule plane (consul_tpu/chaos) on a small
    # dedicated sim — the fault masks enter the jitted scan as a
    # program argument, so this costs one extra executable, not one
    # per schedule. Emits the on-device convergence SLO counters
    # (time-to-first-suspect / confirm / heal, false-positive deaths)
    # as a stable phase for downstream BENCH json consumers.
    try:
        if left() > 60:
            from consul_tpu import chaos as chaos_mod

            cn = int(os.environ.get("BENCH_CHAOS_N", "1024"))
            t_form = time.monotonic()
            csim = build(cn)
            csim.run(64, chunk=32, with_metrics=False)  # form the cluster
            chaos_compile_s = time.monotonic() - t_form
            t_scen = time.monotonic()
            res = csim.run_scenario(
                [chaos_mod.Partition(start=4, stop=16,
                                     side_a=slice(0, int(cn * 0.3)))],
                chunk=32, settle=64,
            )
            _emit({"phase": "chaos", "n": cn, "ticks": res.ticks,
                   "slo": res.slo,
                   "wall_s": round(time.monotonic() - t_scen, 2),
                   # Build + formation: where this phase's programs
                   # (and the schedule-plane executable's inputs) warm.
                   "compile_s": round(chaos_compile_s, 1)})
            del csim
    except Exception as e:
        _emit({"phase": "error", "where": "chaos", "error": repr(e)[:500]})

    # Raft tier: batched multi-group consensus riding the same chunked
    # scan (ops/raft_ops.py). Ladder over R-groups x P-peers shapes on
    # one dedicated sim size: steady-state tick rate with the tier
    # armed, elections/s under a split-vote storm window, and the
    # commit-visibility latency of proposed writes in ticks (chunk
    # resolution — proposals enter at a chunk boundary and commit is
    # observed at the next boundary, so p50/p99 quantize to the probe
    # chunk).
    try:
        if left() > 60:
            from consul_tpu import chaos as chaos_mod

            rn = int(os.environ.get("BENCH_RAFT_N", "1024"))
            ladder = []
            for spec in os.environ.get(
                    "BENCH_RAFT_LADDER", "4x3,16x5").split(","):
                r_s, p_s = spec.strip().lower().split("x")
                ladder.append((int(r_s), int(p_s)))
            rchunk = int(os.environ.get("BENCH_RAFT_CHUNK", "8"))
            t_raft = time.monotonic()
            entries = []
            for rg, rp in ladder:
                if left() < 45:
                    break
                t_c = time.monotonic()
                rsim = build(rn)
                plane = rsim.set_raft(rg, peers=rp)
                # Form the cluster and let every group elect once; this
                # is also where the raft-carrying chunk program warms.
                rsim.run(4 * rchunk, chunk=rchunk, with_metrics=False)
                raft_compile_s = time.monotonic() - t_c
                # Steady state: tick rate with the tier armed.
                t_run = time.monotonic()
                steady = 16 * rchunk
                rsim.run(steady, chunk=rchunk, with_metrics=False)
                steady_s = time.monotonic() - t_run
                # Election churn: a storm window suppresses every
                # leader and splits votes; count elections over wall.
                before = plane.counters_snapshot()["elections_started"]
                t_storm = time.monotonic()
                rsim.run_scenario(
                    [chaos_mod.RaftStorm(start=2, stop=2 + 4 * rchunk)],
                    chunk=rchunk, settle=2 * rchunk)
                storm_s = time.monotonic() - t_storm
                elections = (plane.counters_snapshot()["elections_started"]
                             - before)
                # Commit latency: propose one write per probe, step
                # until the quorum commit point releases the ticket.
                lat = []
                for i in range(8):
                    tk = plane.propose(
                        [("kv_put", f"bench/raft/{rg}x{rp}/{i}", b"v")])
                    ticks = 0
                    while not tk.done.is_set() and ticks < 32 * rchunk:
                        rsim.run(rchunk, chunk=rchunk, with_metrics=False)
                        ticks += rchunk
                    lat.append(ticks)
                lat.sort()
                entries.append({
                    "groups": rg, "peers": rp,
                    "ticks_per_s": round(steady / steady_s, 1),
                    "elections": int(elections),
                    "elections_per_s": round(elections / storm_s, 1),
                    "commit_ticks_p50": lat[len(lat) // 2],
                    "commit_ticks_p99": lat[-1],
                    "compile_s": round(raft_compile_s, 2),
                })
                del rsim, plane
            _emit({"phase": "raft", "n": rn, "chunk": rchunk,
                   "entries": entries,
                   "wall_s": round(time.monotonic() - t_raft, 2)})
    except Exception as e:
        _emit({"phase": "error", "where": "raft", "error": repr(e)[:500]})

    # Topology lab: sweep the same S-scenario fault grid against every
    # registered view-graph family at equal degree (chaos/sweep.py) —
    # the schedules stack on a vmapped scenario axis and the topology
    # tables travel as program arguments, so the whole table runs in
    # ONE executable per (n, degree, S, chunk) shared across families —
    # and emit the bandwidth-vs-convergence Pareto table
    # (bytes/tick/node vs time-to-heal) as a stable "topology" phase.
    try:
        if left() > 90:
            from consul_tpu.chaos import sweep as sweep_mod

            # n=1024 / settle=192 is the largest shape whose 4-family
            # table fits the CPU child budget AND whose settle window
            # outlasts the slowest family's heal tail — a too-short
            # window rails time_to_heal at the window end for every
            # family and erases the convergence axis (the n=4096
            # version of this table lives in tests/test_sweep.py's
            # slow acceptance drill, settle=320).
            tn = int(os.environ.get("BENCH_TOPO_N", "1024"))
            tdeg = int(os.environ.get("BENCH_TOPO_DEGREE", "16"))
            tscen = int(os.environ.get("BENCH_TOPO_SCENARIOS", "16"))
            tsettle = int(os.environ.get("BENCH_TOPO_SETTLE", "192"))
            tfam = tuple(
                f.strip() for f in os.environ.get(
                    "BENCH_TOPO_FAMILIES",
                    "circulant,expander,smallworld,hier").split(",")
                if f.strip())
            t_topo = time.monotonic()
            topo = sweep_mod.bench_pareto(
                n=tn, degree=tdeg, scenarios=tscen, families=tfam,
                settle=tsettle, seed=0)
            topo.setdefault("wall_s", round(time.monotonic() - t_topo, 2))
            _emit({"phase": "topology", **topo})
    except Exception as e:
        _emit({"phase": "error", "where": "topology", "error": repr(e)[:500]})

    # Elasticity drill: the chip-loss survival path end-to-end on a
    # small dedicated sim — preempt a resilient run after one chunk,
    # resume ELASTICALLY (mesh rebuilt from whatever devices survive,
    # restored state re-sharded on entry; runtime/harness.run_resilient)
    # with the per-chunk heartbeat armed, and verify the final digest
    # matches an uninterrupted run; then heal a small DCN federation
    # through injected link faults (timeout + drop) under bounded
    # retry/backoff (parallel/dcn.py). One stable "elasticity" phase
    # line for downstream BENCH json consumers.
    try:
        if left() > 90:
            import signal as _signal

            from consul_tpu.models.federation import FederationConfig
            from consul_tpu.parallel import dcn as dcn_mod
            from consul_tpu.runtime import (CheckpointPolicy, Preempted,
                                            run_resilient)
            from consul_tpu.runtime.policy import SignalTrap
            from consul_tpu.utils import checkpoint as ckpt_mod
            from consul_tpu.utils.telemetry import Sink

            en = int(os.environ.get("BENCH_ELASTIC_N", "512"))
            t_elastic = time.monotonic()
            with tempfile.TemporaryDirectory() as td:
                esim = build(en)
                trap = SignalTrap()
                trap.fired = _signal.SIGTERM  # pre-fired: preempt chunk 1
                try:
                    run_resilient(
                        esim, 128, chunk=32,
                        policy=CheckpointPolicy(
                            directory=td, tag="elastic", min_interval_s=0.0,
                            sink=esim.sink, trap=trap))
                except Preempted:
                    pass
                rsim = build(en)
                report = run_resilient(
                    rsim, 128, chunk=32, elastic=True, heartbeat_s=120.0,
                    policy=CheckpointPolicy(
                        directory=td, tag="elastic", min_interval_s=0.0,
                        sink=rsim.sink))
                ref = build(en)
                ref.run(128, chunk=32)
                d_res = ckpt_mod.save(os.path.join(td, "res.ckpt"),
                                      rsim.state)
                d_ref = ckpt_mod.save(os.path.join(td, "ref.ckpt"),
                                      ref.state)
                del esim, rsim, ref

                fed = dcn_mod.DcnFederation(
                    FederationConfig(
                        n_dc=2, nodes_per_dc=64, servers_per_dc=2,
                        lan=SimConfig(n=64, view_degree=8)),
                    n_islands=2, seed=0, sink=Sink(),
                    link_policy=dcn_mod.LinkPolicy(retry_max=3,
                                                   queue_bound=4))
                fed.inject_link_faults([
                    dcn_mod.LinkFault(src=0, dst=1, start=1, stop=4,
                                      kind="timeout"),
                    dcn_mod.LinkFault(src=1, dst=0, start=1, stop=4),
                ])
                fed.run(16 * 12, sync_every=16, chunk=16)
                snk = fed.sink
                _emit({
                    "phase": "elasticity",
                    "n": en,
                    "wall_s": round(time.monotonic() - t_elastic, 2),
                    "devices": len(jax.devices()),
                    "resumed_from_tick": int(report.resumed_from_tick),
                    "reshards": int(report.reshards),
                    "digest_identical": d_res == d_ref,
                    "hang_status": report.hang_status,
                    "dcn": {
                        "retries": int(snk.counter_sum("sim.dcn.retries")),
                        "send_timeouts": int(
                            snk.counter_sum("sim.dcn.send_timeouts")),
                        "link_down_ticks": int(
                            snk.counter_sum("sim.dcn.link_down_ticks")),
                        "retx_dropped": int(
                            snk.counter_sum("sim.dcn.retx_dropped")),
                        "heals": int(snk.counter_sum("sim.dcn.heals")),
                        "queue_peak": int(fed.queue_peak()),
                        "queue_bound": int(fed.link_policy.queue_bound),
                        "converged": bool(fed.replicas_agree()),
                    },
                })
                del fed
    except Exception as e:
        _emit({"phase": "error", "where": "elasticity", "error": repr(e)[:500]})

    from consul_tpu.models.cluster import SerfSimulation

    # Full-stack serf throughput: the SWIM plane PLUS the user-event/
    # query plane (models/serf.py), measured over an EVENT-BURST
    # LIFECYCLE: 8 fresh events fire before each measured chunk, and
    # the 128-tick window then covers their spread, retransmit drain,
    # and (post-gate) idle tail — the workload's end-to-end cost, not
    # a steady-state busy-plane cost. (A truly continuous measurement
    # would need sub-chunk event injection, i.e. a second scan length,
    # i.e. a second full XLA compile — ~6 min at 1M on TPU; not worth
    # the budget.) The pure-idle rate is reported alongside in a
    # SEPARATE phase line so a deadline during the extension cannot
    # lose the burst number: idle-at-SWIM-speed is the event-phase
    # gate's own headline.
    try:
        if left() > 120:
            t_serf = time.monotonic()
            ssim = build(n, cls=SerfSimulation)
            ssim.run(chunk, chunk=chunk, with_metrics=False)
            ssim.user_event(jnp.arange(n) < 8, 1)
            jax.block_until_ready(ssim.state.ev_key)
            serf_compile_s = time.monotonic() - t_serf
            t1 = time.monotonic()
            for rep in range(2):
                ssim.user_event(jnp.arange(n) < 8, 2 + rep)
                ssim.run(chunk, chunk=chunk, with_metrics=False)
            jax.block_until_ready(ssim.state.ev_key)
            serf_wall = time.monotonic() - t1
            _emit({
                "phase": "serf_throughput",
                "n": n,
                "rounds_per_s": round(chunk * 2 / serf_wall, 2),
                "wall_s": round(serf_wall, 2),
                "compile_s": round(serf_compile_s, 1),
                "counters": ssim.counters_snapshot(),
            })
            if left() > 60:
                # Drain fully, then time the idle plane.
                ssim.run(chunk * 4, chunk=chunk, with_metrics=False)
                jax.block_until_ready(ssim.state.ev_key)
                t2 = time.monotonic()
                ssim.run(chunk, chunk=chunk, with_metrics=False)
                jax.block_until_ready(ssim.state.ev_key)
                idle_wall = time.monotonic() - t2
                _emit({
                    "phase": "serf_idle",
                    "n": n,
                    "rounds_per_s": round(chunk / idle_wall, 2),
                    "wall_s": round(idle_wall, 2),
                })
            del ssim
    except Exception as e:
        _emit({"phase": "error", "where": "serf", "error": repr(e)[:500]})

    # Serving plane: batched NearestN reads straight from the live
    # simulation tensors (consul_tpu/serving) — queries/s/chip to set
    # against the reference's ~7.5-16k req/s KV GET numbers in
    # BASELINE.md. One warm batch compiles the bucket's executable,
    # then the timed region is pure pack + kernel + one device_get per
    # batch. (The n-node scan program is already in _RUNNER_CACHE from
    # the throughput phase, so this phase adds only the projection and
    # the one bucket executable.)
    qsim = None
    try:
        if left() > 60:
            import random as _srv_random

            from consul_tpu.serving import MODE_NEAREST, ServingPlane

            sb = int(os.environ.get("BENCH_SERVE_BATCH", "1024"))
            sk = int(os.environ.get("BENCH_SERVE_K", "8"))
            sreps = int(os.environ.get("BENCH_SERVE_REPS", "32"))
            qsim = build(n)
            qsim.run(chunk, chunk=chunk, with_metrics=False)
            plane = ServingPlane(k=sk, buckets=(sb,))
            qsim.attach_serving(plane)
            srng = _srv_random.Random(0)

            def _serve_batch():
                return [(MODE_NEAREST, srng.randrange(n), -1)
                        for _ in range(sb)]

            t_warm = time.monotonic()
            plane.batcher.execute(_serve_batch())  # warm the bucket
            serve_compile_s = time.monotonic() - t_warm
            plane.batcher.latencies_s.clear()  # p50/p99 = steady state
            t1 = time.monotonic()
            for _ in range(sreps):
                plane.batcher.execute(_serve_batch())
            wall = time.monotonic() - t1
            st = plane.stats()
            _emit({
                "phase": "serving",
                "n": n,
                "batch": sb,
                "k": sk,
                "queries": sreps * sb,
                "queries_per_sec_per_chip": round(sreps * sb / wall, 1),
                "wall_s": round(wall, 2),
                "compile_s": round(serve_compile_s, 1),
                "p50_batch_ms": st["p50_batch_ms"],
                "p99_batch_ms": st["p99_batch_ms"],
                "padding_waste_pct": st["padding_waste_pct"],
            })
            del plane
    except Exception as e:
        _emit({"phase": "error", "where": "serving", "error": repr(e)[:500]})

    # Mixed read/write/watch serving (consul_tpu/serving/mixed): the
    # device write path + watch plane driven at a fixed R:W:Watch
    # ratio against the same formed cluster — per-class q/s/chip and
    # p50/p99 (watch latency = flip + delta kernel + fan-out).
    try:
        if qsim is not None and left() > 60:
            from consul_tpu.serving import ServingPlane as _MixPlane
            from consul_tpu.serving.mixed import run_mixed

            mb = int(os.environ.get("BENCH_MIXED_BATCH", "1024"))
            t_mixed = time.monotonic()
            mixed_plane = _MixPlane(k=8, buckets=(mb,), num_services=8)
            qsim.attach_serving(mixed_plane, writes=True, kv_slots=256)
            mixed = run_mixed(
                qsim, mixed_plane,
                ratio=os.environ.get("BENCH_MIXED_RATIO", "90:9:1"),
                rounds=int(os.environ.get("BENCH_MIXED_ROUNDS", "16")),
                read_batch=mb, watchers=8, seed=0)
            mixed.setdefault("wall_s",
                             round(time.monotonic() - t_mixed, 2))
            _emit({"phase": "serving_mixed", "n": n, **mixed})
            del mixed_plane
    except Exception as e:
        _emit({"phase": "error", "where": "serving_mixed",
               "error": repr(e)[:500]})
    finally:
        del qsim

    # Game day (consul_tpu/gameday): the federated soak — composed
    # Partition+ChurnWave+RaftKill on the compiled schedule, sustained
    # mixed traffic through the chosen host frontend, a DCN federation
    # leg, and watchers on the reduction tree — distilled into the one
    # SLO verdict {pass, p99s, lost_writes, max_time_to_heal_ticks}.
    # BENCH_GAMEDAY=0 skips; BENCH_GAMEDAY_RESUME_DIR arms the
    # phase-boundary resume, and a SIGTERM mid-soak exits the child
    # with EX_TEMPFAIL (75) so the parent stamps the completed phases
    # instead of recording a crash.
    try:
        if left() > 240 and os.environ.get("BENCH_GAMEDAY", "1") != "0":
            from consul_tpu.gameday import GamedayConfig, run_gameday
            from consul_tpu.runtime.policy import SignalTrap

            t_gd = time.monotonic()
            gcfg = GamedayConfig(
                n=int(os.environ.get("BENCH_GAMEDAY_N", "1024")),
                view_degree=16,
                watchers=int(os.environ.get("BENCH_GAMEDAY_WATCHERS",
                                            "256")),
                read_batch=int(os.environ.get("BENCH_GAMEDAY_BATCH",
                                              "256")),
                frontend=os.environ.get("BENCH_GAMEDAY_FRONTEND",
                                        "threaded"),
                steady_rounds=2, fault_rounds=4, heal_rounds=2,
                drain_rounds=2,
                resume_dir=os.environ.get("BENCH_GAMEDAY_RESUME_DIR")
                or None)
            with SignalTrap() as trap:
                verdict = run_gameday(gcfg, trap=trap)
            verdict.pop("thresholds", None)
            _emit({"phase": "gameday",
                   "wall_s": round(time.monotonic() - t_gd, 2),
                   **verdict})
            if trap.fired is not None:
                # Preempted mid-soak with resume state saved: hand the
                # parent the sysexits EX_TEMPFAIL verdict it maps to
                # "preempted" (completed phases stamped, not a crash).
                return 75
    except Exception as e:
        _emit({"phase": "error", "where": "gameday",
               "error": repr(e)[:500]})

    # Weak/strong scaling over the device ladder (1, 2, 4, ... up to
    # the visible count): strong holds n fixed (BENCH_SCALING_N) while
    # devices grow, weak grows n with the devices
    # (BENCH_SCALING_PER_CHIP per device). Each rung rebuilds the sim
    # on a mesh truncated to that device count, so the measured
    # rounds/s is the shard_map program at that grid — the d=1 rung is
    # the true single-device program (no shard_map), the efficiency
    # denominator. parallel_efficiency: strong = rps(d) / (d * rps(1))
    # (ideal speed-up is linear), weak = rps(d) / rps(1) (ideal rate is
    # flat as work grows with the chips). Entries emit incrementally —
    # a deadline mid-ladder keeps the rungs already measured.
    try:
        scaling_chunk = int(os.environ.get("BENCH_SCALING_CHUNK", "32"))
        strong_n = int(os.environ.get("BENCH_SCALING_N", "16384"))
        per_chip = int(os.environ.get("BENCH_SCALING_PER_CHIP", "2048"))
        visible = bench_devices or len(jax.devices())

        def scaling_rung(n_s, d):
            t_w = time.monotonic()
            zsim = build(n_s, device_count=d)
            zsim.run(scaling_chunk, chunk=scaling_chunk,
                     with_metrics=False)  # warm + compile
            jax.block_until_ready(zsim.state.view_key)
            warm_s = time.monotonic() - t_w
            reps = 2
            t1 = time.monotonic()
            zsim.run(scaling_chunk * reps, chunk=scaling_chunk,
                     with_metrics=False)
            jax.block_until_ready(zsim.state.view_key)
            del zsim
            return (scaling_chunk * reps / (time.monotonic() - t1),
                    warm_s)

        for kind, fixed in (("scaling_strong", True), ("scaling_weak", False)):
            try:
                if left() < 120:
                    _emit({"phase": kind, "entries": [],
                           "skipped": "deadline"})
                    continue
                entries, base_rps = [], None
                ladder_compile_s = 0.0
                t_ladder = time.monotonic()
                d = 1
                while d <= visible:
                    n_s = strong_n if fixed else per_chip * d
                    if n_s % d == 0 and left() > 90:
                        rps, warm_s = scaling_rung(n_s, d)
                        ladder_compile_s += warm_s
                        if d == 1:
                            base_rps = rps
                        denom = (d * base_rps if fixed else base_rps) \
                            if base_rps else None
                        entries.append({
                            "devices": d,
                            "n": n_s,
                            "rounds_per_s": round(rps, 2),
                            "rounds_per_s_per_chip": round(rps / d, 2),
                            "compile_s": round(warm_s, 1),
                            "parallel_efficiency":
                                round(rps / denom, 3) if denom else None,
                        })
                    d *= 2
                _emit({"phase": kind, "chunk": scaling_chunk,
                       "devices_visible": visible,
                       **({"n": strong_n} if fixed
                          else {"per_chip": per_chip}),
                       "entries": entries,
                       "wall_s": round(time.monotonic() - t_ladder, 2),
                       "compile_s": round(ladder_compile_s, 1)})
            except Exception as e:
                _emit({"phase": "error", "where": kind,
                       "error": repr(e)[:500]})
    except Exception as e:
        _emit({"phase": "error", "where": "scaling", "error": repr(e)[:500]})

    # Scaling sweep: throughput at each shape, each its own try/except,
    # each gated on remaining deadline (SURVEY §7 phases 4-5 shapes).
    def northstar(sim, s, rps, phase_name, events=0):
        run_northstar(sim, s, rps, phase_name, chunk=chunk,
                      kill_frac=kill_frac, left=left, emit=_emit,
                      events=events)

    sweep_env = os.environ.get("BENCH_SWEEP", "")
    for s in [int(x) for x in sweep_env.split(",") if x.strip()]:
        if left() < 120:
            _emit({"phase": "sweep_skipped", "n": s, "reason": "deadline"})
            continue
        try:
            t = time.monotonic()
            cc0 = compile_cache.stats()
            ssim = build(s)
            ssim.run(chunk, chunk=chunk, with_metrics=False)
            jax.block_until_ready(ssim.state.view_key)
            compile_s = time.monotonic() - t
            t1 = time.monotonic()
            ssim.run(chunk, chunk=chunk, with_metrics=False)
            jax.block_until_ready(ssim.state.view_key)
            sweep_wall = time.monotonic() - t1
            rps = chunk / sweep_wall
            _emit({
                "phase": "sweep",
                "n": s,
                "rounds_per_s": round(rps, 2),
                "wall_s": round(sweep_wall, 2),
                "compile_s": round(compile_s, 1),
                "compile_cache": compile_cache.stats_delta(cc0),
            })
            # The north star (BASELINE.json): converge a 1M-node LAN —
            # mass failure to full agreement — in < 60 s wall-clock.
            # Only attempted when the measured rate could plausibly get
            # there within the remaining deadline (a CPU backend at
            # ~0.03 rounds/s skips; a TPU window records it).
            if s >= 1_000_000 and rps * min(left() - 120, 600) > 512:
                northstar(ssim, s, rps, "northstar")
            del ssim
            # Full-serf numbers at scale (round-3 verdict items 2/10:
            # the event plane live is the product's real step; record
            # its throughput beside SWIM-only at the big shapes, and at
            # 1M attempt the FULL-STACK north star — mass-kill to
            # agreement with the event plane running throughout).
            serf_min = int(os.environ.get("BENCH_SERF_SWEEP_MIN", "262144"))
            if s >= serf_min and left() > 240:
                t3 = time.monotonic()
                cc1 = compile_cache.stats()
                fsim = build(s, cls=SerfSimulation)
                fsim.run(chunk, chunk=chunk, with_metrics=False)
                fsim.user_event(jnp.arange(s) < 8, 1)
                jax.block_until_ready(fsim.state.ev_key)
                serf_compile = time.monotonic() - t3
                t4 = time.monotonic()
                fsim.run(chunk, chunk=chunk, with_metrics=False)
                jax.block_until_ready(fsim.state.ev_key)
                serf_sweep_wall = time.monotonic() - t4
                srps = chunk / serf_sweep_wall
                _emit({
                    "phase": "serf_sweep",
                    "n": s,
                    "rounds_per_s": round(srps, 2),
                    "wall_s": round(serf_sweep_wall, 2),
                    "compile_s": round(serf_compile, 1),
                    "compile_cache": compile_cache.stats_delta(cc1),
                })
                # The serf north star is first-class: 5% mass-kill PLUS
                # an event storm riding the fused gossip core throughout
                # convergence (the product's real step under load).
                if s >= 1_000_000 and srps * min(left() - 120, 600) > 512:
                    northstar(fsim, s, srps, "northstar_serf",
                              events=int(os.environ.get(
                                  "BENCH_EVENT_STORM", "8")))
                del fsim
        except Exception as e:
            _emit({"phase": "error", "where": f"sweep:{s}", "error": repr(e)[:400]})
    # Flight-recorder artifact (obs/trace.py): the host-span ring this
    # child accumulated — chunk markers, xla.backend_compile spans, the
    # serving/checkpoint/DCN seams — exported as one Perfetto-loadable
    # file. Opt-in via BENCH_TRACE_DIR; the path is stamped with the
    # platform so the TPU and CPU children never clobber each other.
    trace_dir = os.environ.get("BENCH_TRACE_DIR", "")
    if trace_dir:
        try:
            from consul_tpu.obs import trace as obs_trace

            t_tr = time.monotonic()
            tracer = obs_trace.get_tracer()
            trace_path = tracer.export(
                os.path.join(trace_dir, f"bench_{platform}_trace.json"))
            _emit({"phase": "trace", "path": trace_path,
                   "events": len(tracer.events()),
                   "dropped_events": tracer.dropped,
                   "wall_s": round(time.monotonic() - t_tr, 2)})
        except Exception as e:
            _emit({"phase": "error", "where": "trace",
                   "error": repr(e)[:500]})
    # Whole-child cache provenance: cumulative hits/misses, so the
    # parent can record whether THIS process compiled or deserialized.
    _emit({"phase": "compile_cache", **compile_cache.stats(),
           "wall_s": round(time.monotonic() - t0, 1)})
    return 0


_CKPT_DIR = os.path.join(_HERE, ".bench_ckpt")


def run_northstar(sim, s, rps, phase_name, *, chunk, kill_frac, left, emit,
                  events: int = 0, ckpt_every_ticks: int = 512,
                  ckpt_dir: str = _CKPT_DIR,
                  ckpt_min_interval_s: float = 120.0):
    """The 1M mass-kill convergence attempt (BASELINE.json): warm the
    metrics-on runner OUTSIDE the timed region, bound the run by the
    measured rate (``rps``) and remaining deadline so a marginal
    backend emits a (failed) result, never a SIGKILL.

    Mid-run checkpoint/resume (SURVEY §5: device arrays -> host
    container; the serf snapshot rejoin-fast precedent, reference
    serf/snapshot.go:59-431): the sim state is snapshotted through
    utils/checkpoint (digest-verified, atomic-rename) at most once per
    ``ckpt_min_interval_s`` of WALL time — a 1M-node save drags the
    whole device state through the remote-TPU tunnel (~150 s measured
    round 5), so tick-paced saves would dominate the run — plus one
    final save whenever the attempt exits unconverged, so a tunnel
    loss or budget exhaustion mid-northstar costs at most one slice:
    the next bench run RESUMES from the checkpoint (provenance in the
    emitted phase: ``resumed_from_tick``) instead of restarting.
    ``ckpt_every_ticks`` only bounds the convergence-check slice size.
    Only a CONVERGED attempt retires its checkpoint.

    The mechanism itself lives in consul_tpu/runtime (CheckpointPolicy:
    the generalized wall-paced, digest-verified, atomic save/restore
    every entry point shares); this function owns only the northstar
    specifics — warm-up, kill injection, rate-bounded budget, and the
    phase dict. ``manifest_meta=False`` keeps the artifact layout this
    phase has always written (provenance in the sidecar only)."""
    import jax
    import jax.numpy as jnp

    from consul_tpu.runtime import CheckpointPolicy
    from consul_tpu.utils import compile_cache

    # Warm the metrics-on runner outside the timed region, but RECORD
    # what it cost: compile time is a real (one-off) part of the
    # attempt's wall, and folding it into ``wall_s`` would poison the
    # <60 s convergence verdict while hiding it loses the number. The
    # cache delta makes a near-zero compile_s legible: with
    # --compile-cache, a second cold process records hits here.
    t_warm = time.monotonic()
    cc0 = compile_cache.stats()
    sim.run(chunk, chunk=chunk, with_metrics=True)  # warm, untimed
    jax.block_until_ready(sim.state.view_key)
    compile_s = time.monotonic() - t_warm
    # The kill fraction is part of the trajectory's identity: a resume
    # under a different BENCH_KILL_FRAC would continue the OLD kill
    # while publishing the new one as provenance.
    policy = CheckpointPolicy(
        directory=ckpt_dir, tag=f"{phase_name}_{s}",
        min_interval_s=ckpt_min_interval_s, manifest_meta=False,
        sink=getattr(sim, "sink", None))
    ident = {"phase": phase_name, "n": s, "kill_frac": kill_frac}
    resumed_tick = 0
    try:
        state, meta = policy.load(sim.state, match=ident)
        if state is not None:
            sim.state = state
            resumed_tick = int(meta["ticks_done"])
    except Exception as e:  # noqa: BLE001 — a bad ckpt restarts clean
        emit({"phase": f"{phase_name}_ckpt_error",
              "error": repr(e)[:200]})
        resumed_tick = 0
    if resumed_tick == 0:
        # Fresh attempt: inject the mass failure. A resumed state
        # already carries it (checkpoints are taken post-kill).
        sim.kill(jnp.arange(s) < int(s * kill_frac))
        if events:
            # Event storm at kill time (serf north star): the fused
            # event plane carries live traffic through the whole
            # convergence window, not an idle second plane.
            sim.user_event(jnp.arange(s) < events, 1)
    budget_ticks = int(rps * max(left() - 90, 60))
    max_ticks = max(chunk, min(4096, budget_ticks))
    ticks_done = resumed_tick
    converged = False
    t0_ns = time.monotonic()
    # Checkpoint cadence is WALL-based, not tick-based: a 1M-node
    # save drags the whole device state through the remote-TPU tunnel
    # (round-5 measurement: ~150 s per save — tick-based saves turned
    # a 53 s northstar into 357 s). Resume exists to bound lost wall
    # time, so pace saves by wall time: a run converging inside the
    # interval pays for zero checkpoints, a genuinely long/wedged run
    # still gets one every ``ckpt_min_interval_s``.
    policy.mark_run_start()
    slice_idx = 0
    while ticks_done - resumed_tick < max_ticks and not converged:
        if events and slice_idx:
            # Keep the storm live across checkpoint slices: fresh
            # events each slice (names cycle within the u8 name space).
            sim.user_event(jnp.arange(s) < events, 2 + (slice_idx % 250))
        slice_idx += 1
        slice_t = min(max(ckpt_every_ticks, chunk),
                      max_ticks - (ticks_done - resumed_tick))
        converged, used, _ = sim.run_until_converged(
            max_ticks=slice_t, chunk=chunk)
        ticks_done += used
        exhausted = ticks_done - resumed_tick >= max_ticks
        # Interval-paced mid-run saves, plus ALWAYS a final save when
        # the attempt ends unconverged — otherwise a short-budget run
        # would leave nothing behind and the next run re-injects the
        # kill from tick 0, voiding the resume guarantee. try_save:
        # a checkpoint failure must never fail the attempt (it is
        # counted and the first one logged, runtime/policy.py).
        if not converged and (policy.wall_due() or exhausted):
            policy.try_save(sim.state, dict(ident, ticks_done=ticks_done))
    wall = time.monotonic() - t0_ns
    if converged:
        # Only a COMPLETED attempt retires its checkpoint; an
        # unconverged budget-exhausted one keeps it so the next bench
        # run (or round) continues the same trajectory.
        policy.retire()
    emit({
        "phase": phase_name,
        "n": s,
        "converged": bool(converged),
        "kill_frac": kill_frac,
        "wall_s": round(wall, 2),
        "compile_s": round(compile_s, 1),
        "compile_cache": compile_cache.stats_delta(cc0),
        "events": int(events),
        "ticks": int(ticks_done),
        "max_ticks": int(max_ticks),
        "resumed_from_tick": int(resumed_tick),
        "ckpt_failures": int(policy.failures),
        "target_wall_s": 60.0,
        # A resumed attempt's wall covers only the post-resume slice
        # and excludes compile_s; the <60s verdict is only meaningful
        # for uninterrupted runs.
        "met": bool(converged) and wall < 60.0 and resumed_tick == 0,
    })


# ----------------------------------------------------------------------
# Parent: orchestrate children, merge, always print one line, rc=0.
# ----------------------------------------------------------------------

# Exit codes that mean "preempted, resumable" rather than "crashed":
# sysexits EX_TEMPFAIL (a child that trapped SIGTERM, checkpointed,
# and exited deliberately) and a raw SIGTERM kill (the watchdog or
# the platform got there before the trap).
_PREEMPT_RCS = (75, -signal.SIGTERM)


def _child_status(status, returncode):
    """Map a finished child's exit to its status string. Preemption is
    its own state — the harvested phases are completed work to resume
    past, not debris from a crash."""
    if status != "ok":
        return status
    if returncode in (0, None):
        return "ok"
    if returncode in _PREEMPT_RCS:
        return "preempted"
    return f"rc={returncode}"


def _run_child(platform: str, timeout_s: float, extra_env=None,
               init_window_s: float = 300.0):
    """Run one backend child; harvest its per-phase JSON lines.

    ``init_window_s``: a child that has not emitted its ``setup`` phase
    by then is killed early — a healthy backend initializes in seconds,
    while a wedged TPU relay hangs *inside* ``jax.devices()``
    indefinitely; waiting out the full budget on it could push the
    whole bench past an outer harness timeout and lose the output."""
    env = dict(os.environ)
    env["BENCH_CHILD"] = platform
    env["BENCH_DEADLINE_S"] = str(timeout_s)
    env.update(extra_env or {})
    fd, out_path = tempfile.mkstemp(prefix=f"bench_{platform}_", suffix=".jsonl")
    phases, status = [], "ok"
    t0 = time.monotonic()
    raw_tail = []

    def _setup_seen():
        # Parse each line (tolerating a partially-written last line)
        # rather than string-matching the literal json.dumps output —
        # a formatting change in _emit must not silently disable the
        # init-hang watchdog.
        try:
            with open(out_path) as f:
                for ln in f:
                    try:
                        obj = json.loads(ln)
                    except ValueError:
                        continue  # stderr fragment / partial last line
                    if isinstance(obj, dict) and obj.get("phase") == "setup":
                        return True
        except OSError:
            pass
        return False

    # Backend-init black box (obs/blackbox.py): an INIT_HANG kill
    # captures env/libtpu/device-progress plus the child's own last
    # output into a per-attempt timestamped directory, and the path
    # rides the attempt dict so with_failover provenance links it.
    bb_dir = os.path.join(
        os.environ.get("BENCH_BLACKBOX_DIR",
                       os.path.join(_HERE, ".bench_blackbox")),
        f"{platform}_{int(t0 * 1000)}")
    wd = runtime_watchdog.InitWatchdog(
        init_window_s=init_window_s, blackbox_dir=bb_dir)
    try:
        with os.fdopen(fd, "w") as out:
            proc = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__)],
                stdout=out, stderr=subprocess.STDOUT, env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            # The supervision loop lives in consul_tpu/runtime (stdlib-
            # only — this parent process must stay jax-free): kill the
            # child early when the init window passes without a setup
            # phase, or at the hard deadline either way.
            status = wd.watch(
                proc, _setup_seen, deadline=t0 + timeout_s,
                child_tail=lambda: obs_blackbox.tail_file(out_path))
        with open(out_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    phases.append(json.loads(line))
                except ValueError:
                    raw_tail.append(line[:200])
    except OSError:
        pass
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass
    status = _child_status(status, proc.returncode)
    out = {
        "status": status,
        "wall_s": round(time.monotonic() - t0, 1),
        # The platform this child was ASKED to run. A hung backend
        # init never emits its setup phase, so the observed platform
        # alone would leave an empty ``backends.tpu_attempt.platform``
        # in the artifact exactly when the provenance matters most.
        "platform_requested": platform,
        "phases": phases,
        "log_tail": raw_tail[-3:],
        # The init-hang postmortem artifact path (None on every other
        # outcome) — with_failover lifts it into attempt provenance.
        "blackbox": getattr(wd, "blackbox_path", None),
    }
    if status == "preempted":
        # Stamp what the child FINISHED before the preemption signal:
        # the resume path (gameday phase-boundary checkpoints, replay
        # keeping live phases) picks up after the last completed
        # phase instead of restarting the whole round.
        out["preempted"] = True
        out["completed_phases"] = [
            p["phase"] for p in phases
            if isinstance(p, dict) and p.get("phase")
            and p["phase"] != "error"
        ]
    return out


def _get(phases, name, key, default=None):
    for p in phases:
        if p.get("phase") == name and key in p:
            return p[key]
    return default


_SESSION_LATEST = os.path.join(_HERE, "BENCH_TPU_SESSION_LATEST.json")


def _latest_tpu_session():
    """Freshest committed TPU session artifact (result dict, path, when).

    Freshness is the artifact's own ``recorded_at`` stamp; an artifact
    without one (pre-provenance rounds) sorts behind every stamped one
    and reports ``when=None`` — file mtime is checkout time on a fresh
    clone, so using it would fabricate freshness."""
    best, best_path, best_t = None, None, (-1, -1.0)
    for p in glob.glob(os.path.join(_HERE, "BENCH_TPU_SESSION*.json")):
        try:
            with open(p) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        if "tpu" not in str(d.get("device", "")).lower() or not d.get("value"):
            continue
        try:
            rec = float(d.get("recorded_at"))
        except (TypeError, ValueError):
            rec = None
        t = (1, rec) if rec else (0, os.path.getmtime(p))
        if t > best_t:
            best, best_path, best_t = d, p, t
    when = best_t[1] if best is not None and best_t[0] == 1 else None
    return best, best_path, when


def _save_tpu_session(result):
    try:
        with open(_SESSION_LATEST, "w") as f:
            json.dump(dict(result, recorded_at=time.time()), f)
    except OSError:
        pass


# Stable result keys that hold a whole child phase dict. Every one of
# them is stamped {"status": "not_run", "reason": ...} when its phase
# never executed — a bare null reads as "lost in transit" downstream,
# while not_run + reason records the skip as a deliberate outcome.
_PHASE_KEYS = ("northstar_1m", "northstar_1m_serf", "compile_cache",
               "elasticity", "memory", "serving", "serving_mixed",
               "scaling_strong", "scaling_weak", "topology", "trace",
               "raft", "gameday", "kernel")


def _phase_or_not_run(phases, name, reason, pick=None):
    """First phase dict matching `name`, optionally projected through
    `pick`; an explicit not_run marker (never a bare null) when the
    child skipped or never reached the phase."""
    for p in phases:
        if p.get("phase") == name:
            return pick(p) if pick else p
    return {"status": "not_run", "reason": reason}


def _maybe_replay(result):
    """When the live TPU window is dead, re-emit the freshest in-session
    TPU artifact as the primary result — with explicit provenance, so
    the round's artifact records real chip numbers AND the fact that
    they were measured earlier in the session, not at round end."""
    saved, path, when = _latest_tpu_session()
    if saved is None:
        return result
    merged = dict(saved)
    merged["replayed_from"] = os.path.basename(path)
    # Honesty marker for downstream consumers: every replayed headline
    # is stale by construction — measured earlier in the session, not
    # at round end — and must never be read as a live observation.
    merged["stale"] = True
    if when is not None:
        merged["replay_recorded_at"] = round(when, 1)
        merged["replay_age_s"] = round(max(0.0, time.time() - when), 1)
    else:
        merged["replay_recorded_at"] = None
        merged["replay_age_s"] = None
        merged["replay_freshness"] = (
            "unknown: artifact predates recorded_at provenance"
        )
    merged["replay_reason"] = result["backends"]["tpu_attempt"]["status"]
    merged.pop("recorded_at", None)
    # Live observations from THIS run stay live.
    merged["cpu_fallback"] = result["cpu_fallback"]
    merged["backends"] = dict(
        saved.get("backends", {}),
        tpu_attempt=result["backends"]["tpu_attempt"],
        cpu=result["backends"]["cpu"],
    )
    merged["total_wall_s"] = result["total_wall_s"]
    # Replayed artifacts may predate newer stable keys (or carry bare
    # nulls from before the not_run contract): stamp every absent phase
    # key explicitly, and mark surviving not_run entries stale so they
    # are never mistaken for a this-run skip decision.
    base = os.path.basename(path)
    # A phase the LIVE chip attempt completed before dying (preemption
    # mid-soak, deadline mid-ladder) beats any replayed copy: the
    # merged artifact resumes from the last completed phase rather than
    # discarding this round's work for an older, stale one. Gated on
    # the live primary actually being the chip — phases measured by the
    # CPU floor child must never masquerade inside a TPU artifact.
    live_is_chip = "tpu" in str(result.get("device", "")).lower()
    resumed = []
    for k in _PHASE_KEYS:
        live = result.get(k) if live_is_chip else None
        if isinstance(live, dict) and live.get("status") != "not_run":
            merged[k] = live
            resumed.append(k)
            continue
        v = merged.get(k)
        if not v:
            merged[k] = {
                "status": "not_run",
                "reason": f"absent from replayed artifact {base}",
                "stale": True,
            }
        elif isinstance(v, dict) and v.get("status") == "not_run":
            merged[k] = dict(v, stale=True)
    if resumed:
        merged["live_phases"] = resumed
    return merged


def main():
    # --compile-cache DIR (same as CONSUL_TPU_COMPILE_CACHE): exported
    # into the child env — this parent never imports jax, so the string
    # is spelled here rather than imported from utils/compile_cache.
    argv = sys.argv[1:]
    if "--compile-cache" in argv:
        i = argv.index("--compile-cache")
        if i + 1 < len(argv):
            os.environ["CONSUL_TPU_COMPILE_CACHE"] = argv[i + 1]
    # --prewarm: each child AOT-compiles its program signatures into
    # the persistent cache before any timed phase (BENCH_PREWARM is
    # inherited through _run_child's env copy).
    if "--prewarm" in argv:
        os.environ["BENCH_PREWARM"] = "1"
    platform_child = os.environ.get("BENCH_CHILD")
    if platform_child:
        deadline = time.monotonic() + float(
            os.environ.get("BENCH_DEADLINE_S", "1200")
        ) - 60.0
        return child(platform_child, deadline)

    total_budget = float(os.environ.get("BENCH_TIMEOUT_TOTAL", "2400"))
    cpu_timeout = float(os.environ.get("BENCH_TIMEOUT_CPU", "600"))
    t_all = time.monotonic()

    # CPU fallback FIRST: it is fast and cannot hang, so even if an
    # outer harness timeout kills this process mid-TPU-attempt, the
    # recorded artifact era is bounded by the cheap phase — and the TPU
    # attempt gets whatever budget remains.
    cpu = _run_child(
        "cpu", cpu_timeout,
        {"BENCH_N": os.environ.get("BENCH_CPU_N", "4096"), "BENCH_SWEEP": ""},
    )
    cpu_ok = _get(cpu["phases"], "throughput", "rounds_per_s")

    tpu_timeout = max(
        120.0,
        min(float(os.environ.get("BENCH_TIMEOUT_TPU", "1800")),
            total_budget - (time.monotonic() - t_all) - 30.0),
    )
    # TPU attempt: the default platform (the axon plugin), full sweep —
    # under the single-flight device lock. A held lock means another
    # JAX client owns the chip; starting a second one can wedge the
    # relay, so record tpu-busy and rely on the replay path instead.
    lock_wait = float(os.environ.get("BENCH_TPU_LOCK_WAIT", "300"))
    t_lock = time.monotonic()
    lock_state = tpu_lock.try_acquire("bench.py", wait_s=lock_wait)
    failover = None
    if lock_state != "busy":
        # "acquired" — or a lock I/O error ("error:..."), in which case
        # no other process could have taken the lock either; proceed
        # with the attempt and record the lock trouble as a diagnostic.
        # The attempt runs under runtime.with_failover: a backend-init-
        # hang gets bounded retries (BENCH_INIT_RETRIES, each bounded
        # by the remaining budget), then an EXPLICIT degraded-mode CPU
        # failover — the already-measured CPU child is the degraded
        # result, and the provenance (degraded_from, retries,
        # hang_wall_s) rides in the artifact instead of being implied
        # by a dead tpu_attempt status.
        last = {}

        def _attempt(plat):
            if plat == "cpu":
                return cpu  # degraded mode reuses the measured child
            budget_left = total_budget - (time.monotonic() - t_all) - 30.0
            if budget_left < 120.0:
                r = {"status": "budget-exhausted", "wall_s": 0.0,
                     "platform_requested": "default",
                     "phases": [], "log_tail": []}
            else:
                r = _run_child(
                    "default", min(tpu_timeout, budget_left),
                    {"BENCH_SWEEP": os.environ.get(
                        "BENCH_SWEEP", "4096,262144,1048576")},
                )
            last[plat] = r
            return r

        try:
            _, failover = runtime_watchdog.with_failover(
                _attempt, ("default", "cpu"),
                max_retries=int(os.environ.get("BENCH_INIT_RETRIES", "1")))
        finally:
            if lock_state == "acquired":
                tpu_lock.release()
        tpu = last.get("default") or {
            "status": "budget-exhausted", "wall_s": 0.0,
            "platform_requested": "default",
            "phases": [], "log_tail": []}
        if lock_state != "acquired":
            tpu["lock_error"] = lock_state
    else:
        tpu = {"status": "tpu-busy",
               "wall_s": round(time.monotonic() - t_lock, 1),
               "platform_requested": "default",
               "phases": [], "log_tail": [],
               "holder": tpu_lock.holder()}
    tpu_ok = _get(tpu["phases"], "throughput", "rounds_per_s")
    # Observed platform when the child got as far as its setup phase;
    # the requested one otherwise (init hang / busy / budget paths), so
    # the attempt provenance is never an empty string.
    tpu_platform = (_get(tpu["phases"], "setup", "platform", "")
                    or tpu.get("platform_requested", ""))

    # The default child is the full-size run (TPU when reachable; the
    # same shapes on CPU otherwise) — prefer it whenever it produced a
    # number; the quick CPU child is only the never-empty floor.
    primary = tpu if tpu_ok is not None else cpu
    value = _get(primary["phases"], "throughput", "rounds_per_s")
    result = {
        "metric": "gossip-rounds/sec/chip",
        "value": value if value is not None else 0.0,
        "unit": "rounds/s",
        # Speed-up over the protocol's real-time cadence (one gossip
        # round per 200 ms, reference memberlist/config.go:252).
        "vs_baseline": round(value / 5.0, 1) if value else 0.0,
        "n_nodes": _get(primary["phases"], "throughput", "n"),
        "view_degree": _get(primary["phases"], "throughput", "view_degree"),
        "device": _get(primary["phases"], "setup", "platform", "none"),
        "converged": _get(primary["phases"], "convergence", "converged"),
        "detect_converge_wall_s": _get(primary["phases"], "convergence", "wall_s"),
        "detect_converge_sim_s": _get(primary["phases"], "convergence", "sim_s"),
        "vivaldi_rmse_ms": _get(primary["phases"], "rmse", "vivaldi_rmse_ms"),
        "agreement": _get(primary["phases"], "rmse", "agreement"),
        "serf_rounds_per_s": _get(
            primary["phases"], "serf_throughput", "rounds_per_s"),
        "serf_idle_rounds_per_s": _get(
            primary["phases"], "serf_idle", "rounds_per_s"),
        # Cumulative on-device gossip counters (models/counters.py) from
        # the primary backend, preferring the convergence phase (it
        # includes the throughput ticks — the dict is cumulative per
        # Simulation). Stable key for downstream BENCH json consumers.
        "counters": (
            _get(primary["phases"], "convergence", "counters")
            or _get(primary["phases"], "throughput", "counters")
        ),
        "serf_counters": _get(
            primary["phases"], "serf_throughput", "counters"),
        # Chaos convergence SLOs (consul_tpu/chaos): stable keys
        # fault_ticks / time_to_first_suspect / time_to_confirm /
        # time_to_heal / false_positive_deaths / messages_dropped.
        "chaos": _get(primary["phases"], "chaos", "slo"),
        "chaos_n": _get(primary["phases"], "chaos", "n"),
        "sweep": [
            {"n": p["n"], "rounds_per_s": p["rounds_per_s"],
             "compile_s": p.get("compile_s")}
            for p in (tpu["phases"] if tpu else [])
            if p.get("phase") == "sweep"
        ],
        "serf_sweep": [
            {"n": p["n"], "rounds_per_s": p["rounds_per_s"],
             "compile_s": p.get("compile_s")}
            for p in (tpu["phases"] if tpu else [])
            if p.get("phase") == "serf_sweep"
        ],
        "northstar_1m": _phase_or_not_run(
            tpu["phases"] if tpu else [], "northstar",
            "needs a live TPU child with time budget left"),
        "northstar_1m_serf": _phase_or_not_run(
            tpu["phases"] if tpu else [], "northstar_serf",
            "needs a live TPU child with time budget left after "
            "northstar"),
        # Persistent-compilation-cache provenance for every compile_s
        # above: {"enabled", "dir", "hits", "misses"} from the primary
        # child (utils/compile_cache). A repeat run with --compile-cache
        # shows hits>0 and near-zero compile_s.
        "compile_cache": _phase_or_not_run(
            primary["phases"], "compile_cache",
            "child exited before the compile-cache report",
            pick=lambda p: {k: p.get(k) for k in
                            ("enabled", "dir", "hits", "misses")}),
        # Elastic-runtime drill (chip-loss resume + DCN fault heal):
        # the whole phase dict under one stable key — reshards,
        # digest_identical, and the nested dcn retry/heal counters.
        "elasticity": _phase_or_not_run(
            primary["phases"], "elasticity",
            "skipped: time budget exhausted or drill errored"),
        # MemoryBudget provenance (runtime/membudget.py): per-layout x
        # kind bytes/node, the packed compaction factor vs the dense
        # f32/i32 baseline, max-n-per-chip, and per-device peak HBM.
        # Stable key for downstream BENCH json consumers.
        "memory": _phase_or_not_run(
            primary["phases"], "memory",
            "skipped: time budget exhausted or planner errored"),
        # Pallas kernel A/B (ops/pallas_gossip.py): per-n entries of
        # {xla, pallas} rounds/s/chip + HBM bytes/tick/node per engine
        # and the speedup ratio. The item-1 TPU campaign reads this key
        # to A/B the fused tick against the 765.6 rounds/s/chip
        # headline without further code changes.
        "kernel": _phase_or_not_run(
            primary["phases"], "kernel",
            "needs a TPU chip (interpret-mode pallas on CPU is an "
            "evaluator, not a measurement; BENCH_KERNEL=1 forces a "
            "tiny-n smoke)"),
        # Serving-plane read throughput (consul_tpu/serving): batched
        # NearestN straight from the simulation tensors —
        # queries_per_sec_per_chip, p50/p99 batch latency, padding
        # waste %. Compare BASELINE.md KV GET (~7.5-16k req/s).
        "serving": _phase_or_not_run(
            primary["phases"], "serving",
            "skipped: time budget exhausted or phase errored"),
        # Mixed read/write/watch serving (consul_tpu/serving/mixed):
        # per-class counts, q/s/chip and p50/p99 under the R:W:Watch
        # ratio, plus write rejected/shed and watch deliveries.
        "serving_mixed": _phase_or_not_run(
            primary["phases"], "serving_mixed",
            "skipped: time budget exhausted or phase errored"),
        # Device-ladder scaling phases: entries of {devices, n,
        # rounds_per_s, rounds_per_s_per_chip, parallel_efficiency}
        # (strong: fixed n; weak: n grows per-chip). Stable keys for
        # the MULTICHIP trajectory artifacts.
        "scaling_strong": _phase_or_not_run(
            primary["phases"], "scaling_strong",
            "skipped: needs >1 visible device or time budget left"),
        "scaling_weak": _phase_or_not_run(
            primary["phases"], "scaling_weak",
            "skipped: needs >1 visible device or time budget left"),
        # Flight-recorder artifact (obs/trace.py): the primary child's
        # exported Perfetto trace path + event count. Opt-in — set
        # BENCH_TRACE_DIR to arm it; not_run otherwise.
        "trace": _phase_or_not_run(
            primary["phases"], "trace",
            "tracing disabled: set BENCH_TRACE_DIR to export the "
            "child's host-span ring"),
        # Topology-lab Pareto table (chaos/sweep.py bench_pareto):
        # bytes/tick/node vs time-to-heal per view-graph family at
        # equal degree, swept over one vmapped scenario grid, plus
        # which families strictly dominate the circulant default.
        "topology": _phase_or_not_run(
            primary["phases"], "topology",
            "skipped: time budget exhausted or sweep errored"),
        # Raft tier (ops/raft_ops.py): per-(groups x peers) ladder of
        # steady tick rate with the tier armed, elections/s under a
        # split-vote storm, and quorum-commit visibility latency of
        # proposed writes in ticks (chunk resolution).
        "raft": _phase_or_not_run(
            primary["phases"], "raft",
            "skipped: time budget exhausted or phase errored"),
        # Game-day soak verdict (consul_tpu/gameday): the single SLO
        # pass/fail over the composed-chaos federated soak — pass,
        # per-class p99s, lost_writes (must be 0), heal bound, watch
        # delivery lag, shed/reject counts, preemption/resume marks.
        "gameday": _phase_or_not_run(
            primary["phases"], "gameday",
            "skipped: time budget exhausted or soak errored"),
        # Mesh + prewarm provenance for the headline number: how many
        # devices the child saw, and what the AOT prewarm pass
        # compiled/deserialized before the timed phases.
        "devices": _get(primary["phases"], "setup", "devices"),
        "mesh": _get(primary["phases"], "throughput", "mesh"),
        "prewarm": [p for p in primary["phases"]
                    if p.get("phase") == "prewarm"] or None,
        "cpu_fallback": {
            "rounds_per_s": cpu_ok,
            "n_nodes": _get(cpu["phases"], "throughput", "n"),
            "converged": _get(cpu["phases"], "convergence", "converged"),
            "wall_s": _get(cpu["phases"], "convergence", "wall_s"),
            "vivaldi_rmse_ms": _get(cpu["phases"], "rmse", "vivaldi_rmse_ms"),
        },
        "backends": {
            "tpu_attempt": {
                "status": tpu["status"],
                "platform": tpu_platform,
                "wall_s": tpu["wall_s"],
                "errors": [p for p in tpu["phases"] if p.get("phase") == "error"],
                # Watchdog/failover provenance (runtime/watchdog.py):
                # degraded_from, retries, hang_wall_s, per-attempt log.
                # None when the attempt never ran (tpu-busy).
                "failover": failover,
                **{k: tpu[k] for k in ("holder", "lock_error") if k in tpu},
            },
            "cpu": {
                "status": cpu["status"],
                "wall_s": cpu["wall_s"],
                "errors": [p for p in cpu["phases"] if p.get("phase") == "error"],
            },
        },
        "total_wall_s": round(time.monotonic() - t_all, 1),
    }
    on_tpu = "tpu" in str(result.get("device", "")).lower() and result["value"]
    # Replay only when the TPU *window* actually died (init-hang /
    # timeout / busy / child crash, or a chip run that produced no
    # number) — a healthy CPU-platform run on a machine with no TPU is
    # an honest result, not a dead window, and must not be overwritten
    # by a stale committed artifact.
    window_dead = tpu["status"] != "ok" or (
        "tpu" in tpu_platform.lower() and tpu_ok is None
    )
    if on_tpu:
        _save_tpu_session(result)
    elif window_dead and os.environ.get("BENCH_NO_REPLAY", "") != "1":
        result = _maybe_replay(result)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
