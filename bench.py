"""Benchmark: gossip throughput + convergence on one chip.

Prints ONE JSON line:
  {"metric": "gossip-rounds/sec/chip", "value": N, "unit": "rounds/s",
   "vs_baseline": R, ...extras}

The scenario is the framework's north-star workload (BASELINE.md): a
formed LAN cluster, a mass failure injected, SWIM + Lifeguard + gossip +
push-pull converging every surviving view, Vivaldi coordinates learning
the ground-truth latency map throughout.

``vs_baseline``: the reference publishes no gossip-throughput numbers
(BASELINE.json ``published: {}``), so the baseline is the protocol's
real-time cadence — a real memberlist cluster advances one gossip round
per 200 ms (5 rounds/s, reference memberlist/config.go:252). The value
is therefore the per-chip simulation speed-up over real time.
"""

import json
import os
import sys
import time


def main():
    n = int(os.environ.get("BENCH_N", "4096"))
    kill_frac = float(os.environ.get("BENCH_KILL_FRAC", "0.05"))

    import jax

    # BENCH_PLATFORM=cpu runs the benchmark without the TPU (for local
    # validation). Note this environment pins jax_platforms via
    # jax.config in sitecustomize, so the env var must be applied here.
    platform = os.environ.get("BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    import jax.numpy as jnp

    from consul_tpu.config import SimConfig
    from consul_tpu.models.cluster import Simulation

    t_setup = time.perf_counter()
    cfg = SimConfig(n=n)
    sim = Simulation(cfg, seed=0)

    # Throughput: pure simulation rate, no host round-trips.
    rounds_per_s = sim.throughput(ticks=512)

    # Convergence: kill a block of nodes, run until every surviving
    # view agrees with ground truth.
    n_kill = int(n * kill_frac)
    sim.kill(jnp.arange(n) < n_kill)
    t0 = time.perf_counter()
    converged, ticks_used, trace = sim.run_until_converged(
        max_ticks=2048, chunk=256
    )
    wall_s = time.perf_counter() - t0
    rmse_ms = sim.rmse() * 1000.0

    sim_seconds = ticks_used * cfg.gossip.tick_ms / 1000.0
    result = {
        "metric": "gossip-rounds/sec/chip",
        "value": round(rounds_per_s, 1),
        "unit": "rounds/s",
        # Speed-up over the protocol's real-time cadence (5 rounds/s).
        "vs_baseline": round(rounds_per_s / 5.0, 1),
        "n_nodes": n,
        "converged": bool(converged),
        "kill_frac": kill_frac,
        "detect_converge_wall_s": round(wall_s, 2),
        "detect_converge_sim_s": round(sim_seconds, 1),
        "vivaldi_rmse_ms": round(rmse_ms, 3),
        "device": str(jax.devices()[0].platform),
        "total_wall_s": round(time.perf_counter() - t_setup, 1),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
