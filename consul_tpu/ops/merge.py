"""The SWIM membership-state merge semilattice.

The reference applies alive/suspect/dead messages serially with
per-message precedence rules (reference memberlist/state.go:868-1240):

  - alive(inc)   applies iff inc >  current inc          (state.go:991)
  - suspect(inc) applies iff inc >= current inc and the current state is
                 alive                                   (state.go:1086,1102)
  - dead(inc)    applies iff inc >= current inc and the current state is
                 not already dead                        (state.go:1174,1182)

For a vectorized, order-free formulation we canonicalize this as a join
semilattice over keys ``(incarnation, state priority)`` ordered
lexicographically, with priority alive=0 < suspect=1 < dead=2 < left=3.
Taking the max key over any batch of concurrent messages is associative,
commutative, and idempotent, so batched scatter-max delivery reaches the
same fixed point as any serial delivery order. The same three algebraic
properties are what let the sharded push-pull merge reductions fold
through the hierarchical recursive-doubling ladder
(``parallel/collective.py tree_psum``) instead of a flat all-reduce:
any reduction-tree shape over a semilattice reaches the same join, so
the (node-shard × DC) tree the fused serf core uses is
observationally identical to the flat fold it replaced.

Known canonicalization (documented divergence): the reference keeps a
dead(inc=5) entry even when a suspect(inc=6) arrives ("ignore non-alive
nodes", state.go:1102), whereas the lattice lets the higher incarnation
win. The reference's own outcome there depends on message arrival order
(dead(5) then suspect(6) keeps dead(5); the reverse order keeps
suspect(6)), i.e. it has no order-free answer to preserve — and the
suspicion timer re-kills the node either way, so the converged state is
identical.

Statuses also index the simulation's per-node ground truth; LEFT models
serf's graceful departure (reference serf/serf.go:1073-…).
"""

from __future__ import annotations

import jax.numpy as jnp

ALIVE = 0
SUSPECT = 1
DEAD = 2
LEFT = 3

N_STATUS = 4
_STATUS_BITS = 2

# Keys are uint32: incarnation in the high 30 bits, priority in the low 2.
# Incarnations only grow by refutation (one bump per suspect/dead message
# about a live node), so 2^30 headroom is far beyond any simulated run.
MAX_INCARNATION = (1 << 30) - 1


def make_key(incarnation, status):
    """Pack (incarnation, status) into a lexicographically ordered uint32."""
    inc = jnp.asarray(incarnation, jnp.uint32)
    st = jnp.asarray(status, jnp.uint32)
    return (inc << _STATUS_BITS) | st


def key_incarnation(key):
    return jnp.asarray(key, jnp.uint32) >> _STATUS_BITS


def key_status(key):
    return (jnp.asarray(key, jnp.uint32) & (N_STATUS - 1)).astype(jnp.int8)


def join(key_a, key_b):
    """The semilattice join: pointwise max of packed keys."""
    return jnp.maximum(jnp.asarray(key_a, jnp.uint32), jnp.asarray(key_b, jnp.uint32))


def demote_dead_to_suspect(key):
    """Map dead-state keys to suspect at the same incarnation.

    Push-pull anti-entropy never kills directly: a remote claim that a
    node is dead is downgraded to a suspicion so the node gets a chance to
    refute (reference memberlist/state.go:1231-1237, mergeState). LEFT is
    exempt: graceful departures are authoritative (serf handles them via
    leave intents, not suspicion). UNKNOWN (0, DEAD) is also exempt —
    "never heard of the subject" is not a death report, and demoting it
    would fabricate incarnation-0 suspicions (with live timers) out of a
    partner's mere ignorance.
    """
    key = jnp.asarray(key, jnp.uint32)
    st = key & (N_STATUS - 1)
    demote = (st == DEAD) & (key != UNKNOWN)
    return jnp.where(demote, (key & ~jnp.uint32(N_STATUS - 1)) | SUSPECT, key)


# Host-side scalar versions of the key algebra (plain ints, no device
# dispatch) — for the transport bridge and other per-fact host loops.

def make_key_int(incarnation: int, status: int) -> int:  # lint: host
    return (int(incarnation) << _STATUS_BITS) | int(status)


def key_incarnation_int(key: int) -> int:
    return int(key) >> _STATUS_BITS


def key_status_int(key: int) -> int:
    return int(key) & (N_STATUS - 1)


# "Never heard of this node": the cold-join sentinel. Distinct from a
# genuine death report, which always carries incarnation >= 1 (nodes are
# born at incarnation 1). Joins below anything, so the first real fact
# about the subject replaces it.
UNKNOWN = (0 << _STATUS_BITS) | DEAD


def is_contactable(key):
    """True where the holder may initiate protocol traffic toward the
    subject: believed alive or suspect (reference kRandomNodes excludes
    dead/left members, memberlist/util.go:125-153) — or never heard of
    at all. The UNKNOWN case models a configured join address (reference
    memberlist.Join dials addresses it has no state for,
    memberlist.go:228 -> pushPullNode state.go:595): a cold-rejoining
    node must be able to announce to / pull from / ping neighbors it has
    no information about, or it could never learn the cluster.
    Genuinely dead entries (incarnation >= 1) stay excluded.
    """
    key = jnp.asarray(key, jnp.uint32)
    st = key_status(key)
    return (st == ALIVE) | (st == SUSPECT) | (key == UNKNOWN)


def is_refutable(key, subject_is_self, own_incarnation):
    """True where a key claims self is suspect/dead at a current-or-newer
    incarnation — the condition under which a live node must refute by
    bumping its incarnation and broadcasting alive (reference
    memberlist/state.go:840-864 refute, :1107-1110, :1187-1192).
    """
    st = key_status(key)
    inc = key_incarnation(key)
    return (
        subject_is_self
        & ((st == SUSPECT) | (st == DEAD))
        & (inc >= jnp.asarray(own_incarnation, jnp.uint32))
    )
