"""Write-path and watch-delta kernels: batched catalog/KV/session
writes applied on device, and per-flip snapshot diffs for watchers.

This is the device tier of the serving *write* plane
(``consul_tpu/serving/writes.py`` / ``watch.py``) — the write-side twin
of ``ops/serving.py``. The host ``WriteBatcher`` coalesces concurrent
register/deregister, KV put/delete, and session ops into fixed-shape
:class:`WriteBatch` tensors (bucketed sizes, the ``models/cluster.py``
memoization idiom) and each batch runs as ONE jitted leader-apply
program here. A monotone raft-style **apply index** lives on device in
:class:`WriteState`; every applied op gets the next index, and every
snapshot flip carries the index it is consistent as of.

Batch semantics (the raft-log contract, ``server/state_store.py``'s
``_commit`` rule): ops apply in batch order, each applied op is
assigned ``apply_index + (its 1-based rank among applied ops)``, and
within one batch the last writer to a node/slot wins — exactly what a
sequential host replay of the same log produces. The host references
:func:`apply_writes_reference` / :func:`diff_snapshots_reference` ARE
that sequential replay (plain numpy, state-store style); the
golden-parity suite (tests/test_writes.py) pins the kernels to them
exactly, single-device and sharded.

Vectorization (lint-clean, no TH109 scatters): per-target last-writer
selection is an O(B·N) one-hot rank-max — ``sel[b, t]`` marks applied
ops addressing target ``t``, ``max_b sel·(b+1)`` finds the winning op,
and plain gathers pull its op/arg/index. B is capped by the batcher's
largest bucket (default 64), so the one-hot never dominates the [N]
state it updates.

Documented narrowings (COVERAGE.md "write/watch plane"): the device KV
models one i32 payload word per key slot (the host ``KeyTable`` owns
string-key -> slot allocation), and device sessions are one id per
node with no KV lock coupling — state-store lock/CAS semantics stay on
the host tier.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# Write ops. NOOP fills padding slots (never applied, never indexed).
OP_NOOP = 0
OP_REGISTER = 1         # target = node, arg = service label (>= 0)
OP_DEREGISTER = 2       # target = node
OP_KV_PUT = 3           # target = kv slot, arg = i32 payload word
OP_KV_DELETE = 4        # target = kv slot
OP_SESSION_CREATE = 5   # target = node, arg = session id (>= 0)
OP_SESSION_DESTROY = 6  # target = node

# Delta kinds for changed-node rows (bitmask).
CHANGE_SERVICE = 1      # service membership changed (label/registration)
CHANGE_WENT_LIVE = 2    # health transition dead -> live
CHANGE_WENT_DEAD = 4    # health transition live -> dead

# Compaction sort-key sentinel (the ops/serving.py discipline: changed
# rows keep their id order, unchanged rows never surface).
_PAD_KEY = float(jnp.finfo(jnp.float32).max)


class WriteState(NamedTuple):
    """Device-resident write-side state, node axis N + KV slot axis S.

    ``service``/``registered`` are the catalog truth the serving plane
    publishes as snapshot labels at every flip (a registered node's
    label is its service; an unregistered node reads as -1).
    ``apply_index`` is the monotone raft-style index: bumped once per
    applied op, stamped on every flip, surfaced as ``X-Consul-Index``.
    """

    service: jax.Array      # [N] i32 service label
    registered: jax.Array   # [N] bool
    session: jax.Array      # [N] i32 session id, -1 = none
    kv_used: jax.Array      # [S] bool
    kv_val: jax.Array       # [S] i32 payload word
    kv_ver: jax.Array       # [S] i32 apply index of last mutation
    apply_index: jax.Array  # [] i32 monotone apply index


class WriteBatch(NamedTuple):
    """One fixed-shape coalesced batch: ``op``/``target``/``arg`` are
    [B] i32, padding slots are OP_NOOP."""

    op: jax.Array
    target: jax.Array
    arg: jax.Array


class DeltaFrame(NamedTuple):
    """One flip-to-flip delta, fixed shape [K] (+ [] counts).

    ``node_ids`` holds the first K changed node ids ascending (-1 pad);
    ``node_kinds`` is the CHANGE_* bitmask per row; ``svc_prev`` /
    ``svc_cur`` are the service labels either side of the flip (-1 =
    unregistered) so service watchers of both the old and new label can
    be routed. ``kv_slots``/``kv_vers`` list the first K changed KV
    slots with their new version. Counts may exceed K — the watch plane
    marks such frames truncated rather than capping silently.
    """

    node_ids: jax.Array      # [K] i32
    node_kinds: jax.Array    # [K] i32 CHANGE_* bitmask
    svc_prev: jax.Array      # [K] i32
    svc_cur: jax.Array       # [K] i32
    n_node_changes: jax.Array  # [] i32
    kv_slots: jax.Array      # [K] i32
    kv_vers: jax.Array       # [K] i32
    n_kv_changes: jax.Array  # [] i32
    apply_index: jax.Array   # [] i32 (the newer flip's index)
    tick: jax.Array          # [] i32 (the newer snapshot's tick)


def init_state(n: int, kv_slots: int, service=None) -> WriteState:
    """Host-built initial WriteState (numpy; the caller device-places
    it — ``cluster._place_node``-style — so [N] leaves shard instead of
    replicating). Every sim seat starts registered with its synthetic
    service label, so attaching a write plane changes NO read until the
    first write lands."""
    if service is None:
        service = np.zeros(n, dtype=np.int32)
    return WriteState(
        service=np.asarray(service, dtype=np.int32),
        registered=np.ones(n, dtype=bool),
        session=np.full(n, -1, dtype=np.int32),
        kv_used=np.zeros(kv_slots, dtype=bool),
        kv_val=np.zeros(kv_slots, dtype=np.int32),
        kv_ver=np.zeros(kv_slots, dtype=np.int32),
        apply_index=np.int32(0),
    )


def _last_writer(sel: jax.Array):
    """Per-target last-writer-wins over an applied-op selection matrix
    ``sel [B, T]``: returns (has [T] bool, bi [T] i32) — whether any op
    addressed the target, and the batch row of the LAST one that did.
    Rank-max over ``(b+1)·sel`` instead of a scatter (TH109)."""
    b = sel.shape[0]
    rank = jnp.arange(1, b + 1, dtype=jnp.int32)
    last = jnp.max(sel.astype(jnp.int32) * rank[:, None], axis=0)
    return last > 0, jnp.maximum(last - 1, 0)


def _apply_writes(ws: WriteState, batch: WriteBatch):
    """One coalesced batch as one program; returns
    ``(new_state, applied [B] bool, index [B] i32)``.

    ``applied[i]`` is False for NOOP padding and out-of-range targets;
    ``index[i]`` is the apply index assigned to op i (the state's index
    after op i — unchanged for unapplied rows), so a write's HTTP
    response can report the index its effect becomes visible at.
    """
    n = ws.service.shape[0]
    s = ws.kv_used.shape[0]
    op, tgt, arg = batch.op, batch.target, batch.arg

    node_op = ((op == OP_REGISTER) | (op == OP_DEREGISTER)
               | (op == OP_SESSION_CREATE) | (op == OP_SESSION_DESTROY))
    kv_op = (op == OP_KV_PUT) | (op == OP_KV_DELETE)
    needs_arg = (op == OP_REGISTER) | (op == OP_SESSION_CREATE)
    in_range = jnp.where(node_op, (tgt >= 0) & (tgt < n),
                         (tgt >= 0) & (tgt < s))
    applied = (node_op | kv_op) & in_range & (~needs_arg | (arg >= 0))

    # Per-op assigned index: apply_index + 1-based rank among applied.
    opidx = ws.apply_index + jnp.cumsum(applied.astype(jnp.int32))

    def family(width, in_family):
        sel = (applied & in_family)[:, None] \
            & (tgt[:, None] == jnp.arange(width, dtype=jnp.int32)[None, :])
        has, bi = _last_writer(sel)
        return has, op[bi], arg[bi], opidx[bi]

    # Catalog family: register/deregister -> service + registered.
    has, fop, farg, _ = family(
        n, (op == OP_REGISTER) | (op == OP_DEREGISTER))
    service = jnp.where(has & (fop == OP_REGISTER), farg, ws.service)
    service = jnp.where(has & (fop == OP_DEREGISTER), jnp.int32(-1),
                        service)
    registered = jnp.where(has, fop == OP_REGISTER, ws.registered)

    # Session family: one id per node (no KV lock coupling — see the
    # module-docstring narrowing).
    has, fop, farg, _ = family(
        n, (op == OP_SESSION_CREATE) | (op == OP_SESSION_DESTROY))
    session = jnp.where(has & (fop == OP_SESSION_CREATE), farg, ws.session)
    session = jnp.where(has & (fop == OP_SESSION_DESTROY), jnp.int32(-1),
                        session)

    # KV family: slot-addressed put/delete; version = mutating op's
    # index (deletes bump it too, the state-store table-index rule).
    has, fop, farg, fidx = family(s, kv_op)
    kv_val = jnp.where(has & (fop == OP_KV_PUT), farg, ws.kv_val)
    kv_used = jnp.where(has, fop == OP_KV_PUT, ws.kv_used)
    kv_ver = jnp.where(has, fidx, ws.kv_ver)

    new = WriteState(
        service=service, registered=registered, session=session,
        kv_used=kv_used, kv_val=kv_val, kv_ver=kv_ver,
        apply_index=ws.apply_index
        + jnp.sum(applied.astype(jnp.int32)))
    return new, applied, opidx


# One jit object; jit's own shape cache yields one executable per
# (B bucket, N, S) — the compile-ledger pin in tests/test_writes.py
# holds steady-state writes to zero new compiles.
apply_writes = jax.jit(_apply_writes)


@jax.jit
def labels_of(ws: WriteState) -> jax.Array:
    """Snapshot service labels from write state: a registered node's
    label is its service, an unregistered node reads -1 (filtered out
    of every service-addressed query)."""
    return jnp.where(ws.registered, ws.service, jnp.int32(-1))


def _compact(changed: jax.Array, k: int):
    """First k set indices of a bool mask, ascending, -1 padded, plus
    the total count (may exceed k). Same top-k compaction as the read
    kernels: key = id where changed else PAD, lower index wins ties."""
    n = changed.shape[0]
    kk = min(k, n)  # top_k caps at the axis length; pad back out to k
    idx = jnp.arange(n, dtype=jnp.int32)
    key = jnp.where(changed, idx.astype(jnp.float32),
                    jnp.float32(_PAD_KEY))
    _, ids = jax.lax.top_k(-key, kk)
    if kk < k:
        ids = jnp.concatenate(
            [ids, jnp.zeros(k - kk, dtype=ids.dtype)])
    count = jnp.sum(changed.astype(jnp.int32))
    valid = jnp.arange(k, dtype=jnp.int32) < jnp.minimum(count, kk)
    return jnp.where(valid, ids.astype(jnp.int32), jnp.int32(-1)), count, \
        valid


def _diff_snapshots(k: int, prev_snap, prev_ws: WriteState, cur_snap,
                    cur_ws: WriteState) -> DeltaFrame:
    """Everything that changed between two consecutive flips, as one
    fixed-shape frame: changed-service membership (label or
    registration), health transitions (snapshot ``live`` bit), and KV
    slot changes (version or liveness). One kernel per flip, one
    device_get in the watch plane, fan-out on the host."""
    svc_prev = jnp.where(prev_ws.registered, prev_ws.service, jnp.int32(-1))
    svc_cur = jnp.where(cur_ws.registered, cur_ws.service, jnp.int32(-1))
    svc_changed = svc_prev != svc_cur
    went_live = cur_snap.live & ~prev_snap.live
    went_dead = prev_snap.live & ~cur_snap.live
    node_changed = svc_changed | went_live | went_dead

    ids, n_nodes, valid = _compact(node_changed, k)
    safe = jnp.maximum(ids, 0)
    kinds = (svc_changed[safe].astype(jnp.int32) * CHANGE_SERVICE
             + went_live[safe].astype(jnp.int32) * CHANGE_WENT_LIVE
             + went_dead[safe].astype(jnp.int32) * CHANGE_WENT_DEAD)
    kinds = jnp.where(valid, kinds, 0)

    kv_changed = (prev_ws.kv_ver != cur_ws.kv_ver) \
        | (prev_ws.kv_used != cur_ws.kv_used)
    slots, n_kv, kv_valid = _compact(kv_changed, k)
    kv_safe = jnp.maximum(slots, 0)

    return DeltaFrame(
        node_ids=ids,
        node_kinds=kinds,
        svc_prev=jnp.where(valid, svc_prev[safe], jnp.int32(-1)),
        svc_cur=jnp.where(valid, svc_cur[safe], jnp.int32(-1)),
        n_node_changes=n_nodes,
        kv_slots=slots,
        kv_vers=jnp.where(kv_valid, cur_ws.kv_ver[kv_safe], jnp.int32(0)),
        n_kv_changes=n_kv,
        apply_index=cur_ws.apply_index,
        tick=cur_snap.tick,
    )


# One jit object per frame width k (the ops/serving.py kernel-cache
# idiom); shapes then memoize inside jit.
_DIFF_CACHE: dict[int, object] = {}


def diff_kernel_for(k: int):
    """Memoized jitted flip-differ for frame width ``k``."""
    fn = _DIFF_CACHE.get(k)
    if fn is None:
        fn = _DIFF_CACHE[k] = jax.jit(functools.partial(_diff_snapshots, k))
    return fn


# ----------------------------------------------------------------------
# Host references (golden parity, the server/rtt.py contract shape):
# plain numpy, sequential per-op replay in state-store style. The
# kernels above are pinned to these EXACTLY by tests/test_writes.py.
# ----------------------------------------------------------------------

def apply_writes_reference(ws: WriteState, batch: WriteBatch):
    """Sequential host replay of one batch: ops in order, one global
    modify index per applied op (``state_store._commit`` semantics),
    last writer wins by construction. Returns the same
    ``(new_state, applied, index)`` triple as the kernel, numpy-typed.
    """
    service = np.array(ws.service, dtype=np.int32, copy=True)
    registered = np.array(ws.registered, dtype=bool, copy=True)
    session = np.array(ws.session, dtype=np.int32, copy=True)
    kv_used = np.array(ws.kv_used, dtype=bool, copy=True)
    kv_val = np.array(ws.kv_val, dtype=np.int32, copy=True)
    kv_ver = np.array(ws.kv_ver, dtype=np.int32, copy=True)
    index = int(ws.apply_index)
    n, s = len(service), len(kv_used)

    ops = np.asarray(batch.op, dtype=np.int32)
    tgts = np.asarray(batch.target, dtype=np.int32)
    args = np.asarray(batch.arg, dtype=np.int32)
    applied = np.zeros(len(ops), dtype=bool)
    opidx = np.zeros(len(ops), dtype=np.int32)

    for i, (op, tgt, arg) in enumerate(zip(ops, tgts, args)):
        ok = False
        if op in (OP_REGISTER, OP_DEREGISTER,
                  OP_SESSION_CREATE, OP_SESSION_DESTROY):
            ok = 0 <= tgt < n and (
                op not in (OP_REGISTER, OP_SESSION_CREATE) or arg >= 0)
            if ok:
                index += 1
                if op == OP_REGISTER:
                    service[tgt], registered[tgt] = arg, True
                elif op == OP_DEREGISTER:
                    service[tgt], registered[tgt] = -1, False
                elif op == OP_SESSION_CREATE:
                    session[tgt] = arg
                else:
                    session[tgt] = -1
        elif op in (OP_KV_PUT, OP_KV_DELETE):
            ok = 0 <= tgt < s
            if ok:
                index += 1
                if op == OP_KV_PUT:
                    kv_used[tgt], kv_val[tgt] = True, arg
                else:
                    kv_used[tgt] = False
                kv_ver[tgt] = index
        applied[i] = ok
        opidx[i] = index

    new = WriteState(service=service, registered=registered,
                     session=session, kv_used=kv_used, kv_val=kv_val,
                     kv_ver=kv_ver, apply_index=np.int32(index))
    return new, applied, opidx


def diff_snapshots_reference(k: int, prev_snap, prev_ws, cur_snap,
                             cur_ws) -> DeltaFrame:
    """Host replay of the flip diff: same frame, numpy-typed."""
    svc_prev = np.where(np.asarray(prev_ws.registered),
                        np.asarray(prev_ws.service), -1).astype(np.int32)
    svc_cur = np.where(np.asarray(cur_ws.registered),
                       np.asarray(cur_ws.service), -1).astype(np.int32)
    prev_live = np.asarray(prev_snap.live)
    cur_live = np.asarray(cur_snap.live)
    svc_changed = svc_prev != svc_cur
    went_live = cur_live & ~prev_live
    went_dead = prev_live & ~cur_live
    node_changed = svc_changed | went_live | went_dead

    ids = np.flatnonzero(node_changed).astype(np.int32)
    n_nodes = len(ids)
    ids = ids[:k]
    node_ids = np.full(k, -1, dtype=np.int32)
    node_ids[:len(ids)] = ids
    kinds = np.zeros(k, dtype=np.int32)
    kinds[:len(ids)] = (svc_changed[ids] * CHANGE_SERVICE
                        + went_live[ids] * CHANGE_WENT_LIVE
                        + went_dead[ids] * CHANGE_WENT_DEAD)
    sp = np.full(k, -1, dtype=np.int32)
    sc = np.full(k, -1, dtype=np.int32)
    sp[:len(ids)] = svc_prev[ids]
    sc[:len(ids)] = svc_cur[ids]

    kv_changed = (np.asarray(prev_ws.kv_ver) != np.asarray(cur_ws.kv_ver)) \
        | (np.asarray(prev_ws.kv_used) != np.asarray(cur_ws.kv_used))
    kslots = np.flatnonzero(kv_changed).astype(np.int32)
    n_kv = len(kslots)
    kslots = kslots[:k]
    kv_slots = np.full(k, -1, dtype=np.int32)
    kv_slots[:len(kslots)] = kslots
    kv_vers = np.zeros(k, dtype=np.int32)
    kv_vers[:len(kslots)] = np.asarray(cur_ws.kv_ver)[kslots]

    return DeltaFrame(
        node_ids=node_ids, node_kinds=kinds, svc_prev=sp, svc_cur=sc,
        n_node_changes=np.int32(n_nodes), kv_slots=kv_slots,
        kv_vers=kv_vers, n_kv_changes=np.int32(n_kv),
        apply_index=np.asarray(cur_ws.apply_index, dtype=np.int32),
        tick=np.asarray(cur_snap.tick, dtype=np.int32),
    )
