"""Lamport clock operations, vectorized over the node axis.

Serf keeps three cluster-wide Lamport clocks per node — membership,
user-event, and query time (reference serf/serf.go:57-60) — with two
operations (reference serf/lamport.go:10-45):

  - ``Increment``: atomically advance the local clock and return the new
    time (used when originating an intent/event/query).
  - ``Witness(v)``: on observing a remote time ``v``, raise the local
    clock to ``v + 1`` if it is behind (CAS loop in the reference; a pure
    ``maximum`` here).

In the vectorized framework the clock is an array ``clock[N]`` and both
operations are elementwise, so a whole cluster's worth of clock traffic
is two fused ops per tick.

Under the fused serf core (models/serf.py ``step_counted``) the ltimes
being witnessed arrive packed in the high bits of the u32 event keys
(``ltime << 9``) riding the SWIM exchange legs; witness stays a pure
``maximum``, which is why the fused step's sentinel can assert clocks
are monotone within a tick — they have no other way to move.
"""

from __future__ import annotations

import jax.numpy as jnp


def witness(clock, observed, mask=None):
    """Raise ``clock`` to ``observed + 1`` where behind (and ``mask``).

    Mirrors LamportClock.Witness (reference serf/lamport.go:29-45).
    """
    clock = jnp.asarray(clock, jnp.uint32)
    bumped = jnp.maximum(clock, jnp.asarray(observed, jnp.uint32) + 1)
    if mask is None:
        return bumped
    return jnp.where(mask, bumped, clock)


def increment(clock, mask=None):
    """Advance the clock by one where ``mask`` (everywhere when None).

    Mirrors LamportClock.Increment (reference serf/lamport.go:23-26).
    Returns the new clock; the originated message carries the *previous*
    value (serf stamps with ``Time()`` then increments, serf.go:447-462).
    """
    clock = jnp.asarray(clock, jnp.uint32)
    if mask is None:
        return clock + 1
    return jnp.where(mask, clock + 1, clock)
