"""Batched multi-group raft as dense tensor ops on device.

The reference's server tier is hashicorp/raft: one event-driven state
machine per server, goroutines and channels per peer (raft.go
runFollower/runCandidate/runLeader). Here R independent raft groups of
P peers each are ONE set of ``[R, P]`` tensors stepped synchronously
inside the same jitted scan as SWIM/serf (models/cluster.py): a tick is
a fixed sub-phase pipeline — timers, election start, one RequestVote
round, leader appends, one AppendEntries round, quorum commit — where
every message exchange is a dense ``[R, P, P]`` one-hot round and every
state update a masked ``jnp.where`` full-array write. Zero
data-dependent scatters (TH109-clean): log writes are masked-arange
selects, vote/append source selection is the rank-max idiom of
``ops/deltas._last_writer``, commit advance is a quorum count over the
static window axis.

Determinism contract: the per-tick randomness is ONE election-timeout
draw per (group, peer), keyed off the scan's existing per-tick key
ladder (``fold_in(fold_in(base_key, t), _RAFT_SALT)`` then a per-seat
fold on the GLOBAL ``group*P + peer`` index). A peer resets its timer
at most once per tick, so the draw table is the complete randomness
spec — the host oracle (server/raft.py LockstepRaftOracle) replays it
exactly via :func:`draw_table`, and the sharded runner reproduces it
bit-for-bit by folding global group ids (``group0`` offset).

Synchronous-model narrowings vs hashicorp/raft (COVERAGE.md server
tier): no membership changes, no InstallSnapshot (the log is a bounded
``window``-entry absolute-index buffer; entry w+1 lives at slot w), and
AppendEntries ships the leader's FULL window with wholesale adoption
instead of per-follower nextIndex backoff — safe because the election
up-to-date rule (§5.4.1) preserves Leader Completeness, so a leader's
log always contains every committed entry and replacing a follower's
suffix can never drop one. Commit advance keeps the §5.4.2
current-term-only rule.

Client traffic is intent-based: the host bumps ``next_seq[r]``
(models/raft.py RaftPlane.propose) and every CURRENT leader of group r
appends client entries until its log holds ``next_seq[r]`` of them —
so entries stranded on a deposed leader's uncommitted suffix are
re-proposed by the next leader automatically, and the k-th committed
client entry of a group is always proposal k (the FIFO ticket mapping
RaftPlane.pump relies on).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.config import RaftConfig

ROLE_FOLLOWER = 0
ROLE_CANDIDATE = 1
ROLE_LEADER = 2

# Salt folded into the scan's per-tick key for the raft draw ladder —
# keeps raft randomness independent of the SWIM/serf split(key, 10)
# consumption at the same tick.
_RAFT_SALT = 7919


class RaftState(NamedTuple):
    """Per-(group, peer) raft state, all dense. ``match`` is row p's
    leader-side view of every peer's replicated length (meaningful only
    while p leads). ``next_seq`` is the host-bumped client-entry intent
    per group (see module docstring)."""

    term: jax.Array        # [R, P] i32
    role: jax.Array        # [R, P] i32 (ROLE_*)
    voted_for: jax.Array   # [R, P] i32, -1 = none this term
    leader: jax.Array      # [R, P] i32, -1 = unknown
    timer: jax.Array       # [R, P] i32 election countdown
    hb: jax.Array          # [R, P] i32 leader heartbeat countdown
    log_term: jax.Array    # [R, P, W] i32, slot w = entry w+1 (0 = empty)
    log_client: jax.Array  # [R, P, W] bool — client entry vs leader no-op
    last_index: jax.Array  # [R, P] i32 entries held
    commit: jax.Array      # [R, P] i32 committed prefix length
    match: jax.Array       # [R, P, P] i32 leader replication view
    next_seq: jax.Array    # [R] i32 client-entry intent


class RaftCounters(NamedTuple):
    """Per-tick raft event tallies, [] i32 — the GossipCounters pattern
    (models/counters.py) as a SEPARATE pytree so arming raft never
    changes the gossip counter stack width (the raft-off byte-identity
    pin). Field order is the wire order of the stacked fetch."""

    elections_started: jax.Array     # timers expired -> candidate
    elections_won: jax.Array         # quorum reached -> leader
    term_changes: jax.Array          # higher term adopted from a message
    commit_advances: jax.Array       # leader commit-index advances
    heartbeats_sent: jax.Array       # heartbeat-cadence AppendEntries
    heartbeats_suppressed: jax.Array  # quiet leader ticks (no send due)
    entries_appended: jax.Array      # log entries appended (noop+client)
    votes_granted: jax.Array         # RequestVote grants issued


FIELDS = RaftCounters._fields

# Sink names (telemetry table: COVERAGE.md server tier;
# tests/test_metric_names.py folds these in like counters.METRIC_NAMES).
METRIC_NAMES = {
    "elections_started": "consul.raft.state.candidate",
    "elections_won": "consul.raft.state.leader",
    "term_changes": "consul.raft.term.changes",
    "commit_advances": "consul.raft.commit.advances",
    "heartbeats_sent": "consul.raft.replication.heartbeat",
    "heartbeats_suppressed": "consul.raft.heartbeat.suppressed",
    "entries_appended": "consul.raft.log.appends",
    "votes_granted": "consul.raft.vote.granted",
}
assert set(METRIC_NAMES) == set(FIELDS)


def counters_zeros() -> RaftCounters:
    z = jnp.zeros((), jnp.int32)
    return RaftCounters(*([z] * len(FIELDS)))


def counters_add(a: RaftCounters, b: RaftCounters) -> RaftCounters:
    return jax.tree.map(jnp.add, a, b)


def counters_stack(c: RaftCounters) -> jax.Array:
    return jnp.stack(list(c))


def counters_unstack(vec) -> RaftCounters:
    return RaftCounters(*(vec[i] for i in range(len(FIELDS))))


def _count(mask) -> jax.Array:
    return jnp.sum(mask).astype(jnp.int32)


# ----------------------------------------------------------------------
# Randomness spec (shared with the host oracle).
# ----------------------------------------------------------------------

def timeout_draws(rcfg: RaftConfig, key, group0, r_count: int):
    """``[r_count, P]`` i32 election-timeout draws in
    [election_ticks_min, election_ticks_max], one per seat, keyed on
    the GLOBAL seat index ``(group0 + r) * P + p`` — shard-invariant by
    construction (the sharded runner passes its group offset)."""
    p = rcfg.peers
    base = jnp.asarray(group0, jnp.int32) * p
    idx = base + jnp.arange(r_count * p, dtype=jnp.int32)
    keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(idx)
    draw = jax.vmap(lambda k: jax.random.randint(
        k, (), rcfg.election_ticks_min, rcfg.election_ticks_max + 1))(keys)
    return draw.reshape(r_count, p).astype(jnp.int32)


def draw_table(rcfg: RaftConfig, base_key, t: int, group0: int = 0,
               r_count: Optional[int] = None) -> np.ndarray:
    """Host view of tick ``t``'s draw table (numpy [R, P]) — the oracle
    consumes exactly what the device consumed."""
    r_count = rcfg.groups if r_count is None else r_count
    tick_key = jax.random.fold_in(base_key, t)
    d = timeout_draws(rcfg, jax.random.fold_in(tick_key, _RAFT_SALT),
                      group0, r_count)
    return np.asarray(jax.device_get(d))


def init(rcfg: RaftConfig, key) -> RaftState:
    """Fresh raft state: everyone a follower at term 0 with a seeded
    initial election timeout (same per-seat fold ladder as the per-tick
    draws, so init is part of the shared randomness spec)."""
    r, p, w = rcfg.groups, rcfg.peers, rcfg.window
    i32 = jnp.int32
    return RaftState(
        term=jnp.zeros((r, p), i32),
        role=jnp.full((r, p), ROLE_FOLLOWER, i32),
        voted_for=jnp.full((r, p), -1, i32),
        leader=jnp.full((r, p), -1, i32),
        timer=timeout_draws(rcfg, key, 0, r),
        hb=jnp.zeros((r, p), i32),
        log_term=jnp.zeros((r, p, w), i32),
        log_client=jnp.zeros((r, p, w), bool),
        last_index=jnp.zeros((r, p), i32),
        commit=jnp.zeros((r, p), i32),
        match=jnp.zeros((r, p, p), i32),
        next_seq=jnp.zeros((r,), i32),
    )


# ----------------------------------------------------------------------
# Chaos masks: raft events -> per-tick liveness/deliverability.
# ----------------------------------------------------------------------

RK_KILL = 1
RK_PARTITION = 2
RK_STORM = 3


def chaos_masks(sched, t, role, group_ids):
    """Evaluate the schedule's raft slots at tick ``t`` down to
    ``(alive [R, P] bool, deliver [R, P, P] bool)`` where
    ``deliver[r, i, j]`` means a message j -> i is deliverable this
    tick. ``role`` is the tick-start role tensor (leader-kill with
    ``peer=-1`` targets whoever currently leads); ``group_ids`` maps
    local rows to global group ids (sharded runs pass an offset).
    ``sched`` None or zero raft slots is a trace-time no-chaos branch
    (the DCE contract — the raft-chaos-free program is byte-identical
    to a schedule-free one)."""
    r_count, p = role.shape
    if sched is None or sched.rk_kind.shape[0] == 0:
        alive = jnp.ones((r_count, p), bool)
        return alive, jnp.ones((r_count, p, p), bool)
    t = jnp.asarray(t, jnp.int32)
    pid = jnp.arange(p, dtype=jnp.int32)
    act = (t >= sched.rk_start) & (t < sched.rk_stop)           # [K]
    gsel = act[:, None] & ((sched.rk_group[:, None] < 0)
                           | (sched.rk_group[:, None]
                              == group_ids[None, :]))           # [K, R]
    kind = sched.rk_kind
    arg = sched.rk_arg
    # Kill: explicit peer id, or -1 = the group's current leader(s).
    kill_target = jnp.where(
        arg[:, None, None] < 0,
        (role == ROLE_LEADER)[None, :, :],
        arg[:, None, None] == pid[None, None, :])               # [K, R, P]
    kill = jnp.any(
        gsel[:, :, None] & (kind == RK_KILL)[:, None, None] & kill_target,
        axis=0)                                                 # [R, P]
    # Partition: peers talk iff both sit on the same side of the cut;
    # Storm: total in-group blackout (the split-vote generator).
    side = pid[None, :] < arg[:, None]                          # [K, P]
    cross = side[:, :, None] != side[:, None, :]                # [K, P, P]
    blocked = jnp.any(
        gsel[:, :, None, None]
        & ((kind == RK_PARTITION)[:, None, None, None]
           & cross[:, None, :, :]
           | (kind == RK_STORM)[:, None, None, None]),
        axis=0)                                                 # [R, P, P]
    alive = ~kill
    deliver = ~blocked & alive[:, :, None] & alive[:, None, :]
    return alive, deliver


def chaos_masks_reference(events, t: int, role: np.ndarray,
                          group_ids) -> tuple:
    """Numpy twin of :func:`chaos_masks` over HOST event entries
    (chaos/schedule.py RaftKill/RaftPartition/RaftStorm) — the golden
    pair the oracle replays (the ``apply_writes_reference`` pattern)."""
    from consul_tpu.chaos import schedule as chaos_mod

    r_count, p = role.shape
    group_ids = np.asarray(group_ids)
    kill = np.zeros((r_count, p), bool)
    blocked = np.zeros((r_count, p, p), bool)
    for e in events:
        if not isinstance(e, (chaos_mod.RaftKill, chaos_mod.RaftPartition,
                              chaos_mod.RaftStorm)):
            continue
        if not (e.start <= t < e.stop):
            continue
        rows = np.nonzero((group_ids == e.group) if e.group >= 0
                          else np.ones(r_count, bool))[0]
        for r in rows:
            if isinstance(e, chaos_mod.RaftKill):
                if e.peer >= 0:
                    kill[r, e.peer] = True
                else:
                    kill[r, role[r] == ROLE_LEADER] = True
            elif isinstance(e, chaos_mod.RaftPartition):
                for i in range(p):
                    for j in range(p):
                        if (i < e.cut) != (j < e.cut):
                            blocked[r, i, j] = True
            else:
                blocked[r, :, :] = True
    alive = ~kill
    deliver = (~blocked & alive[:, :, None] & alive[:, None, :])
    return alive, deliver


# ----------------------------------------------------------------------
# The tick.
# ----------------------------------------------------------------------

def tick(rcfg: RaftConfig, rst: RaftState, t, tick_key, sched=None,
         group0=0) -> tuple:
    """One synchronous raft tick over every group: returns
    ``(RaftState, RaftCounters)``. ``t`` is the global tick (the SWIM
    plane's pre-step ``t``), ``tick_key`` the scan's per-tick key.
    Killed peers are fully frozen — they neither act nor send nor
    receive — and every update below is a masked full-array write
    (no ``.at[traced]`` anywhere; the consul-tpu lint walks this file).
    """
    p, w = rcfg.peers, rcfg.window
    r_count = rst.term.shape[0]
    quorum = rcfg.quorum
    i32 = jnp.int32
    pid = jnp.arange(p, dtype=i32)
    wid = jnp.arange(w, dtype=i32)
    eye = jnp.eye(p, dtype=bool)
    group_ids = jnp.asarray(group0, i32) + jnp.arange(r_count, dtype=i32)

    alive, deliver = chaos_masks(sched, t, rst.role, group_ids)
    draws = timeout_draws(
        rcfg, jax.random.fold_in(tick_key, _RAFT_SALT), group0, r_count)

    term, role, voted = rst.term, rst.role, rst.voted_for
    leader, timer, hb = rst.leader, rst.timer, rst.hb
    log_term, log_client = rst.log_term, rst.log_client
    last, commit, match = rst.last_index, rst.commit, rst.match

    # -- A: election timers tick down for live non-leaders ------------
    timer = jnp.where(alive & (role != ROLE_LEADER), timer - 1, timer)

    # -- B: timeout -> candidate (term++, vote self, fresh timeout) ---
    start = alive & (role != ROLE_LEADER) & (timer <= 0)
    term = jnp.where(start, term + 1, term)
    role = jnp.where(start, ROLE_CANDIDATE, role)
    voted = jnp.where(start, pid[None, :], voted)
    leader = jnp.where(start, -1, leader)
    timer = jnp.where(start, draws, timer)
    c_started = _count(start)

    # -- C: one RequestVote round -------------------------------------
    # Last-log term via a one-hot select over the static window axis.
    llt = jnp.sum(jnp.where(wid[None, None, :] == (last - 1)[..., None],
                            log_term, 0), axis=-1)              # [R, P]
    cand = (role == ROLE_CANDIDATE) & alive                     # senders j
    req = cand[:, None, :] & deliver & ~eye[None]               # [R, i, j]
    # Receivers adopt the max delivered candidate term (> own ->
    # follower, vote cleared) before judging eligibility.
    max_rt = jnp.max(jnp.where(req, term[:, None, :], 0), axis=2)
    adopt = alive & (max_rt > term)
    term_rx = jnp.where(adopt, max_rt, term)
    role = jnp.where(adopt, ROLE_FOLLOWER, role)
    voted = jnp.where(adopt, -1, voted)
    leader = jnp.where(adopt, -1, leader)
    c_terms = _count(adopt)
    # Grant rule: same term, candidate's log up-to-date (§5.4.1), vote
    # free or already his. voted_for makes at most one j eligible when
    # set, so first-True argmax is both "re-grant" and "lowest id".
    up_to_date = (llt[:, None, :] > llt[:, :, None]) | (
        (llt[:, None, :] == llt[:, :, None])
        & (last[:, None, :] >= last[:, :, None]))
    eligible = (req & alive[:, :, None]
                & (term[:, None, :] == term_rx[:, :, None]) & up_to_date
                & ((voted[:, :, None] == -1)
                   | (voted[:, :, None] == pid[None, None, :])))
    any_el = jnp.any(eligible, axis=2)
    grant_to = jnp.where(any_el, jnp.argmax(eligible, axis=2).astype(i32),
                         -1)                                     # [R, i]
    granted = grant_to >= 0
    voted = jnp.where(granted, grant_to, voted)
    timer = jnp.where(granted, draws, timer)
    c_votes = _count(granted)
    term = term_rx
    # Tally: self-vote plus grants whose reply leg (i -> j) delivers.
    gr = granted[:, :, None] & (grant_to[:, :, None] == pid[None, None, :])
    votes = jnp.sum((gr & jnp.transpose(deliver, (0, 2, 1))).astype(i32),
                    axis=1) + 1                                  # [R, j]
    win = (role == ROLE_CANDIDATE) & alive & (votes >= quorum)
    role = jnp.where(win, ROLE_LEADER, role)
    leader = jnp.where(win, pid[None, :], leader)
    hb = jnp.where(win, 0, hb)                # first heartbeat this tick
    c_won = _count(win)
    # Winner appends a no-op barrier entry when the window has room.
    can_noop = win & (last < w)
    noop_at = can_noop[..., None] & (wid[None, None, :] == last[..., None])
    log_term = jnp.where(noop_at, term[..., None], log_term)
    log_client = jnp.where(noop_at, False, log_client)
    last = jnp.where(can_noop, last + 1, last)
    match = jnp.where(win[..., None],
                      jnp.where(eye[None], last[..., None], 0), match)

    # -- D: leaders append pending client intents ---------------------
    is_lead = (role == ROLE_LEADER) & alive
    n_client = jnp.sum((log_client
                        & (wid[None, None, :] < last[..., None])).astype(i32),
                       axis=-1)                                  # [R, P]
    pending = jnp.maximum(rst.next_seq[:, None] - n_client, 0)
    k_app = jnp.where(is_lead, jnp.minimum(pending, w - last), 0)
    app_at = ((wid[None, None, :] >= last[..., None])
              & (wid[None, None, :] < (last + k_app)[..., None]))
    log_term = jnp.where(app_at, term[..., None], log_term)
    log_client = jnp.where(app_at, True, log_client)
    last = last + k_app
    c_appends = _count(noop_at) + _count(app_at)
    match = jnp.where(is_lead[..., None] & eye[None],
                      last[..., None], match)

    # -- E: one AppendEntries round (full-window adoption) ------------
    hb = jnp.where(is_lead, hb - 1, hb)
    lag = jnp.any((match < last[..., None]) & ~eye[None], axis=-1)
    send = is_lead & ((hb <= 0) | lag)
    hb_fire = send & (hb <= 0)
    hb = jnp.where(hb_fire, rcfg.heartbeat_ticks, hb)
    c_hb = _count(hb_fire)
    c_hb_sup = _count(is_lead & ~send)
    # Receiver accepts the highest-term delivering leader (lowest id on
    # the impossible tie — rank-max, ops/deltas._last_writer idiom).
    app = (send[:, None, :] & deliver & ~eye[None] & alive[:, :, None]
           & (term[:, None, :] >= term[:, :, None]))            # [R, i, j]
    score = jnp.where(app, term[:, None, :] * i32(p + 1)
                      + (i32(p) - pid[None, None, :]), -1)
    has_src = jnp.max(score, axis=2) >= 0
    src = jnp.where(has_src, jnp.argmax(score, axis=2).astype(i32), -1)
    src_c = jnp.maximum(src, 0)
    src_term = jnp.take_along_axis(term, src_c, axis=1)
    term_up = has_src & (src_term > term)
    term = jnp.where(has_src, jnp.maximum(term, src_term), term)
    voted = jnp.where(term_up, -1, voted)
    role = jnp.where(has_src, ROLE_FOLLOWER, role)
    leader = jnp.where(has_src, src, leader)
    timer = jnp.where(has_src, draws, timer)
    c_terms = c_terms + _count(term_up)
    # Wholesale log adoption from the chosen leader (gathers only).
    src_lt = jnp.take_along_axis(log_term, src_c[..., None], axis=1)
    src_lc = jnp.take_along_axis(log_client, src_c[..., None], axis=1)
    src_last = jnp.take_along_axis(last, src_c, axis=1)
    src_commit = jnp.take_along_axis(commit, src_c, axis=1)
    log_term = jnp.where(has_src[..., None], src_lt, log_term)
    log_client = jnp.where(has_src[..., None], src_lc, log_client)
    last = jnp.where(has_src, src_last, last)
    commit = jnp.where(
        has_src,
        jnp.maximum(commit, jnp.minimum(src_commit, src_last)), commit)
    # Ack return leg: leader j learns follower i now matches its log.
    ack = (has_src[:, :, None] & (src[:, :, None] == pid[None, None, :])
           & jnp.transpose(deliver, (0, 2, 1)))                 # [R, i, j]
    match = jnp.where(jnp.transpose(ack, (0, 2, 1)),
                      last[:, :, None], match)

    # -- F: quorum commit (current-term entries only, §5.4.2) ---------
    still_lead = (role == ROLE_LEADER) & alive
    repl = jnp.sum(
        (match[:, :, None, :] >= (wid[None, None, :, None] + 1)).astype(i32),
        axis=3)                                                 # [R, P, W]
    ok_w = ((repl >= quorum) & (log_term == term[..., None])
            & (wid[None, None, :] < last[..., None]))
    reach = jnp.max(jnp.where(ok_w, wid[None, None, :] + 1, 0), axis=-1)
    new_commit = jnp.where(still_lead, jnp.maximum(commit, reach), commit)
    c_commit = _count(still_lead & (new_commit > commit))
    commit = new_commit

    out = RaftState(term=term, role=role, voted_for=voted, leader=leader,
                    timer=timer, hb=hb, log_term=log_term,
                    log_client=log_client, last_index=last, commit=commit,
                    match=match, next_seq=rst.next_seq)
    cnt = RaftCounters(
        elections_started=c_started, elections_won=c_won,
        term_changes=c_terms, commit_advances=c_commit,
        heartbeats_sent=c_hb, heartbeats_suppressed=c_hb_sup,
        entries_appended=c_appends, votes_granted=c_votes)
    return out, cnt


# ----------------------------------------------------------------------
# Host-facing summaries (one small fetch per pump).
# ----------------------------------------------------------------------

def summary(rst: RaftState) -> tuple:
    """Per-group ``(term [R], leader [R], commit [R],
    committed_clients [R])`` — max term, highest-term live leader id
    (-1 when none), max committed prefix, and the number of CLIENT
    entries inside any peer's committed prefix. The last is the commit
    frontier RaftPlane.pump maps back to proposal tickets: committed
    prefixes are stable, so client entry k is always proposal k."""
    r_count, p, w = rst.log_term.shape
    i32 = jnp.int32
    pid = jnp.arange(p, dtype=i32)
    wid = jnp.arange(w, dtype=i32)
    term_g = jnp.max(rst.term, axis=1)
    score = jnp.where(rst.role == ROLE_LEADER,
                      rst.term * i32(p + 1) + (i32(p) - pid[None, :]), -1)
    leader_g = jnp.where(jnp.max(score, axis=1) >= 0,
                         jnp.argmax(score, axis=1).astype(i32), -1)
    commit_g = jnp.max(rst.commit, axis=1)
    cc = jnp.sum((rst.log_client
                  & (wid[None, None, :] < rst.commit[..., None])).astype(i32),
                 axis=-1)
    return term_g, leader_g, commit_g, jnp.max(cc, axis=1)
