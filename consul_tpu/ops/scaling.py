"""Cluster-size scaling laws of the SWIM/Lifeguard protocol.

These are the log-scaling formulas the reference applies everywhere the
protocol must stay stable as N grows (reference memberlist/util.go:62-97,
memberlist/suspicion.go:86-97, lib/cluster.go:48-60). They are implemented
as jnp-traceable functions of (possibly batched) array arguments so they
can be evaluated per-node inside the jitted step function.

All time quantities are in abstract *ticks* (callers convert via
GossipConfig); the formulas are scale-free so the units cancel.
"""

from __future__ import annotations

import jax.numpy as jnp


def suspicion_timeout(suspicion_mult, n, probe_interval_ticks):
    """Base (minimum) suspicion timeout for cluster size ``n``.

    Mirrors suspicionTimeout (reference memberlist/util.go:64-69):
    ``mult * max(1, log10(max(1, n))) * probe_interval``. The reference's
    integer Duration math truncates the node scale to 1/1000ths; that
    sub-0.1% effect is not reproduced in float32.
    """
    n = jnp.asarray(n, jnp.float32)
    node_scale = jnp.maximum(1.0, jnp.log10(jnp.maximum(1.0, n)))
    return suspicion_mult * node_scale * probe_interval_ticks


def retransmit_limit(retransmit_mult, n):
    """Per-message retransmission budget.

    Mirrors retransmitLimit (reference memberlist/util.go:72-76):
    ``mult * ceil(log10(n + 1))``.
    """
    n = jnp.asarray(n, jnp.float32)
    # The epsilon guards against float32 log10 landing a hair above an
    # integer (log10(10) evaluates to ~1.00001f) and ceil overshooting;
    # true boundaries are >=0.04 away for any non-power-of-ten n.
    scale = jnp.ceil(jnp.log10(n + 1.0) - 1e-3)
    return (retransmit_mult * scale).astype(jnp.int32)


def push_pull_scale(n):
    """Multiplier on the push-pull interval above 32 nodes.

    Mirrors pushPullScale (reference memberlist/util.go:89-97): 1 up to
    32 nodes, then ``ceil(log2(n) - log2(32)) + 1`` (the 33rd node doubles
    the interval, the 65th triples it).
    """
    n = jnp.asarray(n, jnp.float32)
    # Same float32 epsilon guard as retransmit_limit: keep ceil from
    # overshooting when log2 lands a hair above an integer.
    mult = jnp.ceil(jnp.log2(jnp.maximum(n, 1.0)) - jnp.log2(32.0) - 1e-3) + 1.0
    return jnp.where(n <= 32.0, 1, mult.astype(jnp.int32))


def remaining_suspicion_time(n_confirms, k, elapsed, min_timeout, max_timeout):
    """Remaining suspicion time after ``n_confirms`` independent confirmations.

    Mirrors remainingSuspicionTime (reference memberlist/suspicion.go:86-97):
    the timeout decays from ``max`` toward ``min`` along
    ``log(n+1)/log(k+1)``, floored at ``min``, less time already elapsed.
    May be <= 0, meaning the suspicion has expired. All times in ticks
    (floats allowed); the reference's floor-to-milliseconds is not
    reproduced since tick granularity subsumes it.
    """
    n_confirms = jnp.asarray(n_confirms, jnp.float32)
    k = jnp.asarray(k, jnp.float32)
    frac = jnp.where(
        k > 0.0,
        jnp.log(n_confirms + 1.0) / jnp.log(k + 1.0),
        1.0,  # k <= 0: no confirmations expected, drive straight to min
    )
    raw = max_timeout - frac * (max_timeout - min_timeout)
    return jnp.maximum(raw, min_timeout) - elapsed


def suspicion_k(suspicion_mult, n):
    """Confirmations needed to drive a suspicion timer to its minimum.

    Mirrors the setup in suspectNode (reference memberlist/state.go:1124-1136):
    ``k = suspicion_mult - 2``, zeroed when the cluster is too small to
    provide that many independent confirmers (n - 2 < k).
    """
    n = jnp.asarray(n, jnp.int32)
    k = jnp.asarray(suspicion_mult - 2, jnp.int32)
    return jnp.where(n - 2 < k, 0, k)


def rate_scaled_interval(rate_per_s, min_ticks, n, ticks_per_s):
    """Interval targeting an aggregate cluster-wide action rate.

    Mirrors RateScaledInterval (reference lib/cluster.go:51-60): spread N
    actors so the whole cluster performs ``rate_per_s`` actions per second,
    never below ``min_ticks``. Used for the coordinate-update send rate
    (reference agent/agent.go:1896).
    """
    n = jnp.asarray(n, jnp.float32)
    interval = ticks_per_s * n / rate_per_s
    return jnp.maximum(interval, min_ticks)


def queue_max_depth(max_queue_depth, min_queue_depth, n_members):
    """Dynamic serf broadcast-queue depth limit.

    Mirrors getQueueMax (reference serf/serf.go:1612-1624): the static
    ``MaxQueueDepth`` unless ``MinQueueDepth`` is set, in which case the
    limit scales with the cluster — ``max(2 * n_members,
    min_queue_depth)`` (Consul sets MinQueueDepth=4096, reference
    lib/serf.go:26-28). Host-side helper (plain ints): the limit guards
    host-side queues (wire/bridge.py seam buffers); the in-sim event
    queue's fixed ``event_queue_slots`` capacity is its own, tighter,
    always-enforced bound.
    """
    m = int(max_queue_depth)
    if min_queue_depth > 0:
        m = max(2 * int(n_members), int(min_queue_depth))
    return m
