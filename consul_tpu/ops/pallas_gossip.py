"""Pallas packed-native gossip kernel: one tick, VMEM-resident.

Since PR 11 the state at rest is packed (176 B/node at K=8, 296 at
K=16) but the XLA scan body unpacks to the dense f32/i32 working set in
HBM before every tick and repacks after — the 2.66x byte cut is a
capacity win only, while per-tick HBM traffic stays dense. This module
fuses the whole tick into one ``pl.pallas_call``: the PackedSimState
tiles load into VMEM, the layout codec (models/layout.py pack/unpack —
purely elementwise, so it inlines into the kernel as register math)
widens them in-register, the SWIM probe/ack/suspicion update and the
``roll_many`` circulant payload exchange (including the serf
``extra_tx`` top-k piggyback peel) run on the VMEM-resident working
set, and the state repacks before the VMEM→HBM writeback. HBM bytes
per tick are pure packed bytes — the memory-bound inner loop the
hand-fused-kernel literature targets (fluid-flow stencils, distributed
linear algebra) has exactly this shape.

Kernel-callable core: the step bodies consult
``parallel/collective.in_kernel()`` at the four sites whose XLA idiom
Mosaic cannot lower — ``lax.top_k``/``argsort`` become static
argmax/argmin peels (bit-identical selection, models/swim.py), and the
serf busy-gate/tally ``lax.cond``s run their bodies unconditionally
(bit-identical by the masks' idle-false property, models/serf.py).
Off-kernel the step programs are byte-for-byte untouched — the
``--kernel`` compile-ledger pin counts exactly that.

Tiling scheme: the circulant exchange reads rows at random per-tick
displacements, so a halo-tiled grid would need O(n) halos — the kernel
instead keeps the whole (packed) population resident in one VMEM block
(no grid). Packed residency is what buys the headroom: ~16 MB/core
of VMEM holds ~90k packed nodes at K=8 versus ~34k dense. Populations
beyond that cap need an exchange-split multi-block kernel — an item-1
remainder, like Mosaic input/output aliasing of the state tiles.

Sharded: shard_map calls the kernel once per shard; under
``interpret=True`` the collectives the step emits (ppermute ladders,
all-gathers) trace into the kernel jaxpr and resolve against the
enclosing shard_map axis, so sharded == single-device parity is pinned
on CPU. Real-TPU Mosaic cannot host ICI collectives inside a kernel —
the multi-chip lowering splits the kernel at the three mid-tick
exchange barriers (item-1 remainder); single-chip TPU lowering needs no
split.

Interpret discipline (lint rule TH118): ``interpret=True`` silently
shipping to TPU is a 100x perf cliff. Production entry points thread
:func:`default_interpret` (False on TPU); the one sanctioned truthy
literal is :func:`interpret_tick`, the explicitly-marked test/debug
entry the golden-parity suite drives, allowlisted by symbol.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from consul_tpu.config import SimConfig
from consul_tpu.models import counters as counters_mod
from consul_tpu.models import layout as layout_mod
from consul_tpu.models import swim
from consul_tpu.parallel import collective as coll

XLA = "xla"
PALLAS = "pallas"
KERNELS = (XLA, PALLAS)


def validate_kernel(kernel: str, layout: str) -> None:
    """Reject invalid --kernel selections, host-side and static."""
    if kernel not in KERNELS:
        raise ValueError(
            f"unknown kernel {kernel!r}; expected one of {KERNELS}")
    if kernel == PALLAS and layout != layout_mod.PACKED:
        raise ValueError(
            "--kernel pallas is packed-native (the kernel's contract is "
            "packed HBM bytes per tick); run it with --layout packed")


def default_interpret() -> bool:
    """Kernel evaluation mode for the current backend: False on TPU
    (Mosaic compiles the kernel), True elsewhere (the interpreter twin
    — Mosaic only lowers on TPU, and CPU tier-1 pins golden parity in
    exactly this mode). Production callers must thread THIS value, not
    a literal — interpret mode silently shipping to TPU is the TH118
    100x perf cliff."""
    return jax.default_backend() != "tpu"


def make_tick_kernel(cfg: SimConfig, topo, *, step_fn=swim.step_counted,
                     sentinel: bool = False, interpret: bool = False):
    """Build ``tick(world, sched, packed_state, tick_key) ->
    (packed_state, GossipCounters)`` executing one gossip tick as a
    single Pallas kernel over the packed state.

    ``packed_state`` is a PackedSimState (or a SerfState whose SWIM
    plane is packed); the returned state has identical structure —
    pack∘unpack is shape/dtype-preserving, which is also how the kernel
    declares its output block shapes without an abstract trace. The
    counters cross the kernel boundary as one stacked
    [len(FIELDS)] i32 vector (models/counters.py wire order) and
    unstack outside. ``sched`` may be None (the schedule-free program;
    None is an empty pytree so the kernel simply has no schedule
    operands)."""
    from jax.experimental import pallas as pl  # deferred: jax-optional

    def _tick_math(world, sched, packed, tick_key):
        state = layout_mod.unpack_state(packed)
        with coll.kernel_body():
            state, c = step_fn(cfg, topo, world, state, tick_key, sched,
                               sentinel=sentinel)
        return layout_mod.pack_state(state), counters_mod.stack(c)

    def tick(world, sched, packed, tick_key):
        args = (world, sched, packed, tick_key)
        flat_args, _ = jax.tree.flatten(args)
        # Trace the tick once to a closed jaxpr: the kernel body then
        # replays it primitive-by-primitive, and every captured device
        # array (topology tables, module constants) surfaces in
        # ``consts`` — Pallas kernels cannot close over arrays, so the
        # consts ride in as explicit VMEM inputs alongside the state.
        cj, out_shape = jax.make_jaxpr(_tick_math, return_shape=True)(*args)
        consts = [jnp.asarray(c) for c in cj.consts]
        flat_out, out_tree = jax.tree.flatten(out_shape)
        flat_in = flat_args + consts
        n_args = len(flat_args)
        # Zero-size leaves (e.g. a chaos schedule's empty fault-type
        # slots) cannot be Pallas blocks; they are contentless, so they
        # stay outside the call and rematerialize in-body.
        in_live = [l for l in flat_in if l.size > 0]
        out_live = [o for o in flat_out if o.size > 0]
        n_live = len(in_live)

        def kernel(*refs):
            it = iter(refs[:n_live])
            ins = [next(it)[...] if l.size > 0
                   else jnp.zeros(l.shape, l.dtype) for l in flat_in]
            outs = jax.core.eval_jaxpr(cj.jaxpr, ins[n_args:],
                                       *ins[:n_args])
            ot = iter(refs[n_live:])
            for o, leaf in zip(flat_out, outs):
                if o.size > 0:
                    next(ot)[...] = leaf

        outs = pl.pallas_call(
            kernel,
            out_shape=[jax.ShapeDtypeStruct(o.shape, o.dtype)
                       for o in out_live],
            interpret=interpret,
        )(*in_live)
        it = iter(outs)
        full = [next(it) if o.size > 0 else jnp.zeros(o.shape, o.dtype)
                for o in flat_out]
        out_state, cv = jax.tree.unflatten(out_tree, full)
        return out_state, counters_mod.unstack(cv)

    return tick


def interpret_tick(cfg: SimConfig, topo, *, step_fn=swim.step_counted,
                   sentinel: bool = False):
    """The explicitly-marked test/debug entry point: the
    ``interpret=True`` twin the CPU golden-parity suite
    (tests/test_pallas_gossip.py) drives directly. Never a production
    path — the TH118 allowlist carries exactly this symbol."""
    return make_tick_kernel(cfg, topo, step_fn=step_fn, sentinel=sentinel,
                            interpret=True)


def tick_hbm_bytes_per_node(state, world=None, sched=None,
                            n: Optional[int] = None) -> float:
    """The kernel's HBM-traffic contract, in bytes/tick/node: one read
    of the (packed) state + world (+ schedule) and one write of the
    state — everything else is VMEM-resident. Abstract values welcome
    (pairs with jax.eval_shape); ``n`` defaults to the state's leading
    node-axis length. bench.py's memory phase asserts this stays within
    a small constant of the at-rest bytes/node."""
    if n is None:
        n = max(int(leaf.shape[0]) for leaf in jax.tree.leaves(state)
                if getattr(leaf, "ndim", 0) >= 1)
    total = sum(layout_mod.np_size_bytes(leaf)
                for tree in (state, state, world, sched)
                for leaf in jax.tree.leaves(tree))
    return total / float(n)
