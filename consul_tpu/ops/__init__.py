"""Pure math kernels: scaling laws, merge semilattice, Vivaldi, sampling."""

from consul_tpu.ops import merge as merge  # noqa: F401
from consul_tpu.ops import scaling as scaling  # noqa: F401
from consul_tpu.ops import vivaldi as vivaldi  # noqa: F401
