"""Vectorized Vivaldi network coordinates.

Re-expresses the reference's per-observation serial update (reference
serf/coordinate/client.go:145-234 and coordinate.go:104-203) as pure
batched array functions: every node can absorb its probe-RTT observation
of the tick in one fused elementwise pass. All distances/RTTs are in
**seconds** (like the reference); all arrays are float32 (TPU-native;
the reference uses float64 — tolerances in tests account for this).

State per node: the Euclidean vector, the non-Euclidean height, the
confidence error, the adjustment offset plus its sliding sample window,
and a reset counter (mirroring ClientStats.Resets, client.go:47-51).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from consul_tpu.config import VivaldiConfig

ZERO_THRESHOLD = 1.0e-6
# RTT observations above this are rejected (reference client.go:216-219).
MAX_RTT_SECONDS = 10.0


class VivaldiState(NamedTuple):
    """Struct-of-arrays Vivaldi client state; leading dims are batch dims."""

    vec: jax.Array         # [..., D] float32, Euclidean coordinate (seconds)
    height: jax.Array      # [...]    float32, access-link height (seconds)
    error: jax.Array       # [...]    float32, confidence (dimensionless)
    adjustment: jax.Array  # [...]    float32, offset from window (seconds)
    adj_samples: jax.Array # [..., W] float32, sliding adjustment window
    adj_idx: jax.Array     # [...]    int32, next write slot in the window
    resets: jax.Array      # [...]    int32, NaN/Inf reset count


def new(cfg: VivaldiConfig, batch_shape=()) -> VivaldiState:
    """Fresh origin coordinates (reference coordinate.go:54-61)."""
    shape = tuple(batch_shape)
    return VivaldiState(
        vec=jnp.zeros(shape + (cfg.dimensionality,), jnp.float32),
        height=jnp.full(shape, cfg.height_min, jnp.float32),
        error=jnp.full(shape, cfg.vivaldi_error_max, jnp.float32),
        adjustment=jnp.zeros(shape, jnp.float32),
        adj_samples=jnp.zeros(shape + (cfg.adjustment_window_size,), jnp.float32),
        adj_idx=jnp.zeros(shape, jnp.int32),
        resets=jnp.zeros(shape, jnp.int32),
    )


def raw_distance(vec_a, height_a, vec_b, height_b):
    """Vivaldi distance without adjustments (reference coordinate.go:137-139)."""
    d = jnp.linalg.norm(vec_a - vec_b, axis=-1)
    return d + height_a + height_b


def distance(vec_a, height_a, adj_a, vec_b, height_b, adj_b):
    """Full distance estimate including the adjustment offsets.

    Mirrors DistanceTo (reference coordinate.go:121-132): the adjusted
    distance is used only when it stays positive.
    """
    dist = raw_distance(vec_a, height_a, vec_b, height_b)
    adjusted = dist + adj_a + adj_b
    return jnp.where(adjusted > 0.0, adjusted, dist)


def _unit_vector_at(vec_a, vec_b, key, rnd=None):
    """Unit vector pointing at ``vec_a`` from ``vec_b`` plus the distance.

    Mirrors unitVectorAt (reference coordinate.go:182-203): coincident
    points get a random unit direction (reported magnitude 0) so height
    updates are skipped for them. A caller that knows the batch's row
    identities (a sharded node block) passes the fallback directions in
    via ``rnd``; this module stays sharding-agnostic.
    """
    d = vec_a - vec_b
    mag = jnp.linalg.norm(d, axis=-1, keepdims=True)
    if rnd is None:
        rnd = jax.random.uniform(key, d.shape, jnp.float32, -0.5, 0.5)
    rnd_mag = jnp.linalg.norm(rnd, axis=-1, keepdims=True)
    # Fallback chain: real direction -> random direction -> e0.
    e0 = jnp.zeros_like(d).at[..., 0].set(1.0)
    use_real = mag > ZERO_THRESHOLD
    use_rnd = rnd_mag > ZERO_THRESHOLD
    unit = jnp.where(
        use_real,
        d / jnp.where(use_real, mag, 1.0),
        jnp.where(use_rnd, rnd / jnp.where(use_rnd, rnd_mag, 1.0), e0),
    )
    return unit, jnp.where(use_real[..., 0], mag[..., 0], 0.0)


def apply_force(cfg: VivaldiConfig, vec, height, force, other_vec, other_height,
                key, rnd=None):
    """Apply a scalar force from the direction of ``other``.

    Mirrors ApplyForce (reference coordinate.go:104-117): the vector moves
    along the unit direction; the height blends both endpoints' heights
    scaled by force/distance, clamped to ``height_min``, and is untouched
    for coincident points.
    """
    unit, mag = _unit_vector_at(vec, other_vec, key, rnd)
    new_vec = vec + unit * force[..., None]
    moved = mag > ZERO_THRESHOLD
    new_height = (height + other_height) * force / jnp.where(moved, mag, 1.0) + height
    new_height = jnp.maximum(new_height, cfg.height_min)
    return new_vec, jnp.where(moved, new_height, height)


def update(
    cfg: VivaldiConfig,
    state: VivaldiState,
    other_vec,
    other_height,
    other_error,
    other_adjustment,
    rtt_seconds,
    key,
    fallback_rnd=None,
) -> VivaldiState:
    """One full observation update per batch element.

    Mirrors Client.Update (reference client.go:202-234) minus the latency
    median filter, which lives with the per-peer sample buffers in the
    SWIM state (see ``latency_filter_push``): error-weighted Vivaldi force
    (client.go:145-168), adjustment window (client.go:172-188), gravity
    toward the origin (client.go:193-197), and NaN/Inf reset
    (client.go:228-231). Like the reference's input gate (checkCoordinate
    + the RTT range check, client.go:206-219), an invalid observation — a
    non-finite peer coordinate or an RTT outside [0, 10 s] — is rejected
    per batch element: that element's state passes through untouched.
    ``fallback_rnd``, when given, is a pair of [..., dims] uniform(-0.5,
    0.5) draws used as the coincident-point fallback directions of the
    two apply_force calls (see _unit_vector_at) in place of draws from
    ``key`` — how the sharded node-block caller keeps the global stream.
    """
    k_viv, k_grav = jax.random.split(key)
    rnd_viv, rnd_grav = fallback_rnd if fallback_rnd is not None else (None, None)

    rtt_in = jnp.asarray(rtt_seconds, jnp.float32)
    obs_ok = (
        jnp.all(jnp.isfinite(other_vec), axis=-1)
        & jnp.isfinite(other_height) & jnp.isfinite(other_error)
        & jnp.isfinite(other_adjustment)
        & jnp.isfinite(rtt_in) & (rtt_in >= 0.0) & (rtt_in <= MAX_RTT_SECONDS)
    )

    # -- updateVivaldi (client.go:145-168) --------------------------------
    dist = distance(
        state.vec, state.height, state.adjustment,
        other_vec, other_height, other_adjustment,
    )
    rtt = jnp.maximum(jnp.asarray(rtt_seconds, jnp.float32), ZERO_THRESHOLD)
    wrongness = jnp.abs(dist - rtt) / rtt
    total_error = jnp.maximum(state.error + other_error, ZERO_THRESHOLD)
    weight = state.error / total_error
    error = cfg.vivaldi_ce * weight * wrongness + state.error * (1.0 - cfg.vivaldi_ce * weight)
    error = jnp.minimum(error, cfg.vivaldi_error_max)
    force = cfg.vivaldi_cc * weight * (rtt - dist)
    vec, height = apply_force(
        cfg, state.vec, state.height, force, other_vec, other_height,
        k_viv, rnd_viv,
    )

    # -- updateAdjustment (client.go:172-188) -----------------------------
    w = cfg.adjustment_window_size
    if w:
        raw = raw_distance(vec, height, other_vec, other_height)
        sample = rtt - raw
        adj_samples = _set_along_last(state.adj_samples, state.adj_idx, sample)
        adj_idx = (state.adj_idx + 1) % w
        adjustment = jnp.sum(adj_samples, axis=-1) / (2.0 * w)
    else:
        adj_samples, adj_idx, adjustment = state.adj_samples, state.adj_idx, state.adjustment

    # -- updateGravity (client.go:193-197); origin has zero vec/adjustment,
    #    height_min height, so the distance is the full estimate to origin.
    origin_vec = jnp.zeros_like(vec)
    origin_h = jnp.full_like(height, cfg.height_min)
    dist_origin = distance(vec, height, adjustment, origin_vec, origin_h, jnp.zeros_like(adjustment))
    g_force = -1.0 * (dist_origin / cfg.gravity_rho) ** 2.0
    vec, height = apply_force(
        cfg, vec, height, g_force, origin_vec, origin_h, k_grav, rnd_grav
    )

    # -- validity reset (client.go:228-231) -------------------------------
    finite = (
        jnp.all(jnp.isfinite(vec), axis=-1)
        & jnp.isfinite(height) & jnp.isfinite(error) & jnp.isfinite(adjustment)
    )
    fresh = new(cfg, batch_shape=state.height.shape)
    updated = VivaldiState(
        vec=jnp.where(finite[..., None], vec, fresh.vec),
        height=jnp.where(finite, height, fresh.height),
        error=jnp.where(finite, error, fresh.error),
        adjustment=jnp.where(finite, adjustment, fresh.adjustment),
        adj_samples=jnp.where(finite[..., None], adj_samples, fresh.adj_samples),
        adj_idx=jnp.where(finite, adj_idx, fresh.adj_idx),
        resets=state.resets + jnp.where(finite, 0, 1),
    )
    # Rejected observations leave the element's state untouched.
    return jax.tree.map(
        lambda new_leaf, old_leaf: jnp.where(
            obs_ok.reshape(obs_ok.shape + (1,) * (new_leaf.ndim - obs_ok.ndim)),
            new_leaf,
            old_leaf,
        ),
        updated,
        state,
    )


def latency_filter_push(buf, count, rtt_seconds):
    """Insert an RTT sample into a per-peer ring buffer; return the median.

    Mirrors latencyFilter (reference client.go:123-141): keep the last
    ``S`` samples per peer and return the median, defined as
    ``sorted[len/2]`` (the upper median for even counts). Absent samples
    are padded with +inf before sorting so the index math matches the Go
    slice semantics exactly.

    buf: [..., S] float32, count: [...] int32 (total samples ever pushed).
    """
    s = buf.shape[-1]
    buf = _set_along_last(buf, count % s, jnp.asarray(rtt_seconds, jnp.float32))
    count = count + 1
    filled = jnp.minimum(count, s)
    slot = jnp.arange(s, dtype=jnp.int32)
    padded = jnp.where(slot < filled[..., None], buf, jnp.inf)
    med = jnp.take_along_axis(
        jnp.sort(padded, axis=-1), (filled // 2)[..., None], axis=-1
    )[..., 0]
    return buf, count, med


def _set_along_last(arr, idx, value):
    """arr[..., idx] = value, batched over leading dims."""
    onehot = jnp.arange(arr.shape[-1], dtype=jnp.int32) == idx[..., None]
    return jnp.where(onehot, value[..., None], arr)
