"""Batched read-plane kernels: masked top-k NearestN, node distance,
and health/catalog lookups over a published device snapshot.

This is the device tier of the serving plane (``consul_tpu/serving``):
the host-side ``QueryBatcher`` packs concurrent requests into
fixed-shape padded batches (bucketed sizes so same-shape batches share
one XLA executable, the ``models/cluster.py`` memoization idiom) and
each batch runs as ONE program here — a broadcast Vivaldi distance, a
mode/eligibility mask, and a single ``lax.top_k`` per query, vmapped
over the batch. Thousands of concurrent lookups become one gather/top-k
kernel instead of thousands of host RPCs.

Distance math reuses :func:`consul_tpu.ops.vivaldi.distance`; the host
``server/rtt.py`` stays the documented reference implementation, and
the golden-parity suite (tests/test_serving.py) pins agreement with it,
including the +inf unknown-coordinate and adjustment-clamp edges.

Snapshots are immutable projections of live simulation state published
by the scan loop (see ``Simulation.publish_serving``): readers holding
a snapshot never block the simulation and never observe a torn state —
every result in a batch is consistent as of the snapshot's ``tick``.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from consul_tpu.ops import vivaldi

# Query modes. NOOP fills padding slots (all-false eligibility, so a
# padded slot costs the same top-k but returns count=0 and no ids).
MODE_NOOP = 0
MODE_NEAREST = 1   # live nodes (optionally one service), RTT order
MODE_DIST = 2      # single node distance: arg = target node index
MODE_CATALOG = 3   # all registered nodes (optionally one service), id order
MODE_HEALTH = 4    # live nodes (optionally one service), id order

# Sort-key sentinels. UNKNOWN must order after every real distance but
# before PAD so eligible-but-coordinate-less nodes keep their place at
# the back of the result (host parity: rtt unknown -> inf, sorts last,
# stable) while ineligible/padding rows never surface at all.
_UNKNOWN_KEY = 1e30
_PAD_KEY = float(jnp.finfo(jnp.float32).max)


class Snapshot(NamedTuple):
    """Immutable device projection of one simulation tick.

    All arrays share the node axis N. ``known`` marks finite Vivaldi
    state (pairs with an unknown side answer +inf, the rtt.py rule);
    ``live`` gates NEAREST/HEALTH eligibility; ``service`` is an i32
    label per node (queries filter with arg, -1 = any); ``tick`` is the
    simulation tick the whole snapshot is consistent as of.
    """

    vec: jax.Array         # [N, D] f32 Vivaldi position
    height: jax.Array      # [N] f32
    adjustment: jax.Array  # [N] f32
    known: jax.Array       # [N] bool — finite coordinate state
    live: jax.Array        # [N] bool — alive and not left
    service: jax.Array     # [N] i32 service label
    tick: jax.Array        # [] i32


@jax.jit
def project(state, service: jax.Array) -> Snapshot:
    """Project live SimState into a read snapshot (one fused program).

    Produces fresh output buffers, which is what makes double-buffered
    publication safe: the scan runner donates and overwrites ``state``
    on the next chunk, but a published Snapshot holds independent
    arrays, so readers keep a coherent tick-T view for free.
    """
    viv = state.viv
    known = (jnp.all(jnp.isfinite(viv.vec), axis=-1)
             & jnp.isfinite(viv.height)
             & jnp.isfinite(viv.adjustment))
    live = state.alive_truth & ~state.left
    return Snapshot(vec=viv.vec, height=viv.height,
                    adjustment=viv.adjustment, known=known, live=live,
                    service=service, tick=state.t)


def _execute(k: int, snap: Snapshot, mode: jax.Array, src: jax.Array,
             arg: jax.Array):
    """One padded batch: mode/src/arg are [B] i32; returns
    ``(ids [B,k] i32, rtts [B,k] f32, count [B] i32, tick [] i32)``.

    Per query: broadcast Vivaldi distance from ``src`` to every node,
    mask eligibility by mode, then one stable ``lax.top_k`` over the
    composed sort key. top_k breaks ties toward the lower index, which
    matches Python's stable sort over index-ordered rows — the property
    the golden-parity suite leans on for exact order agreement.
    Invalid slots (beyond ``count``) come back as id -1 / rtt +inf.
    """
    n = snap.height.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    slot = jnp.arange(k, dtype=jnp.int32)

    def one(m, s, a):
        dist = vivaldi.distance(
            snap.vec[s], snap.height[s], snap.adjustment[s],
            snap.vec, snap.height, snap.adjustment)
        pair_known = snap.known[s] & snap.known
        dist = jnp.where(pair_known, dist, jnp.inf)
        svc_ok = (a < jnp.int32(0)) | (snap.service == a)
        elig = jnp.where(
            m == MODE_DIST, idx == a,
            jnp.where(m == MODE_CATALOG, svc_ok,
                      jnp.where((m == MODE_NEAREST) | (m == MODE_HEALTH),
                                snap.live & svc_ok,
                                jnp.zeros_like(snap.live))))
        by_dist = (m == MODE_NEAREST) | (m == MODE_DIST)
        key = jnp.where(
            by_dist,
            jnp.where(jnp.isfinite(dist), dist, jnp.float32(_UNKNOWN_KEY)),
            idx.astype(jnp.float32))
        key = jnp.where(elig, key, jnp.float32(_PAD_KEY))
        _, ids = jax.lax.top_k(-key, k)
        count = jnp.sum(elig.astype(jnp.int32))
        valid = slot < count
        return (jnp.where(valid, ids.astype(jnp.int32), jnp.int32(-1)),
                jnp.where(valid, dist[ids], jnp.inf),
                count)

    ids, rtts, count = jax.vmap(one)(mode, src, arg)
    return ids, rtts, count, snap.tick


# One jit object per result width k; jit's own shape cache then yields
# exactly one executable per (bucket B, node count N, dim D) — the
# compile-ledger pin in tests/test_serving.py holds steady-state
# serving to zero new compiles.
_KERNEL_CACHE: dict[int, object] = {}


def kernel_for(k: int):
    """Memoized jitted batch executor for result width ``k``."""
    fn = _KERNEL_CACHE.get(k)
    if fn is None:
        fn = _KERNEL_CACHE[k] = jax.jit(functools.partial(_execute, k))
    return fn
