"""Batched read-plane kernels: masked top-k NearestN, node distance,
and health/catalog lookups over a published device snapshot.

This is the device tier of the serving plane (``consul_tpu/serving``):
the host-side ``QueryBatcher`` packs concurrent requests into
fixed-shape padded batches (bucketed sizes so same-shape batches share
one XLA executable, the ``models/cluster.py`` memoization idiom) and
each batch runs as ONE program here — a broadcast Vivaldi distance, a
mode/eligibility mask, and a single ``lax.top_k`` per query, vmapped
over the batch. Thousands of concurrent lookups become one gather/top-k
kernel instead of thousands of host RPCs.

Distance math reuses :func:`consul_tpu.ops.vivaldi.distance`; the host
``server/rtt.py`` stays the documented reference implementation, and
the golden-parity suite (tests/test_serving.py) pins agreement with it,
including the +inf unknown-coordinate and adjustment-clamp edges.

Snapshots are immutable projections of live simulation state published
by the scan loop (see ``Simulation.publish_serving``): readers holding
a snapshot never block the simulation and never observe a torn state —
every result in a batch is consistent as of the snapshot's ``tick``.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from consul_tpu.ops import vivaldi

# Query modes. NOOP fills padding slots (all-false eligibility, so a
# padded slot costs the same top-k but returns count=0 and no ids).
MODE_NOOP = 0
MODE_NEAREST = 1   # live nodes (optionally one service), RTT order
MODE_DIST = 2      # single node distance: arg = target node index
MODE_CATALOG = 3   # all registered nodes (optionally one service), id order
MODE_HEALTH = 4    # live nodes (optionally one service), id order

# Sort-key sentinels. UNKNOWN must order after every real distance but
# before PAD so eligible-but-coordinate-less nodes keep their place at
# the back of the result (host parity: rtt unknown -> inf, sorts last,
# stable) while ineligible/padding rows never surface at all.
_UNKNOWN_KEY = 1e30
_PAD_KEY = float(jnp.finfo(jnp.float32).max)


class Snapshot(NamedTuple):
    """Immutable device projection of one simulation tick.

    All arrays share the node axis N. ``known`` marks finite Vivaldi
    state (pairs with an unknown side answer +inf, the rtt.py rule);
    ``live`` gates NEAREST/HEALTH eligibility; ``service`` is an i32
    label per node (queries filter with arg, -1 = any); ``tick`` is the
    simulation tick the whole snapshot is consistent as of.
    """

    vec: jax.Array         # [N, D] f32 Vivaldi position
    height: jax.Array      # [N] f32
    adjustment: jax.Array  # [N] f32
    known: jax.Array       # [N] bool — finite coordinate state
    live: jax.Array        # [N] bool — alive and not left
    service: jax.Array     # [N] i32 service label
    tick: jax.Array        # [] i32


@jax.jit
def project(state, service: jax.Array) -> Snapshot:
    """Project live SimState into a read snapshot (one fused program).

    Produces fresh output buffers, which is what makes double-buffered
    publication safe: the scan runner donates and overwrites ``state``
    on the next chunk, but a published Snapshot holds independent
    arrays, so readers keep a coherent tick-T view for free.
    """
    viv = state.viv
    known = (jnp.all(jnp.isfinite(viv.vec), axis=-1)
             & jnp.isfinite(viv.height)
             & jnp.isfinite(viv.adjustment))
    live = state.alive_truth & ~state.left
    return Snapshot(vec=viv.vec, height=viv.height,
                    adjustment=viv.adjustment, known=known, live=live,
                    service=service, tick=state.t)


def _execute(k: int, snap: Snapshot, mode: jax.Array, src: jax.Array,
             arg: jax.Array):
    """One padded batch: mode/src/arg are [B] i32; returns
    ``(ids [B,k] i32, rtts [B,k] f32, count [B] i32, tick [] i32)``.

    Per query: broadcast Vivaldi distance from ``src`` to every node,
    mask eligibility by mode, then one stable ``lax.top_k`` over the
    composed sort key. top_k breaks ties toward the lower index, which
    matches Python's stable sort over index-ordered rows — the property
    the golden-parity suite leans on for exact order agreement.
    Invalid slots (beyond ``count``) come back as id -1 / rtt +inf.
    """
    n = snap.height.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    slot = jnp.arange(k, dtype=jnp.int32)

    def one(m, s, a):
        dist = vivaldi.distance(
            snap.vec[s], snap.height[s], snap.adjustment[s],
            snap.vec, snap.height, snap.adjustment)
        pair_known = snap.known[s] & snap.known
        dist = jnp.where(pair_known, dist, jnp.inf)
        svc_ok = (a < jnp.int32(0)) | (snap.service == a)
        elig = jnp.where(
            m == MODE_DIST, idx == a,
            jnp.where(m == MODE_CATALOG, svc_ok,
                      jnp.where((m == MODE_NEAREST) | (m == MODE_HEALTH),
                                snap.live & svc_ok,
                                jnp.zeros_like(snap.live))))
        by_dist = (m == MODE_NEAREST) | (m == MODE_DIST)
        key = jnp.where(
            by_dist,
            jnp.where(jnp.isfinite(dist), dist, jnp.float32(_UNKNOWN_KEY)),
            idx.astype(jnp.float32))
        key = jnp.where(elig, key, jnp.float32(_PAD_KEY))
        _, ids = jax.lax.top_k(-key, k)
        count = jnp.sum(elig.astype(jnp.int32))
        valid = slot < count
        return (jnp.where(valid, ids.astype(jnp.int32), jnp.int32(-1)),
                jnp.where(valid, dist[ids], jnp.inf),
                count)

    ids, rtts, count = jax.vmap(one)(mode, src, arg)
    return ids, rtts, count, snap.tick


# One jit object per result width k; jit's own shape cache then yields
# exactly one executable per (bucket B, node count N, dim D) — the
# compile-ledger pin in tests/test_serving.py holds steady-state
# serving to zero new compiles.
_KERNEL_CACHE: dict[int, object] = {}


def kernel_for(k: int):
    """Memoized jitted batch executor for result width ``k``."""
    fn = _KERNEL_CACHE.get(k)
    if fn is None:
        fn = _KERNEL_CACHE[k] = jax.jit(functools.partial(_execute, k))
    return fn


def _execute_sharded(k: int, mesh, snap: Snapshot, mode: jax.Array,
                     src: jax.Array, arg: jax.Array):
    """Two-stage shard_map top-k over a mesh-sharded Snapshot.

    Stage 1 (per shard): each device scores its node block — the same
    distance/eligibility/key math as :func:`_execute` but over
    ``block = N / D`` rows with GLOBAL ids — and takes a local
    ``lax.top_k`` of width ``min(k, block)``. Stage 0 feeds it: each
    query's source row lives on one shard, so the owner contributes it
    to a [B, D+3] psum broadcast (no host gather, no replicated vec).

    Stage 2: all-gather the per-shard candidate (key, id, rtt) triples
    — shard-major, so candidates are ordered by (shard, local rank) —
    and merge with one global ``top_k`` of width ``k``; counts psum.

    Tie-break contract: identical to the single-device kernel. Within a
    shard, top_k's lower-index preference yields ascending global ids
    among equal keys; the shard-major candidate layout keeps lower
    shards (= lower global ids) earlier, and the merge's positional
    preference again picks the earliest. Per-shard truncation cannot
    drop a global winner: any row cut locally has >= k better-or-equal
    lower-id rows in its own shard, which already outrank it globally.
    """
    from consul_tpu.parallel.mesh import node_axes, node_spec, shard_map
    from jax.sharding import PartitionSpec as P

    axis, n_shards = node_axes(mesh)
    n = snap.height.shape[0]
    if n % n_shards != 0:
        raise ValueError(f"snapshot n={n} must divide over {n_shards} shards")
    block = n // n_shards
    kk = min(k, block)
    slot = jnp.arange(k, dtype=jnp.int32)

    def local(snap_l: Snapshot, m, s, a):
        shard = jax.lax.axis_index(axis).astype(jnp.int32)
        base = shard * block
        gidx = base + jnp.arange(block, dtype=jnp.int32)

        li = jnp.clip(s - base, 0, block - 1)
        own = (s >= base) & (s < base + block)
        src_vec = jax.lax.psum(
            jnp.where(own[:, None], snap_l.vec[li], 0.0), axis)
        src_h = jax.lax.psum(jnp.where(own, snap_l.height[li], 0.0), axis)
        src_adj = jax.lax.psum(
            jnp.where(own, snap_l.adjustment[li], 0.0), axis)
        src_known = jax.lax.psum(
            (own & snap_l.known[li]).astype(jnp.int32), axis) > 0

        def one(m1, sv, sh, sa, sk, a1):
            dist = vivaldi.distance(
                sv, sh, sa, snap_l.vec, snap_l.height, snap_l.adjustment)
            pair_known = sk & snap_l.known
            dist = jnp.where(pair_known, dist, jnp.inf)
            svc_ok = (a1 < jnp.int32(0)) | (snap_l.service == a1)
            elig = jnp.where(
                m1 == MODE_DIST, gidx == a1,
                jnp.where(m1 == MODE_CATALOG, svc_ok,
                          jnp.where((m1 == MODE_NEAREST) | (m1 == MODE_HEALTH),
                                    snap_l.live & svc_ok,
                                    jnp.zeros_like(snap_l.live))))
            by_dist = (m1 == MODE_NEAREST) | (m1 == MODE_DIST)
            key = jnp.where(
                by_dist,
                jnp.where(jnp.isfinite(dist), dist,
                          jnp.float32(_UNKNOWN_KEY)),
                gidx.astype(jnp.float32))
            key = jnp.where(elig, key, jnp.float32(_PAD_KEY))
            neg, lids = jax.lax.top_k(-key, kk)
            return (-neg, gidx[lids], dist[lids],
                    jnp.sum(elig.astype(jnp.int32)))

        ck, ci, cr, cl = jax.vmap(one)(
            m, src_vec, src_h, src_adj, src_known, a)
        b = ck.shape[0]
        ak = jnp.moveaxis(jax.lax.all_gather(ck, axis), 0, 1).reshape(b, -1)
        ai = jnp.moveaxis(jax.lax.all_gather(ci, axis), 0, 1).reshape(b, -1)
        ar = jnp.moveaxis(jax.lax.all_gather(cr, axis), 0, 1).reshape(b, -1)
        count = jax.lax.psum(cl, axis)
        _, pos = jax.lax.top_k(-ak, k)
        ids = jnp.take_along_axis(ai, pos, axis=1)
        rtts = jnp.take_along_axis(ar, pos, axis=1)
        valid = slot[None, :] < count[:, None]
        return (jnp.where(valid, ids.astype(jnp.int32), jnp.int32(-1)),
                jnp.where(valid, rtts, jnp.inf),
                count)

    snap_specs = jax.tree.map(lambda l: node_spec(l, n, axis), snap)
    inner = shard_map(
        local, mesh=mesh,
        in_specs=(snap_specs, P(), P(), P()),
        out_specs=(P(), P(), P()), check_vma=False,
    )
    ids, rtts, count = inner(snap, mode, src, arg)
    return ids, rtts, count, snap.tick


# One jit object per (k, mesh fingerprint); the mesh is baked into the
# shard_map program, so — exactly like the chunk-runner memo — a new
# surviving-device grid binds a fresh executable and an old one can
# never serve it.
_SHARDED_KERNEL_CACHE: dict = {}


def sharded_kernel_for(k: int, mesh):
    """Memoized jitted two-stage batch executor for result width ``k``
    over ``mesh``. Same signature and result contract as
    :func:`kernel_for` — drop-in for the batcher when the attached
    simulation runs multi-chip."""
    from consul_tpu.parallel.mesh import mesh_key

    key = (k, mesh_key(mesh))
    fn = _SHARDED_KERNEL_CACHE.get(key)
    if fn is None:
        fn = _SHARDED_KERNEL_CACHE[key] = jax.jit(
            functools.partial(_execute_sharded, k, mesh))
    return fn
