"""Simulated cluster topology and ground-truth latency model.

The simulation plants every node at a ground-truth position in a small
Euclidean world with a per-node access-link height — the same generative
model Vivaldi assumes (reference serf/coordinate/coordinate.go:27-31) and
the moral equivalent of the reference's test helper that fabricates
coordinates at a chosen distance (reference lib/rtt.go:56-61). Observed
RTTs are the true distance with lognormal jitter; the same model feeds
both the SWIM probe timing and the Vivaldi observations, so coordinate
RMSE against ground truth is directly measurable.

Membership views are bounded by a neighbor table ``nbrs[N, K]``:

  - **Dense / complete graph** (``SimConfig.view_degree == 0``): node i's
    neighbors are all other nodes in ring order, ``nbrs[i, k] =
    (i + 1 + k) mod N`` — column lookup is closed-form, no memory needed.
    This matches the reference exactly, where every memberlist member
    tracks every other.
  - **Sparse partial view** (``view_degree = K``): each node tracks a
    random K-subset (sorted per row for binary-search column lookup).
    This is the documented divergence that makes >=100k-node simulation
    feasible — a real 1M-node memberlist cluster would need 10^12 member
    map entries across the fleet, which neither the reference nor any
    simulator can hold. Gossip about nodes outside a receiver's view is
    dropped, like HyParView-style partial-view protocols.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.config import SimConfig


class World(NamedTuple):
    """Ground-truth node placement; all units in seconds (RTT space)."""

    pos: jax.Array     # [N, world_dims] float32
    height: jax.Array  # [N] float32


def make_world(cfg: SimConfig, key) -> World:
    k_pos, k_h = jax.random.split(key)
    diameter_s = cfg.world_diameter_ms / 1000.0
    pos = jax.random.uniform(
        k_pos, (cfg.n, cfg.world_dims), jnp.float32, 0.0, diameter_s
    )
    height = jax.random.uniform(
        k_h, (cfg.n,), jnp.float32,
        cfg.height_ms_min / 1000.0, cfg.height_ms_max / 1000.0,
    )
    return World(pos=pos, height=height)


def true_rtt(world: World, i, j):
    """Noise-free round-trip time between node indices, in seconds."""
    d = jnp.linalg.norm(world.pos[i] - world.pos[j], axis=-1)
    return d + world.height[i] + world.height[j]


def sample_rtt(cfg: SimConfig, world: World, i, j, key):
    """One observed RTT sample: true RTT with lognormal jitter."""
    base = true_rtt(world, i, j)
    if cfg.rtt_jitter_frac <= 0.0:
        return base
    log_jitter = jax.random.normal(key, base.shape, jnp.float32) * cfg.rtt_jitter_frac
    return base * jnp.exp(log_jitter)


def make_neighbors(cfg: SimConfig, key) -> jax.Array:
    """Build the neighbor table ``nbrs[N, K]`` (see module docstring)."""
    n, k_deg = cfg.n, cfg.degree
    if cfg.view_degree == 0:
        ring = (jnp.arange(n)[:, None] + 1 + jnp.arange(k_deg)[None, :]) % n
        return ring.astype(jnp.int32)
    # Sparse: sample K distinct non-self neighbors per row, sorted. Built
    # host-side with numpy (one-time setup; distinct targets mirror
    # kRandomNodes, reference memberlist/util.go:125-153). Fully
    # vectorized — draw with replacement, then re-draw the few per-row
    # collisions (expected ~K^2/2(N-1) per row) until none remain, so a
    # 1M-row table builds in seconds rather than via 1M rng calls.
    rng = np.random.default_rng(int(jax.random.randint(key, (), 0, 2**31 - 1)))
    offsets = rng.integers(0, n - 1, size=(n, k_deg))
    for _ in range(64):
        offsets.sort(axis=1)
        dup = np.zeros_like(offsets, dtype=bool)
        dup[:, 1:] = offsets[:, 1:] == offsets[:, :-1]
        n_dup = int(dup.sum())
        if n_dup == 0:
            break
        offsets[dup] = rng.integers(0, n - 1, size=n_dup)
    else:  # pragma: no cover - K close to N; fall back to exact per-row
        for row in np.unique(np.nonzero(dup)[0]):
            offsets[row] = rng.choice(n - 1, size=k_deg, replace=False)
        offsets.sort(axis=1)
    nbrs = (np.arange(n)[:, None] + 1 + offsets) % n
    nbrs.sort(axis=1)
    return jnp.asarray(nbrs, jnp.int32)


def subject_to_col(cfg: SimConfig, nbrs: jax.Array, row, subject):
    """Column of ``subject`` in ``row``'s neighbor table, or -1 if untracked.

    Dense ring layout is closed-form; sparse rows are sorted, so a
    batched binary search resolves each (row, subject) pair.
    """
    if cfg.view_degree == 0:
        col = (subject - row - 1) % cfg.n
        return jnp.where(col < cfg.degree, col, -1).astype(jnp.int32)
    rows = nbrs[row]                      # [..., K] gather
    # Rank-based lookup (K is small): in a sorted row, the number of
    # entries below ``subject`` is its column if present.
    subject = jnp.asarray(subject)
    col = jnp.sum(rows < subject[..., None], axis=-1).astype(jnp.int32)
    col = jnp.clip(col, 0, cfg.degree - 1)
    found = jnp.take_along_axis(rows, col[..., None], axis=-1)[..., 0] == subject
    return jnp.where(found, col, -1)
