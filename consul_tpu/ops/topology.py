"""Simulated cluster topology and ground-truth latency model.

The simulation plants every node at a ground-truth position in a small
Euclidean world with a per-node access-link height — the same generative
model Vivaldi assumes (reference serf/coordinate/coordinate.go:27-31) and
the moral equivalent of the reference's test helper that fabricates
coordinates at a chosen distance (reference lib/rtt.go:56-61). Observed
RTTs are the true distance with lognormal jitter; the same model feeds
both the SWIM probe timing and the Vivaldi observations, so coordinate
RMSE against ground truth is directly measurable.

Membership views are bounded by a **symmetric circulant neighbor
relation** shared by every node::

    nbrs(i, c) = (i + off[c]) mod N,   off[K] sorted, distinct,
                                       d in off  <=>  N-d in off

  - **Dense / complete graph** (``SimConfig.view_degree == 0``):
    ``off = [1..N-1]`` — every node tracks every other, exactly like a
    real memberlist member map. All column maps are closed-form.
  - **Sparse partial view** (``view_degree = K``): ``off`` is a random
    K-subset closed under negation. Random circulant graphs are
    expanders w.h.p., so epidemics spread in O(log N) rounds like the
    reference's full-graph gossip; unlike per-row random subsets the
    in-degree is *exactly* K for every node, so probe coverage is
    uniform (no under-probed nodes). This is the documented divergence
    that makes >=100k-node simulation feasible — a real 1M-node
    memberlist cluster would need 10^12 member-map entries fleet-wide.

Why circulant rather than per-row random (the TPU-first design move of
this module): the relation is **translation-invariant**, so every
"deliver to receiver" operation inverts into a dense gather *from* the
sender at a fixed shift — ``x[(i - off[j]) mod N]`` — and the column any
gossiped subject occupies at the receiver depends only on the (sender
column, receiver in-column) pair, giving a static ``rcol[K, K]`` remap
table. The whole message plane therefore compiles to gathers, rolls and
table lookups — no scatters, which XLA serializes on TPU (the round-1
scaling cliff).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.config import SimConfig

# rcol sentinel: the subject of this (in-column, sender-column) pair is
# the receiver itself (refutation fodder, never a view merge).
SELF = -2
# rcol sentinel: subject not in the receiver's partial view.
ABSENT = -1


class World(NamedTuple):
    """Ground-truth node placement; all units in seconds (RTT space)."""

    pos: jax.Array     # [N, world_dims] float32
    height: jax.Array  # [N] float32


class Topology(NamedTuple):
    """The shared circulant neighbor relation (see module docstring).

    ``rcol``/``inv`` are None in dense mode, where both are closed-form:
    the tables would be [N-1, N-1]. All helpers below branch on
    ``dense`` (a static Python bool — Topology instances are closed
    over by jitted steps, never traced).
    """

    n: int                       # static
    dense: bool                  # static
    off: jax.Array               # [K] int32, sorted
    rcol: Optional[jax.Array]    # [K, K] int32: receiver column of the
                                 # sender's column c when the sender sits
                                 # at the receiver's in-column j; SELF
                                 # when c == j; ABSENT when untracked
    inv: Optional[jax.Array]     # [K] int32: column of (N - off[j]) —
                                 # where the *sender itself* sits in the
                                 # receiver's view (always present:
                                 # the offset set is symmetric)

    @property
    def degree(self) -> int:
        return self.off.shape[0]


def make_topology(cfg: SimConfig, key) -> Topology:
    """Build the offset table and static remap tables (host-side, once).

    The offset set comes from the family registry
    (consul_tpu/topo/families.py, selected by ``cfg.topo_family``);
    every family emits a symmetric circulant offset set, so the remap
    tables below are family-independent. The default "circulant"
    family consumes the rng exactly like the pre-registry code, so
    default topologies are bit-identical (golden-pinned in tests).
    """
    from consul_tpu import topo as topo_families

    n, k_deg = cfg.n, cfg.degree
    if k_deg == n - 1:  # complete graph (view_degree 0 or >= n-1)
        off = jnp.arange(1, n, dtype=jnp.int32)
        return Topology(n=n, dense=True, off=off, rcol=None, inv=None)
    if k_deg % 2 != 0:
        raise ValueError("sparse view_degree must be even (symmetric offsets)")
    rng = np.random.default_rng(int(jax.random.randint(key, (), 0, 2**31 - 1)))
    off_np = topo_families.offsets_for(
        cfg.topo_family, n, k_deg, rng, param=cfg.topo_param)
    return topology_from_offsets(n, off_np)


def topology_from_offsets(n: int, off_np: np.ndarray) -> Topology:
    """Build the remap/inverse tables for a validated offset set."""
    off_np = np.asarray(off_np, dtype=np.int64)
    k_deg = off_np.shape[0]
    # Static remap: rcol[j, c] = column of (off[c] - off[j]) mod n.
    d = (off_np[None, :] - off_np[:, None]) % n          # [K, K]
    col = np.searchsorted(off_np, d)
    col = np.clip(col, 0, k_deg - 1)
    found = off_np[col] == d
    rcol = np.where(found, col, ABSENT)
    rcol[np.arange(k_deg), np.arange(k_deg)] = SELF      # d == 0
    inv = np.searchsorted(off_np, (n - off_np))          # always found
    return Topology(
        n=n,
        dense=False,
        off=jnp.asarray(off_np, jnp.int32),
        rcol=jnp.asarray(rcol, jnp.int32),
        inv=jnp.asarray(inv, jnp.int32),
    )


# ----------------------------------------------------------------------
# Column algebra. j/c may be traced scalars or arrays.
# ----------------------------------------------------------------------

def neighbor_of(topo: Topology, row, col):
    """Global id of ``row``'s neighbor at ``col``: (row + off[col]) mod N."""
    return (row + topo.off[col]) % topo.n


def nbrs_table(topo: Topology) -> jax.Array:
    """Materialized [N, K] neighbor-id table (tests / host-side only)."""
    rows = jnp.arange(topo.n, dtype=jnp.int32)
    return (rows[:, None] + topo.off[None, :]) % topo.n


def subject_to_col(topo: Topology, row, subject):
    """Column of ``subject`` in ``row``'s view, or ABSENT, or SELF."""
    d = (jnp.asarray(subject) - jnp.asarray(row)) % topo.n
    if topo.dense:
        return jnp.where(d == 0, SELF, d - 1).astype(jnp.int32)
    col = jnp.searchsorted(topo.off, d.astype(jnp.int32)).astype(jnp.int32)
    col_c = jnp.clip(col, 0, topo.degree - 1)
    found = topo.off[col_c] == d
    return jnp.where(d == 0, SELF, jnp.where(found, col_c, ABSENT))


def remap_row(topo: Topology, j):
    """``rcol[j]`` as a [K] vector for a (possibly traced) in-column j.

    Entry c is the receiver's column for the sender's column-c subject
    (SELF when c == j — that subject is the receiver itself).
    """
    if topo.dense:
        k_deg = topo.degree
        c = jnp.arange(k_deg, dtype=jnp.int32)
        d = (c - j) % (k_deg + 1)  # off[c]-off[j] mod n ≡ (c-j) mod n; n=K+1
        return jnp.where(c == j, SELF, (d - 1).astype(jnp.int32))
    return topo.rcol[j]


def inv_col(topo: Topology, j):
    """Column where the sender itself sits in the receiver's view, given
    the sender occupies the receiver's in-column j (i.e. receiver =
    sender + off[j]): the column of offset N - off[j]."""
    if topo.dense:
        return jnp.int32(topo.n - 2) - jnp.asarray(j, jnp.int32)
    return topo.inv[j]


def gather_from_senders(topo: Topology, x: jax.Array, j):
    """``x`` re-indexed so position r holds the value at r's in-column-j
    sender, ``x[(r - off[j]) mod N]`` — the receiver-side inversion of
    "sender s delivers to s + off[j]". Works for [N, ...] arrays."""
    return jnp.roll(x, topo.off[j], axis=0)


def gather_cols(topo: Topology, x: jax.Array) -> jax.Array:
    """[N, K] view of a per-node array along the neighbor relation:
    out[i, c] = x[(i + off[c]) mod N] (used by metrics/tests). Sparse
    mode stacks K static rolls — TPU-cheap contiguous copies — instead
    of an [N, K] per-row gather. When the offsets are a *program
    argument* (chaos/sweep.py passes them traced so same-shape families
    share one executable), the rolls take traced shifts instead."""
    off = topo.off
    if not topo.dense and topo.degree <= 256:
        if isinstance(off, jax.core.Tracer):
            return jnp.stack(
                [jnp.roll(x, -off[c]) for c in range(topo.degree)], axis=1
            )
        off_np = np.asarray(off)
        return jnp.stack(
            [jnp.roll(x, -int(off_np[c])) for c in range(topo.degree)], axis=1
        )
    rows = jnp.arange(topo.n, dtype=jnp.int32)
    return x[(rows[:, None] + off[None, :]) % topo.n]


# ----------------------------------------------------------------------
# Ground-truth world.
# ----------------------------------------------------------------------

def make_world(cfg: SimConfig, key) -> World:
    k_pos, k_h = jax.random.split(key)
    diameter_s = cfg.world_diameter_ms / 1000.0
    pos = jax.random.uniform(
        k_pos, (cfg.n, cfg.world_dims), jnp.float32, 0.0, diameter_s
    )
    height = jax.random.uniform(
        k_h, (cfg.n,), jnp.float32,
        cfg.height_ms_min / 1000.0, cfg.height_ms_max / 1000.0,
    )
    return World(pos=pos, height=height)


def true_rtt(world: World, i, j):
    """Noise-free round-trip time between node indices, in seconds."""
    d = jnp.linalg.norm(world.pos[i] - world.pos[j], axis=-1)
    return d + world.height[i] + world.height[j]


def sample_rtt(cfg: SimConfig, world: World, i, j, key):
    """One observed RTT sample: true RTT with lognormal jitter."""
    base = true_rtt(world, i, j)
    if cfg.rtt_jitter_frac <= 0.0:
        return base
    log_jitter = jax.random.normal(key, base.shape, jnp.float32) * cfg.rtt_jitter_frac
    return base * jnp.exp(log_jitter)
