"""The backend-init black box: capture *why* a backend wedged.

BENCH_r05.json records a real 300 s ``backend-init-hang``; the
watchdog (runtime/watchdog.py) turns that into a kill + a status
string, and with_failover degrades to the next platform — but the
status string is where the diagnosis used to END. A wedged TPU relay
leaves no traceback: the child is blocked inside ``jax.devices()``
when it dies, so the only evidence is environmental. This module is
the flight-recorder dump for that moment — everything the host side
can still see once the child is gone:

- the backend-relevant environment (JAX_*/TPU_*/XLA_*... — the knobs
  that select platforms, relays, and plugin paths);
- the installed libtpu version and the tail of its newest log file
  (libtpu writes under ``TPU_LOG_DIR`` or ``/tmp/tpu_logs``);
- the tail of the child's last stdout/stderr (the supervisor passes
  it — the JSONL phases the child streamed before wedging);
- partial device-enumeration progress: which backends THIS process
  has initialized, read from jax's backend registry without calling
  ``jax.devices()`` (which is exactly the call that hangs — the
  utils/debug.py hang-guard pattern);
- the last N host spans from the process tracer's bounded ring
  (obs/trace.py) — what the host was doing leading up to the hang.

:func:`capture` writes one ``blackbox.json`` and returns the dict;
with_failover provenance links the artifact path so the bench JSON
points at the evidence.
"""

from __future__ import annotations

import json
import os
from typing import Optional

SCHEMA_VERSION = 1

# Environment prefixes that steer backend selection and init — the
# knob set a wedged-relay postmortem always starts from.
_ENV_PREFIXES = ("JAX", "TPU", "XLA", "LIBTPU", "PJRT", "TF_")

# Default log-tail / span-tail sizes: enough to see the last moves,
# bounded so the artifact stays a few KB.
_TAIL_LINES = 50
_LAST_SPANS = 64


def capture_env() -> dict:
    """The backend-relevant environment (sorted, values verbatim —
    these are config knobs, not secrets)."""
    return {k: v for k, v in sorted(os.environ.items())
            if k.startswith(_ENV_PREFIXES)}


def tail_file(path: str, lines: int = _TAIL_LINES) -> Optional[str]:
    """Last ``lines`` lines of a text file; None when unreadable."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - 64 * 1024))
            data = f.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    return "\n".join(data.splitlines()[-lines:])


def libtpu_info() -> dict:
    """Installed libtpu version + the tail of its newest log file.
    Pure metadata reads — never imports or initializes the library."""
    info: dict = {"version": None, "log_file": None, "log_tail": None}
    try:
        from importlib import metadata
        for dist in ("libtpu", "libtpu-nightly"):
            try:
                info["version"] = f"{dist} {metadata.version(dist)}"
                break
            except metadata.PackageNotFoundError:
                continue
    except Exception as e:  # noqa: BLE001 — diagnosis must never raise
        info["version_error"] = repr(e)
    log_dir = os.environ.get("TPU_LOG_DIR", "/tmp/tpu_logs")
    try:
        files = [os.path.join(log_dir, f) for f in os.listdir(log_dir)]
        files = [f for f in files if os.path.isfile(f)]
        if files:
            newest = max(files, key=os.path.getmtime)
            info["log_file"] = newest
            info["log_tail"] = tail_file(newest)
    except OSError:
        pass
    return info


def device_progress() -> dict:
    """How far backend bring-up got in THIS process, read from jax's
    backend registry WITHOUT calling ``jax.devices()`` — that call is
    the one that hangs on a wedged relay (the utils/debug.py
    hang-guard). ``backends`` lists platforms that fully initialized;
    an empty list during an init-hang means the wedge is inside the
    first bring-up."""
    out: dict = {"jax_imported": False, "backends": [], "error": None}
    import sys
    if "jax" not in sys.modules:
        return out  # never pay for (or hang on) a jax import here
    out["jax_imported"] = True
    try:
        from jax._src import xla_bridge as _xb
        backends = getattr(_xb, "_backends", None)
        if backends:
            out["backends"] = sorted(backends.keys())
    except Exception as e:  # noqa: BLE001
        out["error"] = repr(e)
    return out


def capture(path: Optional[str] = None, *,
            status: Optional[str] = None,
            child_tail: Optional[str] = None,
            extra: Optional[dict] = None,
            last_spans: int = _LAST_SPANS) -> dict:
    """Assemble the black box; write it to ``path`` (blackbox.json)
    when given. Every section is best-effort — a postmortem that
    raises is worse than a partial one."""
    from consul_tpu.obs import trace as trace_mod

    box: dict = {
        "schema_version": SCHEMA_VERSION,
        "status": status,
        "env": capture_env(),
        "libtpu": libtpu_info(),
        "devices": device_progress(),
        "child": {"tail": child_tail},
        "spans": trace_mod.get_tracer().last_spans(last_spans),
    }
    if extra:
        box.update(extra)
    if path:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(box, f, indent=2, default=str)
    return box
