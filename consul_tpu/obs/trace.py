"""Host span tracing: the flight recorder for the hot host seams.

The device side of the step already has a profiler (``jax.profiler``
writes an XLA trace); what the repo could not see is the *host* choreography
around it — cohort uploads, batcher pumps, watch-plane flips, checkpoint
I/O, DCN retry rounds, bench phases, and XLA compiles. This module is a
stdlib-only tracer for exactly those seams:

- one shared :class:`Tracer` per process (the Sink idiom: module-level
  singleton behind :func:`get_tracer`), always recording into a bounded
  ring buffer — so the last-N spans are available to the backend-init
  black box even when nobody asked for a trace artifact;
- spans via context manager (:func:`span`) or decorator
  (:func:`traced`), timed with ``time.perf_counter`` (monotonic — the
  TH112 rule bans wall-clock duration math for exactly this job);
- export as Chrome trace-event JSON (:meth:`Tracer.export`), the format
  Perfetto and ``chrome://tracing`` load directly; the on-device lens
  appends its per-node counter tracks to the same file so host spans,
  chunk markers, and node timelines render in one view;
- XLA compile events folded in through the same ``jax.monitoring``
  backend-compile listener the CompileLedger counts
  (:func:`install_jax_hooks`) — every real executable build shows up as
  a ``cat="xla"`` span without wrapping or patching anything;
- span-duration aggregates flow into an attached telemetry Sink as
  ``sim.obs.span.<name>`` samples, whose p50/p99 the Prometheus
  exposition renders (utils/telemetry.to_prometheus).

``jax.profiler.StepTraceAnnotation`` alignment: the chunk loop wraps
each compiled chunk in :func:`chunk_annotation`, which emits BOTH the
XLA step marker (visible in the profiler's trace) and a host ``chunk``
span (visible here) with the same step number — loading the two files
into one Perfetto session lines the timelines up.
"""

from __future__ import annotations

import contextlib
import functools
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

# Pinned by the golden schema test (tests/test_obs.py): consumers of
# the artifact key on these.
SCHEMA_VERSION = 1

# Ring capacity: bounded so an un-exported tracer can never grow the
# process (the InmemSink discipline). 4096 events at ~200 B each is
# under a megabyte.
DEFAULT_CAPACITY = 4096

# Metric-name prefix for span-duration samples (COVERAGE.md telemetry
# table; tests/test_metric_names.py extracts the static prefix).
SPAN_METRIC_PREFIX = "sim.obs.span"


class Tracer:
    """Bounded ring of Chrome trace events, monotonic-clocked.

    Timestamps are microseconds since the tracer's birth on the
    ``perf_counter`` clock — durations are exact, absolute wall time is
    deliberately absent (spans measure, they do not timestamp)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._events: deque = deque(maxlen=capacity)
        self._t0 = time.perf_counter()
        self._pid = os.getpid()
        self._sink = None
        self.dropped = 0  # events evicted by the bounded ring

    # -- clock ----------------------------------------------------------
    def now_us(self) -> float:
        """Microseconds since tracer birth (monotonic)."""
        return (time.perf_counter() - self._t0) * 1e6

    # -- sink mirror ----------------------------------------------------
    def attach_sink(self, sink) -> None:
        """Mirror span durations into a telemetry Sink as
        ``sim.obs.span.<name>`` samples (p50/p99 in to_prometheus).
        Last attach wins — one process, one sink, like the Sink itself.
        """
        self._sink = sink

    # -- recording ------------------------------------------------------
    def _append(self, ev: dict) -> None:
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    def complete(self, name: str, start_us: float, dur_us: float,
                 cat: str = "host", args: Optional[dict] = None,
                 tid: Optional[int] = None) -> None:
        """Record one complete ("X") span with explicit timing — the
        raw entry point the jax compile listener uses (it only learns
        the duration after the fact)."""
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": round(start_us, 3), "dur": round(dur_us, 3),
              "pid": self._pid,
              "tid": tid if tid is not None else threading.get_ident()}
        if args:
            ev["args"] = dict(args)
        self._append(ev)
        sink = self._sink
        if sink is not None:
            sink.add_sample(f"{SPAN_METRIC_PREFIX}.{name}", dur_us / 1e3)

    def instant(self, name: str, cat: str = "host",
                args: Optional[dict] = None) -> None:
        """Record an instant ("i") event — a point marker, no duration
        (and no sink sample)."""
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": round(self.now_us(), 3), "pid": self._pid,
              "tid": threading.get_ident()}
        if args:
            ev["args"] = dict(args)
        self._append(ev)

    def counter(self, name: str, value: float, ts_us: float,
                series: str = "value", pid: Optional[int] = None) -> None:
        """Record a counter ("C") sample — a point on a counter track.
        The lens renders each sampled node's fields as these."""
        self._append({"name": name, "cat": "lens", "ph": "C",
                      "ts": round(ts_us, 3),
                      "pid": pid if pid is not None else self._pid,
                      "args": {series: float(value)}})

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host",
             args: Optional[dict] = None):
        """Time a block as one complete span."""
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            t1 = time.perf_counter()
            self.complete(name, (t0 - self._t0) * 1e6, (t1 - t0) * 1e6,
                          cat=cat, args=args)

    def traced(self, name: Optional[str] = None, cat: str = "host"
               ) -> Callable:
        """Decorator form of :meth:`span`."""
        def deco(fn):
            label = name or fn.__qualname__

            @functools.wraps(fn)
            def wrapper(*a, **kw):
                with self.span(label, cat=cat):
                    return fn(*a, **kw)
            return wrapper
        return deco

    # -- reads ----------------------------------------------------------
    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def last_spans(self, n: int = 64) -> list:
        """The newest ``n`` events — the black box's flight-recorder
        tail."""
        with self._lock:
            evs = list(self._events)
        return evs[-n:]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    # -- export ---------------------------------------------------------
    def to_json(self, extra_events: Optional[list] = None) -> dict:
        """The Chrome trace-event JSON object (the golden schema the
        tests pin): ``traceEvents`` plus provenance in ``otherData``."""
        evs = self.events()
        if extra_events:
            evs = evs + list(extra_events)
        return {
            "traceEvents": evs,
            "displayTimeUnit": "ms",
            "otherData": {
                "schema_version": SCHEMA_VERSION,
                "producer": "consul-tpu obs.trace",
                "clock": "perf_counter_us_since_tracer_birth",
                "dropped_events": self.dropped,
            },
        }

    def export(self, path: str,
               extra_events: Optional[list] = None) -> str:
        """Write the Perfetto-loadable JSON artifact; returns ``path``.
        ``extra_events`` (e.g. the lens's counter tracks) merge into the
        same file."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.to_json(extra_events), f)
        return path


# -- the shared process tracer (the Sink idiom) -------------------------
_TRACER: Optional[Tracer] = None
_TRACER_LOCK = threading.Lock()


def get_tracer() -> Tracer:
    """The one process-wide tracer. Always recording (bounded ring), so
    the black box has a span tail even when nobody exports."""
    global _TRACER
    with _TRACER_LOCK:
        if _TRACER is None:
            _TRACER = Tracer()
        return _TRACER


@contextlib.contextmanager
def span(name: str, cat: str = "host", args: Optional[dict] = None):
    """Module-level sugar: a span on the shared tracer."""
    with get_tracer().span(name, cat=cat, args=args):
        yield


def traced(name: Optional[str] = None, cat: str = "host") -> Callable:
    """Module-level decorator sugar on the shared tracer (bound at call
    time, so tests that reset the tracer see their spans)."""
    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with get_tracer().span(label, cat=cat):
                return fn(*a, **kw)
        return wrapper
    return deco


# -- XLA compile events (the CompileLedger's hook) ----------------------
_JAX_HOOKED = False


def install_jax_hooks() -> None:
    """Register a ``jax.monitoring`` listener for the backend-compile
    duration event (analysis/guards.COMPILE_EVENT — the same event the
    CompileLedger counts), recording every real executable build as a
    ``cat="xla"`` span. Idempotent; needs jax, so it is called from the
    drivers, never at import."""
    global _JAX_HOOKED
    with _TRACER_LOCK:
        if _JAX_HOOKED:
            return
        import jax

        from consul_tpu.analysis.guards import COMPILE_EVENT

        def _on_event(event: str, duration: float, **kw):
            if event != COMPILE_EVENT:
                return
            tr = get_tracer()
            # The listener fires at compile END with the duration; back
            # the start out so the span lands where the compile ran.
            end_us = tr.now_us()
            tr.complete("xla.backend_compile",
                        max(0.0, end_us - duration * 1e6),
                        duration * 1e6, cat="xla")

        jax.monitoring.register_event_duration_secs_listener(_on_event)
        _JAX_HOOKED = True


@contextlib.contextmanager
def chunk_annotation(step_num: int, ticks: int):
    """Bracket one compiled chunk: emits the XLA
    ``StepTraceAnnotation`` (so the device profiler's trace carries the
    chunk marker) AND a host ``chunk`` span with the same step number —
    the alignment key between the two timelines."""
    import jax

    with jax.profiler.StepTraceAnnotation("sim_chunk", step_num=step_num):
        with span("chunk", cat="chunk",
                  args={"step": int(step_num), "ticks": int(ticks)}):
            yield
