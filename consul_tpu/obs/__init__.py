"""Flight-recorder observability plane.

Three layers, one artifact:

- :mod:`consul_tpu.obs.trace` — host span tracing: a stdlib-only
  tracer (context-manager + decorator, monotonic clocks, bounded
  process-wide ring buffer) emitting Chrome trace-event / Perfetto
  JSON, with XLA compile events folded in via the same
  ``jax.monitoring`` listener the CompileLedger counts.
- :mod:`consul_tpu.obs.lens` — the on-device node lens: S statically
  sampled node ids recorded per tick inside the jitted scan, exported
  as per-node counter timelines in the same Perfetto file.
- :mod:`consul_tpu.obs.blackbox` — the backend-init black box: when a
  child wedges inside backend init, capture *why* (env, libtpu, the
  child's last output, device-enumeration progress, the last host
  spans) into a ``blackbox.json`` artifact.

The package is host-tier (never under a trace except the lens snapshot,
which is pure gathers); importing it must not pay for JAX.
"""

from consul_tpu.obs import blackbox, lens, trace  # noqa: F401

__all__ = ["blackbox", "lens", "trace"]
