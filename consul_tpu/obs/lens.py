"""The on-device node lens: replay one node's life out of the batch.

Counters answer "how many false suspicions happened"; they cannot
answer "why was node X falsely suspected at tick 4017" — the batched
representation has no per-node narrative. The lens is that narrative
for S statically sampled node ids: every tick inside the jitted scan,
one ``[S, F]`` row of per-node observables is gathered at *static*
indices (constant-index gathers — zero TH109 scatters) and rides the
scan's stacked output exactly like the TickTrace, so a chunk costs one
extra ``[C, S, F]`` device buffer and ONE explicit batched
``jax.device_get`` at flush time (GossipCounters' transfer discipline).

Toggling follows the ``set_sentinel`` DCE contract: lens off is the
pre-lens program byte-for-byte (the compile-ledger pins zero extra
executables), lens on compiles exactly one more program per shape.

Fields (wire order of the F axis; all recorded as f32 — every value
fits in f32's 24-bit integer range by construction):

  ======================  =============================================
  field                   meaning (source leaf)
  ======================  =============================================
  status                  ground truth: 0 dead / 1 alive / 2 leaving /
                          3 left  (alive_truth, leaving, left)
  incarnation             the node's own incarnation (own_inc)
  susp_age                ticks since the OLDEST active suspicion this
                          node holds; -1 when none (susp_start)
  probe_deadline_delta    ticks until the outstanding probe window
                          closes; -1 when no probe in flight
                          (pending_fail_tick, pending_col)
  lamport                 serf membership Lamport clock; 0 under bare
                          SWIM (SerfState.clock)
  vivaldi_error           Vivaldi confidence estimate (viv.error)
  msgs_tx                 queued broadcast transmits remaining
                          (tx_left row sum + own_tx)
  ======================  =============================================

Export renders each sampled node's fields as Perfetto counter tracks
("C" events under a dedicated ``node-lens`` process) in the same
Chrome trace-event file as the host spans; tick timestamps interpolate
linearly across the enclosing chunk's host span, so node timelines and
host/XLA activity line up in one view.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

# Field order is the wire order of the [.., F] axis — pinned by the
# golden schema test. Keep the module docstring table in sync.
FIELDS = ("status", "incarnation", "susp_age", "probe_deadline_delta",
          "lamport", "vivaldi_error", "msgs_tx")

# Extra field group appended when the raft tier rides the scan
# (Simulation.set_raft + set_lens): lens slot s tracks raft group
# ``ids[s] mod R`` — per-group max term, seat 0's role, the leader id
# the rank-max summary sees (-1 = none), and the group's max commit
# index. Same f32 wire discipline as FIELDS.
RAFT_FIELDS = ("raft_term", "raft_role", "raft_leader", "raft_commit")

# Perfetto process id grouping the lens counter tracks apart from the
# host-span pid (the host tracer uses os.getpid()).
LENS_PID = 2


def normalize_ids(n: int, sample: Union[int, Sequence[int]]) -> tuple:
    """Resolve a lens request to a static id tuple: an int S picks S
    evenly spaced node ids (deterministic — same S, same ids); an
    iterable passes through validated."""
    if isinstance(sample, bool):
        raise TypeError("lens sample must be an int count or id list")
    if isinstance(sample, int):
        if sample <= 0:
            return ()
        s = min(sample, n)
        stride = n // s
        ids = tuple(i * stride for i in range(s))
    else:
        ids = tuple(int(i) for i in sample)
    for i in ids:
        if not 0 <= i < n:
            raise ValueError(f"lens node id {i} outside [0, {n})")
    if len(set(ids)) != len(ids):
        raise ValueError("lens node ids must be distinct")
    return ids


def snapshot(sw, clock, ids: tuple):
    """One per-tick lens row: ``[S, F]`` f32, gathered from the dense
    SWIM plane ``sw`` (and the serf Lamport ``clock`` when the driver
    has one) at the static ``ids``. Runs inside the jitted scan body —
    pure gathers and reductions, no scatters, no host syncs."""
    import jax.numpy as jnp

    idx = jnp.array(ids, dtype=jnp.int32)
    f32 = jnp.float32
    status = jnp.where(
        sw.left[idx], f32(3.0),
        jnp.where(sw.leaving[idx], f32(2.0),
                  jnp.where(sw.alive_truth[idx], f32(1.0), f32(0.0))))
    inc = sw.own_inc[idx].astype(f32)
    ss = sw.susp_start[idx]                      # [S, K]
    active = ss >= 0
    oldest = jnp.min(jnp.where(active, ss, jnp.int32(2 ** 31 - 1)), axis=1)
    susp_age = jnp.where(jnp.any(active, axis=1),
                         (sw.t - oldest).astype(f32), f32(-1.0))
    probing = sw.pending_col[idx] >= 0
    probe_delta = jnp.where(
        probing, (sw.pending_fail_tick[idx] - sw.t).astype(f32), f32(-1.0))
    if clock is None:
        lamport = jnp.zeros((len(ids),), f32)
    else:
        lamport = clock[idx].astype(f32)
    viv_err = sw.viv.error[idx].astype(f32)
    msgs = (jnp.sum(sw.tx_left[idx], axis=1) + sw.own_tx[idx]).astype(f32)
    return jnp.stack([status, inc, susp_age, probe_delta,
                      lamport, viv_err, msgs], axis=1)


def raft_snapshot(rst, ids: tuple):
    """Per-tick raft lens rows: ``[S, len(RAFT_FIELDS)]`` f32, lens
    slot s mapped onto raft group ``ids[s] mod R`` (static indices —
    the snapshot() gather discipline). Concatenated onto the SWIM row
    along the field axis by the chunk body when raft is armed."""
    import jax.numpy as jnp

    from consul_tpu.ops import raft_ops

    r_count = rst.term.shape[0]
    g = jnp.array([i % r_count for i in ids], dtype=jnp.int32)
    f32 = jnp.float32
    term = jnp.max(rst.term[g], axis=1).astype(f32)
    role = rst.role[g, jnp.zeros((len(ids),), jnp.int32)].astype(f32)
    _, leader_g, commit_g, _ = raft_ops.summary(rst)
    return jnp.stack([term, role, leader_g[g].astype(f32),
                      commit_g[g].astype(f32)], axis=1)


class LensRecorder:
    """Host half of the lens: per-chunk ``[C, S, F]`` device buffers
    queue here (references only — no transfer) and drain in ONE
    explicit batched ``jax.device_get`` at :meth:`flush`, keeping the
    chunk loop legal under ``jax.transfer_guard("disallow")``.

    Each chunk records its host wall window (tracer-relative
    microseconds) so export can interpolate a timestamp per tick and
    the node timelines land inside the matching ``chunk`` span."""

    def __init__(self, ids: tuple, tick0: int = 0,
                 fields: tuple = FIELDS):
        self.ids = tuple(ids)
        self.fields = tuple(fields)
        self._next_tick = int(tick0)
        self._pending: list = []   # (tick0, ticks, t0_us, t1_us, dev buf)
        self._chunks: list = []    # same tuples with host numpy buffers

    def record(self, buf, ticks: int,
               t0_us: float = 0.0, t1_us: float = 0.0) -> None:
        """Queue one chunk's device buffer (no transfer here)."""
        self._pending.append(
            (self._next_tick, int(ticks), float(t0_us), float(t1_us), buf))
        self._next_tick += int(ticks)

    def flush(self) -> None:
        """One batched device→host transfer for every queued chunk."""
        if not self._pending:
            return
        import jax

        host = jax.device_get([p[4] for p in self._pending])
        for (t0, ticks, a, b, _), h in zip(self._pending, host):
            self._chunks.append((t0, ticks, a, b, h))
        self._pending = []

    @property
    def ticks_recorded(self) -> int:
        self_len = sum(p[1] for p in self._pending)
        return self_len + sum(c[1] for c in self._chunks)

    def timelines(self):
        """``(ticks [T] i32, values [T, S, F] f32)`` — the whole
        recording as host numpy arrays (flushes first)."""
        import numpy as np

        self.flush()
        if not self._chunks:
            return (np.zeros((0,), np.int32),
                    np.zeros((0, len(self.ids), len(self.fields)),
                             np.float32))
        ticks = np.concatenate([
            np.arange(t0, t0 + n, dtype=np.int32)
            for t0, n, _, _, _ in self._chunks])
        vals = np.concatenate([np.asarray(h, np.float32)
                               for _, _, _, _, h in self._chunks])
        return ticks, vals

    def to_json(self) -> dict:
        """The bundle-able summary (debug bundle ``lens.json``)."""
        ticks, vals = self.timelines()
        return {
            "ids": list(self.ids),
            "fields": list(self.fields),
            "ticks": [int(t) for t in ticks],
            "values": [[[float(v) for v in node] for node in row]
                       for row in vals],
        }

    def to_trace_events(self) -> list:
        """Perfetto counter tracks: one "C" series per (node, field),
        timestamps interpolated across each chunk's host wall window.
        Returned as plain event dicts for ``Tracer.export``'s
        ``extra_events`` — they merge into the host-span file without
        evicting ring entries."""
        self.flush()
        events: list = [
            {"name": "process_name", "ph": "M", "pid": LENS_PID,
             "args": {"name": "node-lens"}},
        ]
        for t0, nticks, a, b, h in self._chunks:
            step_us = (b - a) / max(1, nticks)
            for j in range(nticks):
                ts = a + step_us * j
                for s, nid in enumerate(self.ids):
                    for f, field in enumerate(self.fields):
                        events.append({
                            "name": f"node{nid}/{field}", "cat": "lens",
                            "ph": "C", "ts": round(ts, 3),
                            "pid": LENS_PID,
                            "args": {"value": float(h[j, s, f])},
                        })
        return events
