"""Checkpoint policy: when to save, where, and what provenance rides
along — lifted out of bench.py's private northstar one-off so every
entry point shares one preemption-safe mechanism.

Triggers, composable per save decision (:meth:`CheckpointPolicy.due`):

- **interval**: at most once per ``min_interval_s`` of WALL time — a
  1M-node save drags the whole device state through the remote-TPU
  tunnel (~150 s measured, bench round 5), so tick-paced saves would
  dominate the run; ``every_ticks`` only bounds the slice between
  trigger checks.
- **on-signal**: a :class:`SignalTrap` records SIGTERM (the preemption
  notice every scheduler sends before SIGKILL); the next chunk
  boundary saves immediately and the harness exits cleanly.
- **on-hang**: anything that owns a liveness view (a watchdog thread,
  an external monitor) calls :meth:`CheckpointPolicy.request`; the
  next boundary saves regardless of pacing.

The save itself is utils/checkpoint's digest-verified atomic-rename
write. Run provenance (ticks done, chaos-schedule tick offset and
digest — what a resumed chaos run needs to replay the remaining
schedule bit-identically) is embedded in the checkpoint manifest
(``manifest_meta=True``) and always mirrored to a ``.meta.json``
sidecar readable without touching the payload.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import threading
import time
from typing import Any, Optional

from consul_tpu.utils import checkpoint as ckpt_mod

log = logging.getLogger(__name__)


class SignalTrap:
    """Record (rather than act on) termination signals so the run loop
    can checkpoint at the next chunk boundary — the preemption grace
    window turned into at-most-one-chunk of lost work. Restores the
    previous handlers on exit; outside the main thread (where Python
    forbids signal handlers) it degrades to an inert trap."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self.signals = tuple(signals)
        self.fired: Optional[int] = None
        self._prev: dict = {}

    def _handle(self, signum, frame):
        self.fired = signum

    def __enter__(self) -> "SignalTrap":
        if threading.current_thread() is threading.main_thread():
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._handle)
        return self

    def __exit__(self, *exc):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        return False


@dataclasses.dataclass
class CheckpointPolicy:
    """One run's checkpoint cadence + provenance. ``tag`` names the
    checkpoint file (``{directory}/{tag}.ckpt``) — one trajectory, one
    file, atomically replaced on every save (a torn write can never
    replace a good checkpoint, utils/checkpoint.save).

    ``manifest_meta=False`` keeps provenance in the sidecar only —
    the bench northstar artifact predates manifest meta and its save
    interception point (``ckpt_mod.save(path, state)``) is pinned by
    tests/test_bench_checkpoint.py."""

    directory: str
    tag: str
    every_ticks: int = 0
    min_interval_s: float = 120.0
    manifest_meta: bool = True
    sink: Optional[Any] = None  # telemetry.Sink for failure counters
    trap: Optional[SignalTrap] = None

    def __post_init__(self):
        self._last_save = time.monotonic()
        self._requested = False
        self.failures = 0
        self.first_error: Optional[BaseException] = None

    # -- paths ----------------------------------------------------------
    @property
    def path(self) -> str:
        return os.path.join(self.directory, f"{self.tag}.ckpt")

    @property
    def meta_path(self) -> str:
        return self.path + ".meta.json"

    # -- triggers -------------------------------------------------------
    def request(self):
        """The on-hang trigger: force a save at the next boundary.
        Thread-safe (a bool store) — watchdog threads call this while
        the main thread is blocked inside a device computation."""
        self._requested = True

    @property
    def signal_pending(self) -> bool:
        return self.trap is not None and self.trap.fired is not None

    def wall_due(self) -> bool:
        return time.monotonic() - self._last_save >= self.min_interval_s

    def due(self, ticks_since_save: int = 0) -> bool:
        """Should the caller save at this chunk boundary?"""
        if self._requested or self.signal_pending:
            return True
        if self.every_ticks and ticks_since_save >= self.every_ticks:
            return self.wall_due()
        return self.wall_due() if self.every_ticks == 0 else False

    def mark_run_start(self):
        """Reset the wall pacing clock (call when the timed region
        starts, so compile/warmup time is not charged to the
        interval)."""
        self._last_save = time.monotonic()

    # -- save / load ----------------------------------------------------
    def save(self, state: Any, meta: dict) -> str:
        """Checkpoint ``state`` with ``meta`` provenance. Raises on
        failure (callers that must survive checkpoint trouble use
        :meth:`try_save`)."""
        os.makedirs(self.directory, exist_ok=True)
        if self.manifest_meta:
            digest = ckpt_mod.save(self.path, state, meta=meta)
        else:
            digest = ckpt_mod.save(self.path, state)
        with open(self.meta_path, "w") as f:
            json.dump(dict(meta, saved_at=time.time()), f)
        self._last_save = time.monotonic()
        self._requested = False
        return digest

    def try_save(self, state: Any, meta: dict) -> bool:
        """Best-effort save: a checkpoint failure must never fail the
        run it exists to protect — but it must not vanish either.
        Failures are narrowed to the I/O-and-serialization classes
        (anything else is a real bug and propagates), counted into the
        telemetry sink, and the first one is logged with its traceback."""
        try:
            self.save(state, meta)
            return True
        except (OSError, ValueError) as e:
            self.failures += 1
            if self.sink is not None:
                self.sink.incr_counter("sim.runtime.ckpt_failures", 1)
            if self.first_error is None:
                self.first_error = e
                log.warning("checkpoint save failed (first of possibly "
                            "many; further failures counted silently): %r",
                            e, exc_info=True)
            return False

    def read_meta(self) -> Optional[dict]:
        """The sidecar provenance, or None when absent/unreadable."""
        try:
            with open(self.meta_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def load(self, template: Any, match: Optional[dict] = None):
        """Restore the checkpoint into ``template``'s structure if one
        exists and its provenance agrees with ``match`` (every key in
        ``match`` must equal the stored meta's value — the trajectory's
        identity: shape, phase, injected-failure parameters, chaos
        schedule digest). Returns ``(state, meta)`` or ``(None, None)``
        when there is nothing (or nothing compatible) to resume.
        Corruption raises (utils/checkpoint's digest verification) so
        the caller decides between restart-clean and fail."""
        if not (os.path.exists(self.path) and os.path.exists(self.meta_path)):
            return None, None
        with open(self.meta_path) as f:
            meta = json.load(f)
        for k, v in (match or {}).items():
            if meta.get(k) != v:
                return None, None
        state = ckpt_mod.restore(self.path, template)
        return state, meta

    def retire(self):
        """Remove the checkpoint pair — only a COMPLETED run retires
        its checkpoint; an interrupted one keeps it for the next run."""
        for p in (self.path, self.meta_path):
            try:
                os.unlink(p)
            except OSError:
                pass
