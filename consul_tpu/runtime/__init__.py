"""Resilient run harness (the robustness tentpole, ROADMAP.md).

The paper's Lifeguard thesis — a node should distrust *itself* under
degradation (PAPER.md) — applied to the simulator: a long jitted scan
must survive preemption (checkpoint/resume), detect its own corruption
(on-device invariant sentinels), and route around a wedged backend
(init-hang watchdog + degraded-mode failover) instead of restarting
from zero on a good day's luck. Three layers, used by every entry
point (bench.py phases, ``Simulation.run_scenario`` via
:func:`harness.run_resilient`, and the ``consul-tpu run`` /
``consul-tpu chaos`` CLI subcommands):

- :mod:`policy` — :class:`CheckpointPolicy`: digest-verified atomic
  checkpointing (utils/checkpoint) under interval / wall-paced /
  on-signal / on-hang triggers, with a :class:`SignalTrap` SIGTERM
  handler for preemption and run provenance (tick offset, chaos
  schedule digest) carried in the checkpoint manifest.
- :mod:`harness` — :func:`run_resilient`: the chunked run loop every
  entry point drives through; resumes bit-identically (same seed, same
  chaos schedule offset), including across shard_map layouts
  (:func:`harness.restore_placed`) and across *device counts*: with
  ``elastic=True`` a checkpoint written on k devices resumes on
  whatever mesh the surviving devices support (parallel/mesh.
  ``elastic_mesh``), re-sharded on entry and counted as
  ``sim.runtime.reshards``.
- :mod:`watchdog` — :class:`InitWatchdog` + :class:`HeartbeatMonitor`
  + :func:`with_failover`: the init-hang watchdog with bounded retries
  and explicit CPU failover (``degraded_from`` / retry / hang-wall
  provenance instead of ad-hoc status strings), plus the in-process
  per-chunk heartbeat deadline that classifies a wedged chunk as
  ``mid-run-hang`` and checkpoints the last completed state from the
  monitor thread.

The sentinel *device* tier lives in models/swim.py (_sentinel_check,
folded into step_counted behind a trace-time flag); its *host* tier —
fail-fast on a nonzero violation mask with a diagnostic checkpoint —
lives where counters flush (models/cluster.py) and is re-exported here
as :class:`SentinelViolation`.
"""

# Lazy re-exports (PEP 562): the bench parent process must stay
# jax-free (bench.py top docstring) yet still reach the stdlib-only
# watchdog tier; eager imports here would pull models/cluster -> jax
# into every ``consul_tpu.runtime.*`` importer.
_EXPORTS = {
    "SentinelViolation": ("consul_tpu.models.cluster", "SentinelViolation"),
    "SENTINEL_FIELDS": ("consul_tpu.models.counters", "SENTINEL_FIELDS"),
    "violation_mask": ("consul_tpu.models.counters", "violation_mask"),
    "Preempted": ("consul_tpu.runtime.harness", "Preempted"),
    "RunReport": ("consul_tpu.runtime.harness", "RunReport"),
    "hang_dump_path": ("consul_tpu.runtime.harness", "hang_dump_path"),
    "restore_placed": ("consul_tpu.runtime.harness", "restore_placed"),
    "run_resilient": ("consul_tpu.runtime.harness", "run_resilient"),
    "CheckpointPolicy": ("consul_tpu.runtime.policy", "CheckpointPolicy"),
    "SignalTrap": ("consul_tpu.runtime.policy", "SignalTrap"),
    "MemoryPlan": ("consul_tpu.runtime.membudget", "MemoryPlan"),
    "plan_memory": ("consul_tpu.runtime.membudget", "plan"),
    "HeartbeatMonitor": ("consul_tpu.runtime.watchdog", "HeartbeatMonitor"),
    "InitWatchdog": ("consul_tpu.runtime.watchdog", "InitWatchdog"),
    "with_failover": ("consul_tpu.runtime.watchdog", "with_failover"),
}


def __getattr__(name):
    try:
        mod_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    value = getattr(importlib.import_module(mod_name), attr)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


__all__ = [
    "CheckpointPolicy",
    "HeartbeatMonitor",
    "InitWatchdog",
    "MemoryPlan",
    "Preempted",
    "RunReport",
    "SENTINEL_FIELDS",
    "SentinelViolation",
    "SignalTrap",
    "hang_dump_path",
    "plan_memory",
    "restore_placed",
    "run_resilient",
    "violation_mask",
    "with_failover",
]
