"""Backend hang watchdogs + degraded-mode failover.

A wedged TPU relay hangs *inside* ``jax.devices()`` indefinitely
(BENCH_r05.json records a real 300 s ``backend-init-hang``); waiting
out the full budget on it pushes the whole run past outer harness
timeouts and loses the output. A chip can also wedge *mid-run* — a
device computation that never completes — which an init-only window
cannot see. Lifted out of bench.py's private child loop so any
supervisor of a backend-owning child process gets the same protection:

- :class:`InitWatchdog` — poll a child for its readiness event; kill
  it early when the init window expires without one, or (with
  ``heartbeat_s``) when a ready child stops making observable
  progress — the two failure modes come back as distinct
  classifications (``backend-init-hang`` vs ``mid-run-hang``).
- :class:`HeartbeatMonitor` — the in-process tier: the run loop beats
  at every chunk boundary; a missed deadline classifies the hang and
  hands the last completed state to an ``on_hang`` callback (the
  resilient harness writes a diagnostic checkpoint from it, since the
  main thread is still blocked inside the wedged computation).
- :func:`with_failover` — bounded retries of a hanging attempt, then
  an explicit degraded-mode failover to the next platform, recording
  provenance (``degraded_from``, retry count, hang wall time) into the
  telemetry sink and the returned report instead of ad-hoc status
  strings. Only init hangs retry: a mid-run hang already produced
  partial phases and a diagnostic state, which is a real answer.
"""

from __future__ import annotations

import dataclasses
import logging
import subprocess
import threading
import time
from typing import Any, Callable, Optional, Sequence

log = logging.getLogger(__name__)

# Status strings (stable: bench JSON consumers key on them).
OK = "ok"
INIT_HANG = "backend-init-hang"
MID_RUN_HANG = "mid-run-hang"
TIMEOUT = "timeout"


@dataclasses.dataclass
class InitWatchdog:
    """Supervise one child process: kill it early if it has not proven
    liveness (``ready()`` true) within ``init_window_s``, or at the
    hard ``deadline`` either way. ``ready`` is polled between waits —
    for bench children it parses the JSONL stream for the ``setup``
    phase, but any cheap host-side probe works."""

    init_window_s: float = 300.0
    poll_s: float = 10.0
    heartbeat_s: float = 0.0  # 0 disables mid-run stall detection
    # Where the backend-init black box lands (obs/blackbox.py): when
    # set, an INIT_HANG kill is followed by a best-effort capture of
    # env/libtpu/device-progress/child-tail/host-spans into
    # ``<blackbox_dir>/blackbox.json``; the path is published on
    # ``self.blackbox_path`` for the caller to link into provenance.
    blackbox_dir: Optional[str] = None

    def watch(self, proc: subprocess.Popen, ready: Callable[[], bool],
              deadline: float,
              progress: Optional[Callable[[], Any]] = None,
              child_tail: Optional[Callable[[], Optional[str]]] = None
              ) -> str:
        """Block until the child exits or is killed; returns OK /
        INIT_HANG / MID_RUN_HANG / TIMEOUT (rc mapping is the caller's
        business — only the caller knows which exit codes are
        expected). ``deadline`` is an absolute ``time.monotonic()``
        stamp.

        ``progress`` (optional, with ``heartbeat_s > 0``) is a cheap
        host-side probe of the child's forward motion — any value that
        changes while the child works (bench children: the output
        file's size). Once the child has proven readiness, a progress
        value frozen for longer than ``heartbeat_s`` classifies it as
        a MID_RUN_HANG: the backend came up and then wedged, which is
        a different diagnosis (and failover decision) than never
        coming up at all.

        ``child_tail`` (optional) returns the tail of the child's last
        output for the black box — only consulted after an INIT_HANG
        kill, when the child can no longer produce more."""
        self.blackbox_path: Optional[str] = None
        t0 = time.monotonic()
        seen_ready = False
        last_progress = progress() if progress is not None else None
        last_beat = t0
        try:
            while True:
                step = min(self.poll_s, max(0.1, deadline - time.monotonic()))
                try:
                    proc.wait(timeout=step)
                    return OK
                except subprocess.TimeoutExpired:
                    pass
                now = time.monotonic()
                if now >= deadline:
                    raise subprocess.TimeoutExpired(
                        proc.args, deadline - t0)
                if not seen_ready and ready():
                    seen_ready = True
                    last_beat = now  # the stall clock starts at readiness
                if now - t0 > self.init_window_s and not seen_ready:
                    self._kill(proc)
                    self._capture_blackbox(child_tail)
                    return INIT_HANG
                if progress is not None and self.heartbeat_s > 0 \
                        and seen_ready:
                    cur = progress()
                    if cur != last_progress:
                        last_progress, last_beat = cur, now
                    elif now - last_beat > self.heartbeat_s:
                        self._kill(proc)
                        return MID_RUN_HANG
        except subprocess.TimeoutExpired:
            self._kill(proc)
            return TIMEOUT

    def _capture_blackbox(self, child_tail):
        """Best-effort postmortem (obs/blackbox.py) after an init-hang
        kill. A failed capture must not mask the INIT_HANG diagnosis —
        the classification is the primary product."""
        if not self.blackbox_dir:
            return
        import os

        from consul_tpu.obs import blackbox
        try:
            tail = child_tail() if child_tail is not None else None
            self.blackbox_path = os.path.join(
                self.blackbox_dir, "blackbox.json")
            blackbox.capture(self.blackbox_path, status=INIT_HANG,
                             child_tail=tail)
        except Exception:  # noqa: BLE001
            log.warning("blackbox capture failed", exc_info=True)
            self.blackbox_path = None

    @staticmethod
    def _kill(proc: subprocess.Popen):
        proc.kill()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass  # keep the original diagnosis; the child is a zombie


class HeartbeatMonitor:
    """In-process per-chunk heartbeat deadline: the run loop calls
    :meth:`beat` at every chunk boundary; if no beat lands within
    ``heartbeat_s`` the monitor thread classifies the hang —
    MID_RUN_HANG when at least one chunk completed, INIT_HANG when the
    very first chunk (compile + first execution) never finished — and
    fires ``on_hang(status, ticks_done, last_state)`` exactly once.

    The main thread is blocked inside the wedged device computation
    when this fires, so ``on_hang`` runs on the monitor thread and
    must only touch already-completed buffers: :meth:`beat` stashes a
    reference to the last chunk's finished state for exactly that
    purpose (the resilient harness checkpoints it as the diagnostic
    state). ``sink`` counts the classification
    (``sim.runtime.mid_run_hangs`` / ``sim.runtime.backend_hangs``)
    so the hang is visible in metrics even when the process never
    returns."""

    def __init__(self, heartbeat_s: float, *,
                 on_hang: Optional[Callable[[str, int, Any], None]] = None,
                 sink=None, poll_s: Optional[float] = None):
        self.heartbeat_s = float(heartbeat_s)
        self.on_hang = on_hang
        self.sink = sink
        self.poll_s = poll_s if poll_s is not None \
            else max(0.05, self.heartbeat_s / 4.0)
        self.status: Optional[str] = None  # None until a hang fires
        self.beats = 0
        self.ticks_done = 0
        self._last_state: Any = None
        self._last_beat = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HeartbeatMonitor":
        self._last_beat = time.monotonic()
        self._thread = threading.Thread(
            target=self._watch, name="heartbeat-monitor", daemon=True)
        self._thread.start()
        return self

    def beat(self, ticks_done: int, state: Any = None):
        """Mark liveness at a chunk boundary; ``state`` (optional) is
        the chunk's completed state pytree — the newest buffers that
        are guaranteed ready if a later computation wedges."""
        self.beats += 1
        self.ticks_done = int(ticks_done)
        if state is not None:
            self._last_state = state
        self._last_beat = time.monotonic()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(1.0, 2 * self.poll_s))

    def __enter__(self) -> "HeartbeatMonitor":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _watch(self):
        while not self._stop.wait(self.poll_s):
            if time.monotonic() - self._last_beat <= self.heartbeat_s:
                continue
            self.status = MID_RUN_HANG if self.beats else INIT_HANG
            if self.sink is not None:
                self.sink.incr_counter(
                    "sim.runtime.mid_run_hangs" if self.beats
                    else "sim.runtime.backend_hangs", 1)
            if self.on_hang is not None:
                try:
                    self.on_hang(self.status, self.ticks_done,
                                 self._last_state)
                except Exception:
                    # Diagnosis must not kill the monitor — the
                    # classification already landed in .status and
                    # the sink; the failed dump is worth a traceback.
                    log.warning("heartbeat on_hang callback failed",
                                exc_info=True)
            return  # one-shot: a hang is terminal for this run


def with_failover(attempt: Callable[[str], dict],
                  platforms: Sequence[str], *,
                  max_retries: int = 1,
                  sink=None):
    """Run ``attempt(platform)`` (returning a dict with a ``status``
    key) with bounded retries on init-hang, failing over to the next
    platform when a platform's retries are exhausted. Returns
    ``(result, provenance)`` where provenance is::

        {"platform":     the platform that produced the result,
         "degraded_from": first platform given up on (None if primary),
         "retries":       hang-triggered re-attempts,
         "hang_wall_s":   wall seconds burned inside hangs,
         "attempts":      [{"platform", "status", "wall_s",
                            "blackbox"}, ...]}

    ``blackbox`` is the attempt's backend-init black box artifact path
    (obs/blackbox.py — ``attempt`` puts it under a ``"blackbox"`` key
    when its watchdog captured one), so the provenance record points
    straight at the postmortem evidence for every hung attempt.

    Only INIT_HANG retries/fails over — a child that ran and crashed
    (rc=N) or timed out while *working* is a real answer, not a wedged
    backend, and is returned as-is. ``sink`` (telemetry.Sink) counts
    hangs and failovers so the degraded mode is visible in metrics,
    not only in the artifact."""
    prov = {"platform": None, "degraded_from": None, "retries": 0,
            "hang_wall_s": 0.0, "attempts": []}
    result = None
    for i, plat in enumerate(platforms):
        for _ in range(max_retries + 1):
            result = attempt(plat)
            prov["attempts"].append({
                "platform": plat,
                "status": result.get("status"),
                "wall_s": result.get("wall_s"),
                "blackbox": result.get("blackbox"),
            })
            if result.get("status") != INIT_HANG:
                prov["platform"] = plat
                return result, prov
            prov["hang_wall_s"] += float(result.get("wall_s") or 0.0)
            if sink is not None:
                sink.incr_counter("sim.runtime.backend_hangs", 1)
            prov["retries"] += 1
        # Retries exhausted on this platform: degrade to the next.
        if i + 1 < len(platforms):
            if prov["degraded_from"] is None:
                prov["degraded_from"] = plat
            if sink is not None:
                sink.incr_counter("sim.runtime.degraded_failovers", 1)
    prov["platform"] = platforms[-1] if platforms else None
    return result, prov
