"""Backend init-hang watchdog + degraded-mode failover.

A wedged TPU relay hangs *inside* ``jax.devices()`` indefinitely
(BENCH_r05.json records a real 300 s ``backend-init-hang``); waiting
out the full budget on it pushes the whole run past outer harness
timeouts and loses the output. Lifted out of bench.py's private child
loop so any supervisor of a backend-owning child process gets the same
protection:

- :class:`InitWatchdog` — poll a child for its readiness event; kill
  it early when the init window expires without one.
- :func:`with_failover` — bounded retries of a hanging attempt, then
  an explicit degraded-mode failover to the next platform, recording
  provenance (``degraded_from``, retry count, hang wall time) into the
  telemetry sink and the returned report instead of ad-hoc status
  strings.
"""

from __future__ import annotations

import dataclasses
import subprocess
import time
from typing import Callable, Sequence

# Status strings (stable: bench JSON consumers key on them).
OK = "ok"
INIT_HANG = "backend-init-hang"
TIMEOUT = "timeout"


@dataclasses.dataclass
class InitWatchdog:
    """Supervise one child process: kill it early if it has not proven
    liveness (``ready()`` true) within ``init_window_s``, or at the
    hard ``deadline`` either way. ``ready`` is polled between waits —
    for bench children it parses the JSONL stream for the ``setup``
    phase, but any cheap host-side probe works."""

    init_window_s: float = 300.0
    poll_s: float = 10.0

    def watch(self, proc: subprocess.Popen, ready: Callable[[], bool],
              deadline: float) -> str:
        """Block until the child exits or is killed; returns OK /
        INIT_HANG / TIMEOUT (rc mapping is the caller's business —
        only the caller knows which exit codes are expected).
        ``deadline`` is an absolute ``time.monotonic()`` stamp."""
        t0 = time.monotonic()
        seen_ready = False
        try:
            while True:
                step = min(self.poll_s, max(0.1, deadline - time.monotonic()))
                try:
                    proc.wait(timeout=step)
                    return OK
                except subprocess.TimeoutExpired:
                    pass
                now = time.monotonic()
                if now >= deadline:
                    raise subprocess.TimeoutExpired(
                        proc.args, deadline - t0)
                seen_ready = seen_ready or ready()
                if now - t0 > self.init_window_s and not seen_ready:
                    self._kill(proc)
                    return INIT_HANG
        except subprocess.TimeoutExpired:
            self._kill(proc)
            return TIMEOUT

    @staticmethod
    def _kill(proc: subprocess.Popen):
        proc.kill()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            pass  # keep the original diagnosis; the child is a zombie


def with_failover(attempt: Callable[[str], dict],
                  platforms: Sequence[str], *,
                  max_retries: int = 1,
                  sink=None):
    """Run ``attempt(platform)`` (returning a dict with a ``status``
    key) with bounded retries on init-hang, failing over to the next
    platform when a platform's retries are exhausted. Returns
    ``(result, provenance)`` where provenance is::

        {"platform":     the platform that produced the result,
         "degraded_from": first platform given up on (None if primary),
         "retries":       hang-triggered re-attempts,
         "hang_wall_s":   wall seconds burned inside hangs,
         "attempts":      [{"platform", "status", "wall_s"}, ...]}

    Only INIT_HANG retries/fails over — a child that ran and crashed
    (rc=N) or timed out while *working* is a real answer, not a wedged
    backend, and is returned as-is. ``sink`` (telemetry.Sink) counts
    hangs and failovers so the degraded mode is visible in metrics,
    not only in the artifact."""
    prov = {"platform": None, "degraded_from": None, "retries": 0,
            "hang_wall_s": 0.0, "attempts": []}
    result = None
    for i, plat in enumerate(platforms):
        for _ in range(max_retries + 1):
            result = attempt(plat)
            prov["attempts"].append({
                "platform": plat,
                "status": result.get("status"),
                "wall_s": result.get("wall_s"),
            })
            if result.get("status") != INIT_HANG:
                prov["platform"] = plat
                return result, prov
            prov["hang_wall_s"] += float(result.get("wall_s") or 0.0)
            if sink is not None:
                sink.incr_counter("sim.runtime.backend_hangs", 1)
            prov["retries"] += 1
        # Retries exhausted on this platform: degrade to the next.
        if i + 1 < len(platforms):
            if prov["degraded_from"] is None:
                prov["degraded_from"] = plat
            if sink is not None:
                sink.incr_counter("sim.runtime.degraded_failovers", 1)
    prov["platform"] = platforms[-1] if platforms else None
    return result, prov
