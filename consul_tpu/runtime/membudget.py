"""MemoryBudget: pick chunk, state layout, and cohort plan per device.

The planner answers one question before any array is allocated: *how
does a population of n nodes fit this device?* Given device memory
stats (or an explicit budget) and the run shape (n, kind, chaos, mesh),
it returns a :class:`MemoryPlan` naming

  - the **state layout** (models/layout.py): dense f32/i32 when the
    working set fits comfortably, packed (2.5x smaller at rest) when
    it buys headroom;
  - the **chunk** length for the scan runners;
  - the **cohort plan**: ``cohort_n == n`` resident when the population
    fits, otherwise the largest power-of-two divisor of n whose
    double-buffered working set fits the budget — the shape
    ``models.cluster.StreamedSimulation`` streams host<->device;
  - the **prewarm signature** (utils/prewarm.py): the (ns, kinds,
    chunks, layout) tuple to AOT-compile, so the same binary serves a
    64k CPU run and a 64M pod run by planning instead of editing.

Sizing is arithmetic over ``jax.eval_shape`` — zero allocation. The
working-set model is deliberately conservative: at rest the carry holds
one state copy per buffered cohort, but inside a packed scan body the
step materializes a full dense working copy plus step temporaries, so
live bytes per node are estimated as

    live = buffers * at_rest(layout) + WORKING_MULT * dense_actual

which over- rather than under-provisions (XLA fuses most temporaries
away; the dense copy does not survive the tick).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Optional

import jax

from consul_tpu.config import SimConfig
from consul_tpu.models import layout as layout_mod

KINDS = ("swim", "serf")

# Step-temporary multiplier over the dense per-node working set: the
# scan body holds the dense state plus a small number of same-shaped
# intermediates (gossip payload rolls, merge keys) before XLA fusion.
WORKING_MULT = 3.0

# Fraction of the reported device budget the plan may fill — headroom
# for the executable, RNG keys, counters, and allocator slack.
FILL_FRACTION = 0.8

_SIZE_RE = re.compile(r"^\s*([0-9]+(?:\.[0-9]+)?)\s*([KMGT]?i?B?)\s*$",
                      re.IGNORECASE)
_UNIT = {"": 1, "B": 1,
         "KB": 10**3, "MB": 10**6, "GB": 10**9, "TB": 10**12,
         "KIB": 2**10, "MIB": 2**20, "GIB": 2**30, "TIB": 2**40}


def parse_budget(budget) -> Optional[int]:
    """"auto" -> None (probe the device); int/float bytes pass through;
    "2GB"/"512MiB"-style strings parse with SI/binary units."""
    if budget is None or budget == "auto":
        return None
    if isinstance(budget, (int, float)):
        return int(budget)
    m = _SIZE_RE.match(str(budget))
    if not m:
        raise ValueError(f"unparseable memory budget {budget!r}")
    num, unit = float(m.group(1)), m.group(2).upper()
    if unit in ("K", "M", "G", "T"):
        unit += "B"
    return int(num * _UNIT[unit])


def device_budget_bytes(device=None) -> int:
    """Usable bytes on one device: ``memory_stats`` when the backend
    reports them (TPU/GPU), else host RAM (the CPU tier's arrays live
    in host memory anyway)."""
    dev = device or jax.devices()[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    if stats:
        limit = stats.get("bytes_limit") or stats.get(
            "bytes_reservable_limit")
        if limit:
            return int(limit)
    try:
        return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError):  # pragma: no cover - exotic platform
        return 8 * 2**30


def _state_abstract(cfg: SimConfig, kind: str, layout: str):
    """Shape/dtype skeleton of one population's at-rest state — pure
    eval_shape, no allocation (safe to call for a 64M-node config)."""
    from consul_tpu.models import serf as serf_mod
    from consul_tpu.models import state as sim_state

    init = serf_mod.init if kind == "serf" else sim_state.init

    def build(key):
        st = init(cfg, key)
        if layout == layout_mod.PACKED:
            st = layout_mod.pack_state(st)
        return st

    return jax.eval_shape(build, jax.ShapeDtypeStruct((2,), "uint32"))


def state_bytes_per_node(cfg: SimConfig, kind: str = "swim",
                         layout: str = layout_mod.DENSE) -> float:
    """At-rest bytes per node for (cfg, kind, layout)."""
    return layout_mod.bytes_per_node(_state_abstract(cfg, kind, layout),
                                     cfg.n)


def dense_f32i32_bytes_per_node(cfg: SimConfig, kind: str = "swim") -> float:
    """The ISSUE's comparison baseline: every dense element at 4 bytes
    (bools and narrow serf lanes counted as if f32/i32)."""
    tree = _state_abstract(cfg, kind, layout_mod.DENSE)
    elems = sum(int(l.size) for l in jax.tree.leaves(tree))
    return elems * 4.0 / cfg.n


def live_bytes_per_node(cfg: SimConfig, kind: str, layout: str,
                        buffers: int = 1) -> float:
    """Working-set bytes per node while a population is stepping (see
    module docstring for the model)."""
    at_rest = state_bytes_per_node(cfg, kind, layout)
    dense = state_bytes_per_node(cfg, kind, layout_mod.DENSE)
    return buffers * at_rest + WORKING_MULT * dense


@dataclasses.dataclass(frozen=True)
class MemoryPlan:
    """What the planner decided for one run. ``streamed`` means the
    population exceeds the per-device budget and must go through
    ``StreamedSimulation`` at ``cohort_n`` nodes per cohort."""

    n: int
    kind: str
    layout: str
    chunk: int
    cohort_n: int
    streamed: bool
    devices: int
    budget_bytes: int
    state_bytes_per_node: float
    dense_bytes_per_node: float       # dense-actual at-rest bytes/node
    dense_f32i32_bytes_per_node: float  # the all-4-byte baseline
    resident_bytes: int               # projected peak per device
    max_n_resident: int               # biggest resident pop at layout

    @property
    def packed_cut(self) -> float:
        """Compaction factor vs the dense f32/i32 baseline."""
        return self.dense_f32i32_bytes_per_node / self.state_bytes_per_node

    def prewarm_args(self) -> dict:
        """The signature utils/prewarm.prewarm compiles ahead of time:
        one program shape covers every cohort (and the resident case,
        where the single "cohort" is the whole population)."""
        return {
            "ns": [self.cohort_n],
            "kinds": [self.kind],
            "chunks": [self.chunk],
            "layout": self.layout,
        }

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["packed_cut"] = round(self.packed_cut, 3)
        return d


def _pow2_cohort(n: int, max_cohort: int) -> int:
    """Largest n/2^k (>= 1k floor) that fits ``max_cohort`` nodes."""
    cohort = n
    while cohort > max_cohort and cohort % 2 == 0 and cohort > 1024:
        cohort //= 2
    return cohort


def plan(cfg: SimConfig, kind: str = "swim", layout: str = "auto",
         budget="auto", chaos: bool = False, mesh=None,
         chunk: Optional[int] = None, device=None) -> MemoryPlan:
    """Pick (layout, chunk, cohort plan) for running ``cfg`` on this
    device/mesh under ``budget`` bytes per device.

    ``layout="auto"`` keeps the dense golden reference whenever the
    whole population fits it resident, and switches to packed only when
    compaction is what makes the run fit (or shrinks the cohort count
    of a streamed run). ``chaos`` reserves schedule headroom; ``mesh``
    divides the population over its devices.
    """
    if kind not in KINDS:
        raise ValueError(f"kind must be one of {KINDS}; got {kind!r}")
    devices = 1
    if mesh is not None:
        devices = int(getattr(mesh, "size", None) or len(mesh.devices))
    total = parse_budget(budget)
    if total is None:
        total = device_budget_bytes(device)
    usable = int(total * FILL_FRACTION)
    if chaos:
        # Schedule masks are [N, slots] u8-ish — budget a slim slice.
        usable = int(usable * 0.95)

    n_dev = cfg.n // devices  # nodes this device must hold

    def max_resident(lay: str) -> int:
        return int(usable / live_bytes_per_node(cfg, kind, lay, buffers=1))

    if layout == "auto":
        layout = (layout_mod.DENSE if n_dev <= max_resident(layout_mod.DENSE)
                  else layout_mod.PACKED)
    layout_mod.validate(cfg, layout)

    fits = n_dev <= max_resident(layout)
    if fits:
        cohort_n, streamed, buffers = cfg.n, False, 1
    else:
        if devices > 1:
            raise ValueError(
                "beyond-budget populations stream on a single device; "
                "shrink n per device or raise the budget")
        # Streaming double-buffers: two cohorts resident at the swap.
        per_cohort = int(usable
                         / live_bytes_per_node(cfg, kind, layout, buffers=2))
        cohort_n = _pow2_cohort(cfg.n, per_cohort)
        streamed, buffers = True, 2
        if not cfg.view_degree:
            raise ValueError(
                f"streaming needs the sparse view (view_degree > 0), but "
                f"this config is dense (view_degree=0, topology family "
                f"{cfg.topo_family!r}): a dense view is O(n^2) state and "
                f"cannot stream in cohorts — pass --view-degree (an even "
                f"K, e.g. 16) and optionally --family to pick the view "
                f"graph (consul_tpu/topo/families.py)")

    if chunk is None:
        # Long scans amortize dispatch; huge populations take smaller
        # chunks so a chunk's wall time stays interactive.
        chunk = 64 if (cohort_n if streamed else n_dev) <= 2**21 else 16

    per_node = state_bytes_per_node(cfg, kind, layout)
    resident = int(live_bytes_per_node(cfg, kind, layout, buffers)
                   * (cohort_n if streamed else n_dev))
    return MemoryPlan(
        n=cfg.n,
        kind=kind,
        layout=layout,
        chunk=chunk,
        cohort_n=cohort_n,
        streamed=streamed,
        devices=devices,
        budget_bytes=usable,
        state_bytes_per_node=per_node,
        dense_bytes_per_node=state_bytes_per_node(cfg, kind,
                                                  layout_mod.DENSE),
        dense_f32i32_bytes_per_node=dense_f32i32_bytes_per_node(cfg, kind),
        resident_bytes=resident,
        max_n_resident=max_resident(layout),
    )
