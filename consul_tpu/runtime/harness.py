"""The resilient run loop: chunked execution with checkpoint/resume,
preemption handling, and optional on-device invariant sentinels.

``run_resilient`` is what the entry points drive instead of private
while-loops: ``consul-tpu run`` / ``consul-tpu chaos`` (cli.py) and
scenario replays that need to survive a kill. The guarantee (pinned by
tests/test_runtime.py at 4096 nodes, single-device and sharded, with
and without a chaos schedule): kill -9 the process mid-run, rerun the
same command, and the final state is bit-identical to an uninterrupted
run. Three properties make that hold:

- per-tick randomness is ``fold_in(base_key, t)`` (models/cluster.py)
  and ``t`` rides in the state, so a restored state replays the exact
  key stream;
- the chaos schedule's tick offset (``chaos_t0``) and digest ride in
  the checkpoint provenance, so the resumed run re-rebases the SAME
  schedule to the SAME absolute ticks — the remaining faults replay
  bit-identically — and a checkpoint from a different schedule is
  refused;
- saves are atomic and digest-verified (utils/checkpoint), so a crash
  mid-save can never poison the resume point.

A fourth property makes the trajectory **elastic** (mesh-shape
agnostic): checkpoints hold the globally-gathered leaves plus a
PartitionSpec manifest (utils/checkpoint), so resuming does not need
the mesh that wrote them — ``mesh=``/``elastic=True`` re-shard the
restored state onto whatever the surviving devices support
(parallel/mesh.elastic_mesh), counted as ``sim.runtime.reshards``.
Determinism survives the re-shard because the discrete protocol state
is bit-identical across placements (parallel/shard_step docstring) and
per-tick keys fold the restored on-device tick counter.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional, Sequence

import jax

from consul_tpu.chaos import schedule as chaos_mod
from consul_tpu.models import counters as counters_mod
from consul_tpu.models.cluster import SLO_KEYS
from consul_tpu.runtime.policy import CheckpointPolicy, SignalTrap
from consul_tpu.runtime.watchdog import HeartbeatMonitor
from consul_tpu.utils import checkpoint as ckpt_mod


class Preempted(RuntimeError):
    """The run stopped early on a trapped termination signal — after
    saving a resume point. Carries the report so the caller can emit
    provenance before exiting."""

    def __init__(self, report: "RunReport"):
        self.report = report
        super().__init__(
            f"preempted at tick {report.ticks_done}/{report.ticks_asked} "
            f"(checkpoint: {report.checkpoint_path})"
        )


@dataclasses.dataclass
class RunReport:
    """What one resilient run did — the provenance the entry points
    serialize instead of ad-hoc status strings."""

    ticks_asked: int
    ticks_done: int
    resumed_from_tick: int
    preempted: bool
    checkpoint_path: Optional[str]
    ckpt_failures: int
    counters: dict
    slo: Optional[dict]
    reshards: int = 0
    hang_status: Optional[str] = None
    hang_checkpoint: Optional[str] = None
    # Widen-on-load provenance: set when the resume point was a
    # pre-packing dense checkpoint restored into a packed layout
    # ({"widened_from": <digest>, "widened_to": <digest>}), else None.
    widened: Optional[dict] = None

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _placement_width(state) -> int:
    """How many devices the state's arrays actually live on — the
    mesh-shape provenance a resume compares against to count reshards.
    Host-only pytrees (plain numpy in tests) count as width 1."""
    for leaf in jax.tree.leaves(state):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None:
            try:
                return len(sharding.device_set)
            except AttributeError:
                return 1
    return 1


# The layout digest lives with the serializer now (it guards restores);
# re-exported here because the harness is where callers historically
# found it.
state_layout_digest = ckpt_mod.state_layout_digest


def _scenario_meta(sim, tag: str, ticks: int, t0: int, done: int,
                   sched_digest: str) -> dict:
    return {
        "tag": tag,
        "n": sim.cfg.n,
        "seed": sim.seed,
        "kind": type(sim).__name__,
        "ticks": ticks,
        "t0": t0,
        "ticks_done": done,
        "chaos_t0": t0,
        "schedule_digest": sched_digest,
        # The state schema this checkpoint serialized — resume
        # compatibility, checked EXPLICITLY (clear refusal) rather than
        # via the match dict (silent fresh start) in run_resilient.
        "state_layout": state_layout_digest(sim.state, sim.cfg.n),
        # Provenance only — NOT part of the resume match: the
        # trajectory's identity is device-count-agnostic, which is
        # exactly what lets a smaller mesh pick it up.
        "mesh_devices": _placement_width(sim.state),
        # Serving write-plane provenance (also not matched): the device
        # apply index the last snapshot flip was consistent as of, so a
        # checkpoint records which writes its reads had seen. None when
        # no write-attached plane rides the sim.
        "serving_apply_index": _serving_apply_index(sim),
        # Raft-tier provenance (also not matched): per-group commit
        # frontier at save time when the batched raft tier is armed —
        # which quorum-committed prefix this checkpoint's write plane
        # reflects. None when raft is off.
        "raft": _raft_meta(sim),
    }


def _raft_meta(sim):
    plane = getattr(sim, "raft", None)
    if plane is None:
        return None
    s = plane.summary()
    return {
        "groups": plane.rcfg.groups,
        "peers": plane.rcfg.peers,
        "terms": s["terms"],
        "commit": s["commit"],
    }


def _serving_apply_index(sim):
    plane = getattr(sim, "serving", None)
    if plane is None or not getattr(plane, "has_writes", lambda: False)():
        return None
    return int(plane.apply_index)


def hang_dump_path(dump_dir: str, t: int) -> str:
    """Where the heartbeat monitor drops the mid-run-hang diagnostic
    checkpoint (kept here so tooling and tests agree on the name)."""
    return os.path.join(dump_dir, f"hang_diag_t{int(t)}.ckpt")


def run_resilient(sim, ticks: int, *, chunk: int = 64,
                  with_metrics: bool = False,
                  events: Optional[Sequence] = None,
                  policy: Optional[CheckpointPolicy] = None,
                  sentinel: bool = False,
                  sentinel_dump_dir: Optional[str] = None,
                  heartbeat_s: Optional[float] = None,
                  hang_dump_dir: Optional[str] = None,
                  mesh=None, elastic: bool = False) -> RunReport:
    """Advance ``sim`` by ``ticks`` ticks (with ``events`` as a chaos
    schedule rebased onto the start tick, like ``run_scenario``) under
    the resilient harness: resume from ``policy``'s checkpoint when a
    compatible one exists, save at every due chunk boundary, save and
    raise :class:`Preempted` on SIGTERM, and retire the checkpoint on
    completion. With ``sentinel``, the on-device validator runs and a
    violation fail-fasts (models/cluster.py SentinelViolation) with a
    diagnostic checkpoint in ``sentinel_dump_dir``.

    Elasticity: ``mesh`` places the state (fresh or restored) over an
    explicit device mesh; ``elastic=True`` instead rebuilds the
    largest mesh the currently-surviving devices support
    (parallel/mesh.elastic_mesh). A resume whose checkpoint was
    written on a different device count re-shards on entry and counts
    ``sim.runtime.reshards`` — the trajectory identity (the ``match``
    dict) is deliberately device-count-free.

    ``heartbeat_s`` arms a per-chunk heartbeat deadline
    (watchdog.HeartbeatMonitor): a chunk that fails to complete within
    the deadline is classified (``mid-run-hang`` after at least one
    completed chunk, ``backend-init-hang`` before) and a diagnostic
    checkpoint of the last COMPLETED state is written from the monitor
    thread into ``hang_dump_dir`` (default: ``sentinel_dump_dir``,
    then the policy directory) — the main thread is wedged inside the
    device computation at that point, so each beat mirrors the chunk's
    finished state to the host (the cost of diagnosability; heartbeat
    is opt-in).

    Returns a :class:`RunReport`; the counter deltas cover only the
    ticks THIS invocation ran (a resumed run reports its own slice)."""
    if sentinel:
        sim.set_sentinel(True, sentinel_dump_dir)
    sched = (chaos_mod.compile_schedule(sim.cfg.n, events)
             if events else None)
    sched_digest = chaos_mod.digest_of(sched)
    t0 = (sim._tick() if hasattr(sim, "_tick")
          else int(jax.device_get(sim.swim_state.t)))
    done = 0
    reshards = 0
    sink = (policy.sink if policy is not None else None) \
        or getattr(sim, "sink", None)

    target_mesh = mesh
    if target_mesh is None and elastic:
        from consul_tpu.parallel import mesh as pmesh

        target_mesh = pmesh.elastic_mesh(sim.cfg.n)

    if policy is not None and policy.trap is None:
        policy.trap = SignalTrap()

    # Resume: the trajectory's identity is (shape, seed, driver kind,
    # total ticks, schedule digest). ``t0`` comes FROM the meta — the
    # schedule must rebase to the original start tick, not to wherever
    # the restored state happens to be.
    saved_width = None
    widened_prov = None
    if policy is not None:
        ident = {
            "tag": policy.tag,
            "n": sim.cfg.n,
            "seed": sim.seed,
            "kind": type(sim).__name__,
            "ticks": ticks,
            "schedule_digest": sched_digest,
        }
        # Layout gate BEFORE the restore: a checkpoint that names this
        # trajectory but was written by a program with a different
        # state schema (pre-fusion SerfState: no ev_pending, i32
        # ev_origin/ev_tx) must be refused with a diagnosis — letting
        # ckpt_mod.restore hit the field/dtype mismatch produces a
        # shape crash deep in deserialization instead.
        layout_now = state_layout_digest(sim.state, sim.cfg.n)
        meta0 = policy.read_meta()
        state = meta = None
        if (meta0 is not None and os.path.exists(policy.path)
                and all(meta0.get(k) == v for k, v in ident.items())):
            saved_layout = meta0.get("state_layout")
            if saved_layout != layout_now and (
                    saved_layout is not None
                    or "Serf" in str(meta0.get("kind", ""))):
                # Widen-on-load: if the saved schema is exactly the
                # DENSE twin of this run's packed layout, the
                # checkpoint predates packing but names the same
                # trajectory — restore it dense, pack it, and resume,
                # with both digests carried as provenance. Anything
                # else is a genuine schema mismatch and keeps the
                # clear refusal.
                from consul_tpu.models import layout as layout_mod

                dense_tpl = (layout_mod.unpack_state(sim.state)
                             if layout_mod.is_packed(sim.state) else None)
                if (dense_tpl is not None and saved_layout ==
                        state_layout_digest(dense_tpl, sim.cfg.n)):
                    state, widened_prov = ckpt_mod.restore_widened(
                        policy.path, dense_tpl, layout_mod.pack_state,
                        sim.cfg.n)
                    meta = meta0
                    if sink is not None:
                        sink.incr_counter(
                            "sim.runtime.widened_restores", 1)
                else:
                    raise RuntimeError(
                        f"checkpoint {policy.path} matches this "
                        f"trajectory but was written by an incompatible "
                        f"state layout ({saved_layout or 'pre-layout-digest (pre-fusion)'}"
                        f" vs {layout_now}): it cannot be resumed into "
                        "this program. Retire it (delete the "
                        ".ckpt/.meta.json pair) or rerun with the build "
                        "that wrote it."
                    )
        if state is None:
            state, meta = policy.load(sim.state, match=ident)
        if state is not None:
            sim.state = state
            t0 = int(meta["t0"])
            done = int(meta["ticks_done"])
            saved_width = int(meta.get("mesh_devices") or 1)
    resumed_from = done

    if target_mesh is not None:
        if getattr(sim, "mesh", None) is not None \
                and hasattr(sim, "set_mesh"):
            # The sim already executes under shard_map: continue on the
            # surviving grid — set_mesh re-places world/state/schedule
            # and rebinds the runners, and the mesh fingerprint in the
            # runner memo key guarantees a reshard never reuses the old
            # mesh's executable.
            sim.set_mesh(target_mesh)
        else:
            # Single-device program with a placement mesh: re-place the
            # DATA only, never the execution. This is the layout-only
            # semantics the cross-shape bit-identity pins cover — the
            # sharded program's collectives reassociate float reductions,
            # so flipping a meshless sim into shard_map execution here
            # would silently change the trajectory it is resuming.
            from consul_tpu.parallel import shard_step

            sim.state = shard_step.place(target_mesh, sim.state, sim.cfg.n)
    if saved_width is not None:
        new_width = _placement_width(sim.state)
        if new_width != saved_width:
            # The reshard-on-entry event: same trajectory, different
            # surviving-device count (the checkpoint payload is the
            # gathered global view, so this is pure re-placement).
            reshards += 1
            if sink is not None:
                sink.incr_counter("sim.runtime.reshards", 1)

    # A restore (or re-placement) replaced sim.state wholesale: any
    # attached serving plane is still publishing the pre-resume tick.
    # Republish before the first chunk so reads are consistent as of
    # the restored state, not the orphaned one.
    if getattr(sim, "publish_serving", None) is not None:
        sim.publish_serving()

    prev_sched = sim.chaos
    if sched is not None:
        sim.set_chaos(chaos_mod.shift_schedule(sched, t0))
    before = dict(sim.counters)

    monitor = None
    hang_ckpt: list = [None]  # monitor thread writes, report reads
    if heartbeat_s:
        dump_dir = hang_dump_dir or sentinel_dump_dir or (
            policy.directory if policy is not None else None)

        def _on_hang(status, hung_done, last_state):
            if policy is not None:
                policy.request()  # save if the main thread unblocks
            if dump_dir is None or last_state is None:
                return
            os.makedirs(dump_dir, exist_ok=True)
            path = hang_dump_path(dump_dir, t0 + hung_done)
            ckpt_mod.save(path, last_state, meta=dict(
                _scenario_meta(sim, policy.tag if policy is not None
                               else "hang", ticks, t0, hung_done,
                               sched_digest),
                classification=status))
            hang_ckpt[0] = path

        monitor = HeartbeatMonitor(
            heartbeat_s, on_hang=_on_hang, sink=sink).start()

    def _report(preempted: bool) -> RunReport:
        after = sim.counters
        deltas = {f: after[f] - before[f] for f in counters_mod.FIELDS}
        return RunReport(
            ticks_asked=ticks,
            ticks_done=done,
            resumed_from_tick=resumed_from,
            preempted=preempted,
            checkpoint_path=policy.path if policy is not None else None,
            ckpt_failures=policy.failures if policy is not None else 0,
            counters=deltas,
            slo={SLO_KEYS[f]: deltas[f] for f in SLO_KEYS}
            if sched is not None else None,
            reshards=reshards,
            hang_status=monitor.status if monitor is not None else None,
            hang_checkpoint=hang_ckpt[0],
            widened=widened_prov,
        )

    trap = policy.trap if policy is not None else SignalTrap()
    try:
        with trap:
            if policy is not None:
                policy.mark_run_start()
            since_save = 0
            while done < ticks:
                c = min(chunk, ticks - done)
                sim.run(c, chunk=c, with_metrics=with_metrics)
                done += c
                since_save += c
                if monitor is not None:
                    # Host-mirror the completed chunk's state: the NEXT
                    # chunk donates these buffers, and a wedged device
                    # cannot serve a fetch after the fact.
                    monitor.beat(done, jax.device_get(sim.state))
                if policy is None:
                    continue
                if trap.fired is not None:
                    policy.try_save(sim.state, _scenario_meta(
                        sim, policy.tag, ticks, t0, done, sched_digest))
                    raise Preempted(_report(preempted=True))
                if done < ticks and policy.due(since_save):
                    if policy.try_save(sim.state, _scenario_meta(
                            sim, policy.tag, ticks, t0, done, sched_digest)):
                        since_save = 0
    finally:
        if monitor is not None:
            monitor.stop()
        sim.set_chaos(prev_sched)
    if policy is not None:
        policy.retire()
    return _report(preempted=False)


def restore_placed(path: str, template: Any, mesh=None, n: Optional[int] = None):
    """Restore a checkpoint and re-shard it over ``mesh`` — the round
    trip that lets a sharded run resume a single-device checkpoint and
    vice versa: utils/checkpoint serializes the GLOBAL array view
    (np.asarray gathers the shards), so the on-disk layout is
    placement-free. The checkpoint's PartitionSpec manifest drives the
    re-shard when it names axes the new mesh carries (a sharded source
    re-applies its own partitioning onto any device count that divides
    the axis); a spec-free source (saved unsharded, or a pre-manifest
    checkpoint) falls back to the node-axis rule, which needs ``n``.
    With ``mesh=None`` the arrays stay unsharded (single-device
    resume)."""
    state = ckpt_mod.restore(path, template)
    if mesh is None:
        return state
    from consul_tpu.parallel import mesh as pmesh
    from consul_tpu.parallel import shard_step

    specs = ckpt_mod.read_partition_spec(path)
    axis_names = set(mesh.axis_names)
    if specs is not None and any(
            a in axis_names
            for s in specs if s
            for entry in s
            for a in ([entry] if isinstance(entry, str) or entry is None
                      else entry)):
        shardings = pmesh.sharding_from_manifest(mesh, specs, state)
        return jax.tree.map(jax.device_put, state, shardings)
    if n is None:
        raise ValueError("restore_placed(mesh=...) needs n when the "
                         "checkpoint carries no usable partition spec")
    return shard_step.place(mesh, state, n)


def diagnostic_dump_path(dump_dir: str, t: int) -> str:
    """Where the sentinel host tier drops its diagnostic checkpoint
    (kept here so tooling and tests agree on the name)."""
    return os.path.join(dump_dir, f"sentinel_diag_t{int(t)}.ckpt")
