"""The resilient run loop: chunked execution with checkpoint/resume,
preemption handling, and optional on-device invariant sentinels.

``run_resilient`` is what the entry points drive instead of private
while-loops: ``consul-tpu run`` / ``consul-tpu chaos`` (cli.py) and
scenario replays that need to survive a kill. The guarantee (pinned by
tests/test_runtime.py at 4096 nodes, single-device and sharded, with
and without a chaos schedule): kill -9 the process mid-run, rerun the
same command, and the final state is bit-identical to an uninterrupted
run. Three properties make that hold:

- per-tick randomness is ``fold_in(base_key, t)`` (models/cluster.py)
  and ``t`` rides in the state, so a restored state replays the exact
  key stream;
- the chaos schedule's tick offset (``chaos_t0``) and digest ride in
  the checkpoint provenance, so the resumed run re-rebases the SAME
  schedule to the SAME absolute ticks — the remaining faults replay
  bit-identically — and a checkpoint from a different schedule is
  refused;
- saves are atomic and digest-verified (utils/checkpoint), so a crash
  mid-save can never poison the resume point.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Optional, Sequence

import jax

from consul_tpu.chaos import schedule as chaos_mod
from consul_tpu.models import counters as counters_mod
from consul_tpu.models.cluster import SLO_KEYS
from consul_tpu.runtime.policy import CheckpointPolicy, SignalTrap
from consul_tpu.utils import checkpoint as ckpt_mod


class Preempted(RuntimeError):
    """The run stopped early on a trapped termination signal — after
    saving a resume point. Carries the report so the caller can emit
    provenance before exiting."""

    def __init__(self, report: "RunReport"):
        self.report = report
        super().__init__(
            f"preempted at tick {report.ticks_done}/{report.ticks_asked} "
            f"(checkpoint: {report.checkpoint_path})"
        )


@dataclasses.dataclass
class RunReport:
    """What one resilient run did — the provenance the entry points
    serialize instead of ad-hoc status strings."""

    ticks_asked: int
    ticks_done: int
    resumed_from_tick: int
    preempted: bool
    checkpoint_path: Optional[str]
    ckpt_failures: int
    counters: dict
    slo: Optional[dict]

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _scenario_meta(sim, tag: str, ticks: int, t0: int, done: int,
                   sched_digest: str) -> dict:
    return {
        "tag": tag,
        "n": sim.cfg.n,
        "seed": sim.seed,
        "kind": type(sim).__name__,
        "ticks": ticks,
        "t0": t0,
        "ticks_done": done,
        "chaos_t0": t0,
        "schedule_digest": sched_digest,
    }


def run_resilient(sim, ticks: int, *, chunk: int = 64,
                  with_metrics: bool = False,
                  events: Optional[Sequence] = None,
                  policy: Optional[CheckpointPolicy] = None,
                  sentinel: bool = False,
                  sentinel_dump_dir: Optional[str] = None) -> RunReport:
    """Advance ``sim`` by ``ticks`` ticks (with ``events`` as a chaos
    schedule rebased onto the start tick, like ``run_scenario``) under
    the resilient harness: resume from ``policy``'s checkpoint when a
    compatible one exists, save at every due chunk boundary, save and
    raise :class:`Preempted` on SIGTERM, and retire the checkpoint on
    completion. With ``sentinel``, the on-device validator runs and a
    violation fail-fasts (models/cluster.py SentinelViolation) with a
    diagnostic checkpoint in ``sentinel_dump_dir``.

    Returns a :class:`RunReport`; the counter deltas cover only the
    ticks THIS invocation ran (a resumed run reports its own slice)."""
    if sentinel:
        sim.set_sentinel(True, sentinel_dump_dir)
    sched = (chaos_mod.compile_schedule(sim.cfg.n, events)
             if events else None)
    sched_digest = chaos_mod.digest_of(sched)
    t0 = int(jax.device_get(sim.swim_state.t))
    done = 0

    if policy is not None and policy.trap is None:
        policy.trap = SignalTrap()

    # Resume: the trajectory's identity is (shape, seed, driver kind,
    # total ticks, schedule digest). ``t0`` comes FROM the meta — the
    # schedule must rebase to the original start tick, not to wherever
    # the restored state happens to be.
    if policy is not None:
        state, meta = policy.load(sim.state, match={
            "tag": policy.tag,
            "n": sim.cfg.n,
            "seed": sim.seed,
            "kind": type(sim).__name__,
            "ticks": ticks,
            "schedule_digest": sched_digest,
        })
        if state is not None:
            sim.state = state
            t0 = int(meta["t0"])
            done = int(meta["ticks_done"])
    resumed_from = done

    prev_sched = sim.chaos
    if sched is not None:
        sim.set_chaos(chaos_mod.shift_schedule(sched, t0))
    before = dict(sim.counters)

    def _report(preempted: bool) -> RunReport:
        after = sim.counters
        deltas = {f: after[f] - before[f] for f in counters_mod.FIELDS}
        return RunReport(
            ticks_asked=ticks,
            ticks_done=done,
            resumed_from_tick=resumed_from,
            preempted=preempted,
            checkpoint_path=policy.path if policy is not None else None,
            ckpt_failures=policy.failures if policy is not None else 0,
            counters=deltas,
            slo={SLO_KEYS[f]: deltas[f] for f in SLO_KEYS}
            if sched is not None else None,
        )

    trap = policy.trap if policy is not None else SignalTrap()
    try:
        with trap:
            if policy is not None:
                policy.mark_run_start()
            since_save = 0
            while done < ticks:
                c = min(chunk, ticks - done)
                sim.run(c, chunk=c, with_metrics=with_metrics)
                done += c
                since_save += c
                if policy is None:
                    continue
                if trap.fired is not None:
                    policy.try_save(sim.state, _scenario_meta(
                        sim, policy.tag, ticks, t0, done, sched_digest))
                    raise Preempted(_report(preempted=True))
                if done < ticks and policy.due(since_save):
                    if policy.try_save(sim.state, _scenario_meta(
                            sim, policy.tag, ticks, t0, done, sched_digest)):
                        since_save = 0
    finally:
        sim.set_chaos(prev_sched)
    if policy is not None:
        policy.retire()
    return _report(preempted=False)


def restore_placed(path: str, template: Any, mesh=None, n: Optional[int] = None):
    """Restore a checkpoint and re-shard it over ``mesh``'s node axis —
    the round trip that lets a sharded run resume a single-device
    checkpoint and vice versa: utils/checkpoint serializes the GLOBAL
    array view (np.asarray gathers the shards), so the on-disk layout
    is placement-free and ``shard_step.place`` reinstates whatever
    layout this process runs. With ``mesh=None`` the arrays stay
    unsharded (single-device resume)."""
    state = ckpt_mod.restore(path, template)
    if mesh is not None:
        from consul_tpu.parallel import shard_step

        if n is None:
            raise ValueError("restore_placed(mesh=...) needs n")
        state = shard_step.place(mesh, state, n)
    return state


def diagnostic_dump_path(dump_dir: str, t: int) -> str:
    """Where the sentinel host tier drops its diagnostic checkpoint
    (kept here so tooling and tests agree on the name)."""
    return os.path.join(dump_dir, f"sentinel_diag_t{int(t)}.ckpt")
