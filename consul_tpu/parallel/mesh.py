"""Mesh + sharding specs for the simulated cluster.

Replaces the reference's distributed communication backend (UDP/TCP
transports, yamux RPC pools, NCCL-free Go networking — SURVEY.md §2.5)
with the TPU-native equivalent: the node axis sharded over a device
mesh; message scatter/gather between shards lowers to XLA collectives
over ICI. A second, leading ``dc`` axis federates multiple simulated
datacenters (the LAN/WAN split of reference agent/consul/server.go:223-230).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consul_tpu.models.state import SimState

NODE_AXIS = "nodes"
DC_AXIS = "dc"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-portable ``shard_map``: newer jax exposes it as
    ``jax.shard_map(..., check_vma=)``, older releases as
    ``jax.experimental.shard_map.shard_map(..., check_rep=)`` (the same
    replication check under its earlier name). All shard_map call sites
    in this repo go through here."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)


def mesh_key(mesh: Optional[Mesh]):
    """Hashable fingerprint of a mesh's identity: axis names, shape AND
    the concrete device ids. Every process-wide runner memo that bakes a
    mesh into its program (shard_map closes over the mesh) must include
    this, so an elastic 8->4 reshard can never hit a cached executable
    built for the old device set. ``None`` (single-device, no mesh)
    fingerprints as None."""
    if mesh is None:
        return None
    return (
        tuple(mesh.axis_names),
        tuple(mesh.shape[a] for a in mesh.axis_names),
        tuple(d.id for d in mesh.devices.flat),
    )


def node_axes(mesh: Mesh):
    """(axis_name, n_shards) carrying the node dimension of a flat
    Simulation over ``mesh``. A 1-D mesh shards nodes over NODE_AXIS; a
    2-D (dc, nodes) mesh shards the single node axis over BOTH axes —
    spec ``P((DC_AXIS, NODE_AXIS))`` — so the full device grid
    participates even when the model itself has no dc dimension
    (collectives take the tuple axis name; lax flattens it row-major,
    matching the mesh's device order)."""
    if DC_AXIS in mesh.axis_names:
        return ((DC_AXIS, NODE_AXIS),
                mesh.shape[DC_AXIS] * mesh.shape[NODE_AXIS])
    return (NODE_AXIS, mesh.shape[NODE_AXIS])


def default_mesh(n: int, device_count: Optional[int] = None,
                 n_dc: int = 1) -> Optional[Mesh]:
    """The mesh the CLIs and bench children run over by default: the
    largest elastic mesh the visible devices support — or ``None``
    (single-device execution, no shard_map) when only one device is
    visible or the caller pinned ``--devices 1``. ``device_count``
    truncates ``jax.devices()`` (the --devices override); ``n_dc``
    folds a dc axis in (the --n-dc override)."""
    devices = jax.devices()
    if device_count is not None:
        if device_count < 1:
            raise ValueError(f"device_count={device_count} must be >= 1")
        devices = devices[:device_count]
    if len(devices) <= 1 and n_dc <= 1:
        return None
    return elastic_mesh(n, devices, n_dc=n_dc)


def make_mesh(devices: Optional[Sequence[jax.Device]] = None, n_dc: int = 1) -> Mesh:
    """1-D node mesh, or 2-D (dc, nodes) when federating datacenters."""
    devices = list(devices if devices is not None else jax.devices())
    if n_dc == 1:
        return Mesh(np.array(devices), (NODE_AXIS,))
    assert len(devices) % n_dc == 0, "devices must divide evenly into DCs"
    grid = np.array(devices).reshape(n_dc, -1)
    return Mesh(grid, (DC_AXIS, NODE_AXIS))


def elastic_mesh(n: int, devices: Optional[Sequence[jax.Device]] = None,
                 n_dc: int = 1) -> Mesh:
    """The largest mesh the *surviving* devices support: take the
    biggest device count k ≤ len(devices) that both divides evenly
    into ``n_dc`` datacenters and divides the node axis ``n`` — the
    mesh an elastic resume rebuilds after chips are lost (8→4→1 all
    work for any power-of-two ``n``). Always succeeds for ``n_dc=1``
    (a 1-device mesh divides everything); raises when no surviving
    subset can host ``n_dc`` DCs."""
    devices = list(devices if devices is not None else jax.devices())
    for k in range(len(devices), 0, -1):
        if k % n_dc == 0 and n % (k // n_dc or 1) == 0 and k >= n_dc:
            return make_mesh(devices[:k], n_dc=n_dc)
    raise ValueError(
        f"no usable mesh: {len(devices)} surviving device(s) cannot "
        f"host n={n} nodes across n_dc={n_dc} datacenters")


def sharding_from_manifest(mesh: Mesh, specs: Sequence, tree):
    """Rebuild a NamedSharding pytree from a checkpoint's recorded
    PartitionSpec manifest (utils/checkpoint.read_partition_spec) over
    a NEW mesh — the re-shard half of a shape-agnostic resume. Axis
    names the new mesh does not carry (or leaves saved unsharded,
    spec None) fall back to replication; the node-axis rule re-applies
    them via :func:`node_spec` when the caller knows ``n``."""
    leaves, treedef = jax.tree.flatten(tree)
    if len(specs) != len(leaves):
        raise ValueError(
            f"partition manifest has {len(specs)} entries for "
            f"{len(leaves)} leaves — checkpoint/template mismatch")
    axis_names = set(mesh.axis_names)

    def to_spec(entry):
        if entry is None:
            return P()
        axes = []
        for a in entry:
            names = [a] if isinstance(a, str) or a is None else list(a)
            if all(x is None or x in axis_names for x in names):
                axes.append(tuple(names) if isinstance(a, list) else a)
            else:
                axes.append(None)  # axis lost with the old mesh shape
        return P(*axes)

    shardings = [NamedSharding(mesh, to_spec(s)) for s in specs]
    return jax.tree.unflatten(treedef, shardings)


def node_spec(leaf, n: int, axis=NODE_AXIS) -> P:
    """The one node-axis partition rule: leaves whose leading dim is the
    node count shard on it, everything else replicates. Shared by the
    auto-SPMD path (here) and the shard_map path (parallel/shard_step.py).
    ``axis`` may be a tuple — the 2-D (dc, nodes) grid sharding one flat
    node axis over both mesh axes (:func:`node_axes`)."""
    if leaf.ndim >= 1 and leaf.shape[0] == n:
        return P(axis, *([None] * (leaf.ndim - 1)))
    return P()


def state_sharding(state: SimState, mesh: Mesh) -> SimState:
    """NamedSharding pytree for a SimState: every per-node array is
    sharded on its node axis; scalars are replicated."""
    n = state.alive_truth.shape[0]
    return jax.tree.map(lambda l: NamedSharding(mesh, node_spec(l, n)), state)


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    """Place a host-built SimState onto the mesh."""
    return jax.tree.map(jax.device_put, state, state_sharding(state, mesh))


def data_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Sharding for auxiliary per-node arrays (nbrs, world tensors)."""
    return NamedSharding(mesh, P(NODE_AXIS, *([None] * (ndim - 1))))


def federation_sharding(fed_state, mesh: Mesh):
    """Sharding pytree for a FederationState over a 2-D (dc, nodes)
    mesh: LAN leaves [n_dc, N, ...] shard on both axes (DCs are
    data-parallel shards, nodes shard within a DC); WAN leaves
    [n_wan, ...] shard on the node axis; scalars replicate."""
    n_dc = fed_state.lan.alive_truth.shape[0]
    n = fed_state.lan.alive_truth.shape[1]
    n_wan = fed_state.wan.alive_truth.shape[0]

    def spec(leaf):
        if leaf.ndim >= 2 and leaf.shape[0] == n_dc and leaf.shape[1] == n:
            return NamedSharding(
                mesh, P(DC_AXIS, NODE_AXIS, *([None] * (leaf.ndim - 2)))
            )
        if leaf.ndim >= 1 and leaf.shape[0] == n_dc:
            return NamedSharding(mesh, P(DC_AXIS, *([None] * (leaf.ndim - 1))))
        if leaf.ndim >= 1 and leaf.shape[0] == n_wan and \
                n_wan % mesh.shape[NODE_AXIS] == 0:
            return NamedSharding(
                mesh, P(NODE_AXIS, *([None] * (leaf.ndim - 1)))
            )
        return NamedSharding(mesh, P())

    return jax.tree.map(spec, fed_state)
