"""shard_map execution of the SWIM step with explicit ICI collectives.

Two ways to run the simulation on a device mesh:

  1. ``jit`` with sharding annotations (parallel/mesh.py) — XLA's SPMD
     partitioner chooses the collectives. This is the default path and
     what the federation dryrun uses.
  2. This module: the step runs under ``jax.shard_map`` with the node
     axis split into explicit per-device blocks, and every cross-node
     exchange — the circulant rolls that carry probes, gossip packets
     and push-pull state (models/swim.py) — is an explicit
     ``lax.ppermute`` neighbor transfer around the device ring
     (parallel/collective.py). This is the framework's hand-written
     distributed communication backend, the ICI analogue of the
     reference's UDP/TCP transport (reference
     vendor/github.com/hashicorp/memberlist/transport.go:27-65): rolls
     whose shift is a trace-time constant move exactly one block's rows
     point-to-point; traced shifts take a log2(D) conditional ppermute
     ladder. The serf event plane's two row-addressed exchanges ride an
     [N] all-gather and a reduce-scatter (collective.all_rows /
     sum_scatter_rows). No host round-trips anywhere.

A sharded step matches the unsharded step for the same (state, key):
per-row randomness is generated from the global stream and sliced per
shard (collective.uniform_rows), so the **discrete protocol state**
(views, incarnations, suspicion timers, probe cursors) is bit-identical
and the float coordinate state matches to compiler-rounding tolerance
(different XLA fusions round differently by ~1 ulp). Tested in
tests/test_shardmap.py — the sharding analogue of the determinism tests
that replace the reference's race detector (SURVEY.md §5).

Both topology planes shard: the sparse circulant plane (the production
>=100k configuration) rides static-shift rolls; dense mode's
row-addressed probe reads ride ``collective.take_rows`` (one
all-gather + local gather — dense is a <=few-k-node shape, so the
gathered tables are KBs per device).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consul_tpu.config import SimConfig
from consul_tpu.models import counters as counters_mod
from consul_tpu.models import swim
from consul_tpu.ops.topology import Topology, World
from consul_tpu.parallel import collective as coll
from consul_tpu.parallel.mesh import (
    NODE_AXIS, node_axes, node_spec, shard_map,
)


def _make_sharded(step_fn, cfg: SimConfig, topo: Topology, mesh: Mesh,
                  counted: bool = False, chaos: bool = False,
                  sentinel: bool = False):
    """Shared builder: jit(shard_map(step_fn)) over the node axis with
    the collective context installed and state buffers donated.

    With ``counted=True``, ``step_fn`` is a ``*_counted`` step returning
    (state, GossipCounters): each shard's partial tallies are stacked
    into one [len(FIELDS)] i32 vector and tree-reduced over the node
    axis (collective.tree_psum — a log2(D) recursive-doubling ppermute
    ladder respecting the node x DC hierarchy) so every device holds
    the global totals (out spec P(), replicated).

    With ``chaos=True``, the returned function takes a fault schedule
    after the world: ``step(world, sched, state, key)``. The schedule's
    [N, slots] node masks shard with the state (node_spec) and the
    per-entry scalars replicate, so every per-node chaos term is
    evaluated on the local row block and the link masks stay
    shard-consistent by construction — the same ppermute rolls that
    carry the packets carry the sender-side terms
    (chaos/schedule.py roll_terms).

    With ``sentinel=True``, the on-device invariant validator runs in
    the step (models/swim.py _sentinel_check); its per-row violation
    tallies psum with the other counters, so the host sees global
    counts (sentinel requires ``counted`` to surface them).

    A 2-D (dc, nodes) mesh shards the flat node axis over BOTH axes
    (mesh.node_axes): the collectives take the tuple axis name and the
    device ring is the row-major flattening of the grid."""
    axis, n_shards = node_axes(mesh)
    if cfg.n % n_shards != 0:
        raise ValueError(f"n={cfg.n} must divide over {n_shards} shards")

    world_spec = World(pos=P(axis, None), height=P(axis))

    def local_step(world_local, sched_local, state_local, key):
        with coll.node_axis(axis, n_shards, cfg.n):
            if not counted:
                return step_fn(cfg, topo, world_local, state_local, key,
                               sched_local, sentinel=sentinel)
            st, cnt = step_fn(cfg, topo, world_local, state_local, key,
                              sched_local, sentinel=sentinel)
            red = coll.tree_psum(jnp.stack(list(cnt)))
            return st, counters_mod.unstack(red)

    def out_specs_of(specs):
        return specs if not counted else (
            specs, jax.tree.map(lambda _: P(), counters_mod.zeros()))

    if chaos:
        def global_step(world_g, sched_g, state_g, key):
            specs = jax.tree.map(lambda l: node_spec(l, cfg.n, axis), state_g)
            sched_specs = jax.tree.map(
                lambda l: node_spec(l, cfg.n, axis), sched_g)
            inner = shard_map(
                local_step,
                mesh=mesh,
                in_specs=(world_spec, sched_specs, specs, P()),
                out_specs=out_specs_of(specs),
                check_vma=False,
            )
            return inner(world_g, sched_g, state_g, key)

        return jax.jit(global_step, donate_argnums=(2,))

    def global_step(world_g, state_g, key):
        specs = jax.tree.map(lambda l: node_spec(l, cfg.n, axis), state_g)
        inner = shard_map(
            lambda w, st, k: local_step(w, None, st, k),
            mesh=mesh,
            in_specs=(world_spec, specs, P()),
            out_specs=out_specs_of(specs),
            check_vma=False,
        )
        return inner(world_g, state_g, key)

    return jax.jit(global_step, donate_argnums=(1,))


def make_sharded_step(cfg: SimConfig, topo: Topology, mesh: Mesh):
    """Build ``step(world, state, key) -> state`` running under shard_map
    over ``mesh``'s node axis with explicit ppermute collectives. The
    returned function is jitted with donated state buffers; place inputs
    with :func:`place` first for zero-copy."""
    return _make_sharded(swim.step, cfg, topo, mesh)


def make_sharded_serf_step(cfg: SimConfig, topo: Topology, mesh: Mesh):
    """The FULL serf step (SWIM + events/queries/reap) under shard_map.
    Beyond the SWIM plane's rolls, the event plane adds the two
    row-addressed exchanges: origin-attribute reads via all_gather and
    the query-response tally via reduce-scatter
    (collective.all_rows / sum_scatter_rows)."""
    from consul_tpu.models import serf

    return _make_sharded(serf.step, cfg, topo, mesh)


def make_sharded_counted_step(cfg: SimConfig, topo: Topology, mesh: Mesh,
                              sentinel: bool = False):
    """``step(world, state, key) -> (state, GossipCounters)`` under
    shard_map: the per-shard tallies are psum-reduced over the node axis
    (one extra len(FIELDS)-lane i32 collective), so the returned
    counters are the global per-tick totals, identical on every
    device. ``sentinel=True`` folds the invariant validator in."""
    return _make_sharded(swim.step_counted, cfg, topo, mesh, counted=True,
                         sentinel=sentinel)


def make_sharded_counted_serf_step(cfg: SimConfig, topo: Topology,
                                   mesh: Mesh):
    """The counted full-serf step under shard_map (see
    :func:`make_sharded_counted_step`)."""
    from consul_tpu.models import serf

    return _make_sharded(serf.step_counted, cfg, topo, mesh, counted=True)


def make_sharded_chaos_step(cfg: SimConfig, topo: Topology, mesh: Mesh, *,
                            counted: bool = False, serf: bool = False,
                            sentinel: bool = False):
    """``step(world, sched, state, key)`` under shard_map with a fault
    schedule as a program argument (chaos/schedule.py). The schedule's
    node masks shard with the state; its per-entry scalars replicate —
    every pairwise ``chaos.pair_ok`` check therefore sees exactly the
    same (src, dst, tick) terms on every mesh size, which is what makes
    sharded chaos trajectories bit-identical to single-device ones
    (tests/test_chaos.py)."""
    if serf:
        from consul_tpu.models import serf as serf_m

        fn = serf_m.step_counted if counted else serf_m.step
    else:
        fn = swim.step_counted if counted else swim.step
    return _make_sharded(fn, cfg, topo, mesh, counted=counted, chaos=True,
                         sentinel=sentinel)


def make_sharded_chunk_runner(cfg: SimConfig, topo: Topology, mesh: Mesh,
                              chunk: int, with_metrics: bool, *,
                              step_fn, swim_of,
                              chaos: bool = False, sentinel: bool = False,
                              layout: str = "dense", raft=None,
                              kernel: str = "xla"):
    """The multi-chip analogue of models/cluster.py ``_chunk_runner``:
    one jitted program per (cfg, topo content, chunk, metrics, step,
    chaos shape, sentinel, MESH) signature with the same call convention
    ``run(world, sched, state, base_key) -> (state, counters, trace)``.

    The whole ``chunk``-tick scan executes INSIDE a single shard_map
    region — per-tick keys fold the on-device tick counter, every
    cross-node exchange is an explicit ppermute/all-gather on the node
    axis (parallel/collective.py), and the per-shard counter tallies
    accumulate locally across the scan with exactly ONE tree_psum at
    the chunk boundary (log2(D) ladder instead of chunk psums).

    Metrics differ from the single-device runner by design: computing
    agreement/RMSE per tick would force a global gather inside every
    scan iteration, so the sharded runner samples them ONCE per chunk on
    the final state — outside the shard_map region but inside the same
    jit, where the SPMD partitioner handles the global reductions. The
    returned TickTrace has length-[1] rows; every consumer
    (run_until_converged, _record_chunk) reads only ``trace.*[-1]``, so
    convergence detection and telemetry see identical values at chunk
    granularity. The RMSE sample key matches the single-device last
    row's (fold_in(fold_in(base_key, t_last), 1)) so the chunk-boundary
    rows agree to float tolerance.

    With ``layout="packed"`` the carried state is the compact
    PackedSimState (models/layout.py); the scan body unpacks to the
    dense working set, steps, and re-packs — pack/unpack are purely
    elementwise, so they shard over the node axis like any other local
    math and the discrete protocol plane stays bit-identical to the
    dense runner (tests/test_layout_parity.py covers the sharded
    pairing).

    ``raft`` (a config.RaftConfig, None = off) threads the batched raft
    tier through the scan exactly like the single-device runner: the
    state slot becomes the ``(model_state, RaftState)`` pair and the
    counters the ``(GossipCounters, RaftCounters)`` pair. Sharding rule:
    when ``groups`` divides over the mesh, raft leaves shard on their
    leading group axis and each shard steps its own block with
    ``group0 = shard_index * groups_local`` — the PRNG ladder keys on
    GLOBAL seat ids (raft_ops.timeout_draws), so sharded trajectories
    are bit-identical to single-device ones and the counter psum sums
    disjoint per-shard tallies. Otherwise the raft leaves replicate
    (every shard steps all groups identically) and the replicated
    tallies are zeroed off shard 0 before the psum so globals are not
    multiplied by the shard count."""
    from consul_tpu.models import layout as layout_mod
    from consul_tpu.models.cluster import TickTrace  # deferred: no cycle
    from consul_tpu.utils import metrics

    packed = layout == layout_mod.PACKED
    axis, n_shards = node_axes(mesh)
    if cfg.n % n_shards != 0:
        raise ValueError(f"n={cfg.n} must divide over {n_shards} shards")
    use_pallas = kernel == "pallas"
    if use_pallas:
        # shard_map calls the kernel once per shard; the step's
        # collectives trace INTO the kernel jaxpr and the interpret-
        # mode evaluator resolves them against the enclosing mesh axis
        # (tests/test_pallas_gossip.py pins sharded == single-device).
        # Real-TPU Mosaic cannot host ICI collectives inside a kernel —
        # the multi-chip lowering splits at the three mid-tick exchange
        # barriers (ROADMAP item-1 remainder).
        from consul_tpu.ops import pallas_gossip

        pallas_gossip.validate_kernel(kernel, layout)
        ptick = pallas_gossip.make_tick_kernel(
            cfg, topo, step_fn=step_fn, sentinel=sentinel,
            interpret=pallas_gossip.default_interpret())

    world_spec = World(pos=P(axis, None), height=P(axis))
    cnt_specs = jax.tree.map(lambda _: P(), counters_mod.zeros())
    if raft is not None:
        from consul_tpu.ops import raft_ops

        raft_sharded = raft.groups % n_shards == 0
        r_local = raft.groups // n_shards if raft_sharded else raft.groups
        raft_spec = (
            (lambda l: P(axis, *([None] * (l.ndim - 1))))
            if raft_sharded else (lambda l: P()))
        rcnt_specs = jax.tree.map(lambda _: P(), raft_ops.counters_zeros())

    def local_run(world_l, sched_l, state_l, base_key):
        if raft is not None:
            state_l, rst_l = state_l
            group0 = (jax.lax.axis_index(axis).astype(jnp.int32) * r_local
                      if raft_sharded else jnp.int32(0))
        ticks = swim_of(state_l).t + jnp.arange(chunk, dtype=jnp.int32)
        tick_keys = jax.vmap(
            lambda t: jax.random.fold_in(base_key, t))(ticks)

        def body(carry, tick_key):
            if raft is not None:
                (state, rst), (cnt, rcnt) = carry
            else:
                state, cnt = carry
            if use_pallas:
                if raft is not None:
                    # PRE-step tick, straight off the packed t leaf.
                    t_pre = layout_mod.tick_of(state)
                with coll.node_axis(axis, n_shards, cfg.n):
                    state, c = ptick(world_l, sched_l, state, tick_key)
            else:
                if packed:
                    state = layout_mod.unpack_state(state)
                if raft is not None:
                    # Keyed on the PRE-step tick — the t this tick_key
                    # was folded from — matching the single-device
                    # runner and the lockstep oracle's step(t).
                    t_pre = swim_of(state).t
                with coll.node_axis(axis, n_shards, cfg.n):
                    state, c = step_fn(cfg, topo, world_l, state,
                                       tick_key, sched_l,
                                       sentinel=sentinel)
                if packed:
                    state = layout_mod.pack_state(state)
            cnt = counters_mod.add(cnt, c)
            if raft is not None:
                rst, rc = raft_ops.tick(raft, rst, t_pre, tick_key,
                                        sched=sched_l, group0=group0)
                return ((state, rst),
                        (cnt, raft_ops.counters_add(rcnt, rc))), ()
            return (state, cnt), ()

        if raft is not None:
            carry0 = ((state_l, rst_l),
                      (counters_mod.zeros(), raft_ops.counters_zeros()))
        else:
            carry0 = (state_l, counters_mod.zeros())
        (state_l, cnt), _ = jax.lax.scan(body, carry0, tick_keys)
        if raft is not None:
            (state_l, rst_l), (cnt, rcnt) = state_l, cnt
        with coll.node_axis(axis, n_shards, cfg.n):
            red = coll.tree_psum(jnp.stack(list(cnt)))
            if raft is not None:
                rvec = raft_ops.counters_stack(rcnt)
                if not raft_sharded:
                    # Replicated compute: every shard tallied the SAME
                    # global events — keep shard 0's copy only so the
                    # psum is a broadcast, not a multiply.
                    idx = jax.lax.axis_index(axis).astype(jnp.int32)
                    rvec = jnp.where(idx == 0, rvec, jnp.zeros_like(rvec))
                rred = coll.tree_psum(rvec)
        gcnt = counters_mod.unstack(red)
        if raft is not None:
            return ((state_l, rst_l),
                    (gcnt, raft_ops.counters_unstack(rred)))
        return state_l, gcnt

    def run(world, sched, state, base_key):
        if raft is not None:
            model_state, rst = state
            specs = (jax.tree.map(lambda l: node_spec(l, cfg.n, axis),
                                  model_state),
                     jax.tree.map(raft_spec, rst))
            out_cnt_specs = (cnt_specs, rcnt_specs)
        else:
            specs = jax.tree.map(lambda l: node_spec(l, cfg.n, axis),
                                 state)
            out_cnt_specs = cnt_specs
        if chaos:
            sched_specs = jax.tree.map(
                lambda l: node_spec(l, cfg.n, axis), sched)
            inner = shard_map(
                local_run, mesh=mesh,
                in_specs=(world_spec, sched_specs, specs, P()),
                out_specs=(specs, out_cnt_specs), check_vma=False,
            )
            state, cnt = inner(world, sched, state, base_key)
        else:
            inner = shard_map(
                lambda w, st, k: local_run(w, None, st, k), mesh=mesh,
                in_specs=(world_spec, specs, P()),
                out_specs=(specs, out_cnt_specs), check_vma=False,
            )
            state, cnt = inner(world, state, base_key)
        if not with_metrics:
            return state, cnt, ()
        sw = swim_of(state[0] if raft is not None else state)
        if packed:
            sw = layout_mod.unpack(sw)
        h = metrics.health(cfg, topo, sw)
        last_key = jax.random.fold_in(base_key, sw.t - 1)
        rmse = metrics.vivaldi_rmse(
            cfg, world, sw, jax.random.fold_in(last_key, 1), samples=2048)
        trace = TickTrace(
            h.agreement[None], h.false_positive[None],
            h.undetected[None], rmse[None])
        return state, cnt, trace

    return jax.jit(run, donate_argnums=(2,))


def place(mesh: Mesh, tree, n: int):
    """Shard a pytree's node-axis leaves over the mesh (others
    replicate). On a 2-D (dc, nodes) mesh the flat node axis spans both
    grid axes (mesh.node_axes)."""
    axis, _ = node_axes(mesh)
    return jax.tree.map(
        lambda l: jax.device_put(
            l, NamedSharding(mesh, node_spec(l, n, axis))), tree
    )


# ----------------------------------------------------------------------
# Scenario-sweep plane (chaos/sweep.py): vmap over a leading scenario
# axis INSIDE the shard_map region, topology tables as traced inputs.
# ----------------------------------------------------------------------

def sweep_spec(leaf, n: int, axis=NODE_AXIS) -> P:
    """The node-axis rule for scenario-stacked leaves: a [S, N, ...]
    leaf shards its node dimension (dim 1) over ``axis`` and replicates
    the scenario axis — every device holds all S scenarios of its own
    node block, which is exactly what vmap-inside-shard_map consumes.
    Everything else ([S]-stacked scalars, per-entry chaos terms)
    replicates, mirroring :func:`parallel.mesh.node_spec`."""
    if leaf.ndim >= 2 and leaf.shape[1] == n:
        return P(None, axis, *([None] * (leaf.ndim - 2)))
    return P()


def place_sweep(mesh: Mesh, tree, n: int):
    """:func:`place` for scenario-stacked pytrees (states / schedule
    stacks with a leading [S] axis)."""
    axis, _ = node_axes(mesh)
    return jax.tree.map(
        lambda l: jax.device_put(
            l, NamedSharding(mesh, sweep_spec(l, n, axis))), tree
    )


def make_sharded_sweep_runner(cfg: SimConfig, mesh: Mesh, chunk: int, *,
                              step_fn, swim_of):
    """The multi-chip sweep runner (chaos/sweep.py ``_sweep_runner``):
    ``run(world, off, rcol, inv, scheds, states, base_key) ->
    (states, counters)`` with states/schedules stacked on a leading
    scenario axis and the topology tables as *traced inputs* (the
    program-argument seam — same-shape families share this executable).

    The scenario vmap sits INSIDE the shard_map region: each device
    scans all S scenarios over its own node block, so the per-tick
    ppermute neighbor exchanges batch across scenarios for free (vmap
    adds a leading batch dim to every collective operand) and there is
    still exactly ONE counter tree_psum per (scenario, chunk) — applied
    inside the vmapped body, where psum's batching rule reduces each
    scenario lane independently. The reduced [S]-leaf counters are
    replicated (out spec P()), identical on every device."""
    axis, n_shards = node_axes(mesh)
    if cfg.n % n_shards != 0:
        raise ValueError(f"n={cfg.n} must divide over {n_shards} shards")

    world_spec = World(pos=P(axis, None), height=P(axis))
    cnt_specs = jax.tree.map(lambda _: P(), counters_mod.zeros())

    def local_run(world_l, off, rcol, inv, sched_l, states_l, base_key):
        topo = Topology(n=cfg.n, dense=False, off=off, rcol=rcol, inv=inv)

        def one(sched, state):
            ticks = swim_of(state).t + jnp.arange(chunk, dtype=jnp.int32)
            tick_keys = jax.vmap(
                lambda t: jax.random.fold_in(base_key, t))(ticks)

            def body(carry, tick_key):
                st, cnt = carry
                with coll.node_axis(axis, n_shards, cfg.n):
                    st, c = step_fn(cfg, topo, world_l, st, tick_key,
                                    sched, sentinel=False)
                return (st, counters_mod.add(cnt, c)), ()

            (state, cnt), _ = jax.lax.scan(
                body, (state, counters_mod.zeros()), tick_keys)
            with coll.node_axis(axis, n_shards, cfg.n):
                red = coll.tree_psum(jnp.stack(list(cnt)))
            return state, counters_mod.unstack(red)

        return jax.vmap(one)(sched_l, states_l)

    def run(world, off, rcol, inv, scheds, states, base_key):
        state_specs = jax.tree.map(
            lambda l: sweep_spec(l, cfg.n, axis), states)
        sched_specs = jax.tree.map(
            lambda l: sweep_spec(l, cfg.n, axis), scheds)
        inner = shard_map(
            local_run, mesh=mesh,
            in_specs=(world_spec, P(), P(), P(), sched_specs,
                      state_specs, P()),
            out_specs=(state_specs, cnt_specs), check_vma=False,
        )
        return inner(world, off, rcol, inv, scheds, states, base_key)

    return jax.jit(run, donate_argnums=(5,))
