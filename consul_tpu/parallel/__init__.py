"""Device-mesh construction and sharded execution.

The distribution story of the framework (SURVEY.md §2.5): the node axis
is the one parallel axis that matters — sharded over chips with
``jax.sharding``, cross-shard gossip rides XLA collectives over ICI, and
multiple meshes federate over DCN for the multi-DC WAN topology.
"""

from consul_tpu.parallel import mesh as mesh  # noqa: F401
