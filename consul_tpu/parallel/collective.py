"""Explicit ICI collectives for the node-sharded simulation step.

The reference's distributed communication backend is UDP/TCP sockets
behind ``memberlist.Transport`` (reference
vendor/github.com/hashicorp/memberlist/transport.go:27-65) plus a
yamux-multiplexed RPC pool (reference agent/pool/pool.go:122-533). The
TPU equivalent (SURVEY.md §2.5) is XLA collectives over ICI. This module
is that backend, stated explicitly: every cross-node message exchange in
the SWIM plane is a circulant **roll** along the node axis
(ops/topology.py), and under ``shard_map`` a roll of the node-sharded
array decomposes into at most two ``lax.ppermute`` block transfers
around the device ring (static shift) or a log2(D) conditional-hop
ppermute ladder (traced shift) — the all-neighbor exchange rides ICI
links point-to-point, never a host round-trip. The serf event plane
adds the two row-addressed exchanges rolls cannot express — reading an
arbitrary global row (:func:`all_rows`, one [N] all-gather) and
delivering to one (:func:`sum_scatter_rows`, a reduce-scatter) — both
O(N)-bytes collectives, still no host round-trips.

Design: the step functions (models/swim.py) are written against the
row-axis primitives below. Outside any context they degrade to exactly
the single-device expressions (``jnp.roll``, ``jnp.arange``, plain
``jax.random`` draws), so single-chip behavior is untouched. Inside
:func:`node_axis` — entered by the ``shard_map`` wrapper in
parallel/shard_step.py — the same calls emit ppermute/psum collectives
over the named mesh axis.

Exactness: per-row random draws generate the **global** array from the
replicated key and statically slice the local block, so a sharded step
is bit-identical to the unsharded step (tested in
tests/test_shardmap.py). The redundant generation is O(N·tail) work per
device per draw — the worst case is the probe-order reshuffle's [N, K]
draw (models/swim.py), ~128 MB transient at n=1M/K=32, regenerated on
nearly every tick at scale because some cursor always wraps. If that
ever shows up in a multichip profile, switch the draws to per-row
``fold_in(key, global_row_id)`` streams (shard-count-invariant, each
shard generates only its block) — that keeps a sharded/unsharded
equivalence test but changes the single-device trajectory, so re-pin
any golden numbers when doing it.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp


class NodeAxisCtx(NamedTuple):
    axis_name: str   # shard_map mesh axis carrying the node dimension
    n_shards: int    # devices along that axis
    n_global: int    # global node count (block = n_global // n_shards)


_CTX: contextvars.ContextVar[Optional[NodeAxisCtx]] = contextvars.ContextVar(
    "consul_tpu_node_axis", default=None
)


def current() -> Optional[NodeAxisCtx]:
    return _CTX.get()


def sharded() -> bool:
    """True when tracing inside a node-axis shard_map — for trace-time
    choices between the collective and the single-chip formulation
    (e.g. control flow that must not wrap collectives)."""
    return _CTX.get() is not None


_KERNEL: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "consul_tpu_kernel_body", default=False
)


def in_kernel() -> bool:
    """True while tracing inside the Pallas gossip kernel body
    (ops/pallas_gossip.py) — for trace-time choices between the XLA
    formulation and the kernel-callable core (no ``lax.cond`` around
    pytree operands, no sort-lowered primitives: Mosaic has neither).
    Off this path the step programs are byte-for-byte untouched, which
    is what the ``--kernel`` compile-ledger pin counts."""
    return _KERNEL.get()


@contextlib.contextmanager
def kernel_body():
    """Declare that step code traced inside this context is being
    inlined into the Pallas gossip kernel. Composes with
    :func:`node_axis`: a sharded kernel body traces its collectives
    (ppermute/all-gather) straight into the kernel jaxpr, which the
    interpret-mode evaluator resolves against the enclosing shard_map
    axis (tests/test_pallas_gossip.py pins sharded == single-device)."""
    tok = _KERNEL.set(True)
    try:
        yield
    finally:
        _KERNEL.reset(tok)


@contextlib.contextmanager
def node_axis(axis_name: str, n_shards: int, n_global: int):
    """Declare that per-node arrays inside this context are shard_map
    blocks of ``n_global // n_shards`` rows along ``axis_name``."""
    if n_global % n_shards != 0:
        raise ValueError(f"n_global={n_global} not divisible by {n_shards}")
    tok = _CTX.set(NodeAxisCtx(axis_name, n_shards, n_global))
    try:
        yield
    finally:
        _CTX.reset(tok)


def _block(ctx: NodeAxisCtx) -> int:
    return ctx.n_global // ctx.n_shards


def _perm(ctx: NodeAxisCtx, amt: int):
    """ppermute pairs moving each block ``amt`` seats up the ring: the
    block of device s lands on device (s + amt) mod D, i.e. device d
    receives block d - amt."""
    d = ctx.n_shards
    return [(s, (s + amt) % d) for s in range(d)]


def local_n(n: int) -> int:
    """Local row count for a global node count ``n``."""
    ctx = _CTX.get()
    return n if ctx is None else n // ctx.n_shards


def rows(n: int) -> jax.Array:
    """Global row ids of the rows this program instance holds."""
    ctx = _CTX.get()
    if ctx is None:
        return jnp.arange(n, dtype=jnp.int32)
    b = n // ctx.n_shards
    base = jax.lax.axis_index(ctx.axis_name).astype(jnp.int32) * b
    return base + jnp.arange(b, dtype=jnp.int32)


def _slice_rows(ctx: NodeAxisCtx, x: jax.Array) -> jax.Array:
    """Local block of a globally-shaped per-row array."""
    b = _block(ctx)
    start = jax.lax.axis_index(ctx.axis_name).astype(jnp.int32) * b
    return jax.lax.dynamic_slice_in_dim(x, start, b, axis=0)


def roll(x: jax.Array, shift) -> jax.Array:
    """Global circular roll along the node axis (axis 0):
    ``out[g] = x[(g - shift) mod N]`` in global row coordinates.

    Single-device: ``jnp.roll``. Sharded, static shift: at most two
    ppermutes moving exactly B rows total (the two slices of the rolled
    block live on at most two source devices). Sharded, traced shift:
    conditional ppermute ladder over the bits of the block displacement
    plus one neighbor transfer for the intra-block remainder."""
    ctx = _CTX.get()
    if ctx is None:
        return jnp.roll(x, shift, axis=0)
    b = _block(ctx)
    n = ctx.n_global
    squeeze = x.dtype == jnp.bool_
    if squeeze:  # ppermute bools as uint8 for backend safety
        x = x.astype(jnp.uint8)
    if isinstance(shift, jax.core.Tracer):
        out = _roll_dynamic(ctx, x, jnp.asarray(shift) % n, b)
    else:
        out = _roll_static(ctx, x, int(shift) % n, b)
    return out.astype(jnp.bool_) if squeeze else out


def _roll_static(ctx: NodeAxisCtx, x: jax.Array, s: int, b: int) -> jax.Array:
    if s == 0:
        return x
    q, r = divmod(s, b)
    ax = ctx.axis_name
    if r == 0:
        return jax.lax.ppermute(x, ax, _perm(ctx, q))
    # out rows [0, r) come from block d-q-1 rows [b-r, b);
    # out rows [r, b) come from block d-q rows [0, b-r).
    head_src = x[b - r:]
    tail_src = x[:b - r]
    head = jax.lax.ppermute(head_src, ax, _perm(ctx, (q + 1) % ctx.n_shards)) \
        if (q + 1) % ctx.n_shards != 0 else head_src
    tail = jax.lax.ppermute(tail_src, ax, _perm(ctx, q)) if q != 0 else tail_src
    return jnp.concatenate([head, tail], axis=0)


def _roll_dynamic(ctx: NodeAxisCtx, x: jax.Array, s: jax.Array, b: int) -> jax.Array:
    ax = ctx.axis_name
    q = (s // b).astype(jnp.int32)
    r = (s % b).astype(jnp.int32)
    # Block rotation by traced q: conditional hops over its bits. Every
    # ppermute executes unconditionally (collectives must be uniform
    # across the SPMD program); the hop is selected with a where.
    y = x
    amt, bit = 1, 0
    while amt < ctx.n_shards:
        hopped = jax.lax.ppermute(y, ax, _perm(ctx, amt))
        take = ((q >> bit) & 1) == 1
        y = jnp.where(_bcast(take, y.ndim), hopped, y)
        amt <<= 1
        bit += 1
    # y = block_{d-q}. Neighbor block d-q-1 for the intra-block seam.
    z = jax.lax.ppermute(y, ax, _perm(ctx, 1))
    full = jnp.concatenate([z, y], axis=0)          # rows of blocks d-q-1, d-q
    return jax.lax.dynamic_slice_in_dim(full, b - r, b, axis=0)


def _bcast(pred: jax.Array, ndim: int) -> jax.Array:
    return pred.reshape((1,) * ndim) if ndim else pred


def roll_many(arrays, shift):
    """Roll several same-row-count arrays by one shared shift along the
    node axis. Unsharded: one ``jnp.roll`` per array — XLA fuses the
    static slices, and no packed copy is materialized (packing costs
    ~10% single-chip throughput at >=262k nodes). Sharded: the arrays
    pack into one uint32 payload so the whole exchange is a single
    ppermute per hop, then unpack. Supports bool/int32/uint32 leaves of
    rank 1 or 2; int32 round-trips by bit-pattern (negatives survive).

    Transport-width contract note: the HBM-resident state is what the
    ``--kernel`` flag narrows, not this wire format. Under the XLA path
    the exchange moves 32-bit lanes between dense working-set buffers;
    under the Pallas packed-native path (ops/pallas_gossip.py) the same
    ``roll_many`` calls trace *inside* the kernel body, where the
    working set was unpacked in-register from PackedSimState tiles — so
    the bytes that cross HBM per tick are the packed at-rest bytes
    (bench.py memory phase asserts the ratio), while the in-flight
    lanes here stay 32-bit in both modes."""
    # Packing goes through astype(uint32), which is a VALUE conversion:
    # float dtypes would be silently rounded and 64-bit ints truncated,
    # but only on the sharded path — a divergence invisible single-chip.
    # Fail loudly instead, for any caller, in both contexts.
    for a in arrays:
        if a.dtype not in (jnp.bool_, jnp.int32, jnp.uint32):
            raise TypeError(
                f"roll_many supports bool/int32/uint32 leaves, got {a.dtype}"
                " — pack other dtypes by bit-pattern first"
            )
    ctx = _CTX.get()
    if ctx is None:
        return [jnp.roll(a, shift, axis=0) for a in arrays]
    cols = []
    for a in arrays:
        a2 = a[:, None] if a.ndim == 1 else a
        cols.append(a2.astype(jnp.uint32))
    packed = roll(jnp.concatenate(cols, axis=1), shift)
    out, at = [], 0
    for a in arrays:
        w = 1 if a.ndim == 1 else a.shape[1]
        piece = packed[:, at:at + w]
        at += w
        if a.dtype == jnp.bool_:
            piece = piece != 0
        else:
            piece = piece.astype(a.dtype)
        out.append(piece[:, 0] if a.ndim == 1 else piece)
    return out


def tree_psum(x: jax.Array) -> jax.Array:
    """All-reduce sum over the node axis as a recursive-doubling
    ppermute ladder instead of a flat ``lax.psum``.

    Stage ``s`` exchanges at ring distance ``2^s`` and doubles the
    reduced span, so the reduction is a log2(D)-depth binary tree whose
    early (high-traffic) stages stay between ring neighbors. Under the
    (node-shard x DC) meshes built by parallel/mesh.py the node axis is
    the *minor* (fastest-varying) device axis, so distance-1 and
    distance-2 stages are intra-DC ICI hops and only the last
    log2(n_dc) stages cross the DC seam — the tree respects the mesh
    hierarchy by construction, with no axis bookkeeping needed here.

    Unsharded: identity. Non-power-of-two shard counts fall back to the
    flat ``lax.psum`` (recursive doubling needs the span to tile the
    ring exactly). Exact for integer dtypes — a sum tree reassociates,
    which is bitwise-invisible to i32/u32 counters."""
    ctx = _CTX.get()
    if ctx is None:
        return x
    d = ctx.n_shards
    if d <= 1:
        return x
    if d & (d - 1):
        return jax.lax.psum(x, ctx.axis_name)
    y = x
    amt = 1
    while amt < d:
        y = y + jax.lax.ppermute(y, ctx.axis_name, _perm(ctx, amt))
        amt <<= 1
    return y


def any_rows(x: jax.Array) -> jax.Array:
    """``jnp.any`` over the full (global) node axis. Sharded, the fold
    rides :func:`tree_psum` — a hierarchical scalar reduction rather
    than a flat all-reduce."""
    ctx = _CTX.get()
    local = jnp.any(x)
    if ctx is None:
        return local
    return tree_psum(local.astype(jnp.int32)) > 0


def all_rows(x: jax.Array) -> jax.Array:
    """The full global per-row array, visible on every shard — for
    gathers by arbitrary global row id (e.g. a query's origin). One
    all-gather of a [N]-sized array; identity when unsharded."""
    ctx = _CTX.get()
    if ctx is None:
        return x
    squeeze = x.dtype == jnp.bool_
    g = jax.lax.all_gather(
        x.astype(jnp.uint8) if squeeze else x, ctx.axis_name, tiled=True
    )
    return g.astype(jnp.bool_) if squeeze else g


def take_rows(x: jax.Array, gidx: jax.Array) -> jax.Array:
    """``x`` indexed by GLOBAL row ids: ``all_rows(x)[gidx]`` — a plain
    gather single-chip, one all-gather + local gather sharded. Dense
    mode's row-addressed reads (probe-target attributes, poke checks)
    ride this; at dense scale (n <= a few k) the gathered table is a
    few KB per device, so the cost is noise."""
    return all_rows(x)[gidx]


def sum_scatter_rows(idx: jax.Array, vals: jax.Array, n: int) -> jax.Array:
    """Scatter-add ``vals`` at global row ids ``idx`` and return each
    row's received total (this shard's block under sharding): the
    all-to-all row-addressed delivery (e.g. query-response tallies).
    ``vals`` may carry trailing axes ([rows, Q] tallies land per-slot).
    Each shard accumulates into a global-sized buffer; a reduce-scatter
    (psum_scatter) folds the shards and hands each device exactly its
    block — half the bandwidth of a full psum + slice. Deliberately NOT
    routed through :func:`tree_psum`: a reduce-scatter already IS the
    optimal tree (each device keeps only its block), so a ladder here
    would double the bytes moved."""
    ctx = _CTX.get()
    full = jnp.zeros((n,) + vals.shape[1:], vals.dtype).at[idx].add(vals)
    if ctx is None:
        return full
    return jax.lax.psum_scatter(
        full, ctx.axis_name, scatter_dimension=0, tiled=True
    )


# ----------------------------------------------------------------------
# Per-row randomness with sharding-exact semantics: generate the global
# array from the (replicated) key, slice the local block.
# ----------------------------------------------------------------------

def uniform_rows(key, n: int, tail=(), minval=0.0, maxval=1.0, dtype=jnp.float32):
    ctx = _CTX.get()
    full = jax.random.uniform(key, (n, *tail), dtype, minval, maxval)
    return full if ctx is None else _slice_rows(ctx, full)


def normal_rows(key, n: int, tail=(), dtype=jnp.float32):
    ctx = _CTX.get()
    full = jax.random.normal(key, (n, *tail), dtype)
    return full if ctx is None else _slice_rows(ctx, full)
