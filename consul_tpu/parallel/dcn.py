"""Inter-mesh (DCN) federation: the WAN tier across device meshes.

The reference federates datacenters over real WAN links: every server
joins the global WAN serf pool, and cross-DC traffic rides UDP/TCP
between hosts (reference agent/consul/server.go:223-230, flood.go).
Intra-mesh, this framework's equivalent is ICI collectives
(parallel/collective.py). This module is the remaining tier of the
SURVEY §2.5 communication-backend mapping: **host-mediated DCN exchange
between meshes** — multiple islands, each a mesh (in production: a
pod/host group; here: a device subset or just a separate jit program),
each running its own LAN pools plus a full **replica of the WAN pool**,
reconciled at superstep boundaries through the host.

Why replicas + periodic reconciliation is the honest design (not a
shortcut): the WAN pool's state IS gossip state — per-observer views in
a join-semilattice (ops/merge.py). Between syncs, each island's replica
evolves only the rows it can see locally; at a sync, every island
receives every other island's **owned rows wholesale** (full per-node
protocol state: views, incarnations, budgets, coordinates). That is
exactly a push-pull anti-entropy exchange (reference
memberlist/state.go:573-608) executed at the DCN tier, and the
dissemination of the received facts back into the island's own rows
happens in-protocol, by the replica's subsequent WAN gossip ticks. The
sync period is therefore the modeled DCN latency: a fact crosses
islands in one superstep, then spreads in-replica at gossip speed —
the same two-timescale behavior as the reference's LAN/WAN split.

Ownership: island k owns the WAN rows of the servers in its DCs
(``FederationConfig.dc_offset``/``n_dc``); LAN ground truth flows into
owned rows only (models/federation.py), so a server's liveness is
always authored by the island that simulates its datacenter.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from consul_tpu.models.federation import Federation, FederationConfig


class DcnFederation:
    """Driver for a federation partitioned over ``n_islands`` meshes.

    ``cfg`` describes the WHOLE federation (its ``n_dc`` is the global
    DC count); DCs are partitioned contiguously across islands. Pass
    ``meshes`` (one per island) to shard each island's state over its
    own device subset; default leaves placement to JAX (correctness
    path — the CPU test harness).
    """

    def __init__(self, cfg: FederationConfig, n_islands: int = 2,
                 seed: int = 0, meshes: Optional[Sequence] = None):
        if cfg.n_dc % n_islands != 0:
            raise ValueError(
                f"n_dc={cfg.n_dc} must divide into {n_islands} islands"
            )
        per = cfg.n_dc // n_islands
        self.cfg = cfg
        self.n_islands = n_islands
        self.islands: list[Federation] = []
        for k in range(n_islands):
            icfg = dataclasses.replace(
                cfg, n_dc=per, n_dc_total=cfg.n_dc, dc_offset=k * per
            )
            # Same seed everywhere: the WAN plant (sites, topology) must
            # be identical across replicas; LAN worlds differ because
            # the key stream is indexed by global DC (federation.py).
            isl = Federation(icfg, seed=seed)
            # De-correlate per-tick protocol randomness between islands
            # (each replica is its own gossip universe between syncs).
            isl.base_key = jax.random.fold_in(isl.base_key, k)
            self.islands.append(isl)
        self.meshes = list(meshes) if meshes is not None else None
        if self.meshes is not None and len(self.meshes) != n_islands:
            raise ValueError(
                f"{len(self.meshes)} meshes for {n_islands} islands"
            )
        if self.meshes is not None:
            from consul_tpu.parallel import mesh as pmesh
            for isl, m in zip(self.islands, self.meshes):
                shardings = pmesh.federation_sharding(isl.state, m)
                isl.state = jax.tree.map(jax.device_put, isl.state, shardings)
        s = cfg.servers_per_dc
        self._owner = jnp.repeat(
            jnp.arange(n_islands, dtype=jnp.int32), per * s
        )  # [n_wan] owning island of each WAN row

    # ------------------------------------------------------------------
    def sync(self):
        """One DCN reconciliation: every island's replica takes every
        other island's owned WAN rows wholesale (see module docstring).
        One device->host pull and one host->device push per island —
        the batched host-boundary discipline of SURVEY §7."""
        # The DCN hop: replicas live on disjoint device sets, so the
        # exchange goes through the host — one pull per island, one
        # numpy-side merge, one push per island.
        import numpy as np

        wans = [jax.device_get(isl.state.wan) for isl in self.islands]
        owner = np.asarray(self._owner)

        # Per-field dispatch by NAME, not by a leading-dim shape test: a
        # [K, ...] leaf whose K coincidentally equals n_wan must never be
        # row-merged. SimState's one non-per-row field is the tick
        # counter ``t`` (models/state.py:58-91); every other field —
        # including every nested viv leaf — is [n_wan, ...], which the
        # assert pins against future drift.
        scalar_fields = {"t"}

        def select(*leaves):
            if leaves[0].shape[0] != owner.shape[0]:
                # A hard error (not an assert, which python -O strips):
                # a future non-per-row leaf must fail loudly here, not
                # silently mis-broadcast through np.where.
                raise ValueError(
                    f"per-row WAN leaf with leading dim {leaves[0].shape}"
                )
            sel = owner.reshape((-1,) + (1,) * (leaves[0].ndim - 1))
            out = leaves[0]
            for k in range(1, len(leaves)):
                out = np.where(sel == k, leaves[k], out)
            return out

        merged = type(wans[0])(**{
            name: (getattr(wans[0], name) if name in scalar_fields
                   else jax.tree.map(
                       select, *[getattr(w, name) for w in wans]))
            for name in type(wans[0])._fields
        })
        for i, isl in enumerate(self.islands):
            if self.meshes is not None:
                from consul_tpu.parallel import mesh as pmesh
                wan_shard = pmesh.federation_sharding(
                    isl.state, self.meshes[i]
                ).wan
                wan = jax.tree.map(jax.device_put, merged, wan_shard)
            else:
                # device_put per island: fresh buffers, so the donating
                # per-island runners never alias across replicas.
                wan = jax.tree.map(
                    lambda x: jax.device_put(jnp.asarray(x)), merged
                )
            isl.state = isl.state._replace(wan=wan)

    def run(self, lan_ticks: int, sync_every: int = 16, chunk: int = 16):
        """Advance all islands ``lan_ticks`` LAN ticks, reconciling the
        WAN tier every ``sync_every`` ticks (the DCN cadence; 16 ticks =
        3.2 s of protocol time at the 200 ms LAN tick)."""
        remaining = lan_ticks
        while remaining > 0:
            c = min(sync_every, remaining)
            for isl in self.islands:
                isl.run(c, chunk=min(chunk, c))
            self.sync()
            remaining -= c

    # ------------------------------------------------------------------
    def island_of_dc(self, dc: int) -> tuple[Federation, int]:
        """(owning island, local dc index) for a global DC index."""
        per = self.cfg.n_dc // self.n_islands
        return self.islands[dc // per], dc % per

    def kill(self, dc: int, mask):
        isl, local = self.island_of_dc(dc)
        isl.kill(local, mask)

    def wan_status_seen_by(self, observer_dc: int, subject_dc: int,
                           observer_server: int = 0) -> list[str]:
        """How ``observer_dc``'s server sees ``subject_dc``'s servers,
        read from the OBSERVER's island replica — the cross-island
        convergence probe. Columns the observer's partial view does not
        track report "untracked"."""
        isl, _ = self.island_of_dc(observer_dc)
        s = self.cfg.servers_per_dc
        out = {}
        for m in isl.wan_members_seen_by(observer_dc, observer_server):
            if m["dc"] == f"dc{subject_dc}":
                srv = int(m["id"].split(".")[0][3:])
                out[srv] = m["status"]
        return [out.get(k, "untracked") for k in range(s)]
