"""Inter-mesh (DCN) federation: the WAN tier across device meshes.

The reference federates datacenters over real WAN links: every server
joins the global WAN serf pool, and cross-DC traffic rides UDP/TCP
between hosts (reference agent/consul/server.go:223-230, flood.go).
Intra-mesh, this framework's equivalent is ICI collectives
(parallel/collective.py). This module is the remaining tier of the
SURVEY §2.5 communication-backend mapping: **host-mediated DCN exchange
between meshes** — multiple islands, each a mesh (in production: a
pod/host group; here: a device subset or just a separate jit program),
each running its own LAN pools plus a full **replica of the WAN pool**,
reconciled at superstep boundaries through the host.

Why replicas + periodic reconciliation is the honest design (not a
shortcut): the WAN pool's state IS gossip state — per-observer views in
a join-semilattice (ops/merge.py). Between syncs, each island's replica
evolves only the rows it can see locally; at a sync, every island
receives every other island's **owned rows wholesale** (full per-node
protocol state: views, incarnations, budgets, coordinates). That is
exactly a push-pull anti-entropy exchange (reference
memberlist/state.go:573-608) executed at the DCN tier, and the
dissemination of the received facts back into the island's own rows
happens in-protocol, by the replica's subsequent WAN gossip ticks. The
sync period is therefore the modeled DCN latency: a fact crosses
islands in one superstep, then spreads in-replica at gossip speed —
the same two-timescale behavior as the reference's LAN/WAN split.

Ownership: island k owns the WAN rows of the servers in its DCs
(``FederationConfig.dc_offset``/``n_dc``); LAN ground truth flows into
owned rows only (models/federation.py), so a server's liveness is
always authored by the island that simulates its datacenter.

Fault envelope: real DCN links time out, drop, and partition. Each
directed link (src island -> dst island) runs a small state machine
(:class:`LinkPolicy` / ``_LinkState``): a failed send (injected via
:meth:`DcnFederation.inject_link_faults` — ``timeout`` models a send
that burns its ``send_timeout_s`` budget, ``drop`` a fast failure)
puts the link into bounded exponential backoff measured in SYNC
ROUNDS with deterministic jitter (no wall clocks, no host RNG — this
is a device-tier module, TH103), while the undelivered anti-entropy
payloads buffer in a bounded retransmit queue (drop-oldest; the
newest payload always survives, which is all anti-entropy needs — a
later push-pull supersedes an earlier one). On heal the queue
re-merges oldest-to-newest and the replicas reconverge. Every event
is counted through the telemetry sink: ``sim.dcn.retries``,
``sim.dcn.link_down_ticks``, ``sim.dcn.send_timeouts``,
``sim.dcn.retx_dropped``, ``sim.dcn.heals``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from consul_tpu.models.federation import Federation, FederationConfig
from consul_tpu.obs import trace as obs_trace


@dataclasses.dataclass(frozen=True)
class LinkPolicy:
    """Per-link fault envelope for the DCN tier. Backoff is measured
    in sync rounds (the DCN superstep IS the link's clock — one round
    = ``sync_every`` LAN ticks of modeled time), bounded exponentially:
    after the k-th consecutive failure the link stays down
    ``min(backoff_cap, backoff_base * 2**(k-1)) + jitter`` rounds,
    with deterministic hash jitter so simultaneous link failures
    de-synchronize their retries without host RNG. ``retry_max``
    bounds the consecutive retries before the link is marked degraded
    (it keeps retrying at the capped cadence — a WAN partition must
    heal eventually — but the degradation is counted and visible)."""

    send_timeout_s: float = 2.0     # modeled per-send budget (timeout kind)
    retry_max: int = 5
    backoff_base: int = 1           # sync rounds
    backoff_cap: int = 8            # sync rounds
    queue_bound: int = 4            # buffered anti-entropy payloads


DEFAULT_LINK_POLICY = LinkPolicy()


@dataclasses.dataclass(frozen=True)
class LinkFault:
    """An injected DCN link fault: sends src->dst fail during sync
    rounds [start, stop). ``kind`` is ``"drop"`` (fast failure) or
    ``"timeout"`` (the send burns its ``send_timeout_s`` budget first
    — same outcome, distinct diagnosis and counter)."""

    src: int
    dst: int
    start: int
    stop: int
    kind: str = "drop"


@dataclasses.dataclass
class _LinkState:
    """One directed link's retry machine (host-side bookkeeping)."""

    queue: list = dataclasses.field(default_factory=list)
    attempt: int = 0          # consecutive failures
    down_until: int = 0       # backoff expiry, in sync rounds
    degraded: bool = False
    queue_peak: int = 0


def _jitter(src: int, dst: int, attempt: int) -> int:
    """Deterministic backoff jitter in {0, 1} rounds — a Knuth-style
    hash of (link, attempt), so retries de-correlate across links
    without host randomness (reproducible trajectories, TH103)."""
    h = (src * 73856093) ^ (dst * 19349663) ^ (attempt * 83492791)
    return (h >> 4) & 1


class DcnFederation:
    """Driver for a federation partitioned over ``n_islands`` meshes.

    ``cfg`` describes the WHOLE federation (its ``n_dc`` is the global
    DC count); DCs are partitioned contiguously across islands. Pass
    ``meshes`` (one per island) to shard each island's state over its
    own device subset; default leaves placement to JAX (correctness
    path — the CPU test harness).
    """

    def __init__(self, cfg: FederationConfig, n_islands: int = 2,
                 seed: int = 0, meshes: Optional[Sequence] = None,
                 link_policy: Optional[LinkPolicy] = None, sink=None):
        if cfg.n_dc % n_islands != 0:
            raise ValueError(
                f"n_dc={cfg.n_dc} must divide into {n_islands} islands"
            )
        per = cfg.n_dc // n_islands
        self.cfg = cfg
        self.n_islands = n_islands
        self.islands: list[Federation] = []
        for k in range(n_islands):
            icfg = dataclasses.replace(
                cfg, n_dc=per, n_dc_total=cfg.n_dc, dc_offset=k * per
            )
            # Same seed everywhere: the WAN plant (sites, topology) must
            # be identical across replicas; LAN worlds differ because
            # the key stream is indexed by global DC (federation.py).
            isl = Federation(icfg, seed=seed)
            # De-correlate per-tick protocol randomness between islands
            # (each replica is its own gossip universe between syncs).
            isl.base_key = jax.random.fold_in(isl.base_key, k)
            self.islands.append(isl)
        self.meshes = list(meshes) if meshes is not None else None
        if self.meshes is not None and len(self.meshes) != n_islands:
            raise ValueError(
                f"{len(self.meshes)} meshes for {n_islands} islands"
            )
        if self.meshes is not None:
            from consul_tpu.parallel import mesh as pmesh
            for isl, m in zip(self.islands, self.meshes):
                shardings = pmesh.federation_sharding(isl.state, m)
                isl.state = jax.tree.map(jax.device_put, isl.state, shardings)
        s = cfg.servers_per_dc
        self._owner = jnp.repeat(
            jnp.arange(n_islands, dtype=jnp.int32), per * s
        )  # [n_wan] owning island of each WAN row
        # The DCN fault envelope: one retry machine per directed link.
        self.link_policy = link_policy if link_policy is not None \
            else DEFAULT_LINK_POLICY
        self.sink = sink
        self._links = {
            (a, b): _LinkState()
            for a in range(n_islands) for b in range(n_islands) if a != b
        }
        self._faults: list[LinkFault] = []
        self._round = 0  # sync rounds elapsed — the link-layer clock

    # ------------------------------------------------------------------
    # Link fault envelope
    # ------------------------------------------------------------------
    def inject_link_faults(self, faults: Sequence[LinkFault]):
        """Arm a DCN fault schedule: each entry fails sends on one
        directed link for a sync-round window (chaos for the WAN tier,
        the host-side analogue of chaos/schedule.py's device tensors)."""
        self._faults = list(faults)

    def _fault_kind(self, src: int, dst: int, rnd: int) -> Optional[str]:
        for f in self._faults:
            if f.src == src and f.dst == dst and f.start <= rnd < f.stop:
                return f.kind
        return None

    def _count(self, name: str, n: int = 1):
        if self.sink is not None and n:
            self.sink.incr_counter(name, n)

    def link_state(self, src: int, dst: int) -> _LinkState:
        """The directed link's retry machine (tests + bench probes)."""
        return self._links[(src, dst)]

    def _offer(self, src: int, dst: int, payload, ticks: int) -> list:
        """Run one sync round of the (src -> dst) link: enqueue the
        fresh anti-entropy payload, then either deliver the whole
        buffered queue (link up) or count the failure and back off.
        Returns the payloads to merge at dst, oldest first (empty while
        the link is down)."""
        pol, link, rnd = self.link_policy, self._links[(src, dst)], self._round
        link.queue.append(payload)
        if len(link.queue) > pol.queue_bound:
            # Drop-oldest: anti-entropy payloads supersede each other,
            # so the newest must survive — bounding memory across an
            # arbitrarily long partition.
            dropped = len(link.queue) - pol.queue_bound
            del link.queue[:dropped]
            self._count("sim.dcn.retx_dropped", dropped)
        link.queue_peak = max(link.queue_peak, len(link.queue))

        if rnd < link.down_until:
            # Still backing off: down, not even attempting.
            self._count("sim.dcn.link_down_ticks", ticks)
            return []
        retrying = link.attempt > 0
        if retrying:
            self._count("sim.dcn.retries", 1)
        kind = self._fault_kind(src, dst, rnd)
        if kind is None:
            # Delivered: the link is (back) up — flush the buffer.
            if retrying:
                self._count("sim.dcn.heals", 1)
            link.attempt = 0
            link.degraded = False
            out, link.queue = link.queue, []
            return out
        # Failed send: classify, then bounded exponential backoff.
        if kind == "timeout":
            self._count("sim.dcn.send_timeouts", 1)
        link.attempt += 1
        if link.attempt >= pol.retry_max and not link.degraded:
            link.degraded = True
            self._count("sim.dcn.link_degraded", 1)
        backoff = min(pol.backoff_cap,
                      pol.backoff_base * (1 << min(link.attempt - 1, 16)))
        link.down_until = rnd + 1 + backoff + _jitter(src, dst, link.attempt)
        self._count("sim.dcn.link_down_ticks", ticks)
        return []

    # ------------------------------------------------------------------
    def sync(self, ticks: int = 1):
        """One DCN reconciliation round: every island receives, over
        its per-source links, the other islands' owned WAN rows
        wholesale (see module docstring) — links that are faulted or
        backing off deliver nothing this round and their payloads
        buffer in the retransmit queue instead. One device->host pull
        and one host->device push per island — the batched
        host-boundary discipline of SURVEY §7. ``ticks`` is how many
        LAN ticks this round represents (the run loop passes its sync
        cadence so ``sim.dcn.link_down_ticks`` counts modeled time)."""
        # The DCN hop: replicas live on disjoint device sets, so the
        # exchange goes through the host — one pull per island, one
        # numpy-side merge, one push per island.
        import numpy as np

        tr = obs_trace.get_tracer()
        t0_us = tr.now_us()
        wans = [jax.device_get(isl.state.wan) for isl in self.islands]
        owner = np.asarray(self._owner)

        # Per-field dispatch by NAME, not by a leading-dim shape test: a
        # [K, ...] leaf whose K coincidentally equals n_wan must never be
        # row-merged. SimState's one non-per-row field is the tick
        # counter ``t`` (models/state.py:58-91); every other field —
        # including every nested viv leaf — is [n_wan, ...], which the
        # hard error pins against future drift.
        scalar_fields = {"t"}

        def take_rows(dst_wan, src_wan, src_island):
            """Overwrite ``src_island``'s owned rows in dst's replica
            with the delivered payload's rows."""
            def sel(a, b):
                if a.shape[0] != owner.shape[0]:
                    # A hard error (not an assert, which python -O
                    # strips): a future non-per-row leaf must fail
                    # loudly here, not silently mis-broadcast.
                    raise ValueError(
                        f"per-row WAN leaf with leading dim {a.shape}"
                    )
                m = (owner == src_island).reshape(
                    (-1,) + (1,) * (a.ndim - 1))
                return np.where(m, b, a)

            return type(dst_wan)(**{
                name: (getattr(dst_wan, name) if name in scalar_fields
                       else jax.tree.map(sel, getattr(dst_wan, name),
                                         getattr(src_wan, name)))
                for name in type(dst_wan)._fields
            })

        for d, isl in enumerate(self.islands):
            merged = wans[d]
            for s in range(self.n_islands):
                if s == d:
                    continue
                for payload in self._offer(s, d, wans[s], ticks):
                    # Oldest first: a newer anti-entropy payload
                    # supersedes an older one row-for-row.
                    merged = take_rows(merged, payload, s)
            if self.meshes is not None:
                from consul_tpu.parallel import mesh as pmesh
                wan_shard = pmesh.federation_sharding(
                    isl.state, self.meshes[d]
                ).wan
                wan = jax.tree.map(jax.device_put, merged, wan_shard)
            else:
                # device_put per island: fresh buffers, so the donating
                # per-island runners never alias across replicas.
                wan = jax.tree.map(
                    lambda x: jax.device_put(jnp.asarray(x)), merged
                )
            isl.state = isl.state._replace(wan=wan)
        self._round += 1
        # Explicit timing so the round number rides along as an arg
        # (retry/backoff rounds show as consecutive dcn.sync spans).
        tr.complete("dcn.sync", t0_us, tr.now_us() - t0_us, cat="dcn",
                    args={"round": self._round, "ticks": int(ticks)})

    def run(self, lan_ticks: int, sync_every: int = 16, chunk: int = 16):
        """Advance all islands ``lan_ticks`` LAN ticks, reconciling the
        WAN tier every ``sync_every`` ticks (the DCN cadence; 16 ticks =
        3.2 s of protocol time at the 200 ms LAN tick)."""
        remaining = lan_ticks
        while remaining > 0:
            c = min(sync_every, remaining)
            for isl in self.islands:
                isl.run(c, chunk=min(chunk, c))
            self.sync(ticks=c)
            remaining -= c

    # ------------------------------------------------------------------
    def replicas_agree(self) -> bool:
        """True when every island's WAN replica is element-identical —
        what a clean (all links delivered) sync round guarantees, and
        the convergence probe a healed partition must pass."""
        import numpy as np

        wans = [jax.device_get(isl.state.wan) for isl in self.islands]
        ref_leaves = jax.tree.leaves(wans[0])
        for w in wans[1:]:
            for a, b in zip(ref_leaves, jax.tree.leaves(w)):
                if not np.array_equal(np.asarray(a), np.asarray(b)):
                    return False
        return True

    def queue_peak(self) -> int:
        """High-water retransmit-queue depth across all links (never
        exceeds ``LinkPolicy.queue_bound`` — the bound tests pin)."""
        return max((l.queue_peak for l in self._links.values()), default=0)

    # ------------------------------------------------------------------
    def island_of_dc(self, dc: int) -> tuple[Federation, int]:
        """(owning island, local dc index) for a global DC index."""
        per = self.cfg.n_dc // self.n_islands
        return self.islands[dc // per], dc % per

    def kill(self, dc: int, mask):
        isl, local = self.island_of_dc(dc)
        isl.kill(local, mask)

    def wan_status_seen_by(self, observer_dc: int, subject_dc: int,
                           observer_server: int = 0) -> list[str]:
        """How ``observer_dc``'s server sees ``subject_dc``'s servers,
        read from the OBSERVER's island replica — the cross-island
        convergence probe. Columns the observer's partial view does not
        track report "untracked"."""
        isl, _ = self.island_of_dc(observer_dc)
        s = self.cfg.servers_per_dc
        out = {}
        for m in isl.wan_members_seen_by(observer_dc, observer_server):
            if m["dc"] == f"dc{subject_dc}":
                srv = int(m["id"].split(".")[0][3:])
                out[srv] = m["status"]
        return [out.get(k, "untracked") for k in range(s)]
