"""The vectorized serf layer: Lamport time, user events, queries, leaves.

Serf sits on top of memberlist and adds cluster coordination semantics
(reference serf/serf.go): three Lamport clocks, fire-and-forget **user
events** disseminated epidemically with recent-event dedup, request/
response **queries**, graceful **leave** intents, and **reap** of
failed/left members after a timeout.

Here the whole layer is arrays over the node axis, advanced by
:func:`step` (which first advances the underlying SWIM membership tick):

  reference structure                      -> array here
  ----------------------------------------------------------------
  3 LamportClocks (serf.go:57-60)          -> clock / event_clock /
                                              query_clock  [N] uint32
  eventBroadcasts TransmitLimitedQueue     -> ev_key/ev_origin/ev_tx
    (serf.go, delegate.go GetBroadcasts)      [N, E] fixed slots
  recentIntents / eventBuffer dedup        -> ltime-bucketed buffers
    (serf.go:1860-1926, config EventBuffer)   *_bkt_lt[N,R] + *_bkt_key/
                                              origin[N,R,O], bucket =
                                              ltime % R (serf's own
                                              indexing), O origins/ltime
  query response channel + deadline        -> q_open_key/q_deadline/
    (serf/query.go acks + responses)          q_resps/q_acks [N] +
                                              q_responder[N] handler mask
  failedMembers/leftMembers reap lists     -> down_since[N, K] vs
    (serf.go:1544-1610)                       reap timeouts (derived)

Event/query payloads are modeled as an 8-bit name id; delivery is
exactly-once per node via the ltime-bucketed dedup buffer plus a
Lamport recency floor raised on bucket eviction (serf's LTime dedup +
eventMinTime gates, serf.go:1258-1357) — an event either delivers once
or, past the window, is rejected as stale; it is never double-applied.
Dedup identity is a 32-bit signature of (event key, origin)
(:func:`_sig`). Below ``_EXACT_SIG_MAX_N`` nodes the signature is an
EXACT bit-pack of (origin, name, is_query) — the ltime is deliberately
dropped because the bucket's ``*_bkt_lt`` already carries it
(the dedup state the fused core deduplicates against), so membership
is collision-free and bucket-scoped (:func:`_buf_lookup`). Above that
the pack falls back to a murmur3-finalizer avalanche whose collisions
spuriously dedup at ~2^-31 per (candidate, slot) pair — the same order
of modeled loss as the buffer-overflow drop above.
Fresh arrivals stage into the receiver's own broadcast queue (receive ≠
deliver) and deliver oldest-first at one per tick. The fused core
(:func:`step_counted`) rides event/query packets on the SAME per-tick
gossip exchange as the SWIM plane (swim._gossip_phase ``extra_tx``);
the pre-fusion two-sweep algorithm is preserved verbatim as
:func:`step_reference` for golden parity testing.
Bounded-capacity divergences (vs Go's unbounded structures): intake 2
arrivals/tick, queue eviction under pressure, ``seen_width`` concurrent
same-ltime origins per bucket.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

from consul_tpu.chaos import schedule as chaos_mod
from consul_tpu.config import SimConfig, to_ticks
from consul_tpu.models import counters as counters_mod
from consul_tpu.models import state as sim_state
from consul_tpu.models import swim
from consul_tpu.models.state import SimState
from consul_tpu.ops import lamport, merge, scaling
from consul_tpu.parallel import collective as coll
from consul_tpu.ops.topology import World

# Event key packing: uint32 = (ltime << 9) | (name & 0xff) << 1 | is_query.
_NAME_SHIFT = 1
_LTIME_SHIFT = 9

# Up to here (origin + 1) << 9 stays below bit 31, so the exact-pack
# dedup signature ((1<<31) | (origin+1)<<9 | name/is_query) is
# collision-free; larger clusters fall back to the avalanche hash.
_EXACT_SIG_MAX_N = 1 << 21


def origin_dtype(n: int):
    """Narrowest signed dtype holding every origin row id (plus the -1
    empty marker) for an ``n``-node cluster — the packed ``ev_origin``
    storage dtype. Widened to int32 at every transport/arithmetic
    boundary: parallel/collective.roll_many carries 32-bit in-flight
    lanes in both kernel modes, while the bytes that cross HBM per tick
    under ``--kernel pallas`` stay this narrow at-rest width (the
    exchange traces inside the packed-native kernel body,
    ops/pallas_gossip.py)."""
    return jnp.int16 if n <= 32767 else jnp.int32


def _tx_dtype(cfg: SimConfig):
    """Narrowest dtype for remaining-transmit counters: the retransmit
    budget is ``mult * ceil(log10(n+1))`` (ops/scaling.py) — 28 at one
    million nodes — so int8 holds any sane configuration."""
    with jax.ensure_compile_time_eval():
        lim = int(scaling.retransmit_limit(cfg.gossip.retransmit_mult, cfg.n))
    return jnp.int8 if lim <= 127 else jnp.int32


def make_event_key(ltime, name, is_query=False):
    lt = jnp.asarray(ltime, jnp.uint32)
    nm = jnp.asarray(name, jnp.uint32) & 0xFF
    q = jnp.asarray(is_query, jnp.uint32)
    return (lt << _LTIME_SHIFT) | (nm << _NAME_SHIFT) | q


def event_ltime(key):
    return jnp.asarray(key, jnp.uint32) >> _LTIME_SHIFT


def event_is_query(key):
    return (jnp.asarray(key, jnp.uint32) & 1) == 1


class SerfState(NamedTuple):
    swim: SimState
    # -- Lamport clocks (serf.go:57-60) -------------------------------
    clock: jax.Array         # [N] uint32 — membership intents
    event_clock: jax.Array   # [N] uint32
    query_clock: jax.Array   # [N] uint32
    # -- user-event/query broadcast queue (packed narrow dtypes:
    # origin_dtype(n) for origins, int8 transmit budgets — widened to
    # 32-bit at the roll_many transport boundary) ---------------------
    ev_key: jax.Array        # [N, E] uint32, 0 = empty
    ev_origin: jax.Array     # [N, E] origin_dtype(n)
    ev_tx: jax.Array         # [N, E] int8 transmits remaining
    # Staged-but-undelivered marker: the fused core delivers from this
    # bit (receive != deliver); locally-originated entries are born
    # delivered (pending=False) — they hit the dedup buffer at submit.
    ev_pending: jax.Array    # [N, E] bool
    # -- recent-event dedup buffers (ltime-bucketed; see module doc) ---
    ev_bkt_lt: jax.Array     # [N, R] uint32 ltime owning each bucket, 0=empty
    ev_bkt_sig: jax.Array    # [N, R, O] uint32 (key, origin) sigs, 0=empty
    q_bkt_lt: jax.Array      # [N, R] uint32 (queries have their own
    q_bkt_sig: jax.Array     # [N, R, O]      clock domain, so their own
                             #                buffer, like serf's)
    ev_delivered: jax.Array  # [N] int32 — distinct events delivered
    # Minimum accepted Lamport times: events/queries below the floor are
    # rejected rather than redelivered (eventMinTime/queryMinTime,
    # reference serf/serf.go); the floor rises when a bucket is evicted
    # by a newer ltime landing on it.
    ev_floor: jax.Array      # [N] uint32
    q_floor: jax.Array       # [N] uint32
    # -- outstanding queries ([N, Q] slot axis: Q concurrent queries
    # per origin, reference serf/query.go per-query QueryResponse
    # state; a query past the cap evicts the oldest-deadline slot) ----
    q_open_key: jax.Array    # [N, Q] uint32, 0 = none
    q_deadline: jax.Array    # [N, Q] int32 tick
    q_resps: jax.Array       # [N, Q] int32 responses received
    q_acks: jax.Array        # [N, Q] int32 delivery acks received (the
                             # reference's QueryParam.RequestAck stream,
                             # serf/query.go acks channel — counted
                             # separately from answers)
    # Which nodes ANSWER queries they receive (handler registration,
    # reference serf query handlers; all-true by default — every member
    # acks delivery, only responders send a response).
    q_responder: jax.Array   # [N] bool
    # -- pending graceful leaves --------------------------------------
    leave_at: jax.Array      # [N] int32 tick the node goes quiet, -1 = none
    # -- reap bookkeeping ---------------------------------------------
    down_since: jax.Array    # [N, K] int32 tick entry went dead/left, -1


def init(cfg: SimConfig, key) -> SerfState:
    n, e = cfg.n, cfg.serf.event_queue_slots
    r, o = cfg.serf.seen_ring, cfg.serf.seen_width
    return SerfState(
        swim=sim_state.init(cfg, key),
        clock=jnp.ones((n,), jnp.uint32),
        event_clock=jnp.ones((n,), jnp.uint32),
        query_clock=jnp.ones((n,), jnp.uint32),
        ev_key=jnp.zeros((n, e), jnp.uint32),
        ev_origin=jnp.full((n, e), -1, origin_dtype(n)),
        ev_tx=jnp.zeros((n, e), _tx_dtype(cfg)),
        ev_pending=jnp.zeros((n, e), bool),
        ev_bkt_lt=jnp.zeros((n, r), jnp.uint32),
        ev_bkt_sig=jnp.zeros((n, r, o), jnp.uint32),
        q_bkt_lt=jnp.zeros((n, r), jnp.uint32),
        q_bkt_sig=jnp.zeros((n, r, o), jnp.uint32),
        ev_delivered=jnp.zeros((n,), jnp.int32),
        ev_floor=jnp.zeros((n,), jnp.uint32),
        q_floor=jnp.zeros((n,), jnp.uint32),
        q_open_key=jnp.zeros((n, cfg.serf.query_slots), jnp.uint32),
        q_deadline=jnp.zeros((n, cfg.serf.query_slots), jnp.int32),
        q_resps=jnp.zeros((n, cfg.serf.query_slots), jnp.int32),
        q_acks=jnp.zeros((n, cfg.serf.query_slots), jnp.int32),
        q_responder=jnp.ones((n,), bool),
        leave_at=jnp.full((n,), -1, jnp.int32),
        down_since=jnp.full((n, cfg.degree), -1, jnp.int32),
    )


def query_timeout_ticks(cfg: SimConfig) -> int:
    """Default query timeout (reference serf/serf.go DefaultQueryTimeout):
    ``gossip_interval * QueryTimeoutMult * ceil(log10(N+1))``."""
    scale = math.ceil(math.log10(cfg.n + 1))
    return cfg.gossip.gossip_period_ticks * cfg.serf.query_timeout_mult * scale


# ----------------------------------------------------------------------
# Origination APIs (all jittable, mask-driven).
# ----------------------------------------------------------------------

def _scatter_cols(arr, cols, vals):
    """``arr[i, cols[i, j]] = vals[i, j]`` without a scatter: one-hot
    compare-select over the (small) slot axis, matching the no-scatter
    style of the round-2 gossip plane.  ``cols`` rows must hold distinct
    indices (argsort prefixes do)."""
    slots = jnp.arange(arr.shape[1], dtype=jnp.int32)
    onehot = cols[:, :, None] == slots[None, None, :]        # [N, P, S]
    newv = jnp.sum(jnp.where(onehot, vals[:, :, None], 0), axis=1)
    hit = jnp.any(onehot, axis=1)
    return jnp.where(hit, newv.astype(arr.dtype), arr)


def _equeue_push(cfg: SimConfig, s: SerfState, mask, key_, origin, tx0,
                 pending: bool = False):
    """Insert one event per masked node into its event queue — same slot
    semantics as the SWIM broadcast queue (invalidate same subject,
    else empty slot, else evict most-transmitted; queue.go:182-242).

    ``pending`` marks the entry staged-but-undelivered (intake path);
    locally-originated entries push with ``pending=False`` — their
    origin delivered them to itself at submit time.

    Returns (state, evicted[N] bool) — evicted marks nodes whose push
    displaced a *different* live entry under queue pressure (same-subject
    replacement is an update, not a drop)."""
    same = (s.ev_key == key_[:, None]) & (
        s.ev_origin.astype(jnp.int32) == origin[:, None]
    )
    # Unlike swim._queue_push, a spent (tx<=0) slot is NOT free here:
    # retirement is explicit (ev_key=0 in the step) because a spent
    # entry may still be awaiting its local delivery turn.
    empty = s.ev_key == 0
    score = (
        jnp.where(same, 3_000_000, 0)
        + jnp.where(empty, 2_000_000, 0)
        + (1_000_000 - jnp.minimum(s.ev_tx.astype(jnp.int32), 999_999))
    )
    slot = jnp.argmax(score, axis=1)
    e = cfg.serf.event_queue_slots
    onehot = (jnp.arange(e, dtype=jnp.int32)[None, :] == slot[:, None]) & mask[:, None]
    evicted = jnp.any(onehot & ~same & ~empty, axis=1)
    return s._replace(
        ev_key=jnp.where(onehot, key_[:, None], s.ev_key),
        ev_origin=jnp.where(
            onehot, origin[:, None], s.ev_origin.astype(jnp.int32)
        ).astype(s.ev_origin.dtype),
        ev_tx=jnp.where(onehot, tx0, s.ev_tx.astype(jnp.int32)).astype(
            s.ev_tx.dtype),
        ev_pending=jnp.where(onehot, pending, s.ev_pending),
    ), evicted


def _sig(cfg: SimConfig, key_, origin):
    """32-bit dedup identity of (event key, origin), 0 reserved = empty.

    Below ``_EXACT_SIG_MAX_N`` nodes: an EXACT pack of
    ``(1<<31) | (origin+1)<<9 | (name<<1 | is_query)`` — collision-free,
    and the ltime is deliberately NOT packed: the bucket's ``*_bkt_lt``
    already owns it, so carrying it per slot would duplicate dedup
    state (:func:`_buf_lookup` scopes membership to the candidate's
    bucket and guards on ``bkt_lt == ltime`` instead). Nonzero by the
    forced top bit; ``origin+1`` keeps even the -1 empty marker in
    range. Above the cap: the murmur3-finalizer avalanche of the full
    (key, origin) pair, colliding at ~2^-31 per compare — the module
    docstring's modeled-loss bound."""
    if cfg.n <= _EXACT_SIG_MAX_N:
        org = jnp.asarray(origin, jnp.int32) + 1
        low = jnp.asarray(key_, jnp.uint32) & jnp.uint32((1 << _LTIME_SHIFT) - 1)
        return (
            jnp.uint32(1 << 31)
            | (org.astype(jnp.uint32) << _LTIME_SHIFT)
            | low
        )
    h = jnp.asarray(key_, jnp.uint32) ^ (
        jnp.asarray(origin, jnp.int32).astype(jnp.uint32)
        * jnp.uint32(0x9E3779B9)
    )
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h | jnp.uint32(1)


def _buf_lookup(cfg: SimConfig, bkt_lt, bkt_sig, floor, key_, origin):
    """Is (key, origin) a duplicate/stale for its row's buffer? ``key_``
    and ``origin`` are [N, E] — E candidates per row, each checked
    against that row's own buffer.

    Mirrors the reference's buffer check (serf/serf.go:1258-1357): the
    bucket for ``ltime % R`` either records this ltime (then membership
    of (key, origin) decides, with a full bucket dropping overflow), is
    owned by a *newer* ltime (this message is outside the window), or
    the ltime is below the floor — all three reject.

    Cost shape (the serf plane's hottest path — this went through two
    rounds of on-chip whole-step A/Bs, BASELINE.md): membership is ONE
    [N, E, R·O] bool compare of the candidate sig against every slot,
    scoped to the candidate's bucket by a second compare on the flat
    slot->bucket table (``arange(R*O) // O`` — bucket-major layout) and
    guarded on ``bkt_lt == ltime``. The bucket scoping is what lets the
    exact-pack signature drop the ltime (it lives once per bucket in
    ``bkt_lt``, not per slot): a same-(name, origin) sig surviving from
    an older ltime sits under a bucket whose ``bkt_lt`` differs, so the
    guard rejects the false hit. The only per-candidate bucket selects
    are over the [N, R] bucket ltimes and a precomputed [N, R] fullness
    bit (one-hot via swim._take_cols — per-row-indexed gathers are the
    90x TPU cliff). No [N, E, R, O]-shaped intermediate survives.
    """
    r, o = cfg.serf.seen_ring, cfg.serf.seen_width
    lt = event_ltime(key_)                      # [N, E]
    b = (lt % jnp.uint32(r)).astype(jnp.int32)
    blt = swim._take_cols(bkt_lt, b)            # [N, E]
    full = swim._take_cols(jnp.all(bkt_sig != 0, axis=2), b)   # [N, E]
    flat = bkt_sig.reshape(bkt_sig.shape[0], -1)               # [N, R*O]
    slot_bucket = jnp.arange(r * o, dtype=jnp.int32) // o      # [R*O]
    hit = jnp.any(
        (flat[:, None, :] == _sig(cfg, key_, origin)[:, :, None])
        & (slot_bucket[None, None, :] == b[:, :, None]),
        axis=2,
    )
    return (hit & (blt == lt)) | (full & (blt == lt)) | (blt > lt) \
        | (lt < floor[:, None])


def _buf_apply(cfg: SimConfig, bkt_lt, bkt_sig, floor, mask, key_, origin):
    """Record one (key, origin) per masked node in its ltime buffer.

    A newer ltime landing on an occupied bucket evicts it and raises the
    Lamport floor past the evicted ltime (eventMinTime semantics) so
    evicted events are rejected as stale, never redelivered. Takeover
    clears every other slot of the bucket — the invariant
    ``_buf_lookup``'s flat membership compare relies on.
    """
    r, o = cfg.serf.seen_ring, cfg.serf.seen_width
    lt = event_ltime(key_)
    b = (lt % jnp.uint32(r)).astype(jnp.int32)
    # One-hot bucket select over the small ring axis (no per-row
    # gathers — see _buf_lookup).
    b_sel = jnp.arange(r, dtype=jnp.int32)[None, :] == b[:, None]  # [N, R]
    blt = swim._take_col(bkt_lt, b)
    takeover = mask & (blt != lt)               # empty (0) or older ltime
    evict = takeover & (blt > 0)
    floor = jnp.where(evict, jnp.maximum(floor, blt + 1), floor)

    b_oh = b_sel & mask[:, None]
    bkt_lt = jnp.where(b_oh, lt[:, None], bkt_lt)
    # Slot: 0 on takeover (clearing the rest), else first free slot.
    cur_sig = jnp.sum(
        jnp.where(b_sel[:, :, None], bkt_sig, 0), axis=1
    )                                           # [N, O]
    free = jnp.argmax(cur_sig == 0, axis=1).astype(jnp.int32)
    slot = jnp.where(takeover, 0, free)
    s_oh = (jnp.arange(o, dtype=jnp.int32)[None, :] == slot[:, None])
    new_slot_sig = jnp.where(
        s_oh, _sig(cfg, key_, origin)[:, None],
        jnp.where(takeover[:, None], 0, cur_sig),
    )
    bkt_sig = jnp.where(b_oh[:, :, None], new_slot_sig[:, None, :], bkt_sig)
    return bkt_lt, bkt_sig, floor


def _seen_append(cfg: SimConfig, s: SerfState, mask, key_, origin) -> SerfState:
    """Deliver (key, origin) to the masked nodes: record it in the
    matching (event vs query) ltime buffer and count the delivery."""
    isq = event_is_query(key_) & mask
    isev = ~event_is_query(key_) & mask
    e_lt, e_sig, e_floor = _buf_apply(
        cfg, s.ev_bkt_lt, s.ev_bkt_sig, s.ev_floor, isev, key_, origin,
    )
    q_lt, q_sig, q_floor = _buf_apply(
        cfg, s.q_bkt_lt, s.q_bkt_sig, s.q_floor, isq, key_, origin,
    )
    return s._replace(
        ev_bkt_lt=e_lt, ev_bkt_sig=e_sig, ev_floor=e_floor,
        q_bkt_lt=q_lt, q_bkt_sig=q_sig, q_floor=q_floor,
        # Counts *user events* only (queries are tallied via q_resps).
        ev_delivered=s.ev_delivered + jnp.where(isev, 1, 0),
    )


def user_event(cfg: SimConfig, s: SerfState, mask, name: int) -> SerfState:
    """Fire a user event named ``name`` from every masked node
    (reference serf/serf.go:447-505 UserEvent: stamp with the event
    clock, increment, deliver locally, queue for broadcast)."""
    mask = jnp.asarray(mask, bool)
    rows = jnp.arange(cfg.n, dtype=jnp.int32)
    key_ = make_event_key(s.event_clock, name, False)
    s = s._replace(event_clock=lamport.increment(s.event_clock, mask))
    with jax.ensure_compile_time_eval():
        tx0 = int(scaling.retransmit_limit(cfg.gossip.retransmit_mult, cfg.n))
    s, _ = _equeue_push(cfg, s, mask, key_, rows, tx0)
    return _seen_append(cfg, s, mask, key_, rows)


def query(cfg: SimConfig, s: SerfState, mask, name: int) -> SerfState:
    """Open a query from every masked node (reference serf/serf.go:510-614
    Query: stamp with the query clock, set the log-scaled deadline,
    queue for broadcast; responses tallied in ``q_resps``). The query
    takes a free slot of the origin's [Q] slot axis — concurrent
    queries from one origin each keep their own deadline and tallies
    (serf/query.go per-query QueryResponse state); past the cap the
    oldest-deadline slot is evicted."""
    mask = jnp.asarray(mask, bool)
    rows = jnp.arange(cfg.n, dtype=jnp.int32)
    q = cfg.serf.query_slots
    key_ = make_event_key(s.query_clock, name, True)
    # Slot pick: any free slot (0) wins, else the earliest deadline.
    free = s.q_open_key == 0
    score = jnp.where(free, jnp.iinfo(jnp.int32).max, -s.q_deadline)
    slot = jnp.argmax(score, axis=1)
    oh = (jnp.arange(q, dtype=jnp.int32)[None, :] == slot[:, None]) \
        & mask[:, None]
    s = s._replace(
        query_clock=lamport.increment(s.query_clock, mask),
        q_open_key=jnp.where(oh, key_[:, None], s.q_open_key),
        q_deadline=jnp.where(
            oh, s.swim.t + query_timeout_ticks(cfg), s.q_deadline
        ),
        q_resps=jnp.where(oh, 0, s.q_resps),
        q_acks=jnp.where(oh, 0, s.q_acks),
    )
    with jax.ensure_compile_time_eval():
        tx0 = int(scaling.retransmit_limit(cfg.gossip.retransmit_mult, cfg.n))
    s, _ = _equeue_push(cfg, s, mask, key_, rows, tx0)
    return _seen_append(cfg, s, mask, key_, rows)


def leave(cfg: SimConfig, s: SerfState, mask) -> SerfState:
    """Graceful departure of the masked nodes (reference serf/serf.go:675
    Leave: broadcast a leave intent at the next membership Lamport time;
    memberlist marks the member left rather than failed). The leaver's
    own-fact flips to LEFT (models/state.py own_key) and its own-fact
    broadcast re-arms, so the intent gossips out for
    ``leave_propagate_delay`` (reference lib/serf.go:21-25) before the
    node goes quiet at ``leave_at``; LEFT outranks DEAD in the merge
    lattice (see ops/merge.py), so the departure never reads as a
    failure once the intent lands."""
    mask = jnp.asarray(mask, bool)
    sw = s.swim
    with jax.ensure_compile_time_eval():
        tx0 = int(scaling.retransmit_limit(cfg.gossip.retransmit_mult, cfg.n))
    sw = sw._replace(
        leaving=sw.leaving | mask,
        own_tx=jnp.where(mask, tx0, sw.own_tx),
    )
    delay = to_ticks(cfg.serf.leave_propagate_delay_ms, cfg.gossip.tick_ms)
    return s._replace(
        swim=sw,
        clock=lamport.increment(s.clock, mask),
        leave_at=jnp.where(mask, sw.t + delay, s.leave_at),
    )


# ----------------------------------------------------------------------
# The serf tick.
# ----------------------------------------------------------------------

def step(cfg: SimConfig, topo, world: World, s: SerfState, key,
         sched=None, *, sentinel: bool = False) -> SerfState:
    """One serf tick. Thin wrapper over :func:`step_counted` — XLA dead-
    code-eliminates the unused counter reductions, so existing callers
    pay nothing for them."""
    return step_counted(cfg, topo, world, s, key, sched,
                        sentinel=sentinel)[0]


def step_counted(cfg: SimConfig, topo, world: World, s: SerfState, key,
                 sched=None, *, sentinel: bool = False):
    """One FUSED serf tick: the event/query plane rides the SAME per-tick
    gossip exchange as the SWIM probe/ack plane (swim._gossip_phase
    ``extra_tx`` hook) instead of running a second full sweep over the
    view. Sender-side selection (the top-``piggyback_events`` queue
    entries by remaining budget) happens before the membership tick;
    the packets roll with the membership gossip; intake, delivery,
    budget decrement and the query-response tally run after. Returns
    (SerfState, GossipCounters) — the SWIM tick's counters plus the
    serf intent-queue tallies. ``sched`` (optional chaos schedule, see
    swim.step_counted) gates the fused legs — one drop draw per leg
    covers both planes (they share the packet). ``sentinel``
    additionally validates the serf plane's Lamport clocks (monotone
    within the tick — they only move through lamport.witness) on top
    of the SWIM-plane checks (swim._sentinel_check).

    Pre-fusion algorithm preserved as :func:`step_reference_counted`
    (golden parity, tests/test_serf_fused.py). Documented 1-tick
    divergences vs the reference step: sends are selected from the
    pre-tick queue (the reference selects post-delivery), and a node
    whose oldest staged entry went stale delivers nothing that tick
    instead of skipping to the next fresh entry."""
    k_swim, k_ev = jax.random.split(key)
    t = s.swim.t
    chaos_on = sched is not None and not chaos_mod.is_empty(sched)
    clocks0 = (s.clock, s.event_clock, s.query_clock)

    # ---- Sender-side selection: most-retransmittable queue entries,
    # chosen BEFORE the membership tick so they ride its gossip rolls.
    # Static argmax peeling instead of lax.top_k (sort-lowered on TPU)
    # — pe is tiny and the peel is pure compare-select; selection is
    # identical to top_k's (max value, lowest index on ties). The
    # narrow queue dtypes widen here: roll_many's in-flight lanes are
    # 32-bit in both kernel modes (HBM traffic under --kernel pallas is
    # the packed at-rest bytes — the widening lives in VMEM only; see
    # parallel/collective.roll_many and ops/pallas_gossip.py).
    pe = cfg.serf.piggyback_events
    e_slots = cfg.serf.event_queue_slots
    slots_i = jnp.arange(e_slots, dtype=jnp.int32)
    peel_tx, m_tx_l, order_l = s.ev_tx.astype(jnp.int32), [], []
    for _ in range(pe):
        best = jnp.argmax(peel_tx, axis=1).astype(jnp.int32)
        m_tx_l.append(jnp.max(peel_tx, axis=1))
        order_l.append(best)
        peel_tx = jnp.where(
            slots_i[None, :] == best[:, None], jnp.iinfo(jnp.int32).min,
            peel_tx,
        )
    m_tx = jnp.stack(m_tx_l, axis=1)
    order = jnp.stack(order_l, axis=1)
    m_key = swim._take_cols(s.ev_key, order)
    m_origin = swim._take_cols(s.ev_origin, order).astype(jnp.int32)
    # No activity gate here: the per-sender liveness gate lives in the
    # gossip phase (ex_sendable — inactive senders reach zero legs, so
    # their budgets never decrement either).
    m_valid = (m_key > 0) & (m_tx > 0)

    sw, cnt, (ex_legs, ex_n_sends) = swim.step_counted(
        cfg, topo, world, s.swim, k_swim, sched, sentinel=sentinel,
        extra_tx=[m_key, m_origin, m_valid],
    )
    terms = chaos_mod.node_terms(sched, t) if chaos_on else None
    # Pending graceful leaves whose propagate window closed go quiet now
    # (serf.Leave sleeps LeavePropagateDelay then shuts memberlist down).
    quiet = (s.leave_at >= 0) & (sw.t >= s.leave_at)
    sw = sw._replace(left=sw.left | quiet)
    s = s._replace(swim=sw, leave_at=jnp.where(quiet, -1, s.leave_at))
    active = sw.alive_truth & ~sw.left

    s, (n_queued, n_retx, n_dropped) = _fused_event_post(
        cfg, topo, s, active, k_ev, ex_legs, ex_n_sends,
        m_tx, order, m_valid,
        sched if chaos_on else None, terms,
    )
    cnt = cnt._replace(
        serf_intents_queued=n_queued,
        serf_intents_retx=n_retx,
        serf_intents_dropped=n_dropped,
    )

    # Query expiry: past-deadline slots close (serf/query.go Deadline),
    # elementwise over the [N, Q] slot axis.
    expired = (s.q_open_key > 0) & (sw.t >= s.q_deadline)
    s = s._replace(q_open_key=jnp.where(expired, 0, s.q_open_key))

    # Reap bookkeeping: ticks since each view entry went down
    # (failed/left member lists, serf.go:1544-1610).
    st = merge.key_status(sw.view_key)
    is_down = (st == merge.DEAD) | (st == merge.LEFT)
    down_since = jnp.where(
        is_down & (s.down_since < 0), t, jnp.where(is_down, s.down_since, -1)
    )
    s = s._replace(down_since=down_since)
    if sentinel:
        # Lamport monotonicity: every clock plane only moves through
        # lamport.witness (a max), so a within-tick regression is
        # corruption. Folds into the same counter the SWIM-plane
        # incarnation check uses.
        regress = sum(
            counters_mod.count(after < before)
            for before, after in zip(
                clocks0, (s.clock, s.event_clock, s.query_clock))
        )
        cnt = cnt._replace(
            sentinel_monotonic=cnt.sentinel_monotonic + regress)
    return s, cnt


def step_reference(cfg: SimConfig, topo, world: World, s: SerfState, key,
                   sched=None, *, sentinel: bool = False) -> SerfState:
    """Pre-fusion serf tick (counter-free wrapper); see
    :func:`step_reference_counted`."""
    return step_reference_counted(cfg, topo, world, s, key, sched,
                                  sentinel=sentinel)[0]


def step_reference_counted(cfg: SimConfig, topo, world: World, s: SerfState,
                           key, sched=None, *, sentinel: bool = False):
    """The PRE-FUSION serf tick: the SWIM membership tick first, then
    the event/query plane as a second full sweep over the view
    (_event_phase_ref — the algorithm :func:`step_counted` replaced).
    Kept verbatim (modulo the packed queue dtypes, which widen at the
    same boundaries) as the golden reference for the fused-vs-legacy
    parity suite: same seed, same SWIM trajectory — the fused step must
    reproduce its delivered-event sets, Lamport floors and SLO
    counters. Not a production path; no compile-ledger pin covers it."""
    k_swim, k_ev = jax.random.split(key)
    t = s.swim.t
    chaos_on = sched is not None and not chaos_mod.is_empty(sched)
    clocks0 = (s.clock, s.event_clock, s.query_clock)
    sw, cnt = swim.step_counted(cfg, topo, world, s.swim, k_swim, sched,
                                sentinel=sentinel)
    terms = chaos_mod.node_terms(sched, t) if chaos_on else None
    quiet = (s.leave_at >= 0) & (sw.t >= s.leave_at)
    sw = sw._replace(left=sw.left | quiet)
    s = s._replace(swim=sw, leave_at=jnp.where(quiet, -1, s.leave_at))
    active = sw.alive_truth & ~sw.left

    s, (n_queued, n_retx, n_dropped) = _event_phase_ref(
        cfg, topo, s, active, k_ev,
        sched if chaos_on else None, terms,
    )
    cnt = cnt._replace(
        serf_intents_queued=n_queued,
        serf_intents_retx=n_retx,
        serf_intents_dropped=n_dropped,
    )

    expired = (s.q_open_key > 0) & (sw.t >= s.q_deadline)
    s = s._replace(q_open_key=jnp.where(expired, 0, s.q_open_key))

    st = merge.key_status(sw.view_key)
    is_down = (st == merge.DEAD) | (st == merge.LEFT)
    down_since = jnp.where(
        is_down & (s.down_since < 0), t, jnp.where(is_down, s.down_since, -1)
    )
    s = s._replace(down_since=down_since)
    if sentinel:
        regress = sum(
            counters_mod.count(after < before)
            for before, after in zip(
                clocks0, (s.clock, s.event_clock, s.query_clock))
        )
        cnt = cnt._replace(
            sentinel_monotonic=cnt.sentinel_monotonic + regress)
    return s, cnt


def _lookup_any(cfg: SimConfig, s: SerfState, key_, origin):
    """Duplicate/stale check against the kind-matching buffer; ``key_``
    and ``origin`` are [N, E] candidates per row."""
    seen_ev = _buf_lookup(
        cfg, s.ev_bkt_lt, s.ev_bkt_sig, s.ev_floor, key_, origin,
    )
    seen_q = _buf_lookup(
        cfg, s.q_bkt_lt, s.q_bkt_sig, s.q_floor, key_, origin,
    )
    return jnp.where(event_is_query(key_), seen_q, seen_ev)


def _query_response_tally(cfg: SimConfig, topo, s: SerfState, active,
                          worig, wkey, isq, grows, k_resp,
                          sched=None, terms=None) -> SerfState:
    """Query responses: the deliverer answers the origin directly (one
    response per node per query — exactly-once via the dedup buffer;
    serf/query.go respondTo). Direct packet: origin must be up, the
    packet must survive loss, and the query must still be open.
    With ``query_relay_factor`` > 0, each responder also relays
    duplicate copies through that many random members
    (serf.go relayResponse :244, QueryParam.RelayFactor): a copy
    arrives if its relay is up and BOTH legs survive loss, so the
    response lands unless the direct packet and every relayed copy
    drop. The tally counts each responder once (duplicates are deduped
    by the origin in the reference; q_resps is that deduped count).

    This block is the serf plane's only row-addressed all-to-all (two
    gathers by the delivered entry's origin + two scatter-add tallies
    — the TPU-costly ops). Single-chip it is gated behind ``lax.cond``
    on "any query open anywhere": an event-only epidemic (the common
    workload) pays nothing for the query machinery. Under sharding the
    block stays unconditional — a collective inside data-dependent
    control flow is not safely partitionable, and the collective
    budget census pins the unconditional counts."""
    n, k_deg = cfg.n, cfg.degree

    def tally(s):
        pl = cfg.packet_loss
        u_resp = coll.uniform_rows(k_resp, n)
        rf = cfg.serf.query_relay_factor
        if sched is not None:
            # The response targets an arbitrary origin row: its chaos
            # terms come off the same globally-visible copies the open-
            # query keys do (coll.all_rows + row-addressed read).
            og = chaos_mod.NodeTerms(
                *(coll.all_rows(x)[worig] for x in terms)
            )
            arrived = chaos_mod.pair_ok(sched, terms, og, u_resp, pl)
        else:
            arrived = u_resp >= pl
        if rf > 0 and (sched is not None or pl > 0.0):
            k_relay = jax.random.fold_in(k_resp, 1)
            k_rl1, k_rl2, k_rcol = jax.random.split(k_relay, 3)
            u1 = coll.uniform_rows(k_rl1, n, (rf,))
            u2 = coll.uniform_rows(k_rl2, n, (rf,))
            rcols = jax.random.randint(k_rcol, (rf,), 0, k_deg)
            relay_up = jnp.stack(
                [coll.roll(active, -topo.off[rcols[i]]) for i in range(rf)],
                axis=1,
            )
            if sched is not None:
                legs = []
                for i in range(rf):
                    rt = chaos_mod.roll_terms(terms, -topo.off[rcols[i]])
                    leg1 = chaos_mod.pair_ok(sched, terms, rt, u1[:, i], pl)
                    leg2 = chaos_mod.pair_ok(sched, rt, og, u2[:, i], pl)
                    legs.append(leg1 & leg2)
                relayed = jnp.stack(legs, axis=1)
            else:
                relayed = (u1 >= pl) & (u2 >= pl)
            arrived = arrived | jnp.any(relay_up & relayed, axis=1)
        # The origin is an arbitrary global row: its liveness and
        # open-query keys come from the globally-visible copies, and
        # the tally is a row-addressed all-to-all delivery (under
        # sharding: all_gather + reduce-scatter). The response lands
        # in the [Q] slot whose open key matches the query being
        # answered — concurrent queries from one origin tally
        # independently (serf/query.go per-query QueryResponse state).
        q_open_g = coll.all_rows(s.q_open_key)             # [N, Q]
        up_g = coll.all_rows(s.swim.alive_truth & ~s.swim.left)
        slot_hit = q_open_g[worig] == wkey[:, None]        # [N, Q]
        landed = (
            isq
            & arrived
            & up_g[worig]
            & (worig != grows)  # origin's own delivery happened at submit
            # External (bridge) seats never ack/answer on-device: their
            # REAL agent does, over the wire, and the bridge tallies
            # that one — counting the seat's row too would double-count
            # every attached agent (wire/bridge.py _stage_qtally).
            & ~s.swim.external
        )
        # Ack vs response (serf/query.go acks/responses channels):
        # every delivering member acks; only registered responders
        # answer. Two [N, Q] tallies, two reduce-scatters under
        # sharding (the collective budget test pins this count and the
        # Q-wide payload).
        landed_slot = landed[:, None] & slot_hit
        resp_slot = landed_slot & s.q_responder[:, None]
        return s._replace(
            q_resps=s.q_resps + coll.sum_scatter_rows(
                worig, jnp.where(resp_slot, 1, 0).astype(s.q_resps.dtype),
                n),
            q_acks=s.q_acks + coll.sum_scatter_rows(
                worig, jnp.where(landed_slot, 1, 0).astype(s.q_acks.dtype),
                n),
        )

    if coll.sharded() or coll.in_kernel():
        # Sharded: collectives can't sit inside data-dependent control
        # flow. Kernel body: Mosaic can't branch around a pytree
        # operand. Both run the tally unconditionally — with no open
        # query anywhere every landed mask is false (an open-slot key
        # is always > 0, a closed slot 0, so no delivered wkey can
        # match), the scatter adds zeros, and the result is
        # bit-identical to the cond's pass-through branch.
        return tally(s)
    return jax.lax.cond(jnp.any(s.q_open_key > 0), tally, lambda s: s, s)


def _fused_event_post(cfg: SimConfig, topo, s: SerfState, active, key,
                      ex_legs, ex_n_sends, m_tx, order, m_valid,
                      sched=None, terms=None):
    """Post-gossip half of the fused event plane: delivery, budget
    decrement, intake, query tally. Single-chip, an IDLE event plane
    costs (almost) zero: with no queued event anywhere and no open
    query, every mask in the body is false and the state passes
    through — the whole block rides one ``lax.cond`` on "any traffic
    at all" (the fused legs still rolled a few all-zero lanes with the
    membership packets — the only idle cost left). Under sharding the
    body runs unconditionally: its collectives cannot sit inside
    data-dependent control flow, and the budget census pins them.

    Returns (state, (queued[] i32, retransmits[] i32, drops[] i32)) —
    the idle branch returns zeros of the same structure so both cond
    branches match."""
    if coll.sharded() or coll.in_kernel():
        # Unconditional body in both cases (collectives under sharding,
        # no pytree-operand branching under Mosaic); an idle plane's
        # masks are all false so the body IS the pass-through — the
        # sharded==single-device parity suite already pins exactly this
        # equivalence, and the kernel parity suite re-pins it.
        return _fused_event_post_body(
            cfg, topo, s, active, key, ex_legs, ex_n_sends, m_tx, order,
            m_valid, sched, terms)
    busy = jnp.any(s.ev_key > 0) | jnp.any(s.q_open_key > 0)
    z = jnp.zeros((), jnp.int32)
    return jax.lax.cond(
        busy,
        lambda st: _fused_event_post_body(
            cfg, topo, st, active, key, ex_legs, ex_n_sends, m_tx, order,
            m_valid, sched, terms),
        lambda st: (st, (z, z, z)),
        s,
    )


def _fused_event_post_body(cfg: SimConfig, topo, s: SerfState, active, key,
                           ex_legs, ex_n_sends, m_tx, order, m_valid,
                           sched=None, terms=None):
    """Deliver → decrement/retire → intake, consuming the fused legs.

    ``ex_legs`` is swim._gossip_phase's extra-plane output: per leg,
    the rolled (key, origin, valid) payload of this receiver's sender
    plus the leg's arrival mask (loss/chaos/receiver-liveness already
    applied — the packets shared the membership plane's draws).
    ``ex_n_sends`` counts each sender's delivered legs; ``m_tx`` /
    ``order`` / ``m_valid`` are the sender-side selection the budget
    decrement must mirror (selected pre-tick, see step_counted).

    Delivery runs off the ``ev_pending`` bit (receive != deliver): the
    oldest staged-undelivered entry per node delivers each tick, after
    a staleness re-check against the dedup buffer — the floor may have
    risen (bucket eviction) or a duplicate delivered since staging; a
    stale winner is dropped (pending cleared) without delivering.
    Entries retire (ev_key=0) once their budget is spent AND they are
    not pending — a spent undelivered entry survives to deliver."""
    n = cfg.n
    e_slots = cfg.serf.event_queue_slots
    slots_i = jnp.arange(e_slots, dtype=jnp.int32)
    grows = coll.rows(n)                      # global ids (identity)
    k_resp = key
    sentinel = jnp.uint32(0xFFFFFFFF)
    with jax.ensure_compile_time_eval():
        tx_limit = int(scaling.retransmit_limit(cfg.gossip.retransmit_mult, n))

    # ---- 1. Deliver: oldest staged-undelivered entry of the own queue.
    pend = s.ev_pending & (s.ev_key > 0) & active[:, None]
    del_key = jnp.min(jnp.where(pend, s.ev_key, sentinel), axis=1)
    has = del_key != sentinel
    slot_match = pend & (s.ev_key == del_key[:, None])
    del_slot = jnp.argmax(slot_match, axis=1)
    del_origin = swim._take_col(s.ev_origin, del_slot).astype(jnp.int32)
    wkey = jnp.where(has, del_key, 0)
    worig = jnp.where(has, del_origin, 0)
    stale = _lookup_any(cfg, s, wkey[:, None], worig[:, None])[:, 0]
    deliver = has & ~stale
    s = _seen_append(cfg, s, deliver, wkey, worig)
    lt = event_ltime(wkey)
    isq = event_is_query(wkey) & deliver
    isev = ~event_is_query(wkey) & deliver
    s = s._replace(
        event_clock=lamport.witness(s.event_clock, lt, isev),
        query_clock=lamport.witness(s.query_clock, lt, isq),
    )
    s = _query_response_tally(cfg, topo, s, active, worig, wkey, isq,
                              grows, k_resp, sched, terms)
    # The winner's pending bit clears whether it delivered or proved
    # stale (a stale entry must not win the min again next tick).
    cleared = (slots_i[None, :] == del_slot[:, None]) & has[:, None]
    ev_pending = s.ev_pending & ~cleared

    # ---- 2. Budget decrement by the fused plane's actual sends, then
    # retire spent delivered entries.
    sends = ex_n_sends[:, None] * jnp.where(m_valid, 1, 0)
    ev_tx = _scatter_cols(s.ev_tx, order, jnp.maximum(m_tx - sends, 0))
    retire = (ev_tx <= 0) & ~ev_pending
    s = s._replace(
        ev_tx=ev_tx,
        ev_key=jnp.where(retire, 0, s.ev_key),
        ev_pending=ev_pending,
    )

    # ---- 3. Intake: stage up to 2 fresh arrivals off the fused legs.
    cand_key, cand_orig = [], []
    for payload, ex_arrived in ex_legs:
        r_key, r_orig, r_valid = payload
        ok = ex_arrived[:, None] & r_valid
        cand_key.append(jnp.where(ok, r_key, 0))
        cand_orig.append(jnp.where(ok, r_orig, -1))
    ckey = jnp.concatenate(cand_key, axis=1)       # [N, fan*PE]
    corig = jnp.concatenate(cand_orig, axis=1)
    fresh = (ckey > 0) & ~_lookup_any(cfg, s, ckey, corig)
    n_queued = jnp.zeros((), jnp.int32)
    n_dropped = jnp.zeros((), jnp.int32)
    for _ in range(2):
        win_key = jnp.min(jnp.where(fresh, ckey, sentinel), axis=1)
        got = win_key != sentinel
        slot_i = jnp.argmax(fresh & (ckey == win_key[:, None]), axis=1)
        win_orig = swim._take_col(corig, slot_i)
        s, evicted = _equeue_push(
            cfg, s, got, jnp.where(got, win_key, 0),
            jnp.where(got, win_orig, -1), tx_limit, pending=True,
        )
        n_queued = n_queued + counters_mod.count(got)
        n_dropped = n_dropped + counters_mod.count(evicted)
        taken = (ckey == win_key[:, None]) & (corig == win_orig[:, None]) \
            & got[:, None]
        fresh = fresh & ~taken
    n_retx = jnp.sum(sends).astype(jnp.int32)
    return s, (n_queued, n_retx, n_dropped)


def _event_phase_ref(cfg: SimConfig, topo, s: SerfState, active, key,
                     sched=None, terms=None):
    """Pre-fusion event phase (the second-sweep algorithm), kept for
    :func:`step_reference_counted`. Single-chip the whole phase rides
    one ``lax.cond`` on "any traffic at all"; under sharding the body
    runs unconditionally (collectives cannot sit inside data-dependent
    control flow).

    Returns (state, (queued[] i32, retransmits[] i32, drops[] i32)) —
    the idle branch returns zeros of the same structure so both cond
    branches match."""
    if coll.sharded():
        return _event_phase_body_ref(cfg, topo, s, active, key, sched, terms)
    busy = jnp.any(s.ev_key > 0) | jnp.any(s.q_open_key > 0)
    z = jnp.zeros((), jnp.int32)
    return jax.lax.cond(
        busy,
        lambda st: _event_phase_body_ref(cfg, topo, st, active, key, sched,
                                         terms),
        lambda st: (st, (z, z, z)),
        s,
    )


def _event_phase_body_ref(cfg: SimConfig, topo, s: SerfState, active, key,
                          sched=None, terms=None):
    """Receive → queue → deliver pipeline for user events and queries.

    Receiving and delivering are decoupled, as in the reference (every
    arriving message is handled; rebroadcast rides the same queue,
    serf/delegate.go NotifyMsg → rebroadcast): fresh arrivals are
    *staged into the receiver's own event queue* (which doubles as the
    rebroadcast buffer), and each node *delivers* from its queue — the
    oldest not-yet-delivered entry per tick, keeping Lamport order for
    the eviction floor. Without the staging queue, an event arriving in
    a busy tick would be dropped and lost once the sender's retransmit
    budget drained (the reference never loses an accepted packet).
    Intake is capped at 2 stages/tick and delivery at 1/tick; queue
    capacity pressure can evict (bounded-memory divergence, noted in
    the module docstring).

    Delivery is receiver-side over per-tick shared displacements, like
    the SWIM gossip plane (models/swim.py): each receiver *rolls in*
    its senders' chosen events — no scatters. The only scatter left in
    the serf layer is the per-tick [N] query-response tally add (the
    response targets an arbitrary origin — coll.sum_scatter_rows).
    """
    n, k_deg = cfg.n, cfg.degree
    pe, fan = cfg.serf.piggyback_events, cfg.gossip.gossip_nodes
    e_slots = cfg.serf.event_queue_slots
    grows = coll.rows(n)                      # global ids (identity)
    k_cols, k_loss, k_resp = jax.random.split(key, 3)
    sentinel = jnp.uint32(0xFFFFFFFF)
    with jax.ensure_compile_time_eval():
        tx_limit = int(scaling.retransmit_limit(cfg.gossip.retransmit_mult, n))

    # ---- 1. Deliver: oldest not-yet-delivered entry of the own queue.
    q_fresh = (
        (s.ev_key > 0)
        & ~_lookup_any(cfg, s, s.ev_key, s.ev_origin)
        & active[:, None]
    )                                           # [N, E]
    del_key = jnp.min(jnp.where(q_fresh, s.ev_key, sentinel), axis=1)
    has = del_key != sentinel
    # The matching slot with the lowest index (ties share key+origin
    # only if the queue holds a same-origin duplicate, which
    # _equeue_push's same-subject replacement prevents).
    slot_match = q_fresh & (s.ev_key == del_key[:, None])
    del_slot = jnp.argmax(slot_match, axis=1)
    del_origin = swim._take_col(s.ev_origin, del_slot).astype(jnp.int32)
    wkey = jnp.where(has, del_key, 0)
    worig = jnp.where(has, del_origin, 0)

    s = _seen_append(cfg, s, has, wkey, worig)
    lt = event_ltime(wkey)
    isq = event_is_query(wkey) & has
    isev = ~event_is_query(wkey) & has
    s = s._replace(
        event_clock=lamport.witness(s.event_clock, lt, isev),
        query_clock=lamport.witness(s.query_clock, lt, isq),
    )

    s = _query_response_tally(cfg, topo, s, active, worig, wkey, isq,
                              grows, k_resp, sched, terms)

    # ---- 2. Gossip out: most-retransmittable queue entries, sent along
    # per-tick shared displacements (swim-plane divergence note).
    # Static argmax peeling instead of lax.top_k (sort-lowered on TPU)
    # — pe is tiny and the peel is pure compare-select; selection is
    # identical to top_k's (max value, lowest index on ties). One-hot
    # column selects throughout (the no-gather style; argsort +
    # take_along_axis gathers are the TPU cliff — BASELINE.md).
    peel_tx, m_tx_l, order_l = s.ev_tx.astype(jnp.int32), [], []
    slots_i = jnp.arange(e_slots, dtype=jnp.int32)
    for _ in range(pe):
        best = jnp.argmax(peel_tx, axis=1).astype(jnp.int32)
        m_tx_l.append(jnp.max(peel_tx, axis=1))
        order_l.append(best)
        peel_tx = jnp.where(
            slots_i[None, :] == best[:, None], jnp.iinfo(jnp.int32).min,
            peel_tx,
        )
    m_tx = jnp.stack(m_tx_l, axis=1)
    order = jnp.stack(order_l, axis=1)
    m_key = swim._take_cols(s.ev_key, order)
    m_origin = swim._take_cols(s.ev_origin, order).astype(jnp.int32)
    m_valid = (m_key > 0) & (m_tx > 0) & active[:, None]

    jcols = jax.random.randint(k_cols, (fan,), 0, k_deg)
    peer_status = merge.key_status(s.swim.view_key[:, jcols])   # [N, fan]
    peer_ok = (
        ((peer_status == merge.ALIVE) | (peer_status == merge.SUSPECT))
        & active[:, None]
    )

    # Decrement transmit budgets by actual sends. A slot retires when
    # its budget is spent AND its payload was delivered locally (a spent
    # undelivered entry must survive to be delivered from the queue).
    sends = jnp.sum(peer_ok, axis=1)[:, None] * jnp.where(m_valid, 1, 0)
    ev_tx = _scatter_cols(s.ev_tx, order, jnp.maximum(m_tx - sends, 0))
    delivered_now = (
        jnp.arange(e_slots, dtype=jnp.int32)[None, :] == del_slot[:, None]
    ) & has[:, None]
    still_fresh = q_fresh & ~delivered_now
    retire = (ev_tx <= 0) & ~still_fresh
    s = s._replace(ev_tx=ev_tx, ev_key=jnp.where(retire, 0, s.ev_key))

    # ---- 3. Intake (receiver-side): roll in each displacement-sender's
    # chosen events, then stage up to 2 fresh arrivals per receiver.
    # One exchange per displacement via coll.roll_many (separate fused
    # rolls single-chip; one packed ppermute sharded), as in the SWIM
    # plane.
    recv_up = s.swim.alive_truth & ~s.swim.left
    u_drop = coll.uniform_rows(k_loss, n, (fan,))
    pl = cfg.packet_loss
    tpack = chaos_mod.pack_terms(terms) if sched is not None else []
    cand_key, cand_orig = [], []
    for f in range(fan):
        shift = topo.off[jcols[f]]
        rolled = coll.roll_many(
            [m_key, m_origin, m_valid, peer_ok[:, f]] + tpack, shift
        )
        s_key, s_orig, s_valid, s_peer = rolled[:4]
        if sched is not None:
            s_terms = chaos_mod.unpack_terms(rolled[4:])
            ok_leg = chaos_mod.pair_ok(sched, s_terms, terms, u_drop[:, f], pl)
        else:
            ok_leg = u_drop[:, f] >= pl
        arrived = s_peer & ok_leg & recv_up
        ok = arrived[:, None] & s_valid
        cand_key.append(jnp.where(ok, s_key, 0))
        cand_orig.append(jnp.where(ok, s_orig, -1))
    ckey = jnp.concatenate(cand_key, axis=1)       # [N, fan*PE]
    corig = jnp.concatenate(cand_orig, axis=1)
    fresh = (ckey > 0) & ~_lookup_any(cfg, s, ckey, corig)
    n_queued = jnp.zeros((), jnp.int32)
    n_dropped = jnp.zeros((), jnp.int32)
    for _ in range(2):
        win_key = jnp.min(jnp.where(fresh, ckey, sentinel), axis=1)
        got = win_key != sentinel
        slot_i = jnp.argmax(fresh & (ckey == win_key[:, None]), axis=1)
        win_orig = swim._take_col(corig, slot_i)
        s, evicted = _equeue_push(
            cfg, s, got, jnp.where(got, win_key, 0),
            jnp.where(got, win_orig, -1), tx_limit, pending=True,
        )
        n_queued = n_queued + counters_mod.count(got)
        n_dropped = n_dropped + counters_mod.count(evicted)
        taken = (ckey == win_key[:, None]) & (corig == win_orig[:, None]) \
            & got[:, None]
        fresh = fresh & ~taken
    n_retx = jnp.sum(sends).astype(jnp.int32)
    return s, (n_queued, n_retx, n_dropped)


# ----------------------------------------------------------------------
# Inspection.
# ----------------------------------------------------------------------

def query_slot(s: SerfState, row: int, key: int) -> int:
    """Host-side: which [Q] slot of ``row`` holds the open query
    ``key``; -1 when closed or stale (the bridge's drop-stale gate,
    serf/query.go checking the query is still registered)."""
    import numpy as np
    slots = np.asarray(s.q_open_key[row])
    hits = np.nonzero(slots == np.uint32(key))[0]
    return int(hits[0]) if hits.size else -1


def newest_query_slot(s: SerfState, row: int) -> int:
    """Host-side: the origin's most recently opened slot (highest
    Lamport time); -1 when none open."""
    import numpy as np
    slots = np.asarray(s.q_open_key[row])
    if not (slots != 0).any():
        return -1
    lts = np.where(slots != 0, slots >> _LTIME_SHIFT, 0)
    return int(np.argmax(lts))

def event_coverage(cfg: SimConfig, s: SerfState, key_, origin) -> jax.Array:
    """Fraction of active nodes whose dedup buffer holds (key, origin) —
    the "did the event reach everyone" question serf's convergence
    simulator answers (lib/serf.go:21-25 comment). Under the exact-pack
    signature this aliases same-(name, origin) events across ltimes —
    coverage probes should use distinct (name, origin) pairs (the
    bucket-scoped dedup in :func:`_buf_lookup` does NOT alias; only
    this whole-buffer membership sweep does)."""
    active = s.swim.alive_truth & ~s.swim.left
    key_ = jnp.asarray(key_, jnp.uint32)
    bkt_sig = jnp.where(event_is_query(key_), s.q_bkt_sig, s.ev_bkt_sig)
    got = jnp.any(bkt_sig == _sig(cfg, key_, origin), axis=(1, 2))
    return jnp.sum(got & active) / jnp.maximum(jnp.sum(active), 1)


class MemberCounts(NamedTuple):
    alive: jax.Array    # [N] int32 — per-observer counts over its view
    suspect: jax.Array
    dead: jax.Array     # failed, not yet reaped
    left: jax.Array     # gracefully left, not yet reaped
    reaped: jax.Array   # removed from member lists


def member_counts(cfg: SimConfig, s: SerfState) -> MemberCounts:
    """Per-observer membership roll-up with reap semantics applied:
    failed members vanish after ``reconnect_timeout``, left members
    after ``tombstone_timeout`` (reference serf/serf.go:1544-1568 reap)."""
    g = cfg.gossip
    st = merge.key_status(s.swim.view_key)
    t = s.swim.t
    down_ticks = jnp.where(s.down_since >= 0, t - s.down_since, 0)
    reconnect_ticks = to_ticks(cfg.serf.reconnect_timeout_ms, g.tick_ms)
    tombstone_ticks = to_ticks(cfg.serf.tombstone_timeout_ms, g.tick_ms)
    reaped = ((st == merge.DEAD) & (down_ticks > reconnect_ticks)) | (
        (st == merge.LEFT) & (down_ticks > tombstone_ticks)
    )

    def count(mask):
        return jnp.sum(mask & ~reaped, axis=1).astype(jnp.int32)

    return MemberCounts(
        alive=count(st == merge.ALIVE),
        suspect=count(st == merge.SUSPECT),
        dead=count(st == merge.DEAD),
        left=count(st == merge.LEFT),
        reaped=jnp.sum(reaped, axis=1).astype(jnp.int32),
    )
