"""Simulation state machines: SWIM membership, serf layer, cluster drivers."""
