"""RaftPlane: the host tier of the device raft subsystem.

The device half lives in ``ops/raft_ops.py`` — R groups × P peers of
term/role/log tensors stepped inside the same jitted scan as SWIM/serf
(models/cluster.py threads the :class:`~consul_tpu.ops.raft_ops.
RaftState` through the chunk carry). This module owns everything that
must NOT live in the scan: proposal intake, the commit-point pump that
turns quorum-committed entries into real write applies, and the counter
fold into the telemetry sink.

Commit contract (the tentpole): with a write-attached serving plane,
``WriteBatcher._run_batch`` routes batches here (:meth:`stage`) instead
of applying immediately. Each batch becomes one proposal ticket on a
raft group; the device's per-group commit index advances only when a
quorum of that group's peers holds the entries; and :meth:`pump`
(called from the sim's chunk boundary, right before the serving
republish) applies exactly the tickets whose entries sit inside the
committed prefix — through the batcher's real apply kernel, so the
device apply index (``X-Consul-Index``) moves ONLY at commit. A write
acknowledged with an index therefore survives leader loss by
construction: the index existing means a quorum held the entry, and
the election up-to-date rule forbids any candidate without it from
winning (the leader-kill drill pins this end to end).

Proposals are intent-based (see raft_ops module docstring): propose()
bumps the group's ``next_seq`` and every current leader appends until
its log carries that many client entries, so entries stranded on a
deposed leader re-propose automatically and the k-th committed client
entry of a group is always proposal k — ticket completion is a pure
comparison of the committed-client count against the ticket's end
sequence, no entry ids shipped to the device.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.analysis import ledger
from consul_tpu.config import RaftConfig
from consul_tpu.obs import trace as obs_trace
from consul_tpu.ops import raft_ops

# Folded into the sim's base key for the initial timeout draws — keeps
# raft init independent of topology/state init splits, and gives the
# lockstep oracle (server/raft.py) the same concrete init key.
_INIT_SALT = 40961

# A per-group term jump of at least this many terms between two pumps
# marks an election storm (split votes burning through terms faster
# than single back-to-back timeouts) — surfaced as a flight-recorder
# instant so storms are visible on the trace timeline.
STORM_TERM_JUMP = 3


class RaftTicket:
    """One staged proposal batch: ``ops`` are (op, target, arg) write
    triples, ``end_seq`` the group's client-entry sequence after this
    batch. ``done`` fires at commit with ``results`` holding the real
    per-op WriteResults (quorum-committed indexes)."""

    __slots__ = ("ops", "group", "end_seq", "done", "results", "error")

    def __init__(self, ops, group: int, end_seq: int):
        self.ops = list(ops)
        self.group = group
        self.end_seq = end_seq
        self.done = threading.Event()
        self.results = None
        self.error: Optional[Exception] = None

    def wait(self, timeout_s: float = 30.0):
        if not self.done.wait(timeout_s):
            raise TimeoutError(
                f"raft group {self.group} did not commit seq "
                f"{self.end_seq} in {timeout_s}s")
        if self.error is not None:
            raise self.error
        return self.results


def init_key_of(sim) -> jax.Array:
    """The raft init key for a sim — shared with the parity oracle."""
    return jax.random.fold_in(sim.base_key, _INIT_SALT)


class RaftPlane:
    """Host companion of the in-scan raft tier (built by
    ``Simulation.set_raft``). Holds the live RaftState between chunks,
    the proposal ticket queues, and the cumulative counter dict."""

    def __init__(self, sim, rcfg: RaftConfig):
        self.sim = sim
        self.rcfg = rcfg
        self.state = raft_ops.init(rcfg, init_key_of(sim))
        self.counters = {f: 0 for f in raft_ops.FIELDS}
        self._pending_vecs: list = []
        self._lock = ledger.make_lock("RaftPlane._lock")
        self._tickets = [deque() for _ in range(rcfg.groups)]
        self._next_seq = [0] * rcfg.groups
        self._rr = 0
        self._writes = None  # WriteBatcher applying committed tickets
        self._last_term = np.zeros(rcfg.groups, np.int64)
        self._summary = jax.jit(raft_ops.summary)
        # Host-side intent bumps, folded into the device ``next_seq``
        # at the next chunk dispatch (take_state) — never touching a
        # possibly-donated buffer from a proposer thread.
        self._bumps = np.zeros(rcfg.groups, np.int32)

    # ------------------------------------------------------------------
    # Proposal intake
    # ------------------------------------------------------------------
    def propose(self, ops: Sequence[tuple], group: Optional[int] = None
                ) -> RaftTicket:
        """Stage one batch of write triples on a raft group (round-robin
        by default). Returns the ticket; the entries land in the next
        leader tick and the ticket completes at quorum commit."""
        with self._lock:
            if group is None:
                group = self._rr
                self._rr = (self._rr + 1) % self.rcfg.groups
            group = int(group)
            self._next_seq[group] += len(ops)
            tk = RaftTicket(ops, group, self._next_seq[group])
            self._tickets[group].append(tk)
            self._bumps[group] += len(ops)
        return tk

    def take_state(self):
        """The RaftState to feed the next chunk, with any pending
        proposal intents folded in (one eager [R] add — no traced
        scatter, one executable per shape)."""
        with self._lock:
            bumps = self._bumps.copy() if self._bumps.any() else None
            if bumps is not None:
                self._bumps[:] = 0
        # the jnp.asarray transfer happens outside the lock: proposers
        # must not serialize behind a device round-trip. take_state is
        # only called from the single chunk-driver thread, so the
        # unlocked state swap has exactly one writer.
        if bumps is not None:
            self.state = self.state._replace(
                next_seq=self.state.next_seq + jnp.asarray(bumps))
        return self.state

    def stage(self, batcher, ops: Sequence[tuple]) -> list:
        """WriteBatcher gate: turn an apply-now batch into a proposal.
        Returns provisional ``proposed`` results immediately (the
        batcher's synchronous contract); the REAL results — with
        quorum-committed apply indexes — land on the ticket at commit,
        applied through ``batcher._apply_batch``."""
        from consul_tpu.serving.writes import WriteResult

        self._writes = batcher
        self.propose(ops)
        return [WriteResult(applied=False, index=-1, status="proposed")
                for _ in ops]

    @property
    def inflight(self) -> int:
        with self._lock:
            return sum(len(q) for q in self._tickets)

    # ------------------------------------------------------------------
    # Commit pump (chunk boundary, before the serving republish)
    # ------------------------------------------------------------------
    def pump(self) -> int:
        """Fold pending counters, read the per-group commit frontier
        (one jitted summary + one small device_get), and apply every
        ticket whose entries are quorum-committed. Returns the number
        of tickets applied."""
        self.flush_counters()
        with obs_trace.span("raft.step", cat="raft",
                            args={"groups": self.rcfg.groups}):
            term_g, leader_g, commit_g, cc = jax.device_get(
                self._summary(self.state))
        jump = term_g.astype(np.int64) - self._last_term
        if np.any(jump >= STORM_TERM_JUMP) and np.any(self._last_term > 0):
            obs_trace.get_tracer().instant(
                "raft.election_storm", cat="raft",
                args={"max_jump": int(jump.max()),
                      "terms": [int(x) for x in term_g]})
        self._last_term = term_g.astype(np.int64)
        sink = getattr(self.sim, "sink", None)
        if sink is not None:
            sink.set_gauge("consul.raft.commitIndex", int(commit_g.max()))
        applied = 0
        for r in range(self.rcfg.groups):
            while True:
                with self._lock:
                    q = self._tickets[r]
                    if not q or q[0].end_seq > int(cc[r]):
                        break
                    tk = q.popleft()
                applied += 1
                with obs_trace.span("raft.commit", cat="raft",
                                    args={"group": r, "n": len(tk.ops),
                                          "commit": int(commit_g[r])}):
                    try:
                        if self._writes is not None:
                            tk.results = self._writes._apply_batch(tk.ops)
                        else:
                            from consul_tpu.serving.writes import WriteResult

                            tk.results = [
                                WriteResult(applied=True,
                                            index=int(commit_g[r]),
                                            status="committed")
                                for _ in tk.ops]
                    except Exception as e:  # surface on the waiter
                        tk.error = e
                tk.done.set()
        return applied

    # ------------------------------------------------------------------
    # Counters (the Simulation._flush_counters discipline)
    # ------------------------------------------------------------------
    def absorb(self, rcnt) -> None:
        """Queue one chunk's RaftCounters pytree for a lazy batched
        flush (no device sync on the hot path)."""
        vec = raft_ops.counters_stack(rcnt)
        with self._lock:
            self._pending_vecs.append(vec)

    def flush_counters(self) -> None:
        with self._lock:
            if not self._pending_vecs:
                return
            vecs, self._pending_vecs = self._pending_vecs, []
        # device_get of the queued vectors stays outside the lock
        vals = np.sum(np.stack(jax.device_get(vecs)), axis=0)
        deltas = {f: int(v) for f, v in zip(raft_ops.FIELDS, vals)}
        sink = getattr(self.sim, "sink", None)
        for f, v in deltas.items():
            self.counters[f] += v
            if v and sink is not None:
                sink.incr_counter(raft_ops.METRIC_NAMES[f], v)

    def counters_snapshot(self) -> dict:
        self.flush_counters()
        return dict(self.counters)

    # ------------------------------------------------------------------
    # Introspection
    # ------------------------------------------------------------------
    def summary(self) -> dict:
        """Per-group host view: terms, leader ids (-1 = none), commit
        indexes, committed client-entry counts."""
        term_g, leader_g, commit_g, cc = jax.device_get(
            self._summary(self.state))
        return {
            "terms": [int(x) for x in term_g],
            "leaders": [int(x) for x in leader_g],
            "commit": [int(x) for x in commit_g],
            "committed_clients": [int(x) for x in cc],
        }
