"""Serf gossip snapshot: append-only member-event log + replay rejoin.

Mirrors the reference Snapshotter (reference serf/snapshot.go:59-431):
each agent streams its membership events and Lamport clock values to an
append-only file in the reference's exact line format —

    alive: <name> <addr>\\n        (:328)
    not-alive: <name>\\n           (:336)
    clock: <n>\\n                  (:349)
    event-clock: <n>\\n            (:360)
    query-clock: <n>\\n            (:370)
    leave\\n                       (:274)

compacts the file once it outgrows ``min_compact_size`` (rewrite as the
current alive set + clock floors, :431-479, default 128 KiB), and on
restart replays it to recover the previously-known alive nodes
(``PreviousNode``) and clock floors, which seed a *warm* rejoin
(handleRejoin, serf.go:1705) instead of the blind join-address storm a
cold restart needs.

TPU mapping: a real serf agent snapshots the event stream it observes;
here the observer is one **monitored seat** of the simulated world, and
its event stream is derived from its device view row on chunk
boundaries — one batched device→host diff per observe() call, the same
host-boundary budget as the coordinate batching precedent (SURVEY §7).
``rejoin`` is then ``state.revive`` upgraded with replayed knowledge:
view entries toward the recorded alive nodes start as contactable
``(0, ALIVE)`` join seeds (many seeds ⇒ probes/push-pull/gossip reopen
across the whole neighborhood immediately), and the node's Lamport
clocks are witnessed forward to the recorded floors so stale events are
never re-delivered (the eventMinTime guarantee, serf.go:1258-1357).
"""

from __future__ import annotations

import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from consul_tpu.config import SimConfig
from consul_tpu.models import serf as serf_mod
from consul_tpu.models import state as sim_state
from consul_tpu.ops import lamport, merge


def _seat_name(i: int) -> str:
    return f"sim-{i}"


class Snapshotter:
    """Append-only event log for one monitored seat of the simulation."""

    def __init__(self, path: str, node: int,
                 min_compact_size: int = 128 * 1024,
                 rejoin_after_leave: bool = False):
        self.path = path
        self.node = node
        self.min_compact_size = min_compact_size
        self.rejoin_after_leave = rejoin_after_leave
        # Prime the transition state from the existing file — the
        # reference replays on open (NewSnapshotter -> replay) so a
        # reopened log keeps appending *transitions*, never re-appends
        # the world, and compaction can never regress clock floors.
        prior = replay(path, rejoin_after_leave=True)
        self._last_alive: dict[str, str] = dict(prior.alive)
        self._clocks = {"clock": prior.clock,
                        "event-clock": prior.event_clock,
                        "query-clock": prior.query_clock}
        self._off_np = None  # host offset table, cached on first observe
        self._fh = open(path, "a", encoding="utf-8")
        self.offset = self._fh.tell()

    # -- recording -----------------------------------------------------
    def _append(self, line: str):
        self._fh.write(line)
        self._fh.flush()
        self.offset += len(line.encode())
        if self.offset > self.min_compact_size:
            self.compact()

    def observe(self, cfg: SimConfig, topo, serf_state) -> None:
        """Record the monitored seat's membership transitions + clock
        advances since the last call. One batched device→host fetch per
        call — call on chunk boundaries.

        The transition state (``_last_alive``/``_clocks``) is mutated
        entry-by-entry *before* each append: ``_append`` can trigger
        compaction at any point, and compaction writes the current
        transition state — writing it stale would discard the very
        transitions just logged (the reference mutates then appends in
        the same per-event order, snapshot.go:322-370)."""
        s = serf_state
        if self._off_np is None:
            self._off_np = np.asarray(topo.off)
        off = self._off_np
        nd = self.node
        # One fused device gather: view row + the three clock scalars.
        fetched = np.asarray(jnp.concatenate([
            s.swim.view_key[nd].astype(jnp.uint32),
            jnp.stack([s.clock[nd], s.event_clock[nd], s.query_clock[nd]]),
        ]))
        row, clocks = fetched[:off.shape[0]], fetched[off.shape[0]:]
        statuses = row & (merge.N_STATUS - 1)
        n = cfg.n
        now_alive = {}
        for c in range(off.shape[0]):
            j = (nd + int(off[c])) % n
            if statuses[c] == merge.ALIVE:
                now_alive[_seat_name(j)] = f"{_seat_name(j)}:7946"
        for name, addr in now_alive.items():
            if name not in self._last_alive:
                self._last_alive[name] = addr
                self._append(f"alive: {name} {addr}\n")
        for name in list(self._last_alive):
            if name not in now_alive:
                del self._last_alive[name]
                self._append(f"not-alive: {name}\n")
        for key, v in zip(("clock", "event-clock", "query-clock"),
                          (int(clocks[0]), int(clocks[1]), int(clocks[2]))):
            if v > self._clocks[key]:
                self._clocks[key] = v
                self._append(f"{key}: {v}\n")

    def leave(self):
        """Record an intentional departure: replay then starts from
        scratch unless rejoin_after_leave (snapshot.go:271-279)."""
        self._append("leave\n")

    def close(self):
        self._fh.close()

    # -- compaction (snapshot.go:431-479) ------------------------------
    def compact(self):
        tmp = self.path + ".compact"
        with open(tmp, "w", encoding="utf-8") as out:
            for name, addr in sorted(self._last_alive.items()):
                out.write(f"alive: {name} {addr}\n")
            out.write(f"clock: {self._clocks['clock']}\n")
            out.write(f"event-clock: {self._clocks['event-clock']}\n")
            out.write(f"query-clock: {self._clocks['query-clock']}\n")
        self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        self.offset = self._fh.tell()


class Replay:
    """Recovered state from a snapshot file (snapshot.go replay loop
    :481-431 region: alive/not-alive/clock/leave lines)."""

    def __init__(self, alive: dict[str, str], clock: int, event_clock: int,
                 query_clock: int, left: bool):
        self.alive = alive
        self.clock = clock
        self.event_clock = event_clock
        self.query_clock = query_clock
        self.left = left

    @property
    def previous_nodes(self) -> list[tuple[str, str]]:
        return sorted(self.alive.items())


def replay(path: str, rejoin_after_leave: bool = False) -> Replay:
    alive: dict[str, str] = {}
    clocks = {"clock": 0, "event-clock": 0, "query-clock": 0}
    left = False
    if os.path.exists(path):
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.rstrip("\n")
                if line.startswith("alive: "):
                    parts = line[len("alive: "):].rsplit(" ", 1)
                    if len(parts) == 2:
                        alive[parts[0]] = parts[1]
                elif line.startswith("not-alive: "):
                    alive.pop(line[len("not-alive: "):], None)
                elif line == "leave":
                    left = True
                    if not rejoin_after_leave:
                        alive.clear()
                        clocks = dict.fromkeys(clocks, 0)
                else:
                    for key in clocks:
                        if line.startswith(key + ": "):
                            try:
                                clocks[key] = max(clocks[key],
                                                  int(line[len(key) + 2:]))
                            except ValueError:
                                pass  # torn tail line (crash mid-append)
                            break
    return Replay(alive, clocks["clock"], clocks["event-clock"],
                  clocks["query-clock"], left)


def rejoin(cfg: SimConfig, topo, serf_state, node: int, rep: Replay):
    """Warm restart of ``node`` from a replayed snapshot: revive with
    join seeds at every previously-known-alive neighbor (not the cold
    path's blind handful), and witness the Lamport clocks forward to
    the recorded floors (handleRejoin serf.go:1705 + the clock recovery
    of snapshot.go)."""
    s = serf_state
    n = cfg.n
    mask_np = np.zeros(n, bool)
    mask_np[node] = True
    mask = jnp.asarray(mask_np)
    off = np.asarray(topo.off)
    seed_cols = []
    known = set(rep.alive)
    for c in range(off.shape[0]):
        j = (node + int(off[c])) % n
        if _seat_name(j) in known:
            seed_cols.append(c)
    if not seed_cols:
        # Empty replay (fresh file, or a recorded leave without
        # rejoin_after_leave): nothing to seed from — fall back to the
        # configured join addresses, exactly like the reference, whose
        # restart without a usable snapshot is a plain Join()
        # (memberlist.go:228). Zero seeds would deadlock the node
        # (revive docstring).
        return s._replace(
            swim=sim_state.revive(cfg, s.swim, mask, cold=True))
    # Cold wipe (the process restarted; its memory is the file), then
    # seed (0, ALIVE) toward every replayed alive node in the view.
    new_swim = sim_state.revive(cfg, s.swim, mask, cold=True, join_seeds=0)
    row = np.full(off.shape[0], merge.UNKNOWN, np.uint32)
    row[np.asarray(seed_cols)] = merge.make_key_int(0, merge.ALIVE)
    new_swim = new_swim._replace(
        view_key=new_swim.view_key.at[node].set(jnp.asarray(row)))
    # Clock floors: stale events (ltime <= floor) must never redeliver.
    def witness(arr, floor):
        return lamport.witness(arr, jnp.uint32(floor), mask)

    return s._replace(
        swim=new_swim,
        clock=witness(s.clock, rep.clock),
        event_clock=witness(s.event_clock, rep.event_clock),
        query_clock=witness(s.query_clock, rep.query_clock),
        ev_floor=s.ev_floor.at[node].max(jnp.uint32(rep.event_clock)),
    )
