"""Cluster simulation driver: scan-compiled runs + convergence detection.

The host-side equivalent of the reference's test harness idioms — boot an
in-process cluster, inject faults, poll until convergence with a deadline
(reference sdk/testutil/retry/retry.go:89-166, testrpc/wait.go:14-62) —
except the "cluster" is one jitted ``lax.scan`` over the SWIM step and
polling is a device-side metrics trace.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from consul_tpu.config import SimConfig
from consul_tpu.models import serf as serf_mod
from consul_tpu.models import state as sim_state
from consul_tpu.models import swim
from consul_tpu.ops import topology
from consul_tpu.utils import metrics, telemetry


class TickTrace(NamedTuple):
    """Per-tick metrics emitted by a scan chunk (host-fetched once per
    chunk — batched device->host transfer, the coordinate-batching
    precedent of reference agent/consul/coordinate_endpoint.go:42-53)."""

    agreement: jax.Array       # [C] f32
    false_positive: jax.Array  # [C] f32
    undetected: jax.Array      # [C] f32
    rmse: jax.Array            # [C] f32


def _chunk_runner(cfg: SimConfig, topo, world, chunk: int, with_metrics: bool,
                  step_fn=swim.step, swim_of=lambda st: st):
    """One compiled chunk program. ``step_fn`` is the per-tick step
    (bare SWIM or the full serf stack); ``swim_of`` projects the SWIM
    plane out of the step's state for metrics."""
    def body(state, tick_key):
        state = step_fn(cfg, topo, world, state, tick_key)
        if not with_metrics:
            return state, ()
        sw = swim_of(state)
        h = metrics.health(cfg, topo, sw)
        rmse = metrics.vivaldi_rmse(
            cfg, world, sw, jax.random.fold_in(tick_key, 1), samples=2048
        )
        return state, TickTrace(h.agreement, h.false_positive, h.undetected, rmse)

    def run(state, base_key):
        ticks = swim_of(state).t + jnp.arange(chunk)
        tick_keys = jax.vmap(lambda t: jax.random.fold_in(base_key, t))(ticks)
        return jax.lax.scan(body, state, tick_keys)

    return jax.jit(run, donate_argnums=(0,))


@dataclasses.dataclass
class Simulation:
    """Owns the world, topology, and device state for one simulated DC."""

    cfg: SimConfig
    seed: int = 0

    # Driver hooks (SerfSimulation overrides these two).
    _step_fn = staticmethod(swim.step)
    _swim_of = staticmethod(lambda st: st)

    def _init_state(self, key):
        return sim_state.init(self.cfg, key)

    def __post_init__(self):
        key = jax.random.PRNGKey(self.seed)
        kw, kn, ks, kb = jax.random.split(key, 4)
        self.world = topology.make_world(self.cfg, kw)
        self.topo = topology.make_topology(self.cfg, kn)
        self.state = self._init_state(ks)
        self.base_key = kb
        self._runners = {}
        self._warmed: set = set()
        # Reference-named metrics recorded on chunk boundaries
        # (telemetry.emit_sim_metrics); served by /v1/agent/metrics and
        # the debug bundle.
        self.sink = telemetry.Sink()

    # -- fault injection ------------------------------------------------
    def kill(self, mask):
        self.state = sim_state.kill(self.state, jnp.asarray(mask))

    def revive(self, mask):
        self.state = sim_state.revive(self.cfg, self.state, jnp.asarray(mask))

    # -- execution ------------------------------------------------------
    def _runner(self, chunk: int, with_metrics: bool):
        k = (chunk, with_metrics)
        if k not in self._runners:
            self._runners[k] = _chunk_runner(
                self.cfg, self.topo, self.world, chunk, with_metrics,
                step_fn=type(self)._step_fn, swim_of=type(self)._swim_of,
            )
        return self._runners[k]

    def run(self, ticks: int, chunk: int = 64, with_metrics: bool = True):
        """Advance ``ticks`` ticks; returns the concatenated TickTrace
        (or None when metrics are disabled for pure-throughput runs)."""
        traces = []
        remaining = ticks
        while remaining > 0:
            c = min(chunk, remaining)
            t0 = time.perf_counter()
            self.state, trace = self._runner(c, with_metrics)(self.state, self.base_key)
            if with_metrics:
                # Block before reading the clock: the jitted runner
                # returns on async dispatch, not completion.
                jax.block_until_ready(trace)
                traces.append(trace)
                self._record_chunk(trace, c, t0)
            remaining -= c
        if not with_metrics:
            return None
        return jax.tree.map(lambda *xs: jnp.concatenate(xs), *traces)

    def _record_chunk(self, trace: TickTrace, ticks: int, t0: float):
        """Fold one chunk's trace into the telemetry sink under the
        reference metric names (the batched host-boundary equivalent of
        the reference's per-operation instrumentation). The first run
        of each program shape compiles; its wall time would poison the
        timing aggregates forever, so it is recorded without timing
        (throughput() warms for the same reason)."""
        key = (ticks, True)
        if key in self._warmed:
            wall_s: Optional[float] = time.perf_counter() - t0
        else:
            self._warmed.add(key)
            wall_s = None
        h = metrics.HealthMetrics(
            agreement=trace.agreement[-1],
            false_positive=trace.false_positive[-1],
            undetected=trace.undetected[-1],
            live_nodes=jnp.int32(0),
        )
        telemetry.emit_sim_metrics(
            self.swim_state, self.sink,
            health=h, rmse_s=float(trace.rmse[-1]),
            rounds_per_sec=(ticks / wall_s if wall_s else None),
            chunk_wall_s=wall_s, chunk_ticks=ticks,
            serf_state=self.serf_state,
            queue_depth_warning=self.cfg.serf.queue_depth_warning,
        )

    def run_until_converged(
        self,
        max_ticks: int,
        chunk: int = 64,
        rmse_target_s: Optional[float] = None,
        require_agreement: float = 1.0,
        stable_chunks: int = 1,
    ):
        """Run until membership agreement (and optionally Vivaldi RMSE)
        hold for ``stable_chunks`` consecutive chunks. Returns
        (converged: bool, ticks_used: int, last_trace).

        The retry.Run-with-deadline idiom of the reference test suite.
        """
        used = 0
        streak = 0
        trace = None
        while used < max_ticks:
            c = min(chunk, max_ticks - used)
            t0 = time.perf_counter()
            self.state, trace = self._runner(c, True)(self.state, self.base_key)
            jax.block_until_ready(trace)
            self._record_chunk(trace, c, t0)
            used += c
            ok = float(trace.agreement[-1]) >= require_agreement
            if ok and rmse_target_s is not None:
                ok = float(trace.rmse[-1]) <= rmse_target_s
            streak = streak + 1 if ok else 0
            if streak >= stable_chunks:
                return True, used, trace
        return False, used, trace

    def throughput(self, ticks: int = 256) -> float:
        """Measured gossip rounds (ticks) per wall-clock second.

        Warmup runs the *same* compiled program as the timed region, so
        XLA compilation never lands inside the measurement.
        """
        runner = self._runner(ticks, False)
        self.state, _ = runner(self.state, self.base_key)
        jax.block_until_ready(self.swim_state.view_key)
        t0 = time.perf_counter()
        self.state, _ = runner(self.state, self.base_key)
        jax.block_until_ready(self.swim_state.view_key)
        return ticks / (time.perf_counter() - t0)

    # -- inspection -----------------------------------------------------
    def health(self) -> metrics.HealthMetrics:
        return metrics.health(self.cfg, self.topo, self.swim_state)

    def rmse(self, seed: int = 99) -> float:
        return float(metrics.vivaldi_rmse(
            self.cfg, self.world, self.swim_state, jax.random.PRNGKey(seed)))

    # -- uniform SWIM-state accessors (the transport bridge and other
    # host components work on the SWIM plane regardless of whether the
    # driver runs bare SWIM or the full serf stack) --------------------
    @property
    def swim_state(self) -> sim_state.SimState:
        return self.state

    def set_swim_state(self, st: sim_state.SimState):
        self.state = st

    @property
    def serf_state(self):
        return None  # bare-SWIM driver has no serf plane


@dataclasses.dataclass
class SerfSimulation(Simulation):
    """The full-stack driver: serf.step (SWIM + events + queries +
    reap) instead of the bare SWIM step. Same chunked-scan execution,
    metrics, and telemetry via the base driver's hooks; adds the
    serf-layer verbs."""

    _step_fn = staticmethod(serf_mod.step)
    _swim_of = staticmethod(lambda st: st.swim)

    def _init_state(self, key):
        return serf_mod.init(self.cfg, key)

    # -- serf verbs -----------------------------------------------------
    def user_event(self, mask, name: int):
        self.state = serf_mod.user_event(self.cfg, self.state,
                                         jnp.asarray(mask), name)

    def query(self, mask, name: int):
        self.state = serf_mod.query(self.cfg, self.state,
                                    jnp.asarray(mask), name)

    def leave(self, mask):
        self.state = serf_mod.leave(self.cfg, self.state, jnp.asarray(mask))

    def kill(self, mask):
        self.state = self.state._replace(
            swim=sim_state.kill(self.state.swim, jnp.asarray(mask)))

    def revive(self, mask):
        self.state = self.state._replace(
            swim=sim_state.revive(self.cfg, self.state.swim,
                                  jnp.asarray(mask)))

    @property
    def swim_state(self) -> sim_state.SimState:
        return self.state.swim

    def set_swim_state(self, st: sim_state.SimState):
        self.state = self.state._replace(swim=st)

    @property
    def serf_state(self):
        return self.state
