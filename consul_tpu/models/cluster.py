"""Cluster simulation driver: scan-compiled runs + convergence detection.

The host-side equivalent of the reference's test harness idioms — boot an
in-process cluster, inject faults, poll until convergence with a deadline
(reference sdk/testutil/retry/retry.go:89-166, testrpc/wait.go:14-62) —
except the "cluster" is one jitted ``lax.scan`` over the SWIM step and
polling is a device-side metrics trace.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.chaos import schedule as chaos_mod
from consul_tpu.config import SimConfig
from consul_tpu.models import counters as counters_mod
from consul_tpu.models import layout as layout_mod
from consul_tpu.models import serf as serf_mod
from consul_tpu.models import state as sim_state
from consul_tpu.models import swim
from consul_tpu.obs import lens as lens_obs
from consul_tpu.obs import trace as obs_trace
from consul_tpu.ops import topology
from consul_tpu.parallel import mesh as pmesh
from consul_tpu.utils import checkpoint as ckpt_mod
from consul_tpu.utils import metrics, telemetry


class TickTrace(NamedTuple):
    """Per-tick metrics emitted by a scan chunk (host-fetched once per
    chunk — batched device->host transfer, the coordinate-batching
    precedent of reference agent/consul/coordinate_endpoint.go:42-53)."""

    agreement: jax.Array       # [C] f32
    false_positive: jax.Array  # [C] f32
    undetected: jax.Array      # [C] f32
    rmse: jax.Array            # [C] f32


# Stable serialization names for the chaos SLO counters — the `chaos`
# keys bench.py emits and future PRs regress against. Keys match the
# sink metric suffixes (models/counters.py METRIC_NAMES sim.chaos.*).
SLO_KEYS = {
    "chaos_fault_ticks": "fault_ticks",
    "chaos_first_suspect_wait": "time_to_first_suspect",
    "chaos_confirm_wait": "time_to_confirm",
    "chaos_heal_wait": "time_to_heal",
    "chaos_false_deaths": "false_positive_deaths",
    "chaos_msgs_dropped": "messages_dropped",
}


class ScenarioResult(NamedTuple):
    """What one run_scenario replay measured: ``slo`` is the stable-key
    view of the chaos counters (SLO_KEYS), ``counters`` the full
    protocol-event deltas over the window, ``trace`` the TickTrace when
    metrics were on."""

    slo: dict
    counters: dict
    ticks: int
    trace: object


def _topo_key(topo) -> tuple:
    """Hashable fingerprint of a Topology's compile-time content. The
    offset/remap tables are read *concretely* during tracing (static
    roll shifts, models/swim.py _gather_by_col), so they are part of
    the program's identity, not runtime inputs."""
    return (
        topo.n, topo.dense, np.asarray(topo.off).tobytes(),
        None if topo.rcol is None else np.asarray(topo.rcol).tobytes(),
        None if topo.inv is None else np.asarray(topo.inv).tobytes(),
    )


_RUNNER_CACHE: dict = {}


class SentinelViolation(RuntimeError):
    """An on-device invariant sentinel tripped (models/swim.py
    _sentinel_check): the simulation state violated a protocol
    invariant. Carries the violation bitmask (bit i =
    counters.SENTINEL_FIELDS[i]), the offending counter deltas, and
    the path of the diagnostic checkpoint dumped before raising (None
    when no dump directory was configured)."""

    def __init__(self, mask: int, deltas: dict, dump_path=None):
        self.mask = mask
        self.deltas = {
            f: deltas.get(f, 0) for f in counters_mod.SENTINEL_FIELDS
        }
        self.dump_path = dump_path
        tripped = [f for f in counters_mod.SENTINEL_FIELDS
                   if deltas.get(f, 0)]
        where = f"; diagnostic checkpoint: {dump_path}" if dump_path else ""
        super().__init__(
            f"invariant sentinel tripped (mask {mask:#x}): "
            + ", ".join(f"{f}={deltas.get(f, 0)}" for f in tripped)
            + where
        )


def _chunk_runner(cfg: SimConfig, topo, chunk: int, with_metrics: bool,
                  step_fn=swim.step_counted, swim_of=lambda st: st,
                  chaos_key=None, sentinel: bool = False, mesh=None,
                  layout: str = layout_mod.DENSE, lens: tuple = (),
                  clock_of=None, raft=None, kernel: str = "xla"):
    """One compiled chunk program. ``step_fn`` is the per-tick counted
    step (bare SWIM or the full serf stack) returning
    (state, GossipCounters); ``swim_of`` projects the SWIM plane out of
    the step's state for metrics. The counters ride the scan carry and
    come back as one [] i32 pytree per chunk — the single extra
    device→host fetch the tentpole budgets for.

    Programs are memoized process-wide on (cfg, topology content,
    chunk, with_metrics, step, chaos shape): the world AND the fault
    schedule enter as program *arguments* rather than baked constants,
    so two simulations over the same topology (same seed, or any
    dense-mode pair) share one executable instead of paying XLA twice,
    and any two schedules with the same slot counts
    (chaos.static_key_of) share the chaos-enabled one. ``chaos_key``
    None is the schedule-free program — the runner is then always
    called with ``sched=None`` (Simulation.set_chaos normalizes empty
    schedules away) so its jit cache never grows past one entry. The
    topology itself stays closed over — its tables feed trace-time
    static roll shifts.

    ``sentinel`` joins the memo key exactly like ``chaos_key``: off is
    the pre-sentinel program byte-for-byte (zero extra executables —
    the compile-count pin), on folds the invariant validator in and
    compiles exactly one more program per shape.

    ``mesh`` selects the multi-chip program: a shard_map runner over
    the device grid (parallel/shard_step.make_sharded_chunk_runner)
    with the SAME call convention. The mesh fingerprint — axis names,
    shape AND device ids (parallel/mesh.mesh_key) — joins the memo key,
    so an elastic 8->4 reshard can never reuse the stale 8-device
    executable; each surviving-mesh shape compiles (or persistent-cache
    loads) exactly one program.

    ``layout`` selects the at-rest state encoding (models/layout.py):
    ``"packed"`` carries the compact PackedSimState through the scan —
    the body unpacks to the dense working set, steps, and re-packs, so
    the resident footprint (and the donated carry) is the 2.5x-smaller
    packed form while the step math is unchanged. The dense program is
    byte-for-byte the pre-layout one (the compile-count pin).

    ``lens`` (a static node-id tuple, empty = off) threads the
    on-device node lens (obs/lens.py) through the scan: each tick
    gathers one [S, F] row at the static ids and the chunk returns a
    stacked [C, S, F] buffer as a fourth result. Empty follows the
    ``sentinel``/``layout`` DCE contract — the program (and the return
    arity) is byte-for-byte the pre-lens one, so toggling the lens off
    compiles nothing. ``clock_of`` projects the serf Lamport clock out
    of the step's state for the lens (None under bare SWIM).

    ``raft`` (a config.RaftConfig, None = off) steps the batched raft
    tier (ops/raft_ops.tick) inside the same scan: the carry becomes
    ``((state, RaftState), (GossipCounters, RaftCounters))`` and the
    runner takes/returns the state PAIR in the donated slot. None
    follows the sentinel/lens DCE contract — byte-for-byte the
    pre-raft program, zero extra executables.

    ``kernel`` selects the tick execution engine: ``"xla"`` is the
    scan body above, byte-for-byte the pre-kernel program (the DCE
    pin); ``"pallas"`` replaces the unpack→step→repack triple with one
    packed-native Pallas call per tick (ops/pallas_gossip.py) so the
    per-tick HBM traffic is pure packed bytes. Requires
    ``layout="packed"``; the raft tick, counter accumulation, and the
    (unpacked-once-per-chunk) metrics tail stay outside the kernel."""
    memo = (cfg, _topo_key(topo), chunk, with_metrics, step_fn, swim_of,
            chaos_key, sentinel, pmesh.mesh_key(mesh), layout, lens,
            clock_of, raft, kernel)
    hit = _RUNNER_CACHE.get(memo)
    if hit is not None:
        return hit

    if mesh is not None:
        if lens:
            raise ValueError("the node lens is single-device; clear it "
                             "before installing a mesh")
        from consul_tpu.parallel import shard_step

        jitted = shard_step.make_sharded_chunk_runner(
            cfg, topo, mesh, chunk, with_metrics,
            step_fn=step_fn, swim_of=swim_of,
            chaos=chaos_key is not None, sentinel=sentinel, layout=layout,
            raft=raft, kernel=kernel,
        )
        _RUNNER_CACHE[memo] = jitted
        return jitted

    packed = layout == layout_mod.PACKED
    use_pallas = kernel == "pallas"
    if use_pallas:
        from consul_tpu.ops import pallas_gossip

        pallas_gossip.validate_kernel(kernel, layout)
        if lens:
            raise ValueError(
                "the node lens snapshots the dense working set mid-body; "
                "--kernel pallas keeps the tick VMEM-resident — clear the "
                "lens (set_lens(0)) before selecting it")
        ptick = pallas_gossip.make_tick_kernel(
            cfg, topo, step_fn=step_fn, sentinel=sentinel,
            interpret=pallas_gossip.default_interpret())
    else:
        ptick = None

    def body(world, sched, carry, tick_key):
        if raft is not None:
            (state, rst), (cnt, rcnt) = carry
        else:
            state, cnt = carry
        if use_pallas:
            if raft is not None:
                # PRE-step tick, read straight off the packed t leaf.
                t_pre = layout_mod.tick_of(state)
            state, c = ptick(world, sched, state, tick_key)
        else:
            if packed:
                state = layout_mod.unpack_state(state)
            if raft is not None:
                # The raft tick is keyed on the PRE-step tick (the same
                # t this tick_key was folded from) so chaos windows and
                # the draw ladder line up with the oracle's step(t).
                t_pre = swim_of(state).t
            state, c = step_fn(cfg, topo, world, state, tick_key, sched,
                               sentinel=sentinel)
        cnt = counters_mod.add(cnt, c)
        if raft is not None:
            from consul_tpu.ops import raft_ops

            rst, rc = raft_ops.tick(raft, rst, t_pre, tick_key,
                                    sched=sched)
            rcnt = raft_ops.counters_add(rcnt, rc)
        out = layout_mod.pack_state(state) if packed else state
        if raft is not None:
            carry_out = ((out, rst), (cnt, rcnt))
        else:
            carry_out = (out, cnt)
        if lens:
            row = lens_obs.snapshot(
                swim_of(state),
                None if clock_of is None else clock_of(state),
                lens)
            if raft is not None:
                from consul_tpu.obs import lens as _lens

                row = jnp.concatenate(
                    [row, _lens.raft_snapshot(rst, lens)], axis=1)
        else:
            row = None
        if not with_metrics:
            return carry_out, (row if lens else ())
        # Pallas tick returns packed state: the metrics tail unpacks a
        # transient dense view (metrics runs are not the perf path; the
        # Vivaldi reads see one extra bf16 round-trip, inside the
        # layout-parity tolerance).
        sw = swim_of(layout_mod.unpack_state(state) if use_pallas
                     else state)
        h = metrics.health(cfg, topo, sw)
        rmse = metrics.vivaldi_rmse(
            cfg, world, sw, jax.random.fold_in(tick_key, 1), samples=2048
        )
        trace = TickTrace(h.agreement, h.false_positive, h.undetected, rmse)
        return carry_out, ((trace, row) if lens else trace)

    def run(world, sched, state, base_key):
        if raft is not None:
            from consul_tpu.ops import raft_ops

            model_state, rst = state
            ticks = swim_of(model_state).t + jnp.arange(chunk,
                                                        dtype=jnp.int32)
            carry0 = ((model_state, rst),
                      (counters_mod.zeros(), raft_ops.counters_zeros()))
        else:
            ticks = swim_of(state).t + jnp.arange(chunk, dtype=jnp.int32)
            carry0 = (state, counters_mod.zeros())
        tick_keys = jax.vmap(lambda t: jax.random.fold_in(base_key, t))(ticks)
        (state, cnt), ys = jax.lax.scan(
            functools.partial(body, world, sched), carry0, tick_keys)
        if lens:
            trace, lbuf = ys if with_metrics else (None, ys)
            return state, cnt, trace, lbuf
        return state, cnt, ys

    jitted = jax.jit(run, donate_argnums=(2,))
    _RUNNER_CACHE[memo] = jitted
    return jitted


@dataclasses.dataclass
class Simulation:
    """Owns the world, topology, and device state for one simulated DC."""

    cfg: SimConfig
    seed: int = 0
    # On-device invariant sentinels (consul_tpu/runtime): when on, every
    # chunk runs the compiled validator and the host tier fail-fasts
    # (SentinelViolation) on any nonzero sentinel counter, dumping a
    # diagnostic checkpoint into ``sentinel_dump_dir`` first when set.
    sentinel: bool = False
    sentinel_dump_dir: Optional[str] = None
    # Device mesh (jax.sharding.Mesh or None). When set, chunk runners
    # execute under shard_map over the grid with explicit ppermute
    # collectives (parallel/shard_step.py) and the world/state/schedule
    # live sharded over the node axis. None is the single-device
    # program today's compile-ledger pins count.
    mesh: Optional[object] = None
    # At-rest state encoding (models/layout.py): "dense" is the f32/i32
    # golden-parity reference, "packed" the 2.5x-compacted form that
    # buys the beyond-HBM tier. Chosen per run (the MemoryBudget
    # planner picks it for the CLI); joins the runner memo key.
    layout: str = layout_mod.DENSE
    # Tick execution engine (ops/pallas_gossip.py): "xla" is the scan
    # body every prior compile-ledger pin counts, byte-for-byte;
    # "pallas" fuses unpack→exchange→repack into one packed-native
    # kernel per tick (requires layout="packed"). Joins the runner
    # memo key like layout/sentinel.
    kernel: str = "xla"

    # Driver hooks (SerfSimulation overrides these).
    _step_fn = staticmethod(swim.step_counted)
    _swim_of = staticmethod(lambda st: st)
    # Lamport-clock projection for the node lens (obs/lens.py). Bare
    # SWIM has no serf clock; the lens records 0 for the field.
    _clock_of = None

    def _init_state(self, key):
        return sim_state.init(self.cfg, key)

    def __post_init__(self):
        layout_mod.validate(self.cfg, self.layout)
        if self.kernel != "xla":
            from consul_tpu.ops import pallas_gossip

            pallas_gossip.validate_kernel(self.kernel, self.layout)
        key = jax.random.PRNGKey(self.seed)
        kw, kn, ks, kb = jax.random.split(key, 4)
        self.world = topology.make_world(self.cfg, kw)
        self.topo = topology.make_topology(self.cfg, kn)
        self.state = self._init_state(ks)
        if self.layout == layout_mod.PACKED:
            self.state = layout_mod.pack_state(self.state)
        self.base_key = kb
        self._runners = {}
        self._warmed: set = set()
        # Reference-named metrics recorded on chunk boundaries
        # (telemetry.emit_sim_metrics); served by /v1/agent/metrics and
        # the debug bundle.
        self.sink = telemetry.Sink()
        # Cumulative protocol-event counters (Python ints — i32 only
        # per chunk on device, see models/counters.py). Throughput runs
        # (with_metrics=False) defer the device fetch: per-chunk counter
        # pytrees queue in _pending_counters and flush in one batched
        # transfer when the totals are next read.
        self._counters = {f: 0 for f in counters_mod.FIELDS}
        self._pending_counters = []
        # Installed fault schedule (chaos.ChaosSchedule or None). Enters
        # the chunk runner as a program argument; None is the schedule-
        # free program today's tests pin.
        self.chaos = None
        # Attached read plane (consul_tpu/serving.ServingPlane or None).
        # When set, every chunk boundary republishes a double-buffered
        # device snapshot so concurrent readers see state consistent as
        # of the last completed tick — never torn mid-scan, and never
        # blocking the scan loop.
        self.serving = None
        # On-device node lens (obs/lens.py): the armed static id tuple
        # joins the runner memo key; () is the pre-lens program
        # byte-for-byte (the set_sentinel DCE contract). ``lens`` is
        # the host-side LensRecorder while armed.
        self._lens_ids: tuple = ()
        self.lens = None
        # Device raft tier (models/raft.py): ``_raft_cfg`` (a frozen
        # RaftConfig) joins the runner memo key like chaos/sentinel —
        # None is the byte-identical pre-raft program; ``raft`` is the
        # host RaftPlane (proposals, commit pump, counters) while armed.
        self._raft_cfg = None
        self.raft = None
        # Monotone chunk sequence number — the alignment key shared by
        # the XLA StepTraceAnnotation and the host "chunk" span.
        self._chunk_seq = 0
        # Host span tracing (obs/trace.py): span durations mirror into
        # this sim's sink (last attach wins — one process-wide tracer,
        # the Sink idiom) and XLA compiles fold in as cat="xla" spans.
        obs_trace.get_tracer().attach_sink(self.sink)
        obs_trace.install_jax_hooks()
        if self.mesh is not None:
            self.set_mesh(self.mesh)

    # -- multi-chip placement -------------------------------------------
    def set_mesh(self, mesh):
        """Install (or clear, with None) a device mesh for subsequent
        runs: places the world, state and any installed fault schedule
        sharded over the node axis and rebinds the runners. The
        process-wide _RUNNER_CACHE keys on the mesh fingerprint
        (parallel/mesh.mesh_key), so revisiting a mesh shape — elastic
        4->8 recovery — never recompiles, while a NEW shape can never
        hit the old shape's executable."""
        if mesh is not None and self._lens_ids:
            raise ValueError("the node lens is single-device; "
                             "set_lens(0) before installing a mesh")
        self.mesh = mesh
        self._runners = {}
        if mesh is None:
            return
        from consul_tpu.parallel import shard_step

        self.world = shard_step.place(mesh, self.world, self.cfg.n)
        self.state = shard_step.place(mesh, self.state, self.cfg.n)
        if self.chaos is not None:
            self.chaos = shard_step.place(mesh, self.chaos, self.cfg.n)

    def _place_node(self, value):
        """Host-built per-node array -> device, sharded over the node
        axis when a mesh is installed. The single funnel for fault/verb
        masks: an implicit ``jnp.asarray`` would replicate [N] rows on
        every chip (the TH110 hazard — silent HBM blowup at 1M+)."""
        arr = jnp.asarray(value)
        if self.mesh is None:
            return arr
        from consul_tpu.parallel import shard_step

        return shard_step.place(self.mesh, arr, self.cfg.n)

    # -- serving plane ---------------------------------------------------
    def attach_serving(self, plane, writes: bool = False,
                       kv_slots: int = 256, **write_kw):
        """Attach a serving read plane (consul_tpu/serving): publishes
        a snapshot now and republishes at every chunk boundary. With
        ``writes=True`` the device write path + watch plane come up
        too (``plane.attach_writes``): batched catalog/KV/session
        writes apply between chunks, become visible at flips, and
        every flip carries the monotone device apply index."""
        plane.attach(self)
        if writes:
            plane.attach_writes(kv_slots=kv_slots, **write_kw)

    def publish_serving(self):
        """Republish the serving snapshot from current state (no-op
        when no plane is attached). The projection is one jitted
        program producing fresh buffers, so snapshots survive the
        runner's donated-state overwrite on the next chunk. With the
        raft tier armed, the commit pump runs FIRST: quorum-committed
        proposals apply to the write state here, so the snapshot a
        flip captures is consistent as of the committed prefix."""
        if self.raft is not None:
            self.raft.pump()
        if self.serving is not None:
            self.serving.publish(self)

    # -- raft tier -------------------------------------------------------
    def set_raft(self, groups=None, **kw):
        """Arm (or clear, with None) the batched device raft tier for
        subsequent runs: ``groups`` is an int group count (remaining
        RaftConfig knobs via ``kw``) or a full
        :class:`~consul_tpu.config.RaftConfig`. Arming rebinds the
        runners and builds a fresh :class:`~consul_tpu.models.raft.
        RaftPlane`; toggling follows the set_sentinel/set_lens DCE
        contract — off is the pre-raft program byte-for-byte, and the
        process-wide _RUNNER_CACHE memoizes both programs so flipping
        never recompiles. Returns the RaftPlane (None when cleared)."""
        from consul_tpu.config import RaftConfig

        if groups is None:
            rcfg = None
        elif isinstance(groups, RaftConfig):
            rcfg = groups
        else:
            rcfg = RaftConfig(groups=int(groups), **kw)
        if rcfg != self._raft_cfg:
            self._raft_cfg = rcfg
            self._runners = {}
        if rcfg is None:
            self.raft = None
        else:
            from consul_tpu.models import raft as raft_mod

            self.raft = raft_mod.RaftPlane(self, rcfg)
        # The lens field layout depends on whether raft rides along —
        # restart the recorder so its schema matches the buffers.
        if self._lens_ids:
            self.lens = lens_obs.LensRecorder(
                self._lens_ids, tick0=self._tick(),
                fields=self._lens_fields())
        return self.raft

    def _lens_fields(self) -> tuple:
        return (lens_obs.FIELDS + lens_obs.RAFT_FIELDS
                if self._raft_cfg is not None else lens_obs.FIELDS)

    # -- layout plumbing ------------------------------------------------
    def _to_dense(self):
        """The driver state with a dense SWIM plane (identity when the
        layout already is). Host-side verbs (fault injection, serf
        intents) edit the dense form and hand back via _from_dense —
        one unpack/pack pair per verb, never inside the scan."""
        return layout_mod.unpack_state(self.state)

    def _from_dense(self, st):
        if self.layout == layout_mod.PACKED:
            st = layout_mod.pack_state(st)
        self.state = st

    def _tick(self) -> int:
        """Current tick as a host int — reads the one scalar ``t`` leaf
        directly off the (possibly packed) state, so it never
        materializes a dense copy of a big population."""
        return int(jax.device_get(layout_mod.tick_of(self.state)))

    # -- fault injection ------------------------------------------------
    def kill(self, mask):
        self._from_dense(
            sim_state.kill(self._to_dense(), self._place_node(mask)))
        self.publish_serving()

    def revive(self, mask):
        self._from_dense(sim_state.revive(
            self.cfg, self._to_dense(), self._place_node(mask)))
        self.publish_serving()

    def set_chaos(self, sched):
        """Install (or clear, with None) a fault schedule for subsequent
        runs. Accepts a compiled :class:`chaos.ChaosSchedule` or a
        sequence of schedule entries (compiled here). Empty schedules
        normalize to None so the schedule-free executable keeps exactly
        one jit cache entry (the compile-count pin)."""
        if sched is not None and not isinstance(sched, chaos_mod.ChaosSchedule):
            sched = chaos_mod.compile_schedule(self.cfg.n, sched)
        if sched is not None and chaos_mod.is_empty(sched):
            sched = None
        if sched is not None and self.mesh is not None:
            from consul_tpu.parallel import shard_step

            sched = shard_step.place(self.mesh, sched, self.cfg.n)
        self.chaos = sched
        # Bound runners close over the schedule; rebind lazily. The
        # process-wide _RUNNER_CACHE still memoizes the underlying
        # programs, so toggling chaos on/off never recompiles.
        self._runners = {}

    def set_sentinel(self, on: bool, dump_dir: Optional[str] = None):
        """Toggle the on-device invariant sentinels for subsequent runs.
        ``dump_dir`` (optional) is where a diagnostic checkpoint lands
        if a sentinel trips. Toggling rebinds the runners; the
        process-wide _RUNNER_CACHE memoizes both programs, so flipping
        back and forth never recompiles."""
        if dump_dir is not None:
            self.sentinel_dump_dir = dump_dir
        if on != self.sentinel:
            self.sentinel = on
            self._runners = {}

    def set_kernel(self, kernel: str):
        """Select the tick execution engine for subsequent runs:
        ``"xla"`` (the default scan body) or ``"pallas"`` (the
        packed-native fused kernel, ops/pallas_gossip.py — requires
        ``layout="packed"``). Toggling follows the set_sentinel DCE
        contract: ``"xla"`` is the pre-kernel program byte-for-byte,
        and the process-wide _RUNNER_CACHE memoizes both programs so
        flipping back and forth never recompiles."""
        from consul_tpu.ops import pallas_gossip

        pallas_gossip.validate_kernel(kernel, self.layout)
        if kernel != self.kernel:
            self.kernel = kernel
            self._runners = {}

    def set_lens(self, sample) -> tuple:
        """Arm (or clear, with ``0``/empty) the on-device node lens for
        subsequent runs: ``sample`` is either an int count (evenly
        spaced ids) or an explicit id list (obs/lens.normalize_ids).
        Arming rebinds the runners and starts a fresh
        :class:`obs.lens.LensRecorder` at the live tick (one scalar
        device read here — never per chunk). Toggling follows the
        set_sentinel contract: off is the pre-lens program
        byte-for-byte, and the process-wide _RUNNER_CACHE memoizes both
        programs so flipping never recompiles. Returns the resolved id
        tuple."""
        ids = lens_obs.normalize_ids(self.cfg.n, sample)
        if ids and self.mesh is not None:
            raise ValueError("the node lens is single-device; clear "
                             "the mesh before arming it")
        if ids != self._lens_ids:
            self._lens_ids = ids
            self._runners = {}
        self.lens = (lens_obs.LensRecorder(ids, tick0=self._tick(),
                                           fields=self._lens_fields())
                     if ids else None)
        return ids

    def _check_sentinel(self, deltas):
        """Host tier of the sentinel: fail-fast on a nonzero violation
        mask, dumping a diagnostic checkpoint first so the corrupt
        state is inspectable (and resumable under --no-verify debugging)
        rather than lost with the process."""
        if not self.sentinel:
            return
        mask = counters_mod.violation_mask(deltas)
        if not mask:
            return
        self.sink.incr_counter("sim.sentinel.trips", 1)
        dump = None
        if self.sentinel_dump_dir:
            t_now = self._tick()
            dump = os.path.join(
                self.sentinel_dump_dir, f"sentinel_diag_t{t_now}.ckpt")
            try:
                os.makedirs(self.sentinel_dump_dir, exist_ok=True)
                ckpt_mod.save(dump, self.state, meta={
                    "reason": "sentinel",
                    "mask": mask,
                    "deltas": {f: int(deltas.get(f, 0))
                               for f in counters_mod.SENTINEL_FIELDS},
                    "t": t_now,
                    "n": self.cfg.n,
                })
            except (OSError, ValueError):
                dump = None  # the diagnostic must not mask the trip
        raise SentinelViolation(mask, deltas, dump)

    def run_scenario(self, events, ticks=None, chunk: int = 64,
                     with_metrics: bool = False, settle: int = 64):
        """Replay a *relative* fault schedule from the current tick and
        return the SLO counter deltas it produced.

        ``events`` is a sequence of chaos entries (Partition/LinkLoss/
        ChurnWave/Degrade) with start/stop relative to now; they are
        compiled, rebased onto the live tick (values only — schedules of
        the same shape share one executable), run for ``ticks`` ticks
        (default: last stop + ``settle``, the post-lift window the heal
        probe needs), and uninstalled again. Returns a ScenarioResult:
        ``slo`` holds the six chaos counters plus the protocol-event
        deltas over the scenario window, under the stable key names
        bench.py serializes."""
        sched = chaos_mod.compile_schedule(self.cfg.n, events)
        if ticks is None:
            stops = [int(e.stop) for e in events]
            ticks = (max(stops) if stops else 0) + settle
        t0 = self._tick()
        prev = self.chaos
        self.set_chaos(chaos_mod.shift_schedule(sched, t0))
        before = dict(self.counters)
        try:
            trace = self.run(ticks, chunk=chunk, with_metrics=with_metrics)
        finally:
            self.set_chaos(prev)
        after = self.counters
        deltas = {f: after[f] - before[f] for f in counters_mod.FIELDS}
        slo = {
            SLO_KEYS[f]: deltas[f] for f in SLO_KEYS
        }
        return ScenarioResult(slo=slo, counters=deltas, ticks=ticks,
                              trace=trace)

    def sweep(self, scenarios, *, ticks=None, chunk: int = 32,
              settle: int = 64):
        """Run S fault scenarios against the current state in ONE
        vmapped executable (chaos/sweep.py run_sweep) — each on its own
        state copy, so the simulation itself does not advance. Counter
        semantics match S independent :meth:`run_scenario` replays
        exactly (the sweep parity pin, tests/test_sweep.py)."""
        from consul_tpu.chaos import sweep as sweep_mod

        return sweep_mod.run_sweep(self, scenarios, ticks=ticks,
                                   chunk=chunk, settle=settle)

    # -- execution ------------------------------------------------------
    def _runner(self, chunk: int, with_metrics: bool):
        k = (chunk, with_metrics)
        if k not in self._runners:
            jitted = _chunk_runner(
                self.cfg, self.topo, chunk, with_metrics,
                step_fn=type(self)._step_fn, swim_of=type(self)._swim_of,
                chaos_key=chaos_mod.static_key_of(self.chaos),
                sentinel=self.sentinel, mesh=self.mesh, layout=self.layout,
                lens=self._lens_ids, clock_of=type(self)._clock_of,
                raft=self._raft_cfg, kernel=self.kernel,
            )

            def bound(state, base_key, _j=jitted, _w=self.world,
                      _s=self.chaos):
                return _j(_w, _s, state, base_key)

            bound._cache_size = jitted._cache_size
            self._runners[k] = bound
        return self._runners[k]

    def _exec_chunk(self, c: int, with_metrics: bool):
        """Dispatch one compiled chunk under the observability bracket:
        the XLA ``StepTraceAnnotation`` plus the host ``chunk`` span
        (same step number — the cross-file alignment key), and, when
        the node lens is armed, queue the chunk's ``[C, S, F]`` device
        buffer on the LensRecorder (a reference hand-off — the one
        batched transfer happens at flush). Returns ``(cnt, trace)``;
        ``self.state`` is advanced in place. The span brackets the
        *dispatch* (the runner returns on async enqueue); callers that
        block for completion do so outside, so the lens tick window is
        the dispatch window — monotone and inside the chunk span, which
        is all the export interpolation needs."""
        tr = obs_trace.get_tracer()
        t0_us = tr.now_us()
        step = self._chunk_seq
        self._chunk_seq += 1
        arg = (self.state if self.raft is None
               else (self.state, self.raft.take_state()))
        with obs_trace.chunk_annotation(step, c):
            out = self._runner(c, with_metrics)(arg, self.base_key)
        if self._lens_ids:
            st, cnt, trace, lbuf = out
            if self.lens is not None:
                self.lens.record(lbuf, c, t0_us, tr.now_us())
        else:
            st, cnt, trace = out
        if self.raft is not None:
            (self.state, self.raft.state), (cnt, rcnt) = st, cnt
            self.raft.absorb(rcnt)
        else:
            self.state = st
        return cnt, trace

    def run(self, ticks: int, chunk: int = 64, with_metrics: bool = True):
        """Advance ``ticks`` ticks; returns the concatenated TickTrace
        (or None when metrics are disabled for pure-throughput runs)."""
        traces = []
        remaining = ticks
        while remaining > 0:
            c = min(chunk, remaining)
            t0 = time.perf_counter()
            cnt, trace = self._exec_chunk(c, with_metrics)
            if with_metrics:
                # Block before reading the clock: the jitted runner
                # returns on async dispatch, not completion.
                jax.block_until_ready(trace)
                traces.append(trace)
                self._record_chunk(trace, cnt, c, t0)
            else:
                # Throughput path: no device sync — the chunk's counter
                # pytree queues for a lazy batched flush. With sentinels
                # on, flush every chunk instead: fail-fast within one
                # chunk is the point, and the one [len(FIELDS)] fetch
                # per chunk is the sentinel's documented host cost.
                self._pending_counters.append(cnt)
                if self.sentinel:
                    self._flush_counters()
            self.publish_serving()
            remaining -= c
        if not with_metrics:
            return None
        return jax.tree.map(lambda *xs: jnp.concatenate(xs), *traces)

    # -- counters -------------------------------------------------------
    @property
    def counters(self):
        """Cumulative protocol-event totals (plain-int dict, keyed by
        GossipCounters field name). Flushes any deferred throughput-run
        chunks first."""
        self._flush_counters()
        return self._counters

    def counters_snapshot(self):
        """A copy of :attr:`counters` safe to serialize (bench.py)."""
        return dict(self.counters)

    def _flush_counters(self, extra=None):
        """One explicit batched device→host transfer for every deferred
        chunk — plus, optionally, the current chunk's counters
        (``extra``), whose deltas are returned *unfolded* for the
        caller to record alongside its own telemetry. Batching through
        a single ``jax.device_get`` keeps the throughput path at one
        boundary crossing per flush (and, unlike stacking on device,
        compiles no per-batch-length executables); the explicit API is
        what keeps the whole loop legal under
        ``jax.transfer_guard("disallow")``."""
        stacks = [counters_mod.stack(c) for c in self._pending_counters]
        n_pending = len(stacks)
        if extra is not None:
            stacks.append(counters_mod.stack(extra))
        if not stacks:
            return None
        self._pending_counters = []
        host = jax.device_get(stacks)
        if n_pending:
            vals = np.sum(np.stack(host[:n_pending]), axis=0)
            self._fold_counter_deltas(
                {f: int(v) for f, v in zip(counters_mod.FIELDS, vals)})
        if extra is None:
            return None
        return {f: int(v) for f, v in zip(counters_mod.FIELDS, host[-1])}

    def _fold_counter_deltas(self, deltas):
        for f, v in deltas.items():
            self._counters[f] += v
        telemetry.emit_counter_deltas(self.sink, deltas)
        self._check_sentinel(deltas)

    def _record_chunk(self, trace: TickTrace, cnt, ticks: int, t0: float):
        """Fold one chunk's trace into the telemetry sink under the
        reference metric names (the batched host-boundary equivalent of
        the reference's per-operation instrumentation). The first run
        of each program shape compiles; its wall time would poison the
        timing aggregates forever, so it is recorded without timing
        (throughput() warms for the same reason)."""
        key = (ticks, True)
        if key in self._warmed:
            wall_s: Optional[float] = time.perf_counter() - t0
        else:
            self._warmed.add(key)
            wall_s = None
        h = metrics.HealthMetrics(
            agreement=trace.agreement[-1],
            false_positive=trace.false_positive[-1],
            undetected=trace.undetected[-1],
            live_nodes=jnp.int32(0),
        )
        # Any deferred chunks and this chunk's counter pytree land in
        # ONE device_get; the sink emission goes through
        # emit_sim_metrics with everything else this chunk records.
        deltas = self._flush_counters(extra=cnt)
        for f, v in deltas.items():
            self._counters[f] += v
        telemetry.emit_sim_metrics(
            self.swim_state, self.sink,
            health=h, rmse_s=float(trace.rmse[-1]),
            rounds_per_sec=(ticks / wall_s if wall_s else None),
            chunk_wall_s=wall_s, chunk_ticks=ticks,
            serf_state=self.serf_state,
            queue_depth_warning=self.cfg.serf.queue_depth_warning,
            counters=deltas,
        )
        self._check_sentinel(deltas)

    def run_until_converged(
        self,
        max_ticks: int,
        chunk: int = 64,
        rmse_target_s: Optional[float] = None,
        require_agreement: float = 1.0,
        stable_chunks: int = 1,
    ):
        """Run until membership agreement (and optionally Vivaldi RMSE)
        hold for ``stable_chunks`` consecutive chunks. Returns
        (converged: bool, ticks_used: int, last_trace).

        The retry.Run-with-deadline idiom of the reference test suite.
        """
        used = 0
        streak = 0
        trace = None
        while used < max_ticks:
            c = min(chunk, max_ticks - used)
            t0 = time.perf_counter()
            cnt, trace = self._exec_chunk(c, True)
            jax.block_until_ready(trace)
            self._record_chunk(trace, cnt, c, t0)
            self.publish_serving()
            used += c
            ok = float(trace.agreement[-1]) >= require_agreement
            if ok and rmse_target_s is not None:
                ok = float(trace.rmse[-1]) <= rmse_target_s
            streak = streak + 1 if ok else 0
            if streak >= stable_chunks:
                return True, used, trace
        return False, used, trace

    def throughput(self, ticks: int = 256) -> float:
        """Measured gossip rounds (ticks) per wall-clock second.

        Warmup runs the *same* compiled program as the timed region, so
        XLA compilation never lands inside the measurement.
        """
        cnt, _ = self._exec_chunk(ticks, False)
        self._pending_counters.append(cnt)
        jax.block_until_ready(jax.tree.leaves(self.state))
        t0 = time.perf_counter()
        cnt, _ = self._exec_chunk(ticks, False)
        self._pending_counters.append(cnt)
        jax.block_until_ready(jax.tree.leaves(self.state))
        dt = time.perf_counter() - t0
        self.publish_serving()
        return ticks / dt

    # -- inspection -----------------------------------------------------
    def health(self) -> metrics.HealthMetrics:
        return metrics.health(self.cfg, self.topo, self.swim_state)

    def rmse(self, seed: int = 99) -> float:
        return float(metrics.vivaldi_rmse(
            self.cfg, self.world, self.swim_state, jax.random.PRNGKey(seed)))

    # -- uniform SWIM-state accessors (the transport bridge and other
    # host components work on the SWIM plane regardless of whether the
    # driver runs bare SWIM or the full serf stack) --------------------
    @property
    def swim_state(self) -> sim_state.SimState:
        return layout_mod.swim_plane(self.state)

    def set_swim_state(self, st: sim_state.SimState):
        if self.layout == layout_mod.PACKED:
            st = layout_mod.pack(st)
        self.state = st

    @property
    def serf_state(self):
        return None  # bare-SWIM driver has no serf plane


@dataclasses.dataclass
class SerfSimulation(Simulation):
    """The full-stack driver: serf.step (SWIM + events + queries +
    reap) instead of the bare SWIM step. Same chunked-scan execution,
    metrics, and telemetry via the base driver's hooks; adds the
    serf-layer verbs."""

    _step_fn = staticmethod(serf_mod.step_counted)
    _swim_of = staticmethod(lambda st: st.swim)
    # The serf membership Lamport clock feeds the lens's lamport field.
    _clock_of = staticmethod(lambda st: st.clock)

    def _init_state(self, key):
        return serf_mod.init(self.cfg, key)

    # -- serf verbs (edit the dense SWIM plane; _from_dense re-packs) ---
    def user_event(self, mask, name: int):
        self._from_dense(serf_mod.user_event(
            self.cfg, self._to_dense(), self._place_node(mask), name))

    def query(self, mask, name: int):
        self._from_dense(serf_mod.query(
            self.cfg, self._to_dense(), self._place_node(mask), name))

    def leave(self, mask):
        self._from_dense(serf_mod.leave(
            self.cfg, self._to_dense(), self._place_node(mask)))

    def kill(self, mask):
        st = self._to_dense()
        self._from_dense(st._replace(
            swim=sim_state.kill(st.swim, self._place_node(mask))))

    def revive(self, mask):
        st = self._to_dense()
        self._from_dense(st._replace(
            swim=sim_state.revive(self.cfg, st.swim,
                                  self._place_node(mask))))

    @property
    def swim_state(self) -> sim_state.SimState:
        return layout_mod.swim_plane(self.state)

    def set_swim_state(self, st: sim_state.SimState):
        if self.layout == layout_mod.PACKED:
            st = layout_mod.pack(st)
        self.state = self.state._replace(swim=st)

    @property
    def serf_state(self):
        return self.state


@dataclasses.dataclass
class StreamedSimulation:
    """Beyond-HBM driver: the population streams through the device as
    independent node cohorts, host<->device double-buffered.

    A population too big for device memory is split into
    ``cfg.n / cohort_n`` cohorts of ``cohort_n`` nodes. Each cohort is a
    self-contained gossip island — same circulant topology (ONE set of
    trace-time roll constants, therefore ONE compiled executable for
    every cohort: the compile-ledger pin across cohort flips), its own
    world placement and PRNG stream — modeling a federation of
    same-shaped DCs rather than one flat gossip domain (the documented
    divergence; consul federates WAN pools the same way instead of
    running one planet-wide SWIM domain). At rest cohorts live in host
    RAM as (packed) numpy archives; the device holds at most two: the
    one computing and the one being staged.

    The streaming schedule is cohorts-OUTER, chunks-inner — each cohort
    runs all its ticks in one residency, so a full pass costs exactly C
    host->device uploads and C downloads regardless of tick count. The
    double buffer is JAX's async dispatch: cohort i+1's ``device_put``
    is issued *before* the blocking ``device_get`` on cohort i's result,
    so the upload overlaps the drain (the 2112.09017 out-of-core
    pattern). The per-cohort archive round-trips through the SAME
    chunk-runner seam every other driver uses — the MemoryBudget
    planner (runtime/membudget.py) only picks ``cohort_n``, ``chunk``
    and the layout; nothing about the step changes.

    Scope: single-device execution per cohort (a mesh shards *within* a
    resident population — combine by pointing ``mesh`` runs at the
    resident tier instead), no serving plane, no sentinel. Chaos
    schedules are supported compiled at cohort shape and applied to
    every cohort identically.
    """

    cfg: SimConfig            # the FULL population: cfg.n = total nodes
    cohort_n: int             # resident nodes per cohort (divides cfg.n)
    seed: int = 0
    layout: str = layout_mod.PACKED
    chunk: int = 64           # scan length per compiled program

    _step_fn = staticmethod(swim.step_counted)
    _swim_of = staticmethod(lambda st: st)

    def _init_state(self, cfg, key):
        return sim_state.init(cfg, key)

    def __post_init__(self):
        if self.cfg.n % self.cohort_n != 0:
            raise ValueError(
                f"cohort_n={self.cohort_n} must divide n={self.cfg.n}")
        if not self.cfg.view_degree:
            raise ValueError(
                "streamed cohorts need the sparse view (view_degree>0): "
                "dense mode's topology is population-shaped")
        self.cohorts = self.cfg.n // self.cohort_n
        self.cohort_cfg = dataclasses.replace(self.cfg, n=self.cohort_n)
        layout_mod.validate(self.cohort_cfg, self.layout)
        key = jax.random.PRNGKey(self.seed)
        self._kw, kn, self._ks, self._kb = jax.random.split(key, 4)
        # ONE topology: every cohort shares the same roll constants,
        # so every cohort hits the same executable.
        self.topo = topology.make_topology(self.cohort_cfg, kn)
        self.chaos = None
        self._counters = {f: 0 for f in counters_mod.FIELDS}
        self.sink = telemetry.Sink()
        # Host archives: one (packed) state pytree of numpy leaves per
        # cohort. Worlds are NOT archived — they regenerate from the
        # per-cohort key at swap-in (deterministic, cheaper than RAM).
        self._archive = [None] * self.cohorts
        for i in range(self.cohorts):
            st = self._init_state(
                self.cohort_cfg, jax.random.fold_in(self._ks, i))
            if self.layout == layout_mod.PACKED:
                st = layout_mod.pack_state(st)
            self._archive[i] = jax.device_get(st)

    # -- cohort staging -------------------------------------------------
    def _world_of(self, i: int):
        return topology.make_world(
            self.cohort_cfg, jax.random.fold_in(self._kw, i))

    def _stage(self, i: int):
        """Upload cohort i (async dispatch — returns immediately)."""
        with obs_trace.span("stream.upload", cat="stream",
                            args={"cohort": i}):
            return self._world_of(i), jax.device_put(self._archive[i])

    def _cohort_key(self, i: int):
        return jax.random.fold_in(self._kb, i)

    def set_chaos(self, events):
        """Install a fault schedule, compiled at cohort shape and
        replayed identically inside every cohort (None clears)."""
        sched = events
        if sched is not None and not isinstance(sched,
                                                chaos_mod.ChaosSchedule):
            sched = chaos_mod.compile_schedule(self.cohort_n, sched)
        if sched is not None and chaos_mod.is_empty(sched):
            sched = None
        self.chaos = sched

    def _runner(self, chunk: int):
        return _chunk_runner(
            self.cohort_cfg, self.topo, chunk, False,
            step_fn=type(self)._step_fn, swim_of=type(self)._swim_of,
            chaos_key=chaos_mod.static_key_of(self.chaos),
            sentinel=False, mesh=None, layout=self.layout,
        )

    # -- execution ------------------------------------------------------
    def run(self, ticks: int):
        """Advance every cohort by ``ticks`` ticks (one full streaming
        pass). Returns a summary dict; counters fold into
        :attr:`counters` summed across cohorts."""
        t0 = time.perf_counter()
        staged = self._stage(0)
        for i in range(self.cohorts):
            world, state = staged
            cnts = []
            remaining = ticks
            while remaining > 0:
                c = min(self.chunk, remaining)
                state, cnt, _ = self._runner(c)(
                    world, self.chaos, state, self._cohort_key(i))
                cnts.append(counters_mod.stack(cnt))
                remaining -= c
            if i + 1 < self.cohorts:
                # Double buffer: issue the next upload before blocking
                # on this cohort's drain.
                staged = self._stage(i + 1)
            with obs_trace.span("stream.drain", cat="stream",
                                args={"cohort": i}):
                host_state, host_cnts = jax.device_get((state, cnts))
            self._archive[i] = host_state
            vals = np.sum(np.stack(host_cnts), axis=0)
            for f, v in zip(counters_mod.FIELDS, vals):
                self._counters[f] += int(v)
        wall_s = time.perf_counter() - t0
        self.sink.incr_counter("sim.stream.passes", 1)
        return {
            "cohorts": self.cohorts,
            "cohort_n": self.cohort_n,
            "n": self.cfg.n,
            "ticks": ticks,
            "layout": self.layout,
            "wall_s": wall_s,
        }

    # -- inspection -----------------------------------------------------
    @property
    def counters(self):
        return self._counters

    def counters_snapshot(self):
        return dict(self._counters)

    def _tick(self) -> int:
        """All cohorts advance in lockstep; read cohort 0's clock."""
        return int(layout_mod.tick_of(self._archive[0]))

    def cohort_swim_state(self, i: int) -> sim_state.SimState:
        """Cohort i's SWIM plane, dense, as host arrays (inspection)."""
        return layout_mod.swim_plane(self._archive[i])

    def resident_bytes(self) -> int:
        """Peak device bytes the streaming schedule holds: two cohort
        states (double buffer) plus one world."""
        state_b = sum(layout_mod.np_size_bytes(l)
                      for l in jax.tree.leaves(self._archive[0]))
        world = jax.eval_shape(lambda: self._world_of(0))
        world_b = sum(layout_mod.np_size_bytes(l)
                      for l in jax.tree.leaves(world))
        return 2 * state_b + world_b


@dataclasses.dataclass
class StreamedSerfSimulation(StreamedSimulation):
    """Streamed cohorts over the full serf stack (fused core)."""

    _step_fn = staticmethod(serf_mod.step_counted)
    _swim_of = staticmethod(lambda st: st.swim)

    def _init_state(self, cfg, key):
        return serf_mod.init(cfg, key)


@dataclasses.dataclass
class ReferenceSerfSimulation(SerfSimulation):
    """SerfSimulation on the pre-fusion reference step
    (serf.step_reference_counted): the event/query plane runs as its
    own sweep after the SWIM pass, exactly the PR-1..6 algorithm. Not a
    production path and not covered by the compile-ledger pins — it
    exists for the fused-vs-legacy golden parity suite
    (tests/test_serf_fused.py)."""

    _step_fn = staticmethod(serf_mod.step_reference_counted)
