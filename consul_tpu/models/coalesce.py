"""Event coalescing: collapse bursts before the application sees them.

Mirrors the reference coalescer pipeline (reference serf/coalesce.go:
``coalesceLoop`` — a quantum timer of ``coalesce_period`` capped by a
``quiescent_period`` idle timer; serf/coalesce_member.go — keep only the
latest event per member, suppress repeats of the same type;
serf/coalesce_user.go — keep only the highest-Lamport-time version of
each named event, all same-ltime duplicates flush together).

Timers here are simulation ticks, not wall clocks, and the loop is an
explicit :meth:`tick` the host driver calls once per simulated tick —
the same deadline-array treatment every other reference timer gets in
this framework. Consumers (the transport bridge's event feed to real
agents, or any host-side observer of the simulated event plane) push
raw events with :meth:`ingest`; flushed, coalesced events come back
from :meth:`tick`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

# Member event types (reference serf/event.go EventType).
MEMBER_JOIN = "member-join"
MEMBER_LEAVE = "member-leave"
MEMBER_FAILED = "member-failed"
MEMBER_UPDATE = "member-update"
MEMBER_REAP = "member-reap"
USER = "user"

_MEMBER_TYPES = {MEMBER_JOIN, MEMBER_LEAVE, MEMBER_FAILED, MEMBER_UPDATE,
                 MEMBER_REAP}


@dataclasses.dataclass
class Event:
    type: str
    name: str = ""            # member name, or user-event name
    ltime: int = 0            # user events only
    payload: bytes = b""
    coalesce: bool = True     # user events may opt out (UserEvent.Coalesce)


class _Loop:
    """The coalesceLoop state machine (coalesce.go:30-79), tick-driven."""

    def __init__(self, coalesce_period: int, quiescent_period: int):
        self.cp = coalesce_period
        self.qp = quiescent_period
        self.quantum_at: Optional[int] = None
        self.quiescent_at: Optional[int] = None

    def arm(self, now: int):
        if self.quantum_at is None:
            self.quantum_at = now + self.cp
        self.quiescent_at = now + self.qp

    def due(self, now: int) -> bool:
        return (self.quantum_at is not None and now >= self.quantum_at) or \
            (self.quiescent_at is not None and now >= self.quiescent_at)

    def reset(self):
        self.quantum_at = None
        self.quiescent_at = None


class MemberEventCoalescer:
    """coalesce_member.go: latest event per member wins; a flush skips
    members whose last *flushed* type is unchanged (unless update)."""

    def __init__(self, coalesce_period: int, quiescent_period: int):
        self._loop = _Loop(coalesce_period, quiescent_period)
        self._last: dict[str, str] = {}     # lastEvents
        self._latest: dict[str, Event] = {}  # latestEvents

    def handles(self, e: Event) -> bool:
        return e.type in _MEMBER_TYPES

    def ingest(self, e: Event, now: int) -> Optional[Event]:
        """Returns the event immediately when not coalescible
        (pass-through, coalesce.go:46-49), else buffers it."""
        if not self.handles(e):
            return e
        self._loop.arm(now)
        self._latest[e.name] = e
        return None

    def tick(self, now: int) -> list[Event]:
        if not self._loop.due(now):
            return []
        self._loop.reset()
        out = []
        for name, ev in sorted(self._latest.items()):
            prev = self._last.get(name)
            # Same event re-flushed is suppressed, except updates
            # (coalesce_member.go:44-49).
            if prev == ev.type and ev.type != MEMBER_UPDATE:
                continue
            self._last[name] = ev.type
            out.append(ev)
        self._latest.clear()
        return out


class UserEventCoalescer:
    """coalesce_user.go: per event name keep only the latest Lamport
    time; all same-ltime versions flush together."""

    def __init__(self, coalesce_period: int, quiescent_period: int):
        self._loop = _Loop(coalesce_period, quiescent_period)
        self._events: dict[str, tuple[int, list[Event]]] = {}

    def handles(self, e: Event) -> bool:
        return e.type == USER and e.coalesce

    def ingest(self, e: Event, now: int) -> Optional[Event]:
        if not self.handles(e):
            return e
        self._loop.arm(now)
        cur = self._events.get(e.name)
        if cur is None or cur[0] < e.ltime:
            self._events[e.name] = (e.ltime, [e])
        elif cur[0] == e.ltime:
            cur[1].append(e)
        return None

    def tick(self, now: int) -> list[Event]:
        if not self._loop.due(now):
            return []
        self._loop.reset()
        out = []
        for _, (_, evs) in sorted(self._events.items()):
            out.extend(evs)
        self._events.clear()
        return out


class CoalescePipeline:
    """Both coalescers chained, the way serf wires them when
    CoalescePeriod/UserCoalescePeriod are set (serf.go Create)."""

    def __init__(self, coalesce_period: int = 5, quiescent_period: int = 1,
                 user_coalesce_period: int = 5,
                 user_quiescent_period: int = 1):
        self.member = MemberEventCoalescer(coalesce_period, quiescent_period)
        self.user = UserEventCoalescer(user_coalesce_period,
                                       user_quiescent_period)

    def ingest(self, e: Event, now: int) -> list[Event]:
        out = self.member.ingest(e, now)
        if out is None:
            return []
        out = self.user.ingest(out, now)
        return [] if out is None else [out]

    def tick(self, now: int) -> list[Event]:
        return self.member.tick(now) + self.user.tick(now)
