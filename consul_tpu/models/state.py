"""SimState: the whole simulated cluster as one struct-of-arrays pytree.

Every Go-side per-node data structure of the reference becomes an array
over the node axis N (shardable across chips), and every goroutine/timer
becomes a deadline array compared against the global tick counter:

  reference structure                          -> array here
  ------------------------------------------------------------------
  nodeState map (memberlist/state.go)          -> view_key[N, K] packed
                                                  (incarnation, status)
  per-node probe ticker + shuffled node list   -> next_probe_tick[N],
    (state.go:83-121, :492-513)                   probe_perm[N, K], probe_ptr[N]
  outstanding probe + ack handler channels     -> pending_col[N],
    (state.go:262-457, :759-790)                  pending_fail_tick[N],
                                                  pending_nack_miss[N]
  suspicion time.AfterFunc timers + per-from   -> susp_start[N, K],
    confirmation map (suspicion.go)               susp_seen[N, K] (32-bucket
                                                  accuser hash bitmask)
  TransmitLimitedQueue btree (queue.go)        -> tx_left[N, K] + own_tx[N]
                                                  (see below)
  awareness score (awareness.go)               -> awareness[N]
  Vivaldi client + per-peer latency filter     -> viv (VivaldiState[N]),
    (coordinate/client.go)                        lat_buf[N, K, S], lat_cnt[N, K]
  node's own incarnation (state.go:840-864)    -> own_inc[N]

**The broadcast queue is the view itself.** The reference's
TransmitLimitedQueue holds (subject, message) pairs where the message is
always the holder's current belief about the subject and a same-subject
arrival invalidates the queued one (queue.go:182-242) — so a per-entry
"remaining transmits" counter on the view, reset to the retransmit limit
whenever the entry changes, is an exact vectorization of the queue:
``tx_left[i, c]`` > 0 means node i still gossips its (c-column) belief.
Facts about *oneself* (alive refutations, join announcements, leave
intents) have no view column, so they ride a parallel own-fact channel:
``own_tx[i]`` transmits of ``(own_inc[i], ALIVE-or-LEFT)``. Ordering
fidelity: the queue serves fewest-transmits-first (queue.go:288-373) =
highest ``tx_left`` first — a top-k, not a btree.

``alive_truth``/``left`` are the fault-injection ground truth: whether
the simulated process is actually up (the thing SWIM is trying to
detect), not anyone's belief. ``external`` marks bridge-driven seats
(see wire/bridge.py): the simulation answers probes *to* them from
ground truth but never originates protocol traffic *for* them — a real
agent behind the transport seam does that itself.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from consul_tpu.config import SimConfig
from consul_tpu.ops import merge, vivaldi


class SimState(NamedTuple):
    t: jax.Array              # [] int32, global tick counter
    # -- ground truth (fault injection) -------------------------------
    alive_truth: jax.Array    # [N] bool — process actually up
    left: jax.Array           # [N] bool — gracefully departed
    leaving: jax.Array        # [N] bool — leave intent broadcast, still
                              # gossiping out the propagate window; such a
                              # node must NOT refute suspicions (serf
                              # Leave sets a state that suppresses
                              # refutation, serf/serf.go:675-…)
    external: jax.Array       # [N] bool — transport-bridge seats
    # -- own per-node protocol state ----------------------------------
    own_inc: jax.Array        # [N] uint32
    own_tx: jax.Array         # [N] int32 — own-fact transmits remaining
    awareness: jax.Array      # [N] int32, 0..awareness_max-1
    # -- probe scheduler ----------------------------------------------
    probe_perm: jax.Array     # [N, K] int32, per-node shuffled probe order
    probe_ptr: jax.Array      # [N] int32, cursor into probe_perm
    next_probe_tick: jax.Array  # [N] int32
    pending_col: jax.Array      # [N] int32 target column, -1 = no
                                # outstanding probe
    pending_fail_tick: jax.Array  # [N] int32, when the probe window closes
    pending_nack_miss: jax.Array  # [N] int32 — indirect-probe nacks that
                                  # went missing (Lifeguard NACK deltas,
                                  # reference state.go:437-451)
    # -- membership views ---------------------------------------------
    view_key: jax.Array       # [N, K] uint32 packed (incarnation, status)
    susp_start: jax.Array     # [N, K] int32, tick suspicion began, -1 = none
    susp_seen: jax.Array      # [N, K] uint32, accuser-hash bitmask
    tx_left: jax.Array        # [N, K] int32 — gossip transmits remaining
    # -- Vivaldi ------------------------------------------------------
    viv: vivaldi.VivaldiState  # batched [N]
    lat_buf: jax.Array        # [N, K, S] float32 per-peer RTT samples
    lat_cnt: jax.Array        # [N, K] int32 samples pushed


def own_key(state: SimState) -> jax.Array:
    """Each node's own-fact broadcast payload: alive at its incarnation,
    or a leave intent (LEFT outranks DEAD in the lattice, so a graceful
    departure is never reported as a failure once the intent lands)."""
    status = jnp.where(state.leaving | state.left, merge.LEFT, merge.ALIVE)
    return merge.make_key(state.own_inc, status)


def init(cfg: SimConfig, key) -> SimState:
    """A formed cluster at steady state: every node knows every neighbor
    as alive at incarnation 1, coordinates at the origin, nothing queued.

    (The reference reaches this state through the join/push-pull storm;
    the join process itself is exercised separately via fault injection —
    reviving killed ranges — and the serf intent layer.)
    """
    n, k_deg = cfg.n, cfg.degree
    k_perm, k_stagger = jax.random.split(key)
    # Per-node shuffled probe order over neighbor columns
    # (reference shuffles the node list per wrap, state.go:492-513).
    perm = jnp.argsort(
        jax.random.uniform(k_perm, (n, k_deg)), axis=1
    ).astype(jnp.int32)
    probe_period = cfg.gossip.probe_period_ticks
    return SimState(
        t=jnp.int32(0),
        alive_truth=jnp.ones((n,), bool),
        left=jnp.zeros((n,), bool),
        leaving=jnp.zeros((n,), bool),
        external=jnp.zeros((n,), bool),
        own_inc=jnp.ones((n,), jnp.uint32),
        own_tx=jnp.zeros((n,), jnp.int32),
        awareness=jnp.zeros((n,), jnp.int32),
        probe_perm=perm,
        probe_ptr=jnp.zeros((n,), jnp.int32),
        # Random stagger keeps probes desynchronized, like the
        # reference's randomized ticker start (state.go:104-121).
        next_probe_tick=jax.random.randint(
            k_stagger, (n,), 0, probe_period, jnp.int32
        ),
        pending_col=jnp.full((n,), -1, jnp.int32),
        pending_fail_tick=jnp.zeros((n,), jnp.int32),
        pending_nack_miss=jnp.zeros((n,), jnp.int32),
        view_key=jnp.full((n, k_deg), merge.make_key_int(1, merge.ALIVE),
                          jnp.uint32),
        susp_start=jnp.full((n, k_deg), -1, jnp.int32),
        susp_seen=jnp.zeros((n, k_deg), jnp.uint32),
        tx_left=jnp.zeros((n, k_deg), jnp.int32),
        viv=vivaldi.new(cfg.vivaldi, batch_shape=(n,)),
        lat_buf=jnp.zeros((n, k_deg, cfg.vivaldi.latency_filter_size), jnp.float32),
        lat_cnt=jnp.zeros((n, k_deg), jnp.int32),
    )


def template(cfg: SimConfig) -> SimState:
    """A shape/dtype-only SimState for checkpoint restore
    (utils/checkpoint.restore wants a template tree, never the values):
    tooling that inspects a checkpoint or a sentinel diagnostic dump —
    ``runtime.restore_placed``, post-mortem scripts — builds its target
    from the config alone instead of forming a whole Simulation just to
    overwrite its state."""
    return init(cfg, jax.random.PRNGKey(0))


def kill(state: SimState, mask) -> SimState:
    """Fault injection: hard-kill the masked nodes (they stop probing,
    acking, and gossiping; their entries elsewhere decay via SWIM)."""
    return state._replace(alive_truth=state.alive_truth & ~mask)


def revive(
    cfg: SimConfig,
    state: SimState,
    mask,
    cold: bool = False,
    join_seeds: int = 3,
) -> SimState:
    """Fault injection: restart the masked nodes with a bumped
    incarnation. Like a restarted agent's join (reference
    memberlist.Create setAlive -> aliveNode bootstrap broadcast,
    memberlist.go:206-228), the node announces itself via its own-fact
    channel at the new incarnation — without it, peers that believe the
    node dead would never probe it again.

    ``cold=True`` models a restart with no serf snapshot (reference
    serf/snapshot.go, handleRejoin serf.go:1705): the node forgets its
    member views — every entry drops to (0, DEAD), i.e. "never heard" —
    except for ``join_seeds`` seed entries believed ``(0, ALIVE)``,
    modeling the join addresses a restarted agent is configured with
    (reference memberlist.Join seeds push-pull toward known addresses,
    memberlist.go:228 -> pushPullNode state.go:595). The seeds are what
    make rejoin *possible*: every protocol action gates on believing
    the peer alive/suspect, so a view of all-DEAD would deadlock the
    node — it could never probe, gossip, or initiate push-pull, and
    nothing would ever flow back. From the seeds it relearns the
    cluster through the join storm (push-pull + epidemic). Warm revive
    (default) keeps the pre-crash views, the behavior a replayed
    snapshot buys.
    """
    from consul_tpu.ops import scaling  # local import to avoid cycle

    own_inc = jnp.where(mask, state.own_inc + 1, state.own_inc).astype(jnp.uint32)
    with jax.ensure_compile_time_eval():
        tx0 = int(scaling.retransmit_limit(cfg.gossip.retransmit_mult, cfg.n))
    if cfg.view_degree:
        # The rejoin announcement must cover all K trackers (one full
        # displacement sweep; see swim._gossip_phase coverage note).
        tx0 = max(tx0, cfg.degree)
    state = state._replace(
        alive_truth=state.alive_truth | mask,
        left=state.left & ~mask,
        leaving=state.leaving & ~mask,
        own_inc=own_inc,
        own_tx=jnp.where(mask, tx0, state.own_tx),
    )
    if cold:
        k_deg = state.view_key.shape[1]
        # Seed columns spread across the offset table so a block-kill
        # (contiguous rows) doesn't leave every seed pointing at another
        # cold node at small offsets.
        cols = jnp.arange(k_deg, dtype=jnp.int32)
        unknown = merge.make_key(0, merge.DEAD)
        if join_seeds <= 0:
            # No configured join addresses (snapshot.rejoin seeds its
            # own from the replayed alive set).
            seeded = jnp.full((k_deg,), unknown, jnp.uint32)
        else:
            stride = max(1, k_deg // min(join_seeds, k_deg))
            seeded = jnp.where((cols % stride) == 0,
                               merge.make_key(0, merge.ALIVE), unknown)
        m = mask[:, None]
        state = state._replace(
            view_key=jnp.where(m, seeded[None, :], state.view_key),
            susp_start=jnp.where(m, -1, state.susp_start),
            susp_seen=jnp.where(m, jnp.uint32(0), state.susp_seen),
            tx_left=jnp.where(m, 0, state.tx_left),
            lat_cnt=jnp.where(m, 0, state.lat_cnt),
        )
    return state
