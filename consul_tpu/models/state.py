"""SimState: the whole simulated cluster as one struct-of-arrays pytree.

Every Go-side per-node data structure of the reference becomes an array
over the node axis N (shardable across chips), and every goroutine/timer
becomes a deadline array compared against the global tick counter:

  reference structure                          -> array here
  ------------------------------------------------------------------
  nodeState map (memberlist/state.go)          -> view_key[N, K] packed
                                                  (incarnation, status)
  per-node probe ticker + shuffled node list   -> next_probe_tick[N],
    (state.go:83-121, :492-513)                   probe_perm[N, K], probe_ptr[N]
  outstanding probe + ack handler channels     -> pending_target[N],
    (state.go:262-457, :759-790)                  pending_fail_tick[N]
  suspicion time.AfterFunc timers + per-from   -> susp_start[N, K],
    confirmation map (suspicion.go)               susp_seen[N, K] (32-bucket
                                                  accuser hash bitmask)
  TransmitLimitedQueue btree (queue.go)        -> q_subject/q_key/q_from/
                                                  q_tx[N, B] fixed slots
  awareness score (awareness.go)               -> awareness[N]
  Vivaldi client + per-peer latency filter     -> viv (VivaldiState[N]),
    (coordinate/client.go)                        lat_buf[N, K, S], lat_cnt[N, K]
  node's own incarnation (state.go:840-864)    -> own_inc[N]

``alive_truth``/``left`` are the fault-injection ground truth: whether
the simulated process is actually up (the thing SWIM is trying to
detect), not anyone's belief.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from consul_tpu.config import SimConfig
from consul_tpu.ops import merge, vivaldi


class SimState(NamedTuple):
    t: jax.Array              # [] int32, global tick counter
    # -- ground truth (fault injection) -------------------------------
    alive_truth: jax.Array    # [N] bool — process actually up
    left: jax.Array           # [N] bool — gracefully departed
    leaving: jax.Array        # [N] bool — leave intent broadcast, still
                              # gossiping out the propagate window; such a
                              # node must NOT refute suspicions (serf
                              # Leave sets a state that suppresses
                              # refutation, serf/serf.go:675-…)
    # -- own per-node protocol state ----------------------------------
    own_inc: jax.Array        # [N] uint32
    awareness: jax.Array      # [N] int32, 0..awareness_max-1
    # -- probe scheduler ----------------------------------------------
    probe_perm: jax.Array     # [N, K] int32, per-node shuffled probe order
    probe_ptr: jax.Array      # [N] int32, cursor into probe_perm
    next_probe_tick: jax.Array  # [N] int32
    pending_target: jax.Array   # [N] int32 global id, -1 = no outstanding probe
    pending_fail_tick: jax.Array  # [N] int32, when the probe window closes
    # -- membership views ---------------------------------------------
    view_key: jax.Array       # [N, K] uint32 packed (incarnation, status)
    susp_start: jax.Array     # [N, K] int32, tick suspicion began, -1 = none
    susp_seen: jax.Array      # [N, K] uint32, accuser-hash bitmask
    # -- gossip broadcast queue ---------------------------------------
    q_subject: jax.Array      # [N, B] int32, -1 = empty slot
    q_key: jax.Array          # [N, B] uint32
    q_from: jax.Array         # [N, B] int32 original accuser/source
    q_tx: jax.Array           # [N, B] int32 transmits remaining
    # -- Vivaldi ------------------------------------------------------
    viv: vivaldi.VivaldiState  # batched [N]
    lat_buf: jax.Array        # [N, K, S] float32 per-peer RTT samples
    lat_cnt: jax.Array        # [N, K] int32 samples pushed


def init(cfg: SimConfig, key) -> SimState:
    """A formed cluster at steady state: every node knows every neighbor
    as alive at incarnation 1, coordinates at the origin, queues empty.

    (The reference reaches this state through the join/push-pull storm;
    the join process itself is exercised separately via fault injection —
    reviving killed ranges — and the serf intent layer.)
    """
    n, k_deg, b = cfg.n, cfg.degree, cfg.gossip.queue_slots
    k_perm, k_stagger = jax.random.split(key)
    # Per-node shuffled probe order over neighbor columns
    # (reference shuffles the node list per wrap, state.go:492-513).
    perm = jax.vmap(lambda k2: jax.random.permutation(k2, k_deg))(
        jax.random.split(k_perm, n)
    ).astype(jnp.int32)
    probe_period = cfg.gossip.probe_period_ticks
    return SimState(
        t=jnp.int32(0),
        alive_truth=jnp.ones((n,), bool),
        left=jnp.zeros((n,), bool),
        leaving=jnp.zeros((n,), bool),
        own_inc=jnp.ones((n,), jnp.uint32),
        awareness=jnp.zeros((n,), jnp.int32),
        probe_perm=perm,
        probe_ptr=jnp.zeros((n,), jnp.int32),
        # Random stagger keeps probes desynchronized, like the
        # reference's randomized ticker start (state.go:104-121).
        next_probe_tick=jax.random.randint(
            k_stagger, (n,), 0, probe_period, jnp.int32
        ),
        pending_target=jnp.full((n,), -1, jnp.int32),
        pending_fail_tick=jnp.zeros((n,), jnp.int32),
        view_key=jnp.full((n, k_deg), int(merge.make_key(1, merge.ALIVE)), jnp.uint32),
        susp_start=jnp.full((n, k_deg), -1, jnp.int32),
        susp_seen=jnp.zeros((n, k_deg), jnp.uint32),
        q_subject=jnp.full((n, b), -1, jnp.int32),
        q_key=jnp.zeros((n, b), jnp.uint32),
        q_from=jnp.full((n, b), -1, jnp.int32),
        q_tx=jnp.zeros((n, b), jnp.int32),
        viv=vivaldi.new(cfg.vivaldi, batch_shape=(n,)),
        lat_buf=jnp.zeros((n, k_deg, cfg.vivaldi.latency_filter_size), jnp.float32),
        lat_cnt=jnp.zeros((n, k_deg), jnp.int32),
    )


def kill(state: SimState, mask) -> SimState:
    """Fault injection: hard-kill the masked nodes (they stop probing,
    acking, and gossiping; their entries elsewhere decay via SWIM)."""
    return state._replace(alive_truth=state.alive_truth & ~mask)


def revive(cfg: SimConfig, state: SimState, mask) -> SimState:
    """Fault injection: restart the masked nodes with a bumped
    incarnation. Like a restarted agent's join (reference
    memberlist.Create setAlive -> aliveNode bootstrap broadcast,
    memberlist.go:206-228), the node announces itself by queueing an
    alive broadcast at its new incarnation — without it, peers that
    believe the node dead would never probe it again.
    """
    from consul_tpu.ops import scaling  # local import to avoid cycle

    n = cfg.n
    own_inc = jnp.where(mask, state.own_inc + 1, state.own_inc).astype(jnp.uint32)
    rows = jnp.arange(n, dtype=jnp.int32)
    slot0 = jnp.zeros_like(state.q_subject[..., 0], jnp.int32)[..., None] == jnp.arange(
        state.q_subject.shape[-1], dtype=jnp.int32
    )
    write = mask[..., None] & slot0
    with jax.ensure_compile_time_eval():
        tx0 = int(scaling.retransmit_limit(cfg.gossip.retransmit_mult, n))
    return state._replace(
        alive_truth=state.alive_truth | mask,
        left=state.left & ~mask,
        leaving=state.leaving & ~mask,
        own_inc=own_inc,
        q_subject=jnp.where(write, rows[..., None], state.q_subject),
        q_key=jnp.where(write, merge.make_key(own_inc, merge.ALIVE)[..., None], state.q_key),
        q_from=jnp.where(write, rows[..., None], state.q_from),
        q_tx=jnp.where(write, tx0, state.q_tx),
    )
