"""The vectorized SWIM + Lifeguard step function.

One call to :func:`step` advances every simulated node by one tick
(default 200 ms of protocol time — the LAN gossip interval). Where the
reference runs a goroutine per node with tickers and callback timers
(reference memberlist/state.go:83-121 schedule, suspicion.go timers),
this is a single pure function over struct-of-arrays, so XLA fuses the
whole protocol round into a few kernels and the node axis shards across
chips.

**TPU-first delivery plane (the round-2 redesign).** Every message
exchange is formulated *receiver-side*: instead of senders scattering
into receiver state (`scatter-max`, which XLA serializes on TPU), each
receiver *gathers* from its senders. The circulant topology
(ops/topology.py) makes this a dense re-indexing: the in-column-``j``
sender of node ``r`` is ``r - off[j]``, so "fetch what my sender did"
is ``jnp.roll(sender_array, off[j])``, and the column any gossiped
subject lands in at the receiver is the static table
``remap_row(topo, j)``. The step therefore avoids per-row-indexed
gathers entirely: per-row column selection is one-hot compare-select
(:func:`_take_cols`), per-row *node* indexing is a K-unrolled
static-shift roll accumulation (:func:`_gather_by_col` — the offsets
are trace-time constants), and cross-node delivery is rolls. The hot
path contains no scatter and no per-row gather.

Measured (TPU v5 lite, 2026-07-30, n=262144/K=32, whole-step A/B —
BASELINE.md "formulation validation"): swapping :func:`_take_cols` for
``take_along_axis`` drops the step from 141 to 11.3 rounds/s (12x) —
the native gather wins an isolated microbenchmark but destroys XLA's
fusion of the merge chain in context; swapping :func:`_gather_by_col`
for a cross-row gather drops it to 72.8 (2x). Re-run the A/B before
believing any "gathers are fine now" microbenchmark.

Tick anatomy (mirroring one round of the reference's event loop):

  1. **Suspicion expiry** — per-edge Lifeguard deadline check
     (remainingSuspicionTime, suspicion.go:86-97); expired suspects are
     declared dead locally (state.go:1141-1156); the state change
     re-arms the entry's retransmit budget, which *is* the broadcast.
  2. **Probe resolution** — probe windows that close this tick with no
     ack mark the target suspect (state.go:437-456) and charge
     awareness for the failed cycle plus every missing indirect-probe
     nack (Lifeguard NACK deltas, state.go:437-451).
  3. **Probe launch** — nodes whose probe ticker fires pick the next
     non-dead target in their shuffled order (state.go:193-235), send a
     ping; a direct ack within the timeout feeds Vivaldi with the RTT
     and the peer's coordinate payload (ping_delegate semantics,
     state.go:342-347); otherwise indirect probes through k relays and
     a TCP fallback are modeled (state.go:366-435), and total failure
     opens a pending suspicion window.
  4. **Gossip** — each live node piggybacks its hottest broadcasts
     (fewest-transmits-first, queue.go:288-373 = highest remaining
     budget) to ``gossip_nodes`` peers (state.go:517-567, net.go:631);
     deliveries merge into receiver views via the (incarnation, status)
     join semilattice; newly-learned facts re-arm their budget (the
     epidemic); suspect messages about already-suspect entries register
     Lifeguard confirmations (suspicion.go:103-129); and messages about
     the receiver itself trigger refutation (state.go:840-864).
  5. **Push-pull anti-entropy** — nodes on their staggered cadence
     exchange full views with one partner, both ways, with remote dead
     claims demoted to suspicion (state.go:573-608, :1217-1240).
  6. **Suspicion bookkeeping** — one reconciliation pass derives timer
     starts/resets from the view delta of this tick, then re-arms the
     retransmit budget of every entry that changed.

Documented vectorization divergences from the reference (each argued in
SURVEY.md §7 "hard parts"): the per-tick gossip peers and indirect-probe
relays are the *same random displacement set for every node* (vs
per-node rejection-sampled distinct peers, util.go:125-153) —
displacements are i.i.d. across ticks, so the epidemic still spreads
along O(log N) random generator sums; the within-tick displacement
draws are with replacement; push-pull partners likewise share one
displacement per tick (stagger spreads real pairs across ticks);
Lifeguard confirmations ride the accumulated 32-bucket accuser bitmask
of an entry rather than one accuser per message (collisions undercount,
which only lengthens the timeout — the safe direction); packet-size
packing of the 1400-byte UDP budget is modeled by the
``piggyback_msgs`` cap, not enforced by bytes; gossip to the dead is
not modeled (dead processes cannot receive in the simulation's ground
truth).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from consul_tpu.chaos import schedule as chaos_mod
from consul_tpu.config import SimConfig
from consul_tpu.models import counters as counters_mod
from consul_tpu.models import state as sim_state_mod
from consul_tpu.models.state import SimState, own_key as _own_key
from consul_tpu.ops import merge, scaling, topology, vivaldi
from consul_tpu.ops.topology import Topology, World
from consul_tpu.parallel import collective as coll

_NEG = jnp.int32(-1)

# Above this degree the K-unrolled roll paths would bloat the program;
# fall back to plain gathers (only the dense/small configurations).
_ROLL_DEGREE_MAX = 256


def _statuses(view_key):
    return merge.key_status(view_key)


def _accuser_bit(node_id):
    """32-bucket hash bitmask bit for a confirming accuser (dedup
    approximation of the reference's per-from confirmation map,
    suspicion.go:42-59; collisions undercount, which only lengthens
    the timeout — the safe direction)."""
    return (jnp.uint32(1) << (jnp.asarray(node_id, jnp.uint32) % 32)).astype(jnp.uint32)


def _popcount(x):
    return jax.lax.population_count(jnp.asarray(x, jnp.uint32))


# ----------------------------------------------------------------------
# Gather-free primitives (see module docstring: per-row-indexed gathers
# are ~40x slower than dense compare-select on TPU).
# ----------------------------------------------------------------------

def _take_cols(table: jax.Array, cols: jax.Array, fill=0):
    """Per-row column selection, ``out[i, p] = table[i, cols[i, p]]``,
    with out-of-range ``cols`` yielding ``fill``.

    One-hot compare-select when K is small (per-row-indexed gathers
    measure ~40x slower per element on TPU v5e); a plain gather when K
    is large (dense mode), where the one-hot's K-fold blowup loses."""
    k = table.shape[1]
    ok = (cols >= 0) & (cols < k)
    if k <= _ROLL_DEGREE_MAX:
        oh = cols[:, None, :] == jnp.arange(k, dtype=jnp.int32)[None, :, None]
        t = table.astype(jnp.int32) if table.dtype == jnp.bool_ else table
        vals = jnp.sum(jnp.where(oh, t[:, :, None], 0), axis=1)
        vals = jnp.where(ok, vals, fill)
        return vals.astype(bool) if table.dtype == jnp.bool_ else \
            vals.astype(table.dtype)
    vals = jnp.take_along_axis(table, jnp.where(ok, cols, 0), axis=1)
    return jnp.where(ok, vals, jnp.asarray(fill, table.dtype))


def _take_col(table: jax.Array, col: jax.Array, fill=0):
    """Single-column variant: ``out[i] = table[i, col[i]]``."""
    return _take_cols(table, col[:, None], fill)[:, 0]


def _vec_at(vec: jax.Array, idx: jax.Array):
    """``vec[idx]`` for a table ``vec[K]`` and any-shaped in-range
    ``idx`` — one-hot over K when small, gather otherwise."""
    k = vec.shape[0]
    if k <= _ROLL_DEGREE_MAX:
        oh = idx[..., None] == jnp.arange(k, dtype=jnp.int32)
        return jnp.sum(jnp.where(oh, vec, 0), axis=-1).astype(vec.dtype)
    return vec[idx]


def _gather_by_col(topo: Topology, packed: jax.Array, col: jax.Array,
                   forward: bool = True):
    """``packed[(i + off[col[i]]) % n]`` (forward) without a per-row
    gather: K-unrolled static-shift rolls selected per row. The offsets
    are normally trace-time constants, so every roll is a static
    slice+concat; with a program-argument topology (chaos/sweep.py
    passes ``topo.off`` traced so same-shape families share one
    executable) the K rolls carry traced shifts instead — coll.roll
    handles both. ``packed`` is [N, F]; ``col`` is [N] and must be in
    range where the result is consumed."""
    off = topo.off
    if isinstance(off, jax.core.Tracer):
        shifts = [off[j] for j in range(topo.degree)]
    else:
        off_np = np.asarray(off)
        shifts = [int(off_np[j]) for j in range(topo.degree)]
    acc = jnp.zeros_like(packed)
    for j, shift in enumerate(shifts):
        rolled = coll.roll(packed, -shift if forward else shift)
        acc = jnp.where((col == j)[:, None], rolled, acc)
    return acc


def step(cfg: SimConfig, topo: Topology, world: World, state: SimState, key,
         sched=None, *, sentinel: bool = False) -> SimState:
    """Advance the whole cluster by one tick. Pure; jit/shard-map safe.

    Thin wrapper over :func:`step_counted` discarding the counters —
    XLA dead-code-eliminates the counter reductions, so callers that
    only want the state pay nothing for them."""
    return step_counted(cfg, topo, world, state, key, sched,
                        sentinel=sentinel)[0]


def step_counted(cfg: SimConfig, topo: Topology, world: World, state: SimState,
                 key, sched=None, *, sentinel: bool = False, extra_tx=None):
    """One tick plus its :class:`counters.GossipCounters` event tallies
    (probes, acks/nacks, suspicions, deaths, gossip tx/rx, push-pull
    merges, refutations) — every counter is a reduction over masks the
    step already computes, so the tally adds no communication. Under
    ``shard_map`` the sums are shard-local; parallel/shard_step.py
    psums them into global totals.

    ``extra_tx`` (the serf fusion hook, models/serf.py) is an optional
    list of per-node payload arrays ([N] or [N, P], roll_many dtypes)
    that ride the SAME gossip exchange as the membership plane — one
    roll per displacement leg carries both planes' packets. When given,
    the return value grows a third element ``(ex_legs, ex_n_sends)``:
    ``ex_legs`` is a list of fan ``(payload_arrays, arrived[N])`` pairs
    (the extra payload as seen by each receiver, plus the per-leg
    delivery mask), and ``ex_n_sends[N] i32`` counts how many legs each
    sender actually reached. The extra plane has its OWN sender gate —
    ``alive_truth & ~left``, which INCLUDES external bridge seats: an
    attached agent originates serf events through its seat
    (wire/bridge.py), while the membership plane's ``active`` excludes
    external seats because their real agent runs SWIM itself. ``None``
    (the default) emits exactly the pre-fusion program — the extra
    plane is dead code XLA eliminates.

    ``sched`` is an optional :class:`chaos.ChaosSchedule` — a device
    pytree of tick-indexed faults entering as a program ARGUMENT, so
    same-shape schedules share one executable. ``None`` or an empty
    schedule is a trace-time branch: the emitted program is exactly the
    schedule-free step. With faults installed, every delivery leg keeps
    its existing uniform draw and gates on ``chaos.pair_ok`` instead of
    the bare ``cfg.packet_loss`` threshold, churn waves drive
    kill/revive edges on-device, and the SLO block at the end of the
    tick accumulates detection/heal latencies into the counters.

    ``sentinel`` (trace-time flag, consul_tpu/runtime) folds the
    end-of-tick invariant validator :func:`_sentinel_check` into the
    program; ``False`` (the default) emits exactly the pre-sentinel
    step — the compile-count pin of tests/test_runtime.py."""
    n, k_deg = cfg.n, cfg.degree
    g = cfg.gossip
    t = state.t
    rows = coll.rows(n)
    keys = jax.random.split(key, 10)
    chaos_on = sched is not None and not chaos_mod.is_empty(sched)
    if chaos_on:
        # Churn edges first: a wave starting this tick kills its nodes
        # before the tick runs (they stop probing/acking/gossiping,
        # exactly like host-side kill between chunks); a wave ending
        # revives them warm with a bumped incarnation — the restarted
        # agent's rejoin announcement (models/state.py revive).
        down_now = chaos_mod.down_at(sched, t)
        down_prev = chaos_mod.down_at(sched, t - 1)
        state = sim_state_mod.kill(state, down_now & ~down_prev)
        state = sim_state_mod.revive(cfg, state, down_prev & ~down_now)
        terms = chaos_mod.node_terms(sched, t)
    else:
        terms = None
    # Dense (or very-high-degree) mode runs the gather formulation:
    # probe-target attributes are read by global row id through
    # coll.take_rows — a plain gather single-chip, an all-gather +
    # local gather under shard_map (dense is a <=few-k-node shape, so
    # the gathered tables are KBs; the gossip/push-pull planes ride
    # the same rolls as sparse mode either way).
    roll_mode = (not topo.dense) and k_deg <= _ROLL_DEGREE_MAX

    view0 = state.view_key  # snapshot for end-of-tick bookkeeping
    seen0 = state.susp_seen
    own0 = state.own_inc  # sentinel monotonicity baseline
    active = state.alive_truth & ~state.left & ~state.external

    # Static protocol scalars (cluster-size scaling laws); evaluated at
    # trace time — they depend only on the static cluster size.
    with jax.ensure_compile_time_eval():
        tx_limit = int(scaling.retransmit_limit(g.retransmit_mult, n))
        susp_min = float(
            scaling.suspicion_timeout(g.suspicion_mult, n, g.probe_period_ticks)
        )
        susp_max = g.suspicion_max_timeout_mult * susp_min
        susp_k = int(scaling.suspicion_k(g.suspicion_mult, n))
        pp_period = g.push_pull_period_ticks(n)
        # A self-fact (refutation, rejoin announcement) must reach the
        # node's K specific trackers — not any log(n) members of the
        # full graph — so its budget covers at least one full
        # displacement sweep in sparse mode (see _gossip_phase).
        own_limit = tx_limit if topo.dense else max(tx_limit, k_deg)

    # ------------------------------------------------------------------
    # 1. Suspicion expiry: per-edge deadline check. The local state
    #    change (suspect -> dead) is itself the broadcast: the end-of-
    #    tick budget re-arm queues it for gossip (state.go:1141-1156).
    # ------------------------------------------------------------------
    statuses = _statuses(state.view_key)
    is_suspect = (statuses == merge.SUSPECT) & (state.susp_start >= 0)
    confirms = jnp.maximum(
        _popcount(state.susp_seen).astype(jnp.int32) - 1, 0
    )  # the original accuser is excluded (suspicion.go:58-59)
    elapsed = (t - state.susp_start).astype(jnp.float32)
    remaining = scaling.remaining_suspicion_time(
        confirms, susp_k, elapsed, susp_min, susp_max
    )
    expired = is_suspect & (remaining <= 0.0) & active[:, None]
    dead_key = merge.make_key(merge.key_incarnation(state.view_key), merge.DEAD)
    state = state._replace(view_key=jnp.where(expired, dead_key, state.view_key))
    n_deaths = counters_mod.count(expired)

    # ------------------------------------------------------------------
    # 2. Probe windows closing this tick with no ack -> suspect target,
    #    register self as accuser, charge awareness (+1 for the failed
    #    cycle, +1 per missing nack; state.go:437-456, awareness.go).
    # ------------------------------------------------------------------
    failing = (state.pending_col >= 0) & (t >= state.pending_fail_tick) & active
    n_timeouts = counters_mod.count(failing)
    fcol = jnp.where(failing, state.pending_col, 0)
    fentry = _take_col(state.view_key, fcol)
    # suspectNode applies to alive entries at the known incarnation
    # (state.go:1086-1122); for already-suspect entries the join is a
    # no-op and only the accuser bit below registers (a confirmation).
    fsus_key = merge.make_key(merge.key_incarnation(fentry), merge.SUSPECT)
    fail_oh = (jnp.arange(k_deg, dtype=jnp.int32)[None, :] == fcol[:, None]) \
        & failing[:, None]
    view = jnp.where(
        fail_oh, merge.join(state.view_key, fsus_key[:, None]), state.view_key
    )
    susp_seen = state.susp_seen | jnp.where(fail_oh, _accuser_bit(rows)[:, None], 0)
    awareness = jnp.clip(
        state.awareness
        + jnp.where(failing, 1 + state.pending_nack_miss, 0),
        0, g.awareness_max - 1,
    )
    state = state._replace(
        view_key=view,
        susp_seen=susp_seen,
        awareness=awareness,
        pending_col=jnp.where(failing, _NEG, state.pending_col),
        pending_nack_miss=jnp.where(failing, 0, state.pending_nack_miss),
    )

    # ------------------------------------------------------------------
    # 3. Probe launch.
    # ------------------------------------------------------------------
    probing = active & (t >= state.next_probe_tick)
    # Next contactable target in the shuffled order, looking ahead up to
    # 3 (the reference's skip loop, state.go:196-231).
    cand_off = jnp.arange(3, dtype=jnp.int32)
    cand_pos = (state.probe_ptr[:, None] + cand_off[None, :]) % k_deg
    cand_col = _take_cols(state.probe_perm, cand_pos)
    cand_ok = _take_cols(
        merge.is_contactable(state.view_key), cand_col, fill=False
    )
    has_target = jnp.any(cand_ok, axis=1) & probing
    first_ok = jnp.argmax(cand_ok, axis=1).astype(jnp.int32)
    target_col = _take_col(cand_col, first_ok)
    advance = jnp.where(probing, jnp.where(has_target, first_ok + 1, 3), 0)

    # Target attributes, fetched without per-row gathers: pack what the
    # prober needs to know about its target into [N, F] and select the
    # per-row shift (see _gather_by_col).
    viv = state.viv
    if roll_mode:
        cols = [
            (state.alive_truth & ~state.left).astype(jnp.float32)[:, None],
            world.pos,
            world.height[:, None],
            viv.vec,
            viv.height[:, None],
            viv.error[:, None],
            viv.adjustment[:, None],
        ]
        if chaos_on:
            # Target chaos terms ride the same packed gather; the int
            # bitfields are < 2^20 so the f32 trip is exact
            # (chaos/schedule.py MAX_* caps).
            cols += [
                terms.color.astype(jnp.float32)[:, None],
                terms.a_bits.astype(jnp.float32)[:, None],
                terms.b_bits.astype(jnp.float32)[:, None],
                terms.q_tx[:, None],
                terms.q_rx[:, None],
            ]
        packed = jnp.concatenate(cols, axis=1)
        tat = _gather_by_col(topo, packed, jnp.where(has_target, target_col, 0))
        wd = world.pos.shape[1]
        target_up = (tat[:, 0] > 0.5) & has_target
        t_pos, t_h = tat[:, 1:1 + wd], tat[:, 1 + wd]
        vd = viv.vec.shape[1]
        t_vec = tat[:, 2 + wd:2 + wd + vd]
        t_vh, t_verr, t_vadj = (
            tat[:, 2 + wd + vd], tat[:, 3 + wd + vd], tat[:, 4 + wd + vd]
        )
        if chaos_on:
            cb = 5 + wd + vd
            tgt_terms = chaos_mod.NodeTerms(
                color=tat[:, cb].astype(jnp.int32),
                a_bits=tat[:, cb + 1].astype(jnp.int32),
                b_bits=tat[:, cb + 2].astype(jnp.int32),
                q_tx=tat[:, cb + 3],
                q_rx=tat[:, cb + 4],
            )
    else:
        target = topology.neighbor_of(topo, rows, target_col)
        target_up = coll.take_rows(
            state.alive_truth & ~state.left, target) & has_target
        t_pos = coll.take_rows(world.pos, target)
        t_h = coll.take_rows(world.height, target)
        t_vec = coll.take_rows(viv.vec, target)
        t_vh = coll.take_rows(viv.height, target)
        t_verr = coll.take_rows(viv.error, target)
        t_vadj = coll.take_rows(viv.adjustment, target)
        if chaos_on:
            tgt_terms = chaos_mod.NodeTerms(
                *(coll.take_rows(x, target) for x in terms)
            )
    # The RTT model lives ONCE, shared by both target-attribute paths
    # (ops/topology.true_rtt semantics, jitter drawn shard-aware): a
    # latency-model change cannot diverge roll vs gather mode.
    true_rtt = (
        jnp.linalg.norm(world.pos - t_pos, axis=1) + world.height + t_h
    )
    jitter = coll.normal_rows(keys[0], n) * cfg.rtt_jitter_frac
    rtt_obs = true_rtt * jnp.exp(jitter) if cfg.rtt_jitter_frac > 0 else true_rtt

    timeout_s = g.probe_timeout_ms / 1000.0
    pl = cfg.packet_loss
    u2 = coll.uniform_rows(keys[1], n, (2,))  # direct, TCP legs
    if chaos_on:
        # Same uniform draws as the plain model; only the survival
        # threshold changes (chaos/schedule.py pair_ok). The direct
        # probe and the TCP fallback each model a full round trip on
        # one draw, so both directions' chaos terms compose onto it.
        ok_direct_leg = chaos_mod.pair_ok(
            sched, terms, tgt_terms, u2[:, 0], pl, round_trip=True
        )
        ok_tcp_leg = chaos_mod.pair_ok(
            sched, terms, tgt_terms, u2[:, 1], pl, round_trip=True
        )
    else:
        ok_direct_leg = u2[:, 0] >= pl
        ok_tcp_leg = u2[:, 1] >= pl
    direct_ok = has_target & target_up & (rtt_obs <= timeout_s) & ok_direct_leg
    # Indirect probes via k relays + TCP fallback (state.go:366-435),
    # relay displacements shared per tick like the gossip fan. Legs:
    # prober->relay (a), relay<->target (b), nack return (c).
    ic = g.indirect_checks
    relay_jcols = jax.random.randint(keys[2], (ic,), 0, k_deg)
    relay_ok_nodes = active  # relays must be live non-external members
    relay_avail = jnp.stack(
        [
            coll.roll(relay_ok_nodes, -topo.off[relay_jcols[i]])
            for i in range(ic)
        ],
        axis=1,
    )
    u_a = coll.uniform_rows(keys[3], n, (ic,))
    u_b = coll.uniform_rows(keys[4], n, (ic,))
    u_c = coll.uniform_rows(keys[5], n, (ic,))
    if chaos_on:
        oka, okb, okc = [], [], []
        for i in range(ic):
            # The column-c relay's terms land at the prober's row via
            # the same traced-shift roll that checked its liveness.
            rt = chaos_mod.roll_terms(terms, -topo.off[relay_jcols[i]])
            oka.append(chaos_mod.pair_ok(sched, terms, rt, u_a[:, i], pl))
            okb.append(chaos_mod.pair_ok(
                sched, rt, tgt_terms, u_b[:, i], pl, round_trip=True))
            okc.append(chaos_mod.pair_ok(sched, rt, terms, u_c[:, i], pl))
        ok_a = jnp.stack(oka, axis=1)
        ok_b = jnp.stack(okb, axis=1)
        ok_c = jnp.stack(okc, axis=1)
    else:
        ok_a = u_a >= pl
        ok_b = u_b >= pl
        ok_c = u_c >= pl
    relay_reached = relay_avail & ok_a
    relay_ok = relay_reached & target_up[:, None] & ok_b
    indirect_ok = has_target & jnp.any(relay_ok, axis=1) & ~direct_ok
    tcp_ok = has_target & target_up & ok_tcp_leg
    acked = direct_ok | indirect_ok | tcp_ok
    # Nacks: a relay that got the request but could not reach the
    # target replies nack (state.go:437-451). On a failed cycle every
    # nack that never arrived is an awareness penalty.
    nack_rcvd = relay_reached & ~(target_up[:, None] & ok_b) & ok_c
    nack_miss = ic - jnp.sum(nack_rcvd, axis=1).astype(jnp.int32)
    # Counter view of the probe plane: launches, acks, and the nacks
    # that actually rode a failed-direct cycle (indirect probes only
    # fire after the direct leg misses, state.go:366-435).
    n_probes = counters_mod.count(has_target)
    n_acks = counters_mod.count(acked)
    n_nacks = counters_mod.count(
        nack_rcvd & (has_target & ~direct_ok)[:, None]
    )

    # A ping to a suspect target carries a suspect message so it can
    # refute immediately (compound ping+suspect, state.go:306-331);
    # delivered receiver-side in the gossip phase below.
    target_entry = _take_col(state.view_key, jnp.where(has_target, target_col, 0))
    target_status = merge.key_status(jnp.where(has_target, target_entry, 0))
    target_inc = merge.key_incarnation(target_entry)
    poke_flag = has_target & (target_status == merge.SUSPECT) & ok_direct_leg
    poke_col = jnp.where(has_target, target_col, _NEG)

    # Probe bookkeeping: window for failures, ticker reschedule scaled
    # by local health (awareness.ScaleTimeout, state.go:268).
    pending_col = jnp.where(has_target & ~acked, target_col, state.pending_col)
    pending_fail_tick = jnp.where(
        has_target & ~acked, t + g.probe_period_ticks, state.pending_fail_tick
    )
    pending_nack_miss = jnp.where(
        has_target & ~acked, nack_miss, state.pending_nack_miss
    )
    interval = g.probe_period_ticks * (state.awareness + 1)
    next_probe = jnp.where(probing, t + interval, state.next_probe_tick)
    awareness = jnp.clip(
        state.awareness - jnp.where(acked, 1, 0), 0, g.awareness_max - 1
    )
    ptr = state.probe_ptr + advance
    # Global reshuffle when the slowest cursor wraps (approximates the
    # per-wrap shuffle of state.go:492-513).
    wrapped = ptr >= k_deg
    if coll.in_kernel():
        # Kernel-callable core: no cond (Mosaic can't branch around a
        # pytree operand) and no argsort (sort-lowered). The draw and
        # the unconditional argmin peel produce exactly the cond's
        # taken-branch permutation; rows that did not wrap keep their
        # old perm through the same where-mask below, so the result is
        # bit-identical in both the wrapped and idle cases.
        perm = _argsort_peel(coll.uniform_rows(keys[6], n, (k_deg,)))
    else:
        perm = jax.lax.cond(
            coll.any_rows(wrapped),
            lambda p: jnp.argsort(
                coll.uniform_rows(keys[6], n, (k_deg,)), axis=1
            ).astype(jnp.int32),
            lambda p: p,
            state.probe_perm,
        )
    probe_perm = jnp.where(wrapped[:, None], perm, state.probe_perm)
    # A successful ack is first-hand evidence from the target itself:
    # join (target_incarnation, ALIVE) at the target's column. This is
    # the vectorized form of the refute reply reaching its prober — in
    # the reference the refute is a broadcast (state.go:840-864) whose
    # first hop through the full graph is effectively immediate; in a
    # sparse view plane the prober must hear it on the ack path or a
    # suspicion of a live, acking node could outlive its refutation.
    if roll_mode:
        t_inc = _gather_by_col(
            topo, state.own_inc[:, None],
            jnp.where(has_target, target_col, 0),
        )[:, 0]
    else:
        t_inc = coll.take_rows(state.own_inc, target)
    ack_oh = (
        jnp.arange(k_deg, dtype=jnp.int32)[None, :]
        == jnp.where(acked, target_col, _NEG)[:, None]
    )
    ack_key = merge.make_key(t_inc, merge.ALIVE)
    view_acked = merge.join(
        state.view_key, jnp.where(ack_oh, ack_key[:, None], jnp.uint32(0))
    )

    state = state._replace(
        view_key=view_acked,
        probe_ptr=jnp.where(wrapped, 0, ptr),
        probe_perm=probe_perm,
        next_probe_tick=next_probe,
        pending_col=pending_col,
        pending_fail_tick=pending_fail_tick,
        pending_nack_miss=pending_nack_miss,
        awareness=awareness,
    )

    # Direct ack feeds Vivaldi: RTT through the per-peer median filter,
    # peer coordinate as the ack payload (ping_delegate.go:28-90).
    state = _vivaldi_observe(
        cfg, state, direct_ok, target_col, rtt_obs,
        t_vec, t_vh, t_verr, t_vadj, keys[7],
    )

    # ------------------------------------------------------------------
    # 4. Gossip fan-out and delivery (receiver-side; no scatters).
    # ------------------------------------------------------------------
    gossip_out = _gossip_phase(
        cfg, topo, state, active, keys[8], tx_limit,
        sched if chaos_on else None, terms, extra_tx=extra_tx,
    )
    (state, refute_gossip, n_gossip_tx, n_gossip_rx, n_chaos_drop,
     n_gossip_msgs) = gossip_out[:6]
    refute_poke = _poke_refutes(
        cfg, topo, state, poke_flag, poke_col, target_inc
    )

    # ------------------------------------------------------------------
    # 5. Push-pull anti-entropy (receiver-side, both directions).
    # ------------------------------------------------------------------
    state, refute_pp, n_pp_merges = _push_pull_phase(
        cfg, topo, state, active, pp_period, keys[9],
        sched if chaos_on else None, terms,
    )

    # ------------------------------------------------------------------
    # Refutation: bump own incarnation past any accusation and re-arm
    # the own-fact broadcast (state.go:840-864). Costs health.
    # ------------------------------------------------------------------
    claim = jnp.maximum(jnp.maximum(refute_gossip, refute_poke), refute_pp)
    # A node with a broadcast leave intent does not refute — refuting
    # would outrank its own LEFT record in the merge lattice and convert
    # the graceful departure into a detected failure.
    refuting = (claim > 0) & active & ~state.leaving
    own_inc = jnp.where(refuting, claim + 1, state.own_inc).astype(jnp.uint32)
    state = state._replace(
        own_inc=own_inc,
        own_tx=jnp.where(refuting, own_limit, state.own_tx),
        awareness=jnp.clip(
            state.awareness + jnp.where(refuting, 1, 0), 0, g.awareness_max - 1
        ),
    )

    # ------------------------------------------------------------------
    # 6. Suspicion bookkeeping from this tick's view delta, then re-arm
    #    the retransmit budget of every changed entry (the reference
    #    queues a broadcast wherever state changed; new accuser bits on
    #    a still-suspect entry also re-gossip, suspicion.go:103-129).
    # ------------------------------------------------------------------
    state, n_susp = _reconcile_suspicion(state, view0, t)
    changed = (state.view_key != view0) | ((state.susp_seen & ~seen0) != 0)
    state = state._replace(
        tx_left=jnp.where(changed & active[:, None], tx_limit, state.tx_left)
    )
    # Canonicalize the probe-window deadline while no probe is
    # outstanding: its only reader gates on pending_col >= 0 (phase 2),
    # so pinning it to the current tick is unobservable — and it keeps
    # the tick-anchored i16 delta of the packed StateLayout exact for
    # every live window (models/layout.py).
    state = state._replace(
        pending_fail_tick=jnp.where(
            state.pending_col < 0, t, state.pending_fail_tick
        )
    )

    cnt = counters_mod.zeros()._replace(
        probes_sent=n_probes,
        acks_received=n_acks,
        nacks_received=n_nacks,
        probe_timeouts=n_timeouts,
        suspicions_started=n_susp,
        refutations=counters_mod.count(refuting),
        deaths_declared=n_deaths,
        gossip_tx=n_gossip_tx,
        gossip_rx=n_gossip_rx,
        gossip_msgs_tx=n_gossip_msgs,
        pushpull_merges=n_pp_merges,
    )
    if chaos_on:
        cnt = _chaos_slo(
            cfg, topo, state, sched, terms, t, roll_mode, expired, active,
            n_chaos_drop, cnt,
        )
    if sentinel:
        cnt = _sentinel_check(cfg, state, view0, own0, t, cnt)
    out_state = state._replace(t=t + 1)
    if extra_tx is not None:
        return out_state, cnt, gossip_out[6]
    return out_state, cnt


def _sentinel_check(cfg, state: SimState, view0, own0, t, cnt):
    """On-device invariant sentinel (consul_tpu/runtime): validate the
    end-of-tick state against invariants the protocol is supposed to
    preserve and tally violations into the sentinel_* counters. Every
    check is a reduction over per-row masks — no communication, and
    under shard_map the shard-local tallies psum to global counts like
    every other counter.

    Invariants (the Lifeguard posture turned inward — the *simulator*
    distrusts itself, PAPER.md):

    - **range**: own incarnations within the packed-key headroom
      (ops/merge.py MAX_INCARNATION), awareness inside
      [0, awareness_max), probe cursor and pending probe column inside
      their column ranges, suspicion timers never started in the future.
    - **monotonic**: view keys only move up the merge lattice within a
      tick (join = pointwise max; the only non-join writes land before
      the ``view0`` snapshot), and own incarnations never regress.
    - **suspicion**: after _reconcile_suspicion, a cell is SUSPECT iff
      its timer is armed iff its accuser bitmask is nonzero.
    - **nonfinite**: Vivaldi coordinates (vec/height/error/adjustment)
      and every written RTT-filter slot are finite — the NaN/Inf guard
      for the float plane (ops/vivaldi.py rejects non-finite inputs, so
      a nonzero tally here means corruption, not a bad sample).
    """
    g = cfg.gossip
    k_deg = cfg.degree
    viv = state.viv

    bad_range = (
        (state.own_inc > jnp.uint32(merge.MAX_INCARNATION))
        | (state.awareness < 0)
        | (state.awareness >= g.awareness_max)
        | (state.probe_ptr < 0)
        | (state.probe_ptr >= k_deg)
        | (state.pending_col < -1)
        | (state.pending_col >= k_deg)
        | jnp.any(state.susp_start > t, axis=1)
    )

    n_mono = counters_mod.count(state.view_key < view0) \
        + counters_mod.count(state.own_inc < own0)

    now_suspect = _statuses(state.view_key) == merge.SUSPECT
    timer_armed = state.susp_start >= 0
    seen_nonzero = state.susp_seen != 0
    bad_susp = (now_suspect != timer_armed) | (now_suspect != seen_nonzero)

    bad_coord = (
        jnp.any(~jnp.isfinite(viv.vec), axis=1)
        | ~jnp.isfinite(viv.height)
        | ~jnp.isfinite(viv.error)
        | ~jnp.isfinite(viv.adjustment)
    )

    # Only slots the median filter has actually written are checked —
    # unwritten ring-buffer slots are zero-initialized but semantically
    # undefined after a future format change.
    s = cfg.vivaldi.latency_filter_size
    written = (
        jnp.arange(s, dtype=jnp.int32)[None, None, :]
        < jnp.minimum(state.lat_cnt, s)[:, :, None]
    )
    bad_rtt = written & ~jnp.isfinite(state.lat_buf)

    return cnt._replace(
        sentinel_range=counters_mod.count(bad_range),
        sentinel_monotonic=n_mono,
        sentinel_suspicion=counters_mod.count(bad_susp),
        sentinel_nonfinite_coord=counters_mod.count(bad_coord),
        sentinel_nonfinite_rtt=counters_mod.count(bad_rtt),
    )


def _chaos_slo(cfg, topo: Topology, state: SimState, sched, terms, t,
               roll_mode, expired, active, n_chaos_drop, cnt):
    """On-device convergence SLO probes: compare every tracker's end-of-
    tick *belief* against the ground truth the schedule defines
    (partition colors + liveness) and accumulate tick counters —
    time-to-first-suspect, time-to-confirm, time-to-heal after lift, and
    false-positive deaths. The waits are replicated global indicators
    (one per tick), so under shard_map they are zeroed on all shards
    but 0 before the counter psum (chaos/schedule.py shard_once); the
    per-event tallies (false deaths, chaos drops) live on their rows
    and psum to the true global count."""
    n, k_deg = cfg.n, cfg.degree
    rows = coll.rows(n)
    # Subject ground truth per view column: pack (color, alive, left)
    # into one i32 and move it subject row -> tracker row. Column c's
    # subject sits at row r + off[c] — the same static-shift roll walk
    # the probe plane uses.
    pk = (
        (terms.color << 2)
        | (state.alive_truth.astype(jnp.int32) << 1)
        | state.left.astype(jnp.int32)
    )
    off = topo.off
    if roll_mode:
        if isinstance(off, jax.core.Tracer):
            # Program-argument topology (chaos/sweep.py): traced shifts.
            subj = jnp.stack(
                [coll.roll(pk, -off[j]) for j in range(k_deg)], axis=1
            )
        else:
            off_np = np.asarray(off)
            subj = jnp.stack(
                [coll.roll(pk, -int(off_np[j])) for j in range(k_deg)],
                axis=1,
            )
    else:
        idx = (rows[:, None] + jnp.asarray(off)[None, :]) % n
        subj = coll.take_rows(pk, idx)
    subj_color = subj >> 2
    subj_alive = (subj & 2) != 0
    subj_left = (subj & 1) != 0

    st_now = _statuses(state.view_key)
    suspected = (st_now == merge.SUSPECT) | (st_now == merge.DEAD)
    confirmed = st_now == merge.DEAD
    cross = subj_color != terms.color[:, None]
    # A subject is unreachable from this (active) tracker when the
    # schedule cuts them apart or holds the subject down.
    subj_down = ~subj_alive & ~subj_left
    unreach = active[:, None] & (cross | subj_down)
    fault_now = coll.any_rows(jnp.any(unreach, axis=1))
    detected = coll.any_rows(jnp.any(unreach & suspected, axis=1))
    confirm = coll.any_rows(jnp.any(unreach & confirmed, axis=1))
    # Stale harm after the fault lifts: an active tracker still holding
    # a reachable, live subject in suspect/dead.
    wrong = active[:, None] & suspected & subj_alive & ~subj_left & ~cross
    healing = (
        chaos_mod.fault_started(sched, t)
        & ~fault_now
        & coll.any_rows(jnp.any(wrong, axis=1))
    )
    ind = chaos_mod.shard_once(jnp.stack([
        fault_now,
        fault_now & ~detected,
        fault_now & ~confirm,
        healing,
    ]).astype(jnp.int32))
    # False-positive deaths: suspicion expiries (this tick's phase 1)
    # whose subject was in fact up and reachable.
    false_deaths = counters_mod.count(
        expired & subj_alive & ~subj_left & ~cross
    )
    return cnt._replace(
        chaos_fault_ticks=ind[0],
        chaos_first_suspect_wait=ind[1],
        chaos_confirm_wait=ind[2],
        chaos_heal_wait=ind[3],
        chaos_false_deaths=false_deaths,
        chaos_msgs_dropped=n_chaos_drop,
    )


def _vivaldi_observe(cfg, state: SimState, ok, peer_col, rtt,
                     p_vec, p_h, p_err, p_adj, key):
    """Apply one probe-RTT observation per masked node (median filter +
    full Vivaldi update against the peer's coordinate payload)."""
    s = cfg.vivaldi.latency_filter_size
    k_deg = cfg.degree
    col_c = jnp.where(ok, peer_col, 0)
    # Push the sample into the per-(node, peer) ring buffer where ok.
    cnt = _take_col(state.lat_cnt, col_c)
    slot = cnt % s
    col_oh = jnp.arange(k_deg, dtype=jnp.int32)[None, :] == col_c[:, None]
    slot_oh = jnp.arange(s, dtype=jnp.int32)[None, :] == slot[:, None]
    write = ok[:, None, None] & col_oh[:, :, None] & slot_oh[:, None, :]
    lat_buf = jnp.where(write, rtt[:, None, None], state.lat_buf)
    lat_cnt = jnp.where(ok[:, None] & col_oh, state.lat_cnt + 1, state.lat_cnt)
    # Median over the filled window (client.go:123-141 semantics).
    filled = jnp.minimum(jnp.where(ok, cnt + 1, 1), s)
    row_buf = jnp.sum(
        jnp.where(col_oh[:, :, None], lat_buf, 0.0), axis=1
    )  # [N, S] — exclusive one-hot, no gather
    padded = jnp.where(
        jnp.arange(s, dtype=jnp.int32)[None, :] < filled[:, None],
        row_buf, jnp.inf)
    med = _take_col(jnp.sort(padded, axis=1), filled // 2)
    # Vivaldi update; rejected (rtt=-1) rows pass through untouched. The
    # coincident-point fallback directions are drawn here — this layer
    # knows the rows are a (possibly sharded) node block, ops/vivaldi
    # does not — splitting the key exactly as update() would.
    k_viv, k_grav = jax.random.split(key)
    vd = state.viv.vec.shape[1]
    fallback = (
        coll.uniform_rows(k_viv, cfg.n, (vd,), -0.5, 0.5),
        coll.uniform_rows(k_grav, cfg.n, (vd,), -0.5, 0.5),
    )
    new_viv = vivaldi.update(
        cfg.vivaldi, state.viv, p_vec, p_h, p_err, p_adj,
        jnp.where(ok, med, -1.0), key, fallback_rnd=fallback,
    )
    return state._replace(viv=new_viv, lat_buf=lat_buf, lat_cnt=lat_cnt)


def _top_k_peel(x, p: int):
    """Static argmax peel equal to ``jax.lax.top_k(x, p)`` on integer
    input — per pass, (max value, lowest index on ties), which is
    exactly top_k's tie order. The kernel-callable core
    (ops/pallas_gossip.py) uses this because Mosaic has no sort
    lowering; the XLA path keeps ``lax.top_k`` so its executable is
    byte-for-byte the pre-kernel one."""
    cols = jnp.arange(x.shape[-1], dtype=jnp.int32)
    floor = jnp.iinfo(x.dtype).min
    vals, idxs, work = [], [], x
    for _ in range(p):
        vals.append(jnp.max(work, axis=-1))
        best = jnp.argmax(work, axis=-1).astype(jnp.int32)
        idxs.append(best)
        work = jnp.where(cols == best[..., None], floor, work)
    return jnp.stack(vals, axis=-1), jnp.stack(idxs, axis=-1)


def _argsort_peel(u):
    """Static argmin peel equal to stable ascending
    ``jnp.argsort(u, axis=-1)`` including ties (argmin returns the
    first index of the minimum; masking with +inf peels in the same
    order a stable sort emits). Kernel-callable-core twin of the
    probe-order reshuffle's argsort — see :func:`_top_k_peel`."""
    cols = jnp.arange(u.shape[-1], dtype=jnp.int32)
    out, work = [], u
    for _ in range(u.shape[-1]):
        best = jnp.argmin(work, axis=-1).astype(jnp.int32)
        out.append(best)
        work = jnp.where(cols == best[..., None], jnp.inf, work)
    return jnp.stack(out, axis=-1)


def _gossip_phase(cfg, topo: Topology, state: SimState, active, key, tx_limit,
                  sched=None, terms=None, extra_tx=None):
    """Fan-out + receiver-side delivery + lattice merge + confirmations
    + refute-claim collection. Returns (state, refute_inc[N],
    packets_tx[] i32, packets_rx[] i32, chaos_drops[] i32,
    msgs_tx[] i32), plus a seventh element ``(ex_legs, ex_n_sends)``
    iff ``extra_tx`` is given (the serf fusion hook — see
    :func:`step_counted`).

    Senders pick their ``piggyback_msgs`` hottest view entries (highest
    remaining budget = fewest past transmits, the TransmitLimitedQueue
    order, queue.go:288-373) plus their own-fact, and send them to
    ``gossip_nodes`` displacement-shared peers. Receivers gather. The
    extra plane rides the same per-leg roll (one exchange per hop
    carries both planes), drops on the same chaos/loss draw, and lands
    behind the same receiver-liveness gate — only its sender gate
    differs (includes external seats; see step_counted)."""
    g = cfg.gossip
    n, k_deg = cfg.n, cfg.degree
    ln = coll.local_n(n)
    p, fan = g.piggyback_msgs, g.gossip_nodes
    k_cols, k_drop = jax.random.split(key)
    col_ids = jnp.arange(k_deg, dtype=jnp.int32)

    # Shared per-tick gossip displacements (divergence note: module doc).
    # Dense mode draws them i.i.d. — any receiver can relay a fact to
    # anyone, so coverage follows from the epidemic. Sparse mode SWEEPS
    # the columns deterministically: a fact about subject x can only
    # ever live in x's K trackers, and x's own-fact must reach those K
    # *specific* nodes — i.i.d. draws are a coupon-collector that can
    # strand a tracker in suspect/dead forever. The phase-free cycle
    # guarantees that ANY ceil(K/fan) consecutive ticks serve every
    # column at least once, so a budget >= K armed at an arbitrary tick
    # (refutation, rejoin announcement) covers all K trackers before it
    # runs out — a phase that changes between sweeps would break this
    # for windows straddling the boundary.
    if topo.dense:
        jcols = jax.random.randint(k_cols, (fan,), 0, k_deg)
    else:
        sweep_len = -(-k_deg // fan)  # ceil
        pos = (state.t % sweep_len) * fan
        jcols = (pos + jnp.arange(fan, dtype=jnp.int32)) % k_deg

    # Sender-side selection: top-P entries by remaining budget.
    budget = jnp.where(active[:, None], state.tx_left, 0)
    if coll.in_kernel():
        # Kernel-callable core: argmax peel, bit-identical to top_k
        # (max value, lowest index on ties) — Mosaic has no sort.
        top_tx, scol = _top_k_peel(budget, p)        # [N, P]
    else:
        top_tx, scol = jax.lax.top_k(budget, p)      # [N, P]
    svalid = top_tx > 0
    skey = _take_cols(state.view_key, scol)
    sbits = _take_cols(state.susp_seen, scol)
    ownk = _own_key(state)
    own_sendable = (state.own_tx > 0) & active

    # Sendable per displacement: the sender's own belief of that peer
    # must be contactable — alive/suspect (kRandomNodes filter,
    # state.go:521-535) or never-heard (join-address semantics).
    sendable = merge.is_contactable(state.view_key[:, jcols]) & active[:, None]
    n_sends = jnp.sum(sendable, axis=1).astype(jnp.int32)
    # Queued broadcast messages actually transmitted: each of a sender's
    # n_sends packets carries its top-P valid facts plus the own-fact
    # when armed — the TransmitLimitedQueue drain volume the reference
    # meters per broadcast, and the bandwidth axis of the topology
    # Pareto table (chaos/sweep.py). Pure reduction, no communication.
    n_msgs = jnp.sum(
        n_sends * (jnp.sum(svalid, axis=1).astype(jnp.int32)
                   + own_sendable.astype(jnp.int32))
    ).astype(jnp.int32)

    # Fused extra plane (serf events/queries): its own sender gate —
    # external bridge seats DO originate serf traffic (wire/bridge.py),
    # so the gate is liveness-only, unlike the membership ``active``.
    if extra_tx is not None:
        ex_active = state.alive_truth & ~state.left
        ex_sendable = (
            merge.is_contactable(state.view_key[:, jcols])
            & ex_active[:, None]
        )
        ex_n_sends = jnp.sum(ex_sendable, axis=1).astype(jnp.int32)
        ex_legs = []

    # Budget decrements for actual transmits (queue.go GetBroadcasts).
    sel_oh = jnp.any(
        (scol[:, None, :] == col_ids[None, :, None]) & svalid[:, None, :],
        axis=2,
    )
    tx_left = jnp.maximum(
        state.tx_left - jnp.where(sel_oh, n_sends[:, None], 0), 0
    )
    own_tx = jnp.where(
        own_sendable, jnp.maximum(state.own_tx - n_sends, 0), state.own_tx
    )
    state = state._replace(tx_left=tx_left, own_tx=own_tx)

    # Receiver-side delivery: one packet per (receiver, displacement) —
    # the sender payload rides one exchange per hop (coll.roll_many:
    # separate fused rolls single-chip, one packed ppermute sharded).
    recv_up = state.alive_truth & ~state.left
    u_drop = coll.uniform_rows(k_drop, n, (fan,))
    pl = cfg.packet_loss
    tpack = chaos_mod.pack_terms(terms) if sched is not None else []
    view = state.view_key
    refute_inc = jnp.zeros((ln,), jnp.uint32)
    seen_delta = jnp.zeros((ln, k_deg), jnp.uint32)
    n_rx = jnp.zeros((), jnp.int32)
    n_chaos_drop = jnp.zeros((), jnp.int32)
    cands = []
    for f in range(fan):
        j = jcols[f]
        shift = topo.off[j]
        payload = [sendable[:, f], scol, skey, sbits, svalid, own_sendable,
                   ownk] + tpack
        if extra_tx is not None:
            payload = payload + [ex_sendable[:, f]] + list(extra_tx)
        rolled = coll.roll_many(payload, shift)
        s_send, s_scol, s_skey, s_sbits, s_svalid, s_own_ok, s_ownk = \
            rolled[:7]
        if sched is not None:
            # Sender terms rode the same packet; the leg is one-way
            # sender -> receiver on the existing drop draw.
            s_terms = chaos_mod.unpack_terms(rolled[7:7 + len(tpack)])
            ok_leg = chaos_mod.pair_ok(sched, s_terms, terms, u_drop[:, f], pl)
            n_chaos_drop = n_chaos_drop + counters_mod.count(
                s_send & recv_up & (u_drop[:, f] >= pl) & ~ok_leg
            )
        else:
            ok_leg = u_drop[:, f] >= pl
        arrived = s_send & ok_leg & recv_up
        if extra_tx is not None:
            base = 7 + len(tpack)
            ex_send = rolled[base]
            ex_arrived = ex_send & ok_leg & recv_up
            ex_legs.append((rolled[base + 1:], ex_arrived))
        n_rx = n_rx + counters_mod.count(arrived)
        fact_ok = arrived[:, None] & s_svalid
        rr = topology.remap_row(topo, j)                # [K]
        mycol = _vec_at(rr, s_scol)                     # [N, P]
        about_me = mycol == topology.SELF
        # Facts about the receiver are refutation fodder, not merges
        # (state.go:1107-1110, :1187-1192).
        refut = fact_ok & about_me & merge.is_refutable(
            s_skey, about_me, state.own_inc[:, None]
        )
        refute_inc = jnp.maximum(
            refute_inc,
            jnp.max(jnp.where(refut, merge.key_incarnation(s_skey), 0), axis=1),
        )
        mergeable = fact_ok & (mycol >= 0)
        mkey = jnp.where(mergeable, s_skey, jnp.uint32(0))
        # The sender's own-fact rides the same packet, landing at the
        # receiver column the sender itself occupies.
        icol = topology.inv_col(topo, j)
        own_ok = arrived & s_own_ok
        own_val = jnp.where(own_ok, s_ownk, jnp.uint32(0))
        # Merge: per-row one-hot max over the P facts + the own-fact.
        oh = mycol[:, None, :] == col_ids[None, :, None]          # [N,K,P]
        delta = jnp.max(jnp.where(oh, mkey[:, None, :], 0), axis=2)
        delta = jnp.maximum(
            delta, jnp.where(col_ids[None, :] == icol, own_val[:, None], 0)
        )
        view = merge.join(view, delta)
        cands.append((mycol, mkey, s_sbits, mergeable))

    # Lifeguard confirmations against the post-merge view: a suspect
    # fact at the (still-)current incarnation ORs its accumulated
    # accuser bits into the entry (suspicion.go:103-129).
    for mycol, mkey, bits, ok in cands:
        col_c = jnp.clip(mycol, 0, k_deg - 1)
        post = _take_cols(view, col_c)
        conf = (
            ok
            & (merge.key_status(mkey) == merge.SUSPECT)
            & (merge.key_status(post) == merge.SUSPECT)
            & (merge.key_incarnation(mkey) >= merge.key_incarnation(post))
        )
        for pi in range(p):
            oh = (col_c[:, pi:pi + 1] == col_ids[None, :]) & conf[:, pi:pi + 1]
            seen_delta = seen_delta | jnp.where(oh, bits[:, pi:pi + 1], 0)

    state = state._replace(view_key=view, susp_seen=state.susp_seen | seen_delta)
    base_out = (state, refute_inc, counters_mod.count(sendable), n_rx,
                n_chaos_drop, n_msgs)
    if extra_tx is not None:
        return base_out + ((ex_legs, ex_n_sends),)
    return base_out


def _poke_refutes(cfg, topo: Topology, state: SimState, poke_flag, poke_col,
                  poke_inc):
    """Receiver-side check for compound ping+suspect pokes: was I probed
    this tick by any in-neighbor that believes me suspect? Probes ride
    per-node columns (not the shared displacements), so every in-column
    is checked — K static-shift rolls (sparse) or one dense gather."""
    n, k_deg = cfg.n, cfg.degree
    up = state.alive_truth & ~state.left
    if (not topo.dense) and k_deg <= _ROLL_DEGREE_MAX:
        off = topo.off
        if isinstance(off, jax.core.Tracer):
            # Program-argument topology (chaos/sweep.py): traced shifts.
            shifts = [off[j] for j in range(k_deg)]
        else:
            off_np = np.asarray(off)
            shifts = [int(off_np[j]) for j in range(k_deg)]
        claim = jnp.zeros((coll.local_n(n),), jnp.uint32)
        poked_inc = jnp.where(poke_flag, poke_inc, 0).astype(jnp.uint32)
        for j, shift in enumerate(shifts):
            contrib = coll.roll(
                jnp.where(poke_col == j, poked_inc, 0), shift
            )
            claim = jnp.maximum(claim, contrib)
        refut = (claim >= state.own_inc) & up & (claim > 0)
        return jnp.where(refut, claim, 0)
    rows = coll.rows(n)
    s_mat = (rows[:, None] - topo.off[None, :]) % n      # [B, K] senders
    g_col = coll.all_rows(poke_col)
    g_flag = coll.all_rows(poke_flag)
    g_inc = coll.all_rows(poke_inc)
    hit = (
        (g_col[s_mat] == jnp.arange(k_deg, dtype=jnp.int32)[None, :])
        & g_flag[s_mat]
        & up[:, None]
    )
    inc = jnp.where(hit, g_inc[s_mat], 0).astype(jnp.uint32)
    refut = inc >= state.own_inc[:, None]
    return jnp.max(jnp.where(refut & hit, inc, 0), axis=1)


def _push_pull_phase(cfg, topo: Topology, state: SimState, active, pp_period,
                     key, sched=None, terms=None):
    """Full-state exchange, both directions, with one displacement-shared
    partner per due node (sendAndReceiveState/mergeState,
    net.go:777-1070, state.go:573-608). Receiver-side formulation: the
    pull direction gathers the partner's view forward along the
    displacement; the push direction gathers the initiator's view
    backward; both remap columns through the static tables. Returns
    (state, refute_inc[N], merges_applied[] i32)."""
    n, k_deg = cfg.n, cfg.degree
    rows = coll.rows(n)

    # Fixed per-node phase offset (Knuth-hash stagger; deterministic).
    stagger = (rows * jnp.int32(-1640531527)) % pp_period
    due = active & ((state.t + stagger) % pp_period == 0)

    j = jax.random.randint(key, (), 0, k_deg)
    shift = topo.off[j]
    icol = topology.inv_col(topo, j)          # partner's/initiator's seat
    rr = topology.remap_row(topo, j)          # [K] column remap
    rr_c = jnp.clip(rr, 0, k_deg - 1)

    view0 = state.view_key                    # both directions exchange
    ownk = _own_key(state)                    # the pre-exchange states
    # One exchange per direction: view + own-fact + liveness ride the
    # same hop (coll.roll_many).
    up = state.alive_truth & ~state.left
    pv, fwd_ownk, partner_up = coll.roll_many([view0, ownk, up], -shift)
    init_ok = due & partner_up & merge.is_contactable(view0[:, j])
    if sched is not None:
        # Push-pull is one TCP session: the whole bidirectional exchange
        # happens iff the connection survives the schedule (both
        # directions' chaos terms; no base iid loss — the reference's
        # push-pull rides TCP, which the plain model never drops).
        p_terms = chaos_mod.roll_terms(terms, -shift)
        u_pp = coll.uniform_rows(jax.random.fold_in(key, 1), cfg.n)
        init_ok = init_ok & chaos_mod.pair_ok(
            sched, terms, p_terms, u_pp, 0.0, round_trip=True
        )

    # PULL: the initiator merges its partner's full state (pv holds the
    # partner rows).
    ent = jnp.take(pv, rr_c, axis=1)
    ent = jnp.where(rr[None, :] >= 0, ent, jnp.uint32(0))
    ent = jnp.where(
        jnp.arange(k_deg, dtype=jnp.int32)[None, :] == j,
        fwd_ownk[:, None], ent,
    )
    pull = merge.demote_dead_to_suspect(ent)
    view = merge.join(state.view_key, jnp.where(init_ok[:, None], pull, 0))
    their_view_of_me = pv[:, icol]
    refut1 = init_ok & merge.is_refutable(their_view_of_me, init_ok, state.own_inc)
    refute_inc = jnp.where(
        refut1, merge.key_incarnation(their_view_of_me), 0
    ).astype(jnp.uint32)

    # PUSH: node r receives the full state of s = r - off[j] iff s
    # initiated toward r. The column algebra mirrors the pull with the
    # roles swapped: local column c takes s's column holding the same
    # subject, remapped through the inverse displacement.
    sv, bwd_ownk, bwd_init = coll.roll_many([view0, ownk, init_ok], shift)
    s_ok = bwd_init & up                              # sv: initiator rows
    rr2 = topology.remap_row(topo, icol)
    rr2_c = jnp.clip(rr2, 0, k_deg - 1)
    ent2 = jnp.take(sv, rr2_c, axis=1)
    ent2 = jnp.where(rr2[None, :] >= 0, ent2, jnp.uint32(0))
    ent2 = jnp.where(
        jnp.arange(k_deg, dtype=jnp.int32)[None, :] == icol,
        bwd_ownk[:, None], ent2,
    )
    push = merge.demote_dead_to_suspect(ent2)
    view = merge.join(view, jnp.where(s_ok[:, None], push, 0))
    their_view_of_me2 = sv[:, j]
    refut2 = s_ok & merge.is_refutable(their_view_of_me2, s_ok, state.own_inc)
    refute_inc = jnp.maximum(
        refute_inc,
        jnp.where(refut2, merge.key_incarnation(their_view_of_me2), 0).astype(
            jnp.uint32
        ),
    )

    n_merges = counters_mod.count(init_ok) + counters_mod.count(s_ok)
    return state._replace(view_key=view), refute_inc, n_merges


def _reconcile_suspicion(state: SimState, view0, t):
    """Derive suspicion-timer starts/resets from this tick's view delta:
    entries entering suspect (or re-suspected at a higher incarnation)
    start a timer now; entries leaving suspect clear it
    (state.go:1000-1001, :1124-1158, :1178-1179). Returns
    (state, timers_started[] i32)."""
    st0, st1 = merge.key_status(view0), merge.key_status(state.view_key)
    inc0, inc1 = merge.key_incarnation(view0), merge.key_incarnation(state.view_key)
    now_suspect = st1 == merge.SUSPECT
    fresh = now_suspect & (st0 != merge.SUSPECT)
    re_inc = now_suspect & (st0 == merge.SUSPECT) & (inc1 > inc0)
    restarted = fresh | re_inc
    susp_start = jnp.where(
        restarted, t, jnp.where(now_suspect, state.susp_start, -1)
    )
    susp_seen = jnp.where(now_suspect, state.susp_seen, jnp.uint32(0))
    # A re-suspicion at a higher incarnation is a NEW timer: the old
    # incarnation's accuser bits must not accelerate it (they may be
    # mixed with this tick's, so reset to the starter placeholder —
    # undercounting is the safe direction).
    susp_seen = jnp.where(re_inc, jnp.uint32(1), susp_seen)
    # Fresh suspicions keep this tick's accuser bits; seed a starter bit
    # if none landed (e.g. local probe-failure path) so popcount-1
    # counts confirmations beyond the first accuser.
    susp_seen = jnp.where(
        fresh & (susp_seen == 0), jnp.uint32(1), susp_seen
    )
    return state._replace(susp_start=susp_start, susp_seen=susp_seen), \
        counters_mod.count(restarted)
