"""The vectorized SWIM + Lifeguard step function.

One call to :func:`step` advances every simulated node by one tick
(default 200 ms of protocol time — the LAN gossip interval). Where the
reference runs a goroutine per node with tickers and callback timers
(reference memberlist/state.go:83-121 schedule, suspicion.go timers),
this is a single pure function over struct-of-arrays, so XLA fuses the
whole protocol round into a few kernels and the node axis shards across
chips.

Tick anatomy (mirroring one round of the reference's event loop):

  1. **Suspicion expiry** — per-edge Lifeguard deadline check
     (remainingSuspicionTime, suspicion.go:86-97); expired suspects are
     declared dead locally (state.go:1141-1156) and the loudest few are
     broadcast.
  2. **Probe resolution** — probe windows that close this tick with no
     ack mark the target suspect and broadcast (state.go:437-456).
  3. **Probe launch** — nodes whose probe ticker fires pick the next
     non-dead target in their shuffled order (state.go:193-235), send a
     ping; a direct ack within the timeout feeds Vivaldi with the RTT
     and the peer's coordinate payload (ping_delegate semantics,
     state.go:342-347); otherwise indirect probes through k relays and
     a TCP fallback are modeled (state.go:366-435), and total failure
     opens a pending suspicion window.
  4. **Gossip** — each live node piggybacks its queued broadcasts to
     ``gossip_nodes`` random peers (state.go:517-567, net.go:631);
     deliveries merge into receiver views via the (incarnation, status)
     join semilattice; newly-learned facts are re-queued (the epidemic),
     suspect messages about already-suspect entries register Lifeguard
     confirmations (suspicion.go:103-129), and messages about the
     receiver itself trigger refutation (state.go:840-864).
  5. **Push-pull anti-entropy** — nodes on their staggered cadence pick
     a random live peer and exchange full views both ways, with remote
     dead claims demoted to suspicion (state.go:573-608, :1217-1240).
  6. **Suspicion bookkeeping** — one reconciliation pass derives timer
     starts/resets from the view delta of this tick.

Documented vectorization divergences from the reference (each argued in
SURVEY.md §7 "hard parts"): random gossip-peer sampling is
with-replacement within a tick (vs rejection-sampled distinct peers,
util.go:125-153); at most one Lifeguard confirmation bit registers per
entry per tick (later gossip rounds deliver the rest); mass
simultaneous expiries all apply locally but only the two most-overdue
broadcast per node per tick; packet-size packing of the 1400-byte UDP
budget is modeled by the ``piggyback_msgs`` cap, not enforced by bytes;
gossip-to-the-dead is not modeled (dead processes cannot receive in the
simulation's ground truth).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from consul_tpu.config import SimConfig
from consul_tpu.models.state import SimState
from consul_tpu.ops import merge, scaling, topology, vivaldi
from consul_tpu.ops.topology import World

_NEG = jnp.int32(-1)


def _statuses(view_key):
    return merge.key_status(view_key)


def _accuser_bit(node_id):
    """32-bucket hash bitmask bit for a confirming accuser (dedup
    approximation of the reference's per-from confirmation map,
    suspicion.go:42-59; collisions undercount, which only lengthens
    the timeout — the safe direction)."""
    return (jnp.uint32(1) << (jnp.asarray(node_id, jnp.uint32) % 32)).astype(jnp.uint32)


def _queue_push(cfg: SimConfig, state: SimState, mask, subject, key, src, tx0):
    """Insert one broadcast per masked node into its transmit queue.

    Slot choice mirrors TransmitLimitedQueue semantics (reference
    memberlist/queue.go:182-242): a message about the same subject
    invalidates/replaces the old one; otherwise take an empty slot;
    otherwise evict the most-transmitted (lowest remaining) message.
    """
    b = cfg.gossip.queue_slots
    same = state.q_subject == subject[:, None]
    empty = (state.q_subject < 0) | (state.q_tx <= 0)
    # Higher score wins the argmax slot choice.
    score = (
        jnp.where(same, 3_000_000, 0)
        + jnp.where(empty, 2_000_000, 0)
        + (1_000_000 - jnp.minimum(state.q_tx, 999_999))
    )
    slot = jnp.argmax(score, axis=1)
    onehot = (jnp.arange(b, dtype=jnp.int32)[None, :] == slot[:, None]) & mask[:, None]
    return state._replace(
        q_subject=jnp.where(onehot, subject[:, None], state.q_subject),
        q_key=jnp.where(onehot, key[:, None], state.q_key),
        q_from=jnp.where(onehot, src[:, None], state.q_from),
        q_tx=jnp.where(onehot, tx0, state.q_tx),
    )


def step(cfg: SimConfig, nbrs: jax.Array, world: World, state: SimState, key) -> SimState:
    """Advance the whole cluster by one tick. Pure; jit/shard-map safe."""
    n, k_deg = cfg.n, cfg.degree
    g = cfg.gossip
    t = state.t
    rows = jnp.arange(n, dtype=jnp.int32)
    keys = jax.random.split(key, 9)

    view0 = state.view_key  # snapshot for end-of-tick suspicion bookkeeping
    active = state.alive_truth & ~state.left

    # Static protocol scalars (cluster-size scaling laws); evaluated at
    # trace time — they depend only on the static cluster size.
    with jax.ensure_compile_time_eval():
        tx_limit = int(scaling.retransmit_limit(g.retransmit_mult, n))
        susp_min = float(
            scaling.suspicion_timeout(g.suspicion_mult, n, g.probe_period_ticks)
        )
        susp_max = g.suspicion_max_timeout_mult * susp_min
        susp_k = int(scaling.suspicion_k(g.suspicion_mult, n))
        pp_period = g.push_pull_period_ticks(n)

    # ------------------------------------------------------------------
    # 1. Suspicion expiry: per-edge deadline check.
    # ------------------------------------------------------------------
    statuses = _statuses(state.view_key)
    is_suspect = (statuses == merge.SUSPECT) & (state.susp_start >= 0)
    confirms = jnp.maximum(
        _popcount(state.susp_seen).astype(jnp.int32) - 1, 0
    )  # the original accuser is excluded (suspicion.go:58-59)
    elapsed = (t - state.susp_start).astype(jnp.float32)
    remaining = scaling.remaining_suspicion_time(
        confirms, susp_k, elapsed, susp_min, susp_max
    )
    expired = is_suspect & (remaining <= 0.0) & active[:, None]
    dead_key = merge.make_key(merge.key_incarnation(state.view_key), merge.DEAD)
    view = jnp.where(expired, dead_key, state.view_key)
    state = state._replace(view_key=view)

    # Broadcast the two most-overdue expiries per node (the rest still
    # applied locally above; peers' own timers + push-pull cover them).
    overdue_rank = jnp.where(expired, remaining, jnp.inf)
    for pick in range(2):
        col = jnp.argmin(overdue_rank, axis=1).astype(jnp.int32)
        has = jnp.take_along_axis(expired, col[:, None], axis=1)[:, 0] & active
        subj = jnp.take_along_axis(nbrs, col[:, None], axis=1)[:, 0]
        bkey = jnp.take_along_axis(dead_key, col[:, None], axis=1)[:, 0]
        state = _queue_push(cfg, state, has, subj, bkey, rows, tx_limit)
        overdue_rank = jnp.where(
            jnp.arange(k_deg)[None, :] == col[:, None], jnp.inf, overdue_rank
        )

    # ------------------------------------------------------------------
    # 2. Probe windows closing this tick with no ack -> suspect target.
    # ------------------------------------------------------------------
    failing = (state.pending_target >= 0) & (t >= state.pending_fail_tick) & active
    ftarget = jnp.where(failing, state.pending_target, 0)
    fcol = topology.subject_to_col(cfg, nbrs, rows, ftarget)
    fvalid = failing & (fcol >= 0)
    fcol_c = jnp.where(fvalid, fcol, 0)
    fentry = jnp.take_along_axis(state.view_key, fcol_c[:, None], axis=1)[:, 0]
    # suspectNode applies to alive entries at the known incarnation
    # (state.go:1086-1122); for already-suspect entries the join is a
    # no-op and only the accuser bit below registers (a confirmation).
    fsus_key = merge.make_key(merge.key_incarnation(fentry), merge.SUSPECT)
    fnew = merge.join(fentry, jnp.where(fvalid, fsus_key, jnp.uint32(0)))
    view = _scatter_row_col_max(state.view_key, rows, fcol_c, jnp.where(fvalid, fnew, 0))
    # The prober registers itself as an accuser: on an already-suspect
    # entry this is a Lifeguard confirmation (timer.Confirm in
    # suspectNode, state.go:1094-1099); on a fresh one the bookkeeping
    # pass seeds the timer from it.
    fail_oh = (jnp.arange(k_deg, dtype=jnp.int32)[None, :] == fcol_c[:, None]) & fvalid[:, None]
    susp_seen = state.susp_seen | jnp.where(fail_oh, _accuser_bit(rows)[:, None], 0)
    state = state._replace(
        view_key=view,
        susp_seen=susp_seen,
        pending_target=jnp.where(failing, _NEG, state.pending_target),
    )
    state = _queue_push(cfg, state, fvalid, ftarget, fsus_key, rows, tx_limit)
    # Failed probe cycle degrades local health (awareness.go; simplified
    # from the nack-counting form, state.go:437-451).
    awareness = jnp.clip(
        state.awareness + jnp.where(failing, 1, 0), 0, g.awareness_max - 1
    )
    state = state._replace(awareness=awareness)

    # ------------------------------------------------------------------
    # 3. Probe launch.
    # ------------------------------------------------------------------
    probing = active & (t >= state.next_probe_tick)
    statuses = _statuses(state.view_key)
    # Next non-dead target in the shuffled order, looking ahead up to 3
    # (the reference's skip loop, state.go:196-231).
    cand_off = jnp.arange(3, dtype=jnp.int32)
    cand_pos = (state.probe_ptr[:, None] + cand_off[None, :]) % k_deg
    cand_col = jnp.take_along_axis(state.probe_perm, cand_pos, axis=1)
    cand_status = jnp.take_along_axis(statuses, cand_col, axis=1)
    cand_ok = (cand_status == merge.ALIVE) | (cand_status == merge.SUSPECT)
    has_target = jnp.any(cand_ok, axis=1) & probing
    first_ok = jnp.argmax(cand_ok, axis=1).astype(jnp.int32)
    target_col = jnp.take_along_axis(cand_col, first_ok[:, None], axis=1)[:, 0]
    target = jnp.take_along_axis(nbrs, target_col[:, None], axis=1)[:, 0]
    advance = jnp.where(probing, jnp.where(has_target, first_ok + 1, 3), 0)

    target_up = state.alive_truth[target] & ~state.left[target]
    rtt_obs = topology.sample_rtt(cfg, world, rows, target, keys[0])
    timeout_s = g.probe_timeout_ms / 1000.0
    loss = jax.random.uniform(keys[1], (n, 2)) < cfg.packet_loss  # direct, TCP legs
    direct_ok = has_target & target_up & (rtt_obs <= timeout_s) & ~loss[:, 0]
    # Indirect probes via k random live relays + TCP fallback
    # (state.go:366-435): with iid loss both directions per relay.
    relay_col = jax.random.randint(keys[2], (n, g.indirect_checks), 0, k_deg)
    relay = jnp.take_along_axis(nbrs, relay_col, axis=1)
    relay_ok = (
        state.alive_truth[relay]
        & ~(jax.random.uniform(keys[3], relay.shape) < cfg.packet_loss)
        & ~(jax.random.uniform(keys[4], relay.shape) < cfg.packet_loss)
    )
    indirect_ok = has_target & target_up & jnp.any(relay_ok, axis=1) & ~direct_ok
    tcp_ok = has_target & target_up & ~loss[:, 1]
    acked = direct_ok | indirect_ok | tcp_ok

    # A ping to a suspect target carries a suspect message so it can
    # refute immediately (compound ping+suspect, state.go:306-331).
    target_status = jnp.take_along_axis(statuses, target_col[:, None], axis=1)[:, 0]
    target_inc = merge.key_incarnation(
        jnp.take_along_axis(state.view_key, target_col[:, None], axis=1)[:, 0]
    )
    # (Loss for the poke is applied once, by the shared gossip-delivery
    # drop in _gossip_phase — not here, which would square it.)
    poke_suspect = has_target & (target_status == merge.SUSPECT) & target_up

    # Probe bookkeeping: window for failures, ticker reschedule scaled
    # by local health (awareness.ScaleTimeout, state.go:268).
    pending_target = jnp.where(has_target & ~acked, target, state.pending_target)
    pending_fail_tick = jnp.where(
        has_target & ~acked, t + g.probe_period_ticks, state.pending_fail_tick
    )
    interval = g.probe_period_ticks * (state.awareness + 1)
    next_probe = jnp.where(probing, t + interval, state.next_probe_tick)
    awareness = jnp.clip(
        state.awareness - jnp.where(acked, 1, 0), 0, g.awareness_max - 1
    )
    ptr = state.probe_ptr + advance
    # Global reshuffle when the slowest cursor wraps (approximates the
    # per-wrap shuffle of state.go:492-513).
    wrapped = ptr >= k_deg
    perm = jax.lax.cond(
        jnp.any(wrapped),
        lambda p: jax.vmap(jax.random.permutation, in_axes=(0, None))(
            jax.random.split(keys[5], n), k_deg
        ).astype(jnp.int32),
        lambda p: p,
        state.probe_perm,
    )
    probe_perm = jnp.where(wrapped[:, None], perm, state.probe_perm)
    state = state._replace(
        probe_ptr=jnp.where(wrapped, 0, ptr),
        probe_perm=probe_perm,
        next_probe_tick=next_probe,
        pending_target=pending_target,
        pending_fail_tick=pending_fail_tick,
        awareness=awareness,
    )

    # Direct ack feeds Vivaldi: RTT through the per-peer median filter,
    # peer coordinate as the ack payload (ping_delegate.go:28-90).
    state = _vivaldi_observe(cfg, state, direct_ok, target, target_col, rtt_obs, keys[6])

    # ------------------------------------------------------------------
    # 4. Gossip fan-out and delivery.
    # ------------------------------------------------------------------
    state, refute_inc_gossip = _gossip_phase(
        cfg, nbrs, state, active, poke_suspect, target, target_inc, tx_limit, keys[7]
    )

    # ------------------------------------------------------------------
    # 5. Push-pull anti-entropy.
    # ------------------------------------------------------------------
    state, refute_inc_pp = _push_pull_phase(cfg, nbrs, state, active, pp_period, keys[8])

    # ------------------------------------------------------------------
    # Refutation: bump own incarnation past any accusation and broadcast
    # alive (state.go:840-864). Costs health (awareness +1).
    # ------------------------------------------------------------------
    claim = jnp.maximum(refute_inc_gossip, refute_inc_pp)
    # A node with a broadcast leave intent does not refute — refuting
    # would outrank its own LEFT record in the merge lattice and convert
    # the graceful departure into a detected failure.
    refuting = (claim > 0) & active & ~state.leaving
    own_inc = jnp.where(refuting, claim + 1, state.own_inc).astype(jnp.uint32)
    state = state._replace(
        own_inc=own_inc,
        awareness=jnp.clip(
            state.awareness + jnp.where(refuting, 1, 0), 0, g.awareness_max - 1
        ),
    )
    state = _queue_push(
        cfg, state, refuting, rows, merge.make_key(own_inc, merge.ALIVE), rows, tx_limit
    )

    # ------------------------------------------------------------------
    # 6. Suspicion bookkeeping from this tick's view delta.
    # ------------------------------------------------------------------
    state = _reconcile_suspicion(state, view0, t)

    return state._replace(t=t + 1)


def _popcount(x):
    return jax.lax.population_count(jnp.asarray(x, jnp.uint32))


def _scatter_row_col_max(view, row_idx, col_idx, key_vals):
    """view[row, col] = max(view[row, col], key) for one (col, key) per row."""
    flat = view.reshape(-1)
    idx = row_idx * view.shape[1] + col_idx
    return flat.at[idx].max(key_vals).reshape(view.shape)


def _vivaldi_observe(cfg, state: SimState, ok, peer, peer_col, rtt, key):
    """Apply one probe-RTT observation per masked node (median filter +
    full Vivaldi update against the peer's coordinate)."""
    s = cfg.vivaldi.latency_filter_size
    k_deg = cfg.degree
    # Push the sample into the per-(node, peer) ring buffer where ok.
    cnt = jnp.take_along_axis(state.lat_cnt, peer_col[:, None], axis=1)[:, 0]
    slot = cnt % s
    col_oh = jnp.arange(k_deg, dtype=jnp.int32)[None, :] == peer_col[:, None]
    slot_oh = jnp.arange(s, dtype=jnp.int32)[None, :] == slot[:, None]
    write = ok[:, None, None] & col_oh[:, :, None] & slot_oh[:, None, :]
    lat_buf = jnp.where(write, rtt[:, None, None], state.lat_buf)
    lat_cnt = jnp.where(ok[:, None] & col_oh, state.lat_cnt + 1, state.lat_cnt)
    # Median over the filled window (client.go:123-141 semantics).
    filled = jnp.minimum(jnp.where(ok, cnt + 1, 1), s)
    row_buf = jnp.take_along_axis(
        lat_buf, jnp.where(ok, peer_col, 0)[:, None, None].repeat(s, axis=2), axis=1
    )[:, 0, :]
    padded = jnp.where(jnp.arange(s)[None, :] < filled[:, None], row_buf, jnp.inf)
    med = jnp.take_along_axis(
        jnp.sort(padded, axis=1), (filled // 2)[:, None], axis=1
    )[:, 0]
    # Vivaldi update; rejected (rtt=-1) rows pass through untouched.
    viv = state.viv
    new_viv = vivaldi.update(
        cfg.vivaldi,
        viv,
        viv.vec[peer],
        viv.height[peer],
        viv.error[peer],
        viv.adjustment[peer],
        jnp.where(ok, med, -1.0),
        key,
    )
    return state._replace(viv=new_viv, lat_buf=lat_buf, lat_cnt=lat_cnt)


def _gossip_phase(cfg, nbrs, state: SimState, active, poke_suspect, poke_target,
                  poke_inc, tx_limit, key):
    """Queue fan-out, delivery, view merge, rebroadcast, confirmations,
    and refute-claim collection. Returns (state, refute_inc[N])."""
    g = cfg.gossip
    n, k_deg, b = cfg.n, cfg.degree, g.queue_slots
    p, fan = g.piggyback_msgs, g.gossip_nodes
    rows = jnp.arange(n, dtype=jnp.int32)
    k_peer, k_loss = jax.random.split(key)

    # Select the P most-retransmittable queue slots per node (the btree
    # order: fewest past transmits first, queue.go:288-373).
    order = jnp.argsort(-state.q_tx, axis=1)[:, :p]
    m_subject = jnp.take_along_axis(state.q_subject, order, axis=1)
    m_key = jnp.take_along_axis(state.q_key, order, axis=1)
    m_from = jnp.take_along_axis(state.q_from, order, axis=1)
    m_tx = jnp.take_along_axis(state.q_tx, order, axis=1)
    m_valid = (m_subject >= 0) & (m_tx > 0) & active[:, None]

    # Gossip peers: fan random neighbor columns whose view state is
    # alive or suspect (kRandomNodes filter, state.go:521-535).
    peer_col = jax.random.randint(k_peer, (n, fan), 0, k_deg)
    peer = jnp.take_along_axis(nbrs, peer_col, axis=1)
    peer_status = jnp.take_along_axis(_statuses(state.view_key), peer_col, axis=1)
    peer_ok = (
        ((peer_status == merge.ALIVE) | (peer_status == merge.SUSPECT))
        & active[:, None]
    )

    # Flatten to M = N * fan * P messages (+ N compound ping-suspect pokes).
    dst = jnp.repeat(peer[:, :, None], p, axis=2).reshape(-1)
    subj = jnp.repeat(m_subject[:, None, :], fan, axis=1).reshape(-1)
    mkey = jnp.repeat(m_key[:, None, :], fan, axis=1).reshape(-1)
    mfrom = jnp.repeat(m_from[:, None, :], fan, axis=1).reshape(-1)
    mok = (
        jnp.repeat(peer_ok[:, :, None], p, axis=2)
        & jnp.repeat(m_valid[:, None, :], fan, axis=1)
    ).reshape(-1)
    # The self-addressed suspect tacked onto pings of suspect targets.
    dst = jnp.concatenate([dst, poke_target])
    subj = jnp.concatenate([subj, poke_target])
    mkey = jnp.concatenate([mkey, merge.make_key(poke_inc, merge.SUSPECT)])
    mfrom = jnp.concatenate([mfrom, rows])
    mok = jnp.concatenate([mok, poke_suspect])

    drop = jax.random.uniform(k_loss, dst.shape) < cfg.packet_loss
    mok = mok & ~drop & state.alive_truth[dst] & ~state.left[dst]

    # Decrement transmit budgets by actual sends; retire exhausted slots.
    sends = jnp.sum(peer_ok, axis=1)[:, None] * jnp.where(m_valid, 1, 0)
    new_tx_sel = jnp.maximum(m_tx - sends, 0)
    q_tx = _scatter_cols(state.q_tx, order, new_tx_sel)
    q_subject = jnp.where(q_tx <= 0, -1, state.q_subject)
    state = state._replace(q_tx=q_tx, q_subject=q_subject)

    # Deliveries about the receiver itself are refutation fodder
    # (state.go:1107-1110, :1187-1192), not view merges.
    to_self = mok & (subj == dst)
    refutable = to_self & merge.is_refutable(mkey, to_self, state.own_inc[dst])
    refute_inc = (
        jnp.zeros((n,), jnp.uint32)
        .at[dst]
        .max(jnp.where(refutable, merge.key_incarnation(mkey), 0))
    )

    # Merge the rest into receiver views (batched scatter-max join).
    col = topology.subject_to_col(cfg, nbrs, dst, subj)
    deliver = mok & (col >= 0)
    col_c = jnp.where(deliver, col, 0)
    flat_idx = jnp.where(deliver, dst * k_deg + col_c, 0)
    scatter_key = jnp.where(deliver, mkey, jnp.uint32(0))
    old_flat = state.view_key.reshape(-1)
    new_flat = old_flat.at[flat_idx].max(scatter_key)
    view_new = new_flat.reshape(n, k_deg)

    # Lifeguard confirmations: a suspect message about an entry that is
    # (still) suspect at that incarnation registers its accuser's hash
    # bit; at most one new bit lands per entry per tick (divergence note
    # in the module docstring).
    post_key = new_flat[flat_idx]
    confirm = (
        deliver
        & (merge.key_status(mkey) == merge.SUSPECT)
        & (merge.key_status(post_key) == merge.SUSPECT)
        & (merge.key_incarnation(mkey) >= merge.key_incarnation(post_key))
    )
    bits = jnp.where(confirm, _accuser_bit(mfrom), jnp.uint32(0))
    tick_bits = (
        jnp.zeros((n * k_deg,), jnp.uint32).at[flat_idx].max(bits).reshape(n, k_deg)
    )

    # Rebroadcast the strongest newly-learned fact per receiver
    # (the epidemic re-queue of NotifyMsg, delegate rebroadcast path).
    learned = deliver & (mkey > old_flat[flat_idx])
    win_key = (
        jnp.zeros((n,), jnp.uint32).at[dst].max(jnp.where(learned, mkey, 0))
    )
    is_win = learned & (mkey == win_key[dst]) & (win_key[dst] > 0)
    midx = jnp.arange(dst.shape[0], dtype=jnp.int32)
    win_idx = (
        jnp.full((n,), midx.shape[0], jnp.int32)
        .at[dst]
        .min(jnp.where(is_win, midx, midx.shape[0]))
    )
    has_win = win_idx < midx.shape[0]
    win_idx_c = jnp.where(has_win, win_idx, 0)
    state = state._replace(view_key=view_new, susp_seen=state.susp_seen | tick_bits)
    state = _queue_push(
        cfg, state, has_win, subj[win_idx_c], mkey[win_idx_c], mfrom[win_idx_c], tx_limit
    )
    return state, refute_inc


def _push_pull_phase(cfg, nbrs, state: SimState, active, pp_period, key):
    """Full-state exchange with one random live partner, both directions
    (sendAndReceiveState/mergeState, net.go:777-1070, state.go:573-608)."""
    n, k_deg = cfg.n, cfg.degree
    rows = jnp.arange(n, dtype=jnp.int32)
    k_partner = key

    stagger = jax.random.randint(
        jax.random.PRNGKey(17), (n,), 0, pp_period, jnp.int32
    )  # fixed per-node phase offset (deterministic across ticks)
    due = active & ((state.t + stagger) % pp_period == 0)

    pcol = jax.random.randint(k_partner, (n,), 0, k_deg)
    partner = jnp.take_along_axis(nbrs, pcol[:, None], axis=1)[:, 0]
    partner_ok = due & state.alive_truth[partner] & ~state.left[partner]

    subjects = nbrs  # [N, K] global ids of my entries
    # Remote's column for each of my subjects (and mine for theirs).
    pcols = topology.subject_to_col(
        cfg, nbrs, partner[:, None] * jnp.ones((1, k_deg), jnp.int32), subjects
    )
    valid = partner_ok[:, None] & (pcols >= 0)
    pcols_c = jnp.where(valid, pcols, 0)
    remote_entry = state.view_key[
        jnp.where(partner_ok, partner, 0)[:, None], pcols_c
    ]
    # The partner's record of itself is its live own-state.
    self_key = merge.make_key(state.own_inc, merge.ALIVE)
    remote_entry = jnp.where(
        subjects == partner[:, None], self_key[partner][:, None], remote_entry
    )
    # Remote dead claims arrive as suspicion (mergeState, state.go:1231-1237).
    remote_entry = merge.demote_dead_to_suspect(remote_entry)
    # My own entry in their state: refutation check, not a merge.
    about_me = subjects == rows[:, None]  # never true (nbrs exclude self)

    pull = jnp.where(valid & ~about_me, remote_entry, jnp.uint32(0))
    view = merge.join(state.view_key, pull)

    # Push direction: my entries (dead demoted likewise) scatter-join
    # into the partner's view, plus my own alive record.
    push_key = merge.demote_dead_to_suspect(state.view_key)
    flat_idx = jnp.where(valid, partner[:, None] * k_deg + pcols_c, 0)
    flat_val = jnp.where(valid, push_key, jnp.uint32(0))
    my_col_at_partner = topology.subject_to_col(cfg, nbrs, partner, rows)
    me_ok = partner_ok & (my_col_at_partner >= 0)
    me_idx = jnp.where(me_ok, partner * k_deg + jnp.where(me_ok, my_col_at_partner, 0), 0)
    view_flat = view.reshape(-1)
    view_flat = view_flat.at[flat_idx.reshape(-1)].max(flat_val.reshape(-1))
    view_flat = view_flat.at[me_idx].max(jnp.where(me_ok, self_key, jnp.uint32(0)))
    view = view_flat.reshape(n, k_deg)

    # Refute claims: the partner's view of ME, from the columns already
    # resolved for the push direction.
    their_view_of_me = state.view_key[
        jnp.where(me_ok, partner, 0), jnp.where(me_ok, my_col_at_partner, 0)
    ]
    refut = me_ok & merge.is_refutable(their_view_of_me, me_ok, state.own_inc)
    refute_inc = jnp.where(refut, merge.key_incarnation(their_view_of_me), 0).astype(
        jnp.uint32
    )

    return state._replace(view_key=view), refute_inc


def _reconcile_suspicion(state: SimState, view0, t):
    """Derive suspicion-timer starts/resets from this tick's view delta:
    entries entering suspect (or re-suspected at a higher incarnation)
    start a timer now; entries leaving suspect clear it
    (state.go:1000-1001, :1124-1158, :1178-1179)."""
    st0, st1 = merge.key_status(view0), merge.key_status(state.view_key)
    inc0, inc1 = merge.key_incarnation(view0), merge.key_incarnation(state.view_key)
    now_suspect = st1 == merge.SUSPECT
    fresh = now_suspect & (st0 != merge.SUSPECT)
    re_inc = now_suspect & (st0 == merge.SUSPECT) & (inc1 > inc0)
    restarted = fresh | re_inc
    susp_start = jnp.where(
        restarted, t, jnp.where(now_suspect, state.susp_start, -1)
    )
    susp_seen = jnp.where(now_suspect, state.susp_seen, jnp.uint32(0))
    # A re-suspicion at a higher incarnation is a NEW timer: the old
    # incarnation's accuser bits must not accelerate it (they may be
    # mixed with this tick's, so reset to the starter placeholder —
    # undercounting is the safe direction).
    susp_seen = jnp.where(re_inc, jnp.uint32(1), susp_seen)
    # Fresh suspicions keep this tick's accuser bits; seed a starter bit
    # if none landed (e.g. local probe-failure path) so popcount-1
    # counts confirmations beyond the first accuser.
    susp_seen = jnp.where(
        fresh & (susp_seen == 0), jnp.uint32(1), susp_seen
    )
    return state._replace(susp_start=susp_start, susp_seen=susp_seen)


def _scatter_cols(arr, cols, vals):
    """arr[i, cols[i, j]] = vals[i, j] for the selected columns."""
    n, b = arr.shape
    rows = jnp.arange(n, dtype=jnp.int32)[:, None] * b
    flat = arr.reshape(-1).at[(rows + cols).reshape(-1)].set(vals.reshape(-1))
    return flat.reshape(n, b)
