"""Multi-datacenter federation: LAN pools + the WAN gossip pool.

The reference federates datacenters with two gossip tiers (reference
agent/consul/server.go:223-230: every server is in its DC's LAN serf
pool *and* the global WAN pool, with slower WAN timing
memberlist/config.go:272-281; LAN server lists flood into the WAN pool,
flood.go): LAN pools detect node failures inside a DC, the WAN pool
detects server/DC failures globally and carries the WAN coordinate
space that drives cross-DC routing (agent/router).

TPU-native shape (BASELINE config 5, SURVEY.md §7 phase 6):

  - All DCs run the SAME vectorized SWIM program, stacked on a leading
    ``dc`` axis and advanced with one ``vmap``-ped jitted step — on
    hardware the (dc, nodes) axes map onto a 2-D device mesh, so DCs
    are data-parallel shards and the node axis shards within each DC.
  - The WAN pool is a second, smaller simulation over the union of
    every DC's server subset (nodes ``0..servers_per_dc-1`` of each
    DC), running the WAN timing profile. LAN ticks are the global
    clock; WAN ticks fire on a Bresenham schedule so e.g. a 500 ms WAN
    tick interleaves 200 ms LAN ticks as 3,2,3,2,…
  - Ground truth: DC sites are planted far apart (inter-DC RTTs
    dominate), servers sit near their site — so learned WAN Vivaldi
    coordinates recover the inter-DC distance ordering used by
    ``Router.get_datacenters_by_distance``.

Fault injection spans both tiers: killing a node kills it in its LAN
pool and, if it is a server, in the WAN pool too.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from consul_tpu.config import GossipConfig, SimConfig
from consul_tpu.models import state as sim_state
from consul_tpu.models import swim
from consul_tpu.models.cluster import _topo_key
from consul_tpu.models.state import SimState
from consul_tpu.ops import merge, topology
from consul_tpu.ops.topology import World
from consul_tpu.utils import metrics


@dataclasses.dataclass(frozen=True)
class FederationConfig:
    n_dc: int = 2
    nodes_per_dc: int = 256
    servers_per_dc: int = 3
    # Intra-DC latency world (LAN profile defaults).
    lan: SimConfig = dataclasses.field(default_factory=SimConfig)
    # Inter-DC spread for the WAN ground truth (ms).
    wan_diameter_ms: float = 120.0
    # Inter-mesh (DCN) partitioning: this instance owns the ``n_dc``
    # datacenters starting at global index ``dc_offset`` out of
    # ``n_dc_total`` — its WAN pool replica spans ALL DCs' servers, but
    # LAN ground truth flows into only the owned rows
    # (parallel/dcn.py). Defaults = single-mesh: own everything. The
    # None sentinel is kept un-materialized so a later
    # ``dataclasses.replace(cfg, n_dc=...)`` tracks the new total
    # (read via :attr:`dc_total`).
    n_dc_total: Optional[int] = None
    dc_offset: int = 0

    def __post_init__(self):
        object.__setattr__(
            self, "lan", dataclasses.replace(self.lan, n=self.nodes_per_dc)
        )

    @property
    def dc_total(self) -> int:
        return self.n_dc_total if self.n_dc_total is not None else self.n_dc

    @property
    def wan(self) -> SimConfig:
        """The WAN pool's SimConfig: server subset, WAN gossip profile
        (reference memberlist/config.go:272-281)."""
        return dataclasses.replace(
            self.lan,
            n=self.dc_total * self.servers_per_dc,
            gossip=GossipConfig.wan(),
            world_diameter_ms=self.wan_diameter_ms,
        )

    @property
    def n_wan(self) -> int:
        return self.dc_total * self.servers_per_dc


class FederationState(NamedTuple):
    lan: SimState        # stacked [n_dc, ...] over the dc axis
    wan: SimState        # flat [n_wan, ...]
    wan_accum_ms: jax.Array  # [] int32 — Bresenham accumulator


def _fed_step(cfg: FederationConfig, lan_topo, wan_topo):
    """The per-tick federation step, with everything instance-specific
    passed as program *arguments* (the cluster.py _chunk_runner idiom):
    the LAN/WAN worlds and the WAN row offset of this instance's owned
    slice. Only the configs and topology tables stay closed over — they
    are read concretely during tracing and are part of the program's
    identity."""
    lan_cfg, wan_cfg = cfg.lan, cfg.wan
    lan_step = functools.partial(swim.step, lan_cfg, lan_topo)

    def step(lan_world, wan_world, off, state: FederationState, key):
        k_lan, k_wan = jax.random.split(key)
        lan_keys = jax.random.split(k_lan, cfg.n_dc)
        lan = jax.vmap(lan_step)(lan_world, state.lan, lan_keys)
        # WAN servers that died in their LAN pool are dead on the WAN
        # too (same process; reference: one serf agent in both pools).
        # Ground truth flows LAN -> WAN, into the OWNED rows only —
        # other islands' rows keep their last-synced truth. ``off`` is
        # a traced scalar so same-shape islands share one executable.
        server_alive = lan.alive_truth[:, :cfg.servers_per_dc].reshape(-1)
        server_left = lan.left[:, :cfg.servers_per_dc].reshape(-1)
        wan = state.wan._replace(
            alive_truth=jax.lax.dynamic_update_slice(
                state.wan.alive_truth, server_alive, (off,)),
            left=jax.lax.dynamic_update_slice(
                state.wan.left, server_left, (off,)),
        )
        # Bresenham: fire a WAN tick whenever accumulated LAN time
        # crosses the WAN tick size.
        accum = state.wan_accum_ms + lan_cfg.gossip.tick_ms
        fire = accum >= wan_cfg.gossip.tick_ms
        wan = jax.lax.cond(
            fire,
            lambda w: swim.step(wan_cfg, wan_topo, wan_world, w, k_wan),
            lambda w: w, wan,
        )
        accum = jnp.where(fire, accum - wan_cfg.gossip.tick_ms, accum)
        return FederationState(lan=lan, wan=wan, wan_accum_ms=accum)

    return step


_FED_RUNNER_CACHE: dict = {}


def _fed_chunk_runner(cfg: FederationConfig, lan_topo, wan_topo,
                      chunk: int, mesh=None):
    """Scan-compiled multi-tick federation runner, memoized
    process-wide like cluster.py's _chunk_runner. ``dc_offset`` is
    normalized out of the memo key and enters the program as a scalar
    argument, so every same-shape island of a DCN federation — and
    every later Federation built over the same configs/topologies —
    reuses one executable instead of paying XLA per instance.

    The mesh fingerprint (parallel/mesh.mesh_key — axis names, shape,
    device ids) joins the memo key like cluster.py's: a Federation
    placed over a new surviving-device grid after an elastic reshard
    binds a fresh runner rather than one whose sharding assumptions
    were baked for the old mesh."""
    from consul_tpu.parallel.mesh import mesh_key

    cfg = dataclasses.replace(cfg, dc_offset=0)
    memo = (cfg, _topo_key(lan_topo), _topo_key(wan_topo), chunk,
            mesh_key(mesh))
    hit = _FED_RUNNER_CACHE.get(memo)
    if hit is not None:
        return hit

    step = _fed_step(cfg, lan_topo, wan_topo)

    def run(lan_world, wan_world, off, state, base_key):
        def body(st, _):
            k = jax.random.fold_in(base_key, st.lan.t[0])
            return step(lan_world, wan_world, off, st, k), ()
        return jax.lax.scan(
            body, state, jnp.arange(chunk, dtype=jnp.int32))[0]

    jitted = jax.jit(run, donate_argnums=(3,))
    _FED_RUNNER_CACHE[memo] = jitted
    return jitted


class Federation:
    """Driver for one federated simulation (LAN pools + WAN pool)."""

    def __init__(self, cfg: FederationConfig, seed: int = 0, mesh=None):
        self.cfg = cfg
        # Device mesh the state is placed over (parallel/mesh.py
        # federation_sharding); joins the runner memo key so reshards
        # rebind executables. Placement itself stays the caller's job
        # (runtime/dcn.py / the dryrun own the device_put).
        self.mesh = mesh
        lan, wan = cfg.lan, cfg.wan
        key = jax.random.PRNGKey(seed)
        k_lan_w, k_lan_s, k_wan_w, k_wan_s, k_centers, self.base_key = \
            jax.random.split(key, 6)

        # LAN: identical circulant topology in every DC; per-DC worlds/
        # states. Distinct subkeys per use (round-1 advisor finding:
        # topology and initial protocol state must not share a seed).
        k_lan_t, k_lan_i, k_wan_t, k_wan_i = jax.random.split(
            jax.random.fold_in(k_lan_s, 1), 4
        )
        self.lan_topo = topology.make_topology(lan, k_lan_t)
        # Key streams are laid out over the GLOBAL DC index so a
        # partitioned (DCN) island plants the same worlds its DCs would
        # have in the equivalent single-mesh federation.
        dcs = slice(cfg.dc_offset, cfg.dc_offset + cfg.n_dc)
        lan_keys = jax.random.split(k_lan_w, cfg.dc_total)[dcs]
        self.lan_world = jax.vmap(lambda k: topology.make_world(lan, k))(
            lan_keys
        )
        init_keys = jax.random.split(k_lan_i, cfg.dc_total)[dcs]
        lan_state = jax.vmap(lambda k: sim_state.init(lan, k))(init_keys)

        # WAN: servers planted near their DC site (all DCs — the WAN
        # pool replica is global even when this instance owns a slice).
        self.wan_topo = topology.make_topology(wan, k_wan_t)
        centers = jax.random.uniform(
            k_centers, (cfg.dc_total, lan.world_dims), jnp.float32,
            0.0, cfg.wan_diameter_ms / 1000.0,
        )
        local = topology.make_world(wan, k_wan_w)
        site = jnp.repeat(centers, cfg.servers_per_dc, axis=0)
        wan_world = World(pos=site + 0.02 * local.pos, height=local.height)
        self.wan_world = wan_world
        wan_state = sim_state.init(wan, k_wan_i)

        self.state = FederationState(
            lan=lan_state, wan=wan_state, wan_accum_ms=jnp.int32(0)
        )
        self._wan_off = jnp.int32(cfg.dc_offset * cfg.servers_per_dc)

    # ------------------------------------------------------------------
    def run(self, lan_ticks: int, chunk: int = 32):
        """Advance ``lan_ticks`` in scan-compiled chunks: the whole
        chunk executes on-device with zero host round-trips (round-1
        weakness #4 — the per-tick ``int(t)`` host sync — removed;
        per-tick keys fold the on-device tick counter, the cluster.py
        idiom). Runners come from the process-wide memo, so repeated
        instances and same-shape DCN islands share executables."""
        remaining = lan_ticks
        while remaining > 0:
            c = min(chunk, remaining)
            runner = _fed_chunk_runner(
                self.cfg, self.lan_topo, self.wan_topo, c, mesh=self.mesh
            )
            self.state = runner(
                self.lan_world, self.wan_world, self._wan_off,
                self.state, self.base_key,
            )
            remaining -= c
        return self.state

    # ------------------------------------------------------------------
    # Fault injection
    # ------------------------------------------------------------------
    def kill(self, dc: int, mask):
        """Kill nodes in one locally-owned DC (LAN + WAN if servers);
        ``dc`` is the local index within this instance's slice."""
        mask = jnp.asarray(mask, bool)
        lan_alive = self.state.lan.alive_truth.at[dc].set(
            self.state.lan.alive_truth[dc] & ~mask
        )
        s = self.cfg.servers_per_dc
        g = (self.cfg.dc_offset + dc) * s
        wan_alive = self.state.wan.alive_truth.at[
            g:g + s
        ].set(self.state.wan.alive_truth[g:g + s] & ~mask[:s])
        self.state = self.state._replace(
            lan=self.state.lan._replace(alive_truth=lan_alive),
            wan=self.state.wan._replace(alive_truth=wan_alive),
        )

    def kill_dc(self, dc: int):
        self.kill(dc, jnp.ones((self.cfg.nodes_per_dc,), bool))

    # ------------------------------------------------------------------
    # Inspection
    # ------------------------------------------------------------------
    def lan_health(self, dc: int) -> metrics.HealthMetrics:
        state_dc = jax.tree.map(lambda x: x[dc], self.state.lan)
        return metrics.health(self.cfg.lan, self.lan_topo, state_dc)

    def wan_health(self) -> metrics.HealthMetrics:
        return metrics.health(self.cfg.wan, self.wan_topo, self.state.wan)

    def wan_server_coord(self, dc: int, server: int) -> dict:
        """A WAN server's learned Vivaldi coordinate in store/router
        form (the WAN coordinate of reference agent/router sorting)."""
        i = dc * self.cfg.servers_per_dc + server
        viv = self.state.wan.viv
        return {
            "vec": [float(x) for x in viv.vec[i]],
            "error": float(viv.error[i]),
            "height": float(viv.height[i]),
            "adjustment": float(viv.adjustment[i]),
        }

    def wan_members_seen_by(self, observer_dc: int,
                            observer_server: int = 0) -> list[dict]:
        """The WAN member list as one server sees it — feeds the router
        the way serf WAN membership events do (reference
        agent/router/serf_adapter.go)."""
        i = observer_dc * self.cfg.servers_per_dc + observer_server
        st = merge.key_status(self.state.wan.view_key)[i]
        wan_nbrs = topology.nbrs_table(self.wan_topo)
        out = []
        for col in range(self.cfg.wan.degree):
            j = int(wan_nbrs[i, col])
            dc, srv = divmod(j, self.cfg.servers_per_dc)
            out.append({
                "id": f"srv{srv}.dc{dc}", "dc": f"dc{dc}",
                "status": ["alive", "suspect", "dead", "left"][int(st[col])],
            })
        return out

    def true_dc_distance_order(self, from_dc: int) -> list[int]:
        """Ground-truth DC ordering by site distance (for tests)."""
        s = self.cfg.servers_per_dc
        sites = self.wan_world.pos[::s]
        d = jnp.linalg.norm(sites - sites[from_dc], axis=1)
        return [int(i) for i in jnp.argsort(d)]
