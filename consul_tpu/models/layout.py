"""StateLayout: packed per-node state for beyond-HBM populations.

The dense ``SimState`` spends 4 bytes on every per-node element (189
elements = 756 B/node at the default K=16 view), which caps a chip at
~16M nodes. This module defines a packed twin — ``PackedSimState`` —
that stores the same information in 296 B/node (2.55x smaller) and the
``pack``/``unpack`` bijection between them. The dense f32/i32 path
remains the golden-parity reference (tests/test_layout_parity.py), the
same contract ``step_reference`` carries for the fused serf core.

Encoding rules, with the invariants that make round-trips exact:

* **Discrete plane — bit-exact.** Every integer field is narrowed to
  the width its protocol bound needs: statuses are 2 bits, gossip
  retransmit budgets 6 bits (``retransmit_mult * log10`` stays < 64 up
  to 10^15 nodes), probe-permutation columns and probe cursors 8 bits
  (requires K <= 255), incarnations 16 bits (saturating; a simulated
  node refutes a handful of times, never 65k). Unpack(pack(x)) == x
  whenever the bounds hold, so the SWIM plane is *bit-identical* to the
  dense reference — asserted, not hoped, by the parity suite.

* **Tick-anchored deadlines become saturating i16/u16 deltas.**
  ``next_probe_tick``/``pending_fail_tick`` are stored relative to the
  current tick (live values span at most one awareness-scaled probe
  interval); ``susp_start`` as age-since with a u16 sentinel for
  "none". A *frozen* deadline on a dead node drifts past the i16 range
  and saturates — behaviorally identical because both the packed and
  dense step only ever compare ``t >= deadline``, and a saturated past
  deadline is still past. ``pending_fail_tick`` is additionally
  canonicalized to ``t`` every tick while no probe is outstanding
  (models/swim.py step tail) so the delta of every *live* window fits
  exactly.

* **Vivaldi floats in bf16 at rest, f32 in flight.** Coordinates,
  heights, errors and adjustments round to bfloat16 between ticks; the
  step computes in f32 as before (unpack widens). bf16's ~0.4% relative
  rounding sits an order of magnitude below the 5% RTT jitter the world
  model injects, so convergence is not degraded — the parity suite
  asserts the packed path's final RMSE matches the dense reference's
  within tolerance rather than trusting this argument.

* **RTT sample windows in scaled float8.** ``lat_buf``/``adj_samples``
  hold RTT-magnitude seconds; stored as ``float8_e4m3fn`` scaled by
  256 (a power of two, so the scaling itself is exact). Range: +-1.75 s
  saturating (beyond the chaos Degrade envelope; the Vivaldi gate
  rejects >10 s observations anyway), resolution floor 2^-9/256 ~ 7.6us
  against millisecond-scale RTTs.

Documented bounds (validate() enforces the static ones): K <= 255,
retransmit limit <= 63, awareness_max <= 256, probe interval <= 32767
ticks, adjustment window <= 255; saturation beyond incarnation 65535,
suspicion age 65534 ticks, or 65535 latency samples per peer
(~5.2M ticks at the probe cadence) is accepted and documented rather
than guarded — all far outside simulated regimes.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from consul_tpu.config import SimConfig
from consul_tpu.ops import merge, vivaldi

DENSE = "dense"
PACKED = "packed"
LAYOUTS = (DENSE, PACKED)

# float8_e4m3fn codec for RTT-scale seconds: scale by 2^8 (exact), clip
# to the format's finite range. max finite e4m3fn = 448 -> +-1.75 s.
_F8 = jnp.float8_e4m3fn
_F8_SCALE = 256.0
_F8_CLIP = 448.0 / _F8_SCALE

# Sentinels for "none" in narrowed fields.
_NO_COL = 255        # pending_col == -1
_NO_SUSP = 65535     # susp_start == -1
_SUSP_MAX = 65534    # saturation for live suspicion ages

# meta[N, K] bit layout: status(2) | tx_left(6) | probe_perm(8).
_META_STATUS_BITS = 2
_META_TX_BITS = 6
_META_TX_MAX = (1 << _META_TX_BITS) - 1


def _to_f8(x):
    """f32 seconds -> scaled float8 (saturating)."""
    return (jnp.clip(x, -_F8_CLIP, _F8_CLIP) * _F8_SCALE).astype(_F8)


def _from_f8(x):
    """Scaled float8 -> f32 seconds (exact: power-of-two scale)."""
    return x.astype(jnp.float32) / _F8_SCALE


class PackedVivaldi(NamedTuple):
    """VivaldiState at rest: bf16 coordinates, float8 sample window."""

    vec: jax.Array          # [..., D] bfloat16
    height: jax.Array       # [...]    bfloat16
    error: jax.Array        # [...]    bfloat16
    adjustment: jax.Array   # [...]    bfloat16
    adj_samples: jax.Array  # [..., W] float8_e4m3fn (x256 codec)
    adj_idx: jax.Array      # [...]    uint8 (W <= 255)
    resets: jax.Array       # [...]    uint8 (wraps mod 256; diagnostic)


class PackedSimState(NamedTuple):
    """SimState at rest, 296 B/node at K=16 (vs 756 dense f32/i32)."""

    t: jax.Array            # [] int32 — the global tick stays wide; the
                            # deltas below are anchored to it
    flags: jax.Array        # [N] uint8: alive_truth|left<<1|leaving<<2|
                            # external<<3
    own_inc: jax.Array      # [N] uint16 (saturating)
    own_tx: jax.Array       # [N] uint8 (own_limit <= max(63, K) <= 255)
    awareness: jax.Array    # [N] uint8 (awareness_max <= 256)
    probe_ptr: jax.Array    # [N] uint8 (K <= 255)
    next_probe_delta: jax.Array   # [N] int16 = next_probe_tick - t (sat)
    pending_col: jax.Array        # [N] uint8, 255 = none
    pending_fail_delta: jax.Array  # [N] int16 = pending_fail_tick - t (sat)
    pending_nack_miss: jax.Array   # [N] uint8 (<= indirect_checks/tick,
                                   # cleared on window close)
    view_inc: jax.Array     # [N, K] uint16 view incarnation (saturating)
    meta: jax.Array         # [N, K] uint16: status(2)|tx_left(6)|perm(8)
    susp_delta: jax.Array   # [N, K] uint16 = t - susp_start, 65535 = none
    susp_seen: jax.Array    # [N, K] uint32 accuser bitmask (irreducible:
                            # 32 hash buckets are the protocol)
    lat_cnt: jax.Array      # [N, K] uint16 (saturating at 65535 samples)
    lat_buf: jax.Array      # [N, K, S] float8_e4m3fn (x256 codec)
    viv: PackedVivaldi      # batched [N]


def validate(cfg: SimConfig, layout: str) -> None:
    """Reject configs whose protocol bounds overflow the packed widths.
    Static, host-side, and exhaustive: any config that passes here
    round-trips the discrete plane exactly."""
    if layout not in LAYOUTS:
        raise ValueError(f"unknown state layout {layout!r}; "
                         f"expected one of {LAYOUTS}")
    if layout == DENSE:
        return
    from consul_tpu.ops import scaling

    k_deg = cfg.degree
    if k_deg > 255:
        raise ValueError(
            f"packed layout needs view degree <= 255 (8-bit probe "
            f"columns + pending_col sentinel); got K={k_deg}")
    tx_limit = int(scaling.retransmit_limit(cfg.gossip.retransmit_mult,
                                            cfg.n))
    if tx_limit > _META_TX_MAX:
        raise ValueError(
            f"packed layout stores tx_left in {_META_TX_BITS} bits "
            f"(<= {_META_TX_MAX}); retransmit limit for n={cfg.n} is "
            f"{tx_limit}")
    if cfg.gossip.awareness_max > 256:
        raise ValueError(
            f"packed layout stores awareness in 8 bits; awareness_max="
            f"{cfg.gossip.awareness_max} > 256")
    interval_max = cfg.gossip.probe_period_ticks * cfg.gossip.awareness_max
    if interval_max > 32767:
        raise ValueError(
            f"packed layout stores probe deadlines as i16 tick deltas; "
            f"max probe interval {interval_max} overflows")
    if cfg.vivaldi.adjustment_window_size > 255:
        raise ValueError(
            f"packed layout stores the adjustment-window cursor in 8 "
            f"bits; window size {cfg.vivaldi.adjustment_window_size}")


def pack(state) -> PackedSimState:
    """Dense SimState -> PackedSimState (elementwise; shard_map-safe)."""
    t = state.t
    flags = (state.alive_truth.astype(jnp.uint8)
             | (state.left.astype(jnp.uint8) << 1)
             | (state.leaving.astype(jnp.uint8) << 2)
             | (state.external.astype(jnp.uint8) << 3))
    status = (state.view_key & (merge.N_STATUS - 1)).astype(jnp.uint16)
    tx = jnp.clip(state.tx_left, 0, _META_TX_MAX).astype(jnp.uint16)
    meta = (status
            | (tx << _META_STATUS_BITS)
            | (state.probe_perm.astype(jnp.uint16)
               << (_META_STATUS_BITS + _META_TX_BITS)))
    susp_age = jnp.clip(t - state.susp_start, 0, _SUSP_MAX)
    susp_delta = jnp.where(state.susp_start < 0, _NO_SUSP,
                           susp_age).astype(jnp.uint16)
    v = state.viv
    return PackedSimState(
        t=t,
        flags=flags,
        own_inc=jnp.minimum(state.own_inc, 65535).astype(jnp.uint16),
        own_tx=jnp.clip(state.own_tx, 0, 255).astype(jnp.uint8),
        awareness=state.awareness.astype(jnp.uint8),
        probe_ptr=state.probe_ptr.astype(jnp.uint8),
        next_probe_delta=jnp.clip(
            state.next_probe_tick - t, -32768, 32767).astype(jnp.int16),
        pending_col=jnp.where(state.pending_col < 0, _NO_COL,
                              state.pending_col).astype(jnp.uint8),
        pending_fail_delta=jnp.clip(
            state.pending_fail_tick - t, -32768, 32767).astype(jnp.int16),
        pending_nack_miss=jnp.clip(
            state.pending_nack_miss, 0, 255).astype(jnp.uint8),
        view_inc=jnp.minimum(merge.key_incarnation(state.view_key),
                             65535).astype(jnp.uint16),
        meta=meta,
        susp_delta=susp_delta,
        susp_seen=state.susp_seen,
        lat_cnt=jnp.minimum(state.lat_cnt, 65535).astype(jnp.uint16),
        lat_buf=_to_f8(state.lat_buf),
        viv=PackedVivaldi(
            vec=v.vec.astype(jnp.bfloat16),
            height=v.height.astype(jnp.bfloat16),
            error=v.error.astype(jnp.bfloat16),
            adjustment=v.adjustment.astype(jnp.bfloat16),
            adj_samples=_to_f8(v.adj_samples),
            adj_idx=v.adj_idx.astype(jnp.uint8),
            resets=v.resets.astype(jnp.uint8),
        ),
    )


def unpack(packed: PackedSimState):
    """PackedSimState -> dense SimState the step functions consume."""
    from consul_tpu.models import state as sim_state

    t = packed.t
    status = (packed.meta & (merge.N_STATUS - 1)).astype(jnp.uint32)
    tx_left = ((packed.meta >> _META_STATUS_BITS)
               & _META_TX_MAX).astype(jnp.int32)
    perm = (packed.meta
            >> (_META_STATUS_BITS + _META_TX_BITS)).astype(jnp.int32)
    susp_start = jnp.where(
        packed.susp_delta == _NO_SUSP, jnp.int32(-1),
        t - packed.susp_delta.astype(jnp.int32))
    pv = packed.viv
    return sim_state.SimState(
        t=t,
        alive_truth=(packed.flags & 1) != 0,
        left=(packed.flags & 2) != 0,
        leaving=(packed.flags & 4) != 0,
        external=(packed.flags & 8) != 0,
        own_inc=packed.own_inc.astype(jnp.uint32),
        own_tx=packed.own_tx.astype(jnp.int32),
        awareness=packed.awareness.astype(jnp.int32),
        probe_perm=perm,
        probe_ptr=packed.probe_ptr.astype(jnp.int32),
        next_probe_tick=t + packed.next_probe_delta.astype(jnp.int32),
        pending_col=jnp.where(packed.pending_col == _NO_COL, jnp.int32(-1),
                              packed.pending_col.astype(jnp.int32)),
        pending_fail_tick=t + packed.pending_fail_delta.astype(jnp.int32),
        pending_nack_miss=packed.pending_nack_miss.astype(jnp.int32),
        view_key=merge.make_key(packed.view_inc.astype(jnp.uint32), status),
        susp_start=susp_start,
        susp_seen=packed.susp_seen,
        tx_left=tx_left,
        viv=vivaldi.VivaldiState(
            vec=pv.vec.astype(jnp.float32),
            height=pv.height.astype(jnp.float32),
            error=pv.error.astype(jnp.float32),
            adjustment=pv.adjustment.astype(jnp.float32),
            adj_samples=_from_f8(pv.adj_samples),
            adj_idx=pv.adj_idx.astype(jnp.int32),
            resets=pv.resets.astype(jnp.int32),
        ),
        lat_buf=_from_f8(packed.lat_buf),
        lat_cnt=packed.lat_cnt.astype(jnp.int32),
    )


# ----------------------------------------------------------------------
# Whole-driver-state dispatch: SerfState keeps its (already PR-7-packed)
# event/query plane verbatim and swaps only the SWIM plane.
# ----------------------------------------------------------------------

def pack_state(state):
    """Pack a driver state (SimState or SerfState) for at-rest storage.
    Idempotent: an already-packed SWIM plane passes through."""
    if hasattr(state, "swim"):
        if isinstance(state.swim, PackedSimState):
            return state
        return state._replace(swim=pack(state.swim))
    if isinstance(state, PackedSimState):
        return state
    return pack(state)


def unpack_state(state):
    """Inverse of :func:`pack_state` (idempotent on dense input)."""
    if hasattr(state, "swim"):
        if isinstance(state.swim, PackedSimState):
            return state._replace(swim=unpack(state.swim))
        return state
    if isinstance(state, PackedSimState):
        return unpack(state)
    return state


def is_packed(state) -> bool:
    sw = state.swim if hasattr(state, "swim") else state
    return isinstance(sw, PackedSimState)


def swim_plane(state):
    """The SWIM plane of any driver state, dense, without touching the
    rest: the cheap accessor host code uses to read ``t`` off a packed
    state without materializing a dense copy of the K-plane."""
    sw = state.swim if hasattr(state, "swim") else state
    if isinstance(sw, PackedSimState):
        return unpack(sw)
    return sw


def tick_of(state):
    """Current tick of any (possibly packed) driver state — reads the
    ``t`` leaf directly, no unpacking, no dense materialization."""
    sw = state.swim if hasattr(state, "swim") else state
    return sw.t


def bytes_per_node(tree, n: int) -> float:
    """At-rest bytes per node of a state pytree with node axis size n
    (abstract values welcome — pairs with jax.eval_shape)."""
    total = sum(int(np_size_bytes(l)) for l in jax.tree.leaves(tree))
    return total / float(n)


def np_size_bytes(leaf) -> int:
    return int(leaf.size) * int(jnp.dtype(leaf.dtype).itemsize)
