"""On-device gossip counters: the per-tick event tallies the reference
emits through go-metrics, accumulated inside the jitted scan.

The reference increments a counter per protocol event — every
``aliveNode``/``suspectNode``/``deadNode`` processed, every UDP packet
sent/received, every push-pull exchange (memberlist state.go/net.go),
every serf event queued or rebroadcast (serf/serf.go) — on the host,
per operation. Here the same accounting is a :class:`GossipCounters`
pytree of i32 scalars threaded through ``swim.step_counted`` /
``serf.step_counted`` and summed across the chunk scan
(models/cluster.py), so true counter semantics cost one extra
device→host fetch per chunk and zero extra XLA compiles. The sharded
path ``psum``-reduces the pytree over the node axis
(parallel/shard_step.py), so each counter is the global total on every
device.

Counter dtypes are i32 *per chunk*: the largest per-chunk tally
(gossip_rx at n=1M, fan=3, chunk=128 ≈ 4·10⁸) fits comfortably; the
host accumulates chunk deltas into Python ints (models/cluster.py
``Simulation.counters``), so cumulative totals never wrap.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class GossipCounters(NamedTuple):
    """Per-tick (or per-chunk, after scan summation) protocol event
    tallies. All [] i32. Field order is the wire order of the stacked
    device→host fetch — keep FIELDS in sync."""

    probes_sent: jax.Array          # probe cycles launched (§3)
    acks_received: jax.Array        # probes acked (direct|indirect|tcp)
    nacks_received: jax.Array       # Lifeguard nacks returned by relays
    probe_timeouts: jax.Array       # probe windows closed with no ack
    suspicions_started: jax.Array   # suspicion timers started/restarted
    refutations: jax.Array          # own-incarnation bumps (refute)
    deaths_declared: jax.Array      # suspicion expiries -> dead declared
    gossip_tx: jax.Array            # gossip packets put on the wire
    gossip_rx: jax.Array            # gossip packets accepted by a live rx
    gossip_msgs_tx: jax.Array       # queued broadcast msgs transmitted
                                    # (packets x piggybacked facts — the
                                    # TransmitLimitedQueue drain volume,
                                    # the sweep Pareto bandwidth axis)
    pushpull_merges: jax.Array      # push-pull merges applied (both dirs)
    serf_intents_queued: jax.Array  # serf events/queries staged into queues
    serf_intents_retx: jax.Array    # serf queue entries retransmitted
    serf_intents_dropped: jax.Array  # serf queue evictions under pressure
    # -- chaos SLO probes (consul_tpu/chaos): ticks are accumulated
    # on-device while the condition holds, so a chunk delta divided by
    # the scenario's fault count is the mean time-to-X in ticks. All
    # zero when no fault schedule is installed (the chaos block is a
    # trace-time branch, models/swim.py).
    chaos_fault_ticks: jax.Array        # ticks any injected fault active
    chaos_first_suspect_wait: jax.Array  # fault ticks before 1st suspicion
    chaos_confirm_wait: jax.Array       # fault ticks before 1st death
    chaos_heal_wait: jax.Array          # post-lift ticks with stale views
    chaos_false_deaths: jax.Array       # deaths of up, reachable nodes
    chaos_msgs_dropped: jax.Array       # gossip packets cut by chaos alone
    # -- invariant sentinels (consul_tpu/runtime): violation tallies
    # from the compiled end-of-tick validator (models/swim.py
    # _sentinel_check). All zero on a healthy run; the host tier
    # fail-fasts on any nonzero field (models/cluster.py). Like the
    # chaos block, the validator is a trace-time branch — sentinels off
    # emits the exact pre-sentinel program.
    sentinel_range: jax.Array           # values outside their legal range
    sentinel_monotonic: jax.Array       # incarnation/Lamport regressions
    sentinel_suspicion: jax.Array       # timer/accuser-bitmask mismatches
    sentinel_nonfinite_coord: jax.Array  # NaN/Inf Vivaldi coordinate rows
    sentinel_nonfinite_rtt: jax.Array   # NaN/Inf RTT filter entries
    # -- serving write plane (consul_tpu/serving/writes.py): applied
    # device writes. The scan never touches this field; the
    # WriteBatcher folds it host-side per batch through
    # ``Simulation._fold_counter_deltas``, so the cumulative total IS
    # the monotone device apply index — every counters_snapshot() and
    # bench artifact carries the index its reads are consistent as of.
    writes_applied: jax.Array           # serving writes applied on device


FIELDS = GossipCounters._fields

# Sink names each counter folds into at the chunk boundary
# (telemetry.emit_counter_deltas). Reference names where the reference
# has a counter for the event; ``sim.*`` where it does not (the
# COVERAGE.md telemetry table maps every name to its reference source,
# and tests/test_metric_names.py asserts the table stays complete).
METRIC_NAMES = {
    "probes_sent": "memberlist.probeNode",
    "acks_received": "sim.probe.acks",
    "nacks_received": "sim.probe.nacks",
    "probe_timeouts": "sim.probe.timeouts",
    "suspicions_started": "memberlist.msg.suspect",
    "refutations": "memberlist.msg.alive",
    "deaths_declared": "memberlist.msg.dead",
    "gossip_tx": "memberlist.udp.sent",
    "gossip_rx": "memberlist.udp.received",
    "gossip_msgs_tx": "sim.gossip.msgs_sent",
    "pushpull_merges": "memberlist.pushPullNode",
    "serf_intents_queued": "serf.events",
    "serf_intents_retx": "sim.serf.event_retransmits",
    "serf_intents_dropped": "sim.serf.event_drops",
    "chaos_fault_ticks": "sim.chaos.fault_ticks",
    "chaos_first_suspect_wait": "sim.chaos.time_to_first_suspect",
    "chaos_confirm_wait": "sim.chaos.time_to_confirm",
    "chaos_heal_wait": "sim.chaos.time_to_heal",
    "chaos_false_deaths": "sim.chaos.false_positive_deaths",
    "chaos_msgs_dropped": "sim.chaos.messages_dropped",
    "sentinel_range": "sim.sentinel.range_violations",
    "sentinel_monotonic": "sim.sentinel.monotonicity_violations",
    "sentinel_suspicion": "sim.sentinel.suspicion_violations",
    "sentinel_nonfinite_coord": "sim.sentinel.nonfinite_coordinates",
    "sentinel_nonfinite_rtt": "sim.sentinel.nonfinite_rtt",
    "writes_applied": "sim.serving.writes_applied",
}
assert set(METRIC_NAMES) == set(FIELDS)

# The invariant-sentinel fields, in bitmask order: bit i of the host
# tier's violation mask (violation_mask) is SENTINEL_FIELDS[i].
SENTINEL_FIELDS = tuple(f for f in FIELDS if f.startswith("sentinel_"))


def violation_mask(deltas: dict) -> int:
    """Fold a counter-delta dict into the sentinel violation bitmask:
    bit i set iff SENTINEL_FIELDS[i] saw a nonzero tally. Zero means
    every checked invariant held over the window."""
    mask = 0
    for i, f in enumerate(SENTINEL_FIELDS):
        if deltas.get(f, 0):
            mask |= 1 << i
    return mask


def zeros() -> GossipCounters:
    z = jnp.zeros((), jnp.int32)
    return GossipCounters(*([z] * len(FIELDS)))


def count(mask) -> jax.Array:
    """Sum a bool mask of any shape down to one i32 scalar."""
    return jnp.sum(mask).astype(jnp.int32)


def add(a: GossipCounters, b: GossipCounters) -> GossipCounters:
    return jax.tree.map(jnp.add, a, b)


def stack(c: GossipCounters) -> jax.Array:
    """[len(FIELDS)] i32 — the single batched transfer shape."""
    return jnp.stack(list(c))


def unstack(vec) -> GossipCounters:
    return GossipCounters(*(vec[i] for i in range(len(FIELDS))))
