"""Agent-local state + anti-entropy sync into the catalog.

Mirrors the reference's local state (reference agent/local/state.go,
1339 LoC): the agent owns its service/check registrations with per-entry
``in_sync`` flags; anti-entropy diffs local vs remote catalog state
(``updateSyncState`` :829) and pushes the difference (``SyncFull``
:1003 / ``SyncChanges`` :1021) — remote entries the agent doesn't know
are deregistered, local entries out of sync are re-registered.

The syncer cadence logic (cluster-size-scaled stagger, retry on
failure) mirrors ``ae.StateSyncer`` (reference agent/ae/ae.go:52-143).
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any, Callable, Optional

SYNC_INTERVAL_S = 60.0       # reference ae.go DefaultSyncInterval
SYNC_STAGGER_FRAC = 1 / 3    # reference ae.go staggerFn scaleFactor base


@dataclasses.dataclass
class LocalService:
    id: str
    service: str
    port: int = 0
    tags: list = dataclasses.field(default_factory=list)
    meta: dict = dataclasses.field(default_factory=dict)
    in_sync: bool = False


@dataclasses.dataclass
class LocalCheck:
    check_id: str
    status: str = "critical"
    service_id: str = ""
    output: str = ""
    in_sync: bool = False


class LocalState:
    """The agent's own registrations; the source of truth that
    anti-entropy imposes on the catalog."""

    def __init__(self, node: str, address: str):
        self.node = node
        self.address = address
        self.services: dict[str, LocalService] = {}
        self.checks: dict[str, LocalCheck] = {}
        self.node_in_sync = False

    # -- registration API (reference agent/local/state.go AddService
    # :214, AddCheck :356, Remove* — each marks the entry dirty) -------
    def add_service(self, service_id: str, service: str, port: int = 0,
                    tags: Optional[list] = None, meta: Optional[dict] = None):
        self.services[service_id] = LocalService(
            service_id, service, port, tags or [], meta or {}
        )

    def remove_service(self, service_id: str):
        self.services.pop(service_id, None)
        for cid in [c for c, chk in self.checks.items()
                    if chk.service_id == service_id]:
            del self.checks[cid]

    def add_check(self, check_id: str, status: str = "critical",
                  service_id: str = "", output: str = ""):
        self.checks[check_id] = LocalCheck(check_id, status, service_id, output)

    def remove_check(self, check_id: str):
        self.checks.pop(check_id, None)

    def update_check(self, check_id: str, status: str, output: str = ""):
        """Check status changes mark the entry dirty so the next sync
        pushes it (reference local/state.go UpdateCheck :505)."""
        c = self.checks.get(check_id)
        if c is None:
            return
        if c.status != status or c.output != output:
            c.status, c.output, c.in_sync = status, output, False

    # -- anti-entropy --------------------------------------------------
    def update_sync_state(self, rpc: Callable[..., Any]):
        """Diff local vs remote and mark out-of-sync entries
        (reference local/state.go updateSyncState :829). Returns the
        set of remote-only ids to deregister."""
        remote_services = {
            s["id"]: s for s in rpc("Catalog.NodeServices",
                                    node=self.node)["value"]
        }
        remote_checks = {
            c["check_id"]: c
            for c in rpc("Health.NodeChecks", node=self.node)["value"]
        }
        for sid, svc in self.services.items():
            r = remote_services.get(sid)
            svc.in_sync = bool(
                r and r["service"] == svc.service and r["port"] == svc.port
                and r["tags"] == svc.tags
            )
        for cid, chk in self.checks.items():
            r = remote_checks.get(cid)
            chk.in_sync = bool(
                r and r["status"] == chk.status and
                r.get("output", "") == chk.output
            )
        extra_services = set(remote_services) - set(self.services)
        # serfHealth is owned by the leader reconcile loop, never the
        # agent (reference local/state.go:889 skips it).
        extra_checks = {c for c in set(remote_checks) - set(self.checks)
                        if c != "serfHealth"}
        return extra_services, extra_checks

    def sync_changes(self, rpc: Callable[..., Any]) -> int:
        """Push every out-of-sync entry (reference SyncChanges :1021).
        Returns the number of writes issued."""
        writes = 0
        extra_services, extra_checks = self.update_sync_state(rpc)
        for sid in extra_services:
            rpc("Catalog.Deregister", node=self.node, service_id=sid)
            writes += 1
        for cid in extra_checks:
            rpc("Catalog.Deregister", node=self.node, check_id=cid)
            writes += 1
        if not self.node_in_sync:
            rpc("Catalog.Register", node=self.node, address=self.address)
            self.node_in_sync = True
            writes += 1
        for svc in self.services.values():
            if not svc.in_sync:
                rpc("Catalog.Register", node=self.node, address=self.address,
                    service={"id": svc.id, "service": svc.service,
                             "port": svc.port, "tags": svc.tags,
                             "meta": svc.meta})
                svc.in_sync = True
                writes += 1
        for chk in self.checks.values():
            if not chk.in_sync:
                rpc("Catalog.Register", node=self.node, address=self.address,
                    check={"check_id": chk.check_id, "status": chk.status,
                           "service_id": chk.service_id,
                           "output": chk.output})
                chk.in_sync = True
                writes += 1
        return writes

    def sync_full(self, rpc: Callable[..., Any]) -> int:
        """Mark everything dirty, then sync (reference SyncFull :1003)."""
        self.node_in_sync = False
        for svc in self.services.values():
            svc.in_sync = False
        for chk in self.checks.values():
            chk.in_sync = False
        return self.sync_changes(rpc)


def sync_stagger_s(cluster_size: int, rng: random.Random,
                   interval_s: float = SYNC_INTERVAL_S) -> float:
    """Anti-entropy interval with cluster-size scaling + random stagger
    (reference ae.go:92-…: the interval scales up by log-ish factors as
    the cluster grows so aggregate sync load stays bounded)."""
    scale = 1.0
    if cluster_size > 128:
        import math
        scale = math.ceil(math.log2(cluster_size) - math.log2(128)) + 1.0
    base = interval_s * scale
    return base + rng.uniform(0, base * SYNC_STAGGER_FRAC)
