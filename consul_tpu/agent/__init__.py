"""Agent tier: the per-node runtime around the server core.

The reference runs an agent on every node (reference agent/agent.go):
local service/check registrations, anti-entropy sync into the catalog,
health-check execution, the coordinate send loop, and a TTL/refresh
cache of RPC results. This package is that runtime for the TPU
framework; the heavy per-node protocol work (SWIM, gossip, Vivaldi)
lives in the vectorized simulation, and agents bridge into it.
"""
