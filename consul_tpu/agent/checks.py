"""Health check runners: TTL and monitor (callback) checks.

The reference ships script/HTTP/TCP/TTL/gRPC/Docker/alias monitors
(reference agent/checks/check.go, 1325 LoC) that all funnel into the
same place: a status update on the agent's local state, which
anti-entropy then syncs to the catalog. This module keeps the two
shapes that exist in a simulation-first framework:

  - :class:`CheckTTL` — the application heartbeats via
    ``pass_/warn/fail``; silence past the TTL turns critical
    (reference checks/check.go CheckTTL).
  - :class:`CheckMonitor` — a callback probes something (a simulated
    node's ground truth, a subprocess, an HTTP endpoint — any callable)
    on an interval; its return value becomes the status (reference
    CheckMonitor for scripts, the callable generalizes the rest).

Both are time-explicit (``now`` parameters) so drivers and tests
control the clock, like the rest of the framework.
"""

from __future__ import annotations

from typing import Callable, Optional

from consul_tpu.agent.local import LocalState


class CheckTTL:
    def __init__(self, local: LocalState, check_id: str, ttl_s: float,
                 now: float = 0.0):
        self.local = local
        self.check_id = check_id
        self.ttl_s = ttl_s
        self.deadline = now + ttl_s

    def _update(self, status: str, output: str, now: float):
        self.deadline = now + self.ttl_s
        self.local.update_check(self.check_id, status, output)

    def pass_(self, now: float, output: str = ""):
        self._update("passing", output, now)

    def warn(self, now: float, output: str = ""):
        self._update("warning", output, now)

    def fail(self, now: float, output: str = ""):
        self._update("critical", output, now)

    def tick(self, now: float):
        """Expire: no heartbeat within the TTL means critical
        (reference check.go CheckTTL ttl timer)."""
        if now >= self.deadline:
            self.local.update_check(
                self.check_id, "critical",
                f"TTL expired ({self.ttl_s}s without update)",
            )


class CheckMonitor:
    def __init__(self, local: LocalState, check_id: str,
                 probe: Callable[[], tuple[str, str]],
                 interval_s: float, now: float = 0.0):
        self.local = local
        self.check_id = check_id
        self.probe = probe
        self.interval_s = interval_s
        self.next_run = now  # first probe runs immediately

    def tick(self, now: float):
        if now < self.next_run:
            return
        self.next_run = now + self.interval_s
        try:
            status, output = self.probe()
        except Exception as e:  # noqa: BLE001 — a crashing probe is critical
            status, output = "critical", f"check raised: {e!r}"
        if status not in ("passing", "warning", "critical"):
            status, output = "critical", f"bad probe status {status!r}"
        self.local.update_check(self.check_id, status, output)


class CheckRunner:
    """Owns all of an agent's checks and pumps them on the agent tick
    (replacing the reference's goroutine-per-check model with the
    framework's explicit time-step idiom)."""

    def __init__(self, local: LocalState):
        self.local = local
        self.checks: dict[str, object] = {}

    def add_ttl(self, check_id: str, ttl_s: float, service_id: str = "",
                now: float = 0.0) -> CheckTTL:
        self.local.add_check(check_id, "critical", service_id,
                             "TTL check has not reported yet")
        c = CheckTTL(self.local, check_id, ttl_s, now)
        self.checks[check_id] = c
        return c

    def add_monitor(self, check_id: str, probe: Callable[[], tuple[str, str]],
                    interval_s: float, service_id: str = "",
                    now: float = 0.0) -> CheckMonitor:
        self.local.add_check(check_id, "critical", service_id)
        c = CheckMonitor(self.local, check_id, probe, interval_s, now)
        self.checks[check_id] = c
        return c

    def remove(self, check_id: str):
        self.checks.pop(check_id, None)
        self.local.remove_check(check_id)

    def tick(self, now: float):
        for c in self.checks.values():
            c.tick(now)
