"""Health check runners: TTL and monitor (callback) checks.

The reference ships script/HTTP/TCP/TTL/gRPC/Docker/alias monitors
(reference agent/checks/check.go, 1325 LoC) that all funnel into the
same place: a status update on the agent's local state, which
anti-entropy then syncs to the catalog. This module keeps the two
shapes that exist in a simulation-first framework:

  - :class:`CheckTTL` — the application heartbeats via
    ``pass_/warn/fail``; silence past the TTL turns critical
    (reference checks/check.go CheckTTL).
  - :class:`CheckMonitor` — a callback probes something (a simulated
    node's ground truth, a subprocess, an HTTP endpoint — any callable)
    on an interval; its return value becomes the status (reference
    CheckMonitor for scripts, the callable generalizes the rest).

Both are time-explicit (``now`` parameters) so drivers and tests
control the clock, like the rest of the framework.
"""

from __future__ import annotations

from typing import Callable, Optional

from consul_tpu.agent.local import LocalState


class CheckTTL:
    def __init__(self, local: LocalState, check_id: str, ttl_s: float,
                 now: float = 0.0):
        self.local = local
        self.check_id = check_id
        self.ttl_s = ttl_s
        self.deadline = now + ttl_s

    def _update(self, status: str, output: str, now: float):
        self.deadline = now + self.ttl_s
        self.local.update_check(self.check_id, status, output)

    def pass_(self, now: float, output: str = ""):
        self._update("passing", output, now)

    def warn(self, now: float, output: str = ""):
        self._update("warning", output, now)

    def fail(self, now: float, output: str = ""):
        self._update("critical", output, now)

    def tick(self, now: float):
        """Expire: no heartbeat within the TTL means critical
        (reference check.go CheckTTL ttl timer)."""
        if now >= self.deadline:
            self.local.update_check(
                self.check_id, "critical",
                f"TTL expired ({self.ttl_s}s without update)",
            )


class CheckMonitor:
    def __init__(self, local: LocalState, check_id: str,
                 probe: Callable[[], tuple[str, str]],
                 interval_s: float, now: float = 0.0,
                 background: bool = False):
        """``background=True`` runs each probe on its own thread and
        posts the result when it completes — the reference runs every
        check in a goroutine (checks/check.go) precisely so a slow
        HTTP/TCP target cannot stall the agent; synchronous mode stays
        the default for deterministic in-process probes."""
        self.local = local
        self.check_id = check_id
        self.probe = probe
        self.interval_s = interval_s
        self.next_run = now  # first probe runs immediately
        self.background = background
        self._in_flight = False

    def _run_probe(self):
        try:
            status, output = self.probe()
        except Exception as e:  # noqa: BLE001 — a crashing probe is critical
            status, output = "critical", f"check raised: {e!r}"
        if status not in ("passing", "warning", "critical"):
            status, output = "critical", f"bad probe status {status!r}"
        self.local.update_check(self.check_id, status, output)
        self._in_flight = False

    def tick(self, now: float):
        if now < self.next_run or self._in_flight:
            return
        self.next_run = now + self.interval_s
        if self.background:
            import threading

            self._in_flight = True
            threading.Thread(target=self._run_probe, daemon=True).start()
        else:
            self._run_probe()


def http_probe(url: str, timeout_s: float = 10.0,
               method: str = "GET") -> tuple[str, str]:
    """One HTTP check probe (reference agent/checks/check.go CheckHTTP):
    2xx -> passing, 429 -> warning, anything else (or a transport
    error) -> critical; the body is the check output."""
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            body = resp.read(4096).decode(errors="replace")
            return "passing", f"HTTP {method} {url}: {resp.status}  " + body
    except urllib.error.HTTPError as e:
        body = (e.read(4096) or b"").decode(errors="replace")
        if e.code == 429:  # Too Many Requests (check.go:329-333)
            return "warning", f"HTTP {method} {url}: {e.code}  " + body
        return "critical", f"HTTP {method} {url}: {e.code}  " + body
    except OSError as e:
        return "critical", f"HTTP {method} {url} failed: {e}"


def tcp_probe(host: str, port: int, timeout_s: float = 10.0) -> tuple[str, str]:
    """One TCP check probe (reference CheckTCP): a completed connect is
    passing; refusal/timeouts are critical."""
    import socket

    try:
        with socket.create_connection((host, port), timeout=timeout_s):
            return "passing", f"TCP connect {host}:{port}: Success"
    except OSError as e:
        return "critical", f"TCP connect {host}:{port} failed: {e}"


class CheckRunner:
    """Owns all of an agent's checks and pumps them on the agent tick
    (replacing the reference's goroutine-per-check model with the
    framework's explicit time-step idiom).

    Runner inventory vs the reference (agent/checks/): TTL, monitor
    (script-check equivalent: any Python callable), HTTP, TCP, and
    alias are implemented. gRPC (grpc.go) and Docker (docker.go)
    runners are deliberately absent: neither the grpc package nor a
    container runtime exists in this build environment, and a runner
    that cannot execute would be dead code — both fit the
    ``add_monitor`` extension point (a probe returning (status,
    output)) when their dependencies exist."""

    def __init__(self, local: LocalState):
        self.local = local
        self.checks: dict[str, object] = {}

    def add_ttl(self, check_id: str, ttl_s: float, service_id: str = "",
                now: float = 0.0) -> CheckTTL:
        self.local.add_check(check_id, "critical", service_id,
                             "TTL check has not reported yet")
        c = CheckTTL(self.local, check_id, ttl_s, now)
        self.checks[check_id] = c
        return c

    def add_monitor(self, check_id: str, probe: Callable[[], tuple[str, str]],
                    interval_s: float, service_id: str = "",
                    now: float = 0.0, background: bool = False) -> CheckMonitor:
        self.local.add_check(check_id, "critical", service_id)
        c = CheckMonitor(self.local, check_id, probe, interval_s, now,
                         background)
        self.checks[check_id] = c
        return c

    def add_http(self, check_id: str, url: str, interval_s: float,
                 timeout_s: float = 10.0, service_id: str = "",
                 now: float = 0.0, background: bool = True) -> CheckMonitor:
        """HTTP check (reference CheckHTTP): a monitor over http_probe,
        backgrounded by default so a hung endpoint never stalls the
        agent tick."""
        return self.add_monitor(
            check_id, lambda: http_probe(url, timeout_s), interval_s,
            service_id, now, background)

    def add_tcp(self, check_id: str, host: str, port: int,
                interval_s: float, timeout_s: float = 10.0,
                service_id: str = "", now: float = 0.0,
                background: bool = True) -> CheckMonitor:
        """TCP check (reference CheckTCP): a monitor over tcp_probe,
        backgrounded by default."""
        return self.add_monitor(
            check_id, lambda: tcp_probe(host, port, timeout_s), interval_s,
            service_id, now, background)

    def add_script(self, check_id: str, argv: list, interval_s: float,
                   timeout_s: float = 30.0, service_id: str = "",
                   now: float = 0.0,
                   background: bool = True) -> CheckMonitor:
        """Script check (reference agent/checks/check.go CheckMonitor
        over exec: exit 0 = passing, 1 = warning, anything else —
        including a timeout or spawn failure — critical; output is the
        combined stdout/stderr tail)."""
        def probe() -> tuple[str, str]:
            import subprocess
            try:
                out = subprocess.run(
                    argv, capture_output=True, text=True,
                    errors="replace",  # binary output must not flip a
                    timeout=timeout_s)  # passing check to critical
            except subprocess.TimeoutExpired:
                return "critical", f"check timed out after {timeout_s}s"
            except OSError as e:
                return "critical", f"failed to run check: {e}"
            status = {0: "passing", 1: "warning"}.get(
                out.returncode, "critical")
            text = (out.stdout + out.stderr)[-4096:]
            return status, text

        return self.add_monitor(check_id, probe, interval_s, service_id,
                                now, background)

    def add_alias(self, check_id: str, rpc, target_node: str,
                  target_service_id: str = "", interval_s: float = 1.0,
                  service_id: str = "", now: float = 0.0,
                  background: bool = True) -> CheckMonitor:
        """Alias check (reference agent/checks/alias.go CheckAlias):
        mirrors the health of another node (or one service on it) into
        a local check. Worst-status-wins over the aliased checks; a
        node with no checks at all is passing (alias.go:150-158); an
        unreachable catalog is critical. The reference watches the
        remote health via blocking query; the tick-driven monitor polls
        the same RPC on its interval."""
        def probe() -> tuple[str, str]:
            try:
                out = rpc("Health.NodeChecks", node=target_node)
                rows = out["value"] if isinstance(out, dict) else out
            except Exception as e:  # noqa: BLE001 — check boundary
                return "critical", f"alias target query failed: {e}"
            if target_service_id:
                rows = [r for r in rows
                        if r.get("service_id") == target_service_id]
            # No checks on the target -> passing (alias.go:150-158).
            from consul_tpu.utils.health import worst_status
            worst = worst_status(r.get("status", "critical")
                                 for r in rows)
            return worst, (
                "All checks passing." if worst == "passing"
                else f"Aliased check(s) {worst} ({len(rows)} watched)."
            )

        return self.add_monitor(check_id, probe, interval_s, service_id,
                                now, background)

    def remove(self, check_id: str):
        self.checks.pop(check_id, None)
        self.local.remove_check(check_id)

    def tick(self, now: float):
        for c in self.checks.values():
            c.tick(now)
