"""DNS interface: service discovery over real DNS packets.

Mirrors the reference DNS server (reference agent/dns.go:186-1250):
``<node>.node[.<dc>].consul`` A lookups, ``[tag.]<service>.service
[.<dc>].consul`` A/SRV lookups over healthy instances, RFC 2782
``_service._tag.service.consul`` SRV syntax, ``<name>.query[.<dc>]
.consul`` prepared-query execution, ``<ip>.addr.consul`` and reverse
``in-addr.arpa`` PTR lookups, NXDOMAIN+SOA negative answers, shuffled
answers for load spread, and UDP truncation with the TC bit.

The wire codec is implemented here from the RFCs (1035/2782) — the
environment ships no DNS library, and the subset Consul speaks is
small: queries with one question, responses with A/AAAA/CNAME/SRV/PTR/
SOA records, name compression on decode (we emit uncompressed names).
Cross-DC lookups ride the same ``dc=`` RPC forwarding as HTTP.
"""

from __future__ import annotations

import ipaddress
import random
import socket
import socketserver
import struct
import threading
from typing import Any, Callable, Optional

# Record types (RFC 1035 / 2782).
A, NS, CNAME, SOA, PTR, TXT, AAAA, SRV, ANY = \
    1, 2, 5, 6, 12, 16, 28, 33, 255
# Response codes.
NOERROR, FORMERR, SERVFAIL, NXDOMAIN, NOTIMP, REFUSED = 0, 1, 2, 3, 4, 5

DEFAULT_UDP_ANSWER_LIMIT = 3          # reference config: dns_config.udp_answer_limit
MAX_UDP_PAYLOAD = 512                 # pre-EDNS0 classic limit


# ----------------------------------------------------------------------
# Wire codec
# ----------------------------------------------------------------------

def encode_name(name: str) -> bytes:
    out = b""
    for label in name.rstrip(".").split("."):
        if not label:
            continue
        raw = label.encode()
        if len(raw) > 63:
            raise ValueError(f"label too long: {label!r}")
        out += bytes([len(raw)]) + raw
    return out + b"\x00"


def decode_name(data: bytes, off: int) -> tuple[str, int]:
    """Decode a (possibly compressed) name; returns (name, next offset).
    Follows RFC 1035 §4.1.4 pointers with a hop cap against loops."""
    labels, hops, jumped, end = [], 0, False, off
    while True:
        if off >= len(data):
            raise ValueError("truncated name")
        ln = data[off]
        if ln & 0xC0 == 0xC0:
            if off + 1 >= len(data):
                raise ValueError("truncated pointer")
            ptr = ((ln & 0x3F) << 8) | data[off + 1]
            if not jumped:
                end = off + 2
            off, jumped, hops = ptr, True, hops + 1
            if hops > 32:
                raise ValueError("compression loop")
            continue
        off += 1
        if ln == 0:
            if not jumped:
                end = off
            break
        labels.append(data[off:off + ln].decode("ascii", "replace"))
        off += ln
    return ".".join(labels), end


def encode_query(qid: int, qname: str, qtype: int) -> bytes:
    # Flags: RD set (standard resolver behavior).
    return (struct.pack(">HHHHHH", qid, 0x0100, 1, 0, 0, 0)
            + encode_name(qname) + struct.pack(">HH", qtype, 1))


def _rdata(rtype: int, value: Any) -> bytes:
    if rtype == A:
        return ipaddress.IPv4Address(value).packed
    if rtype == AAAA:
        return ipaddress.IPv6Address(value).packed
    if rtype in (CNAME, PTR, NS):
        return encode_name(value)
    if rtype == SRV:
        pri, weight, port, target = value
        return struct.pack(">HHH", pri, weight, port) + encode_name(target)
    if rtype == TXT:
        raw = value.encode() if isinstance(value, str) else value
        return bytes([len(raw)]) + raw
    if rtype == SOA:
        mname, rname, serial, refresh, retry, expire, minimum = value
        return (encode_name(mname) + encode_name(rname)
                + struct.pack(">IIIII", serial, refresh, retry, expire,
                              minimum))
    raise ValueError(f"unsupported rtype {rtype}")


def encode_response(qid: int, qname: str, qtype: int, answers: list,
                    authority: list = (), rcode: int = NOERROR,
                    tc: bool = False) -> bytes:
    """answers/authority: [(name, rtype, ttl, value)]."""
    flags = 0x8480 | (0x0200 if tc else 0) | (rcode & 0xF)
    out = struct.pack(">HHHHHH", qid, flags, 1, len(answers),
                      len(authority), 0)
    out += encode_name(qname) + struct.pack(">HH", qtype, 1)
    for name, rtype, ttl, value in [*answers, *authority]:
        rd = _rdata(rtype, value)
        out += (encode_name(name)
                + struct.pack(">HHIH", rtype, 1, int(ttl), len(rd)) + rd)
    return out


def decode_message(data: bytes) -> dict:
    """Decode header + question + answer/authority records (the subset
    a test client or stub resolver needs)."""
    qid, flags, qd, an, ns_n, _ = struct.unpack(">HHHHHH", data[:12])
    off = 12
    questions = []
    for _ in range(qd):
        name, off = decode_name(data, off)
        qtype, qclass = struct.unpack(">HH", data[off:off + 4])
        off += 4
        questions.append({"name": name, "qtype": qtype})
    def records(n, off):
        out = []
        for _ in range(n):
            name, off = decode_name(data, off)
            rtype, _, ttl, rdlen = struct.unpack(">HHIH", data[off:off + 10])
            off += 10
            body = data[off:off + rdlen]
            if rtype == A:
                value: Any = str(ipaddress.IPv4Address(body))
            elif rtype == AAAA:
                value = str(ipaddress.IPv6Address(body))
            elif rtype in (CNAME, PTR, NS):
                value, _ = decode_name(data, off)
            elif rtype == SRV:
                pri, weight, port = struct.unpack(">HHH", body[:6])
                target, _ = decode_name(data, off + 6)
                value = (pri, weight, port, target)
            else:
                value = body
            off += rdlen
            out.append({"name": name, "rtype": rtype, "ttl": ttl,
                        "value": value})
        return out, off
    answers, off = records(an, off)
    authority, off = records(ns_n, off)
    return {"id": qid, "flags": flags, "rcode": flags & 0xF,
            "tc": bool(flags & 0x0200), "questions": questions,
            "answers": answers, "authority": authority}


# ----------------------------------------------------------------------
# Server
# ----------------------------------------------------------------------

class DNSServer:
    """Serves the ``.consul`` domain from the agent's RPC surface.

    ``rpc(method, **args)``: same route a HTTPApi uses (dc-aware).
    The server is transport-split like the reference (dns.go
    ListenAndServe starts a UDP and a TCP listener on the same port):
    UDP answers are truncated to ``udp_answer_limit`` with TC set when
    trimmed (trimDNSResponse), TCP returns everything length-prefixed.

    ``authz``: DNS packets carry no ACL token, so the reference
    resolves every lookup with the agent's own token under the
    configured default policy (agent/dns.go → agent.tokens; the vetters
    inside each catalog/health endpoint). Here the boot tier hands in
    one ``(resource, name, access) -> bool`` gate built from the agent
    token (boot.py _dns_authz); ``None`` means ACLs are off and every
    lookup is open. A denied service/node read answers REFUSED — never
    records, and never an NXDOMAIN that would poison negative caches
    for authorized resolvers on the same name.
    """

    def __init__(self, rpc: Callable[..., Any], *, node_name: str = "",
                 domain: str = "consul", datacenter: str = "dc1",
                 node_ttl_s: int = 0, service_ttl_s: int = 0,
                 udp_answer_limit: int = DEFAULT_UDP_ANSWER_LIMIT,
                 only_passing: bool = False, seed: int = 0,
                 authz: Optional[Callable[[str, str, str], bool]] = None,
                 serving: Optional[Callable[[list], list]] = None):
        self.rpc = rpc
        self.authz = authz
        # Optional serving-plane row sorter (rows -> rows): when set,
        # service answers come back in device-computed NearestN order
        # from this agent's node instead of the reference's random
        # shuffle. Opt-in; default DNS behavior is unchanged.
        self.serving = serving
        self.node_name = node_name
        self.domain = domain.strip(".").lower()
        self.datacenter = datacenter
        self.node_ttl_s = node_ttl_s
        self.service_ttl_s = service_ttl_s
        self.udp_answer_limit = udp_answer_limit
        self.only_passing = only_passing
        self.rng = random.Random(seed)
        self._udp: Optional[socketserver.ThreadingUDPServer] = None
        self._tcp: Optional[socketserver.ThreadingTCPServer] = None
        self.port = 0
        self.metrics = {"queries": 0, "nxdomain": 0, "errors": 0,
                        "truncated": 0}

    # -- lifecycle -----------------------------------------------------
    def serve(self, host: str = "127.0.0.1", port: int = 0) -> int:
        outer = self

        class UDPHandler(socketserver.BaseRequestHandler):
            def handle(self):
                data, sock = self.request
                out = outer.handle_packet(data, udp=True)
                if out:
                    sock.sendto(out, self.client_address)

        class TCPHandler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    hdr = self.request.recv(2)
                    if len(hdr) < 2:
                        return
                    (ln,) = struct.unpack(">H", hdr)
                    data = b""
                    while len(data) < ln:
                        chunk = self.request.recv(ln - len(data))
                        if not chunk:
                            return
                        data += chunk
                    out = outer.handle_packet(data, udp=False)
                    if out:
                        self.request.sendall(struct.pack(">H", len(out))
                                             + out)
                except OSError:
                    pass

        class _TCPServer(socketserver.ThreadingTCPServer):
            # Scoped to this subclass — mutating the stdlib class
            # would leak SO_REUSEADDR into every TCP server in the
            # process.
            allow_reuse_address = True

        self._udp = socketserver.ThreadingUDPServer((host, port), UDPHandler)
        self.port = self._udp.server_address[1]
        # TCP rides the same port number (dns.go serves both).
        self._tcp = _TCPServer((host, self.port), TCPHandler)
        for srv in (self._udp, self._tcp):
            srv.daemon_threads = True
            threading.Thread(target=srv.serve_forever, daemon=True).start()
        return self.port

    def close(self):
        for srv in (self._udp, self._tcp):
            if srv is not None:
                srv.shutdown()
                srv.server_close()

    # -- core ----------------------------------------------------------
    def handle_packet(self, data: bytes, udp: bool) -> Optional[bytes]:
        self.metrics["queries"] += 1
        try:
            msg = decode_message(data)
            q = msg["questions"][0]
        except (ValueError, struct.error, IndexError):
            self.metrics["errors"] += 1
            return None
        qid, qname, qtype = msg["id"], q["name"], q["qtype"]
        try:
            answers, rcode = self.answer(qname, qtype)
        except Exception:  # noqa: BLE001 — a lookup error is SERVFAIL
            self.metrics["errors"] += 1
            return encode_response(qid, qname, qtype, [], rcode=SERVFAIL)
        authority = []
        if rcode == NXDOMAIN or (rcode == NOERROR and not answers):
            # Negative answers carry the SOA (dns.go addSOA).
            self.metrics["nxdomain"] += rcode == NXDOMAIN
            authority = [(self.domain, SOA, 0, self._soa_value())]
        tc = False
        if udp and len(answers) > self.udp_answer_limit:
            # trimDNSResponse: drop answers, flag truncation so the
            # client can retry over TCP.
            answers = answers[:self.udp_answer_limit]
            tc = True
        out = encode_response(qid, qname, qtype, answers, authority,
                              rcode, tc)
        # Size trim too: a classic (non-EDNS0) stub drops datagrams
        # past 512 bytes, so keep shedding answers until we fit
        # (trimDNSResponse trims by size as well as count).
        while udp and len(out) > MAX_UDP_PAYLOAD and answers:
            answers = answers[:-1]
            tc = True
            out = encode_response(qid, qname, qtype, answers, authority,
                                  rcode, tc)
        if tc:
            self.metrics["truncated"] += 1
        return out

    def _soa_value(self):
        ns = f"ns.{self.domain}"
        return (ns, f"hostmaster.{self.domain}", 0, 3600, 600, 86400, 0)

    # -- dispatch (dns.go doDispatch:555-700) --------------------------
    def answer(self, qname: str, qtype: int) -> tuple[list, int]:
        labels = [p for p in qname.lower().split(".") if p]
        if labels[-2:] == ["in-addr", "arpa"]:
            return self._ptr_lookup(qname, labels)
        if not labels or labels[-1] != self.domain:
            return [], REFUSED
        labels = labels[:-1]
        if labels == ["ns"] or not labels:
            # Apex/NS queries answer the server itself (nameservers()).
            return ([(qname, SOA, 0, self._soa_value())]
                    if qtype in (SOA, ANY) else []), NOERROR
        kind_i = next((i for i in range(len(labels) - 1, -1, -1)
                       if labels[i] in ("service", "connect", "node",
                                        "query", "addr")), None)
        if kind_i is None:
            if qtype == SRV and labels and labels[-1].startswith("_"):
                # SRV's optional "service" label (doDispatch default arm).
                kind, parts, suffixes = "service", labels, []
            else:
                return [], NXDOMAIN
        else:
            kind = labels[kind_i]
            parts, suffixes = labels[:kind_i], labels[kind_i + 1:]
        dc = None
        if suffixes:
            if len(suffixes) > 1:
                return [], NXDOMAIN
            dc = suffixes[0] if suffixes[0] != self.datacenter else None
        if not parts:
            return [], NXDOMAIN
        if kind == "node":
            return self._node_lookup(qname, qtype, ".".join(parts), dc)
        if kind in ("service", "connect"):
            if (len(parts) == 2 and parts[0].startswith("_")
                    and parts[1].startswith("_")):
                # RFC 2782 _name._tag; _tcp means untagged (doDispatch).
                tag = parts[1][1:]
                return self._service_lookup(
                    qname, qtype, parts[0][1:],
                    "" if tag == "tcp" else tag, dc)
            tag = ".".join(parts[:-1]) if len(parts) >= 2 else ""
            return self._service_lookup(qname, qtype, parts[-1], tag, dc)
        if kind == "query":
            return self._query_lookup(qname, qtype, ".".join(parts), dc)
        if kind == "addr":
            # <hex-ip>.addr.consul (dns.go:680): echo the encoded
            # address back as an A record.
            try:
                ip = str(ipaddress.IPv4Address(bytes.fromhex(parts[0])))
            except ValueError:
                return [], NXDOMAIN
            return [(qname, A, self.node_ttl_s, ip)], NOERROR
        return [], NXDOMAIN

    # -- lookups -------------------------------------------------------
    def _allowed(self, resource: str, name: str) -> bool:
        return self.authz is None or self.authz(resource, name, "read")

    def _addr_records(self, qname: str, address: str, ttl: int) -> list:
        """A for IPv4, AAAA for IPv6, CNAME otherwise (dns.go
        formatNodeRecord)."""
        try:
            ip = ipaddress.ip_address(address)
        except ValueError:
            return [(qname, CNAME, ttl, address)]
        return [(qname, AAAA if ip.version == 6 else A, ttl, str(ip))]

    def _node_lookup(self, qname, qtype, node, dc):
        if not self._allowed("node", node):
            return [], REFUSED
        got = self.rpc("Internal.NodeInfo",
                       **({"node": node, "dc": dc} if dc
                          else {"node": node}))
        rows = got["value"]
        if not rows:
            return [], NXDOMAIN
        addr = rows[0].get("address", "")
        if not addr:
            return [], NXDOMAIN
        if qtype in (A, AAAA, ANY, TXT, SRV):
            return self._addr_records(qname, addr, self.node_ttl_s), NOERROR
        return [], NOERROR

    def _service_rows_to_records(self, qname, qtype, rows, ttl):
        if self.serving is not None:
            rows = self.serving(rows)
        else:
            self.rng.shuffle(rows)
        answers = []
        for r in rows:
            addr = (r["service"].get("address")
                    or r.get("address") or "")
            if qtype == SRV:
                target = f"{r['node']}.node.{self.domain}"
                answers.append((qname, SRV, ttl,
                                (1, 1, r["service"].get("port", 0),
                                 target)))
            elif addr:
                answers.extend(self._addr_records(qname, addr, ttl))
        return answers

    def _service_lookup(self, qname, qtype, service, tag, dc):
        if not self._allowed("service", service):
            return [], REFUSED
        args: dict = {"service": service,
                      "passing_only": self.only_passing}
        if dc:
            args["dc"] = dc
        out = self.rpc("Health.ServiceNodes", **args)
        rows = out["value"]
        if tag:
            rows = [r for r in rows
                    if tag in (r["service"].get("tags") or [])]
        # DNS always filters critical instances (lookupServiceNodes
        # filters; only_passing additionally drops warning).
        rows = [r for r in rows
                if r.get("aggregate_status", "passing") != "critical"]
        if not rows:
            return [], NXDOMAIN
        return (self._service_rows_to_records(
            qname, qtype, rows, self.service_ttl_s), NOERROR)

    def _query_lookup(self, qname, qtype, name, dc):
        args: dict = {"query_id_or_name": name}
        if dc:
            args["dc"] = dc
        if self.node_name:
            args["near"] = self.node_name
        try:
            out = self.rpc("PreparedQuery.Execute", **args)
        except KeyError:
            return [], NXDOMAIN
        raw = out.get("dns", {}).get("ttl", "")
        if isinstance(raw, (int, float)):
            # Tolerate a numeric TTL (seconds) — clients DO send
            # {"DNS": {"TTL": 10}}; crashing here would SERVFAIL every
            # lookup of the query.
            ttl = int(raw)
        else:
            try:
                ttl = int(float(raw.rstrip("s"))) if raw \
                    else self.service_ttl_s
            except (ValueError, AttributeError):
                ttl = self.service_ttl_s
        rows = out["nodes"]
        if not rows:
            return [], NXDOMAIN
        # Preserve the query's RTT sort: no extra shuffle when the
        # query declared Near (preparedQueryLookup keeps order).
        answers = []
        for r in rows:
            addr = (r["service"].get("address")
                    or r.get("address") or "")
            if qtype == SRV:
                answers.append((qname, SRV, ttl,
                                (1, 1, r["service"].get("port", 0),
                                 f"{r['node']}.node.{self.domain}")))
            elif addr:
                answers.extend(self._addr_records(qname, addr, ttl))
        return answers, NOERROR

    def _ptr_lookup(self, qname, labels):
        """Reverse lookup (dns.go handlePtr): match the address against
        catalog nodes."""
        octets = labels[:-2]
        if len(octets) != 4:
            return [], NXDOMAIN
        addr = ".".join(reversed(octets))
        out = self.rpc("Catalog.ListNodes")
        for n in out["value"]:
            if n.get("address") == addr:
                # Node-read gating filters, like the reference's row
                # vetting: a denied PTR looks like an absent record.
                if not self._allowed("node", n.get("node", "")):
                    return [], NXDOMAIN
                return [(qname, PTR, self.node_ttl_s,
                         f"{n['node']}.node.{self.domain}")], NOERROR
        return [], NXDOMAIN


def lookup(host: str, port: int, qname: str, qtype: int = A,
           timeout_s: float = 3.0, tcp: bool = False) -> dict:
    """Minimal stub resolver for tests/CLI (the dig of this module)."""
    qid = random.randrange(0x10000)
    pkt = encode_query(qid, qname, qtype)
    if tcp:
        with socket.create_connection((host, port),
                                      timeout=timeout_s) as s:
            s.sendall(struct.pack(">H", len(pkt)) + pkt)
            hdr = s.recv(2)
            (ln,) = struct.unpack(">H", hdr)
            data = b""
            while len(data) < ln:
                data += s.recv(ln - len(data))
    else:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.settimeout(timeout_s)
            s.sendto(pkt, (host, port))
            data, _ = s.recvfrom(4096)
    return decode_message(data)
