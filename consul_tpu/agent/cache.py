"""Agent RPC cache: TTL expiry + background blocking refresh.

Mirrors the reference agent cache (reference agent/cache/cache.go,
1511 LoC): typed entries keyed by request, fetched through a registered
type, served from memory with a TTL, and — for refresh-typed entries —
kept warm by a background goroutine running blocking queries so reads
are always fresh-ish and cheap. DNS/HTTP/proxycfg all read through it
(reference agent/cache-types/).

Here fetchers are callables returning ``{"index": i, "value": v}`` (the
blocking-read convention of the endpoint layer); refresh runs on
daemon threads issuing blocking queries with the last seen index.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional


class CacheEntry:
    def __init__(self, value: Any, index: int, expires_at: float):
        self.value = value
        self.index = index
        self.expires_at = expires_at
        self.hits = 0


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, CacheEntry] = {}
        self._refreshing: set[str] = set()
        self.metrics = {"hits": 0, "misses": 0, "fetches": 0}
        self._stop = threading.Event()

    def get(self, key: str, fetch: Callable[[int, float], dict],
            ttl_s: float = 3.0, refresh: bool = False,
            now: Optional[float] = None) -> Any:
        """Serve ``key`` from cache or fetch it. ``fetch(min_index,
        wait_s)`` must return ``{"index": i, "value": v}``. With
        ``refresh=True`` a background thread keeps the entry current via
        blocking queries (reference cache.go refresh types)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            e = self._entries.get(key)
            if e is not None and now < e.expires_at:
                e.hits += 1
                self.metrics["hits"] += 1
                return e.value
            self.metrics["misses"] += 1
        out = fetch(0, 0.0)
        with self._lock:
            self.metrics["fetches"] += 1
            self._entries[key] = CacheEntry(out["value"], out["index"],
                                            now + ttl_s)
            start_refresh = refresh and key not in self._refreshing
            if start_refresh:
                self._refreshing.add(key)
        if start_refresh:
            t = threading.Thread(
                target=self._refresh_loop, args=(key, fetch, ttl_s),
                daemon=True,
            )
            t.start()
        return out["value"]

    def _refresh_loop(self, key: str, fetch, ttl_s: float):
        """Background blocking-query loop (reference cache.go
        fetch/refresh goroutine): each round waits at the server for a
        change past the last index, then replaces the entry."""
        while not self._stop.is_set():
            with self._lock:
                e = self._entries.get(key)
                if e is None:
                    self._refreshing.discard(key)
                    return
                idx = e.index
            try:
                out = fetch(idx, 5.0)
            except Exception:  # noqa: BLE001 — server away; retry with backoff
                if self._stop.wait(0.2):
                    return
                continue
            with self._lock:
                cur = self._entries.get(key)
                if cur is not None:
                    cur.value = out["value"]
                    cur.index = out["index"]
                    cur.expires_at = time.monotonic() + ttl_s

    def invalidate(self, key: str):
        with self._lock:
            self._entries.pop(key, None)

    def close(self):
        self._stop.set()
