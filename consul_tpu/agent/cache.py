"""Agent RPC cache: typed entries, TTL expiry, background blocking
refresh, and shared blocking reads.

Mirrors the reference agent cache (reference agent/cache/cache.go,
1511 LoC): typed entries keyed by request, fetched through a registered
type, served from memory with a TTL, and — for refresh-typed entries —
kept warm by a background goroutine running blocking queries so reads
are always fresh-ish and cheap. DNS/HTTP/proxycfg all read through it
(reference agent/cache-types/, e.g. health_services.go).

The scalability trick being reproduced (reference cache.go Get with
MinIndex + the refresh goroutine): N HTTP long-pollers of the same
request do NOT open N store watches — they all park on the one cache
entry, which a SINGLE background blocking query keeps current; every
index advance wakes all parked watchers at once. ``get_blocking`` is
that path; the per-entry fetch counter is what tests assert on.

Fetchers are callables returning ``{"index": i, "value": v}`` (the
blocking-read convention of the endpoint layer); refresh runs on
daemon threads issuing blocking queries with the last seen index.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, NamedTuple, Optional


class CacheClosedError(RuntimeError):
    """``get()`` on a closed cache with nothing cached to serve — a
    closed cache never issues fetches, so there is no way to answer."""


class CacheType(NamedTuple):
    """A registered entry type (reference agent/cache-types/*): how to
    fetch this kind of request and its freshness policy."""

    name: str
    fetch_factory: Callable[..., Callable[[int, float], dict]]
    ttl_s: float
    refresh: bool


class CacheEntry:
    def __init__(self, value: Any, index: int, expires_at: float):
        self.value = value
        self.index = index
        self.expires_at = expires_at
        self.hits = 0
        self.fetches = 0  # store round-trips made on behalf of this key
        self.changed = threading.Condition()


class Cache:
    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, CacheEntry] = {}
        self._refreshing: set[str] = set()
        self._types: dict[str, CacheType] = {}
        self.metrics = {"hits": 0, "misses": 0, "fetches": 0}
        self._stop = threading.Event()
        # Live refresh threads, joined by close() so a dropped cache
        # takes its background blocking queries down with it.
        self._threads: list[threading.Thread] = []

    # -- typed entries (reference cache.go RegisterType + cache-types/) --
    def register_type(self, name: str, fetch_factory, ttl_s: float = 3.0,
                      refresh: bool = True) -> None:
        """``fetch_factory(**req)`` returns the fetcher for one concrete
        request of this type — e.g. the health-services type maps
        ``service="web"`` to a blocking Health.ServiceNodes call
        (reference agent/cache-types/health_services.go)."""
        self._types[name] = CacheType(name, fetch_factory, ttl_s, refresh)

    @staticmethod
    def _key(name: str, req: dict) -> str:
        return name + ":" + json.dumps(req, sort_keys=True, default=str)

    def get_typed(self, name: str, now: Optional[float] = None, **req):
        t = self._types[name]
        return self.get(self._key(name, req), t.fetch_factory(**req),
                        ttl_s=t.ttl_s, refresh=t.refresh, now=now)

    def get_blocking(self, name: str, min_index: int = 0,
                     wait_s: float = 10.0, **req) -> dict:
        """Blocking read THROUGH the cache: park until the entry's index
        passes ``min_index`` (or timeout), without opening a per-caller
        store watch — all callers of the same request share the one
        background refresh query. Returns ``{"index", "value"}``."""
        t = self._types[name]
        key = self._key(name, req)
        if self._stop.is_set():
            with self._lock:
                e = self._entries.get(key)
            if e is not None:
                return {"index": e.index, "value": e.value, "hit": True}
            raise CacheClosedError(key)
        if not t.refresh:
            # A non-refresh type has no background loop to advance the
            # entry — a parked read would only ever time out. Serve the
            # blocking read directly (the reference requires refresh
            # cache-types for background blocking support).
            out = t.fetch_factory(**req)(min_index, wait_s)
            return {"index": out["index"], "value": out["value"],
                    "hit": False}
        with self._lock:
            hit = key in self._entries
        deadline = time.monotonic() + wait_s
        e = None
        for _ in range(2):
            # Ensure the entry + its refresh loop exist (first caller
            # pays the initial fetch; everyone after rides the warm
            # entry). A concurrent invalidate() can drop the entry
            # between get() and the read — re-create, never KeyError.
            self.get(key, t.fetch_factory(**req), ttl_s=t.ttl_s,
                     refresh=True)
            with self._lock:
                e = self._entries.get(key)
            if e is not None:
                break
        if e is None:
            if self._stop.is_set():
                raise CacheClosedError(key)
            out = t.fetch_factory(**req)(min_index, wait_s)
            return {"index": out["index"], "value": out["value"],
                    "hit": False}
        with e.changed:
            while e.index <= min_index and min_index > 0:
                left = deadline - time.monotonic()
                if left <= 0 or self._stop.is_set():
                    break
                e.changed.wait(timeout=min(left, 1.0))
            return {"index": e.index, "value": e.value, "hit": hit}

    def get(self, key: str, fetch: Callable[[int, float], dict],
            ttl_s: float = 3.0, refresh: bool = False,
            now: Optional[float] = None) -> Any:
        """Serve ``key`` from cache or fetch it. ``fetch(min_index,
        wait_s)`` must return ``{"index": i, "value": v}``. With
        ``refresh=True`` a background thread keeps the entry current via
        blocking queries (reference cache.go refresh types)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            e = self._entries.get(key)
            if self._stop.is_set():
                # Closed: never fetch again (the close() contract). Any
                # cached value — stale included — is the best available
                # answer; with nothing cached there is no answer.
                if e is not None:
                    e.hits += 1
                    self.metrics["hits"] += 1
                    return e.value
                raise CacheClosedError(key)
            # Refresh-typed entries never TTL-expire (reference cache.go
            # exempts refresh types): the background loop IS their
            # freshness, and its blocking re-arm (5 s) outlasts short
            # TTLs — expiring mid-re-arm would hand every concurrent
            # caller its own synchronous store fetch, exactly the load
            # the cache exists to absorb.
            if e is not None and (now < e.expires_at
                                  or key in self._refreshing):
                e.hits += 1
                self.metrics["hits"] += 1
                return e.value
            self.metrics["misses"] += 1
        out = fetch(0, 0.0)
        with self._lock:
            self.metrics["fetches"] += 1
            e = self._entries.get(key)
            if e is None:
                e = self._entries[key] = CacheEntry(
                    out["value"], out["index"], now + ttl_s)
            e.fetches += 1
            # A close() that landed while the fetch above was in flight
            # must still win: store the data we already have, but never
            # start a refresh loop on a closed cache.
            start_refresh = (refresh and key not in self._refreshing
                             and not self._stop.is_set())
            if start_refresh:
                self._refreshing.add(key)
        # Update in place + notify: parked get_blocking watchers hold a
        # reference to THIS entry's condition — replacing the object
        # would orphan them.
        self._store(e, out, ttl_s, now)
        if start_refresh:
            t = threading.Thread(
                target=self._refresh_loop, args=(key, fetch, ttl_s),
                daemon=True,
            )
            with self._lock:
                self._threads.append(t)
            t.start()
        return out["value"]

    @staticmethod
    def _store(e: CacheEntry, out: dict, ttl_s: float,
               now: Optional[float] = None):
        # ``now`` honors a caller-driven clock (tests, deterministic
        # drivers); the refresh loop passes None for real time.
        with e.changed:
            e.value = out["value"]
            e.index = out["index"]
            e.expires_at = (time.monotonic() if now is None else now) + ttl_s
            e.changed.notify_all()

    def _refresh_loop(self, key: str, fetch, ttl_s: float):
        """Background blocking-query loop (reference cache.go
        fetch/refresh goroutine): each round waits at the server for a
        change past the last index, then replaces the entry."""
        while not self._stop.is_set():
            with self._lock:
                e = self._entries.get(key)
                if e is None:
                    self._refreshing.discard(key)
                    return
                idx = e.index
            try:
                out = fetch(idx, 5.0)
            except Exception:  # noqa: BLE001 — server away; retry with backoff
                if self._stop.wait(0.2):
                    return
                continue
            if self._stop.is_set():
                # Fetch was in flight when close() landed: drop the
                # result rather than storing into (and waking watchers
                # of) a closed cache.
                return
            with self._lock:
                cur = self._entries.get(key)
                self.metrics["fetches"] += 1
                if cur is not None:
                    cur.fetches += 1
            if cur is not None:
                # In-place + notify — wakes every parked watcher of
                # this entry at once (the N-watchers-one-watch shape).
                self._store(cur, out, ttl_s)

    def fetch_count(self, name: str, **req) -> int:
        """Store round-trips made for one typed request — the number
        tests pin to prove N watchers share one watch."""
        with self._lock:
            e = self._entries.get(self._key(name, req))
            return 0 if e is None else e.fetches

    def invalidate(self, key: str):
        with self._lock:
            self._entries.pop(key, None)

    def close(self):
        """Stop the cache: no further fetches will be issued (``get``
        serves only what is already cached, raising
        :class:`CacheClosedError` when nothing is), parked
        ``get_blocking`` watchers wake immediately instead of timing
        out, and refresh threads are joined. The fix for refresh-typed
        entries issuing blocking queries after the cache was dropped."""
        self._stop.set()
        with self._lock:
            entries = list(self._entries.values())
            threads = list(self._threads)
        for e in entries:
            with e.changed:
                e.changed.notify_all()
        for t in threads:
            # Worst case a refresh fetch is mid-flight (bounded at 5 s
            # server-side); don't hang shutdown on it — the loop drops
            # the result on return regardless, and the daemon thread
            # exits at its next _stop check.
            t.join(timeout=0.2)
