"""The HTTP API: the reference's REST surface over the agent.

Mirrors the endpoint registry (reference agent/http_register.go:4-110,
107 endpoints; the subset here covers the subsystems this framework
implements) with the same wire conventions: JSON bodies, base64 KV
values, ``?index=`` + ``?wait=`` blocking queries answered with
``X-Consul-Index`` (reference agent/http.go parseWait/setIndex),
``?near=`` RTT sorting, ``?recurse``/``?cas``/``?acquire``/``?release``
KV semantics, and agent-local service/check registration.

Served by a threading HTTP server so blocking queries long-poll without
starving other requests (goroutine-per-conn equivalent).
"""

from __future__ import annotations

import base64
import functools
import json
import threading
import time as _time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import parse_qs, urlparse

from consul_tpu.agent.agent import Agent
from consul_tpu.server.endpoints import Server
from consul_tpu.server.raft import NotLeader
from consul_tpu.utils import bexpr
from consul_tpu.utils import health as _health


def _dur_to_s(s: str) -> float:
    """Parse Go-style durations ('10s', '1m', '150ms')."""
    s = s.strip()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    if s.endswith("m"):
        return float(s[:-1]) * 60.0
    return float(s)


def parse_blocking(q: dict, default_wait_s: float = 10.0
                   ) -> tuple[int, float]:
    """``?index=`` + ``?wait=`` -> (min_index, wait_s): the reference
    parseWait contract (agent/http.go), shared between this threaded
    surface and the async serving frontend so both answer blocking
    queries with identical parameter semantics."""
    min_index = int(q.get("index", 0))
    wait_s = _dur_to_s(q["wait"]) if "wait" in q else default_wait_s
    return min_index, wait_s


class HTTPApi:
    """Routes parsed requests to the agent + its RPC surface. Transport
    free: the handler below serves it over a socket; tests may call
    :meth:`handle` directly (the httptest idiom)."""

    def __init__(self, agent: Agent, server: Optional[Server] = None,
                 wait_write: Optional[Any] = None,
                 datacenter: Optional[str] = None,
                 acl: Optional[dict] = None):
        self.agent = agent
        # ACL enforcement config (reference agent/acl.go: every
        # endpoint resolves the request token and checks its family):
        # {"enabled": bool, "default_policy": "allow"|"deny",
        #  "master_token": str}. None/disabled = open (ACLs off).
        acl = acl or {}
        self.acl_enabled = bool(acl.get("enabled"))
        self.acl_default_allow = acl.get("default_policy",
                                         "allow") != "deny"
        self.acl_master_token = acl.get("master_token", "")
        # Script-check registration opt-in (reference
        # enable_script_checks, default OFF — an exec check is remote
        # command execution on this host).
        self.enable_script_checks = False
        # This agent's own datacenter: ?dc= naming it resolves to the
        # plain local path (reference parseDC treats the local DC as
        # no-op), keeping the shared cache entries usable.
        self.datacenter = datacenter
        # server: for endpoints needing direct store access (snapshot) —
        # present in server mode, None in pure client mode.
        self.server = server
        # wait_write(index): blocks until the raft entry is applied, so
        # a write's HTTP response reflects the committed state (the
        # synchronous raftApply contract, reference rpc.go:377). Driver
        # clusters pump raft on a background thread and poll here.
        self.wait_write = wait_write or (lambda idx: None)

    # ------------------------------------------------------------------
    def handle(self, method: str, path: str, query: dict[str, list[str]],
               body: bytes, headers: Optional[dict] = None,
               ) -> tuple[int, Any, dict[str, str]]:
        """Returns (status, json-serializable body, extra headers).

        Wraps the dispatch in a ``consul.http.<METHOD>.<path>`` latency
        sample (reference agent/http.go wrap(): MeasureSince with the
        method + first path parts as labels), keyed by the first two
        path segments so /v1/kv/<anything> aggregates under one name."""
        t0 = _time.perf_counter()
        try:
            return self._handle(method, path, query, body, headers)
        finally:
            sink = getattr(self.agent, "sink", None)
            if sink is not None:
                parts = [p for p in path.split("/") if p][:2]
                sink.measure_since(
                    f"consul.http.{method.upper()}.{'.'.join(parts)}", t0)

    def _handle(self, method: str, path: str, query: dict[str, list[str]],
                body: bytes, headers: Optional[dict] = None,
                ) -> tuple[int, Any, dict[str, str]]:
        q = {k: v[-1] for k, v in query.items()}
        min_index, wait_s = parse_blocking(q)
        near = q.get("near", "")
        try:
            if self.acl_enabled:
                denied = self._acl_gate(method, path, q, body, headers)
                if denied is not None:
                    return denied
            status, payload, hdrs = self._route(
                method, path, q, query, body, min_index, wait_s, near,
                headers)
            if "filter" in q and status == 200:
                # ?filter= boolean expressions over results (reference
                # agent/http.go parseFilter -> go-bexpr, wired into the
                # catalog/health/agent listings): one central
                # application point. List results filter rows; map
                # results (the agent's id-keyed services/checks
                # listings) filter values, keeping matching keys.
                if isinstance(payload, list) and \
                        all(isinstance(r, dict) for r in payload):
                    payload = bexpr.apply_filter(q["filter"], payload)
                elif isinstance(payload, dict) and payload and \
                        all(isinstance(v, dict)
                            for v in payload.values()):
                    flt = bexpr.Filter(q["filter"])
                    payload = {k: v for k, v in payload.items()
                               if flt.match(v)}
            return status, payload, hdrs
        except NotLeader as e:
            return 500, {"error": f"no leader: {e}"}, {}
        except (ValueError, KeyError) as e:
            return 400, {"error": str(e)}, {}
        except Exception as e:  # noqa: BLE001 — never drop the connection
            return 500, {"error": f"internal: {e!r}"}, {}

    def _rpc_write(self, method: str, dc: str | None = None, **args):
        """Propose a write and wait for it to apply locally; returns
        ``(raft_index, fsm_result)`` — the synchronous raftApply
        contract (reference rpc.go:377-447: the HTTP layer receives the
        FSM's response, e.g. a CAS verdict, not an inference from a
        racy re-read). Methods that return a non-index value directly
        (e.g. a pre-assigned session id) come back as ``(None, out)``.
        With ``dc`` the write rides the cross-DC forward (rpc.go:315
        forwardDC) and the apply is confirmed against the REMOTE DC's
        ApplyResult — the local wait would poll the wrong raft."""
        if dc:
            args["dc"] = dc
        out = self.agent.rpc(method, **args)
        if isinstance(out, bool):
            # A pre-apply verdict with NO raft entry (e.g. a lock-delay
            # rejection): nothing to wait for. bool is carved out
            # before int — isinstance(False, int) is True.
            return None, out
        if isinstance(out, int) and dc:
            return out, self._confirm_dc_apply(out, dc)
        if isinstance(out, int):
            # wait_write may return the found ApplyResult itself (the
            # client-mode pool does, saving a wire round trip); a None
            # return means "applied, fetch the verdict yourself".
            res = self.wait_write(out)
            if not isinstance(res, dict) or not res.get("found"):
                res = self.agent.rpc("Status.ApplyResult", index=out)
            if not res.get("found"):
                # The entry committed but its verdict is unreachable
                # (applied-before-wait, evicted ring entry): surface an
                # error rather than fabricate a false/true verdict —
                # the reference's lost-future equivalent is an RPC
                # error, never a wrong answer.
                raise RuntimeError(
                    f"apply result for raft index {out} unavailable"
                )
            return out, res["result"]
        return None, out

    def _confirm_dc_apply(self, index: int, dc: str):
        """Poll the REMOTE DC's ApplyResult for a forwarded write's
        verdict — the local raft's indexes are meaningless for it."""
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline:
            res = self.agent.rpc("Status.ApplyResult", index=index, dc=dc)
            if res.get("found"):
                return res["result"]
            _time.sleep(0.01)
        raise RuntimeError(
            f"apply result for raft index {index} in {dc} unavailable")

    # -- ACL enforcement (reference agent/acl.go vetters: each endpoint
    # family resolves the token and checks its resource) ----------------
    @staticmethod
    def _secret_from(q, headers) -> str:
        """The request's ACL secret: X-Consul-Token header
        (case-insensitive — urllib lowercases it on the wire) or
        ?token= — ONE implementation for the gate and token/self."""
        return next((v for k, v in (headers or {}).items()
                     if k.lower() == "x-consul-token"), "") \
            or q.get("token", "")

    def _authorizer(self, q, headers):
        from consul_tpu.server import acl as acl_mod
        secret = self._secret_from(q, headers)
        if self.acl_master_token and secret == self.acl_master_token:
            # The agent-config master token (reference acl_master_token)
            # is management without a store round-trip.
            return acl_mod.management_authorizer()
        res = self.agent.rpc("ACL.Resolve", secret_id=secret)
        if res["management"]:
            return acl_mod.management_authorizer()
        return acl_mod.Authorizer(res["rules"],
                                  default_allow=self.acl_default_allow)

    def _acl_gate(self, method, path, q, body, headers):
        """Family → (resource, name, access) mapping, the one
        enforcement point (the reference checks inside each endpoint;
        the divergence — 403 up front instead of row filtering on
        catalog listings — is documented in COVERAGE.md). Returns a
        403 response tuple or None to proceed."""
        parts = [p for p in path.split("/") if p][1:]
        if not parts:
            return None
        fam = parts[0]
        write = method in ("PUT", "POST", "DELETE")
        # Status + bootstrap stay open (reference: status endpoints are
        # unauthenticated; bootstrap must work before tokens exist).
        if fam == "status" or parts == ["acl", "bootstrap"] or \
                parts == ["acl", "token", "self"]:
            # token/self is authenticated by POSSESSION of the secret
            # (the reference requires no ACL privilege to read your
            # own token).
            return None
        try:
            authz = self._authorizer(q, headers)
        except Exception as e:  # noqa: BLE001 — resolution failure
            return 500, {"error": f"ACL resolution failed: {e!r}"}, {}
        node = self.agent.node
        checks: list[tuple[str, str, str]] = []
        if fam == "kv":
            key = _kv_key(path, parts)
            acc = "write" if write else "read"
            if "recurse" in q or "keys" in q:
                # Subtree operations authorize the whole prefix
                # (KeyWritePrefix semantics) — an exact-key grant must
                # not escalate to everything underneath it.
                if not authz.allowed_prefix("key", key, acc):
                    return 403, {"error": "Permission denied"}, {}
                checks = []
            else:
                checks = [("key", key, acc)]
        elif fam == "txn":
            try:
                for op in json.loads(body or b"[]"):
                    if "KV" in op:
                        kv = op["KV"]
                        verb = kv.get("Verb", "")
                        key = kv.get("Key", "")
                        if verb == "delete-tree":
                            # Subtree semantics, same as ?recurse on
                            # /v1/kv: an exact-key grant must not
                            # escalate to everything underneath.
                            if not authz.allowed_prefix("key", key,
                                                        "write"):
                                return 403, {"error":
                                             "Permission denied"}, {}
                        else:
                            acc = "read" if verb == "get" else "write"
                            checks.append(("key", key, acc))
                    elif "Node" in op:
                        checks.append(("node", op["Node"].get(
                            "Node", {}).get("Node", ""), "write"))
                    elif "Service" in op:
                        # The op keys on a service ID: authorization
                        # covers the op's name AND, when that ID
                        # already exists under a DIFFERENT stored name,
                        # the stored name too — the body's name must
                        # not pick the rule (an ID-keyed delete or
                        # overwrite would otherwise bypass the victim
                        # service's ACL). An ID-only op with a stored
                        # match checks the stored name alone.
                        sv = op["Service"]
                        svc = sv.get("Service", {})
                        name_in_op = svc.get("Service", "")
                        sid = svc.get("ID") or name_in_op
                        stored = None
                        if sid and sv.get("Node"):
                            try:
                                rows = self.agent.rpc(
                                    "Catalog.NodeServices",
                                    node=sv["Node"])["value"]
                                stored = next(
                                    (r["service"] for r in rows
                                     if r["id"] == sid), None)
                            except Exception:  # noqa: BLE001
                                stored = None
                        if name_in_op:
                            checks.append(("service", name_in_op,
                                           "write"))
                        if stored and stored != name_in_op:
                            checks.append(("service", stored, "write"))
                        if not name_in_op and not stored:
                            checks.append(("service", "", "write"))
                    elif "Check" in op:
                        checks.append(("node", op["Check"].get(
                            "Check", {}).get("Node", ""), "write"))
            except (ValueError, AttributeError):
                checks = [("key", "", "write")]
        elif fam == "catalog":
            if parts[1:2] == ["register"]:
                try:
                    checks = [("node", json.loads(body).get("Node", ""),
                               "write")]
                except ValueError:
                    checks = [("node", "", "write")]
            elif parts[1:2] == ["deregister"]:
                try:
                    checks = [("node", json.loads(body).get("Node", ""),
                               "write")]
                except ValueError:
                    checks = [("node", "", "write")]
            elif parts[1:2] == ["service"] and len(parts) > 2:
                checks = [("service", parts[2], "read")]
            elif parts[1:2] == ["node"] and len(parts) > 2:
                checks = [("node", parts[2], "read")]
            else:
                checks = [("node", "", "read")]
        elif fam == "health":
            if parts[1:2] in (["service"], ["checks"]) and len(parts) > 2:
                checks = [("service", parts[2], "read")]
            elif parts[1:2] == ["node"] and len(parts) > 2:
                checks = [("node", parts[2], "read")]
            else:
                checks = [("node", "", "read")]
        elif fam == "session":
            if parts[1:2] == ["create"]:
                try:
                    name = json.loads(body or b"{}").get("Node", node)
                except ValueError:
                    name = node
                checks = [("session", name, "write")]
            elif parts[1:2] in (["destroy"], ["renew"]):
                # By-id writes authorize against the STORED session's
                # node (reference session_endpoint.go SessionDestroy/
                # SessionRenew: fetch the session, then SessionWrite on
                # its Node) — the URL names whatever id the caller
                # wants and must not pick the rule that protects it,
                # and the empty name would match any ``session ""``
                # prefix rule. An unknown id is a deny, not a 404: the
                # route handler only 404s for callers whose token could
                # have touched the session.
                stored = None
                if len(parts) > 2:
                    try:
                        got = self.agent.rpc("Session.Get",
                                             session_id=parts[2])
                        if got["value"]:
                            stored = got["value"][0].get("node", "")
                    except Exception:  # noqa: BLE001 — treat as unknown
                        pass
                if stored is None:
                    # Management still reaches the handler (honest 404
                    # on unknown ids); everyone else is denied.
                    if not authz.management:
                        return 403, {"error": "Permission denied"}, {}
                    checks = []
                else:
                    checks = [("session", stored, "write")]
            else:
                checks = [("session", "", "read")]
        elif fam == "event":
            if parts[1:2] == ["fire"]:
                checks = [("event", parts[2] if len(parts) > 2 else "",
                           "write")]
            else:
                checks = [("event", q.get("name", ""), "read")]
        elif fam == "query":
            name = parts[1] if len(parts) > 1 else ""
            if len(parts) == 3 and parts[2] in ("execute", "explain"):
                checks = [("query", name, "read")]
            else:
                checks = [("query", name,
                           "write" if write else "read")]
        elif fam == "coordinate":
            if parts[1:2] == ["update"]:
                try:
                    checks = [("node", json.loads(body).get("Node", ""),
                               "write")]
                except ValueError:
                    checks = [("node", "", "write")]
            else:
                checks = [("node", "", "read")]
        elif fam == "connect":
            if parts[1:2] == ["ca"]:
                # Roots are public trust material (the reference serves
                # CARoots without a token); configuration is operator;
                # and a ca path must NEVER fall into the intention
                # checks below.
                if parts[2:3] == ["configuration"]:
                    checks = [("operator", "",
                               "write" if write else "read")]
                for resource, name, access in checks:
                    if not authz.allowed(resource, name, access):
                        return 403, {"error": "Permission denied"}, {}
                return None
            # Intentions ride service ACLs (reference: intention writes
            # need service:intentions write on the destination). By-id
            # operations authorize against the STORED intention's
            # destination — the request body names whatever the caller
            # wants and must not pick the rule that protects it; an
            # update changing the destination needs write on BOTH.
            rest = parts[2:]
            if rest[:1] in (["check"], ["match"]):
                checks = [("service",
                           q.get("destination", q.get("name", "")),
                           "read")]
            elif len(rest) == 1 and rest[0] not in ("check", "match"):
                stored = ""
                try:
                    got = self.agent.rpc("Intention.Get",
                                         intention_id=rest[0])
                    if got["value"]:
                        stored = got["value"][0]["destination"]
                except Exception:  # noqa: BLE001 — route will 404/500
                    pass
                acc = "write" if write else "read"
                checks = [("service", stored, acc)]
                if method == "PUT":
                    try:
                        body_dst = json.loads(body or b"{}").get(
                            "DestinationName", "")
                    except ValueError:
                        body_dst = ""
                    if body_dst and body_dst != stored:
                        checks.append(("service", body_dst, "write"))
            elif write:
                try:
                    name = json.loads(body or b"{}").get(
                        "DestinationName", "")
                except ValueError:
                    name = ""
                checks = [("service", name, "write")]
            else:
                checks = [("service", "", "read")]
        elif fam == "config":
            checks = [("operator", "", "write" if write else "read")]
        elif fam == "operator":
            if parts[1:2] == ["keyring"]:
                checks = [("keyring", "",
                           "write" if method != "GET" else "read")]
            else:
                checks = [("operator", "",
                           "write" if write else "read")]
        elif fam == "snapshot":
            checks = [("operator", "", "write" if write else "read")]
        elif fam == "internal":
            checks = [("node", "", "read")]
        elif fam == "agent":
            if parts[1:4] == ["connect", "ca", "leaf"]:
                # Leaf certs need service:write on the named service
                # (agent_endpoint.go AgentConnectCALeafCert ACL).
                checks = [("service",
                           parts[4] if len(parts) > 4 else "", "write")]
            elif parts[1:4] == ["connect", "ca", "roots"]:
                checks = []  # public trust material
            elif parts[1:3] == ["connect", "authorize"]:
                # Reference AgentConnectAuthorize requires service
                # write on the TARGET, not an agent permission.
                try:
                    target = json.loads(body or b"{}").get("Target", "")
                except (ValueError, AttributeError):
                    target = ""
                checks = [("service", target, "write")]
            else:
                checks = [("agent", node,
                           "write" if write else "read")]
        elif fam == "acl":
            checks = [("acl", "", "write" if write else "read")]
        elif fam == "discovery-chain":
            checks = [("service", parts[1] if len(parts) > 1 else "",
                       "read")]
        else:
            # FAIL CLOSED: an endpoint family this gate doesn't know
            # is still subject to the default policy (a new route must
            # be mapped here consciously, never silently open under
            # default-deny).
            checks = [("operator", "", "write" if write else "read")]
        for resource, name, access in checks:
            if not authz.allowed(resource, name, access):
                return 403, {"error": "Permission denied"}, {}
        return None

    def _acl_routes(self, method, parts, q, body, min_index, wait_s, rpc,
                    headers=None):
        """/v1/acl/* (reference acl_endpoint.go HTTP surface — the
        token/policy API subset; legacy create/update/info and
        roles/auth-methods are out)."""
        if parts == ["acl", "bootstrap"] and method == "PUT":
            # The pre-propose check can race another bootstrap (or run
            # against a lagging replica): the FSM's verdict is the
            # truth — a False means the marker already existed at
            # apply time and THIS token was discarded. Answering 200
            # with it would hand out a credential that resolves as
            # anonymous.
            try:
                out, verdict = self._apply_confirmed("ACL.Bootstrap")
            except ValueError as e:
                return 403, {"error": str(e)}, {}
            if verdict is False:
                return 403, {"error": "ACL system already "
                             "bootstrapped"}, {}
            return 200, _token_to_api(out["token"]), {}
        if parts == ["acl", "token"] and method == "PUT":
            out = self.agent.rpc("ACL.TokenSet",
                                 token=_token_from_api(json.loads(body)))
            self.wait_write(out["index"])
            return 200, _token_to_api(out["token"]), {}
        if parts == ["acl", "token", "self"]:
            # Reference /v1/acl/token/self: the token the request
            # authenticated with, resolved from its own secret —
            # read-only, and both the resolve and the fetch ride the
            # same (dc-bound) rpc so ?dc= stays consistent.
            if method != "GET":
                return 405, {"error": "method not allowed"}, {}
            secret = self._secret_from(q, headers)
            res = rpc("ACL.Resolve", secret_id=secret)
            if not res.get("known"):
                return 404, {"error": "token not found"}, {}
            out = rpc("ACL.TokenGet", accessor_id=res["accessor_id"])
            if not out["value"]:
                return 404, {"error": "token not found"}, {}
            return 200, _token_to_api(out["value"][0]), {}
        if len(parts) == 3 and parts[:2] == ["acl", "token"]:
            if method == "GET":
                out = rpc("ACL.TokenGet", accessor_id=parts[2],
                          min_index=min_index, wait_s=wait_s)
                if not out["value"]:
                    return 404, {"error": "token not found"}, {}
                return 200, _token_to_api(out["value"][0]), {
                    "X-Consul-Index": str(out["index"])}
            if method == "PUT":
                t = _token_from_api(json.loads(body))
                t["accessor_id"] = parts[2]
                existing = rpc("ACL.TokenGet", accessor_id=parts[2])
                if not existing["value"]:
                    return 404, {"error": "token not found"}, {}
                # SecretID immutability is enforced by the endpoint
                # itself (ACL.TokenSet pins the stored secret).
                out = self.agent.rpc("ACL.TokenSet", token=t)
                self.wait_write(out["index"])
                return 200, _token_to_api(out["token"]), {}
            if method == "DELETE":
                try:
                    idx = self.agent.rpc("ACL.TokenDelete",
                                         accessor_id=parts[2])
                except KeyError:
                    return 404, {"error": "token not found"}, {}
                self.wait_write(idx)
                return 200, True, {}
        if parts == ["acl", "tokens"]:
            out = rpc("ACL.TokenList", min_index=min_index, wait_s=wait_s)
            return 200, [_token_to_api(t) for t in out["value"]], {
                "X-Consul-Index": str(out["index"])}
        if parts == ["acl", "policy"] and method == "PUT":
            out = self.agent.rpc(
                "ACL.PolicySet", policy=_policy_from_api(json.loads(body)))
            self.wait_write(out["index"])
            return 200, _policy_to_api(out["policy"]), {}
        if len(parts) == 4 and parts[:3] == ["acl", "policy", "name"]:
            out = rpc("ACL.PolicyGet", name=parts[3],
                      min_index=min_index, wait_s=wait_s)
            if not out["value"]:
                return 404, {"error": "policy not found"}, {}
            return 200, _policy_to_api(out["value"][0]), {
                "X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[:2] == ["acl", "policy"]:
            if method == "PUT":
                p = _policy_from_api(json.loads(body))
                p["name"] = parts[2]
                out = self.agent.rpc("ACL.PolicySet", policy=p)
                self.wait_write(out["index"])
                return 200, _policy_to_api(out["policy"]), {}
            if method == "DELETE":
                try:
                    idx = self.agent.rpc("ACL.PolicyDelete", name=parts[2])
                except KeyError:
                    return 404, {"error": "policy not found"}, {}
                self.wait_write(idx)
                return 200, True, {}
            out = rpc("ACL.PolicyGet", name=parts[2],
                      min_index=min_index, wait_s=wait_s)
            if not out["value"]:
                return 404, {"error": "policy not found"}, {}
            return 200, _policy_to_api(out["value"][0]), {
                "X-Consul-Index": str(out["index"])}
        if parts == ["acl", "policies"]:
            out = rpc("ACL.PolicyList", min_index=min_index,
                      wait_s=wait_s)
            return 200, [_policy_to_api(p) for p in out["value"]], {
                "X-Consul-Index": str(out["index"])}
        return 404, {"error": f"no such ACL endpoint"}, {}

    def _intentions(self, method, rest, q, body, min_index, wait_s, rpc,
                    dc):
        """/v1/connect/intentions family (reference agent/
        intentions_endpoint.go: list/create, match, check, CRUD by id).
        A write confirms the FSM verdict — False is a replicated
        duplicate (source, destination) pair, a 409 like the
        reference's DuplicateFound error. Writes thread ?dc= through
        the shared apply-confirm helper like every other write."""
        def confirmed(**args):
            return self._apply_confirmed("Intention.Apply", dc=dc, **args)

        if not rest and method == "GET":
            out = rpc("Intention.List", min_index=min_index, wait_s=wait_s)
            return 200, [_ixn_to_api(x) for x in out["value"]], {
                "X-Consul-Index": str(out["index"])}
        if not rest and method == "POST":
            out, verdict = confirmed(op="create",
                                     intention=_ixn_from_api(
                                         json.loads(body)))
            if verdict is False:
                return 409, {"error": "duplicate intention found"}, {}
            return 200, {"ID": out["id"]}, {}
        if rest == ["match"]:
            if method != "GET":
                return 405, {"error": "method not allowed"}, {}
            by = q.get("by", "")
            out = rpc("Intention.Match", by=by, name=q.get("name", ""),
                      min_index=min_index, wait_s=wait_s)
            return 200, {q.get("name", ""):
                         [_ixn_to_api(x) for x in out["value"]]}, {
                "X-Consul-Index": str(out["index"])}
        if rest == ["check"]:
            if method != "GET":
                return 405, {"error": "method not allowed"}, {}
            if not q.get("source") or not q.get("destination"):
                # A confident wrong answer to a typo'd param is worse
                # than an error (the reference 400s too).
                return 400, {"error":
                             "?source= and ?destination= required"}, {}
            out = rpc("Intention.Check", source=q["source"],
                      destination=q["destination"],
                      default_allow=(not self.acl_enabled
                                     or self.acl_default_allow))
            return 200, {"Allowed": out["allowed"]}, {}
        if len(rest) == 1:
            iid = rest[0]
            if method == "GET":
                out = rpc("Intention.Get", intention_id=iid,
                          min_index=min_index, wait_s=wait_s)
                if not out["value"]:
                    return 404, {"error": "intention not found"}, {
                        "X-Consul-Index": str(out["index"])}
                return 200, _ixn_to_api(out["value"][0]), {
                    "X-Consul-Index": str(out["index"])}
            if method == "PUT":
                x = _ixn_from_api(json.loads(body))
                x["id"] = iid
                try:
                    _, verdict = confirmed(op="update", intention=x)
                except KeyError:
                    return 404, {"error": "intention not found"}, {}
                if verdict is False:
                    return 409, {"error": "duplicate intention found"}, {}
                return 200, True, {}
            if method == "DELETE":
                try:
                    confirmed(op="delete", intention_id=iid)
                except KeyError:
                    return 404, {"error": "intention not found"}, {}
                return 200, True, {}
        return 404, {"error": "no such intentions endpoint"}, {}

    def _query(self, method, parts, q, body, min_index, wait_s, rpc, dc):
        """/v1/query family (reference agent/prepared_query_endpoint.go:
        General=list/create, Specific=get/update/delete/execute/explain).
        Writes confirm their apply verdict — a False from the FSM is a
        replicated name collision, answered 400 like the reference's
        endpoint error, never a silent success."""
        def confirmed_apply(**args):
            return self._apply_confirmed("PreparedQuery.Apply", dc=dc,
                                         **args)

        if parts == ["query"] and method == "POST":
            out, verdict = confirmed_apply(
                op="create", query=_pq_from_api(json.loads(body)))
            if verdict is False:
                return 400, {"error": "prepared query name already in "
                             "use"}, {}
            return 200, {"ID": out["id"]}, {}
        if parts == ["query"] and method == "GET":
            out = rpc("PreparedQuery.List", min_index=min_index,
                      wait_s=wait_s)
            return 200, [_pq_to_api(x) for x in out["value"]], {
                "X-Consul-Index": str(out["index"])}
        if len(parts) < 2:
            return 404, {"error": "missing query id"}, {}
        qid = parts[1]
        if len(parts) == 3 and parts[2] == "execute":
            near = q.get("near", "")
            if near == "_agent":
                # The magic self-locating value (Execute:392) — only
                # this tier knows which agent asked.
                near = self.agent.node
            try:
                out = rpc("PreparedQuery.Execute", query_id_or_name=qid,
                          limit=int(q.get("limit", 0)), near=near)
            except KeyError:
                return 404, {"error": f"prepared query {qid!r} not "
                             "found"}, {}
            return 200, {
                "Service": out["service"], "Nodes": out["nodes"],
                "Datacenter": out["datacenter"],
                "Failovers": out["failovers"],
                "DNS": {"TTL": out["dns"].get("ttl", "")},
            }, {"X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[2] == "explain":
            try:
                out = rpc("PreparedQuery.Explain", query_id_or_name=qid)
            except KeyError:
                return 404, {"error": f"prepared query {qid!r} not "
                             "found"}, {}
            return 200, {"Query": _pq_to_api(out["query"])}, {}
        if method == "GET":
            out = rpc("PreparedQuery.Get", query_id=qid,
                      min_index=min_index, wait_s=wait_s)
            if not out["value"]:
                return 404, {"error": f"prepared query {qid!r} not "
                             "found"}, {"X-Consul-Index": str(out["index"])}
            return 200, [_pq_to_api(x) for x in out["value"]], {
                "X-Consul-Index": str(out["index"])}
        if method == "PUT":
            query = _pq_from_api(json.loads(body))
            query["id"] = qid
            try:
                _, verdict = confirmed_apply(op="update", query=query)
            except KeyError as e:
                # Only an unknown QUERY is a 404; an unknown session
                # (or other validation KeyError) is the caller's bad
                # request and must say so (the endpoint raises both).
                if "session" in str(e):
                    return 400, {"error": str(e)}, {}
                return 404, {"error": f"prepared query {qid!r} not "
                             "found"}, {}
            if verdict is False:
                return 400, {"error": "prepared query name already in "
                             "use"}, {}
            return 200, True, {}
        if method == "DELETE":
            try:
                confirmed_apply(op="delete", query_id=qid)
            except KeyError:
                return 404, {"error": f"prepared query {qid!r} not "
                             "found"}, {}
            return 200, True, {}
        return 405, {"error": "method not allowed"}, {}

    def _apply_confirmed(self, method: str, dc: Optional[str] = None,
                         **args) -> tuple[Any, Any]:
        """Propose through ``method`` and confirm the FSM's verdict —
        the ONE apply-and-confirm helper (PreparedQuery/Intention/ACL
        writes whose Apply returns ``{'id','index'}`` or a bare index).
        Returns (apply output, FSM verdict). dc-aware: a forwarded
        write confirms against the REMOTE raft's ApplyResult."""
        out = self.agent.rpc(method, **(dict(args, dc=dc) if dc else args))
        idx = out["index"] if isinstance(out, dict) else out
        if dc:
            return out, self._confirm_dc_apply(idx, dc)
        res = self.wait_write(idx)
        if not isinstance(res, dict) or not res.get("found"):
            res = self.agent.rpc("Status.ApplyResult", index=idx)
        if not res.get("found"):
            raise RuntimeError(
                f"{method} apply at index {idx} unconfirmed")
        return out, res["result"]

    def _local_service_health(self, service_ids: list[str]) -> str:
        """Worst status over the named local services' checks plus the
        node-level ones (reference agent/agent.go AgentLocalBlockingQuery
        health rollup for /v1/agent/health/service/*)."""
        worst = "passing"
        for c in self.agent.local.checks.values():
            if c.service_id and c.service_id not in service_ids:
                continue
            if _severity(c.status) > _severity(worst):
                worst = c.status
        return worst

    # -- device serving-plane routes (write-attached planes only) -------
    @staticmethod
    def _device_block(srv, min_index: int, wait_s: float) -> int:
        """The ``?index=`` blocking contract against the DEVICE apply
        index: ``index=0`` answers immediately at the current index;
        ``index=N`` parks on the watch plane until a snapshot flip
        advances past N (or the wait expires). The returned index is
        never smaller than the caller's and never less than 1 — the
        reference blockingQuery floor."""
        if min_index > 0:
            return srv.watch.wait_index(min_index, wait_s)
        return max(srv.apply_index, 1)

    def _device_route(self, srv, method, parts, q, body, min_index,
                      wait_s):
        """Serve catalog/health/kv endpoints from the device plane.
        Returns None for paths the device tier doesn't model (they fall
        through to the store tier). Device addressing is by simulation
        index; service labels are i32 (a non-integer service path
        segment falls through). KV carries one i32 word per key (the
        ops/deltas.py narrowing): PUT bodies parse as an integer or
        hash to one word."""
        import zlib

        # -- blocking reads --------------------------------------------
        if method == "GET" and parts == ["catalog", "nodes"]:
            idx = self._device_block(srv, min_index, wait_s)
            res = srv.catalog_nodes(-1)
            rows = [{"Node": node, "ServiceID": -1} for node, _ in res.nodes]
            return 200, rows, {"X-Consul-Index": str(idx)}
        if method == "GET" and parts == ["health", "state", "any"]:
            idx = self._device_block(srv, min_index, wait_s)
            res = srv.health_nodes(-1)
            rows = [{"Node": node, "Status": "passing"}
                    for node, _ in res.nodes]
            return 200, rows, {"X-Consul-Index": str(idx)}
        if method == "GET" and len(parts) == 3 and \
                parts[:2] == ["health", "service"] and \
                parts[2].lstrip("-").isdigit():
            idx = self._device_block(srv, min_index, wait_s)
            res = srv.health_nodes(int(parts[2]))
            rows = [{"Node": node, "Status": "passing"}
                    for node, _ in res.nodes]
            return 200, rows, {"X-Consul-Index": str(idx)}
        if method == "GET" and len(parts) >= 2 and parts[0] == "kv":
            key = "/".join(parts[1:])
            idx = self._device_block(srv, min_index, wait_s)
            row = srv.kv_get(key)
            if row is None:
                return 404, None, {"X-Consul-Index": str(idx)}
            return 200, [row], {"X-Consul-Index": str(idx)}

        # -- writes (coalesced through the WriteBatcher) ---------------
        if method == "PUT" and len(parts) >= 2 and parts[0] == "kv":
            key = "/".join(parts[1:])
            try:
                word = int(body)
            except (TypeError, ValueError):
                word = zlib.crc32(body or b"") & 0x7FFFFFFF
            out = srv.kv_put(key, word)
            return 200, out.applied, {"X-Consul-Index": str(out.index)}
        if method == "DELETE" and len(parts) >= 2 and parts[0] == "kv":
            key = "/".join(parts[1:])
            out = srv.kv_delete(key)
            return 200, out.applied, {"X-Consul-Index": str(out.index)}
        if method == "PUT" and parts == ["catalog", "register"]:
            req = json.loads(body)
            node = req.get("Node")
            if isinstance(node, (int, str)) and str(node).isdigit():
                svc = (req.get("Service") or {}).get("Service", 0)
                out = srv.register(int(node), int(svc))
                return 200, out.applied, \
                    {"X-Consul-Index": str(out.index)}
            return None  # named nodes stay on the store tier
        if method == "PUT" and parts == ["catalog", "deregister"]:
            req = json.loads(body)
            node = req.get("Node")
            if isinstance(node, (int, str)) and str(node).isdigit():
                out = srv.deregister(int(node))
                return 200, out.applied, \
                    {"X-Consul-Index": str(out.index)}
            return None
        return None

    def _route(self, method, path, q, query, body, min_index, wait_s,
               near, headers=None):
        parts = [p for p in path.split("/") if p]
        if not parts or parts[0] != "v1":
            return 404, {"error": "not found"}, {}
        parts = parts[1:]
        # ?dc= routes the request through the WAN (reference http.go
        # parseDC -> QueryOptions.Datacenter; rpc.go:315 forwardDC).
        # Reads and writes alike. Naming the LOCAL DC is a no-op (the
        # cache stays usable); agent-local endpoints (and snapshot/
        # event, which this framework serves agent-side) never forward
        # and say so instead of silently answering locally.
        dc = q.get("dc") or None
        if dc and dc == self.datacenter:
            dc = None
        if dc and (parts[0] in ("agent", "event") or parts == ["snapshot"]):
            return 400, {"error":
                         f"?dc= is not supported on /v1/{parts[0]}: this "
                         "endpoint is agent-local and does not forward — "
                         "address an agent in that datacenter"}, {}
        if dc:
            rpc = functools.partial(self.agent.rpc, dc=dc)
        else:
            rpc = self.agent.rpc
        rpc_write = functools.partial(self._rpc_write, dc=dc)

        # ---- device serving plane (write-attached) --------------------
        # When the agent carries a sim-backed serving plane WITH the
        # device write path, catalog/health/kv reads and writes serve
        # straight from the device tensors: blocking ``?index=`` parks
        # on the watch plane's apply index (snapshot flips wake it) and
        # ``X-Consul-Index`` IS the device apply index. Agents without
        # a write-attached plane fall through to the store tier
        # untouched.
        srv = getattr(self.agent, "serving", None)
        if srv is not None and not dc and \
                getattr(srv, "has_writes", lambda: False)():
            hit = self._device_route(srv, method, parts, q, body,
                                     min_index, wait_s)
            if hit is not None:
                return hit

        # ---- status ---------------------------------------------------
        if parts == ["status", "leader"]:
            return 200, rpc("Status.Leader"), {}
        if parts == ["status", "peers"]:
            return 200, rpc("Status.Peers"), {}

        # ---- catalog --------------------------------------------------
        if parts == ["catalog", "nodes"]:
            out = rpc("Catalog.ListNodes", min_index=min_index,
                      wait_s=wait_s, near=near)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if parts == ["catalog", "datacenters"]:
            # Sorted by WAN coordinate distance (reference
            # /v1/catalog/datacenters, catalog_endpoint.go).
            return 200, rpc("Catalog.ListDatacenters"), {}
        if parts == ["catalog", "services"]:
            out = rpc("Catalog.ListServices", min_index=min_index,
                      wait_s=wait_s)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[:2] == ["catalog", "service"]:
            out = rpc("Catalog.ServiceNodes", service=parts[2],
                      tag=q.get("tag"), min_index=min_index, wait_s=wait_s,
                      near=near)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[:2] == ["catalog", "node"]:
            out = rpc("Catalog.NodeServices", node=parts[2])
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if parts == ["catalog", "register"] and method == "PUT":
            req = json.loads(body)
            idx, _ = rpc_write(
                "Catalog.Register", node=req["Node"],
                address=req.get("Address", ""),
                service=_lower_keys(req.get("Service")),
                check=_check_from_api(req.get("Check")),
            )
            return 200, True, {"X-Consul-Index": str(idx)}
        if parts == ["catalog", "deregister"] and method == "PUT":
            req = json.loads(body)
            rpc_write("Catalog.Deregister", node=req["Node"],
                      service_id=req.get("ServiceID"),
                      check_id=req.get("CheckID"))
            return 200, True, {}

        # ---- config entries (reference agent/config_endpoint.go) ------
        if parts == ["config"] and method == "PUT":
            req = json.loads(body)
            kind, name = req.pop("Kind"), req.pop("Name")
            cas = int(q["cas"]) if "cas" in q else None
            idx, ok = rpc_write(
                "ConfigEntry.Apply", kind=kind, name=name, entry=req,
                cas_index=cas)
            return 200, bool(ok), {"X-Consul-Index": str(idx)}
        if len(parts) == 2 and parts[0] == "config" and method == "GET":
            out = rpc("ConfigEntry.List", kind=parts[1],
                      min_index=min_index, wait_s=wait_s)
            return 200, [_config_to_api(e) for e in out["value"]], {
                "X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[0] == "config" and method == "GET":
            out = rpc("ConfigEntry.Get", kind=parts[1], name=parts[2],
                      min_index=min_index, wait_s=wait_s)
            if out["value"] is None:
                return 404, {"error": "config entry not found"}, {
                    "X-Consul-Index": str(out["index"])}
            return 200, _config_to_api(out["value"]), {
                "X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[0] == "config" and method == "DELETE":
            cas = int(q["cas"]) if "cas" in q else None
            idx, ok = rpc_write(
                "ConfigEntry.Delete", kind=parts[1], name=parts[2],
                cas_index=cas)
            return 200, bool(ok), {"X-Consul-Index": str(idx)}

        # ---- health ---------------------------------------------------
        if len(parts) == 3 and parts[:2] == ["health", "service"]:
            # near= needs a per-request RTT sort the shared cache entry
            # cannot hold, and the cache holds LOCAL-DC data only (the
            # reference keys cache entries by Datacenter) — both fall
            # through to the direct (dc-forwarding) path rather than
            # silently answering with the wrong data.
            if "cached" in q and not near and not dc:
                # Serve through the agent cache's typed entry: any
                # number of ?cached long-pollers share ONE background
                # store watch (reference HTTP ?cached + agent/cache
                # health-services type, cache.go Get MinIndex path).
                out = self.agent.cache.get_blocking(
                    "health-services", min_index=min_index, wait_s=wait_s,
                    service=parts[2], passing_only="passing" in q,
                )
                return 200, out["value"], {
                    "X-Consul-Index": str(out["index"]),
                    "X-Cache": "HIT" if out["hit"] else "MISS",
                }
            out = rpc("Health.ServiceNodes", service=parts[2],
                      passing_only="passing" in q, min_index=min_index,
                      wait_s=wait_s, near=near)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[:2] == ["health", "node"]:
            out = rpc("Health.NodeChecks", node=parts[2],
                      min_index=min_index, wait_s=wait_s)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[:2] == ["health", "checks"]:
            out = rpc("Health.ServiceChecks", service=parts[2],
                      min_index=min_index, wait_s=wait_s)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[:2] == ["health", "state"]:
            out = rpc("Health.ChecksInState", state=parts[2],
                      min_index=min_index, wait_s=wait_s)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}

        # ---- ACL (reference acl_endpoint.go; /v1/acl/*) ---------------
        if parts[0] == "acl":
            return self._acl_routes(method, parts, q, body, min_index,
                                    wait_s, rpc, headers)

        # ---- discovery chain (reference agent/discovery_chain_
        # endpoint.go; /v1/discovery-chain/:service) --------------------
        if len(parts) == 2 and parts[0] == "discovery-chain":
            if method not in ("GET", "POST"):
                return 405, {"error": "method not allowed"}, {}
            from consul_tpu.server.discovery_chain import \
                ChainCompileError
            try:
                out = rpc("DiscoveryChain.Get", service=parts[1],
                          min_index=min_index, wait_s=wait_s)
            except ChainCompileError as e:
                return 400, {"error": str(e)}, {}
            return 200, {"Chain": out["value"]}, {
                "X-Consul-Index": str(out["index"])}

        # ---- connect CA (reference agent/connect_ca_endpoint.go;
        # /v1/connect/ca/* + the agent-side roots/leaf reads) -----------
        if parts[:3] == ["connect", "ca", "roots"]:
            if method != "GET":
                return 405, {"error": "method not allowed"}, {}
            out = rpc("ConnectCA.Roots", min_index=min_index,
                      wait_s=wait_s)
            v = out["value"]
            return 200, {
                "ActiveRootID": v["active_root_id"],
                "TrustDomain": v["trust_domain"],
                "Roots": [_ca_root_to_api(r) for r in v["roots"]],
            }, {"X-Consul-Index": str(out["index"])}
        if parts[:3] == ["connect", "ca", "configuration"]:
            if method == "GET":
                return 200, rpc("ConnectCA.ConfigurationGet"), {}
            if method == "PUT":
                req = json.loads(body or b"{}")
                cfg = {bexpr.snake_case(k): v for k, v in req.items()}
                rpc_write("ConnectCA.ConfigurationSet", config=cfg)
                return 200, True, {}
            return 405, {"error": "method not allowed"}, {}
        if parts[:4] == ["agent", "connect", "ca", "roots"]:
            # Agent-side mirror of the cluster roots (the proxy
            # bootstrap read, agent_endpoint.go AgentConnectCARoots).
            return self._route("GET", "/v1/connect/ca/roots", q, query,
                               b"", min_index, wait_s, near, headers)
        if len(parts) == 5 and parts[:4] == ["agent", "connect", "ca",
                                             "leaf"]:
            leaf = rpc("ConnectCA.Sign", service=parts[4])
            return 200, {
                "SerialNumber": leaf["serial_number"],
                "CertPEM": leaf["cert_pem"],
                "PrivateKeyPEM": leaf["private_key_pem"],
                "Service": leaf["service"],
                "ServiceURI": leaf["spiffe_id"],
                "ValidAfter": leaf["valid_after"],
                "ValidBefore": leaf["valid_before"],
                # Which root signed it — the rotation signal the
                # connect_leaf watch keys on.
                "RootID": leaf["root_id"],
            }, {}

        # ---- intentions (reference agent/intentions_endpoint.go;
        # routes http_register.go /v1/connect/intentions*) --------------
        if parts[0] == "connect" and parts[1:2] == ["intentions"]:
            return self._intentions(method, parts[2:], q, body,
                                    min_index, wait_s, rpc, dc)

        # ---- prepared queries (reference agent/prepared_query_
        # endpoint.go; routes http_register.go /v1/query) ----------------
        if parts[0] == "query":
            return self._query(method, parts, q, body, min_index, wait_s,
                               rpc, dc)

        # ---- kv -------------------------------------------------------
        if parts[0] == "kv":
            # Trailing slashes are part of the key space ("tree/" is a
            # narrower recurse prefix than "tree") — recover them from
            # the raw path, the split dropped them.
            key = _kv_key(path, ["kv", *parts[1:]])
            return self._kv(method, key, q, body, min_index, wait_s,
                            rpc, rpc_write)

        # ---- session --------------------------------------------------
        if parts == ["session", "create"] and method == "PUT":
            req = json.loads(body or b"{}")
            ttl = _dur_to_s(req["TTL"]) if req.get("TTL") else 0.0
            # LockDelay: a Go duration string, or a number — small
            # numbers are seconds, large ones are time.Duration
            # nanoseconds (reference structs.go FixupLockDelay:
            # values below the threshold are interpreted as seconds).
            # null/"" means unspecified -> the 15s default; an
            # explicit 0 turns the window off.
            ld = req.get("LockDelay", "15s")
            if ld is None or ld == "":
                lock_delay_s = 15.0
            elif isinstance(ld, str):
                lock_delay_s = _dur_to_s(ld)
            else:
                lock_delay_s = (float(ld) / 1e9 if float(ld) >= 1000
                                else float(ld))
            _, created = rpc_write(
                "Session.Apply", op="create",
                node=req.get("Node", self.agent.node), ttl_s=ttl,
                behavior=req.get("Behavior", "release"),
                checks=req.get("Checks"),
                lock_delay_s=lock_delay_s,
            )
            # The create carries its raft index; wait for the apply so
            # an immediate renew/acquire from the same client cannot
            # race the commit — and CONFIRM it, like the int path: an
            # unconfirmed apply must not answer 200 with a session id
            # the store may never hold (e.g. proposal lost to a leader
            # change in client mode). With ?dc= the index belongs to
            # the REMOTE raft: confirm there (the dc-aware rpc), never
            # against the local log.
            if dc:
                self._confirm_dc_apply(created["index"], dc)
                return 200, {"ID": created["id"]}, {}
            res = self.wait_write(created["index"])
            if not isinstance(res, dict) or not res.get("found"):
                res = self.agent.rpc("Status.ApplyResult",
                                     index=created["index"])
            if not res.get("found"):
                raise RuntimeError(
                    f"session create at raft index {created['index']} "
                    "unconfirmed")
            return 200, {"ID": created["id"]}, {}
        if len(parts) == 3 and parts[:2] == ["session", "destroy"]:
            rpc_write("Session.Apply", op="destroy",
                      session_id=parts[2])
            return 200, True, {}
        if parts == ["session", "list"]:
            out = rpc("Session.List")
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[:2] == ["session", "renew"] and \
                method == "PUT":
            # Reset the TTL deadline (reference /v1/session/renew/:id,
            # session_endpoint.go Renew). 404 on unknown sessions.
            try:
                s = rpc("Session.Renew", session_id=parts[2])
            except KeyError:
                return 404, {"error": f"unknown session {parts[2]}"}, {}
            return 200, [s], {}
        if len(parts) == 3 and parts[:2] == ["session", "info"]:
            # Reference /v1/session/info/:id (session_endpoint.go Get):
            # a list — empty for an unknown id, never a 404.
            out = rpc("Session.Get", session_id=parts[2],
                      min_index=min_index, wait_s=wait_s)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[:2] == ["session", "node"]:
            out = rpc("Session.NodeSessions", node=parts[2],
                      min_index=min_index, wait_s=wait_s)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}

        # ---- coordinates ----------------------------------------------
        if parts == ["coordinate", "datacenters"]:
            # Per-DC WAN server coordinates (reference
            # /v1/coordinate/datacenters, coordinate_endpoint.go:159).
            return 200, rpc("Coordinate.ListDatacenters"), {}
        if parts == ["coordinate", "nodes"]:
            if "cached" in q and not dc:
                out = self.agent.cache.get_blocking(
                    "coordinate-nodes", min_index=min_index, wait_s=wait_s,
                )
                return 200, out["value"], {
                    "X-Consul-Index": str(out["index"]),
                    "X-Cache": "HIT" if out["hit"] else "MISS",
                }
            out = rpc("Coordinate.ListNodes", min_index=min_index,
                      wait_s=wait_s)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if len(parts) == 3 and parts[:2] == ["coordinate", "node"]:
            out = rpc("Coordinate.Node", node=parts[2])
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if parts == ["coordinate", "update"] and method == "PUT":
            # Reference /v1/coordinate/update (coordinate_endpoint.go
            # CoordinateUpdate): stage one node's coordinate for the
            # server's batched flush. Validation (dimensionality,
            # finite components) happens server-side.
            req = json.loads(body)
            rpc("Coordinate.Update", node=req["Node"],
                coord=req["Coord"], segment=req.get("Segment", ""))
            return 200, True, {}

        # ---- txn ------------------------------------------------------
        if parts == ["txn"] and method == "PUT":
            # All four reference op families (structs/txn.go TxnOp: KV,
            # Node, Service, Check) — catalog verbs compile to the same
            # REGISTER/DEREGISTER commands the FSM already applies
            # atomically inside TXN batches.
            ops = []
            for op in json.loads(body):
                if "KV" in op:
                    kv = op["KV"]
                    ops.append({
                        "type": "kv", "op": kv["Verb"], "key": kv["Key"],
                        "value": base64.b64decode(kv.get("Value", "")),
                        "cas_index": kv.get("Index"),
                        "session": kv.get("Session"),
                    })
                elif "Node" in op:
                    nd = op["Node"]
                    node = nd["Node"]
                    if nd["Verb"] == "set":
                        ops.append({"type": "register",
                                    "node": node["Node"],
                                    "address": node.get("Address", ""),
                                    "node_meta": node.get("Meta")})
                    elif nd["Verb"] == "delete":
                        ops.append({"type": "deregister",
                                    "node": node["Node"]})
                    else:
                        raise ValueError(
                            f"unsupported Node verb {nd['Verb']!r}")
                elif "Service" in op:
                    sv = op["Service"]
                    svc = sv["Service"]
                    if sv["Verb"] == "set":
                        ops.append({"type": "register",
                                    "node": sv["Node"],
                                    "service": _lower_keys(svc)})
                    elif sv["Verb"] == "delete":
                        ops.append({"type": "deregister",
                                    "node": sv["Node"],
                                    "service_id": svc.get(
                                        "ID", svc.get("Service"))})
                    else:
                        raise ValueError(
                            f"unsupported Service verb {sv['Verb']!r}")
                elif "Check" in op:
                    ck = op["Check"]
                    chk = ck["Check"]
                    if ck["Verb"] == "set":
                        ops.append({"type": "register",
                                    "node": chk["Node"],
                                    "check": _check_from_api(chk)})
                    elif ck["Verb"] == "delete":
                        ops.append({"type": "deregister",
                                    "node": chk["Node"],
                                    "check_id": chk.get("CheckID")})
                    else:
                        raise ValueError(
                            f"unsupported Check verb {ck['Verb']!r}")
                else:
                    raise ValueError(
                        "txn op needs one of KV/Node/Service/Check")
            _, result = rpc_write("Txn.Apply", ops=ops)
            if isinstance(result, dict) and result.get("ok"):
                return 200, {"Results": [
                    # get-op rows carry bytes: render as API KV rows.
                    {"KV": _kv_to_api(r)} if isinstance(r, dict)
                    and "value" in r else r
                    for r in result.get("results", [])
                ]}, {}
            # Rolled-back transaction: 409 with the failing op, like the
            # reference txn endpoint (agent/txn_endpoint.go).
            err = (result or {}).get("failed") or (result or {}).get("error")
            return 409, {"Results": [], "Errors": [{"What": str(err)}]}, {}

        # ---- operator snapshot (reference snapshot/, agent/consul/
        # rpc.go:196 RPCSnapshot byte; CLI `consul snapshot`) -----------
        if parts == ["snapshot"]:
            if self.server is None:
                return 500, {"error": "snapshot requires server mode"}, {}
            if method == "GET":
                return 200, _jsonify(self.server.store.snapshot()), {}
            if method == "PUT":
                snap = _unjsonify(json.loads(body))
                # Restore is leader-driven in the reference (streams the
                # archive through raft.Restore); raft-lite installs it
                # directly into the store.
                self.server.store.restore(snap)
                return 200, True, {}

        # ---- agent ----------------------------------------------------
        # ---- user events (reference agent/event_endpoint.go) ----------
        if len(parts) == 3 and parts[:2] == ["event", "fire"] and \
                method == "PUT":
            ev = self.agent.fire_event(parts[2], body or b"")
            return 200, {"ID": ev["ID"], "Name": ev["Name"],
                         "LTime": ev["LTime"]}, {}
        if parts == ["event", "list"]:
            idx, evs = self.agent.event_list(
                q.get("name", ""), min_index, wait_s if min_index else 0.0)
            out = [{"ID": e["ID"], "Name": e["Name"], "LTime": e["LTime"],
                    "Payload": base64.b64encode(e["Payload"]).decode()
                    if e["Payload"] else None} for e in evs]
            return 200, out, {"X-Consul-Index": str(idx)}

        if len(parts) == 3 and parts[:2] == ["agent", "join"] and \
                method == "PUT":
            # Post-boot join (reference /v1/agent/join/:address,
            # http_register.go): route a running client agent onto a
            # server's RPC address.
            return 200, self.agent.join(parts[2]), {}
        if len(parts) == 3 and parts[:2] == ["agent", "force-leave"] and \
                method == "PUT":
            # ForceLeave (reference agent/agent.go ForceLeave ->
            # serf.RemoveFailedNode): route through the driver hook
            # into the gossip plane; without one it is a no-op.
            return 200, self.agent.force_leave(parts[2]), {}
        if parts == ["agent", "monitor"]:
            # Log streaming (reference /v1/agent/monitor,
            # http_register.go:38): long-poll the monitor tap with
            # ?index= + ?loglevel= (the reference streams; the
            # blocking-query shape fits this framework's HTTP model).
            if self.agent.monitor is None:
                return 500, {"error": "no monitor handler configured"}, {}
            seq, lines = self.agent.monitor.tail(
                min_index, wait_s if min_index else 0.0,
                q.get("loglevel", ""))
            # The raw sequence IS the cursor; flooring it would skip
            # the first line for clients that connect before any logs.
            return 200, lines, {"X-Consul-Index": str(seq)}
        if parts == ["agent", "self"]:
            return 200, {"Config": {"NodeName": self.agent.node},
                         "Member": {"Name": self.agent.node,
                                    "Addr": self.agent.address}}, {}
        if parts == ["agent", "members"]:
            # Reference /v1/agent/members (agent_endpoint.go
            # AgentMembers: the serf membership view). Gossip
            # membership is reconciled into the catalog by the leader
            # (leader.py reconcile), so the member view here is the
            # catalog + serfHealth rollup; ?wan= on a federated server
            # lists the WAN pool (server_serf.go).
            if q.get("wan") in ("1", "true"):
                srv = self.server
                if srv is None or srv.wan_registry is None:
                    return 400, {"error":
                                 "?wan= requires a federated server"}, {}
                return 200, [
                    {"Name": wid, "Addr": s.id, "Status": "alive",
                     "Tags": {"dc": s.dc, "role": "consul"}}
                    for wid, s in sorted(srv.wan_registry.items())
                ], {}
            nodes = rpc("Catalog.ListNodes")["value"]
            checks = rpc("Health.ChecksInState", state="any")["value"]
            by_node = {c["node"]: c["status"] for c in checks
                       if c["check_id"] == "serfHealth"}
            return 200, [
                {"Name": n["node"], "Addr": n.get("address", ""),
                 "Status": {"passing": "alive",
                            "critical": "failed"}.get(
                                by_node.get(n["node"], ""), "alive"),
                 "Tags": {}}
                for n in nodes
            ], {}
        if parts == ["agent", "connect", "authorize"] and method == "POST":
            # Reference /v1/agent/connect/authorize (agent_endpoint.go
            # AgentConnectAuthorize): would a connection from the
            # client's identity to Target be allowed by intentions?
            # The source rides a SPIFFE cert URI (.../svc/<name>) or,
            # for non-mTLS callers here, a plain ClientServiceName.
            req = json.loads(body or b"{}")
            if not isinstance(req, dict):
                return 400, {"error": "body must be a JSON object"}, {}
            target = req.get("Target", "")
            if not target:
                return 400, {"error": "Target must be set"}, {}
            source = req.get("ClientServiceName", "")
            uri = req.get("ClientCertURI", "")
            if not source and uri:
                _, sep, svc = uri.rpartition("/svc/")
                if not sep or not svc:
                    # Not a service identity (e.g. an agent cert) —
                    # reject, never authorize it by default
                    # (AgentConnectAuthorize errors on non-service
                    # URIs).
                    return 400, {"error": "ClientCertURI is not a "
                                 "service identity"}, {}
                source = svc
            if not source:
                return 400, {"error": "ClientCertURI or "
                             "ClientServiceName must identify the "
                             "source service"}, {}
            out = rpc("Intention.Check", source=source,
                      destination=target,
                      default_allow=(not self.acl_enabled
                                     or self.acl_default_allow))
            reason = ("Allowed by intention" if out["matched"]
                      else "Default behavior") if out["allowed"] else \
                ("Denied by intention" if out["matched"]
                 else "Default behavior (deny)")
            return 200, {"Authorized": out["allowed"],
                         "Reason": reason}, {}
        if parts == ["agent", "leave"] and method == "PUT":
            # Graceful leave (reference /v1/agent/leave → agent.Leave):
            # deregister, stop duties, signal the runtime to exit.
            return 200, self.agent.leave(), {}
        if parts == ["agent", "host"]:
            # Reference /v1/agent/host (agent_endpoint.go AgentHost via
            # gopsutil): host diagnostics for `consul debug`.
            import os as _os
            import platform as _pf
            u = _pf.uname()
            mem = {}
            try:
                with open("/proc/meminfo") as f:
                    for line in f:
                        k, _, v = line.partition(":")
                        if k in ("MemTotal", "MemAvailable"):
                            mem[k] = int(v.split()[0]) * 1024
            except (OSError, ValueError):
                pass
            return 200, {
                "Host": {"hostname": u.node, "os": u.system.lower(),
                         "kernelVersion": u.release, "arch": u.machine},
                "CPU": {"count": _os.cpu_count()},
                "Memory": mem,
            }, {}
        if len(parts) == 5 and parts[:4] == ["agent", "health", "service",
                                             "id"]:
            # Reference /v1/agent/health/service/id/:id
            # (agent_endpoint.go AgentHealthServiceByID): the LOCAL
            # rollup — worst status over the service's local checks
            # plus node-level ones; the HTTP status encodes it
            # (200/429/503, health.go).
            s = self.agent.local.services.get(parts[4])
            if s is None:
                return 404, {"error": f"unknown service id {parts[4]}"}, {}
            status = self._local_service_health([s.id])
            return {"passing": 200, "warning": 429,
                    "critical": 503}[status], {
                "AggregatedStatus": status,
                "Service": {"ID": s.id, "Service": s.service}}, {}
        if len(parts) == 5 and parts[:4] == ["agent", "health", "service",
                                             "name"]:
            ids = [s.id for s in self.agent.local.services.values()
                   if s.service == parts[4]]
            if not ids:
                return 404, {"error": f"unknown service {parts[4]}"}, {}
            status = self._local_service_health(ids)
            return {"passing": 200, "warning": 429,
                    "critical": 503}[status], [{
                "AggregatedStatus": status,
                "Service": {"ID": sid, "Service": parts[4]}}
                for sid in ids], {}
        if parts == ["agent", "services"]:
            # The agent's LOCAL registrations (reference
            # /v1/agent/services, agent_endpoint.go AgentServices —
            # local state, not a catalog query).
            return 200, {
                s.id: {"ID": s.id, "Service": s.service, "Port": s.port,
                       "Tags": list(s.tags), "Meta": dict(s.meta)}
                for s in self.agent.local.services.values()
            }, {}
        if parts == ["agent", "checks"]:
            # Reference /v1/agent/checks (agent_endpoint.go AgentChecks).
            return 200, {
                c.check_id: {"CheckID": c.check_id, "Status": c.status,
                             "ServiceID": c.service_id,
                             "Output": c.output}
                for c in self.agent.local.checks.values()
            }, {}
        if parts == ["agent", "metrics"]:
            # go-metrics DisplayMetrics shape (reference
            # http_register.go:39 -> lib/telemetry.go InmemSink), with
            # the agent's own duty counters folded in as gauges.
            # ?format=prometheus renders the text exposition format
            # (agent_endpoint.go:90 promhttp).
            for k, v in self.agent.metrics.items():
                self.agent.sink.set_gauge(f"consul.agent.{k}", v)
            serving = getattr(self.agent, "serving", None)
            if serving is not None:
                # Read-plane stats as consul.serving.* gauges (queries,
                # batches, padded_slots, cache_hits, padding waste and
                # batch-latency percentiles) so the device serving path
                # shows up in the same Prometheus scrape as the rest of
                # the agent.
                for k, v in serving.stats().items():
                    self.agent.sink.set_gauge(f"consul.serving.{k}", v)
            snap = self.agent.sink.snapshot()
            if q.get("format") == "prometheus":
                from consul_tpu.utils import telemetry as _tm
                return 200, _tm.to_prometheus(snap), {
                    "Content-Type": "text/plain; version=0.0.4"}
            return 200, snap, {}
        if parts == ["agent", "service", "register"] and method == "PUT":
            req = json.loads(body)
            ttl = None
            if req.get("Check", {}).get("TTL"):
                ttl = _dur_to_s(req["Check"]["TTL"])
            sid = req.get("ID", req["Name"])
            dcsa = req.get("Check", {}).get(
                "DeregisterCriticalServiceAfter")
            if dcsa and ttl is None:
                # Validate BEFORE mutating: accept-and-drop would be a
                # silent lie, and a 400 must not leave the service
                # half-registered (the reference rejects checks with
                # no type).
                return 400, {"error":
                             "DeregisterCriticalServiceAfter "
                             "requires a check (set Check.TTL)"}, {}
            self.agent.add_service(
                sid, req["Name"],
                req.get("Port", 0), req.get("Tags"), check_ttl_s=ttl,
            )
            if dcsa:
                # The service's TTL check carries the reap timeout
                # (reference check_type.go:55).
                self.agent.set_reap_after(f"service:{sid}",
                                          _dur_to_s(dcsa))
            self.agent.tick(_now())
            return 200, True, {}
        if len(parts) == 4 and parts[:3] == ["agent", "service", "deregister"]:
            self.agent.remove_service(parts[3])
            self.agent.tick(_now())
            return 200, True, {}
        if len(parts) == 3 and parts[0] == "agent" and \
                parts[1] == "service" and method == "GET":
            # Reference /v1/agent/service/:id (agent_endpoint.go
            # AgentService): one LOCAL registration. (The reference
            # hash-blocks on this; a plain read fits the model here.)
            s = self.agent.local.services.get(parts[2])
            if s is None:
                return 404, {"error": f"unknown service id {parts[2]}"}, {}
            return 200, {"ID": s.id, "Service": s.service, "Port": s.port,
                         "Tags": list(s.tags), "Meta": dict(s.meta)}, {}
        if parts == ["agent", "check", "register"] and method == "PUT":
            # Reference /v1/agent/check/register (agent_endpoint.go
            # AgentRegisterCheck): standalone check definitions —
            # TTL / HTTP / TCP / alias runners (agent/checks/check.go).
            req = json.loads(body)
            cid = req.get("ID") or req.get("CheckID") or req["Name"]
            sid = req.get("ServiceID", "")
            if sid and sid not in self.agent.local.services:
                return 400, {"error": f"unknown service id {sid!r}"}, {}
            interval = _dur_to_s(req["Interval"]) if req.get("Interval") \
                else 10.0
            now = _now()
            if req.get("TTL"):
                self.agent.checks.add_ttl(cid, _dur_to_s(req["TTL"]), sid,
                                          now=now)
            elif req.get("HTTP"):
                self.agent.checks.add_http(cid, req["HTTP"], interval,
                                           service_id=sid, now=now)
            elif req.get("TCP"):
                host, port = _parse_tcp_target(req["TCP"])
                self.agent.checks.add_tcp(cid, host, port, interval,
                                          service_id=sid, now=now)
            elif req.get("AliasNode"):
                self.agent.checks.add_alias(
                    cid, self.agent.rpc, req["AliasNode"],
                    req.get("AliasService", ""), interval_s=interval,
                    service_id=sid, now=now)
            elif req.get("Args"):
                # Script check (the reference's exec check; exit 0/1/
                # other -> passing/warning/critical). DISABLED unless
                # the agent opted in — registering one is arbitrary
                # command execution on the agent host (reference
                # enable_script_checks, off by default).
                if not self.enable_script_checks:
                    return 403, {"error":
                                 "script checks are disabled; set "
                                 "enable_script_checks in the agent "
                                 "config"}, {}
                kw = {"service_id": sid, "now": now}
                if req.get("Timeout"):
                    kw["timeout_s"] = _dur_to_s(req["Timeout"])
                self.agent.checks.add_script(
                    cid, list(req["Args"]), interval, **kw)
            else:
                return 400, {"error": "check needs one of "
                             "TTL/HTTP/TCP/AliasNode/Args"}, {}
            if req.get("DeregisterCriticalServiceAfter"):
                self.agent.set_reap_after(
                    cid, _dur_to_s(req["DeregisterCriticalServiceAfter"]))
            self.agent.tick(_now())
            return 200, True, {}
        if len(parts) == 4 and parts[:3] == ["agent", "check",
                                             "deregister"] and method == "PUT":
            if parts[3] not in self.agent.checks.checks:
                return 404, {"error": f"unknown check {parts[3]}"}, {}
            self.agent.checks.remove(parts[3])
            self.agent.tick(_now())
            return 200, True, {}
        if len(parts) == 4 and parts[:3] == ["agent", "check", "update"] \
                and method == "PUT":
            # Reference /v1/agent/check/update/:id (AgentCheckUpdate):
            # set a TTL check's status + output in one call.
            req = json.loads(body or b"{}")
            chk = self.agent.checks.checks.get(parts[3])
            if chk is None:
                return 404, {"error": f"unknown check {parts[3]}"}, {}
            verb = {"passing": "pass_", "warning": "warn",
                    "critical": "fail"}.get(req.get("Status", ""))
            if verb is None or not hasattr(chk, verb):
                return 400, {"error":
                             "Status must be passing/warning/critical "
                             "on a TTL check"}, {}
            getattr(chk, verb)(_now(), req.get("Output", ""))
            self.agent.tick(_now())
            return 200, True, {}
        if parts == ["agent", "reload"] and method == "PUT":
            # Reference /v1/agent/reload (http_register.go): re-read
            # config sources, apply the safe subset, report what moved.
            applied = self.agent.reload()
            if applied is None:
                return 500, {"error": "reload not wired on this agent"}, {}
            return 200, {"Applied": applied}, {}

        if parts == ["agent", "maintenance"] and method == "PUT":
            # Reference agent/agent_endpoint.go AgentNodeMaintenance.
            if q.get("enable", "") in ("true", "1"):
                self.agent.enable_node_maintenance(q.get("reason", ""))
            else:
                self.agent.disable_node_maintenance()
            return 200, True, {}

        if len(parts) == 4 and parts[:3] == ["agent", "service",
                                             "maintenance"] \
                and method == "PUT":
            enable = q.get("enable", "") in ("true", "1")
            ok = (self.agent.enable_service_maintenance(
                      parts[3], q.get("reason", ""))
                  if enable else
                  self.agent.disable_service_maintenance(parts[3]))
            if not ok:
                return 404, {"error": f"unknown service {parts[3]}"}, {}
            return 200, True, {}

        # ---- operator raft / autopilot (reference operator_raft_
        # endpoint.go, operator_autopilot_endpoint.go; routes
        # http_register.go /v1/operator/*) ------------------------------
        if parts == ["operator", "raft", "configuration"]:
            return 200, rpc("Operator.RaftGetConfiguration"), {}
        if parts == ["operator", "raft", "peer"] and method == "DELETE":
            if "id" not in q:
                return 400, {"error": "?id= required"}, {}
            _, _ = rpc_write("Operator.RaftRemovePeer", id=q["id"])
            return 200, True, {}
        if parts == ["operator", "autopilot", "configuration"]:
            if method == "GET":
                return 200, rpc("Operator.AutopilotGetConfiguration"), {}
            if method == "PUT":
                cas = int(q["cas"]) if "cas" in q else None
                _, ok = rpc_write(
                    "Operator.AutopilotSetConfiguration",
                    config=json.loads(body or b"{}"), cas_index=cas)
                # ?cas returns the verdict like the reference (a bare
                # set returns true).
                return 200, bool(ok), {}
        if parts == ["operator", "autopilot", "health"]:
            # Reference /v1/operator/autopilot/health
            # (operator_autopilot_endpoint.go ServerHealth →
            # OperatorHealthReply).
            h = rpc("Operator.ServerHealth")
            return 200, {
                "Healthy": h["healthy"],
                "FailureTolerance": h["failure_tolerance"],
                "Servers": [{
                    "ID": s["id"], "Name": s["name"],
                    "Healthy": s["healthy"], "Voter": s["voter"],
                    "Leader": s["leader"],
                    "LastContact": s["last_contact_ticks"],
                    "TrailingLogs": s["trailing_logs"],
                    "Reason": s["reason"],
                } for s in h["servers"]],
            }, {}

        # ---- internal (reference internal_endpoint.go NodeInfo/
        # NodeDump via /v1/internal/ui/*) --------------------------------
        if parts == ["internal", "ui", "nodes"]:
            out = rpc("Internal.NodeDump", min_index=min_index,
                      wait_s=wait_s)
            return 200, out["value"], {"X-Consul-Index": str(out["index"])}
        if len(parts) == 4 and parts[:3] == ["internal", "ui", "node"]:
            out = rpc("Internal.NodeInfo", node=parts[3],
                      min_index=min_index, wait_s=wait_s)
            rows = out["value"]
            if not rows:
                return 404, {"error": f"unknown node {parts[3]}"}, {}
            return 200, rows[0], {"X-Consul-Index": str(out["index"])}
        if parts == ["internal", "ui", "services"]:
            # Reference /v1/internal/ui/services (ui_endpoint.go
            # UIServices): per-service rollup — instance count and
            # worst check status — aggregated from the node dump.
            out = rpc("Internal.NodeDump", min_index=min_index,
                      wait_s=wait_s)
            summary: dict[str, dict] = {}
            for nd in out["value"]:
                svc_checks = {}
                node_worst = "passing"
                for c in nd.get("checks", []):
                    # Catalog check statuses are unvalidated on
                    # registration — bucket anything unknown as
                    # critical rather than 400ing the whole rollup.
                    st = c.get("status", "critical")
                    if st not in ("passing", "warning"):
                        st = "critical"
                    sid = c.get("service_id") or ""
                    if sid:
                        prev = svc_checks.get(sid, "passing")
                        if _severity(st) > _severity(prev):
                            svc_checks[sid] = st
                        else:
                            svc_checks.setdefault(sid, st)
                    elif _severity(st) > _severity(node_worst):
                        node_worst = st
                for s in nd.get("services", []):
                    name = s.get("service", "")
                    row = summary.setdefault(name, {
                        "Name": name, "Nodes": [], "InstanceCount": 0,
                        "ChecksPassing": 0, "ChecksWarning": 0,
                        "ChecksCritical": 0, "Tags": set(),
                    })
                    if nd["node"] not in row["Nodes"]:
                        row["Nodes"].append(nd["node"])
                    row["InstanceCount"] += 1
                    row["Tags"].update(s.get("tags") or [])
                    worst = svc_checks.get(s.get("id", ""), "passing")
                    if _severity(node_worst) > _severity(worst):
                        worst = node_worst  # node-level checks gate it
                    row[{"passing": "ChecksPassing",
                         "warning": "ChecksWarning",
                         "critical": "ChecksCritical"}[worst]] += 1
            rows = [dict(r, Tags=sorted(r["Tags"]))
                    for _, r in sorted(summary.items())]
            return 200, rows, {"X-Consul-Index": str(out["index"])}

        if parts == ["operator", "keyring"]:
            # Reference operator/keyring (agent/operator_endpoint.go):
            # GET=list, POST=install, PUT=use, DELETE=remove, each a
            # cluster-wide serf query through the KeyManager.
            km = getattr(self.agent, "key_manager", None)
            if km is None:
                return 500, {"error": "keyring not enabled "
                             "(gossip encryption is off)"}, {}
            if method == "GET":
                r = km.list_keys()
                return 200, [{
                    "Keys": r.keys, "NumNodes": r.num_nodes,
                    "NumResp": r.num_resp, "NumErr": r.num_err,
                    "Messages": r.messages,
                }], {}
            req = json.loads(body or b"{}")
            key_b = base64.b64decode(req.get("Key", ""))
            op = {"POST": km.install_key, "PUT": km.use_key,
                  "DELETE": km.remove_key}.get(method)
            if op is None:
                return 405, {"error": "method not allowed"}, {}
            r = op(key_b)
            if not r.ok:
                return 500, {"error": "; ".join(
                    f"{n}: {m}" for n, m in r.messages.items())}, {}
            return 200, True, {}

        if len(parts) == 4 and parts[0] == "agent" and parts[1] == "check" \
                and parts[2] in ("pass", "warn", "fail"):
            chk = self.agent.checks.checks.get(parts[3])
            if chk is None:
                return 404, {"error": f"unknown check {parts[3]}"}, {}
            getattr(chk, {"pass": "pass_", "warn": "warn",
                          "fail": "fail"}[parts[2]])(
                _now(), q.get("note", "")
            )
            self.agent.tick(_now())
            return 200, True, {}

        return 404, {"error": f"no such endpoint {path}"}, {}

    def _kv(self, method, key, q, body, min_index, wait_s,
            rpc, rpc_write):
        if method == "GET":
            if "keys" in q:
                out = rpc("KVS.List", prefix=key, min_index=min_index,
                          wait_s=wait_s)
                keys = [r["key"] for r in out["value"]]
                sep = q.get("separator", "")
                if sep:
                    # Directory-style listing (reference state/kvs.go
                    # kvsListKeys): each key truncates at the first
                    # separator past the prefix; "subdirectories"
                    # collapse to one entry ending in the separator.
                    seen: dict = {}
                    for k in keys:
                        rest = k[len(key):]
                        i = rest.find(sep)
                        if i >= 0:
                            k = key + rest[:i + len(sep)]
                        seen.setdefault(k, None)
                    keys = list(seen)
                return 200, keys, {
                    "X-Consul-Index": str(out["index"])}
            if "recurse" in q:
                out = rpc("KVS.List", prefix=key, min_index=min_index,
                          wait_s=wait_s)
                rows = out["value"]
            else:
                out = rpc("KVS.Get", key=key, min_index=min_index,
                          wait_s=wait_s)
                if out["value"] is None:
                    return 404, None, {"X-Consul-Index": str(out["index"])}
                rows = [out["value"] | {"key": key}]
            return 200, [_kv_to_api(r) for r in rows], {
                "X-Consul-Index": str(out["index"])}
        if method == "PUT":
            op, cas, session = "set", None, None
            if "cas" in q:
                op, cas = "cas", int(q["cas"])
            if "acquire" in q:
                op, session = "lock", q["acquire"]
            if "release" in q:
                op, session = "unlock", q["release"]
            _, ok = rpc_write("KVS.Apply", op=op, key=key, value=body,
                              flags=int(q.get("flags", 0)), cas_index=cas,
                              session=session)
            # ok is the FSM's own verdict for this exact log entry
            # (CAS/lock success), not an inference from a re-read that a
            # concurrent writer could have changed.
            return 200, bool(ok), {}
        if method == "DELETE":
            cas = int(q["cas"]) if "cas" in q else None
            _, ok = rpc_write(
                "KVS.Apply",
                op="delete-cas" if cas is not None else (
                    "delete-tree" if "recurse" in q else "delete"),
                key=key, cas_index=cas)
            return 200, bool(ok), {}
        return 405, {"error": "method not allowed"}, {}


def _kv_to_api(row: dict) -> dict:
    val = row.get("value", b"")
    return {
        "Key": row["key"],
        "Value": base64.b64encode(val).decode() if val else None,
        "Flags": row.get("flags", 0),
        "Session": row.get("session"),
        "CreateIndex": row.get("create_index", row.get("modify_index", 0)),
        "ModifyIndex": row.get("modify_index", 0),
    }


def _config_to_api(meta: dict) -> dict:
    """Store meta row -> API shape (reference config entries marshal
    Kind/Name at the top level beside the entry's own fields)."""
    return {
        "Kind": meta["kind"],
        "Name": meta["name"],
        **meta["entry"],
        "CreateIndex": meta["create_index"],
        "ModifyIndex": meta["modify_index"],
    }


def _lower_keys(d: Optional[dict]) -> Optional[dict]:
    if d is None:
        return None
    return {{"ID": "id", "Service": "service", "Port": "port",
             "Tags": "tags", "Meta": "meta"}.get(k, k.lower()): v
            for k, v in d.items()}


def _ca_root_to_api(r: dict) -> dict:
    return {"ID": r.get("id", ""), "Name": r.get("name", ""),
            "RootCert": r.get("root_cert", ""),
            "Active": bool(r.get("active")),
            "TrustDomain": r.get("trust_domain", ""),
            "NotAfter": r.get("not_after", "")}


def _ixn_from_api(d: dict) -> dict:
    out = {}
    for api_k, k in (("ID", "id"), ("SourceName", "source"),
                     ("DestinationName", "destination"),
                     ("Action", "action"),
                     ("Description", "description"), ("Meta", "meta")):
        if api_k in d:
            out[k] = d[api_k]
    return out


def _ixn_to_api(x: dict) -> dict:
    return {"ID": x.get("id", ""), "SourceName": x.get("source", ""),
            "DestinationName": x.get("destination", ""),
            "Action": x.get("action", ""),
            "Precedence": x.get("precedence", 0),
            "Description": x.get("description", ""),
            "Meta": x.get("meta", {})}


def _kv_key(path: str, parts: list) -> str:
    """KV key from the request path, preserving a meaningful trailing
    slash that the empty-segment-dropping split loses."""
    key = "/".join(parts[1:])
    if key and path.endswith("/"):
        key += "/"
    return key


def _token_from_api(d: dict) -> dict:
    out = {}
    for api_k, k in (("AccessorID", "accessor_id"),
                     ("SecretID", "secret_id"),
                     ("Description", "description")):
        if api_k in d:
            out[k] = d[api_k]
    out["policies"] = [p["Name"] if isinstance(p, dict) else p
                       for p in d.get("Policies") or []]
    return out


def _token_to_api(t: dict) -> dict:
    out = {"AccessorID": t.get("accessor_id", ""),
           "Description": t.get("description", ""),
           "Policies": [{"Name": p} for p in t.get("policies", [])]}
    if "secret_id" in t:
        out["SecretID"] = t["secret_id"]
    return out


def _policy_from_api(d: dict) -> dict:
    out = {}
    for api_k, k in (("ID", "id"), ("Name", "name"),
                     ("Description", "description"),
                     ("Rules", "rules")):
        if api_k in d:
            out[k] = d[api_k]
    return out


def _policy_to_api(p: dict) -> dict:
    return {"ID": p.get("id", ""), "Name": p.get("name", ""),
            "Description": p.get("description", ""),
            "Rules": p.get("rules", "")}


def _parse_tcp_target(addr: str) -> tuple[str, int]:
    """``host:port`` with bracketed-IPv6 support (``[::1]:8080`` →
    ``::1``); a missing or non-numeric port is a named 400, not a
    check that can never pass."""
    host, _, port = addr.rpartition(":")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    if not host or not port.isdigit():
        raise ValueError(
            f"TCP check target {addr!r} must be host:port "
            "(IPv6 as [addr]:port)")
    return host, int(port)


def _pq_from_api(d: dict) -> dict:
    """PreparedQueryDefinition (reference api/prepared_query.go) →
    the endpoint's snake_case definition. Unknown fields fall through
    to normalize()'s validation."""
    out: dict = {}
    for api_k, k in (("ID", "id"), ("Name", "name"),
                     ("Session", "session"), ("Token", "token")):
        if api_k in d:
            out[k] = d[api_k]
    t = d.get("Template") or {}
    if t:
        out["template"] = {"type": t.get("Type", ""),
                           "regexp": t.get("Regexp", ""),
                           "remove_empty_tags":
                               bool(t.get("RemoveEmptyTags", False))}
    s = d.get("Service") or {}
    fo = s.get("Failover") or {}
    out["service"] = {
        "service": s.get("Service", ""),
        "failover": {"nearest_n": int(fo.get("NearestN", 0)),
                     "datacenters": fo.get("Datacenters") or []},
        "only_passing": bool(s.get("OnlyPassing", False)),
        "ignore_check_ids": s.get("IgnoreCheckIDs") or [],
        "near": s.get("Near", ""),
        "tags": s.get("Tags") or [],
        "node_meta": s.get("NodeMeta") or {},
        "service_meta": s.get("ServiceMeta") or {},
    }
    dns = d.get("DNS") or {}
    if dns:
        out["dns"] = {"ttl": dns.get("TTL", "")}
    return out


def _pq_to_api(q: dict) -> dict:
    svc = q.get("service", {})
    fo = svc.get("failover", {})
    t = q.get("template", {})
    return {
        "ID": q.get("id", ""), "Name": q.get("name", ""),
        "Session": q.get("session", ""), "Token": q.get("token", ""),
        "Template": {"Type": t.get("type", ""),
                     "Regexp": t.get("regexp", ""),
                     "RemoveEmptyTags": t.get("remove_empty_tags", False)},
        "Service": {
            "Service": svc.get("service", ""),
            "Failover": {"NearestN": fo.get("nearest_n", 0),
                         "Datacenters": fo.get("datacenters", [])},
            "OnlyPassing": svc.get("only_passing", False),
            "IgnoreCheckIDs": svc.get("ignore_check_ids", []),
            "Near": svc.get("near", ""),
            "Tags": svc.get("tags", []),
            "NodeMeta": svc.get("node_meta", {}),
            "ServiceMeta": svc.get("service_meta", {}),
        },
        "DNS": {"TTL": q.get("dns", {}).get("ttl", "")},
    }


def _severity(status: str) -> int:
    """Check-status severity ordering — the shared helper (one
    definition serves the agent rollups, UI services, prepared-query
    filtering, and alias checks)."""
    return _health.severity(status)


def _check_from_api(d: Optional[dict]) -> Optional[dict]:
    if d is None:
        return None
    return {"check_id": d.get("CheckID", d.get("Name", "check")),
            "status": d.get("Status", "critical"),
            "service_id": d.get("ServiceID", ""),
            "output": d.get("Output", "")}


def _now() -> float:
    import time
    return time.monotonic()


def _jsonify(obj: Any) -> Any:
    """Make a store snapshot JSON-safe: bytes become base64-tagged
    dicts (KV values are raw bytes in the store)."""
    if isinstance(obj, bytes):
        return {"__b64__": base64.b64encode(obj).decode()}
    if isinstance(obj, dict):
        return {k: _jsonify(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonify(v) for v in obj]
    return obj


def _unjsonify(obj: Any) -> Any:
    if isinstance(obj, dict):
        if set(obj) == {"__b64__"}:
            return base64.b64decode(obj["__b64__"])
        return {k: _unjsonify(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unjsonify(v) for v in obj]
    return obj


# ----------------------------------------------------------------------
# Socket server
# ----------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    api: HTTPApi  # class attribute injected by serve()

    def _do(self, method: str):
        parsed = urlparse(self.path)
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b""
        status, payload, headers = self.api.handle(
            method, parsed.path,
            parse_qs(parsed.query, keep_blank_values=True), body,
            headers=dict(self.headers),
        )
        if isinstance(payload, str) and headers.get(
                "Content-Type", "").startswith("text/"):
            # Raw text responses (Prometheus exposition format).
            data = payload.encode()
        else:
            data = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type",
                         headers.pop("Content-Type", "application/json"))
        self.send_header("Content-Length", str(len(data)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 (stdlib naming)
        self._do("GET")

    def do_PUT(self):  # noqa: N802
        self._do("PUT")

    def do_POST(self):  # noqa: N802
        self._do("POST")

    def do_DELETE(self):  # noqa: N802
        self._do("DELETE")

    def log_message(self, *args):  # silence per-request stderr noise
        pass


def serve(api: HTTPApi, host: str = "127.0.0.1", port: int = 0,
          tls=None):
    """Start the HTTP server on a background thread; returns
    (server, bound_port). Port 0 picks a free port (the
    randomPortsSource idiom of reference agent/testagent.go:376).
    ``tls``: a utils/tls.Configurator makes this an HTTPS listener
    (the reference's ports.https + tlsutil IncomingHTTPSConfig)."""
    handler = type("BoundHandler", (_Handler,), {"api": api})
    httpd = ThreadingHTTPServer((host, port), handler)
    if tls is not None:
        # Defer the handshake off the accept loop: with
        # do_handshake_on_connect=False the TLS handshake happens on
        # first IO in the per-connection handler thread, so one stalled
        # client can never block accept() for everyone else.
        httpd.socket = tls.incoming_ctx().wrap_socket(
            httpd.socket, server_side=True,
            do_handshake_on_connect=False)
    th = threading.Thread(target=httpd.serve_forever, daemon=True)
    th.start()
    return httpd, httpd.server_address[1]
