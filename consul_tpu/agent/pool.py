"""Client-side server pool: routing, rebalancing, failure marking.

The reference multiplexes agent→server RPC over a yamux conn pool and
keeps the server list shuffled and rebalanced so load spreads and a dead
server is cycled away from (reference agent/pool/pool.go:122-533;
agent/router/manager.go:297 RebalanceServers, failed-server rotation
manager.go NotifyFailedServer). Real sockets don't exist in this
framework — the pool's *routing policy* does: which server an agent's
next RPC goes to, how failures rotate it out, and when the list
reshuffles.

``ServerPool`` wraps a name→rpc-callable map (in-process Server objects
or bridge-backed remotes alike):

  - round-robin over a shuffled list (manager.go cycles the list head);
  - ``rpc()`` tries up to ``len(servers)`` entries, rotating past
    failures (pool.go's redial-next behavior) and raising the last
    error when all fail;
  - ``notify_failed`` moves a server to the tail immediately
    (manager.go NotifyFailedServer);
  - ``rebalance`` reshuffles on the reference's cadence
    (manager.go:297, default 2 min scaled by cluster size).
"""

from __future__ import annotations

import random
import threading
from typing import Any, Callable

REBALANCE_INTERVAL_S = 120.0  # router/manager.go clientRPCMinReuseDuration


class NoServersError(ConnectionError):
    """Every pooled server failed the call (pool.go exhausted)."""


class ServerPool:
    def __init__(self, servers: dict[str, Callable[..., Any]],
                 seed: int = 0,
                 rebalance_interval_s: float = REBALANCE_INTERVAL_S):
        # An EMPTY pool is legal: a client agent may boot solo and be
        # routed onto servers later via the join verb (/v1/agent/join);
        # until then every rpc() raises NoServersError. A populated
        # pool still refuses remove() down to zero — an operator
        # detaching the last server is almost certainly a mistake.
        self._rpcs = dict(servers)
        self._order = list(servers)
        self._rng = random.Random(seed)
        self._rng.shuffle(self._order)
        self._interval = rebalance_interval_s
        self._next_rebalance = self._interval
        # The pool is shared by concurrently-executing HTTP handler
        # threads in a live client agent (agent/boot.py); an RLock
        # keeps the rotation list consistent under racing rpc() calls.
        self._lock = threading.RLock()
        self.metrics = {"rpc_calls": 0, "rpc_failures": 0, "rebalances": 0}

    @property
    def servers(self) -> list[str]:
        with self._lock:
            return list(self._order)

    def current(self) -> str:
        with self._lock:
            if not self._order:
                raise NoServersError("pool is empty (not joined yet)")
            return self._order[0]

    def add(self, name: str, rpc: Callable[..., Any]):
        with self._lock:
            if name not in self._rpcs:
                self._rpcs[name] = rpc
                # New servers join at a random position (manager.go
                # AddServer reshuffle-on-change keeps load spread).
                self._order.insert(
                    self._rng.randrange(len(self._order) + 1), name)

    def remove(self, name: str):
        """Refuses to drop the last server: constructed-empty (pre-
        join) is legal, but REMOVING down to empty is an operator
        mistake — a joined agent would silently lose all routing."""
        with self._lock:
            if name in self._order and len(self._order) == 1:
                raise ValueError("cannot remove the last pooled server")
            self._rpcs.pop(name, None)
            if name in self._order:
                self._order.remove(name)

    def notify_failed(self, name: str):
        """Rotate a failed server to the tail (manager.go
        NotifyFailedServer) so the next call tries someone else."""
        with self._lock:
            if name in self._order:
                self._order.remove(name)
                self._order.append(name)

    def rebalance(self, now: float) -> bool:
        """Reshuffle on the cadence (manager.go RebalanceServers)."""
        with self._lock:
            if now < self._next_rebalance:
                return False
            self._next_rebalance = now + self._interval
            self._rng.shuffle(self._order)
            self.metrics["rebalances"] += 1
            return True

    def rpc(self, method: str, **args) -> Any:
        """Issue one RPC through the pool: try the head, rotate past
        CONNECTION failures (pool.go redials the next server), raise
        NoServersError after a full cycle. Application-level errors
        (validation, unknown RPC) propagate immediately — re-sending a
        doomed request to every server would mark them all failed for
        nothing."""
        from consul_tpu.server.raft import NotLeader

        with self._lock:
            self.metrics["rpc_calls"] += 1
            n = len(self._order)
        if n == 0:
            raise NoServersError("pool is empty (not joined yet)")
        last_err: Exception | None = None
        for _ in range(n):
            with self._lock:
                name = self._order[0]
                fn = self._rpcs[name]
            try:
                return fn(method, **args)
            except (ConnectionError, NotLeader) as e:
                # Connection failures rotate (pool.go redials the next
                # server); NotLeader rotates too (the forward loop's
                # retry). Application errors propagate above.
                with self._lock:
                    self.metrics["rpc_failures"] += 1
                last_err = e
                self.notify_failed(name)
        raise NoServersError(
            f"all {n} pooled servers failed {method}"
        ) from last_err
