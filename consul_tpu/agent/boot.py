"""``consul-tpu agent``: boot a node from a config file.

The reference's flagship command (reference command/agent/agent.go +
main.go:19-60) turns a config file into a running agent: delegate
(client or server), HTTP API, check runners, anti-entropy, coordinate
loop, signal handling (SIGHUP reload, SIGINT/SIGTERM shutdown). This
module is that surface for the framework: it boots the in-process
server tier (the reference's ``-dev`` mode similarly runs a
single-binary in-memory server, agent/consul/server.go raftInmem) and
drives the tick loop against the wall clock.

Config file (JSON or HCL)::

    {
      "node_name": "node-1",          // reference -node
      "datacenter": "dc1",            // -datacenter
      "bind_addr": "10.0.0.1",        // -bind (catalog + RPC address)
      "server": true,                 // -server; false = client mode
      "n_servers": 1,                 // -dev => 1; 3/5 for quorum sims
      "bootstrap_expect": 0,          // -bootstrap-expect
      "data_dir": "",                 // -data-dir => raft durability
      "http": {"host": "127.0.0.1", "port": 8500},  // ports.http; 0 = free
      "rpc_port": 0,                  // ports.server (8300): the msgpack-
                                      //  RPC listener client agents dial
      "retry_join_rpc": [],           // client mode: server "host:port"
                                      //  RPC addresses to join through
                                      //  (server/rpc_wire.py + the
                                      //  agent/pool rotation policy)
      "wan_join_rpc": [],             // remote-DC server RPC addresses:
                                      //  process-level WAN federation
                                      //  with retry (-retry-join-wan)
      "dns": {"host": ..., "port": 0},// the DNS interface (ports.dns)
      "acl": {"enabled": true, ...},  // ACLs (default_policy, master_token)
      "tls": {"cert": ..., ...},      // TLS on the RPC wire + HTTPS
      "sim": { ... }                  // gossip tunables, config_loader
    }

On ready, one JSON line goes to stdout:
``{"ready": true, "node": ..., "http_port": ...}`` — the script-facing
analogue of "Consul agent running!" (command/agent/agent.go).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from typing import Any, Optional

from consul_tpu import config_loader
from consul_tpu.agent.agent import Agent
from consul_tpu.agent.http import HTTPApi, serve
from consul_tpu.server.endpoints import ServerCluster

_DEFAULTS = {
    "node_name": "node-1",
    "datacenter": "dc1",
    "bind_addr": "127.0.0.1",
    "server": True,
    "n_servers": 1,
    "bootstrap_expect": 0,
    "data_dir": "",
    "http": {"host": "127.0.0.1", "port": 8500},
    # Server mode: the msgpack-RPC listener other processes' client
    # agents dial (reference ports.server 8300); 0 picks a free port.
    "rpc_port": 0,
    # Client mode (server=false): RPC addresses of server processes to
    # join, "host:port" (reference -retry-join, resolved against the
    # RPC tier rather than gossip — the gossip seam is the bridge).
    "retry_join_rpc": [],
    # TLS on the RPC wire (reference conn.go RPCTLS + tlsutil):
    # server mode {"cert":..., "key":..., "ca":..., "require_tls": bool,
    # "verify_incoming": bool} — require_tls refuses plaintext
    # connections; verify_incoming additionally demands a client cert
    # signed by the CA (the reference's VerifyIncoming, which is both).
    # Client mode {"ca":..., ["cert":..., "key":...]} turns on the
    # outgoing upgrade (cert/key only needed against verify_incoming
    # servers).
    "tls": None,
    # DNS interface (reference ports.dns 8600; agent/dns.go):
    # {"host": ..., "port": 0} enables it (0 = free port); null = off.
    # Tunables mirror dns_config: udp_answer_limit, only_passing,
    # node_ttl_s / service_ttl_s.
    "dns": None,
    # ACLs (reference acl block): {"enabled": true, "default_policy":
    # "allow"|"deny", "master_token": "...", "agent_token": "..."};
    # null = ACLs off. agent_token is the token DNS lookups resolve
    # with (DNS packets carry none — reference agent/dns.go resolves
    # via agent.tokens).
    "acl": None,
    # WAN federation across PROCESSES (reference -retry-join-wan /
    # ports.serf_wan): RPC addresses ("host:port") of servers in OTHER
    # datacenters. Each is dialed over the msgpack-RPC wire, its DC
    # learned via Status.Datacenter, and registered in the WAN router
    # so ?dc= forwarding crosses process boundaries. Federation is
    # per-direction: each side lists the other.
    "wan_join_rpc": [],
    # Opt-in for exec checks over the HTTP API (reference
    # enable_script_checks; off by default — it is remote command
    # execution on this host).
    "enable_script_checks": False,
    "sim": None,
}

_TLS_KEYS = {"cert", "key", "ca", "require_tls", "verify_incoming"}


def _validate_tls(cfg: dict):
    """Eager config-time validation (load_config contract: a typo'd
    key or missing material fails at boot, not as a handshake error
    at first RPC)."""
    t = cfg.get("tls")
    if not t:
        return
    if not isinstance(t, dict):
        raise ValueError("tls: must be an object")
    unknown = sorted(set(t) - _TLS_KEYS)
    if unknown:
        raise ValueError(f"unknown tls config keys: {unknown}")
    if cfg["server"]:
        for k in ("cert", "key"):
            if not t.get(k):
                raise ValueError(f"tls.{k} is required in server mode")
    elif not t.get("ca"):
        raise ValueError(
            "tls.ca is required in client mode — falling back to the "
            "system trust store would never verify a cluster CA")
    for k in ("cert", "key", "ca"):
        if t.get(k) and not os.path.exists(t[k]):
            raise ValueError(f"tls.{k}: no such file: {t[k]}")


def _tls_for(cfg: dict, *, server: bool):
    """Build the wire-TLS object from the agent config: a Configurator
    (server mode, owns cert material) or a client SSLContext
    (OutgoingRPCConfig with VerifyOutgoing)."""
    t = cfg.get("tls")
    if not t:
        return None, False
    if server:
        from consul_tpu.utils.tls import Configurator
        conf = Configurator(t["cert"], t["key"], ca=t.get("ca"),
                            verify_incoming=bool(t.get("verify_incoming")))
        return conf, bool(t.get("require_tls"))
    from consul_tpu.utils.tls import client_ctx
    return client_ctx(t["ca"], cert=t.get("cert"), key=t.get("key")), False


def load_config(path: Optional[str], overrides: Optional[dict] = None) -> dict:
    cfg = dict(_DEFAULTS)
    if path:
        doc = config_loader._read_config_file(path)
        if not isinstance(doc, dict):
            raise ValueError(f"config file {path}: top level must be an object")
        unknown = sorted(set(doc) - set(_DEFAULTS))
        if unknown:
            raise ValueError(f"unknown agent config keys: {unknown}")
        http = dict(cfg["http"], **doc.get("http", {}))
        cfg.update(doc)
        cfg["http"] = http
    cfg.update(overrides or {})
    # Client mode with NO retry_join_rpc boots solo: every RPC fails
    # with NoServersError until a post-boot `consul-tpu join`
    # (/v1/agent/join) routes it onto a server set.
    for addr in cfg["retry_join_rpc"]:
        _parse_hostport(addr, field="retry_join_rpc entry")
    for addr in cfg["wan_join_rpc"]:
        _parse_hostport(addr, field="wan_join_rpc entry")
    _validate_tls(cfg)
    if cfg["sim"] is not None:
        # Validate the gossip tunables through the layered loader.
        config_loader.load(overrides=config_loader._flatten(cfg["sim"]))
    return cfg


def _parse_hostport(addr: str, field: str = "address") -> tuple[str, int]:
    """One shared host:port parse for config validation, dialing, and
    the join verb — identical acceptance everywhere."""
    host, _, port = str(addr).rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"{field} {addr!r} is not host:port")
    return host, int(port)


class _WanWireRemote:
    """A remote-DC server reachable over the msgpack-RPC wire, shaped
    like a local Server for the router/forwardDC path (``rpc`` +
    raft-liveness duck type). A connection failure puts it on a short
    COOLDOWN — not a terminal blacklist: RpcClient reconnects on the
    next call, and a transient timeout (or the wire's busy-as-
    ConnectionError under load) must not sever cross-DC routing
    forever. The reference's NotifyFailedServer likewise only cycles
    the server in the rotation."""

    FAIL_COOLDOWN_S = 5.0

    class _Liveness:
        def __init__(self):
            self.failed_until = 0.0

        @property
        def stopped(self) -> bool:
            return time.monotonic() < self.failed_until

    def __init__(self, wan_id: str, dc: str, client):
        self.id = wan_id
        self.dc = dc
        self._client = client
        self.raft = self._Liveness()

    def rpc(self, method: str, **args):
        try:
            return self._client.call(method, **args)
        except (ConnectionError, OSError):
            self.raft.failed_until = time.monotonic() + \
                self.FAIL_COOLDOWN_S
            raise

    def close(self):
        try:
            self._client.close()
        except Exception:  # noqa: BLE001 — teardown must not raise
            pass


class AgentRuntime:
    """Everything ``consul-tpu agent`` runs: server tier + agent +
    HTTP listener + wall-clock tick loop."""

    def __init__(self, cfg: dict):
        self.cfg = cfg
        self._stop = threading.Event()
        self._reload_requested = threading.Event()
        self.cluster = None
        self.rpc_listener = None
        self.rpc_port = None
        self.dns = None
        self.dns_port = None

        if cfg["server"]:
            rpc, wait_write, api_server = self._build_server_tier()
        else:
            rpc, wait_write, api_server = self._build_client_tier()

        self.agent = Agent(
            cfg["node_name"], cfg["bind_addr"], rpc,
            cluster_size=int(cfg["n_servers"]),
        )
        # One telemetry sink per process: the RPC listener's wire
        # counters and the agent's own metrics land in the same sink, so
        # /v1/agent/metrics (and the debug bundle) shows the full tier.
        if self.rpc_listener is not None:
            self.agent.sink = self.rpc_listener.sink
        self.agent.reload_hook = self._reload
        self.agent.join_hook = getattr(self, "_join", None)
        # /v1/agent/leave: answer 200, then the main loop shuts down
        # (setting the stop flag here, not calling shutdown(), keeps
        # the HTTP response from racing its own listener teardown).
        self.agent.leave_hook = self._stop.set
        self.api = HTTPApi(self.agent, server=api_server,
                           wait_write=wait_write,
                           datacenter=cfg["datacenter"],
                           acl=cfg.get("acl"))
        self.api.enable_script_checks = bool(
            cfg.get("enable_script_checks"))
        self.httpd = None
        self.http_port = None

    def _build_server_tier(self):
        cfg = self.cfg
        self.cluster = ServerCluster(
            n=int(cfg["n_servers"]),
            dc=cfg["datacenter"],
            bootstrap_expect=int(cfg["bootstrap_expect"]),
            data_dir=cfg["data_dir"],
        )
        if not cfg["bootstrap_expect"]:
            self.cluster.wait_converged()

        # No runtime-level lock here: raft-lite's mutation surface is
        # internally locked (Transport.lock — tick/pump/propose), and
        # blocking reads park on the state store's condition, so HTTP
        # handler threads never serialize behind each other or stall
        # the pump (a lock held across a 10 s long-poll would deadlock
        # the write that should wake it).
        def rpc(method, **args):
            led = self.cluster.raft.leader()
            if led is None:
                led = self.cluster.raft.wait_converged()
            return self.cluster.registry[led.id].rpc(method, **args)

        def wait_write(idx):
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                led = self.cluster.raft.leader()
                if led is not None and led.last_applied >= idx:
                    return
                time.sleep(0.002)

        # The inter-process RPC listener (reference ports.server 8300):
        # client agents in OTHER processes dial this and speak
        # server/rpc_wire.py's msgpack-RPC.
        from consul_tpu.server.rpc_wire import RpcListener
        tls, require_tls = _tls_for(cfg, server=True)

        def _leader_store():
            led = self.cluster.raft.leader() or self.cluster.raft.wait_converged()
            return self.cluster.registry[led.id].store

        self.rpc_listener = RpcListener(
            rpc, host=cfg["bind_addr"], port=int(cfg["rpc_port"]),
            tls=tls, require_tls=require_tls,
            snapshot_fn=lambda: _leader_store().snapshot(),
            restore_fn=lambda snap: _leader_store().restore(snap))
        self.rpc_port = self.rpc_listener.port
        api_server = self.cluster.registry[
            self.cluster.raft.wait_converged().id]
        if cfg["wan_join_rpc"]:
            self._join_wan_over_wire(cfg, tls)
        return rpc, wait_write, api_server

    def _join_wan_over_wire(self, cfg: dict, tls) -> None:
        """Federate this DC with remote-DC server PROCESSES over the
        msgpack-RPC wire (the reference's WAN serf + yamux pool,
        process-shaped): dial each wan_join_rpc address, learn its DC
        via Status.Datacenter, and register a wire-backed proxy in
        every local server's router so forwardDC crosses process
        boundaries. Addresses that are unreachable at boot RETRY on a
        background loop until they join (the reference's
        -retry-join-wan contract) — a supervisor starting both DCs
        concurrently must not lose federation to boot order."""
        self._wan_remotes: list[_WanWireRemote] = []
        self._wan_tls = tls
        pending = self._wan_try_join(cfg, list(cfg["wan_join_rpc"]))
        if pending:
            def retry():
                left = pending
                while left and not self._stop.is_set():
                    self._stop.wait(5.0)
                    if self._stop.is_set():
                        return
                    left = self._wan_try_join(cfg, left)
            threading.Thread(target=retry, daemon=True).start()

    def _wan_try_join(self, cfg: dict, addrs: list) -> list:
        """Dial each address once; returns the ones still unreachable.
        Every success re-registers the routers with the full remote
        set."""
        from consul_tpu.server.rpc_wire import RpcClient
        from consul_tpu.server.router import Router, flood_join

        remaining = []
        joined_any = False
        for addr in addrs:
            host, port = _parse_hostport(addr, field="wan_join_rpc entry")
            try:
                client = RpcClient(host, port, tls=self._wan_tls)
                dc = client.call("Status.Datacenter")
            except (OSError, ConnectionError, ValueError) as e:
                print(f"agent: wan join {addr}: unreachable ({e}); "
                      "will retry", file=sys.stderr)
                remaining.append(addr)
                continue
            if dc == cfg["datacenter"]:
                print(f"agent: wan join {addr}: same datacenter "
                      f"{dc!r}; skipping", file=sys.stderr)
                client.close()
                continue
            self._wan_remotes.append(
                _WanWireRemote(f"wire:{addr}.{dc}", dc, client))
            joined_any = True
        if joined_any:
            wan_registry = {s.wan_id: s for s in self.cluster.servers}
            wan_registry.update({r.id: r for r in self._wan_remotes})
            local_ids = [s.wan_id for s in self.cluster.servers]
            by_dc: dict = {}
            for r in self._wan_remotes:
                by_dc.setdefault(r.dc, []).append(r.id)
            for s in self.cluster.servers:
                router = Router(local_dc=cfg["datacenter"])
                flood_join(router, cfg["datacenter"], local_ids)
                for dc, ids in by_dc.items():
                    flood_join(router, dc, ids)
                s.join_wan(router, wan_registry)
        return remaining

    def _build_client_tier(self):
        """Client mode: no local consensus — every RPC rides the wire
        to a server process through the pooled connections (reference
        client.go RPC via the conn pool), with the pool's rotate-past-
        failure policy."""
        from consul_tpu.agent.pool import ServerPool
        from consul_tpu.server.rpc_wire import RpcClient, RpcWireError

        tls, _ = _tls_for(self.cfg, server=False)

        def dial(addr: str):
            host, port = _parse_hostport(addr)
            return RpcClient(host, port, tls=tls).call

        pool = ServerPool({addr: dial(addr)
                           for addr in self.cfg["retry_join_rpc"]})
        self._pool = pool

        def join(addr: str) -> bool:
            """The /v1/agent/join verb: aim this client at another
            server's RPC address at runtime (reference agent.JoinLAN;
            here the pool gains a member, reference AddServer). The
            target is PROBED first — `consul join` errors on an
            unreachable address rather than polluting the pool with a
            dead entry every rebalance would rotate back to the head."""
            host, port = _parse_hostport(addr, field="join address")
            if addr in pool.servers:
                # Idempotent like `consul join` of a current member —
                # and no probe client is created for it (pool.add would
                # silently no-op, leaking the probe's socket + reader
                # thread on every repeat join).
                return True
            probe = RpcClient(host, port, timeout_s=5.0, tls=tls)
            try:
                probe.call("Status.Leader")
            except (ConnectionError, OSError) as e:
                probe.close()
                raise ValueError(
                    f"join {addr}: server unreachable ({e})") from e
            pool.add(addr, probe.call)
            return True

        self._join = join

        def rpc(method, **args):
            return pool.rpc(method, **args)

        def wait_write(idx):
            # Returns the found ApplyResult so the HTTP tier skips its
            # own follow-up fetch (one wire round trip per write saved).
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                try:
                    res = pool.rpc("Status.ApplyResult", index=idx)
                    if res["found"]:
                        return res
                except (RpcWireError, ConnectionError):
                    pass
                time.sleep(0.01)
            return None

        return rpc, wait_write, None

    # ------------------------------------------------------------------
    def _dns_authz(self):
        """DNS packets carry no token: the reference resolves every
        lookup with the agent's own token under the configured default
        policy (agent/dns.go → agent.tokens, then the catalog/health
        endpoint vetters). Returns an ``(resource, name, access) ->
        bool`` gate for DNSServer, or None when ACLs are off (open,
        exactly the pre-ACL behavior)."""
        acl_cfg = self.cfg.get("acl") or {}
        if not acl_cfg.get("enabled"):
            return None
        from consul_tpu.server import acl as acl_mod
        default_allow = acl_cfg.get("default_policy", "allow") != "deny"
        token = str(acl_cfg.get("agent_token", ""))
        master = str(acl_cfg.get("master_token", ""))

        def allowed(resource: str, name: str, access: str = "read"):
            if master and token == master:
                return True
            try:
                res = self.agent.rpc("ACL.Resolve", secret_id=token)
                if res["management"]:
                    return True
                authz = acl_mod.Authorizer(res["rules"],
                                           default_allow=default_allow)
            except Exception:  # noqa: BLE001 — fail closed under ACLs
                return False
            return authz.allowed(resource, name, access)

        return allowed

    def start(self) -> int:
        """Bind HTTP (+ DNS when configured), start the raft pump
        (server mode); returns the bound HTTP port."""
        self.httpd, self.http_port = serve(
            self.api, self.cfg["http"]["host"], int(self.cfg["http"]["port"])
        )
        dns_cfg = self.cfg.get("dns")
        if dns_cfg:
            from consul_tpu.agent.dns import DNSServer
            self.dns = DNSServer(
                self.agent.rpc, node_name=self.cfg["node_name"],
                datacenter=self.cfg["datacenter"],
                udp_answer_limit=int(
                    dns_cfg.get("udp_answer_limit", 3)),
                only_passing=bool(dns_cfg.get("only_passing", False)),
                node_ttl_s=int(dns_cfg.get("node_ttl_s", 0)),
                service_ttl_s=int(dns_cfg.get("service_ttl_s", 0)),
                authz=self._dns_authz(),
            )
            self.dns_port = self.dns.serve(
                dns_cfg.get("host", "127.0.0.1"),
                int(dns_cfg.get("port", 0)))
        if self.cluster is not None:
            threading.Thread(target=self._pump, daemon=True).start()
            # Seed the serfHealth record for this node (the leader's
            # serf reconcile would author it if a gossip plane were
            # attached; a standalone boot has exactly one, live,
            # member: itself — leader.go:1065 reconcileMember alive).
            from consul_tpu.server.leader import reconcile_member
            led = self.cluster.raft.wait_converged()
            reconcile_member(
                self.cluster.registry[led.id],
                self.cfg["node_name"], self.cfg["bind_addr"], "alive",
            )
        return self.http_port

    def _pump(self):
        """Continuous raft/timer advance (the goroutine tickers of
        reference agent/consul/server.go collapse into one pump),
        including leader duties: coordinate flush and session TTL
        expiry (reference leader.go initializeSessionTimers — timers
        rebuild from the store when leadership moves)."""
        timers_for = None  # leader id the current timers belong to
        next_ttl_pass = 0.0
        while not self._stop.is_set():
            try:
                self.cluster.step()
                led = self.cluster.raft.leader()
                if led is not None and led.id in self.cluster.registry:
                    srv = self.cluster.registry[led.id]
                    srv.flush_coordinates()
                    if timers_for != led.id:
                        from consul_tpu.server.leader import SessionTimers
                        if timers_for is not None and \
                                timers_for in self.cluster.registry:
                            self.cluster.registry[
                                timers_for].session_timers = None
                        srv.session_timers = SessionTimers(srv)
                        timers_for = led.id
                    now = time.monotonic()
                    if now >= next_ttl_pass:  # ~10 Hz, not per 2ms step
                        next_ttl_pass = now + 0.1
                        srv.session_timers.tick(now)
            except Exception as e:  # noqa: BLE001
                # A pump death would leave the agent serving HTTP with
                # raft frozen (writes hang with no diagnostic) — log
                # and keep pumping; consensus state is unharmed.
                print(f"agent: raft pump error: {e!r}", file=sys.stderr)
                time.sleep(0.1)
            time.sleep(0.002)

    def _reload(self) -> list:
        """SIGHUP / /v1/agent/reload: re-read the config file and report
        which changed keys applied (agent-level keys need a restart —
        the reference's ReloadConfig safe-subset contract)."""
        path = self.cfg.get("_config_path")
        if not path:
            return []
        try:
            new = load_config(path)
        except (OSError, ValueError) as e:
            # A broken file on SIGHUP must never kill the agent: log
            # and keep the old config (the reference's reload path
            # logs the builder error and carries on).
            print(f"agent: reload failed, keeping old config: {e}",
                  file=sys.stderr)
            return []
        changed = [k for k in new
                   if k != "_config_path" and new[k] != self.cfg.get(k)]
        # Nothing agent-level is live-appliable yet; report-only, like
        # the reference logging ignored non-reloadable fields.
        return [k for k in changed if k == "sim"]

    def install_signals(self):
        """Main-thread only; must run BEFORE readiness is announced, or
        a prompt SIGTERM from a supervisor races the default handler."""
        signal.signal(signal.SIGTERM, lambda *_: self._stop.set())
        signal.signal(signal.SIGINT, lambda *_: self._stop.set())
        try:
            signal.signal(signal.SIGHUP,
                          lambda *_: self._reload_requested.set())
        except (AttributeError, ValueError):
            pass  # platform without SIGHUP

    def run_forever(self, tick_s: float = 0.05) -> int:
        """The main loop: agent anti-entropy + checks + coordinates at
        wall-clock cadence until SIGINT/SIGTERM."""
        while not self._stop.is_set():
            self.agent.tick(time.time())
            if self._reload_requested.is_set():
                self._reload_requested.clear()
                applied = self.agent.reload()
                print(json.dumps({"reload": applied}), flush=True)
            time.sleep(tick_s)
        self.shutdown()
        return 0

    def shutdown(self):
        self._stop.set()
        for r in getattr(self, "_wan_remotes", []):
            r.close()
        if self.dns is not None:
            self.dns.close()
        if self.rpc_listener is not None:
            self.rpc_listener.close()
        if self.httpd is not None:
            self.httpd.shutdown()


def run(config_file: Optional[str], overrides: Optional[dict] = None) -> int:
    """CLI entry: boot, announce readiness, serve until signalled."""
    try:
        cfg = load_config(config_file, overrides)
        cfg["_config_path"] = config_file
        # Construction can fail on environment problems too (an
        # unassignable bind_addr for the RPC listener, a busy port, an
        # unwritable data_dir) — all exit cleanly, never a traceback.
        rt = AgentRuntime(cfg)
    except (OSError, ValueError) as e:
        print(f"agent: {e}", file=sys.stderr)
        return 1
    rt.install_signals()
    port = rt.start()
    print(json.dumps({
        "ready": True, "node": cfg["node_name"], "dc": cfg["datacenter"],
        "http_port": port,
        "mode": "server" if cfg["server"] else "client",
        "servers": int(cfg["n_servers"]) if cfg["server"] else 0,
        "rpc_port": rt.rpc_port,
        "dns_port": rt.dns_port,
    }), flush=True)
    return rt.run_forever()
