"""The Agent: per-node runtime wiring local state, checks, cache, and
the coordinate loop to the server tier.

Mirrors the reference agent lifecycle (reference agent/agent.go:371-550
Start sequence: local state → ae syncer → cache → delegate → checks →
sendCoordinate): an Agent holds its registrations, runs its checks,
anti-entropy-syncs into the catalog through its RPC route, and sends
its Vivaldi coordinate on the rate-scaled cadence (reference
agent/agent.go:1891-1940 sendCoordinate with
``lib.RateScaledInterval(SyncCoordinateRateTarget, min, N)``).

Agents are time-explicit: ``tick(now)`` drives checks, sync, and the
coordinate send, so a driver can pump thousands of agents against the
simulation clock deterministically (the TestAgent idiom, reference
agent/testagent.go:44-129, without real sockets).
"""

from __future__ import annotations

import random
import threading
import uuid
from typing import Any, Callable, Optional

from consul_tpu.agent.cache import Cache
from consul_tpu.agent.checks import CheckRunner
from consul_tpu.agent.local import LocalState, sync_stagger_s

# Reference defaults (agent/config/default.go SyncCoordinateRateTarget
# = 64 updates/s cluster-wide, SyncCoordinateIntervalMin = 15s).
COORDINATE_RATE_TARGET_PER_S = 64.0
COORDINATE_INTERVAL_MIN_S = 15.0


def coordinate_interval_s(cluster_size: int) -> float:
    """Rate-scaled coordinate send interval (reference
    lib/cluster.go:51-60 RateScaledInterval, agent/agent.go:1896)."""
    return max(cluster_size / COORDINATE_RATE_TARGET_PER_S,
               COORDINATE_INTERVAL_MIN_S)


class Agent:
    def __init__(self, node: str, address: str, rpc: Callable[..., Any],
                 coordinate_source: Optional[Callable[[], dict]] = None,
                 cluster_size: int = 1, seed: int = 0):
        """``rpc(method, **args)``: the agent's route to a server (in
        client mode a Server picked from the connection pool; in server
        mode the local Server) — reference agent.RPC via the delegate.
        ``coordinate_source``: returns this node's current Vivaldi
        coordinate (from the simulation's VivaldiState row, the
        serf.GetCoordinate of reference agent/agent.go:1919)."""
        self.node = node
        self.address = address
        self.rpc = rpc
        self.coordinate_source = coordinate_source
        self.rng = random.Random(seed)
        self.local = LocalState(node, address)
        self.checks = CheckRunner(self.local)
        self.cache = Cache()
        self.cluster_size = cluster_size
        # Device serving plane (consul_tpu/serving.ServingPlane), wired
        # by attach_serving(); None means host-path reads only.
        self.serving = None
        self._register_cache_types()

        self._next_sync = 0.0  # first tick syncs immediately
        self._next_coord = self.rng.uniform(
            0, coordinate_interval_s(cluster_size)
        )
        self.metrics = {"syncs": 0, "sync_writes": 0, "coordinate_sends": 0,
                        "sync_failures": 0, "services_reaped": 0}
        # DeregisterCriticalServiceAfter (reference structs/
        # check_type.go:55 + agent.go reapServicesInternal): per-check
        # reap timeout; critical-since bookkeeping feeds the reap pass
        # in tick().
        self._reap_after: dict[str, float] = {}
        self._critical_since: dict[str, float] = {}
        # go-metrics sink served at /v1/agent/metrics (reference
        # lib/telemetry.go always attaches an InmemSink).
        from consul_tpu.utils import telemetry
        self.sink = telemetry.Sink()
        # User-event buffer for /v1/event/fire + /v1/event/list
        # (reference agent/event_endpoint.go; the agent retains the most
        # recent 256 events, agent/user_event.go eventBuf). fire_hook
        # lets a driver forward fired events into the simulated serf
        # event plane (models/serf.user_event).
        self.events: list[dict] = []
        self.event_seq = 0
        self.fire_hook: Optional[Callable[[str, bytes], None]] = None
        self._event_cond = threading.Condition()
        # ForceLeave route into the gossip plane (reference
        # agent/agent.go ForceLeave -> serf.RemoveFailedNode; the driver
        # wires this to models/serf.leave on the failed seat).
        self.force_leave_hook: Optional[Callable[[str], bool]] = None
        # Log monitor tap for /v1/agent/monitor (utils/logger.setup
        # returns one; None until logging is configured).
        self.monitor = None
        # Cluster keyring manager for /v1/operator/keyring (a driver
        # attaches a wire/keymanager.KeyManager when gossip encryption
        # is on; None = encryption off, endpoint returns an error).
        self.key_manager = None
        # Config reload for /v1/agent/reload (reference agent
        # ReloadConfig via SIGHUP or the endpoint): a driver wires this
        # to config_loader.apply_safe on its Simulation; returns the
        # list of applied knob paths.
        self.reload_hook: Optional[Callable[[], list]] = None
        # Graceful leave (reference agent.Leave, agent/agent.go:
        # serf.Leave + catalog deregistration). left stops the duty
        # cycle so anti-entropy cannot re-register the node after the
        # deregister; leave_hook lets a runtime turn the leave into a
        # process shutdown (boot wires it to the stop flag).
        self.left = False
        self.leave_hook: Optional[Callable[[], None]] = None
        # Post-boot join (reference /v1/agent/join + `consul join`):
        # a client-mode boot wires this to add a server RPC address to
        # the connection pool at runtime; None = not joinable this way
        # (server mode federates via bridge/federate()).
        self.join_hook: Optional[Callable[[str], bool]] = None

    def join(self, address: str) -> bool:
        """Join this agent to a server set (reference agent.JoinLAN,
        agent/agent.go; here the wire-tier re-aim of retry_join_rpc)."""
        if self.join_hook is None:
            raise ValueError(
                "join is a client-mode verb (a server federates via "
                "the bridge/WAN configuration)")
        return bool(self.join_hook(address))

    def _register_cache_types(self):
        """The typed cache entries this agent serves (reference
        agent/cache-types/: health_services.go, catalog_services.go,
        the coordinate reads) — each maps a request to a blocking RPC
        fetcher; refresh keeps them warm in the background so any
        number of readers cost the store one watch."""

        def health_services(service: str, passing_only: bool = False):
            def fetch(min_index: int, wait_s: float) -> dict:
                return self.rpc(
                    "Health.ServiceNodes", service=service,
                    passing_only=passing_only,
                    min_index=min_index, wait_s=wait_s,
                )
            return fetch

        def catalog_services():
            def fetch(min_index: int, wait_s: float) -> dict:
                return self.rpc("Catalog.ListServices",
                                min_index=min_index, wait_s=wait_s)
            return fetch

        def coordinate_nodes():
            def fetch(min_index: int, wait_s: float) -> dict:
                return self.rpc("Coordinate.ListNodes",
                                min_index=min_index, wait_s=wait_s)
            return fetch

        self.cache.register_type("health-services", health_services)
        self.cache.register_type("catalog-services", catalog_services)
        self.cache.register_type("coordinate-nodes", coordinate_nodes)

    def attach_serving(self, plane) -> None:
        """Wire a device serving plane into this agent: registers the
        ``serving-nearest`` cache type (the batched device path IS the
        fetcher, so TTL-fresh NearestN reads cost zero device
        round-trips) and exposes the plane's stats at
        ``/v1/agent/metrics`` as ``consul.serving.*`` gauges."""
        self.serving = plane
        if getattr(plane, "sink", None) is None:
            plane.sink = self.sink
        plane.register_cache_type(self.cache)

    def serving_nearest(self, src, service: int = -1) -> dict:
        """NearestN through the agent cache (requires
        :meth:`attach_serving`); repeated reads within the TTL are
        cache hits counted into ``sim.serving.cache_hits``."""
        if self.serving is None:
            raise RuntimeError("no serving plane attached")
        return self.serving.cached_nearest(self.cache, src, service=service)

    def reload(self) -> Optional[list]:
        """Re-read config sources and apply the safe subset; None when
        no driver wired a reload path."""
        if self.reload_hook is None:
            return None
        return list(self.reload_hook())

    # -- service/check registration API (reference agent endpoints
    # /v1/agent/service/register etc.) ---------------------------------
    def add_service(self, service_id: str, service: str, port: int = 0,
                    tags: Optional[list] = None,
                    check_ttl_s: Optional[float] = None, now: float = 0.0):
        self.local.add_service(service_id, service, port, tags)
        # A re-registration is a FRESH definition: stale reap config
        # or critical-since bookkeeping from the previous registration
        # must not survive it (the caller re-arms if still wanted).
        self._reap_after.pop(f"service:{service_id}", None)
        self._critical_since.pop(f"service:{service_id}", None)
        if check_ttl_s is None:
            # A fresh definition WITHOUT a check must not keep the
            # previous registration's TTL check alive (it would sit
            # critical forever with nothing renewing it).
            self.checks.remove(f"service:{service_id}")
        if check_ttl_s is not None:
            self.checks.add_ttl(f"service:{service_id}", check_ttl_s,
                                service_id=service_id, now=now)

    def remove_service(self, service_id: str):
        self.checks.remove(f"service:{service_id}")
        self.local.remove_service(service_id)

    # -- user events (reference agent/event_endpoint.go) ----------------
    def fire_event(self, name: str, payload: bytes = b"") -> dict:
        """Fire a user event: buffer it (last 256 retained, reference
        agent/user_event.go) and forward to the gossip plane when a
        driver attached one."""
        with self._event_cond:
            self.event_seq += 1
            ev = {"ID": str(uuid.uuid4()), "Name": name,
                  "Payload": payload, "LTime": self.event_seq}
            self.events.append(ev)
            del self.events[:-256]
            self._event_cond.notify_all()
        if self.fire_hook is not None:
            self.fire_hook(name, payload)
        return ev

    def event_list(self, name: str = "", min_index: int = 0,
                   wait_s: float = 0.0) -> tuple[int, list[dict]]:
        """List buffered events, optionally filtered by name, with
        blocking-query semantics over the event sequence (the reference
        event endpoint supports ?index long-polling on an event hash)."""
        import time as _time

        deadline = _time.monotonic() + wait_s

        def filtered():
            return [e for e in self.events
                    if not name or e["Name"] == name]

        def index_of(evs):
            # Per-FILTER watch index (the reference long-polls a hash of
            # the filtered events): +1 past the newest matching LTime,
            # so unrelated events never wake a name-scoped watcher.
            return (evs[-1]["LTime"] if evs else 0) + 1

        with self._event_cond:
            evs = filtered()
            while min_index and index_of(evs) <= min_index:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                self._event_cond.wait(remaining)
                evs = filtered()
            return index_of(evs), evs

    def force_leave(self, node: str) -> bool:
        """Transition a failed member to left (reference ForceLeave):
        forwarded through the driver hook; True when it acted."""
        if self.force_leave_hook is None:
            return False
        return bool(self.force_leave_hook(node))

    def leave(self) -> bool:
        """Graceful leave (reference agent.Leave, agent/agent.go:1387:
        serf leave broadcast + catalog deregistration before shutdown).
        Sets ``left`` FIRST so a concurrent tick cannot re-register the
        node between the deregister and the flag. The gossip plane must
        hear the leave too — otherwise the leader's serf reconcile sees
        an alive member with no catalog entry and registers it right
        back — so the force-leave hook (the route into models/serf
        .leave) is applied to OURSELVES before the deregister, the
        self-targeted serf Leave broadcast of the reference. Both
        effects are best-effort: leaving while the servers are down
        still leaves."""
        self.left = True
        if self.force_leave_hook is not None:
            try:
                self.force_leave_hook(self.node)
            except Exception:  # noqa: BLE001 — gossip plane gone
                pass
        try:
            self.rpc("Catalog.Deregister", node=self.node)
        except Exception:  # noqa: BLE001 — unreachable server
            pass
        if self.leave_hook is not None:
            self.leave_hook()
        return True

    # -- maintenance mode (reference agent/agent.go EnableNodeMaintenance
    # / EnableServiceMaintenance): a synthetic critical check that flows
    # through anti-entropy into the catalog, so ?passing= discovery and
    # DNS-equivalent filtering exclude the node/service. --------------
    NODE_MAINT_CHECK_ID = "_node_maintenance"
    SERVICE_MAINT_PREFIX = "_service_maintenance:"
    _DEFAULT_NODE_REASON = (
        "Maintenance mode is enabled for this node, "
        "but no reason was provided. This is a default message."
    )
    _DEFAULT_SERVICE_REASON = (
        "Maintenance mode is enabled for this service, "
        "but no reason was provided. This is a default message."
    )

    def enable_node_maintenance(self, reason: str = ""):
        self.local.add_check(
            self.NODE_MAINT_CHECK_ID, status="critical",
            output=reason or self._DEFAULT_NODE_REASON,
        )

    def disable_node_maintenance(self):
        self.local.remove_check(self.NODE_MAINT_CHECK_ID)

    def in_node_maintenance(self) -> bool:
        return self.NODE_MAINT_CHECK_ID in self.local.checks

    def enable_service_maintenance(self, service_id: str,
                                   reason: str = "") -> bool:
        if service_id not in self.local.services:
            return False
        self.local.add_check(
            self.SERVICE_MAINT_PREFIX + service_id, status="critical",
            service_id=service_id,
            output=reason or self._DEFAULT_SERVICE_REASON,
        )
        return True

    def disable_service_maintenance(self, service_id: str) -> bool:
        """Idempotent like the reference DisableServiceMaintenance:
        errors only for an unknown service; disabling a service that is
        not in maintenance is a no-op success."""
        if service_id not in self.local.services:
            return False
        self.local.remove_check(self.SERVICE_MAINT_PREFIX + service_id)
        return True

    # -- the periodic work ---------------------------------------------
    def tick(self, now: float) -> dict:
        """One agent pump: run checks, sync if due, send coordinate if
        due. Returns which duties ran (for drivers/tests)."""
        ran = {"sync": False, "coordinate": False}
        if self.left:
            # A left agent runs no duties: syncing would re-register
            # the node leave() just deregistered (reference: Leave
            # stops the state syncer before deregistering).
            return ran
        self.checks.tick(now)
        self._reap_critical_services(now)
        # Check status changes mark entries dirty; sync as scheduled or
        # immediately when something is dirty (changes trigger
        # SyncChanges promptly in the reference, local/state.go:505).
        dirty = (
            not self.local.node_in_sync
            or any(not s.in_sync for s in self.local.services.values())
            or any(not c.in_sync for c in self.local.checks.values())
        )
        if now >= self._next_sync or dirty:
            try:
                self.metrics["sync_writes"] += self.local.sync_changes(self.rpc)
                self.metrics["syncs"] += 1
                ran["sync"] = True
            except Exception:  # noqa: BLE001 — server unreachable; retry soon
                self.metrics["sync_failures"] += 1
                self._next_sync = now + 1.0
            else:
                self._next_sync = now + sync_stagger_s(
                    self.cluster_size, self.rng
                )
        if self.coordinate_source is not None and now >= self._next_coord:
            try:
                self.rpc("Coordinate.Update", node=self.node,
                         coord=self.coordinate_source())
                self.metrics["coordinate_sends"] += 1
                ran["coordinate"] = True
            except Exception:  # noqa: BLE001
                pass
            self._next_coord = now + coordinate_interval_s(self.cluster_size)
        return ran

    def set_reap_after(self, check_id: str, seconds: float):
        """Arm DeregisterCriticalServiceAfter for one check (reference
        check_type.go:55; the reference floors tiny values at 1 min —
        here the given value is honored so tests can run fast, with
        the floor left to config policy)."""
        self._reap_after[check_id] = float(seconds)

    def _reap_critical_services(self, now: float):
        """Deregister services whose check has been critical past its
        reap timeout (reference agent.go reapServicesInternal)."""
        for cid, c in list(self.local.checks.items()):
            if c.status == "critical":
                self._critical_since.setdefault(cid, now)
            else:
                self._critical_since.pop(cid, None)
        for cid in list(self._critical_since):
            # A check deregistered while critical must not leak its
            # bookkeeping forever.
            if cid not in self.local.checks:
                self._critical_since.pop(cid, None)
        for cid, timeout in list(self._reap_after.items()):
            c = self.local.checks.get(cid)
            if c is None:
                self._reap_after.pop(cid, None)
                self._critical_since.pop(cid, None)
                continue
            since = self._critical_since.get(cid)
            if not c.service_id or timeout <= 0 or since is None:
                continue
            if now - since > timeout:
                self.metrics["services_reaped"] += 1
                self.remove_service(c.service_id)
                self._reap_after.pop(cid, None)
                self._critical_since.pop(cid, None)
                # Deregister the catalog side PROMPTLY: removal leaves
                # no dirty local entry for the dirty-detector to see,
                # so pull the next anti-entropy pass to THIS tick
                # (the reference's reap deregisters immediately).
                self._next_sync = 0.0

    # -- reads through the cache (reference DNS/HTTP read path) --------
    def cached_service_nodes(self, service: str, ttl_s: float = 3.0,
                             refresh: bool = False) -> Any:
        return self.cache.get(
            f"service-nodes:{service}",
            lambda idx, wait: self.rpc("Health.ServiceNodes", service=service,
                                       min_index=idx, wait_s=wait),
            ttl_s=ttl_s, refresh=refresh,
        )

    def close(self):
        self.cache.close()
        # The serving plane's close mirrors the cache's: wake parked
        # batcher waiters and watch pollers, reject new submits with
        # ServingClosedError — no thread is ever left parked on a
        # plane that will not pump again.
        if self.serving is not None:
            self.serving.close()
