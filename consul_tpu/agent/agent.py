"""The Agent: per-node runtime wiring local state, checks, cache, and
the coordinate loop to the server tier.

Mirrors the reference agent lifecycle (reference agent/agent.go:371-550
Start sequence: local state → ae syncer → cache → delegate → checks →
sendCoordinate): an Agent holds its registrations, runs its checks,
anti-entropy-syncs into the catalog through its RPC route, and sends
its Vivaldi coordinate on the rate-scaled cadence (reference
agent/agent.go:1891-1940 sendCoordinate with
``lib.RateScaledInterval(SyncCoordinateRateTarget, min, N)``).

Agents are time-explicit: ``tick(now)`` drives checks, sync, and the
coordinate send, so a driver can pump thousands of agents against the
simulation clock deterministically (the TestAgent idiom, reference
agent/testagent.go:44-129, without real sockets).
"""

from __future__ import annotations

import random
from typing import Any, Callable, Optional

from consul_tpu.agent.cache import Cache
from consul_tpu.agent.checks import CheckRunner
from consul_tpu.agent.local import LocalState, sync_stagger_s

# Reference defaults (agent/config/default.go SyncCoordinateRateTarget
# = 64 updates/s cluster-wide, SyncCoordinateIntervalMin = 15s).
COORDINATE_RATE_TARGET_PER_S = 64.0
COORDINATE_INTERVAL_MIN_S = 15.0


def coordinate_interval_s(cluster_size: int) -> float:
    """Rate-scaled coordinate send interval (reference
    lib/cluster.go:51-60 RateScaledInterval, agent/agent.go:1896)."""
    return max(cluster_size / COORDINATE_RATE_TARGET_PER_S,
               COORDINATE_INTERVAL_MIN_S)


class Agent:
    def __init__(self, node: str, address: str, rpc: Callable[..., Any],
                 coordinate_source: Optional[Callable[[], dict]] = None,
                 cluster_size: int = 1, seed: int = 0):
        """``rpc(method, **args)``: the agent's route to a server (in
        client mode a Server picked from the connection pool; in server
        mode the local Server) — reference agent.RPC via the delegate.
        ``coordinate_source``: returns this node's current Vivaldi
        coordinate (from the simulation's VivaldiState row, the
        serf.GetCoordinate of reference agent/agent.go:1919)."""
        self.node = node
        self.address = address
        self.rpc = rpc
        self.coordinate_source = coordinate_source
        self.rng = random.Random(seed)
        self.local = LocalState(node, address)
        self.checks = CheckRunner(self.local)
        self.cache = Cache()
        self.cluster_size = cluster_size

        self._next_sync = 0.0  # first tick syncs immediately
        self._next_coord = self.rng.uniform(
            0, coordinate_interval_s(cluster_size)
        )
        self.metrics = {"syncs": 0, "sync_writes": 0, "coordinate_sends": 0,
                        "sync_failures": 0}
        # go-metrics sink served at /v1/agent/metrics (reference
        # lib/telemetry.go always attaches an InmemSink).
        from consul_tpu.utils import telemetry
        self.sink = telemetry.Sink()

    # -- service/check registration API (reference agent endpoints
    # /v1/agent/service/register etc.) ---------------------------------
    def add_service(self, service_id: str, service: str, port: int = 0,
                    tags: Optional[list] = None,
                    check_ttl_s: Optional[float] = None, now: float = 0.0):
        self.local.add_service(service_id, service, port, tags)
        if check_ttl_s is not None:
            self.checks.add_ttl(f"service:{service_id}", check_ttl_s,
                                service_id=service_id, now=now)

    def remove_service(self, service_id: str):
        self.checks.remove(f"service:{service_id}")
        self.local.remove_service(service_id)

    # -- the periodic work ---------------------------------------------
    def tick(self, now: float) -> dict:
        """One agent pump: run checks, sync if due, send coordinate if
        due. Returns which duties ran (for drivers/tests)."""
        ran = {"sync": False, "coordinate": False}
        self.checks.tick(now)
        # Check status changes mark entries dirty; sync as scheduled or
        # immediately when something is dirty (changes trigger
        # SyncChanges promptly in the reference, local/state.go:505).
        dirty = (
            not self.local.node_in_sync
            or any(not s.in_sync for s in self.local.services.values())
            or any(not c.in_sync for c in self.local.checks.values())
        )
        if now >= self._next_sync or dirty:
            try:
                self.metrics["sync_writes"] += self.local.sync_changes(self.rpc)
                self.metrics["syncs"] += 1
                ran["sync"] = True
            except Exception:  # noqa: BLE001 — server unreachable; retry soon
                self.metrics["sync_failures"] += 1
                self._next_sync = now + 1.0
            else:
                self._next_sync = now + sync_stagger_s(
                    self.cluster_size, self.rng
                )
        if self.coordinate_source is not None and now >= self._next_coord:
            try:
                self.rpc("Coordinate.Update", node=self.node,
                         coord=self.coordinate_source())
                self.metrics["coordinate_sends"] += 1
                ran["coordinate"] = True
            except Exception:  # noqa: BLE001
                pass
            self._next_coord = now + coordinate_interval_s(self.cluster_size)
        return ran

    # -- reads through the cache (reference DNS/HTTP read path) --------
    def cached_service_nodes(self, service: str, ttl_s: float = 3.0,
                             refresh: bool = False) -> Any:
        return self.cache.get(
            f"service-nodes:{service}",
            lambda idx, wait: self.rpc("Health.ServiceNodes", service=service,
                                       min_index=idx, wait_s=wait),
            ttl_s=ttl_s, refresh=refresh,
        )

    def close(self):
        self.cache.close()
