"""consul-tpu: a TPU-native distributed-coordination simulation framework.

A brand-new JAX/XLA framework with the capabilities of HashiCorp Consul's
gossip core (reference: /root/reference): the SWIM failure detector,
Lifeguard suspicion/awareness extensions, push-pull anti-entropy, gossip
dissemination, and Vivaldi network coordinates — re-expressed as a pure,
jit-compiled, time-stepped state machine over struct-of-arrays, sharded
over a TPU device mesh.

Layout:
  config.py    — tick-based protocol configs (LAN/WAN/Local profiles with
                 the reference's timing constants).
  ops/         — pure math kernels: log-scaling laws, the SWIM merge
                 semilattice, Vivaldi spring relaxation, RNG helpers.
  models/      — the simulation state machines: SimState pytree, the SWIM
                 step function, the serf event layer, cluster drivers.
  parallel/    — device mesh construction, sharded step, WAN federation.
  utils/       — convergence metrics, checkpointing, telemetry.
"""

__version__ = "0.5.0"

from consul_tpu import config as config  # noqa: F401
