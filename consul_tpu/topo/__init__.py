"""Topology lab: the view-graph family registry and structural probes.

See consul_tpu/topo/families.py for the family contract (symmetric
circulant offset sets) and consul_tpu/chaos/sweep.py for the
program-argument sweep plane built on top of it.
"""

from consul_tpu.topo.families import (  # noqa: F401
    FAMILIES,
    offsets_for,
    register,
    spectral_gap,
    validate_offsets,
)
