"""View-graph family registry: symmetric circulant offset generators.

Every family here emits a *symmetric circulant* offset set — sorted
distinct offsets in ``[1, n-1]`` closed under negation (``d`` present
iff ``n - d`` present). That invariant is what lets the SWIM/serf step
deliver every neighbor column with a dense roll instead of a scatter
(ops/topology.py), so families differ **only** in how the offsets are
chosen; the remap/inverse/roll machinery is family-independent and the
offset tensors can travel as program arguments (chaos/sweep.py) so
same-shape families share one XLA executable.

Families:

``circulant``
    The original uniform draw of ``K/2`` half-offsets — preserved
    bit-identically (same rng consumption order) as the default.
``expander``
    Best-of-m random circulant unions scored by spectral gap. Random
    circulants are near-Ramanujan with high probability; taking the
    best of ``m`` draws (default 32) pushes the gap toward the
    ``1 - 2*sqrt(K-1)/K`` bound.
``smallworld``
    Watts–Strogatz on the offset set: the ring lattice
    ``{±1..±K/2}`` with each half-offset beyond ±1 rewired to a
    uniform long-range offset with probability beta (default 0.2).
    ±1 is always kept so the ring stays connected.
``hier``
    Hierarchical DC-aware: dense intra-DC circulant (small offsets)
    plus sparse inter-DC bridges that are exact multiples of the
    per-DC block size — under the dc-major node numbering used by the
    ``(dc, nodes)`` mesh (parallel/mesh.py), a multiple-of-``n/n_dc``
    offset hops whole datacenters while keeping the same in-DC seat.

All generators are host-side numpy (they run once per Simulation
build); the spectral-gap probe uses the circulant closed form
``lambda_d = sum_c cos(2 pi off_c d / n)`` — O(nK), no eigensolver.
"""

from __future__ import annotations

import math
from functools import reduce
from typing import Callable, Dict

import numpy as np

# family name -> generator(n, k_deg, rng, param) -> sorted symmetric
# int64 offsets of length k_deg. Registered below via @register.
FAMILIES: Dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        FAMILIES[name] = fn
        return fn
    return deco


def offsets_for(family: str, n: int, k_deg: int, rng: np.random.Generator,
                param: float = 0.0) -> np.ndarray:
    """Generate and validate the offset set for one family."""
    try:
        gen = FAMILIES[family]
    except KeyError:
        raise ValueError(
            f"unknown topology family {family!r}; registered families: "
            f"{', '.join(sorted(FAMILIES))}") from None
    off = gen(n, k_deg, rng, param)
    validate_offsets(off, n, k_deg, family=family)
    return off


# ---------------------------------------------------------------------------
# validators + spectral probe

def validate_offsets(off: np.ndarray, n: int, k_deg: int,
                     family: str = "?") -> None:
    """Structural invariants every family must satisfy.

    Checks degree bound, range, strict sortedness (distinctness),
    symmetry closure, and connectivity. Connectivity of a circulant
    graph has an exact arithmetic form: the offsets generate Z_n iff
    gcd(off_1, ..., off_K, n) == 1 — no BFS needed at any n.
    """
    off = np.asarray(off)
    if off.shape != (k_deg,):
        raise ValueError(
            f"family {family!r}: expected {k_deg} offsets, got shape "
            f"{off.shape} (degree bound violated)")
    if off.size and (off.min() < 1 or off.max() > n - 1):
        raise ValueError(
            f"family {family!r}: offsets must lie in [1, {n - 1}], got "
            f"range [{off.min()}, {off.max()}]")
    if np.any(np.diff(off) <= 0):
        raise ValueError(
            f"family {family!r}: offsets must be sorted and distinct")
    if set(int(d) for d in off) != set(int(n - d) for d in off):
        raise ValueError(
            f"family {family!r}: offset set not closed under negation "
            f"(symmetric circulant needs d and n-d together)")
    if reduce(math.gcd, (int(d) for d in off), n) != 1:
        raise ValueError(
            f"family {family!r}: offsets do not generate Z_{n} "
            f"(gcd(offsets, n) != 1) — the view graph is disconnected")


def spectral_gap(off: np.ndarray, n: int) -> float:
    """Normalized spectral gap of the circulant view graph.

    Circulant adjacency eigenvalues in closed form:
    ``lambda_d = sum_c cos(2 pi off_c d / n)`` for d = 0..n-1 (the
    sine parts cancel by symmetry closure). Returns
    ``1 - max_{d != 0} |lambda_d| / K`` in [0, 1]; larger means faster
    gossip mixing. Ramanujan quality would be
    ``>= 1 - 2 sqrt(K-1) / K``. Host-side O(nK).
    """
    off = np.asarray(off, dtype=np.float64)
    k_deg = off.shape[0]
    if k_deg == 0 or n <= 1:
        return 0.0
    d = np.arange(1, n, dtype=np.float64)
    lam = np.zeros(n - 1, dtype=np.float64)
    for s in off:  # K accumulations over an [n-1] vector, not [n-1, K]
        lam += np.cos((2.0 * np.pi * s / n) * d)
    return float(1.0 - np.max(np.abs(lam)) / k_deg)


# ---------------------------------------------------------------------------
# generators

def _close(half: np.ndarray, n: int) -> np.ndarray:
    """Sorted symmetric closure {d, n-d} of a half-offset set."""
    half = np.asarray(half, dtype=np.int64)
    return np.sort(np.concatenate([half, n - half]))


def _draw_half(n: int, k_half: int, rng: np.random.Generator) -> np.ndarray:
    """The original uniform half-offset draw (bit-identity anchor).

    Must consume the rng exactly like the pre-family make_topology did:
    one rng.choice over [1, (n+1)//2) without replacement.
    """
    return rng.choice(np.arange(1, (n + 1) // 2), size=k_half, replace=False)


@register("circulant")
def circulant(n: int, k_deg: int, rng: np.random.Generator,
              param: float = 0.0) -> np.ndarray:
    """The default family: one uniform random symmetric circulant,
    conditioned on connectivity.

    The first draw consumes the rng exactly like the pre-registry
    topology code and is returned unchanged whenever it generates Z_n
    — which keeps every connected pre-registry topology bit-identical
    (golden-pinned in tests/test_topology.py). A disconnected draw
    (all offsets sharing a factor with n — ~5% at n=128, K=8) is
    redrawn; the pre-registry code silently accepted those broken
    graphs, the registry's connectivity validator does not.
    """
    for _ in range(256):
        off = _close(_draw_half(n, k_deg // 2, rng).astype(np.int64), n)
        if reduce(math.gcd, (int(d) for d in off), n) == 1:
            return off
    return off  # let validate_offsets report the disconnection


@register("expander")
def expander(n: int, k_deg: int, rng: np.random.Generator,
             param: float = 0.0) -> np.ndarray:
    """Best-of-m random circulant unions by spectral gap (m = param or
    32). Disconnected candidates score gap 0 exactly (lambda at
    d = n/gcd hits K), so maximizing the gap also selects for
    connectivity whenever any candidate connects."""
    candidates = int(param) if param else 32
    best, best_gap = None, -np.inf
    for _ in range(max(1, candidates)):
        off = _close(_draw_half(n, k_deg // 2, rng).astype(np.int64), n)
        gap = spectral_gap(off, n)
        if gap > best_gap:
            best, best_gap = off, gap
    return best


@register("smallworld")
def smallworld(n: int, k_deg: int, rng: np.random.Generator,
               param: float = 0.0) -> np.ndarray:
    """Watts–Strogatz on the half-offset set (beta = param or 0.2).

    Start from the ring lattice {1..K/2}; each half-offset above 1 is
    rewired to a uniform long-range half-offset with probability beta.
    ±1 is never rewired, so the base ring (which alone generates Z_n)
    keeps the graph connected at any beta.
    """
    beta = float(param) if param else 0.2
    k_half = k_deg // 2
    hi = (n + 1) // 2  # half-offsets live in [1, hi)
    used: set = set()
    half = []
    for d in range(1, k_half + 1):
        cand = d
        if d > 1 and rng.random() < beta:
            cand = int(rng.integers(2, hi))
        while cand in used or cand >= hi:
            cand = int(rng.integers(2, hi))
        used.add(cand)
        half.append(cand)
    return _close(np.asarray(half, dtype=np.int64), n)


@register("hier")
def hier(n: int, k_deg: int, rng: np.random.Generator,
         param: float = 0.0) -> np.ndarray:
    """Hierarchical DC-aware view (n_dc = param or 8).

    Node ids are dc-major (node i lives in DC ``i // (n/n_dc)``, the
    same layout the (dc, nodes) mesh shards). Offsets split into:
      - bridges: multiples of ``per_dc = n / n_dc`` — pure inter-DC
        hops (same seat, +j DCs), about 1/4 of the half-degree;
      - intra: small offsets < per_dc — mostly-local neighbors.
    """
    n_dc = int(param) if param else 8
    if n_dc < 2 or n % n_dc != 0:
        raise ValueError(
            f"hier family needs n divisible by n_dc >= 2, got n={n} "
            f"n_dc={n_dc} (pass n_dc via topo_param / --family-param)")
    per_dc = n // n_dc
    k_half = k_deg // 2
    hi = (n + 1) // 2

    # Inter-DC bridge half-offsets: distinct multiples of per_dc below
    # n/2 (a multiple equal to n/2 would be its own negation).
    mult = per_dc * np.arange(1, n_dc, dtype=np.int64)
    mult = mult[mult < hi]
    n_bridge = min(max(1, k_half // 4), len(mult), k_half - 1)
    bridges = np.sort(rng.choice(mult, size=n_bridge, replace=False))

    # Intra-DC half-offsets: the smallest offsets, skipping anything
    # that collides with a bridge (possible only when per_dc is tiny).
    used = set(int(b) for b in bridges)
    half = [int(b) for b in bridges]
    d = 1
    while len(half) < k_half:
        if d >= hi:
            raise ValueError(
                f"hier family: cannot place {k_half} half-offsets in "
                f"[1, {hi}) for n={n} n_dc={n_dc}")
        if d not in used:
            used.add(d)
            half.append(d)
        d += 1
    return _close(np.asarray(half, dtype=np.int64), n)
