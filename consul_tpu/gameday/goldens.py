"""Golden SLO measurement points: the worst-case regression alarm.

Two fixed, fast, deterministic probes whose results are stored as
data (``slo_goldens.json``) and re-measured by a tier-1 test
(``tests/test_slo_goldens.py``):

- **topology**: the ``worst_case`` heal-time argmax over the standard
  partition scenario grid at a fixed (n, degree, S) point — the
  number ROADMAP's topology lab optimizes; a future PR that slows
  worst-case heal beyond the stored tolerance fails fast, in tier-1,
  not in a multi-hour soak.
- **raft**: commit-visibility latency (ticks, chunk-quantized — the
  bench raft ladder's probe) for proposed writes on a small armed
  sim; the regression alarm for the quorum-commit path the game-day
  lost-writes gate depends on.

Both probes reuse the exact code paths the slow tiers measure
(``chaos/sweep.run_sweep`` + ``worst_case``, ``RaftPlane.propose`` +
the chunk pump), just at regression-test scale.
"""

from __future__ import annotations


def measure_topology(n: int = 256, degree: int = 8, scenarios: int = 4,
                     settle: int = 96, chunk: int = 32,
                     form_ticks: int = 64, seed: int = 0) -> dict:
    """Worst-case heal time over the standard partition grid at one
    fixed sweep point. Deterministic for a fixed config."""
    from consul_tpu.chaos import sweep as sweep_mod
    from consul_tpu.config import SimConfig
    from consul_tpu.models.cluster import Simulation

    sim = Simulation(SimConfig(n=n, view_degree=degree), seed=seed)
    sim.run(form_ticks, chunk=chunk, with_metrics=False)
    results = sweep_mod.run_sweep(
        sim, sweep_mod.scenario_grid(n, scenarios),
        chunk=chunk, settle=settle)
    wi = sweep_mod.worst_case(results)
    worst = results[wi]["slo"]
    return {
        "n": n, "degree": degree, "scenarios": scenarios,
        "settle": settle, "chunk": chunk, "seed": seed,
        "worst_index": wi,
        "time_to_heal": int(worst["time_to_heal"]),
        "false_positive_deaths": int(worst["false_positive_deaths"]),
        "time_to_first_suspect": int(worst["time_to_first_suspect"]),
    }


def measure_raft_commit(n: int = 256, groups: int = 4, peers: int = 3,
                        window: int = 64, probes: int = 6,
                        rchunk: int = 8, seed: int = 0) -> dict:
    """Commit-visibility latency in ticks for proposed writes (the
    bench raft ladder's probe at regression scale): propose one
    entry, step the sim in ``rchunk``-tick chunks until the quorum
    commit point releases the ticket. Quantizes to ``rchunk``."""
    from consul_tpu.config import RaftConfig, SimConfig
    from consul_tpu.models.cluster import Simulation

    sim = Simulation(SimConfig(n=n, view_degree=8), seed=seed)
    plane = sim.set_raft(RaftConfig(groups=groups, peers=peers,
                                    window=window))
    # Form + first elections (also warms the raft-carrying program).
    sim.run(4 * rchunk, chunk=rchunk, with_metrics=False)
    lat = []
    for i in range(probes):
        tk = plane.propose([("kv_put", f"golden/raft/{i}", b"v")])
        ticks = 0
        while not tk.done.is_set() and ticks < 32 * rchunk:
            sim.run(rchunk, chunk=rchunk, with_metrics=False)
            ticks += rchunk
        lat.append(ticks)
    lat.sort()
    return {
        "n": n, "groups": groups, "peers": peers, "window": window,
        "probes": probes, "rchunk": rchunk, "seed": seed,
        "commit_ticks_p50": int(lat[len(lat) // 2]),
        "commit_ticks_p99": int(lat[-1]),
        "all_committed": all(x < 32 * rchunk for x in lat),
    }


if __name__ == "__main__":
    # Re-measure both probes at their default (golden) configs; paste
    # the values into slo_goldens.json when a deliberate protocol
    # change moves them.
    import json

    print(json.dumps({"topology": measure_topology(),
                      "raft": measure_raft_commit()}, indent=2))
