"""Game-day plane: the federated soak harness with an SLO gate.

``harness.py`` runs the full stack at once — composed chaos riding
the compiled schedule, sustained mixed traffic through either host
frontend, a DCN federation leg, O(1k+) watchers through the
reduction tree — phased warmup -> steady -> fault -> heal -> drain,
with preemption-safe resume at drained phase boundaries. ``slo.py``
turns the measurements into the single pass/fail verdict (and holds
the golden regression thresholds as data); ``swarm.py`` is the
multi-process HTTP client swarm for the async frontend's socket
surface.
"""

from consul_tpu.gameday.harness import (GamedayConfig, PHASES,
                                        run_gameday)
from consul_tpu.gameday.slo import SloThresholds, evaluate, load_goldens

__all__ = [
    "GamedayConfig", "PHASES", "SloThresholds", "evaluate",
    "load_goldens", "run_gameday",
]
