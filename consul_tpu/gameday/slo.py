"""Game-day SLO contract: thresholds as data, verdict as one dict.

The soak harness (``gameday/harness.py``) measures; this module
judges. :class:`SloThresholds` is the pass/fail envelope —
per-class p99 latency ceilings, the zero-lost-writes invariant, the
bounded time-to-heal, the watch delivery-lag bound — and
:func:`evaluate` folds a measurement dict into the single verdict
shape bench.py and the CLI serialize:

``{"pass": bool, "violations": [...], "p99_read_ms", "p99_write_ms",
  "p99_watch_ms", "lost_writes", "max_time_to_heal_ticks",
  "watch_delivery_lag", "shed", "rejected", ...}``

Golden regression thresholds (satellite: the worst-case alarm in
tier-1) live next door in ``slo_goldens.json`` — stored as data so a
future PR that degrades worst-case heal time or raft commit
visibility fails a fast test, not a multi-hour soak.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

GOLDENS_PATH = os.path.join(os.path.dirname(__file__), "slo_goldens.json")


@dataclasses.dataclass(frozen=True)
class SloThresholds:
    """The game-day pass/fail envelope. Latency ceilings are generous
    by design (CPU CI boxes included); the hard invariants are the
    interesting gates: ``lost_writes`` MUST be 0 (X-Consul-Index
    continuity across leader kill), heal time MUST be bounded, and
    the watch plane MUST catch up by drain."""

    p99_read_ms: float = 2000.0
    p99_write_ms: float = 2000.0
    p99_watch_ms: float = 4000.0
    max_lost_writes: int = 0
    max_time_to_heal_ticks: int = 4096
    max_watch_delivery_lag: int = 0
    # Shed/reject ceilings: None = unbounded (reported, not gated).
    max_shed: Optional[int] = None
    max_rejected: Optional[int] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# The measurement keys evaluate() gates on, with the comparison each
# threshold applies (latencies and counts are ceilings).
_GATES = (
    ("p99_read_ms", "p99_read_ms"),
    ("p99_write_ms", "p99_write_ms"),
    ("p99_watch_ms", "p99_watch_ms"),
    ("lost_writes", "max_lost_writes"),
    ("max_time_to_heal_ticks", "max_time_to_heal_ticks"),
    ("watch_delivery_lag", "max_watch_delivery_lag"),
    ("shed", "max_shed"),
    ("rejected", "max_rejected"),
)


def evaluate(measured: dict,
             thresholds: Optional[SloThresholds] = None) -> dict:
    """Fold a harness measurement dict into the stable SLO verdict.

    ``measured`` must carry every gated key (missing keys are
    violations — a soak that could not measure a gate does not pass).
    The verdict is ``measured`` plus ``pass``/``violations``/
    ``thresholds``; the harness merges its own context (phases,
    counters, chaos deltas) around it."""
    th = thresholds if thresholds is not None else SloThresholds()
    violations = []
    for key, tkey in _GATES:
        limit = getattr(th, tkey)
        if limit is None:
            continue
        if key not in measured:
            violations.append(f"{key}: not measured (gate {tkey}<={limit})")
            continue
        val = measured[key]
        if val is None or val > limit:
            violations.append(f"{key}={val} exceeds {tkey}={limit}")
    out = dict(measured)
    out["pass"] = not violations
    out["violations"] = violations
    out["thresholds"] = th.to_dict()
    return out


def load_goldens(path: Optional[str] = None) -> dict:
    """The checked-in golden regression points (satellite alarm):
    worst-case topology heal time at a fixed (n, degree, scenarios)
    sweep point and the raft commit-visibility p99 from the bench
    ladder, each with the config that measured it and the tolerance a
    future PR must stay within."""
    with open(path or GOLDENS_PATH, encoding="utf-8") as f:
        return json.load(f)
