"""Federated game-day soak: the full stack under one composed drill.

One run exercises every production claim at once instead of in
isolation: a meshed simulation with the raft tier armed and the
serving write path + watch plane attached takes sustained mixed
R:W:Watch traffic while a composed chaos timeline (Partition +
ChurnWave + leader-killing RaftKill riding ONE compiled schedule)
plays through the middle of it, a DCN-federated multi-island leg
heals link faults on the WAN tier, and the phase clock walks

    warmup -> steady -> fault -> heal -> drain

sampling per-class latency continuously. The output is a single SLO
verdict (``gameday/slo.py``): per-class p99s, ``lost_writes`` (MUST
be 0 — every acknowledged ledger write is read back after drain and
the X-Consul-Index samples must be monotone across the leader-kill
window), ``max_time_to_heal_ticks`` (the chaos heal counter delta
over the fault+heal window), watch delivery lag, and shed/reject
accounting.

Traffic can drive either host frontend: the classic threaded path
(``QueryBatcher``/``WriteBatcher`` direct) or the async event-loop
frontend (``serving/frontend.py``) — same ops, same kernels, parity
pinned by tests/test_frontend.py. In async mode an optional
multi-process client swarm (``gameday/swarm.py``) additionally drives
the real HTTP surface over sockets.

Preemption safety (multi-hour soaks on preemptible capacity): with
``resume_dir`` set, the harness checkpoints sim + write state at
drained phase boundaries (after warmup/steady/heal — never inside a
chaos window, and only with zero raft proposals in flight so the
device raft log can be rebuilt empty on resume) plus a JSON manifest
of completed phases, latency samples, and the acknowledged write
ledger. A rerun with the same config resumes from the last completed
boundary and replays the saved records instead of restarting the
soak. The raft log itself is NOT checkpointed — boundaries are
drained, so an empty rebuilt log plus a warm re-election is
state-equivalent (documented narrowing).
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import time
from typing import Optional

from consul_tpu.gameday import slo as slo_mod
from consul_tpu.obs import trace as obs_trace

PHASES = ("warmup", "steady", "fault", "heal", "drain")

# Phase boundaries eligible for a resume checkpoint: never between
# fault and heal (the chaos windows must replay whole), and drain
# completing means the run is done.
_SAVE_AFTER = ("warmup", "steady", "heal")

_LEDGER_PREFIX = "gameday/ledger/"


@dataclasses.dataclass(frozen=True)
class GamedayConfig:
    """One game day's shape. Defaults are the CPU-scale acceptance
    drill (n=4096, 2 DCN islands, 1k+ watchers); TPU soaks scale n,
    rounds, and watchers up without changing the contract."""

    n: int = 4096
    seed: int = 0
    view_degree: int = 16
    services: int = 8
    kv_slots: int = 512
    # Raft tier: window 0 = auto-size to the planned write volume
    # (the bounded on-device log admits at most ``window`` client
    # entries per group per run — the no-InstallSnapshot narrowing).
    raft_groups: int = 4
    raft_peers: int = 3
    raft_window: int = 0
    # DCN federation leg: islands of a small WAN-federated cluster
    # healing link faults alongside the main sim's fault window.
    # < 2 disables the leg.
    dcn_islands: int = 2
    dcn_nodes_per_dc: int = 64
    dcn_servers_per_dc: int = 2
    # Watch plane: watchers spread over service labels plus a kv
    # prefix pool; the queue bound is kept small so shed accounting
    # is exercised, not just possible.
    watchers: int = 1024
    watch_queue: int = 8
    watch_k: int = 64
    # Traffic mix.
    ratio: str = "90:9:1"
    read_batch: int = 256
    k: int = 8
    ledger_per_round: int = 4
    wait_s: float = 0.25          # per-round blocking-query bound
    # Phase clock.
    chunk: int = 32
    warmup_ticks: int = 64
    ticks_per_round: int = 32
    steady_rounds: int = 4
    fault_rounds: int = 6
    heal_rounds: int = 4
    drain_rounds: int = 4
    # Composed chaos shape (fractions of n).
    partition_frac: float = 0.25
    churn_frac: float = 0.05
    # Host frontend: "threaded" (batcher-direct) or "async" (the
    # event-loop frontend) — parity-pinned paths over one kernel set.
    frontend: str = "threaded"
    admission: str = "shed_oldest"
    max_pending: int = 4096
    # Client swarm (async frontend only): OS processes driving the
    # real HTTP surface over sockets. 0 disables.
    swarm_procs: int = 0
    swarm_requests: int = 64
    # Preemption-safe resume.
    resume_dir: Optional[str] = None
    thresholds: Optional[slo_mod.SloThresholds] = None

    @property
    def traffic_rounds(self) -> int:
        return self.steady_rounds + self.fault_rounds + self.heal_rounds

    def resolved_window(self) -> int:
        if self.raft_window:
            return int(self.raft_window)
        from consul_tpu.serving.mixed import parse_ratio

        r, w_share, _ = parse_ratio(self.ratio)
        write_batch = max(1, round(self.read_batch * w_share / r))
        total = (self.traffic_rounds
                 * (write_batch + self.ledger_per_round)
                 + self.drain_rounds + 8)
        per_group = -(-total // max(1, self.raft_groups))
        w = 32
        while w < per_group * 2 + 8:
            w *= 2
        return min(w, 8192)

    def ident(self) -> str:
        """Shape fingerprint a resume manifest must match — every
        field that changes tensor shapes or the phase plan."""
        keys = ("n", "seed", "view_degree", "services", "kv_slots",
                "raft_groups", "raft_peers", "watchers", "watch_queue",
                "watch_k", "ratio", "read_batch", "k",
                "ledger_per_round", "chunk", "warmup_ticks",
                "ticks_per_round", "steady_rounds", "fault_rounds",
                "heal_rounds", "drain_rounds", "partition_frac",
                "churn_frac")
        parts = [f"{k}={getattr(self, k)}" for k in keys]
        parts.append(f"window={self.resolved_window()}")
        return ";".join(parts)


# ----------------------------------------------------------------------
# Traffic drivers: one op contract, two host frontends.
# ----------------------------------------------------------------------

class _ThreadedDriver:
    """The classic path: pre-assembled batches straight into the
    batchers, blocking queries through WatchPlane.wait_index."""

    name = "threaded"

    def __init__(self, sim, plane):
        self.sim = sim
        self.plane = plane

    def read_batch(self, ops):
        return self.plane.batcher.execute(ops)

    def write_batch(self, ops):
        return self.plane.writes.execute(ops)

    def wait_index(self, min_index: int, wait_s: float) -> int:
        return self.plane.watch.wait_index(min_index, wait_s)

    def owned_threads(self) -> int:
        return 0

    def close(self) -> None:
        pass


class _AsyncDriver:
    """The event-loop frontend: the same ops as futures, multiplexed
    on ONE owned thread; blocking queries park as loop timers."""

    name = "async"

    def __init__(self, sim, plane):
        from consul_tpu.serving.frontend import AsyncFrontend

        self.sim = sim
        self.plane = plane
        self.frontend = AsyncFrontend(plane).start()

    def read_batch(self, ops):
        futs = [self.frontend.submit_read(m, s, a) for m, s, a in ops]
        return [f.result(60.0) for f in futs]

    def write_batch(self, ops):
        futs = [self.frontend.submit_write(o, t, a) for o, t, a in ops]
        return [f.result(60.0) for f in futs]

    def wait_index(self, min_index: int, wait_s: float) -> int:
        return self.frontend.wait_index(min_index, wait_s).result(
            wait_s + 30.0)

    def owned_threads(self) -> int:
        return self.frontend.owned_threads()

    def close(self) -> None:
        self.frontend.close()


# ----------------------------------------------------------------------
# Resume plumbing.
# ----------------------------------------------------------------------

def _manifest_path(d: str) -> str:
    return os.path.join(d, "gameday_manifest.json")


def _load_resume(cfg: GamedayConfig) -> Optional[dict]:
    if not cfg.resume_dir:
        return None
    path = _manifest_path(cfg.resume_dir)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            man = json.load(f)
    except (OSError, ValueError):
        return None
    if man.get("ident") != cfg.ident():
        return None
    return man


def _save_resume(cfg: GamedayConfig, sim, plane, man: dict) -> bool:
    """Checkpoint state + manifest at a drained phase boundary.
    Returns False (and saves nothing) when raft still has proposals
    in flight — a resume point must be rebuildable with an empty
    device raft log."""
    from consul_tpu.utils import checkpoint as ckpt_mod

    if sim.raft is not None and sim.raft.inflight:
        return False
    os.makedirs(cfg.resume_dir, exist_ok=True)
    ckpt_mod.save(os.path.join(cfg.resume_dir, "gameday_state.ckpt"),
                  sim.state, meta={"ident": cfg.ident()})
    ckpt_mod.save(os.path.join(cfg.resume_dir, "gameday_writes.ckpt"),
                  plane.write_state, meta={"ident": cfg.ident()})
    man = dict(man)
    man["ident"] = cfg.ident()
    man["keys"] = [plane.keys.key_of(s) for s in range(len(plane.keys))]
    tmp = _manifest_path(cfg.resume_dir) + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(man, f)
    os.replace(tmp, _manifest_path(cfg.resume_dir))
    return True


def _restore_resume(cfg: GamedayConfig, sim, plane, man: dict) -> None:
    from consul_tpu.utils import checkpoint as ckpt_mod

    sim.state = ckpt_mod.restore(
        os.path.join(cfg.resume_dir, "gameday_state.ckpt"), sim.state)
    # restore() reads the file and materializes device arrays — do the
    # blocking work outside write_lock, swap the reference under it
    restored = ckpt_mod.restore(
        os.path.join(cfg.resume_dir, "gameday_writes.ckpt"),
        plane.write_state)
    with plane.write_lock:
        plane.write_state = restored
    for key in man.get("keys", []):
        plane.keys.slot_for(key, create=True)
    sim.publish_serving()


class _Stop(Exception):
    """Internal: unwind the phase clock after a preemption trip."""


# ----------------------------------------------------------------------
# The soak itself.
# ----------------------------------------------------------------------

def run_gameday(cfg: GamedayConfig, *, trap=None, emit=None) -> dict:
    """Run (or resume) one game day; returns the SLO verdict dict.

    ``trap`` is an optional :class:`~consul_tpu.runtime.policy.
    SignalTrap`: when it fires, the harness stops at the next round
    boundary with a partial, failing verdict (``preempted: true``) —
    the resume artifacts already on disk (``resume_dir``) let the
    next invocation continue from the last completed boundary.
    ``emit`` (optional callable) receives one progress dict per
    phase."""
    from consul_tpu.chaos import schedule as chaos_mod
    from consul_tpu.config import RaftConfig, SimConfig
    from consul_tpu.models import cluster as cluster_mod
    from consul_tpu.ops import deltas as deltas_mod
    from consul_tpu.serving import MODE_NEAREST, ServingPlane
    from consul_tpu.serving.mixed import _pcts, parse_ratio

    t_start = time.monotonic()
    say = emit if emit is not None else (lambda rec: None)
    r_share, w_share, _watch_share = parse_ratio(cfg.ratio)
    write_batch = max(1, round(cfg.read_batch * w_share / r_share))

    sim = cluster_mod.Simulation(
        SimConfig(n=cfg.n, view_degree=cfg.view_degree), seed=cfg.seed)
    sink = sim.sink
    sim.set_raft(RaftConfig(groups=cfg.raft_groups, peers=cfg.raft_peers,
                            window=cfg.resolved_window()))
    plane = ServingPlane(k=cfg.k, buckets=(cfg.read_batch,),
                         num_services=cfg.services)
    sim.attach_serving(plane, writes=True, kv_slots=cfg.kv_slots,
                       max_pending=cfg.max_pending, policy=cfg.admission,
                       watch_k=cfg.watch_k, watch_queue=cfg.watch_queue)

    # -- resume state ---------------------------------------------------
    man = _load_resume(cfg)
    completed: list = list(man["completed"]) if man else []
    records: dict = dict(man["records"]) if man else {}
    acked: dict = ({int(s): int(v) for s, v in man["acked"]}
                   if man else {})
    seq = int(man["seq"]) if man else 0
    apply_samples: list = list(man["apply_samples"]) if man else []
    if man:
        _restore_resume(cfg, sim, plane, man)
        sink.incr_counter("sim.gameday.resumes", 1)
        say({"gameday": "resume", "completed": list(completed)})

    # -- watch plane population ----------------------------------------
    svc_width = max(cfg.services, 1)
    hooks = [plane.watch.register("service", i % svc_width)
             for i in range(max(1, cfg.watchers))]
    kv_hook = plane.watch.register("kv_prefix", "gameday/")
    lag_probe = plane.watch.register("any")

    driver = (_AsyncDriver(sim, plane) if cfg.frontend == "async"
              else _ThreadedDriver(sim, plane))

    # -- accumulators (replayed phases preload them) --------------------
    read_lats: list = []
    write_lats: list = []
    flip_lats: list = []
    chaos_deltas: Optional[dict] = None
    dcn_report: Optional[dict] = None
    swarm_report: Optional[dict] = None
    for ph in completed:
        rec = records.get(ph, {})
        read_lats += rec.get("read_lats", [])
        write_lats += rec.get("write_lats", [])
        flip_lats += rec.get("flip_lats", [])
        if "chaos" in rec:
            chaos_deltas = rec["chaos"]
        if "dcn" in rec:
            dcn_report = rec["dcn"]
        if "swarm" in rec:
            swarm_report = rec["swarm"]

    preempted = False
    verdict_extra: dict = {}

    def _tripped() -> bool:
        return trap is not None and getattr(trap, "fired", None) is not None

    def _manifest() -> dict:
        return {"completed": completed, "records": records,
                "acked": sorted(acked.items()), "seq": seq,
                "apply_samples": apply_samples}

    def _ledger_ops() -> tuple[list, list]:
        nonlocal seq
        ops, entries = [], []
        for _ in range(cfg.ledger_per_round):
            key = f"{_LEDGER_PREFIX}{seq}"
            slot = plane.keys.slot_for(key, create=True)
            if slot < 0:
                break  # slot table full — size kv_slots to the plan
            val = seq & 0x7FFFFFFF
            ops.append((deltas_mod.OP_KV_PUT, slot, val))
            entries.append((seq, val))
            seq += 1
        return ops, entries

    def _mix_ops(rng) -> list:
        ops = []
        for _ in range(write_batch):
            roll = rng.random()
            node = rng.randrange(cfg.n)
            if roll < 0.5:
                ops.append((deltas_mod.OP_REGISTER, node,
                            rng.randrange(svc_width)))
            elif roll < 0.75:
                slot = plane.keys.slot_for(
                    f"gameday/kv/{rng.randrange(64)}", create=True)
                if slot >= 0:
                    ops.append((deltas_mod.OP_KV_PUT, slot,
                                rng.randrange(1000)))
            else:
                ops.append((deltas_mod.OP_DEREGISTER, node, -1))
        return ops

    def _traffic_round(rng) -> None:
        """One soak round: read batch, write batch (mix + ledger),
        sim ticks (flips + commit pump ride the chunk boundary), one
        explicit flip, one blocking query, one index sample."""
        read_ops = [(MODE_NEAREST, rng.randrange(cfg.n), -1)
                    for _ in range(cfg.read_batch)]
        t0 = time.perf_counter()
        driver.read_batch(read_ops)
        read_lats.append(time.perf_counter() - t0)
        sink.incr_counter("sim.gameday.reads", len(read_ops))

        led_ops, led_entries = _ledger_ops()
        ops = _mix_ops(rng) + led_ops
        t0 = time.perf_counter()
        results = driver.write_batch(ops)
        write_lats.append(time.perf_counter() - t0)
        sink.incr_counter("sim.gameday.writes", len(ops))
        if led_entries:
            for (s, v), res in zip(led_entries,
                                   results[-len(led_entries):]):
                if res is not None and (
                        res.applied or res.status == "proposed"):
                    acked[s] = v

        sim.run(cfg.ticks_per_round, chunk=cfg.chunk, with_metrics=False)
        t0 = time.perf_counter()
        sim.publish_serving()
        flip_lats.append(time.perf_counter() - t0)
        prev = apply_samples[-1] if apply_samples else 0
        idx = driver.wait_index(prev, cfg.wait_s)
        apply_samples.append(int(idx))
        sink.incr_counter("sim.gameday.rounds", 1)

    def _finish_phase(name: str, rec: dict) -> None:
        completed.append(name)
        records[name] = rec
        sink.incr_counter("sim.gameday.phases", 1)
        if cfg.resume_dir and name in _SAVE_AFTER:
            _save_resume(cfg, sim, plane, _manifest())
        say({"gameday": name,
             **{k: v for k, v in rec.items() if not isinstance(v, list)}})

    def _run_rounds(name: str, rounds: int, extras=None) -> None:
        """Run one traffic phase; raises _Stop on preemption."""
        nonlocal preempted
        rng = random.Random(f"{cfg.seed}:{name}")
        r0, w0, f0 = len(read_lats), len(write_lats), len(flip_lats)
        t0 = time.monotonic()
        with obs_trace.span(f"gameday.{name}", cat="gameday",
                            args={"rounds": rounds}):
            for _ in range(rounds):
                if _tripped():
                    preempted = True
                    raise _Stop()
                _traffic_round(rng)
        rec = {
            "rounds": rounds,
            "wall_s": round(time.monotonic() - t0, 2),
            "read_lats": read_lats[r0:],
            "write_lats": write_lats[w0:],
            "flip_lats": flip_lats[f0:],
        }
        if extras:
            rec.update(extras)
        _finish_phase(name, rec)

    # ------------------------------------------------------------------
    # Phase clock.
    # ------------------------------------------------------------------
    try:
        # warmup: form the cluster, elect leaders, warm every
        # executable (read bucket, write batch, flip + diff) so the
        # timed phases measure steady state, not compiles.
        if "warmup" not in completed:
            if _tripped():
                preempted = True
                raise _Stop()
            with obs_trace.span("gameday.warmup", cat="gameday",
                                args={"ticks": cfg.warmup_ticks}):
                t0 = time.monotonic()
                sim.run(cfg.warmup_ticks, chunk=cfg.chunk,
                        with_metrics=False)
                rng = random.Random(f"{cfg.seed}:warm")
                driver.read_batch(
                    [(MODE_NEAREST, rng.randrange(cfg.n), -1)
                     for _ in range(cfg.read_batch)])
                driver.write_batch(_mix_ops(rng))
                sim.run(cfg.chunk, chunk=cfg.chunk, with_metrics=False)
                sim.publish_serving()
            read_lats.clear()
            write_lats.clear()
            flip_lats.clear()
            _finish_phase("warmup", {
                "ticks": cfg.warmup_ticks,
                "wall_s": round(time.monotonic() - t0, 2)})

        # steady: clean-path traffic (plus the client swarm when an
        # async HTTP surface is up).
        if "steady" not in completed:
            swarm_handle = None
            swarm_mod = None
            if (cfg.frontend == "async" and cfg.swarm_procs > 0
                    and isinstance(driver, _AsyncDriver)):
                from consul_tpu.gameday import swarm as swarm_mod

                host, port = driver.frontend.serve_http()
                swarm_handle = swarm_mod.start_swarm(
                    host, port, procs=cfg.swarm_procs,
                    requests=cfg.swarm_requests, seed=cfg.seed)
            try:
                _run_rounds("steady", cfg.steady_rounds)
            finally:
                if swarm_handle is not None:
                    swarm_report = swarm_mod.collect_swarm(swarm_handle)
                    sink.incr_counter("sim.gameday.swarm_requests",
                                      int(swarm_report.get("requests",
                                                           0)))
                    if "steady" in completed:
                        records["steady"]["swarm"] = swarm_report

        # fault + heal: install the composed chaos timeline and keep
        # traffic running straight through it. Windows end inside the
        # fault phase; the schedule stays installed through heal so
        # post-lift heal counters accumulate under the same program,
        # then unhooks (run_scenario's discipline). No resume point
        # between the two — the windows replay whole.
        if "heal" not in completed:
            fault_ticks = cfg.fault_rounds * cfg.ticks_per_round
            events = _composed_events(cfg, fault_ticks)
            sched = chaos_mod.shift_schedule(
                chaos_mod.compile_schedule(cfg.n, events), sim._tick())
            before = sim.counters_snapshot()
            sim.set_chaos(sched)
            try:
                extras = {}
                if cfg.dcn_islands >= 2:
                    dcn_report = _dcn_leg(cfg)
                    extras["dcn"] = dcn_report
                if "fault" not in completed:
                    _run_rounds("fault", cfg.fault_rounds, extras=extras)
                _run_rounds("heal", cfg.heal_rounds)
            finally:
                sim.set_chaos(None)
            after = sim.counters_snapshot()
            chaos_deltas = {
                cluster_mod.SLO_KEYS[f]: after[f] - before[f]
                for f in cluster_mod.SLO_KEYS}
            records["heal"]["chaos"] = chaos_deltas
            if cfg.resume_dir:
                _save_resume(cfg, sim, plane, _manifest())

        # drain: stop offering traffic, pump until every in-flight
        # raft proposal commits, then flush one marker write so the
        # final flip carries a fresh delta to the lag probe.
        if "drain" not in completed:
            if _tripped():
                preempted = True
                raise _Stop()
            t0 = time.monotonic()
            with obs_trace.span("gameday.drain", cat="gameday"):
                tries = max(1, cfg.drain_rounds) * 4
                while (sim.raft is not None and sim.raft.inflight
                       and tries > 0 and not _tripped()):
                    sim.run(cfg.ticks_per_round, chunk=cfg.chunk,
                            with_metrics=False)
                    sim.publish_serving()
                    tries -= 1
                drained = sim.raft is None or sim.raft.inflight == 0
                slot = plane.keys.slot_for("gameday/drain-marker",
                                           create=True)
                if slot >= 0 and drained:
                    driver.write_batch([(deltas_mod.OP_KV_PUT, slot, 1)])
                    sim.run(cfg.ticks_per_round, chunk=cfg.chunk,
                            with_metrics=False)
                    sim.publish_serving()
                    drained = sim.raft is None or sim.raft.inflight == 0
            apply_samples.append(int(plane.apply_index))
            _finish_phase("drain", {
                "drained": drained,
                "wall_s": round(time.monotonic() - t0, 2)})
            verdict_extra["drained"] = drained
            # A completed soak retires its resume point — the next
            # run with this directory starts a fresh round instead of
            # skipping to the end of this one.
            if cfg.resume_dir:
                try:
                    os.remove(_manifest_path(cfg.resume_dir))
                except OSError:
                    pass
        else:
            verdict_extra["drained"] = records["drain"].get("drained",
                                                            True)
    except _Stop:
        pass
    finally:
        for h in hooks:
            plane.watch.unregister(h)
        plane.watch.unregister(kv_hook)
        live_threads = driver.owned_threads()
        driver.close()

    # ------------------------------------------------------------------
    # Verdict assembly.
    # ------------------------------------------------------------------
    drained = bool(verdict_extra.get("drained", False))
    lost, misses, regressions = _audit_writes(
        plane, acked, apply_samples, drained=drained and not preempted)
    if lost:
        sink.incr_counter("sim.gameday.lost_writes", lost)
    final_index = int(plane.apply_index)
    lag = (max(0, final_index - int(lag_probe.index))
           if not preempted else None)
    plane.watch.unregister(lag_probe)

    rp50, rp99 = _pcts(read_lats)
    wp50, wp99 = _pcts(write_lats)
    fp50, fp99 = _pcts(flip_lats)
    wstats = plane.writes.stats() if plane.writes is not None else {}
    watchstats = plane.watch.stats() if plane.watch is not None else {}

    measured = {
        "p99_read_ms": rp99 if read_lats else None,
        "p99_write_ms": wp99 if write_lats else None,
        "p99_watch_ms": fp99 if flip_lats else None,
        "lost_writes": lost if not preempted else None,
        "max_time_to_heal_ticks": (chaos_deltas or {}).get("time_to_heal"),
        "watch_delivery_lag": lag,
        "shed": (int(wstats.get("shed", 0))
                 + int(watchstats.get("watch_shed", 0))),
        "rejected": int(wstats.get("rejected", 0)),
    }
    verdict = slo_mod.evaluate(measured, cfg.thresholds)
    if preempted:
        verdict["pass"] = False
        verdict["violations"].append("preempted mid-soak (resumable)")
    verdict.update({
        "preempted": preempted,
        "phases": list(completed),
        "frontend": driver.name,
        "frontend_threads": live_threads,
        "p50_read_ms": rp50,
        "p50_write_ms": wp50,
        "p50_watch_ms": fp50,
        "ledger": {"written": seq, "acked": len(acked),
                   "readback_misses": misses,
                   "index_regressions": regressions},
        "apply_index": final_index,
        "watchers": int(watchstats.get("watchers", 0)),
        "deliveries": int(watchstats.get("deltas", 0)),
        "flips": int(watchstats.get("flips", 0)),
        "chaos": chaos_deltas,
        "dcn": dcn_report,
        "swarm": swarm_report,
        "raft": sim.raft.summary() if sim.raft is not None else None,
        "wall_s": round(time.monotonic() - t_start, 2),
        "n": cfg.n,
        "drained": drained,
    })
    say({"gameday": "verdict", "pass": verdict["pass"],
         "violations": verdict["violations"]})
    return verdict


def _composed_events(cfg: GamedayConfig, window: int) -> list:
    """The composed fault timeline, relative to the fault phase start:
    a partition over the first half, a churn wave pulsing through the
    middle, and a leader-kill window (every group, whoever leads) over
    the first half — all riding ONE compiled schedule."""
    from consul_tpu.chaos import schedule as chaos_mod

    half = max(4, window // 2)
    return [
        chaos_mod.Partition(
            start=2, stop=half,
            side_a=slice(0, max(2, int(cfg.n * cfg.partition_frac)))),
        chaos_mod.ChurnWave(
            start=max(2, window // 4), stop=max(6, 3 * window // 4),
            nodes=slice(0, max(1, int(cfg.n * cfg.churn_frac))),
            period=8, down_ticks=4),
        chaos_mod.RaftKill(start=2, stop=half, group=-1, peer=-1),
    ]


def _dcn_leg(cfg: GamedayConfig) -> dict:
    """The federation leg: a small multi-island WAN-federated cluster
    heals injected DCN link faults (timeout one way, drop the other)
    while the main sim rides its own fault window. Reported into the
    verdict; the DCN tier's own counters carry the detail."""
    from consul_tpu.config import SimConfig
    from consul_tpu.models.federation import FederationConfig
    from consul_tpu.parallel import dcn as dcn_mod
    from consul_tpu.utils.telemetry import Sink

    snk = Sink()
    fed = dcn_mod.DcnFederation(
        FederationConfig(
            n_dc=cfg.dcn_islands, nodes_per_dc=cfg.dcn_nodes_per_dc,
            servers_per_dc=cfg.dcn_servers_per_dc,
            lan=SimConfig(n=cfg.dcn_nodes_per_dc, view_degree=8)),
        n_islands=cfg.dcn_islands, seed=cfg.seed, sink=snk,
        link_policy=dcn_mod.LinkPolicy(retry_max=3, queue_bound=4))
    fed.inject_link_faults([
        dcn_mod.LinkFault(src=0, dst=1, start=1, stop=4, kind="timeout"),
        dcn_mod.LinkFault(src=1, dst=0, start=1, stop=4),
    ])
    fed.run(16 * 12, sync_every=16, chunk=16)
    return {
        "islands": cfg.dcn_islands,
        "converged": bool(fed.replicas_agree()),
        "heals": int(snk.counter_sum("sim.dcn.heals")),
        "retries": int(snk.counter_sum("sim.dcn.retries")),
        "link_down_ticks": int(
            snk.counter_sum("sim.dcn.link_down_ticks")),
        "queue_peak": int(fed.queue_peak()),
    }


def _audit_writes(plane, acked: dict, apply_samples: list, *,
                  drained: bool) -> tuple[int, int, int]:
    """The lost-writes audit: every acknowledged ledger entry must
    read back with its value and a real ModifyIndex, and the
    X-Consul-Index samples must be monotone across the whole soak
    (leader kill included). Returns (lost, readback_misses,
    index_regressions); an un-drained run counts every acked entry
    unaccounted — the harness fails loudly, never optimistically."""
    misses = 0
    for s, v in acked.items():
        row = plane.kv_get(f"{_LEDGER_PREFIX}{s}")
        if row is None or int(row["Value"]) != v \
                or int(row["ModifyIndex"]) <= 0:
            misses += 1
    regressions = sum(
        1 for a, b in zip(apply_samples, apply_samples[1:]) if b < a)
    if not drained:
        misses = max(misses, len(acked))
    return misses + regressions, misses, regressions
