"""Multi-process client swarm for the game-day HTTP surface.

Each worker is a real OS process (``python -m consul_tpu.gameday.swarm
HOST PORT REQUESTS SEED``) hammering the async frontend's socket
listener with stdlib ``http.client`` — catalog reads, health lookups,
KV puts, and short blocking queries (``?index=`` + ``?wait=``) — and
printing ONE JSON stats line on stdout. The parent
(:func:`start_swarm` / :func:`collect_swarm`) spawns N workers with
``subprocess.Popen`` and folds their lines into one report. Workers
are plain subprocesses on purpose: the point of the drill is traffic
arriving over real sockets from outside the serving process's GIL,
the way a production agent fleet would.

Documented narrowing: the swarm drives the HTTP surface only — the
DNS surface (``agent/dns.py``) stays covered by its own test tier,
since the async frontend serves HTTP (the blocking-query surface the
event loop exists for) and DNS queries are non-blocking one-shots.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time


def worker(host: str, port: int, requests: int, seed: int) -> dict:
    """One swarm worker's request loop (runs in the child process).
    Mix: ~60% reads (catalog/health), ~20% KV puts, ~20% short
    blocking queries riding the last seen X-Consul-Index."""
    import http.client

    rng = random.Random(seed)
    pid = os.getpid()
    ok = failed = blocking = 0
    lats = []
    last_index = 0
    conn = http.client.HTTPConnection(host, port, timeout=30)
    for i in range(requests):
        roll = rng.random()
        if roll < 0.3:
            path = "/v1/catalog/nodes"
        elif roll < 0.6:
            path = f"/v1/health/service/{rng.randrange(8)}"
        elif roll < 0.8:
            path = f"/v1/kv/swarm/{pid}/{i}"
        else:
            blocking += 1
            path = (f"/v1/kv/swarm/{pid}/blk"
                    f"?index={last_index}&wait=50ms")
        t0 = time.perf_counter()
        try:
            if "/v1/kv/" in path and "?" not in path:
                conn.request("PUT", path, body=str(i))
            else:
                conn.request("GET", path)
            resp = conn.getresponse()
            resp.read()
            idx = resp.getheader("X-Consul-Index")
            if idx is not None:
                last_index = max(last_index, int(idx))
            if resp.status < 500:
                ok += 1
            else:
                failed += 1
        except (OSError, http.client.HTTPException):
            failed += 1
            conn.close()
            conn = http.client.HTTPConnection(host, port, timeout=30)
        lats.append(time.perf_counter() - t0)
    conn.close()
    lats.sort()
    return {
        "pid": pid,
        "requests": ok + failed,
        "ok": ok,
        "failed": failed,
        "blocking": blocking,
        "last_index": last_index,
        "p50_ms": round(lats[len(lats) // 2] * 1e3, 3) if lats else 0.0,
        "p99_ms": round(
            lats[min(len(lats) - 1, int(len(lats) * 0.99))] * 1e3, 3)
        if lats else 0.0,
    }


def start_swarm(host: str, port: int, *, procs: int, requests: int,
                seed: int = 0) -> list:
    """Spawn the worker processes (non-blocking); returns the handle
    list :func:`collect_swarm` folds. Workers inherit the current
    interpreter; JAX is never imported on their path."""
    out = []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for j in range(max(1, procs)):
        out.append(subprocess.Popen(
            [sys.executable, "-m", "consul_tpu.gameday.swarm",
             host, str(port), str(requests), str(seed + j)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True))
    return out


def collect_swarm(handles: list, timeout_s: float = 120.0) -> dict:
    """Join every worker and fold the per-process stats lines into one
    report. A worker that dies or times out counts its whole quota as
    failed — the swarm never under-reports trouble."""
    procs = requests = ok = failed = blocking = 0
    p99s = []
    last_index = 0
    for p in handles:
        procs += 1
        try:
            stdout, _ = p.communicate(timeout=timeout_s)
            line = stdout.strip().splitlines()[-1] if stdout.strip() \
                else "{}"
            st = json.loads(line)
        except (subprocess.TimeoutExpired, ValueError, IndexError):
            p.kill()
            p.wait()
            st = {}
        if not st or p.returncode != 0:
            failed += 1
            continue
        requests += int(st.get("requests", 0))
        ok += int(st.get("ok", 0))
        failed += int(st.get("failed", 0))
        blocking += int(st.get("blocking", 0))
        last_index = max(last_index, int(st.get("last_index", 0)))
        p99s.append(float(st.get("p99_ms", 0.0)))
    return {
        "procs": procs,
        "requests": requests,
        "ok": ok,
        "failed": failed,
        "blocking": blocking,
        "last_index": last_index,
        "p99_ms": max(p99s) if p99s else 0.0,
    }


def main(argv=None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 4:
        print(json.dumps({"error": "usage: swarm HOST PORT REQS SEED"}))
        return 2
    host, port, requests, seed = (argv[0], int(argv[1]), int(argv[2]),
                                  int(argv[3]))
    print(json.dumps(worker(host, port, requests, seed)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
