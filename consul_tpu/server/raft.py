"""Raft-lite: deterministic in-process consensus for the server tier.

The reference vendors hashicorp/raft (~8.7k LoC; reference
vendor/github.com/hashicorp/raft) for leader election, log replication,
and FSM snapshots, driven by wall-clock timers over TCP. This
implementation keeps the protocol core — terms, randomized election
timeouts, RequestVote/AppendEntries with the log-matching property,
quorum commit, log compaction with InstallSnapshot — but is
**tick-driven and deterministic**: timers are tick counters, randomness
is a per-node seeded RNG, and messages flow through an in-memory
transport with explicit partition control (the moral equivalent of the
reference's inmem_transport.go used by dev mode and every raft test).

Determinism is the point: the TPU framework's control plane must be
replayable the same way the data plane is (same seed ⇒ same trajectory),
so consensus tests never flake and fault injection (partitions, node
stops) is scriptable — SURVEY.md §5 "race detection" TPU equivalent.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
import zlib
from typing import Any, Callable, Optional

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"

# Membership/suffrage changes ride the log as configuration entries
# (reference raft configuration LogConfiguration entries): command
# ``{"type": RAFT_CONFIG, "op": promote|demote|add_nonvoter|remove,
# "id": node_id}``. They apply when APPENDED, not when committed
# (hashicorp raft's configurations.latest semantics), so the voter set
# a node computes quorum from always reflects the latest change it has
# seen — and a node crashed mid-change recovers it from its own
# persisted log (or from catch-up replication) instead of restarting
# with a stale out-of-band voter set, which could yield two disjoint
# majorities. Divergence, documented: a truncated uncommitted config
# entry is not rolled back (at most one change is in flight through
# the cluster-level APIs, so the conflict window does not arise).
RAFT_CONFIG = "raft_config"


def _config_cmd(command: Any) -> Optional[dict]:
    if isinstance(command, dict) and command.get("type") == RAFT_CONFIG:
        return command
    return None

HEARTBEAT_TICKS = 2
ELECTION_TICKS_MIN = 10
ELECTION_TICKS_MAX = 20


@dataclasses.dataclass
class LogEntry:
    term: int
    index: int
    command: Any


@dataclasses.dataclass
class Message:
    mtype: str        # request_vote | vote_reply | append | append_reply | install_snapshot
    src: str
    dst: str
    term: int
    payload: dict


class Transport:
    """In-memory message bus with partition faults (reference raft
    inmem_transport.go + test partitioning idioms)."""

    def __init__(self):
        self.nodes: dict[str, "RaftNode"] = {}
        self.queues: dict[str, list[Message]] = {}
        self.cut: set[tuple[str, str]] = set()
        # One lock for the whole cluster's mutation surface: tick/pump
        # from a driver thread and propose from HTTP handler threads
        # interleave in live deployments (agent/boot.py) — entry points
        # take this lock so raft-lite is thread-safe without changing
        # its deterministic single-threaded behavior (RLock: in-process
        # forwarding re-enters propose). Blocking *reads* never touch
        # it; they park on the state store's own condition instead.
        self.lock = threading.RLock()

    def register(self, node: "RaftNode"):
        self.nodes[node.id] = node
        self.queues[node.id] = []

    def send(self, msg: Message):
        if (msg.src, msg.dst) in self.cut or msg.dst not in self.queues:
            return
        self.queues[msg.dst].append(msg)

    def partition(self, a: str, b: str):
        # chaos controls race the pump/propose threads; RLock is cheap
        with self.lock:
            self.cut.add((a, b))
            self.cut.add((b, a))

    def heal(self, a: Optional[str] = None, b: Optional[str] = None):
        with self.lock:
            if a is None:
                self.cut.clear()
            else:
                self.cut.discard((a, b))
                self.cut.discard((b, a))

    def pump(self):
        """Deliver every queued message (messages sent during delivery
        land next pump, keeping rounds deterministic)."""
        with self.lock:
            for node_id in sorted(self.queues):
                batch, self.queues[node_id] = self.queues[node_id], []
                node = self.nodes[node_id]
                for msg in batch:
                    if not node.stopped:
                        node.handle(msg)


class NotLeader(Exception):
    def __init__(self, leader_hint: Optional[str]):
        super().__init__(f"not leader (hint: {leader_hint})")
        self.leader_hint = leader_hint


class RaftNode:
    """One consensus participant. ``apply_fn(index, command)`` receives
    committed entries in order (the FSM boundary, fsm.go:107)."""

    def __init__(self, node_id: str, peer_ids: list[str], transport: Transport,
                 apply_fn: Callable[[int, Any], Any], seed: int = 0,
                 snapshot_fn: Optional[Callable[[], dict]] = None,
                 restore_fn: Optional[Callable[[dict], None]] = None,
                 snapshot_threshold: int = 1024,
                 store=None, voter: bool = True,
                 voters: Optional[set] = None, sink=None):
        self.id = node_id
        self.peers = [p for p in peer_ids if p != node_id]
        # Voter configuration (reference raft Voter vs Nonvoter
        # suffrage): non-voters replicate the log but neither start
        # elections nor count toward any quorum. Like remove_server,
        # membership/suffrage changes are raft-lite's out-of-band
        # reconfiguration — managed by autopilot, not log entries.
        self.voter = voter
        self.voters: set = set(voters) if voters is not None else set(peer_ids)
        if voter:
            self.voters.add(node_id)
        self.transport = transport
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.snapshot_threshold = snapshot_threshold
        # crc32, not hash(): str hashing is salted per process, which
        # would break same-seed-same-trajectory across runs.
        self.rng = random.Random((seed << 32) ^ zlib.crc32(node_id.encode()))

        self.state = FOLLOWER
        self.term = 0
        self.voted_for: Optional[str] = None
        self.leader_id: Optional[str] = None
        # Log with compaction: entries[0] corresponds to index base+1.
        self.log: list[LogEntry] = []
        self.log_base_index = 0   # index of the last compacted entry
        self.log_base_term = 0
        self.pending_snapshot: Optional[dict] = None
        self.commit_index = 0
        self.last_applied = 0
        self.votes: set[str] = set()
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        self.apply_errors: list[tuple[int, str]] = []
        # FSM responses by log index — the raftApply future's resolved
        # value (reference rpc.go:377-447: the caller gets the FSM's
        # return, e.g. a CAS verdict). Bounded ring; every replica holds
        # the results of its own recent applies.
        self.apply_results: dict[int, Any] = {}
        self.apply_results_cap = 4096
        # Telemetry (optional): the reference raft library's
        # consul.raft.apply / consul.raft.commitTime /
        # consul.raft.leader.lastContact instrumentation
        # (hashicorp/raft raft.go + api.go metrics). _commit_t0 stamps
        # propose time per index; _follower_contact stamps the last
        # successful AppendEntries reply per follower.
        self.sink = sink
        self._commit_t0: dict[int, float] = {}
        self._follower_contact: dict[str, float] = {}
        self.stopped = False
        # Stats surface for autopilot's StatsFetcher (stats_fetcher.go).
        self.ticks = 0
        self.last_contact_tick = 0
        # Durable storage (server/raft_store.py — the raft-boltdb role,
        # reference bolt_store.go:1-305 at server.go:558-600). When a
        # populated store is handed in, this IS a crash-restart: term,
        # vote, log, and snapshot come back from disk and the FSM is
        # rebuilt from snapshot + committed-log replay once a leader
        # re-establishes the commit index.
        self.store = store
        rec = store.load() if store is not None else None
        if rec is not None:
            self.term = rec["term"]
            self.voted_for = rec["voted_for"]
            if rec.get("suffrage") is not None:
                self.voter = rec["suffrage"]["voter"]
                self.voters = set(rec["suffrage"]["voters"])
            self.log = [LogEntry(**e) for e in rec["entries"]]
            self.log_base_index = rec["base_index"]
            self.log_base_term = rec["base_term"]
            # Re-apply configuration entries from the recovered log:
            # suffrage persisted in the stable store already reflects
            # them (persist happens at apply), but replay covers a
            # crash between log append and the stable write.
            for e in self.log:
                cfg = _config_cmd(e.command)
                if cfg is not None:
                    self._apply_config(cfg)
            self.pending_snapshot = rec["snapshot"]
            if rec["snapshot"] is not None and self.restore_fn is not None:
                self.restore_fn(rec["snapshot"])
            # Commit index is NOT persisted (hashicorp/raft doesn't
            # either): entries beyond the snapshot re-commit via the
            # next leader's AppendEntries commit_index.
            self.commit_index = self.log_base_index
            self.last_applied = self.log_base_index
        elif store is not None:
            store.set_stable(
                self.term, self.voted_for,
                {"voter": self.voter, "voters": sorted(self.voters)},
            )
        self._reset_election_timer()
        transport.register(self)

    def _apply_config(self, cmd: dict):
        """Apply one configuration entry to this node's view of the
        membership (reference raft appendConfigurationEntry →
        configurations.latest). Idempotent; persisted immediately so a
        crash cannot roll suffrage back."""
        op, sid = cmd["op"], cmd["id"]
        if op == "promote":
            self.voters.add(sid)
            if sid == self.id:
                self.voter = True
        elif op == "demote":
            self.voters.discard(sid)
            if sid == self.id:
                self.voter = False
        elif op == "add_nonvoter":
            if sid != self.id and sid not in self.peers:
                self.peers.append(sid)
        elif op == "remove":
            self.voters.discard(sid)
            if sid == self.id:
                if self.state == LEADER:
                    # A leader removing itself stays on just long
                    # enough to commit and answer the entry (hashicorp
                    # raft removes the leader only after the config
                    # entry commits); the halt happens at commit in
                    # _apply_committed.
                    pass
                else:
                    # A removed server halts (Consul shuts it down via
                    # serf leave after RemoveServer).
                    self.stopped = True
            elif sid in self.peers:
                self.peers.remove(sid)
            self.next_index.pop(sid, None)
            self.match_index.pop(sid, None)
        else:
            raise ValueError(f"unknown raft_config op {op!r}")
        self._persist_stable()

    def _persist_stable(self):
        if self.store is not None:
            self.store.set_stable(
                self.term, self.voted_for,
                {"voter": self.voter, "voters": sorted(self.voters)},
            )

    def _persist_append(self, entries: list[LogEntry]):
        if self.store is not None:
            self.store.append([dataclasses.asdict(e) for e in entries])

    def _persist_log_rewrite(self):
        if self.store is not None:
            self.store.rewrite_log(
                [dataclasses.asdict(e) for e in self.log]
            )

    # ------------------------------------------------------------------
    # Log helpers (with compaction offsets)
    # ------------------------------------------------------------------
    def last_log_index(self) -> int:
        return self.log_base_index + len(self.log)

    def last_log_term(self) -> int:
        return self.log[-1].term if self.log else self.log_base_term

    def entry_at(self, index: int) -> Optional[LogEntry]:
        i = index - self.log_base_index - 1
        return self.log[i] if 0 <= i < len(self.log) else None

    def term_at(self, index: int) -> Optional[int]:
        if index == self.log_base_index:
            return self.log_base_term
        e = self.entry_at(index)
        return e.term if e else None

    # ------------------------------------------------------------------
    # Timers
    # ------------------------------------------------------------------
    def _reset_election_timer(self):
        self.election_ticks = self.rng.randint(
            ELECTION_TICKS_MIN, ELECTION_TICKS_MAX
        )

    def tick(self):
        if self.stopped:
            return
        with self.transport.lock:
            self._tick_locked()

    def _tick_locked(self):
        self.ticks += 1
        if self.state == LEADER:
            self.heartbeat_ticks = getattr(self, "heartbeat_ticks", 0) - 1
            if self.heartbeat_ticks <= 0:
                self.heartbeat_ticks = HEARTBEAT_TICKS
                self._broadcast_appends()
                if self.sink is not None and self._follower_contact:
                    # Staleness of the slowest follower, in ms
                    # (consul.raft.leader.lastContact).
                    now = time.perf_counter()
                    self.sink.add_sample(
                        "consul.raft.leader.lastContact",
                        max(now - t
                            for t in self._follower_contact.values())
                        * 1000.0)
            return
        if not self.voter:
            return  # non-voters never campaign
        self.election_ticks -= 1
        if self.election_ticks <= 0:
            self._start_election()

    # ------------------------------------------------------------------
    # Election (raft §5.2)
    # ------------------------------------------------------------------
    def _start_election(self):
        self.state = CANDIDATE
        self.term += 1
        self.voted_for = self.id
        self._persist_stable()
        self.votes = {self.id}
        self.leader_id = None
        self._reset_election_timer()
        for p in self.peers:
            self.transport.send(Message(
                "request_vote", self.id, p, self.term,
                {"last_log_index": self.last_log_index(),
                 "last_log_term": self.last_log_term()},
            ))
        self._maybe_win()

    def _maybe_win(self):
        votes = len(self.votes & self.voters)
        if self.state == CANDIDATE and votes * 2 > len(self.voters):
            self.state = LEADER
            self.leader_id = self.id
            self.heartbeat_ticks = 0
            nxt = self.last_log_index() + 1
            self.next_index = {p: nxt for p in self.peers}
            self.match_index = {p: 0 for p in self.peers}
            # Commit a current-term no-op immediately so quorum-
            # replicated entries from prior terms become committable
            # (raft §5.4.2; hashicorp/raft's LogNoop on election).
            self.log.append(LogEntry(self.term, nxt, {"type": "noop"}))
            self._persist_append(self.log[-1:])
            self._broadcast_appends()
            # A single-node cluster is its own quorum (dev mode,
            # reference raftInmem server.go:177) — commit immediately.
            self._advance_commit()

    # ------------------------------------------------------------------
    # Replication (raft §5.3)
    # ------------------------------------------------------------------
    def propose(self, command: Any) -> int:
        """Leader-only append; returns the entry's log index. Commit is
        observed via apply_fn once a quorum replicates (raftApply
        semantics, reference agent/consul/rpc.go:377)."""
        with self.transport.lock:
            if self.state != LEADER:
                raise NotLeader(self.leader_id)
            entry = LogEntry(self.term, self.last_log_index() + 1, command)
            self.log.append(entry)
            if self.sink is not None:
                self.sink.incr_counter("consul.raft.apply")
                self._commit_t0[entry.index] = time.perf_counter()
                if len(self._commit_t0) > 4096:  # uncommittable leftovers
                    self._commit_t0.pop(next(iter(self._commit_t0)))
            self._persist_append([entry])
            self._broadcast_appends()
            # Configuration entries take effect at append (after the
            # broadcast, so a leader proposing its own removal still
            # ships the entry before halting).
            cfg = _config_cmd(command)
            if cfg is not None:
                self._apply_config(cfg)
            self._advance_commit()  # no-op unless we alone are a quorum
            return entry.index

    def _broadcast_appends(self):
        for p in self.peers:
            self._send_append(p)

    def _send_append(self, peer: str):
        nxt = self.next_index.get(peer, self.last_log_index() + 1)
        if nxt <= self.log_base_index:
            # Peer is behind the compaction horizon: ship the snapshot
            # (InstallSnapshot, raft §7 / reference raft/snapshot.go).
            if self.pending_snapshot is not None:
                self.transport.send(Message(
                    "install_snapshot", self.id, peer, self.term,
                    {"snapshot": self.pending_snapshot,
                     "last_index": self.log_base_index,
                     "last_term": self.log_base_term,
                     # Config entries behind the compaction horizon are
                     # gone from the log; the current membership rides
                     # the snapshot (reference raft snapshots embed the
                     # configuration).
                     "voters": sorted(self.voters),
                     "members": sorted({self.id, *self.peers})},
                ))
            return
        prev_index = nxt - 1
        prev_term = self.term_at(prev_index)
        entries = [dataclasses.asdict(e) for e in
                   self.log[prev_index - self.log_base_index:]]
        self.transport.send(Message(
            "append", self.id, peer, self.term,
            {"prev_index": prev_index, "prev_term": prev_term,
             "entries": entries, "commit_index": self.commit_index},
        ))

    # ------------------------------------------------------------------
    # Message handling
    # ------------------------------------------------------------------
    def handle(self, msg: Message):
        if msg.mtype == "request_vote" and msg.src not in self.voters:
            # A server outside the voter configuration cannot start an
            # election we honor (hashicorp raft ignores RequestVote
            # from non-members) — a removed-but-alive server must not
            # inflate terms or win votes. Reply without a term bump.
            self.transport.send(Message(
                "vote_reply", self.id, msg.src, self.term,
                {"granted": False},
            ))
            return
        if msg.term > self.term:
            self.term = msg.term
            self.state = FOLLOWER
            self.voted_for = None
            self._persist_stable()
            # A deposed leader must not keep claiming itself; the new
            # leader's identity arrives with its first AppendEntries.
            self.leader_id = None
        if msg.mtype == "request_vote":
            self._on_request_vote(msg)
        elif msg.mtype == "vote_reply":
            self._on_vote_reply(msg)
        elif msg.mtype == "append":
            self._on_append(msg)
        elif msg.mtype == "append_reply":
            self._on_append_reply(msg)
        elif msg.mtype == "install_snapshot":
            self._on_install_snapshot(msg)

    def _on_request_vote(self, msg: Message):
        p = msg.payload
        up_to_date = (p["last_log_term"], p["last_log_index"]) >= (
            self.last_log_term(), self.last_log_index()
        )
        grant = (
            msg.term >= self.term
            and self.voted_for in (None, msg.src)
            and up_to_date
        )
        if grant:
            self.voted_for = msg.src
            # The vote must be durable before the grant leaves this
            # node (a re-vote in the same term after restart would let
            # two leaders win); transport defers delivery to the next
            # pump, so persisting here precedes the send.
            self._persist_stable()
            self._reset_election_timer()
        self.transport.send(Message(
            "vote_reply", self.id, msg.src, self.term, {"granted": grant}
        ))

    def _on_vote_reply(self, msg: Message):
        if self.state == CANDIDATE and msg.term == self.term and \
                msg.payload["granted"]:
            self.votes.add(msg.src)
            self._maybe_win()

    def _on_append(self, msg: Message):
        if msg.term < self.term:
            self.transport.send(Message(
                "append_reply", self.id, msg.src, self.term,
                {"success": False, "match_index": 0},
            ))
            return
        self.state = FOLLOWER
        self.leader_id = msg.src
        self.last_contact_tick = self.ticks
        self._reset_election_timer()
        p = msg.payload
        if self.term_at(p["prev_index"]) != p["prev_term"]:
            self.transport.send(Message(
                "append_reply", self.id, msg.src, self.term,
                {"success": False,
                 "match_index": min(p["prev_index"] - 1, self.last_log_index())},
            ))
            return
        # Append, truncating conflicts (log matching property).
        added, truncated = [], False
        for e in p["entries"]:
            entry = LogEntry(**e)
            existing = self.entry_at(entry.index)
            if existing is not None and existing.term != entry.term:
                del self.log[entry.index - self.log_base_index - 1:]
                existing = None
                truncated = True
            if existing is None and entry.index == self.last_log_index() + 1:
                self.log.append(entry)
                added.append(entry)
        if truncated:
            self._persist_log_rewrite()  # conflict suffix must not revive
        elif added:
            self._persist_append(added)
        for e in added:
            cfg = _config_cmd(e.command)
            if cfg is not None:
                self._apply_config(cfg)  # config applies at append
        match = p["prev_index"] + len(p["entries"])
        if p["commit_index"] > self.commit_index:
            self.commit_index = min(p["commit_index"], self.last_log_index())
            self._apply_committed()
        self.transport.send(Message(
            "append_reply", self.id, msg.src, self.term,
            {"success": True, "match_index": match},
        ))

    def _on_append_reply(self, msg: Message):
        if self.state != LEADER or msg.term != self.term:
            return
        p = msg.payload
        if p["success"]:
            self.match_index[msg.src] = max(
                self.match_index.get(msg.src, 0), p["match_index"]
            )
            self.next_index[msg.src] = self.match_index[msg.src] + 1
            if self.sink is not None:
                self._follower_contact[msg.src] = time.perf_counter()
            self._advance_commit()
        else:
            self.next_index[msg.src] = max(1, p["match_index"] + 1)
            self._send_append(msg.src)

    def _advance_commit(self):
        """Commit = the highest index replicated on a quorum, current
        term only (raft §5.4.2 safety rule)."""
        for idx in range(self.last_log_index(), self.commit_index, -1):
            if self.term_at(idx) != self.term:
                break
            replicas = (1 if self.id in self.voters else 0) + sum(
                1 for p in self.peers
                if p in self.voters and self.match_index.get(p, 0) >= idx
            )
            if replicas * 2 > len(self.voters):
                self.commit_index = idx
                self._apply_committed()
                break

    def _apply_committed(self):
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            if self.sink is not None:
                # Propose→commit latency on the proposing leader
                # (consul.raft.commitTime, hashicorp/raft
                # dispatchLogs/processLogs timing).
                t0 = self._commit_t0.pop(self.last_applied, None)
                if t0 is not None:
                    self.sink.measure_since("consul.raft.commitTime", t0)
            entry = self.entry_at(self.last_applied)
            if entry is None or entry.command == {"type": "noop"}:
                continue
            cfg = _config_cmd(entry.command)
            if cfg is not None:
                # Configuration entries applied at append; at commit
                # they only resolve the raftApply future — and complete
                # a leader's deferred self-removal.
                result = {"ok": True, "op": cfg.get("op")}
                if cfg["op"] == "remove" and cfg["id"] == self.id:
                    self.stopped = True
            else:
                try:
                    result = self.apply_fn(entry.index, entry.command)
                except Exception as e:  # noqa: BLE001
                    # A bad committed entry must not kill the raft loop
                    # (every replica would crash identically); record it
                    # and keep applying — endpoint-side validation is
                    # the real gate, this is the backstop.
                    self.apply_errors.append((entry.index, repr(e)))
                    result = {"error": repr(e)}
            self.apply_results[entry.index] = result
            while len(self.apply_results) > self.apply_results_cap:
                self.apply_results.pop(next(iter(self.apply_results)))
        self._maybe_compact()

    # ------------------------------------------------------------------
    # Snapshots / compaction (raft §7)
    # ------------------------------------------------------------------
    def _maybe_compact(self):
        if self.snapshot_fn is None or \
                self.last_applied - self.log_base_index < self.snapshot_threshold:
            return
        self.pending_snapshot = self.snapshot_fn()
        base_term = self.term_at(self.last_applied)
        self.log = self.log[self.last_applied - self.log_base_index:]
        self.log_base_index = self.last_applied
        self.log_base_term = base_term
        if self.store is not None:
            self.store.save_snapshot(
                self.pending_snapshot, self.log_base_index,
                self.log_base_term,
            )
            self._persist_log_rewrite()

    def _on_install_snapshot(self, msg: Message):
        p = msg.payload
        if msg.term < self.term or p["last_index"] <= self.last_applied:
            return
        self.state = FOLLOWER
        self.leader_id = msg.src
        self.last_contact_tick = self.ticks
        self._reset_election_timer()
        if self.restore_fn is not None:
            self.restore_fn(p["snapshot"])
        self.log = []
        self.log_base_index = p["last_index"]
        self.log_base_term = p["last_term"]
        self.commit_index = p["last_index"]
        self.last_applied = p["last_index"]
        self.pending_snapshot = p["snapshot"]
        if "voters" in p:
            self.voters = set(p["voters"])
            self.voter = self.id in self.voters
            self.peers = [m for m in p.get("members", [self.id, *self.peers])
                          if m != self.id]
            self._persist_stable()
        if self.store is not None:
            self.store.save_snapshot(
                p["snapshot"], p["last_index"], p["last_term"]
            )
            self._persist_log_rewrite()
        self.transport.send(Message(
            "append_reply", self.id, msg.src, self.term,
            {"success": True, "match_index": p["last_index"]},
        ))

    # ------------------------------------------------------------------
    def stop(self):
        """Fault injection: crash-stop (the Shutdown() idiom of the
        reference's leader tests)."""
        self.stopped = True

    def restart(self):
        self.stopped = False
        self.state = FOLLOWER
        self._reset_election_timer()


class RaftCluster:
    """Test/driver harness: n nodes, one transport, lock-step rounds
    (the in-process multi-server cluster pattern of reference
    agent/consul/helper_test.go wantRaft/wantPeers)."""

    def __init__(self, n: int, apply_factory: Callable[[str], Callable],
                 seed: int = 0, snapshot_threshold: int = 1024,
                 snapshot_factory=None, restore_factory=None,
                 store_factory=None, sink=None):
        self.transport = Transport()
        ids = [f"srv{i}" for i in range(n)]
        self.nodes = {}
        self._factories = (apply_factory, snapshot_factory, restore_factory,
                           store_factory)
        self._seed = seed
        self._snapshot_threshold = snapshot_threshold
        self._sink = sink
        for node_id in ids:
            self.nodes[node_id] = self._make_node(node_id, ids)

    def _make_node(self, node_id: str, ids: list[str]) -> RaftNode:
        apply_f, snap_f, restore_f, store_f = self._factories
        return RaftNode(
            node_id, ids, self.transport, apply_f(node_id),
            seed=self._seed, snapshot_threshold=self._snapshot_threshold,
            snapshot_fn=snap_f(node_id) if snap_f else None,
            restore_fn=restore_f(node_id) if restore_f else None,
            store=store_f(node_id) if store_f else None,
            sink=self._sink,
        )

    def add_nonvoter(self, node_id: str) -> RaftNode:
        """Join a fresh server as a non-voter (reference raft
        AddNonvoter; Consul servers join staging before autopilot
        promotes them). It replicates from the leader but counts
        toward no quorum until promoted."""
        if node_id in self.nodes:
            raise ValueError(f"{node_id} already a member")
        voters = next(iter(self.nodes.values())).voters
        ids = sorted({node_id, *self.nodes})
        apply_f, snap_f, restore_f, store_f = self._factories
        node = RaftNode(
            node_id, ids, self.transport, apply_f(node_id),
            seed=self._seed, snapshot_threshold=self._snapshot_threshold,
            snapshot_fn=snap_f(node_id) if snap_f else None,
            restore_fn=restore_f(node_id) if restore_f else None,
            store=store_f(node_id) if store_f else None,
            voter=False, voters=set(voters), sink=self._sink,
        )
        self.nodes[node_id] = node
        node._persist_stable()  # records voter=False before any crash
        for other in self.nodes.values():
            if other.id != node_id and node_id not in other.peers:
                other.peers.append(node_id)
        # Record the membership in the log too, so a member crashed
        # right now still learns of the new peer on restart/replay.
        led = self.leader()
        if led is not None:
            led.propose({"type": RAFT_CONFIG, "op": "add_nonvoter",
                         "id": node_id})
        return node

    def promote(self, node_id: str) -> None:
        """Grant suffrage (reference raft AddVoter on promotion,
        autopilot.go:256-320) — a replicated configuration entry: the
        change reaches every member, including ones crashed mid-change,
        through the log rather than out-of-band mutation (the
        split-brain a stale restarted voter set could otherwise
        cause). Synchronous: steps until every running member has
        adopted the new configuration."""
        if node_id not in self.nodes:
            raise ValueError(f"unknown server {node_id!r}")
        led = self.wait_leader()
        idx = led.propose({"type": RAFT_CONFIG, "op": "promote",
                           "id": node_id})
        target = self.nodes[node_id]
        for _ in range(400):
            # Wait for commit + the target's own adoption; a
            # partitioned *other* member catches up later via normal
            # replication — best-effort after the cap, like
            # remove_server, never an exception that would kill an
            # autopilot loop.
            if led.commit_index >= idx and node_id in target.voters:
                return
            self.step()

    def crash(self, node_id: str):
        """Hard-kill: the in-memory RaftNode object is discarded (its
        volatile state is gone for good), pending inbox dropped. Only
        what its DurableRaftStore wrote can come back."""
        node = self.nodes.pop(node_id)
        node.stopped = True
        if node.store is not None:
            node.store.close()
        del self.transport.nodes[node_id]
        del self.transport.queues[node_id]

    def restart_from_disk(self, node_id: str) -> RaftNode:
        """Recover a crashed node purely from its store directory —
        requires a ``store_factory`` (crash-restart of a store-less
        node would be an amnesiac rejoining under an old identity)."""
        if self._factories[3] is None:
            raise ValueError("restart_from_disk requires store_factory")
        ids = sorted({node_id, *self.nodes})
        node = self._make_node(node_id, ids)
        self.nodes[node_id] = node
        return node

    def step(self, rounds: int = 1):
        for _ in range(rounds):
            for node in self.nodes.values():
                node.tick()
            self.transport.pump()

    def leader(self) -> Optional[RaftNode]:
        leaders = [n for n in self.nodes.values()
                   if n.state == LEADER and not n.stopped]
        if not leaders:
            return None
        return max(leaders, key=lambda n: n.term)

    def wait_leader(self, max_rounds: int = 400) -> RaftNode:
        for _ in range(max_rounds):
            led = self.leader()
            if led is not None:
                return led
            self.step()
        raise TimeoutError("no leader elected")

    def wait_converged(self, max_rounds: int = 400) -> RaftNode:
        """Step until every running node knows the same leader."""
        for _ in range(max_rounds):
            led = self.leader()
            if led is not None and all(
                n.leader_id == led.id
                for n in self.nodes.values() if not n.stopped
            ):
                return led
            self.step()
        raise TimeoutError("leadership did not converge")

    def propose_and_commit(self, command: Any, max_rounds: int = 200) -> int:
        led = self.wait_leader()
        idx = led.propose(command)
        for _ in range(max_rounds):
            self.step()
            if led.commit_index >= idx:
                return idx
        raise TimeoutError(f"entry {idx} not committed")


# ----------------------------------------------------------------------
# Lockstep oracle for the device raft tier (models/raft.py).
# ----------------------------------------------------------------------

class LockstepRaftOracle:
    """Golden replay of ``ops/raft_ops.tick`` as plain-Python scalar
    loops over concrete ints — deliberately NOT the dense tensor
    expressions, so a vectorization bug in the device kernel cannot
    hide in a shared implementation. Shares only the randomness spec
    (``raft_ops.draw_table`` — the per-seat timeout fold ladder) and
    the chaos semantics (``raft_ops.chaos_masks_reference``); everything
    else is the six tick sub-phases written the way hashicorp/raft's
    runFollower/runCandidate/runLeader read, one peer at a time.

    The parity contract (tests/test_raft_device.py) is exact equality
    of the FULL state arrays after every tick — terms, roles, votes,
    leader views, timers, logs, commit indexes, even the leader-side
    ``match`` matrix — for single-device and sharded runs alike.
    """

    FIELDS = ("elections_started", "elections_won", "term_changes",
              "commit_advances", "heartbeats_sent",
              "heartbeats_suppressed", "entries_appended",
              "votes_granted")

    FOLLOWER_ROLE = 0
    CANDIDATE_ROLE = 1
    LEADER_ROLE = 2

    def __init__(self, rcfg, base_key, init_key, events=(),
                 group0: int = 0):
        import jax as _jax
        import numpy as _np

        from consul_tpu.ops import raft_ops as _rops

        self.rcfg = rcfg
        self.base_key = base_key
        self.events = tuple(events)
        self.group0 = int(group0)
        self._rops = _rops
        r, p, w = rcfg.groups, rcfg.peers, rcfg.window
        self.group_ids = _np.arange(r, dtype=_np.int64) + self.group0
        self.term = _np.zeros((r, p), _np.int64)
        self.role = _np.zeros((r, p), _np.int64)
        self.voted = _np.full((r, p), -1, _np.int64)
        self.leader = _np.full((r, p), -1, _np.int64)
        self.timer = _np.asarray(_jax.device_get(_rops.timeout_draws(
            rcfg, init_key, self.group0, r))).astype(_np.int64)
        self.hb = _np.zeros((r, p), _np.int64)
        self.log_term = _np.zeros((r, p, w), _np.int64)
        self.log_client = _np.zeros((r, p, w), bool)
        self.last = _np.zeros((r, p), _np.int64)
        self.commit = _np.zeros((r, p), _np.int64)
        self.match = _np.zeros((r, p, p), _np.int64)
        self.next_seq = [0] * r
        self.cnt = {f: 0 for f in self.FIELDS}

    def bump(self, group: int, k: int = 1) -> int:
        """Mirror RaftPlane.propose's intent bump; returns the 1-based
        client sequence the k-th new proposal will commit as."""
        self.next_seq[group] += int(k)
        return self.next_seq[group]

    def step(self, t: int) -> None:
        draws = self._rops.draw_table(
            self.rcfg, self.base_key, int(t), self.group0,
            self.rcfg.groups).astype("int64")
        alive, deliver = self._rops.chaos_masks_reference(
            self.events, int(t), self.role.copy(), self.group_ids)
        for r in range(self.rcfg.groups):
            self._step_group(r, draws[r], alive[r], deliver[r])

    def run(self, ticks) -> None:
        for t in ticks:
            self.step(int(t))

    # -- one group, one tick, scalar style -----------------------------
    def _step_group(self, r, draws, alive, deliver):
        cfg = self.rcfg
        peers, w_cap, quorum = cfg.peers, cfg.window, cfg.quorum
        fol, cand_r, led_r = (self.FOLLOWER_ROLE, self.CANDIDATE_ROLE,
                              self.LEADER_ROLE)
        term, role = self.term[r], self.role[r]
        voted, lead = self.voted[r], self.leader[r]
        timer, hb = self.timer[r], self.hb[r]
        lt, lc = self.log_term[r], self.log_client[r]
        last, com, match = self.last[r], self.commit[r], self.match[r]
        cnt = self.cnt

        # A: timers run for live non-leaders.
        for p in range(peers):
            if alive[p] and role[p] != led_r:
                timer[p] -= 1

        # B: expiry -> candidate.
        for p in range(peers):
            if alive[p] and role[p] != led_r and timer[p] <= 0:
                term[p] += 1
                role[p] = cand_r
                voted[p] = p
                lead[p] = -1
                timer[p] = draws[p]
                cnt["elections_started"] += 1

        # C: one RequestVote round.
        llt = [int(lt[p][last[p] - 1]) if last[p] > 0 else 0
               for p in range(peers)]
        s_term = term.copy()  # senders' post-B terms
        s_last = last.copy()
        req = [[bool(role[j] == cand_r and alive[j] and deliver[i][j]
                     and i != j) for j in range(peers)]
               for i in range(peers)]
        term_rx = term.copy()
        for i in range(peers):
            mx = max((int(s_term[j]) for j in range(peers) if req[i][j]),
                     default=0)
            if alive[i] and mx > term[i]:
                term_rx[i] = mx
                role[i] = fol
                voted[i] = -1
                lead[i] = -1
                cnt["term_changes"] += 1
        grant_to = [-1] * peers
        for i in range(peers):
            for j in range(peers):
                up_to_date = (llt[j] > llt[i]
                              or (llt[j] == llt[i]
                                  and s_last[j] >= s_last[i]))
                if (req[i][j] and alive[i] and s_term[j] == term_rx[i]
                        and up_to_date and voted[i] in (-1, j)):
                    grant_to[i] = j
                    break
            if grant_to[i] >= 0:
                voted[i] = grant_to[i]
                timer[i] = draws[i]
                cnt["votes_granted"] += 1
        term[:] = term_rx
        votes = [1] * peers
        for j in range(peers):
            for i in range(peers):
                if grant_to[i] == j and deliver[j][i]:
                    votes[j] += 1
        for j in range(peers):
            if role[j] == cand_r and alive[j] and votes[j] >= quorum:
                role[j] = led_r
                lead[j] = j
                hb[j] = 0
                cnt["elections_won"] += 1
                if last[j] < w_cap:
                    lt[j][last[j]] = term[j]
                    lc[j][last[j]] = False
                    last[j] += 1
                    cnt["entries_appended"] += 1
                match[j][:] = 0
                match[j][j] = last[j]

        # D: leaders append pending client intents.
        for p in range(peers):
            if role[p] == led_r and alive[p]:
                n_client = sum(1 for w in range(last[p]) if lc[p][w])
                k = min(max(self.next_seq[r] - n_client, 0),
                        w_cap - int(last[p]))
                for _ in range(k):
                    lt[p][last[p]] = term[p]
                    lc[p][last[p]] = True
                    last[p] += 1
                    cnt["entries_appended"] += 1
                match[p][p] = last[p]

        # E: one AppendEntries round, full-window adoption.
        send = [False] * peers
        for p in range(peers):
            if role[p] == led_r and alive[p]:
                hb[p] -= 1
                lag = any(match[p][i] < last[p]
                          for i in range(peers) if i != p)
                send[p] = bool(hb[p] <= 0 or lag)
                if send[p] and hb[p] <= 0:
                    hb[p] = cfg.heartbeat_ticks
                    cnt["heartbeats_sent"] += 1
                if not send[p]:
                    cnt["heartbeats_suppressed"] += 1
        e_term, e_lt = term.copy(), lt.copy()
        e_lc, e_last, e_com = lc.copy(), last.copy(), com.copy()
        src = [-1] * peers
        for i in range(peers):
            best, best_score = -1, -1
            for j in range(peers):
                if (j != i and send[j] and deliver[i][j] and alive[i]
                        and e_term[j] >= e_term[i]):
                    score = int(e_term[j]) * (peers + 1) + (peers - j)
                    if score > best_score:
                        best, best_score = j, score
            src[i] = best
        for i in range(peers):
            j = src[i]
            if j < 0:
                continue
            if e_term[j] > term[i]:
                voted[i] = -1
                cnt["term_changes"] += 1
            term[i] = max(int(term[i]), int(e_term[j]))
            role[i] = fol
            lead[i] = j
            timer[i] = draws[i]
            lt[i][:] = e_lt[j]
            lc[i][:] = e_lc[j]
            last[i] = e_last[j]
            com[i] = max(int(com[i]), min(int(e_com[j]), int(e_last[j])))
        # Ack return leg: the device writes the leader's POST-adoption
        # length (a same-tick deposed leader's row goes stale — harmless,
        # rows are re-zeroed on election — but parity is exact equality).
        for i in range(peers):
            j = src[i]
            if j >= 0 and deliver[j][i]:
                match[j][i] = last[j]

        # F: quorum commit, current-term entries only.
        for p in range(peers):
            if role[p] == led_r and alive[p]:
                best = 0
                for w in range(w_cap):
                    repl = sum(1 for i in range(peers)
                               if match[p][i] >= w + 1)
                    if (repl >= quorum and lt[p][w] == term[p]
                            and w < last[p]):
                        best = w + 1
                if best > com[p]:
                    com[p] = best
                    cnt["commit_advances"] += 1

    # -- comparison views ----------------------------------------------
    def snapshot(self) -> dict:
        import numpy as _np

        return {
            "term": self.term.copy(), "role": self.role.copy(),
            "voted_for": self.voted.copy(), "leader": self.leader.copy(),
            "timer": self.timer.copy(), "hb": self.hb.copy(),
            "log_term": self.log_term.copy(),
            "log_client": self.log_client.copy(),
            "last_index": self.last.copy(), "commit": self.commit.copy(),
            "match": self.match.copy(),
            "next_seq": _np.asarray(self.next_seq, _np.int64),
        }

    def summary(self):
        """(term, leader, commit, committed_clients) per group — the
        device ``raft_ops.summary`` quadruple."""
        r_n, peers = self.term.shape
        terms, leaders, commits, clients = [], [], [], []
        for r in range(r_n):
            terms.append(int(self.term[r].max()))
            best, best_score = -1, -1
            for p in range(peers):
                if self.role[r][p] == self.LEADER_ROLE:
                    score = (int(self.term[r][p]) * (peers + 1)
                             + (peers - p))
                    if score > best_score:
                        best, best_score = p, score
            leaders.append(best)
            commits.append(int(self.commit[r].max()))
            clients.append(max(
                sum(1 for w in range(int(self.commit[r][p]))
                    if self.log_client[r][p][w])
                for p in range(peers)))
        return terms, leaders, commits, clients
