"""WAN router: multi-datacenter server tracking and RTT-aware routing.

Mirrors the reference router (reference agent/router/router.go:
areas → managers → servers; ``GetDatacentersByDistance`` :395,
``GetDatacenterMaps`` :469; ``Manager.RebalanceServers`` manager.go:297)
plus the LAN→WAN flood join (reference agent/consul/flood.go:12-66):
every server floods its LAN server list into the WAN pool so remote DCs
can route to it.

Coordinates come from the WAN coordinate space (in this framework, a
federation's WAN simulation or the store's coordinate table); distance
sorting reuses the same Vivaldi math as catalog ``?near=``.
"""

from __future__ import annotations

import math
import random
from typing import Optional

from consul_tpu.server import rtt


class Manager:
    """Per-(area, dc) server list with rebalancing (reference
    agent/router/manager.go: shuffled server order spreads RPC load;
    ``NotifyFailedServer`` cycles a failed server to the end)."""

    def __init__(self, dc: str, seed: int = 0):
        self.dc = dc
        self.servers: list[str] = []
        self.rng = random.Random(seed)

    def add_server(self, server_id: str):
        if server_id not in self.servers:
            self.servers.append(server_id)

    def remove_server(self, server_id: str):
        if server_id in self.servers:
            self.servers.remove(server_id)

    def find_server(self) -> Optional[str]:
        return self.servers[0] if self.servers else None

    def rebalance(self):
        self.rng.shuffle(self.servers)

    def notify_failed(self, server_id: str):
        """Move a failed server to the end of the rotation."""
        if server_id in self.servers:
            self.servers.remove(server_id)
            self.servers.append(server_id)


class Router:
    """Areas of datacenters with coordinate-based distance sorting."""

    LOCAL_AREA = "wan"  # reference types.AreaWAN

    def __init__(self, local_dc: str, seed: int = 0):
        self.local_dc = local_dc
        self.seed = seed
        # area -> dc -> Manager
        self.areas: dict[str, dict[str, Manager]] = {}
        # server id -> WAN coordinate (dict form)
        self.coords: dict[str, dict] = {}
        # server id -> dc
        self.server_dc: dict[str, str] = {}

    # ------------------------------------------------------------------
    def add_server(self, server_id: str, dc: str,
                   area: str = LOCAL_AREA,
                   coord: Optional[dict] = None):
        """Track a server (the serf WAN member-join path, reference
        agent/router/serf_adapter.go handleMemberEvent)."""
        managers = self.areas.setdefault(area, {})
        managers.setdefault(dc, Manager(dc, seed=self.seed)).add_server(server_id)
        self.server_dc[server_id] = dc
        if coord is not None:
            self.coords[server_id] = coord

    def remove_server(self, server_id: str, area: str = LOCAL_AREA):
        dc = self.server_dc.pop(server_id, None)
        self.coords.pop(server_id, None)
        if dc and area in self.areas and dc in self.areas[area]:
            self.areas[area][dc].remove_server(server_id)
            if not self.areas[area][dc].servers:
                del self.areas[area][dc]

    def fail_server(self, server_id: str, area: str = LOCAL_AREA):
        dc = self.server_dc.get(server_id)
        if dc and area in self.areas and dc in self.areas[area]:
            self.areas[area][dc].notify_failed(server_id)

    def update_coordinate(self, server_id: str, coord: dict):
        self.coords[server_id] = coord

    # ------------------------------------------------------------------
    def datacenters(self, area: str = LOCAL_AREA) -> list[str]:
        return sorted(self.areas.get(area, {}))

    def find_route(self, dc: str, area: str = LOCAL_AREA) -> Optional[str]:
        """A server to forward a cross-DC RPC to (reference
        router.go:312 FindRoute → forwardDC rpc.go:315)."""
        m = self.areas.get(area, {}).get(dc)
        return m.find_server() if m else None

    def get_datacenters_by_distance(self, area: str = LOCAL_AREA) -> list[str]:
        """DCs sorted by median coordinate distance from the local DC's
        servers (reference router.go:395 GetDatacentersByDistance,
        sorting by min-median RTT; ties/unknowns sort by name last)."""
        out = []
        for dc in self.datacenters(area):
            d = self._dc_distance(dc, area)
            out.append((d, dc))
        out.sort(key=lambda t: (t[0], t[1]))
        return [dc for _, dc in out]

    def _dc_distance(self, dc: str, area: str) -> float:
        if dc == self.local_dc:
            return 0.0
        local = self.areas.get(area, {}).get(self.local_dc)
        remote = self.areas.get(area, {}).get(dc)
        if not local or not remote:
            return math.inf
        dists = []
        for a in local.servers:
            ca = self.coords.get(a)
            for b in remote.servers:
                cb = self.coords.get(b)
                d = rtt.compute_distance(ca, cb)
                if math.isfinite(d):
                    dists.append(d)
        if not dists:
            return math.inf
        dists.sort()
        return dists[len(dists) // 2]

    def get_datacenter_maps(self, area: str = LOCAL_AREA) -> dict[str, list[str]]:
        """dc -> server ids (reference router.go:469 GetDatacenterMaps)."""
        return {dc: list(m.servers)
                for dc, m in self.areas.get(area, {}).items()}


def flood_join(router: Router, dc: str, lan_server_ids: list[str],
               coords: Optional[dict[str, dict]] = None,
               area: str = Router.LOCAL_AREA) -> int:
    """Flood the LAN server list into the WAN pool (reference
    agent/consul/flood.go:27-66 Flood: every local server joins the WAN
    member list on a ticker + membership notifications). Returns the
    number of servers newly added."""
    existing = set(router.get_datacenter_maps(area).get(dc, []))
    added = 0
    for sid in lan_server_ids:
        if sid not in existing:
            router.add_server(sid, dc, area,
                              (coords or {}).get(sid))
            added += 1
        elif coords and sid in coords:
            router.update_coordinate(sid, coords[sid])
    return added
