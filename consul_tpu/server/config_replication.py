"""ConfigEntry replication: primary DC -> secondaries.

The reference replicates centralized configuration entries from the
primary datacenter into every secondary (reference
agent/consul/config_replication.go:1-60 replicateConfig: list remote,
diff against local, apply deltas through raft; driven from the leader
loop, leader.go startConfigReplication). This module is that pass for
the framework, riding the cross-DC RPC path (endpoints.py _forward_dc
over the WAN router) the way the reference rides its connection pool:

  - :func:`replicate_config_entries` — one diff-and-apply pass on a
    secondary's leader: upsert entries whose payload differs, delete
    local entries the primary no longer has. Local writes go through
    the secondary's own raft, so replicated entries survive secondary
    leader failover like any other committed state.
  - :class:`ConfigReplicator` — the leader-loop driver: skips
    non-leaders and the primary itself, short-circuits on an unchanged
    remote index (the reference's remote-index watermark), and backs
    off after errors instead of hammering a dead WAN link.
"""

from __future__ import annotations

from typing import Optional

from consul_tpu.server.endpoints import NoPathToDatacenter, Server
from consul_tpu.server.raft import NotLeader

REPLICATION_INTERVAL_S = 0.5     # reference runs at applyRate limits
ERROR_BACKOFF_S = 2.0


def replicate_config_entries(server: Server, primary_dc: str,
                             remote: Optional[dict] = None,
                             local: Optional[dict] = None) -> dict:
    """One replication pass. Returns ``{"upserts": [(kind, name)...],
    "deletes": [...], "remote_index", "local_index"}``. ``remote`` /
    ``local`` are optional pre-fetched ConfigEntry.List results so the
    loop's watermark check and the diff share ONE list per side.
    Raises NoPathToDatacenter / NotLeader like any cross-DC RPC; the
    caller (ConfigReplicator) turns those into backoff."""
    if server.dc == primary_dc:
        raise ValueError("the primary datacenter does not replicate "
                         "from itself (config_replication.go gates on "
                         "DC != primary)")
    if remote is None:
        remote = server.rpc("ConfigEntry.List", dc=primary_dc)
    if local is None:
        local = server.rpc("ConfigEntry.List")
    remote_by = {(e["kind"], e["name"]): e for e in remote["value"]}
    local_by = {(e["kind"], e["name"]): e for e in local["value"]}
    out = {"upserts": [], "deletes": [], "remote_index": remote["index"],
           "local_index": local["index"]}
    # Deletes first, then upserts in deterministic order (the reference
    # applies deletions before updates so a rename never leaves both).
    for key in sorted(set(local_by) - set(remote_by)):
        server.rpc("ConfigEntry.Delete", kind=key[0], name=key[1])
        out["deletes"].append(key)
    for key in sorted(remote_by):
        le = local_by.get(key)
        if le is None or le["entry"] != remote_by[key]["entry"]:
            server.rpc("ConfigEntry.Apply", kind=key[0], name=key[1],
                       entry=remote_by[key]["entry"])
            out["upserts"].append(key)
    return out


class ConfigReplicator:
    """Periodic replication from the secondary leader's loop (the
    reference's startConfigReplication leader routine)."""

    def __init__(self, server: Server, primary_dc: str,
                 interval_s: float = REPLICATION_INTERVAL_S):
        self.server = server
        self.primary_dc = primary_dc
        self.interval_s = interval_s
        self._next_run = 0.0
        self._last_remote_index: Optional[int] = None
        self._last_local_index: Optional[int] = None
        self.metrics = {"runs": 0, "skips_unchanged": 0, "errors": 0,
                        "upserts": 0, "deletes": 0}

    def maybe_run(self, now: float) -> Optional[dict]:
        """Run a pass if due. Leader-only, secondary-only; errors back
        off instead of raising (a severed WAN must not kill the leader
        loop)."""
        if self.server.dc == self.primary_dc or now < self._next_run \
                or not self.server.is_leader():
            return None
        self._next_run = now + self.interval_s
        try:
            # Watermark: skip the diff only when BOTH sides are
            # unchanged — a remote-only watermark would let an
            # out-of-band secondary write diverge forever while the
            # primary is idle. The remote list is fetched ONCE and
            # shared with the diff.
            remote = self.server.rpc("ConfigEntry.List",
                                     dc=self.primary_dc)
            local = self.server.rpc("ConfigEntry.List")
            if remote["index"] == self._last_remote_index and \
                    local["index"] == self._last_local_index:
                self.metrics["skips_unchanged"] += 1
                return None
            out = replicate_config_entries(self.server, self.primary_dc,
                                           remote=remote, local=local)
        except (NoPathToDatacenter, NotLeader, ConnectionError):
            self.metrics["errors"] += 1
            self._next_run = now + ERROR_BACKOFF_S
            return None
        self._last_remote_index = out["remote_index"]
        # A productive pass's own applies advance the local index past
        # this (pre-apply) watermark, so the NEXT pass re-diffs — an
        # idempotent no-op that settles the watermark; only then does
        # skipping begin. The same mechanism reopens the diff after
        # any out-of-band local write.
        self._last_local_index = out["local_index"]
        self.metrics["runs"] += 1
        self.metrics["upserts"] += len(out["upserts"])
        self.metrics["deletes"] += len(out["deletes"])
        return out
